"""Full Lucene query_string grammar -> DSL Query tree.

Reference analog: `index/query/QueryStringQueryBuilder.java` over Lucene's
classic QueryParser, and `SimpleQueryStringBuilder.java` for the lenient
variant. Grammar covered by `parse_query_string`:

    field:term   field:(a OR b)   "a phrase"~slop   wild*card   prefix*
    fuzzy~   fuzzy~1   [a TO b]   {a TO b}   /regex/   term^boost
    + - ! NOT AND OR && ||   ( grouping )   _exists_:field   *:*
    \\ escaping of specials inside terms; dotted field names; field^boost
    in the `fields` list.

Boolean combination follows the classic parser's addClause algorithm
(AND retro-promotes the previous SHOULD clause to MUST; with a default
AND operator, OR demotes it) — which is exactly how the canonical
`a AND b OR c` => (+a +b c) behavior arises.

The output is a plain dsl Query tree (BoolQuery/MatchQuery/RangeQuery/
WildcardQuery/...), so the plan compiler treats parsed strings exactly
like native JSON DSL — same device plans, same caches.
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

from . import query_dsl as dsl

FieldSpec = Tuple[str, float]          # (name, boost)


def _float_or_400(v: str, what: str) -> float:
    try:
        return float(v)
    except ValueError:
        raise dsl.QueryParseError(f"[query_string] bad {what} [{v}]")


def parse_field_specs(fields: List[str]) -> List[FieldSpec]:
    """["title^5", "body"] -> [("title", 5.0), ("body", 1.0)]"""
    out = []
    for f in fields:
        if "^" in f:
            name, b = f.rsplit("^", 1)
            out.append((name, _float_or_400(b, "field boost")))
        else:
            out.append((f, 1.0))
    return out


def _unescape(s: str) -> str:
    return re.sub(r"\\(.)", r"\1", s)


def _wild_tokens(text: str) -> List[Tuple[str, str]]:
    """[("wild", "*"|"?") | ("lit", ch)]: only UNESCAPED * ? are wild."""
    out: List[Tuple[str, str]] = []
    i = 0
    while i < len(text):
        c = text[i]
        if c == "\\" and i + 1 < len(text):
            out.append(("lit", text[i + 1]))
            i += 2
        elif c in "*?":
            out.append(("wild", c))
            i += 1
        else:
            out.append(("lit", c))
            i += 1
    return out


def _wild_pattern(toks: List[Tuple[str, str]]) -> str:
    """fnmatch pattern: literal * ? [ are bracket-escaped so only the
    intended wildcards stay active."""
    out = []
    for kind, c in toks:
        if kind == "wild":
            out.append(c)
        elif c in "*?[":
            out.append(f"[{c}]")
        else:
            out.append(c)
    return "".join(out)


_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<lparen>\()
  | (?P<rparen>\))
  | (?P<and>AND\b|&&)
  | (?P<or>OR\b|\|\|)
  | (?P<not>NOT\b|!)
  | (?P<plus>\+)
  | (?P<minus>-)
  | (?P<phrase>"(?:\\.|[^"\\])*")
  | (?P<regex>/(?:\\.|[^/\\])+/)
  | (?P<range>[\[{](?:\\.|[^\]}\\])*?\s+TO\s+(?:\\.|[^\]}\\])*?[\]}])
  | (?P<caret>\^(?P<boost>[\d.]+))
  | (?P<tilde>~(?P<fuzz>[\d.]+)?)
  | (?P<field>(?:\\.|[*]|[^\s\\+\-!():^\[\]"{}~*?/|&])
              (?:\\.|[*+\-]|&(?!&)|\|(?!\|)|[^\s\\!():^\[\]"{}~*?/|&])*\s*:)
  | (?P<term>(?:\\.|[*?]|&(?!&)|\|(?!\|)|[^\s\\+\-!():^\[\]"{}~/|&])
             (?:\\.|[*?+\-]|&(?!&)|\|(?!\|)|[^\s\\!():^\[\]"{}~/|&])*)
""", re.X)
# NB: '+'/'-' are special only at clause start (their named groups match
# first); INSIDE a term they are literal, matching Lucene's _TERM_CHAR —
# "well-known", "C++" are single terms. Single '&'/'|' are literal; only
# '&&'/'||' are operators (the lookaheads stop the term before them).


def _lex(s: str) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if m is None:
            raise dsl.QueryParseError(
                f"[query_string] cannot parse at offset {pos}: "
                f"{s[pos:pos + 20]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        if kind == "caret":
            out.append(("CARET", m.group("boost")))
        elif kind == "tilde":
            out.append(("TILDE", m.group("fuzz") or ""))
        elif kind == "field":
            out.append(("FIELD",
                        _unescape(m.group(0).rstrip()[:-1].rstrip())))
        else:
            out.append((kind.upper(), m.group(0)))
    out.append(("EOF", ""))
    return out


class _Parser:
    def __init__(self, tokens, fields: List[FieldSpec], op_and: bool,
                 phrase_slop: int):
        self.toks = tokens
        self.i = 0
        self.fields = fields
        self.op_and = op_and
        self.phrase_slop = phrase_slop

    def peek(self) -> Tuple[str, str]:
        return self.toks[self.i]

    def next(self) -> Tuple[str, str]:
        t = self.toks[self.i]
        self.i += 1
        return t

    # ---- boolean clause list (classic QueryParser.addClause) ----

    def query(self, scope: Optional[List[FieldSpec]],
              in_group: bool = False) -> Optional[dsl.Query]:
        clauses: List[list] = []       # [occur, query]
        while True:
            kind, _ = self.peek()
            if kind == "EOF" or (in_group and kind == "RPAREN"):
                break
            conj = None
            if kind in ("AND", "OR"):
                conj = kind
                self.next()
                kind, _ = self.peek()
                if kind == "EOF" or (in_group and kind == "RPAREN"):
                    break
            mods = None
            while self.peek()[0] in ("PLUS", "MINUS", "NOT"):
                k = self.next()[0]
                mods = "+" if k == "PLUS" else "-"
            q = self.clause(scope)
            if q is None:
                continue
            self._add_clause(clauses, conj, mods, q)
        return self._assemble(clauses)

    def _add_clause(self, clauses: List[list], conj, mods, q) -> None:
        if clauses and conj == "AND" and clauses[-1][0] == "should":
            clauses[-1][0] = "must"
        if clauses and self.op_and and conj == "OR" \
                and clauses[-1][0] == "must":
            clauses[-1][0] = "should"
        if mods == "-":
            occur = "must_not"
        elif mods == "+":
            occur = "must"
        elif conj == "AND":
            occur = "must"           # AND requires the current clause too
        elif self.op_and and conj != "OR":
            occur = "must"
        else:
            occur = "should"
        clauses.append([occur, q])

    def _assemble(self, clauses: List[list]) -> Optional[dsl.Query]:
        if not clauses:
            return None
        if len(clauses) == 1 and clauses[0][0] in ("should", "must"):
            return clauses[0][1]
        b = dsl.BoolQuery()
        for occur, q in clauses:
            getattr(b, {"must": "must", "should": "should",
                        "must_not": "must_not"}[occur]).append(q)
        if b.should and not b.must:
            b.minimum_should_match = "1"
        return b

    # ---- a single clause ----

    def clause(self, scope: Optional[List[FieldSpec]]) -> Optional[dsl.Query]:  # noqa: C901
        kind, val = self.peek()

        if kind == "FIELD":
            self.next()
            fname = val
            if fname == "_exists_":
                k2, v2 = self.peek()
                if k2 not in ("TERM", "PHRASE"):
                    raise dsl.QueryParseError(
                        "[query_string] _exists_: needs a field name")
                self.next()
                q: dsl.Query = dsl.ExistsQuery(field=_unescape(
                    v2.strip('"')))
                return self._postfix_boost(q)
            if fname == "*" and self.peek() == ("TERM", "*"):
                self.next()
                return self._postfix_boost(dsl.MatchAllQuery())
            return self.clause([(fname, 1.0)])

        fields = scope or self.fields

        if kind == "LPAREN":
            self.next()
            q = self.query(fields, in_group=True)
            if self.peek()[0] != "RPAREN":
                raise dsl.QueryParseError(
                    "[query_string] missing closing \")\"")
            self.next()
            if q is None:
                return None
            return self._postfix_boost(q)

        if kind == "PHRASE":
            self.next()
            text = _unescape(val[1:-1])
            slop = self.phrase_slop
            boost = 1.0
            while self.peek()[0] in ("TILDE", "CARET"):
                k2, v2 = self.next()
                if k2 == "TILDE":
                    slop = int(_float_or_400(v2, "slop")) if v2 else slop
                else:
                    boost = _float_or_400(v2, "boost")
            return self._multi(
                fields,
                lambda f: dsl.MatchPhraseQuery(field=f, query=text,
                                               slop=slop), boost)

        if kind == "RANGE":
            self.next()
            include_lo = val[0] == "["
            include_hi = val[-1] == "]"
            body = val[1:-1]
            m = re.split(r"\s+TO\s+", body, maxsplit=1)
            if len(m) != 2:
                raise dsl.QueryParseError(
                    f"[query_string] bad range [{val}]")
            lo = _unescape(m[0].strip().strip('"'))
            hi = _unescape(m[1].strip().strip('"'))

            def mk_range(f):
                rq = dsl.RangeQuery(field=f)
                if lo not in ("*", ""):
                    setattr(rq, "gte" if include_lo else "gt", lo)
                if hi not in ("*", ""):
                    setattr(rq, "lte" if include_hi else "lt", hi)
                return rq
            return self._multi(fields, mk_range, self._boost_suffix())

        if kind == "REGEX":
            self.next()
            pat = _unescape(val[1:-1])
            return self._multi(fields,
                               lambda f: dsl.RegexpQuery(field=f, value=pat),
                               self._boost_suffix())

        if kind == "TERM":
            self.next()
            text = val
            fuzz = None
            boost = 1.0
            while self.peek()[0] in ("TILDE", "CARET"):
                k2, v2 = self.next()
                if k2 == "TILDE":
                    fuzz = v2 if v2 else "AUTO"
                else:
                    boost = _float_or_400(v2, "boost")
            toks = _wild_tokens(text)
            wild_idx = [i for i, (k, _) in enumerate(toks) if k == "wild"]
            plain = _unescape(text)

            def mk_term(f):
                if fuzz is not None:
                    fz = ("AUTO" if fuzz == "AUTO"
                          else int(_float_or_400(fuzz, "fuzziness")))
                    return dsl.FuzzyQuery(field=f, value=plain, fuzziness=fz)
                if wild_idx:
                    if len(toks) == 1 and toks[0] == ("wild", "*"):
                        return dsl.ExistsQuery(field=f)
                    if (wild_idx == [len(toks) - 1]
                            and toks[-1] == ("wild", "*")):
                        return dsl.PrefixQuery(
                            field=f,
                            value="".join(c for _k, c in toks[:-1]))
                    return dsl.WildcardQuery(field=f,
                                             value=_wild_pattern(toks))
                op = "and" if self.op_and else "or"
                return dsl.MatchQuery(field=f, query=plain, operator=op)
            return self._multi(fields, mk_term, boost)

        if kind == "RPAREN":
            raise dsl.QueryParseError("[query_string] unexpected \")\"")
        if kind in ("CARET", "TILDE"):
            self.next()  # dangling postfix: skip
            return None
        raise dsl.QueryParseError(
            f"[query_string] unexpected token {val!r}")

    def _boost_suffix(self) -> float:
        if self.peek()[0] == "CARET":
            return _float_or_400(self.next()[1], "boost")
        return 1.0

    def _postfix_boost(self, q: dsl.Query) -> dsl.Query:
        b = self._boost_suffix()
        if b != 1.0:
            q.boost = q.boost * b
        return q

    def _multi(self, fields: List[FieldSpec], mk, boost: float) -> dsl.Query:
        qs = []
        for fname, fboost in fields:
            q = mk(fname)
            q.boost = fboost * boost
            qs.append(q)
        if len(qs) == 1:
            return qs[0]
        dm = dsl.DisMaxQuery(queries=qs)
        return dm


def parse_query_string(query: str, fields: List[str],
                       default_operator: str = "or",
                       phrase_slop: int = 0) -> dsl.Query:
    toks = _lex(query)
    p = _Parser(toks, parse_field_specs(fields),
                str(default_operator).lower() == "and", phrase_slop)
    q = p.query(None)
    if p.peek()[0] != "EOF":
        raise dsl.QueryParseError(
            f"[query_string] trailing input at token {p.peek()[1]!r}")
    return q if q is not None else dsl.MatchNoneQuery()


# ---------------------------------------------------------------------------
# simple_query_string: the lenient grammar (+ | - " ( ) * ~N), never throws
# ---------------------------------------------------------------------------

_SQS_TOKEN = re.compile(r"""
    (?P<ws>\s+)
  | (?P<lparen>\()
  | (?P<rparen>\))
  | (?P<or>\|)
  | (?P<plus>\+)
  | (?P<minus>-)
  | (?P<phrase>"(?:\\.|[^"\\])*"?)
  | (?P<tilde>~(?P<n>\d+)?)
  | (?P<term>(?:\\.|[^\s\\+\-|()"~])(?:\\.|-|[^\s\\+\-|()"~])*)
""", re.X)
# '-' negates only at clause start (SimpleQueryParser); mid-term it is
# literal so "well-known" stays one term. '+' remains an operator anywhere
# unescaped, as in the reference.


def _sqs_lex(s: str) -> List[Tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(s):
        m = _SQS_TOKEN.match(s, pos)
        if m is None:          # lenient: skip one char
            pos += 1
            continue
        pos = m.end()
        if m.lastgroup == "ws":
            continue
        if m.lastgroup == "tilde":
            out.append(("TILDE", m.group("n") or "1"))
        else:
            out.append((m.lastgroup.upper(), m.group(0)))
    out.append(("EOF", ""))
    return out


class _SqsParser:
    """or_expr := seq ('|' seq)* ; seq := chunk+ (default-op joined);
    chunk := unit ('+' unit)* (must-joined); unit := '-'? atom."""

    def __init__(self, toks, fields: List[FieldSpec], op_and: bool):
        self.toks = toks
        self.i = 0
        self.fields = fields
        self.op_and = op_and

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def or_expr(self, in_group=False) -> Optional[dsl.Query]:
        parts = []
        while True:
            s = self.seq(in_group)
            if s is not None:
                parts.append(s)
            if self.peek()[0] == "OR":
                self.next()
                continue
            break
        if not parts:
            return None
        if len(parts) == 1:
            return parts[0]
        # a purely-negative alternative ("-a | b") becomes its own
        # NOT-clause inside the OR, never a bare sentinel
        parts = [dsl.BoolQuery(must_not=[p.q]) if isinstance(p, _Negated)
                 else p for p in parts]
        return dsl.BoolQuery(should=parts, minimum_should_match="1")

    def seq(self, in_group) -> Optional[dsl.Query]:
        chunks = []
        while True:
            kind, _ = self.peek()
            if kind in ("EOF", "OR") or (in_group and kind == "RPAREN"):
                break
            if kind == "RPAREN":   # lenient: stray ) is skipped
                self.next()
                continue
            c = self.chunk(in_group)
            if c is not None:
                chunks.append(c)
            elif self.i < len(self.toks) - 1 and self.peek()[0] not in (
                    "EOF", "OR", "RPAREN"):
                self.next()        # lenient: skip unusable token
            else:
                break
        if not chunks:
            return None
        if len(chunks) == 1:
            return chunks[0]
        pos = [c for c in chunks if not isinstance(c, _Negated)]
        neg = [c.q for c in chunks if isinstance(c, _Negated)]
        if self.op_and:
            return dsl.BoolQuery(must=pos, must_not=neg)
        return dsl.BoolQuery(should=pos, must_not=neg,
                             minimum_should_match="1" if pos else None)

    def chunk(self, in_group) -> Optional[dsl.Query]:
        units = []
        u = self.unit(in_group)
        if u is None:
            return None
        units.append(u)
        while self.peek()[0] == "PLUS":
            self.next()
            u = self.unit(in_group)
            if u is not None:
                units.append(u)
        if len(units) == 1:
            return units[0]
        pos = [c for c in units if not isinstance(c, _Negated)]
        neg = [c.q for c in units if isinstance(c, _Negated)]
        return dsl.BoolQuery(must=pos, must_not=neg)

    def unit(self, in_group):
        negate = False
        while self.peek()[0] == "MINUS":
            self.next()
            negate = not negate
        q = self.atom(in_group)
        if q is None:
            return None
        if not negate:
            return q
        if isinstance(q, _Negated):      # "-(-a)" cancels
            return q.q
        return _Negated(q)

    def atom(self, in_group) -> Optional[dsl.Query]:
        kind, val = self.peek()
        if kind == "LPAREN":
            self.next()
            q = self.or_expr(in_group=True)
            if self.peek()[0] == "RPAREN":
                self.next()
            return q
        if kind == "PHRASE":
            self.next()
            text = _unescape(val.strip('"'))
            slop = 0
            if self.peek()[0] == "TILDE":
                slop = int(self.next()[1])
            if not text:
                return None
            return self._multi(
                lambda f: dsl.MatchPhraseQuery(field=f, query=text,
                                               slop=slop))
        if kind == "TERM":
            self.next()
            text = _unescape(val)
            fuzz = None
            if self.peek()[0] == "TILDE":
                fuzz = int(self.next()[1])

            def mk(f):
                if fuzz is not None:
                    return dsl.FuzzyQuery(field=f, value=text, fuzziness=fuzz)
                if text.endswith("*"):
                    return dsl.PrefixQuery(field=f, value=text[:-1])
                op = "and" if self.op_and else "or"
                return dsl.MatchQuery(field=f, query=text, operator=op)
            return self._multi(mk)
        return None

    def _multi(self, mk) -> dsl.Query:
        qs = []
        for fname, fboost in self.fields:
            q = mk(fname)
            q.boost = fboost
            qs.append(q)
        return qs[0] if len(qs) == 1 else dsl.DisMaxQuery(queries=qs)


class _Negated:
    def __init__(self, q):
        self.q = q


def parse_simple_query_string(query: str, fields: List[str],
                              default_operator: str = "or") -> dsl.Query:
    p = _SqsParser(_sqs_lex(query), parse_field_specs(fields),
                   str(default_operator).lower() == "and")
    q = p.or_expr()
    if isinstance(q, _Negated):
        q = dsl.BoolQuery(must_not=[q.q])
    return q if q is not None else dsl.MatchNoneQuery()
