"""Launch/fetch split for device execution paths (docs/SERVING.md).

The dispatch discipline this repo grew up with was fully synchronous:
one thread assembled a batch, invoked the jitted program, `device_get`-ed
the outputs, and rendered responses back-to-back — so the chip idled
during every host phase and the host idled during every device phase.
The inference-serving classic fixes that: *launch* returns as soon as
the program invocation is enqueued (JAX's async dispatch hands back
unfetched device arrays), and *fetch* — the single `jax.device_get`
plus all host-side finishing (verify ladders, response rendering) —
happens later, on whichever thread completes the request.

`LaunchHandle` is the seam between the two stages:

- `launch_*()` entry points (`MeshSearchService.launch_msearch`,
  `executor.launch_msearch_batched`, `fastpath.launch_batch`) do every
  host-side preparation AND the jitted call(s), then capture the
  unfetched device arrays plus everything needed to finish the request
  in a closure and return a handle. Launch-stage code must never block
  on device results — oslint OSL504 enforces that statically.
- `handle.fetch()` runs the closure exactly once (idempotent; a second
  call returns the memoized result or re-raises the memoized error),
  releases the captured device arrays, and records the launch→fetch
  latency into the metrics registry (`serving.launch_to_fetch`).

The synchronous entry points (`try_msearch`, `msearch_batched`,
`batch_search`) are now `launch(...).fetch()` — byte-identical results,
same transfer discipline (one `device_get` per program group), with the
split available to the serving scheduler's pipelined dispatcher.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..utils.metrics import METRICS


class LaunchHandle:
    """One launched-but-unfetched unit of device work.

    Created by a `launch_*()` entry point after the jitted program
    call(s) were enqueued; `fetch()` performs the deferred device sync
    and host-side finishing and returns the responses. The handle owns
    the only reference to the captured device arrays — dropping an
    unfetched handle releases them."""

    __slots__ = ("kind", "launched_at", "fetched_at", "_finish", "_result",
                 "_error", "_done", "info", "ws_alloc", "__weakref__")

    def __init__(self, finish: Callable[[], object], kind: str = "device",
                 info: Optional[dict] = None):
        self.kind = kind
        self.launched_at = time.monotonic()
        self.fetched_at: Optional[float] = None
        self._finish = finish
        self._result = None
        self._error: Optional[BaseException] = None
        self._done = False
        # launch-stage forensics the creator chooses to expose (dispatch
        # lock wait, new program compiles, group count) — the serving
        # scheduler copies this into per-request flight-recorder launch
        # events; None when the recorder is disabled (obs/ lazy-payload
        # discipline)
        self.info = info
        # optional HBM-ledger workspace allocation (obs/hbm_ledger.py):
        # the serving scheduler registers the in-flight batch's pinned
        # output buffers against the launched handle; released (ledger
        # release is idempotent) when the deferred sync retires it
        self.ws_alloc = None

    @property
    def done(self) -> bool:
        return self._done

    def fetch(self):
        """Device sync + host finishing. Idempotent: the first call runs
        the deferred stage, later calls replay its outcome.

        Deliberately records only a retirement counter here: the
        `serving.launch_to_fetch` latency histogram is the PIPELINE's
        deferred-sync window and is recorded by the scheduler for the
        handles it parks in the in-flight window — the synchronous
        wrappers (`try_msearch` et al.) fetch back-to-back and would
        drown the metric in zero-width samples."""
        if self._done:
            if self._error is not None:
                raise self._error
            return self._result
        finish, self._finish = self._finish, None   # release on any exit
        try:
            self._result = finish()
        except BaseException as e:
            self._error = e
            raise
        finally:
            self._done = True
            self.fetched_at = time.monotonic()
            METRICS.counter(f"launch.{self.kind}.fetched").inc()
            if self.ws_alloc is not None:
                from ..obs.hbm_ledger import LEDGER
                LEDGER.release(self.ws_alloc)
                self.ws_alloc = None
        return self._result

    def launch_to_fetch_ms(self) -> Optional[float]:
        if self.fetched_at is None:
            return None
        return (self.fetched_at - self.launched_at) * 1000.0


def completed(result, kind: str = "host") -> LaunchHandle:
    """A pre-resolved handle for paths that did no device work (e.g. a
    wholesale mesh decline): fetch() just returns `result`."""
    return LaunchHandle(lambda: result, kind=kind)
