"""Plain highlighter. Analog of reference
`search/fetch/subphase/highlight/PlainHighlighter.java`: re-analyzes the
stored field text, marks query-term occurrences, and emits the best
fragments."""

from __future__ import annotations

from typing import Dict, List, Set

from ..analysis import Analyzer


def highlight_field(text: str, terms: Set[str], analyzer: Analyzer,
                    pre_tag: str = "<em>", post_tag: str = "</em>",
                    fragment_size: int = 100, number_of_fragments: int = 5) -> List[str]:
    # terms ending in "*" are prefixes (match_phrase_prefix's last position)
    exact = {t for t in terms if not t.endswith("*")}
    prefixes = tuple(t[:-1] for t in terms if t.endswith("*") and len(t) > 1)
    tokens = analyzer.analyze(text)
    hits = [(t.start_offset, t.end_offset) for t in tokens
            if t.text in exact or (prefixes and t.text.startswith(prefixes))]
    if not hits:
        return []
    if number_of_fragments == 0:
        # highlight whole field
        return [_mark(text, hits, pre_tag, post_tag)]
    # greedy fragmenting: grow a window around consecutive hits
    fragments: List[tuple] = []
    cur: List[tuple] = []
    for h in hits:
        if cur and h[1] - cur[0][0] > fragment_size:
            fragments.append(tuple(cur))
            cur = []
        cur.append(h)
    if cur:
        fragments.append(tuple(cur))
    out = []
    for frag_hits in fragments[:number_of_fragments]:
        s = max(0, frag_hits[0][0] - (fragment_size - (frag_hits[-1][1] - frag_hits[0][0])) // 2)
        e = min(len(text), s + max(fragment_size, frag_hits[-1][1] - frag_hits[0][0]))
        rel = [(a - s, b - s) for a, b in frag_hits if a >= s and b <= e]
        out.append(_mark(text[s:e], rel, pre_tag, post_tag))
    return out


def highlight_fvh(text: str, terms: Set[str],
                  tv_entries: List[tuple],
                  pre_tag: str = "<em>", post_tag: str = "</em>",
                  fragment_size: int = 100,
                  number_of_fragments: int = 5) -> List[str]:
    """Real FastVectorHighlighter path (reference
    `search/fetch/subphase/highlight/FastVectorHighlighter`): hit offsets
    come from the PERSISTED term vectors (term_vector=with_positions_offsets
    at index time), no re-analysis, and fragments rank by match count
    (score-ordered like the reference's ScoreOrderFragmentsBuilder)."""
    exact = {t for t in terms if not t.endswith("*")}
    prefixes = tuple(t[:-1] for t in terms if t.endswith("*") and len(t) > 1)
    hits = sorted(
        (s, e) for term, _pos, s, e in tv_entries
        if (term in exact or (prefixes and term.startswith(prefixes)))
        and 0 <= s and e <= len(text))
    if not hits:
        return []
    if number_of_fragments == 0:
        return [_mark(text, hits, pre_tag, post_tag)]
    fragments: List[tuple] = []
    cur: List[tuple] = []
    for h in hits:
        if cur and h[1] - cur[0][0] > fragment_size:
            fragments.append(tuple(cur))
            cur = []
        cur.append(h)
    if cur:
        fragments.append(tuple(cur))
    # FVH scores fragments: most matches first (stable on position)
    fragments.sort(key=lambda fr: -len(fr))
    out = []
    for frag_hits in fragments[:number_of_fragments]:
        s = max(0, frag_hits[0][0]
                - (fragment_size - (frag_hits[-1][1] - frag_hits[0][0])) // 2)
        e = min(len(text), s + max(fragment_size,
                                   frag_hits[-1][1] - frag_hits[0][0]))
        rel = [(a - s, b - s) for a, b in frag_hits if a >= s and b <= e]
        out.append(_mark(text[s:e], rel, pre_tag, post_tag))
    return out


def highlight_unified(text: str, terms: Set[str], analyzer: Analyzer,
                      pre_tag: str = "<em>", post_tag: str = "</em>",
                      fragment_size: int = 100,
                      number_of_fragments: int = 5) -> List[str]:
    """Unified-highlighter analog (reference
    `subphase/highlight/UnifiedHighlighter.java` over Lucene's passage
    formatter): sentence-bounded passages scored by distinct matched terms
    (unique-term coverage first, then hit count), best passages returned in
    score order."""
    exact = {t for t in terms if not t.endswith("*")}
    prefixes = tuple(t[:-1] for t in terms if t.endswith("*") and len(t) > 1)
    tokens = analyzer.analyze(text)
    hits = [(t.start_offset, t.end_offset, t.text) for t in tokens
            if t.text in exact or (prefixes and t.text.startswith(prefixes))]
    if not hits:
        return []
    if number_of_fragments == 0:
        return [_mark(text, [(a, b) for a, b, _ in hits], pre_tag, post_tag)]
    # sentence-ish passage boundaries, merged up to ~fragment_size
    bounds = [0]
    for i, ch in enumerate(text):
        if ch in ".!?\n":
            bounds.append(i + 1)
    if bounds[-1] != len(text):
        bounds.append(len(text))
    passages: List[tuple] = []
    s = bounds[0]
    for e in bounds[1:]:
        if e - s >= fragment_size and s != e:
            passages.append((s, e))
            s = e
    if s < len(text):
        passages.append((s, len(text)))
    scored = []
    for (a, b) in passages:
        ph = [(ha, hb, tt) for ha, hb, tt in hits if ha >= a and hb <= b]
        if not ph:
            continue
        uniq = len({tt for _, _, tt in ph})
        scored.append((uniq, len(ph), a, b, ph))
    scored.sort(key=lambda x: (-x[0], -x[1], x[2]))
    out = []
    for _u, _n, a, b, ph in scored[:number_of_fragments]:
        rel = [(ha - a, hb - a) for ha, hb, _ in ph]
        out.append(_mark(text[a:b], rel, pre_tag, post_tag))
    return out


def _mark(text: str, spans: List[tuple], pre: str, post: str) -> str:
    out = []
    prev = 0
    for a, b in spans:
        out.append(text[prev:a])
        out.append(pre)
        out.append(text[a:b])
        out.append(post)
        prev = b
    out.append(text[prev:])
    return "".join(out)


def collect_query_terms(lnode) -> Dict[str, Set[str]]:
    """field -> query terms, walked from the logical plan (for highlighting)."""
    from .compiler import (LBool, LBoosting, LConstScore, LDisMax, LFuncScore,
                           LPhrase, LTerms)

    out: Dict[str, Set[str]] = {}

    def walk(n):
        if n is None:
            return
        if isinstance(n, LPhrase):
            s = out.setdefault(n.field, set())
            s.update(n.terms[:-1] if n.prefix_last else n.terms)
            if n.prefix_last:
                s.add(n.terms[-1] + "*")  # "*" suffix marks a prefix match
        elif isinstance(n, LTerms):
            out.setdefault(n.field, set()).update(n.terms)
        elif isinstance(n, LBool):
            for c in n.musts + n.shoulds + n.filters:
                walk(c)
        elif isinstance(n, LConstScore):
            walk(n.child)
        elif isinstance(n, LDisMax):
            for c in n.children:
                walk(c)
        elif isinstance(n, LBoosting):
            walk(n.positive)
        elif isinstance(n, LFuncScore):
            walk(n.child)

    walk(lnode)
    return out
