"""Query DSL parsing: JSON dicts -> QueryBuilder tree. Analog of reference
`index/query/*QueryBuilder.java` fromXContent parsers (same DSL surface).

The tree is *unrewritten*: analysis, multi-term expansion, and idf weighting
happen in `compiler.rewrite` (the analog of QueryBuilder.rewrite +
Query.createWeight, which need index statistics).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Tuple


class QueryParseError(ValueError):
    """Analog of reference ParsingException (HTTP 400)."""


@dataclass
class Query:
    boost: float = 1.0
    name: Optional[str] = None  # _name for matched_queries


@dataclass
class MatchAllQuery(Query):
    pass


@dataclass
class MatchNoneQuery(Query):
    pass


@dataclass
class TermQuery(Query):
    field: str = ""
    value: Any = None
    case_insensitive: bool = False


@dataclass
class TermsQuery(Query):
    field: str = ""
    values: List[Any] = dc_field(default_factory=list)


@dataclass
class MatchQuery(Query):
    field: str = ""
    query: Any = None
    operator: str = "or"
    minimum_should_match: Optional[str] = None
    analyzer: Optional[str] = None
    fuzziness: Optional[Any] = None


@dataclass
class MultiMatchQuery(Query):
    fields: List[str] = dc_field(default_factory=list)
    query: Any = None
    type: str = "best_fields"
    operator: str = "or"
    tie_breaker: float = 0.0
    minimum_should_match: Optional[str] = None


@dataclass
class MatchPhraseQuery(Query):
    field: str = ""
    query: Any = None
    slop: int = 0
    analyzer: Optional[str] = None
    prefix: bool = False               # match_phrase_prefix
    max_expansions: int = 50


@dataclass
class SpanTermQuery(Query):
    field: str = ""
    value: str = ""


@dataclass
class SpanNearQuery(Query):
    clauses: List[Query] = dc_field(default_factory=list)
    slop: int = 0
    in_order: bool = True


@dataclass
class SpanOrQuery(Query):
    clauses: List[Query] = dc_field(default_factory=list)


@dataclass
class SpanNotQuery(Query):
    include: Optional[Query] = None
    exclude: Optional[Query] = None
    pre: int = 0
    post: int = 0


@dataclass
class SpanFirstQuery(Query):
    match: Optional[Query] = None
    end: int = 0


@dataclass
class SpanContainingQuery(Query):
    big: Optional[Query] = None
    little: Optional[Query] = None


@dataclass
class SpanWithinQuery(Query):
    big: Optional[Query] = None
    little: Optional[Query] = None


@dataclass
class SpanMultiQuery(Query):
    match: Optional[Query] = None      # prefix/wildcard/fuzzy/regexp


@dataclass
class FieldMaskingSpanQuery(Query):
    query: Optional[Query] = None
    field: str = ""                    # the masked-as field


@dataclass
class IntervalRule:
    """One node of the intervals source tree (reference
    IntervalsSourceProvider: match/prefix/wildcard/fuzzy/all_of/any_of with
    an optional filter)."""

    kind: str                          # match|prefix|wildcard|fuzzy|all_of|any_of
    query: str = ""
    max_gaps: int = -1
    ordered: bool = False
    analyzer: Optional[str] = None
    rules: List["IntervalRule"] = dc_field(default_factory=list)
    fuzziness: Any = "AUTO"
    prefix_length: int = 0
    filter_kind: Optional[str] = None  # containing|contained_by|not_containing|
    #                                    not_contained_by|not_overlapping|before|after
    filter_rule: Optional["IntervalRule"] = None


@dataclass
class IntervalsQuery(Query):
    field: str = ""
    rule: Optional[IntervalRule] = None
    # back-compat accessors for the old single-match form
    query: str = ""
    max_gaps: int = -1
    ordered: bool = False
    analyzer: Optional[str] = None


@dataclass
class BoolQuery(Query):
    must: List[Query] = dc_field(default_factory=list)
    should: List[Query] = dc_field(default_factory=list)
    must_not: List[Query] = dc_field(default_factory=list)
    filter: List[Query] = dc_field(default_factory=list)
    minimum_should_match: Optional[str] = None


@dataclass
class RangeQuery(Query):
    field: str = ""
    gte: Any = None
    gt: Any = None
    lte: Any = None
    lt: Any = None
    date_format: Optional[str] = None
    relation: str = "intersects"   # range-field targets (RangeFieldMapper)


@dataclass
class ExistsQuery(Query):
    field: str = ""


@dataclass
class IdsQuery(Query):
    values: List[str] = dc_field(default_factory=list)


@dataclass
class ConstantScoreQuery(Query):
    filter: Optional[Query] = None


@dataclass
class BoostingQuery(Query):
    positive: Optional[Query] = None
    negative: Optional[Query] = None
    negative_boost: float = 0.5


@dataclass
class DisMaxQuery(Query):
    queries: List[Query] = dc_field(default_factory=list)
    tie_breaker: float = 0.0


@dataclass
class PrefixQuery(Query):
    field: str = ""
    value: str = ""
    case_insensitive: bool = False


@dataclass
class WildcardQuery(Query):
    field: str = ""
    value: str = ""
    case_insensitive: bool = False


@dataclass
class RegexpQuery(Query):
    field: str = ""
    value: str = ""


@dataclass
class FuzzyQuery(Query):
    field: str = ""
    value: str = ""
    fuzziness: Any = "AUTO"
    prefix_length: int = 0


@dataclass
class QueryStringQuery(Query):
    query: str = ""
    default_field: Optional[str] = None
    fields: List[str] = dc_field(default_factory=list)
    default_operator: str = "or"
    phrase_slop: int = 0


@dataclass
class SimpleQueryStringQuery(Query):
    query: str = ""
    fields: List[str] = dc_field(default_factory=list)
    default_operator: str = "or"


@dataclass
class GeoDistanceQuery(Query):
    field: str = ""
    lat: float = 0.0
    lon: float = 0.0
    distance_m: float = 0.0
    # internal: strict < for agg-refinement ring boundaries ("_inclusive")
    inclusive: bool = True


@dataclass
class GeoBoundingBoxQuery(Query):
    field: str = ""
    top: float = 0.0
    left: float = 0.0
    bottom: float = 0.0
    right: float = 0.0


@dataclass
class TermsSetQuery(Query):
    """terms_set: per-DOC minimum_should_match from a numeric field or a
    script (reference TermsSetQueryBuilder.java)."""

    field: str = ""
    terms: List[Any] = dc_field(default_factory=list)
    minimum_should_match_field: Optional[str] = None
    minimum_should_match_script: Optional[Any] = None


@dataclass
class MatchBoolPrefixQuery(Query):
    field: str = ""
    query: Any = None
    operator: str = "or"
    analyzer: Optional[str] = None


@dataclass
class CombinedFieldsQuery(Query):
    """combined_fields: BM25F over weighted fields — combined tf/dl on
    device, union df for the idf (reference CombinedFieldsQueryBuilder)."""

    query: Any = None
    fields: List[str] = dc_field(default_factory=list)
    operator: str = "or"
    minimum_should_match: Optional[str] = None


@dataclass
class PinnedQuery(Query):
    ids: List[str] = dc_field(default_factory=list)
    organic: Optional[Query] = None


@dataclass
class GeoPolygonQuery(Query):
    field: str = ""
    # vertex lists, parallel (lat[i], lon[i])
    lats: List[float] = dc_field(default_factory=list)
    lons: List[float] = dc_field(default_factory=list)


@dataclass
class GeoShapeQuery(Query):
    field: str = ""
    shape: Any = None              # GeoJSON dict or WKT string
    relation: str = "intersects"   # intersects | disjoint | within | contains
    ignore_unmapped: bool = False


@dataclass
class ScoreFunction:
    kind: str                      # weight | field_value_factor | random_score | script_score | decay
    weight: float = 1.0
    filter: Optional[Query] = None
    field: Optional[str] = None
    factor: float = 1.0
    modifier: str = "none"
    missing: Optional[float] = None
    seed: int = 0
    script: Optional[str] = None   # painless-lite source
    script_params: Optional[dict] = None
    # decay (gauss | exp | linear) — reference functionscore/
    # GaussDecayFunctionBuilder.java / ExponentialDecayFunctionBuilder.java /
    # LinearDecayFunctionBuilder.java
    decay_shape: Optional[str] = None   # gauss | exp | linear
    origin: Any = None
    scale: Any = None
    offset: Any = None
    decay: float = 0.5


@dataclass
class MoreLikeThisQuery(Query):
    """Reference `index/query/MoreLikeThisQueryBuilder.java` (Lucene
    MoreLikeThis): select interesting terms from liked texts/docs by tf·idf,
    search as a weighted OR."""

    fields: List[str] = dc_field(default_factory=list)
    like: List[Any] = dc_field(default_factory=list)      # str | {"_id": ...}
    unlike: List[Any] = dc_field(default_factory=list)
    max_query_terms: int = 25
    min_term_freq: int = 2
    min_doc_freq: int = 5
    max_doc_freq: int = 2**31 - 1
    min_word_length: int = 0
    max_word_length: int = 0          # 0 = unbounded
    stop_words: List[str] = dc_field(default_factory=list)
    minimum_should_match: Optional[str] = "30%"
    boost_terms: float = 0.0
    include: bool = False


@dataclass
class FunctionScoreQuery(Query):
    query: Optional[Query] = None
    functions: List[ScoreFunction] = dc_field(default_factory=list)
    score_mode: str = "multiply"   # multiply | sum | avg | max | min | first
    boost_mode: str = "multiply"   # multiply | sum | replace | avg | max | min
    max_boost: float = 3.4e38
    min_score: Optional[float] = None


@dataclass
class ScriptQuery(Query):
    """`script` query: filter docs where the expression is truthy."""

    source: str = ""
    params: Optional[dict] = None


@dataclass
class ScriptScoreQuery(Query):
    """`script_score` query: replace the child's score with the script's."""

    query: Optional[Query] = None
    source: str = ""
    params: Optional[dict] = None
    min_score: Optional[float] = None


@dataclass
class KnnQuery(Query):
    field: str = ""
    vector: List[float] = dc_field(default_factory=list)
    k: int = 10
    filter: Optional[Query] = None
    # ANN overrides (reference k-NN query `method_parameters`): nprobe
    # widens/narrows the IVF probe; exact=True forces the brute-force scan
    nprobe: Optional[int] = None
    exact: bool = False


@dataclass
class NestedQuery(Query):
    path: str = ""
    query: Optional[Query] = None
    score_mode: str = "avg"   # avg | sum | max | min | none
    ignore_unmapped: bool = False
    inner_hits: Optional[dict] = None


@dataclass
class HasChildQuery(Query):
    """Parents with matching children (reference modules/parent-join
    HasChildQueryBuilder)."""

    type: str = ""
    query: Optional[Query] = None
    score_mode: str = "none"  # none | min | max | sum | avg
    min_children: int = 1
    max_children: int = 2**31 - 1
    ignore_unmapped: bool = False
    inner_hits: Optional[dict] = None


@dataclass
class HasParentQuery(Query):
    """Children whose parent matches (reference HasParentQueryBuilder)."""

    parent_type: str = ""
    query: Optional[Query] = None
    score: bool = False
    ignore_unmapped: bool = False
    inner_hits: Optional[dict] = None


@dataclass
class RankFeatureQuery(Query):
    """Score docs by a rank_feature(s) value through one of four monotone
    functions (reference mapper-extras RankFeatureQueryBuilder)."""

    field: str = ""
    function: str = "saturation"   # saturation | log | sigmoid | linear
    pivot: Optional[float] = None  # saturation/sigmoid
    scaling_factor: Optional[float] = None  # log
    exponent: Optional[float] = None        # sigmoid


@dataclass
class DistanceFeatureQuery(Query):
    """Decaying proximity score on date/geo fields:
    boost * pivot / (pivot + distance) (reference DistanceFeatureQueryBuilder)."""

    field: str = ""
    origin: Any = None
    pivot: Any = None


@dataclass
class NeuralSparseQuery(Query):
    """Learned-sparse dot product over a rank_features/sparse_vector field
    (reference neural-search plugin neural_sparse, raw query_tokens mode —
    model inference happens outside the engine)."""

    field: str = ""
    tokens: Dict[str, float] = dc_field(default_factory=dict)


@dataclass
class HybridQuery(Query):
    """Top-level hybrid retrieval (reference neural-search plugin
    HybridQueryBuilder): N independent sub-queries — lexical,
    `neural_sparse`, `knn` — each executed as its own per-shard retrieval
    in its own score domain, fused at the coordinator merge
    (search/fusion.py) with RRF or normalized linear combination.
    Sub-queries stay RAW dicts: each one is re-parsed and served through
    the full serving ladder exactly as if it were the only query."""

    queries: List[dict] = dc_field(default_factory=list)
    # validated fusion parameters (method, rank_constant, weights,
    # normalization, window_size) — see fusion.FusionSpec
    fusion: Dict[str, Any] = dc_field(default_factory=dict)


@dataclass
class PercolateQuery(Query):
    """Match stored percolator queries against candidate document(s)
    (reference modules/percolator PercolateQueryBuilder)."""

    field: str = ""
    documents: List[dict] = dc_field(default_factory=list)
    # reference to an existing doc (resolved by the REST layer before parse)
    index: Optional[str] = None
    id: Optional[str] = None
    routing: Optional[str] = None


@dataclass
class ParentIdQuery(Query):
    """Children of one specific parent id (reference ParentIdQueryBuilder)."""

    type: str = ""
    id: str = ""
    ignore_unmapped: bool = False


def _one_entry(d: dict, what: str) -> Tuple[str, Any]:
    if not isinstance(d, dict) or len(d) != 1:
        raise QueryParseError(f"[{what}] malformed query, expected a single field object")
    return next(iter(d.items()))


def _common(q: Query, body: Any) -> None:
    if isinstance(body, dict):
        q.boost = float(body.get("boost", 1.0))
        q.name = body.get("_name")


def parse_query(dsl: Optional[dict]) -> Query:
    """DSL dict -> Query tree (reference: SearchModule registered parsers)."""
    if dsl is None:
        return MatchAllQuery()
    kind, body = _one_entry(dsl, "query")

    if kind == "match_all":
        q = MatchAllQuery(); _common(q, body); return q
    if kind == "match_none":
        q = MatchNoneQuery(); _common(q, body); return q

    if kind == "term":
        f, spec = _one_entry(body, "term")
        if isinstance(spec, dict):
            q = TermQuery(field=f, value=spec.get("value"),
                          case_insensitive=spec.get("case_insensitive", False))
            _common(q, spec)
        else:
            q = TermQuery(field=f, value=spec)
        return q

    if kind == "terms":
        opts = {k: v for k, v in body.items() if k in ("boost", "_name")}
        fields = [(k, v) for k, v in body.items() if k not in ("boost", "_name")]
        if len(fields) != 1:
            raise QueryParseError("[terms] query requires exactly one field")
        f, vals = fields[0]
        q = TermsQuery(field=f, values=list(vals))
        _common(q, opts)
        return q

    if kind == "match":
        f, spec = _one_entry(body, "match")
        if isinstance(spec, dict):
            q = MatchQuery(field=f, query=spec.get("query"),
                           operator=str(spec.get("operator", "or")).lower(),
                           minimum_should_match=spec.get("minimum_should_match"),
                           analyzer=spec.get("analyzer"),
                           fuzziness=spec.get("fuzziness"))
            _common(q, spec)
        else:
            q = MatchQuery(field=f, query=spec)
        return q

    if kind == "multi_match":
        q = MultiMatchQuery(fields=list(body.get("fields", [])), query=body.get("query"),
                            type=body.get("type", "best_fields"),
                            operator=str(body.get("operator", "or")).lower(),
                            tie_breaker=float(body.get("tie_breaker", 0.0)),
                            minimum_should_match=body.get("minimum_should_match"))
        _common(q, body)
        return q

    if kind in ("match_phrase", "match_phrase_prefix"):
        f, spec = _one_entry(body, kind)
        prefix = kind == "match_phrase_prefix"
        if isinstance(spec, dict):
            q = MatchPhraseQuery(field=f, query=spec.get("query"),
                                 slop=int(spec.get("slop", 0)), analyzer=spec.get("analyzer"),
                                 prefix=prefix,
                                 max_expansions=int(spec.get("max_expansions", 50)))
            _common(q, spec)
        else:
            q = MatchPhraseQuery(field=f, query=spec, prefix=prefix)
        return q

    if kind == "terms_set":
        f, spec = _one_entry(body, "terms_set")
        if not isinstance(spec, dict) or "terms" not in spec:
            raise QueryParseError("[terms_set] requires [terms]")
        msf = spec.get("minimum_should_match_field")
        mss = spec.get("minimum_should_match_script")
        if msf is None and mss is None:
            raise QueryParseError(
                "[terms_set] requires [minimum_should_match_field] or "
                "[minimum_should_match_script]")
        q = TermsSetQuery(field=f, terms=list(spec["terms"]),
                          minimum_should_match_field=msf,
                          minimum_should_match_script=mss)
        _common(q, spec)
        return q

    if kind == "match_bool_prefix":
        f, spec = _one_entry(body, "match_bool_prefix")
        if isinstance(spec, dict):
            q = MatchBoolPrefixQuery(field=f, query=spec.get("query"),
                                     operator=str(spec.get("operator",
                                                           "or")).lower(),
                                     analyzer=spec.get("analyzer"))
            _common(q, spec)
        else:
            q = MatchBoolPrefixQuery(field=f, query=spec)
        return q

    if kind == "combined_fields":
        q = CombinedFieldsQuery(query=body.get("query"),
                                fields=list(body.get("fields", [])),
                                operator=str(body.get("operator",
                                                      "or")).lower(),
                                minimum_should_match=body.get(
                                    "minimum_should_match"))
        if not q.fields:
            raise QueryParseError("[combined_fields] requires [fields]")
        _common(q, body)
        return q

    if kind == "wrapper":
        import base64
        import json as _json
        try:
            inner = _json.loads(base64.b64decode(body["query"]))
        except Exception as e:
            raise QueryParseError(f"[wrapper] cannot decode query: {e}")
        return parse_query(inner)

    if kind == "pinned":
        organic = body.get("organic")
        q = PinnedQuery(ids=[str(i) for i in body.get("ids", [])],
                        organic=parse_query(organic) if organic else None)
        _common(q, body)
        return q

    if kind == "span_term":
        f, spec = _one_entry(body, "span_term")
        if isinstance(spec, dict):
            q = SpanTermQuery(field=f, value=str(spec.get("value")))
            _common(q, spec)
        else:
            q = SpanTermQuery(field=f, value=str(spec))
        return q

    if kind == "span_near":
        q = SpanNearQuery(clauses=[parse_query(c) for c in body.get("clauses", [])],
                          slop=int(body.get("slop", 0)),
                          in_order=bool(body.get("in_order", True)))
        _common(q, body)
        return q

    if kind == "span_or":
        q = SpanOrQuery(clauses=[parse_query(c)
                                 for c in body.get("clauses", [])])
        _common(q, body)
        return q

    if kind == "span_not":
        dist = int(body.get("dist", 0))
        if "include" not in body or "exclude" not in body:
            raise QueryParseError("[span_not] requires [include] and [exclude]")
        q = SpanNotQuery(include=parse_query(body["include"]),
                         exclude=parse_query(body["exclude"]),
                         pre=int(body.get("pre", dist)),
                         post=int(body.get("post", dist)))
        _common(q, body)
        return q

    if kind == "span_first":
        if "end" not in body or "match" not in body:
            raise QueryParseError("[span_first] requires [match] and [end]")
        q = SpanFirstQuery(match=parse_query(body["match"]),
                           end=int(body["end"]))
        _common(q, body)
        return q

    if kind == "span_containing":
        if "big" not in body or "little" not in body:
            raise QueryParseError(
                "[span_containing] requires [big] and [little]")
        q = SpanContainingQuery(big=parse_query(body["big"]),
                                little=parse_query(body["little"]))
        _common(q, body)
        return q

    if kind == "span_within":
        if "big" not in body or "little" not in body:
            raise QueryParseError("[span_within] requires [big] and [little]")
        q = SpanWithinQuery(big=parse_query(body["big"]),
                            little=parse_query(body["little"]))
        _common(q, body)
        return q

    if kind == "span_multi":
        if "match" not in body:
            raise QueryParseError("[span_multi] requires [match]")
        q = SpanMultiQuery(match=parse_query(body["match"]))
        _common(q, body)
        return q

    if kind == "field_masking_span":
        if "query" not in body:
            raise QueryParseError("[field_masking_span] requires [query]")
        q = FieldMaskingSpanQuery(query=parse_query(body["query"]),
                                  field=body.get("field", ""))
        _common(q, body)
        return q

    if kind == "intervals":
        f, spec = _one_entry(body, "intervals")
        if not isinstance(spec, dict):
            raise QueryParseError("[intervals] needs a rule object")
        rule = parse_interval_rule(spec)
        q = IntervalsQuery(field=f, rule=rule)
        _common(q, spec)
        return q

    if kind == "bool":
        def many(key):
            v = body.get(key, [])
            v = v if isinstance(v, list) else [v]
            return [parse_query(x) for x in v]
        q = BoolQuery(must=many("must"), should=many("should"),
                      must_not=many("must_not"), filter=many("filter"),
                      minimum_should_match=body.get("minimum_should_match"))
        _common(q, body)
        return q

    if kind == "range":
        f, spec = _one_entry(body, "range")
        q = RangeQuery(field=f, gte=spec.get("gte", spec.get("from")),
                       gt=spec.get("gt"), lte=spec.get("lte", spec.get("to")),
                       lt=spec.get("lt"), date_format=spec.get("format"),
                       relation=str(spec.get("relation",
                                             "intersects")).lower())
        _common(q, spec)
        return q

    if kind == "exists":
        q = ExistsQuery(field=body["field"]); _common(q, body); return q

    if kind == "ids":
        q = IdsQuery(values=list(body.get("values", []))); _common(q, body); return q

    if kind == "constant_score":
        q = ConstantScoreQuery(filter=parse_query(body["filter"]))
        _common(q, body)
        return q

    if kind == "boosting":
        q = BoostingQuery(positive=parse_query(body["positive"]),
                          negative=parse_query(body["negative"]),
                          negative_boost=float(body.get("negative_boost", 0.5)))
        _common(q, body)
        return q

    if kind == "dis_max":
        q = DisMaxQuery(queries=[parse_query(x) for x in body.get("queries", [])],
                        tie_breaker=float(body.get("tie_breaker", 0.0)))
        _common(q, body)
        return q

    if kind in ("prefix", "wildcard", "regexp", "fuzzy"):
        f, spec = _one_entry(body, kind)
        if isinstance(spec, dict):
            value = spec.get("value", spec.get(kind))
            ci = spec.get("case_insensitive", False)
        else:
            value, ci, spec = spec, False, {}
        if kind == "prefix":
            q = PrefixQuery(field=f, value=str(value), case_insensitive=ci)
        elif kind == "wildcard":
            q = WildcardQuery(field=f, value=str(value), case_insensitive=ci)
        elif kind == "regexp":
            q = RegexpQuery(field=f, value=str(value))
        else:
            q = FuzzyQuery(field=f, value=str(value),
                           fuzziness=spec.get("fuzziness", "AUTO"),
                           prefix_length=int(spec.get("prefix_length", 0)))
        _common(q, spec)
        return q

    if kind == "query_string":
        q = QueryStringQuery(query=body["query"], default_field=body.get("default_field"),
                             fields=list(body.get("fields", [])),
                             default_operator=str(body.get("default_operator", "or")).lower(),
                             phrase_slop=int(body.get("phrase_slop", 0)))
        _common(q, body)
        return q

    if kind == "simple_query_string":
        q = SimpleQueryStringQuery(query=body["query"], fields=list(body.get("fields", [])),
                                   default_operator=str(body.get("default_operator", "or")).lower())
        _common(q, body)
        return q

    if kind == "geo_distance":
        dist = _parse_distance(body["distance"])
        fields = [(k, v) for k, v in body.items()
                  if k not in ("distance", "boost", "_name",
                               "validation_method", "_inclusive")]
        f, point = fields[0]
        lat, lon = _parse_point(point)
        q = GeoDistanceQuery(field=f, lat=lat, lon=lon, distance_m=dist,
                             inclusive=bool(body.get("_inclusive", True)))
        _common(q, body)
        return q

    if kind == "geo_bounding_box":
        fields = [(k, v) for k, v in body.items() if k not in ("boost", "_name", "validation_method")]
        f, box = fields[0]
        tl = box.get("top_left")
        br = box.get("bottom_right")
        if tl is not None:
            tlat, tlon = _parse_point(tl)
            blat, blon = _parse_point(br)
        else:
            tlat, tlon, blat, blon = box["top"], box["left"], box["bottom"], box["right"]
        q = GeoBoundingBoxQuery(field=f, top=tlat, left=tlon, bottom=blat, right=blon)
        _common(q, body)
        return q

    if kind == "geo_polygon":
        fields = [(k, v) for k, v in body.items()
                  if k not in ("boost", "_name", "validation_method")]
        if not fields or not isinstance(fields[0][1], dict):
            raise QueryParseError("[geo_polygon] requires a field with "
                                  "a [points] object")
        f, spec = fields[0]
        pts = [_parse_point(p) for p in spec.get("points", [])]
        if len(pts) < 3:
            raise QueryParseError(
                "[geo_polygon] requires at least 3 points")
        q = GeoPolygonQuery(field=f, lats=[p[0] for p in pts],
                            lons=[p[1] for p in pts])
        _common(q, body)
        return q

    if kind == "geo_shape":
        fields = [(k, v) for k, v in body.items()
                  if k not in ("boost", "_name", "ignore_unmapped")]
        if not fields:
            raise QueryParseError("[geo_shape] requires a field")
        f, spec = fields[0]
        shape = spec.get("shape", spec.get("indexed_shape"))
        if shape is None:
            raise QueryParseError(
                "[geo_shape] requires [shape] (or a resolved [indexed_shape])")
        rel = str(spec.get("relation", "intersects")).lower()
        if rel not in ("intersects", "disjoint", "within", "contains"):
            raise QueryParseError(f"[geo_shape] unknown relation [{rel}]")
        q = GeoShapeQuery(field=f, shape=shape, relation=rel,
                          ignore_unmapped=bool(body.get("ignore_unmapped",
                                                        False)))
        _common(q, body)
        return q

    if kind == "more_like_this":
        like = body.get("like", [])
        like = like if isinstance(like, list) else [like]
        unlike = body.get("unlike", [])
        unlike = unlike if isinstance(unlike, list) else [unlike]
        if not like:
            raise QueryParseError("[more_like_this] requires [like]")
        q = MoreLikeThisQuery(
            fields=list(body.get("fields", [])), like=like, unlike=unlike,
            max_query_terms=int(body.get("max_query_terms", 25)),
            min_term_freq=int(body.get("min_term_freq", 2)),
            min_doc_freq=int(body.get("min_doc_freq", 5)),
            max_doc_freq=int(body.get("max_doc_freq", 2**31 - 1)),
            min_word_length=int(body.get("min_word_length", 0)),
            max_word_length=int(body.get("max_word_length", 0)),
            stop_words=list(body.get("stop_words", [])),
            minimum_should_match=body.get("minimum_should_match", "30%"),
            boost_terms=float(body.get("boost_terms", 0.0)),
            include=bool(body.get("include", False)))
        _common(q, body)
        return q

    if kind == "function_score":
        inner = parse_query(body.get("query")) if body.get("query") else MatchAllQuery()
        functions = []
        raw_fns = body.get("functions", [])
        if not raw_fns:  # single-function shorthand
            raw_fns = [{k: v for k, v in body.items()
                        if k in ("weight", "field_value_factor", "random_score",
                                 "script_score", "gauss", "exp", "linear")}]
        for fn in raw_fns:
            filt = parse_query(fn["filter"]) if "filter" in fn else None
            shape = next((s for s in ("gauss", "exp", "linear") if s in fn), None)
            if shape is not None:
                spec = dict(fn[shape])
                spec.pop("multi_value_mode", None)
                if len(spec) != 1:
                    raise QueryParseError(
                        f"[{shape}] decay needs exactly one field")
                dfield, dspec = next(iter(spec.items()))
                if "scale" not in dspec:
                    raise QueryParseError(f"[{shape}] requires [scale]")
                functions.append(ScoreFunction(
                    "decay", fn.get("weight", 1.0), filt, dfield,
                    decay_shape=shape, origin=dspec.get("origin"),
                    scale=dspec["scale"], offset=dspec.get("offset", 0),
                    decay=float(dspec.get("decay", 0.5))))
            elif "field_value_factor" in fn:
                fv = fn["field_value_factor"]
                functions.append(ScoreFunction("field_value_factor", fn.get("weight", 1.0),
                                               filt, fv["field"], fv.get("factor", 1.0),
                                               fv.get("modifier", "none"), fv.get("missing")))
            elif "random_score" in fn:
                functions.append(ScoreFunction("random_score", fn.get("weight", 1.0), filt,
                                               seed=int(fn["random_score"].get("seed", 0))))
            elif "script_score" in fn:
                src, prm = parse_script_spec(fn["script_score"].get("script"))
                functions.append(ScoreFunction("script_score", fn.get("weight", 1.0),
                                               filt, script=src, script_params=prm))
            elif "weight" in fn:
                functions.append(ScoreFunction("weight", float(fn["weight"]), filt))
        q = FunctionScoreQuery(query=inner, functions=functions,
                               score_mode=body.get("score_mode", "multiply"),
                               boost_mode=body.get("boost_mode", "multiply"),
                               min_score=body.get("min_score"))
        _common(q, body)
        return q

    if kind == "script":
        src, prm = parse_script_spec(body.get("script"))
        q = ScriptQuery(source=src, params=prm)
        _common(q, body)
        return q

    if kind == "script_score":
        src, prm = parse_script_spec(body.get("script"))
        q = ScriptScoreQuery(query=parse_query(body.get("query")), source=src,
                             params=prm, min_score=body.get("min_score"))
        _common(q, body)
        return q

    if kind == "knn":
        # OpenSearch k-NN plugin form: {"knn": {"fieldname": {"vector": [...],
        # "k": 10, "filter": {...}}}}
        f, spec = _one_entry(body, "knn")
        mp = spec.get("method_parameters", {})
        nprobe = mp.get("nprobe", spec.get("nprobe"))
        q = KnnQuery(field=f, vector=list(spec["vector"]),
                     k=int(spec.get("k", 10)),
                     filter=parse_query(spec["filter"]) if spec.get("filter") else None,
                     nprobe=int(nprobe) if nprobe is not None else None,
                     exact=bool(spec.get("exact", False)))
        _common(q, spec)
        return q

    if kind == "nested":
        q = NestedQuery(path=body["path"], query=parse_query(body["query"]),
                        score_mode=body.get("score_mode", "avg"),
                        ignore_unmapped=bool(body.get("ignore_unmapped", False)),
                        inner_hits=body.get("inner_hits"))
        _common(q, body)
        return q

    if kind == "has_child":
        if body.get("score_mode", "none") not in ("none", "min", "max", "sum", "avg"):
            raise QueryParseError(
                f"[has_child] unknown score_mode [{body['score_mode']}]")
        q = HasChildQuery(type=body["type"], query=parse_query(body["query"]),
                          score_mode=body.get("score_mode", "none"),
                          min_children=int(body.get("min_children", 1)),
                          max_children=int(body.get("max_children", 2**31 - 1)),
                          ignore_unmapped=bool(body.get("ignore_unmapped", False)),
                          inner_hits=body.get("inner_hits"))
        _common(q, body)
        return q

    if kind == "has_parent":
        q = HasParentQuery(parent_type=body["parent_type"],
                           query=parse_query(body["query"]),
                           score=bool(body.get("score", False)),
                           ignore_unmapped=bool(body.get("ignore_unmapped", False)),
                           inner_hits=body.get("inner_hits"))
        _common(q, body)
        return q

    if kind == "parent_id":
        q = ParentIdQuery(type=body["type"], id=str(body["id"]),
                          ignore_unmapped=bool(body.get("ignore_unmapped", False)))
        _common(q, body)
        return q

    if kind == "rank_feature":
        fns = [k for k in ("saturation", "log", "sigmoid", "linear") if k in body]
        if len(fns) > 1:
            raise QueryParseError("[rank_feature] accepts at most one function")
        fn = fns[0] if fns else "saturation"
        spec = body.get(fn) or {}
        if fn == "log" and "scaling_factor" not in spec:
            raise QueryParseError("[rank_feature] [log] requires scaling_factor")
        if fn == "sigmoid" and ("pivot" not in spec or "exponent" not in spec):
            raise QueryParseError("[rank_feature] [sigmoid] requires pivot and exponent")
        q = RankFeatureQuery(field=body["field"], function=fn,
                             pivot=spec.get("pivot"),
                             scaling_factor=spec.get("scaling_factor"),
                             exponent=spec.get("exponent"))
        _common(q, body)
        return q

    if kind == "distance_feature":
        if body.get("origin") is None or body.get("pivot") is None:
            raise QueryParseError("[distance_feature] requires origin and pivot")
        q = DistanceFeatureQuery(field=body["field"], origin=body["origin"],
                                 pivot=body["pivot"])
        _common(q, body)
        return q

    if kind == "neural_sparse":
        f, spec = _one_entry(body, "neural_sparse")
        tokens = spec.get("query_tokens")
        if not isinstance(tokens, dict) or not tokens:
            raise QueryParseError(
                "[neural_sparse] requires query_tokens (raw token weights; "
                "model inference is out of engine scope)")
        q = NeuralSparseQuery(field=f,
                              tokens={str(t): float(w) for t, w in tokens.items()})
        _common(q, spec)
        return q

    if kind == "hybrid":
        subs = body.get("queries")
        if not isinstance(subs, list) or not subs:
            raise QueryParseError("[hybrid] requires a non-empty [queries] "
                                  "list")
        if len(subs) > MAX_HYBRID_SUB_QUERIES:
            raise QueryParseError(
                f"[hybrid] supports at most {MAX_HYBRID_SUB_QUERIES} "
                f"sub-queries, got {len(subs)}")
        for sub in subs:
            if not isinstance(sub, dict):
                raise QueryParseError("[hybrid] sub-queries must be query "
                                      "objects")
            inner = parse_query(sub)   # surface malformed subs as 400s now
            if isinstance(inner, HybridQuery):
                raise QueryParseError("[hybrid] queries cannot nest")
        q = HybridQuery(queries=[dict(s) for s in subs],
                        fusion=parse_fusion_spec(body.get("fusion"),
                                                 len(subs)))
        _common(q, body)
        return q

    if kind == "percolate":
        docs = body.get("documents")
        if docs is None and body.get("document") is not None:
            docs = [body["document"]]
        if docs is None and body.get("index") is None:
            raise QueryParseError(
                "[percolate] requires `document`, `documents`, or `index`+`id`")
        q = PercolateQuery(field=body["field"], documents=list(docs or []),
                           index=body.get("index"), id=body.get("id"),
                           routing=body.get("routing"))
        _common(q, body)
        return q

    raise QueryParseError(f"unknown query [{kind}]")


# reference neural-search HybridQueryBuilder caps sub-queries at 5
MAX_HYBRID_SUB_QUERIES = 5

_FUSION_METHODS = ("rrf", "linear")
_FUSION_NORMS = ("min_max", "l2")
# fused pages must be stable under pagination: the fused list is computed
# over fixed-depth per-sub-query rank windows, so `from`/`size` page INTO
# one deterministic list instead of re-fusing a different window per page
DEFAULT_FUSION_WINDOW = 100


def parse_fusion_spec(spec, n_sub: int) -> Dict[str, Any]:
    """Validate the [hybrid] fusion parameters -> canonical dict.

    - method: "rrf" (default) | "linear"
    - rank_constant: RRF k (default 60, >= 1)
    - weights: per-sub-query weights (default all 1.0, non-negative)
    - normalization: "min_max" (default) | "l2" — linear only; RRF fuses
      in the rank domain, which is score-domain-free by construction
    - window_size: per-sub-query rank-list depth the fusion sees
      (default 100); `from + size` beyond it is a 400, never a silent
      re-fusion at a different depth
    """
    spec = dict(spec or {})
    method = str(spec.get("method", "rrf")).lower()
    if method not in _FUSION_METHODS:
        raise QueryParseError(
            f"[hybrid] unknown fusion method [{method}] "
            f"(supported: {', '.join(_FUSION_METHODS)})")
    norm = str(spec.get("normalization", "min_max")).lower()
    if norm not in _FUSION_NORMS:
        # raw sub-query scores live in incomparable similarity domains
        # (BM25 vs cosine vs learned-sparse dot); a linear combination
        # without a normalizer is meaningless — refuse it (OSL604)
        raise QueryParseError(
            f"[hybrid] unknown normalization [{norm}] "
            f"(supported: {', '.join(_FUSION_NORMS)})")
    try:
        rank_constant = float(spec.get("rank_constant", 60))
        window = int(spec.get("window_size", DEFAULT_FUSION_WINDOW))
        weights = [float(w) for w in spec.get("weights",
                                              [1.0] * n_sub)]
    except (TypeError, ValueError) as e:
        raise QueryParseError(f"[hybrid] malformed fusion spec: {e}")
    if rank_constant < 1:
        raise QueryParseError("[hybrid] rank_constant must be >= 1")
    if window < 1:
        raise QueryParseError("[hybrid] window_size must be >= 1")
    if len(weights) != n_sub:
        raise QueryParseError(
            f"[hybrid] weights length [{len(weights)}] must match the "
            f"sub-query count [{n_sub}]")
    if any(w < 0 or w != w for w in weights):
        raise QueryParseError("[hybrid] weights must be finite and "
                              "non-negative")
    return {"method": method, "rank_constant": rank_constant,
            "weights": weights, "normalization": norm,
            "window_size": window}


def parse_script_spec(spec) -> Tuple[str, dict]:
    """{"source": ..., "params": ...} | "inline src" -> (source, params)
    (reference Script.parse; `lang` is accepted and ignored — painless-lite
    is the only engine)."""
    if spec is None:
        raise QueryParseError("missing required [script]")
    if isinstance(spec, str):
        return spec, {}
    if isinstance(spec, dict):
        src = spec.get("source", spec.get("inline"))
        if not isinstance(src, str):
            raise QueryParseError("script requires a [source] string")
        return src, dict(spec.get("params") or {})
    raise QueryParseError("malformed [script]")


def _parse_distance(d) -> float:
    """'5km', '100m', '2mi' -> meters (reference DistanceUnit). Longest
    suffix wins ('5nmi' is nautical miles, not '5n' miles)."""
    if isinstance(d, (int, float)):
        return float(d)
    s = str(d).strip().lower()
    units = [("nauticalmiles", 1852.0), ("kilometers", 1000.0),
             ("meters", 1.0), ("miles", 1609.344), ("nmi", 1852.0),
             ("km", 1000.0), ("mi", 1609.344), ("yd", 0.9144),
             ("ft", 0.3048), ("in", 0.0254), ("mm", 0.001), ("cm", 0.01),
             ("m", 1.0)]
    for suf, mult in units:
        if s.endswith(suf):
            return float(s[: -len(suf)]) * mult
    return float(s)


def _parse_point(p) -> Tuple[float, float]:
    if isinstance(p, dict):
        return float(p["lat"]), float(p["lon"])
    if isinstance(p, str):
        lat, lon = p.split(",")
        return float(lat), float(lon)
    return float(p[1]), float(p[0])  # GeoJSON [lon, lat]


_INTERVAL_FILTERS = ("containing", "contained_by", "not_containing",
                     "not_contained_by", "not_overlapping", "before", "after")


def parse_interval_rule(spec: dict) -> IntervalRule:
    """Parse one intervals source node (reference IntervalsSourceProvider)."""
    kinds = [k for k in spec if k in ("match", "prefix", "wildcard", "fuzzy",
                                      "all_of", "any_of")]
    if len(kinds) != 1:
        raise QueryParseError(
            "[intervals] rule must define exactly one of "
            "[match|prefix|wildcard|fuzzy|all_of|any_of]")
    kind = kinds[0]
    body = spec[kind]
    if not isinstance(body, dict):
        body = {"query": body}
    rule = IntervalRule(kind=kind)
    if kind in ("match", "prefix", "wildcard", "fuzzy"):
        rule.query = str(body.get("query", body.get(kind, body.get(
            "prefix" if kind == "prefix" else "pattern", ""))))
        rule.analyzer = body.get("analyzer")
        rule.max_gaps = int(body.get("max_gaps", -1))
        rule.ordered = bool(body.get("ordered", False))
        if kind == "fuzzy":
            rule.query = str(body.get("term", body.get("query", "")))
            rule.fuzziness = body.get("fuzziness", "AUTO")
            rule.prefix_length = int(body.get("prefix_length", 0))
    else:
        rule.max_gaps = int(body.get("max_gaps", -1))
        rule.ordered = bool(body.get("ordered", False))
        rule.rules = [parse_interval_rule(r) for r in body.get("intervals", [])]
        if not rule.rules:
            raise QueryParseError(f"[intervals] [{kind}] needs [intervals]")
    filt = body.get("filter")
    if filt:
        fk = [k for k in filt if k in _INTERVAL_FILTERS]
        if len(fk) != 1:
            raise QueryParseError(
                f"[intervals] filter must be one of {_INTERVAL_FILTERS}")
        rule.filter_kind = fk[0]
        rule.filter_rule = parse_interval_rule(filt[fk[0]])
    return rule


def parse_minimum_should_match(spec: Optional[str], n_optional: int) -> int:
    """'2', '-1', '75%', '-25%', and conditional '3<90%' / multi
    '2<-25% 9<-3' semantics (reference Queries.calculateMinShouldMatch)."""
    if spec is None or n_optional == 0:
        return 0 if spec is None else 0
    s = str(spec).strip()
    if "<" in s:
        # each "n<rule": when n_optional > n, apply rule; pick the clause
        # with the LARGEST matching n (Lucene applies them in order)
        result = n_optional  # fewer than every threshold -> all required
        best_n = -1
        for part in s.split():
            if "<" not in part:
                raise QueryParseError(f"invalid minimum_should_match [{spec}]")
            left, right = part.split("<", 1)
            try:
                thr = int(left)
            except ValueError:
                raise QueryParseError(f"invalid minimum_should_match [{spec}]")
            if n_optional > thr and thr > best_n:
                best_n = thr
                result = parse_minimum_should_match(right, n_optional)
        return result
    try:
        if s.endswith("%"):
            pct = float(s[:-1])
            if pct < 0:
                return max(n_optional - int(-pct / 100.0 * n_optional), 0)
            return int(pct / 100.0 * n_optional)
        v = int(s)
        if v < 0:
            return max(n_optional + v, 0)
        return min(v, n_optional)
    except ValueError:
        raise QueryParseError(f"invalid minimum_should_match [{spec}]")
