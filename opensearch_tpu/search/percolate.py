"""Percolator: reverse search — stored queries matched against candidate
documents. Reference `modules/percolator` (PercolatorFieldMapper extracts
query terms at index time; PercolateQueryBuilder builds a MemoryIndex per
candidate doc and runs the pre-filtered stored queries against it).

TPU-native shape: the "MemoryIndex" is an ordinary in-memory `Segment` built
from the candidate doc(s); stored queries are pre-filtered by their extracted
terms (indexed as a hidden `<field>#terms` keyword column, NUL-joined
"field\\0term" strings) and then evaluated by a **host numpy evaluator** over
the logical plan — percolation runs thousands of tiny 1-doc matches, where a
per-query XLA compile would dwarf the work; the device path stays the
fallback for node kinds the host evaluator doesn't cover (scripts, joins,
knn)."""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from ..index.mappings import Mappings
from ..index.segment import Segment, build_segment
from . import compiler as C
from . import query_dsl as dsl

# ---------------------------------------------------------------------------
# index-time term extraction (reference QueryAnalyzer)
# ---------------------------------------------------------------------------


def _extract(n) -> Optional[Set[Tuple[str, str]]]:
    """A set of (field, term) pairs such that a doc can only match `n` if it
    contains at least one of them — or None when no such guarantee exists
    (the stored query must then always be evaluated)."""
    if isinstance(n, C.LTerms):
        if not n.terms:
            return None
        if n.msm >= len(n.terms):
            # conjunction: every term is individually necessary; one suffices
            return {(n.field, n.terms[0])}
        return {(n.field, t) for t in n.terms}
    if isinstance(n, C.LPhrase):
        terms = n.terms[:-1] if n.prefix_last and len(n.terms) > 1 else n.terms
        if not terms or (n.prefix_last and len(n.terms) == 1):
            return None
        return {(n.field, terms[0])}
    if isinstance(n, C.LBool):
        best: Optional[Set] = None
        for c in n.musts + n.filters:
            s = _extract(c)
            if s is not None and (best is None or len(s) < len(best)):
                best = s
        if best is not None:
            return best
        if n.shoulds and n.msm >= 1 and not n.musts and not n.filters:
            union: Set = set()
            for c in n.shoulds:
                s = _extract(c)
                if s is None:
                    return None
                union |= s
            return union
        return None
    if isinstance(n, C.LConstScore):
        return _extract(n.child)
    if isinstance(n, C.LBoosting):
        return _extract(n.positive)
    if isinstance(n, C.LDisMax):
        union = set()
        for c in n.children:
            s = _extract(c)
            if s is None:
                return None
            union |= s
        return union
    if isinstance(n, C.LFuncScore):
        return _extract(n.child)
    if isinstance(n, C.LNested):
        return _extract(n.child)
    if isinstance(n, C.LMatchNone):
        return set()  # never matches; empty necessary set keeps it skippable
    return None


def extract_index_terms(qdict: dict, mappings: Mappings) -> Tuple[List[str], bool]:
    """Parse+validate a stored percolator query and extract its pre-filter
    terms. Returns (["field\\0term", ...], always_run)."""
    q = dsl.parse_query(qdict)
    ctx = C.ShardContext(mappings, [])
    lroot = C.rewrite(q, ctx, scoring=False)
    s = _extract(lroot)
    if s is None:
        return [], True
    return sorted({f"{f}\x00{t}" for f, t in s}), False


# ---------------------------------------------------------------------------
# candidate "memory index"
# ---------------------------------------------------------------------------


def _clone_mappings(m: Mappings) -> Mappings:
    """Shallow clone so dynamic mapping of unseen candidate-doc fields never
    leaks into the real index mappings (reference maps unmapped percolated
    fields in a throwaway context the same way)."""
    m2 = copy.copy(m)
    m2.fields = dict(m.fields)
    m2.aliases = dict(m.aliases)
    m2.nested_paths = set(m.nested_paths)
    m2.dynamic_templates = list(m.dynamic_templates)
    return m2


def build_mini(mappings: Mappings, documents: List[dict]):
    """Candidate docs -> (mini Segment, stats context) — the MemoryIndex."""
    m2 = _clone_mappings(mappings)
    parsed = [m2.parse(str(i), doc) for i, doc in enumerate(documents)]
    seg = build_segment("_percolate", parsed, m2)
    ctx = C.ShardContext(m2, [seg])
    return seg, ctx


def candidate_terms(seg: Segment) -> Set[str]:
    out: Set[str] = set()
    for f, pb in seg.postings.items():
        out.update(f"{f}\x00{t}" for t in pb.vocab)
    for blk in seg.nested.values():
        out |= candidate_terms(blk.child)
    return out


# ---------------------------------------------------------------------------
# host evaluator over the logical plan (matched masks only)
# ---------------------------------------------------------------------------


def host_eval(n, seg: Segment, ctx: C.ShardContext) -> np.ndarray:
    """bool[ndocs] matched mask for one LNode over a host-resident segment.
    Mirrors emit()'s matched semantics; falls back to the jitted device path
    for node kinds it doesn't model."""
    live = seg.live[: seg.ndocs]

    if isinstance(n, C.LMatchAll):
        return live.copy()
    if isinstance(n, C.LMatchNone):
        return np.zeros(seg.ndocs, bool)
    if isinstance(n, C.LTerms):
        pb = seg.postings.get(n.field)
        if pb is None:
            return np.zeros(seg.ndocs, bool)
        count = np.zeros(seg.ndocs, np.int32)
        for t in n.terms:
            r = pb.row(t)
            if r >= 0:
                a, b = pb.row_slice(r)
                count[pb.doc_ids[a:b]] += 1
        return (count >= max(n.msm, 1)) & live
    if isinstance(n, C.LExpandTerms):
        rows = n.expander(seg)
        pb = seg.postings.get(n.field)
        mask = np.zeros(seg.ndocs, bool)
        if pb is not None:
            for r in np.asarray(rows).tolist():
                a, b = pb.row_slice(int(r))
                mask[pb.doc_ids[a:b]] = True
        return mask & live
    if isinstance(n, C.LPhrase):
        from .executor import _host_phrase_freq
        mask = np.zeros(seg.ndocs, bool)
        for d in range(seg.ndocs):
            if live[d] and _host_phrase_freq(n, seg, d) > 0:
                mask[d] = True
        return mask
    if isinstance(n, C.LRange):
        col = seg.numeric_cols.get(n.field)
        if col is None:
            return np.zeros(seg.ndocs, bool)
        v = col.values[: seg.ndocs]
        mask = col.present[: seg.ndocs].copy()
        if n.lo is not None:
            mask &= (v >= n.lo) if n.include_lo else (v > n.lo)
        if n.hi is not None:
            mask &= (v <= n.hi) if n.include_hi else (v < n.hi)
        return mask & live
    if isinstance(n, C.LExists):
        f = n.field
        if f in seg.numeric_cols:
            present = seg.numeric_cols[f].present[: seg.ndocs]
        elif f in seg.keyword_cols:
            present = seg.keyword_cols[f].min_ord[: seg.ndocs] >= 0
        elif f in seg.geo_cols:
            present = seg.geo_cols[f].present[: seg.ndocs]
        elif f in seg.doc_lens:
            present = seg.doc_lens[f][: seg.ndocs] > 0
        else:
            return np.zeros(seg.ndocs, bool)
        return np.asarray(present, bool) & live
    if isinstance(n, C.LIds):
        mask = np.zeros(seg.ndocs, bool)
        for i in n.ids:
            d = seg.id2doc.get(i)
            if d is not None:
                mask[d] = True
        return mask & live
    if isinstance(n, C.LBool):
        mask = live.copy()
        for c in n.musts + n.filters:
            mask &= host_eval(c, seg, ctx)
        for c in n.must_nots:
            mask &= ~host_eval(c, seg, ctx)
        if n.shoulds:
            cnt = np.zeros(seg.ndocs, np.int32)
            for c in n.shoulds:
                cnt += host_eval(c, seg, ctx)
            mask &= cnt >= n.msm
        return mask
    if isinstance(n, C.LConstScore):
        return host_eval(n.child, seg, ctx)
    if isinstance(n, C.LBoosting):
        return host_eval(n.positive, seg, ctx)
    if isinstance(n, C.LDisMax):
        mask = np.zeros(seg.ndocs, bool)
        for c in n.children:
            mask |= host_eval(c, seg, ctx)
        return mask
    if isinstance(n, C.LFuncScore) and n.min_score is None:
        return host_eval(n.child, seg, ctx)
    if isinstance(n, C.LNested):
        blk = seg.nested.get(n.path)
        if blk is None or blk.child.ndocs == 0:
            return np.zeros(seg.ndocs, bool)
        cm = host_eval(n.child, blk.child, n.child_ctx)
        mask = np.zeros(seg.ndocs, bool)
        np.logical_or.at(mask, blk.parent_of[cm], True)
        return mask & live
    if isinstance(n, C.LGeoDist):
        col = seg.geo_cols.get(n.field)
        if col is None:
            return np.zeros(seg.ndocs, bool)
        r = 6371008.8
        p1 = np.deg2rad(col.lat[: seg.ndocs].astype(np.float64))
        p2 = np.deg2rad(n.lat)
        dphi = p2 - p1
        dlmb = np.deg2rad(n.lon - col.lon[: seg.ndocs].astype(np.float64))
        a = np.sin(dphi / 2) ** 2 + np.cos(p1) * np.cos(p2) * np.sin(dlmb / 2) ** 2
        d = 2 * r * np.arcsin(np.sqrt(np.clip(a, 0.0, 1.0)))
        return (d <= n.radius_m) & col.present[: seg.ndocs] & live
    if isinstance(n, C.LGeoBox):
        col = seg.geo_cols.get(n.field)
        if col is None:
            return np.zeros(seg.ndocs, bool)
        lat, lon = col.lat[: seg.ndocs], col.lon[: seg.ndocs]
        return ((lat <= n.top) & (lat >= n.bottom) & (lon >= n.left)
                & (lon <= n.right) & col.present[: seg.ndocs] & live)

    # fallback: jitted device evaluation (scripts, knn, joins, min_score)
    params: Dict[str, Any] = {}
    spec = C.prepare(n, seg, ctx, params)
    docs = np.arange(seg.ndocs_pad, dtype=np.int32)
    _, matched = C.run_gather_scores(spec, seg.device_arrays(), params, docs)
    return np.asarray(matched)[: seg.ndocs] > 0


# ---------------------------------------------------------------------------
# percolate-time matching
# ---------------------------------------------------------------------------


def _stored_query(seg: Segment, doc: int, field: str) -> Optional[dsl.Query]:
    cache = getattr(seg, "_percolator_queries", None)
    if cache is None:
        cache = {}
        seg._percolator_queries = cache
    key = (field, doc)
    if key not in cache:
        node: Any = seg.sources[doc]
        for part in field.split("."):
            node = node.get(part) if isinstance(node, dict) else None
            if node is None:
                break
        try:
            cache[key] = dsl.parse_query(node) if isinstance(node, dict) else None
        except dsl.QueryParseError:
            cache[key] = None
    return cache[key]


def candidate_docs(seg: Segment, field: str, cand: Set[str]) -> np.ndarray:
    """Pre-filter: percolator docs whose extracted terms intersect the
    candidate doc's terms, plus always-run docs (reference: the extracted
    terms disjunction + the verified/unknown split)."""
    run = np.zeros(seg.ndocs, bool)
    kcol = seg.keyword_cols.get(f"{field}#terms")
    if kcol is not None and kcol.vocab:
        member = np.fromiter((v in cand for v in kcol.vocab), bool,
                             count=len(kcol.vocab))
        hit = member[kcol.ords]
        np.logical_or.at(run, kcol.doc_of_value[hit], True)
    fcol = seg.keyword_cols.get(f"{field}#flags")
    if fcol is not None:
        run |= fcol.min_ord[: seg.ndocs] >= 0
    return run & seg.live[: seg.ndocs]


def segment_mask(field: str, mini_seg: Segment, mini_ctx: C.ShardContext,
                 seg: Segment) -> np.ndarray:
    """f32[ndocs_pad]: 1.0 for each stored query in `seg` that matches at
    least one candidate doc."""
    mask = np.zeros(seg.ndocs_pad, np.float32)
    cand = candidate_terms(mini_seg)
    for doc in np.nonzero(candidate_docs(seg, field, cand))[0]:
        q = _stored_query(seg, int(doc), field)
        if q is None:
            continue
        lq = C.rewrite(q, mini_ctx, scoring=False)
        if host_eval(lq, mini_seg, mini_ctx).any():
            mask[doc] = 1.0
    return mask


def document_slots(field: str, mini_seg: Segment, mini_ctx: C.ShardContext,
                   seg: Segment, doc: int) -> List[int]:
    """Which candidate documents one stored query matched (fetch-phase
    `_percolator_document_slot`)."""
    q = _stored_query(seg, doc, field)
    if q is None:
        return []
    lq = C.rewrite(q, mini_ctx, scoring=False)
    return [int(i) for i in np.nonzero(host_eval(lq, mini_seg, mini_ctx))[0]]
