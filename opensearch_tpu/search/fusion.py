"""Hybrid retrieval fusion: N independent sub-query retrievals fused at
the coordinator merge (reference neural-search plugin normalization
processor + HybridQueryBuilder; Anserini-HNSW dense+lexical hybrid
serving, arxiv 2304.12139).

Design contract (docs/HYBRID.md):

- A `hybrid` query runs each sub-query as a COMPLETE independent
  retrieval (its own per-shard query phase, its own serving ladder —
  fastpath / impactpath / knn / mesh decline — its own fetch) with a
  fixed rank-window `window_size`. Fusion is then a PURE function of the
  N ranked sub-pages, so the fused page is byte-identical on every
  serving arm that serves byte-identical sub-pages: single-node vs
  `cluster/distnode.py` distributed, scheduler on/off, replica failover.
- Hit identity across sub-pages is `(_index, _id)` — topology-invariant,
  unlike internal doc coordinates.
- **RRF** (`method: rrf`): score(d) = Σ_i w_i / (rank_constant +
  rank_i(d)), rank 1-based, absent lists contribute 0. Rank-domain,
  score-domain-free by construction.
- **Linear** (`method: linear`): per-list scores pass through a
  NORMALIZER first — `min_max` ((s-min)/(max-min); a degenerate
  constant list maps to 1.0 for present docs) or `l2` (s/‖s‖₂) — then
  fused = Σ_i w_i · norm_i(d). Raw sub-query scores live in
  incomparable similarity domains (BM25 sums vs cosine vs sparse dot);
  combining them unnormalized is an oslint error (OSL604).
- Deterministic total order: fused score desc, then the best
  (sub-query index, rank) coordinate a doc holds, then `(_index, _id)`.
  Commutative over shard/node arrival order because it never looks at
  arrival order.
- Pagination: `from + size` must fit inside `window_size` (400
  otherwise). The fused list over fixed-depth windows is one
  deterministic list — page 2 continues exactly where page 1 stopped.
- Totals are an honest lower bound: the union size of N sub-result sets
  is unknowable from their top windows, so `hits.total` reports the max
  sub-total with relation `gte` (unless there is a single sub-query).
- Aggregations ride as ONE extra sub-search over the fused candidate
  window (an `ids` query, size 0) after fusion: buckets/metrics
  describe the fused candidate set — a pure function of the sub-pages,
  so agg bytes are as arm-invariant as the fused page itself.
- Sub-retrievals run as parallel legs (`utils/legs.py`;
  `OPENSEARCH_TPU_LEGS=0` selects the serial arm): hybrid latency is
  the MAX of the sub-retrievals, not the SUM, and the fused bytes are
  identical across arms because fusion never sees scheduling.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs import flight_recorder as _fr
from ..utils import legs as _legs
from ..utils.metrics import METRICS, CounterGroup
from ..utils.trace import TRACER
from . import query_dsl as dsl

STATS = CounterGroup(METRICS, "hybridpath", {
    "searches": 0, "sub_queries": 0, "rrf_fused": 0, "linear_fused": 0,
    "agg_over_fusion": 0,
    "knn_batched": 0, "knn_batch_launches": 0, "knn_batch_declined": 0})


def stats() -> dict:
    return dict(STATS)


# body keys a hybrid search cannot carry: they either change per-shard
# collection semantics in ways the N independent sub-retrievals cannot
# honor coherently, or they re-rank outside the fusion contract.
# `aggs`/`aggregations` are NOT forbidden: they run as one extra
# sub-search over the fused candidate window after fusion (see
# run_hybrid / docs/HYBRID.md "aggregations over fused results").
_FORBIDDEN_BODY_KEYS = ("sort", "collapse",
                        "suggest", "rescore", "search_after", "min_score",
                        "knn", "terminate_after", "scroll", "pit")

# body keys that ride ALONG to every sub-search so the winning hits come
# back fully hydrated (the fused page reuses the sub-pages' hit dicts)
_PASSTHROUGH_KEYS = ("_source", "stored_fields", "docvalue_fields",
                     "fields", "script_fields", "highlight", "explain",
                     "derived", "track_scores", "track_total_hits",
                     "timeout", "allow_partial_search_results", "profile",
                     "preference")


def is_hybrid_body(body) -> bool:
    """Cheap top-level screen — True iff the body's query is `hybrid`."""
    if not isinstance(body, dict):
        return False
    q = body.get("query")
    return isinstance(q, dict) and "hybrid" in q


def parse_hybrid(body: dict) -> Optional[dsl.HybridQuery]:
    """-> the validated HybridQuery of a hybrid body, or None. Raises
    QueryParseError (HTTP 400) on malformed hybrid bodies."""
    if not is_hybrid_body(body):
        return None
    q = dsl.parse_query(body.get("query"))
    if not isinstance(q, dsl.HybridQuery):
        return None
    for k in _FORBIDDEN_BODY_KEYS:
        if body.get(k):
            raise dsl.QueryParseError(
                f"[hybrid] does not support [{k}] — each sub-query is an "
                f"independent retrieval; fused pages re-rank at the "
                f"coordinator only")
    frm = int(body.get("from", 0))
    size = int(body.get("size", 10))
    window = int(q.fusion["window_size"])
    if frm + size > window:
        raise dsl.QueryParseError(
            f"[hybrid] from + size ({frm + size}) exceeds the fusion "
            f"window_size ({window}); raise fusion.window_size — pages "
            f"fuse over a FIXED rank window so pagination stays stable")
    return q


def sub_bodies(body: dict, q: dsl.HybridQuery) -> List[dict]:
    """The N independent sub-search bodies: each sub-query retrieves its
    own fixed `window_size`-deep page with the parent's hydration
    options."""
    window = int(q.fusion["window_size"])
    out = []
    for sub in q.queries:
        sb = {"query": sub, "from": 0, "size": window}
        for k in _PASSTHROUGH_KEYS:
            if k in body:
                sb[k] = body[k]
        out.append(sb)
    return out


# ---------------------------------------------------------------------
# fusion algebra (pure host functions — the oracle tests mirror these)
# ---------------------------------------------------------------------

def minmax_normalize(scores: List[float]) -> List[float]:
    """(s - min)/(max - min) per list; a constant list (max == min) maps
    every present doc to 1.0 — presence in the window is the only signal
    the list carries (reference MinMaxScoreNormalizationTechnique)."""
    if not scores:
        return []
    lo, hi = min(scores), max(scores)
    if hi <= lo:
        return [1.0] * len(scores)
    rng = hi - lo
    return [(s - lo) / rng for s in scores]


def l2_normalize(scores: List[float]) -> List[float]:
    """s / ||s||_2 per list (reference L2ScoreNormalizationTechnique);
    an all-zero list stays zero."""
    nrm = sum(s * s for s in scores) ** 0.5
    if nrm <= 0.0:
        return [0.0] * len(scores)
    return [s / nrm for s in scores]


def normalize_scores(scores: List[float], how: str) -> List[float]:
    """THE designated score-domain normalizer (oslint OSL604): every
    linear combination of sub-query scores passes through here."""
    if how == "l2":
        return l2_normalize(scores)
    if how == "min_max":
        return minmax_normalize(scores)
    raise ValueError(f"unknown normalization [{how}]")


def fuse_ranked_lists(lists: List[List[Tuple[Any, float]]],
                      fusion: Dict[str, Any]) -> List[Tuple[Any, float]]:
    """Fuse N ranked `(key, score)` lists -> one ranked `(key, fused)`
    list under the spec's method. Deterministic total order: fused desc,
    best (list index, rank) asc, key asc. Commutative in shard/node
    arrival order because nothing here ever sees arrival order."""
    method = fusion["method"]
    weights = fusion["weights"]
    fused: Dict[Any, float] = {}
    best_coord: Dict[Any, Tuple[int, int]] = {}
    for li, lst in enumerate(lists):
        w = float(weights[li])
        if method == "rrf":
            k = float(fusion["rank_constant"])
            contribs = [w / (k + rank) for rank in range(1, len(lst) + 1)]
        else:
            norms = normalize_scores([s for _, s in lst],
                                     fusion["normalization"])
            contribs = [w * n for n in norms]
        for rank0, ((key, _s), c) in enumerate(zip(lst, contribs)):
            fused[key] = fused.get(key, 0.0) + c
            coord = (li, rank0)
            if key not in best_coord or coord < best_coord[key]:
                best_coord[key] = coord
    order = sorted(fused,
                   key=lambda key: (-fused[key], best_coord[key], key))
    return [(key, fused[key]) for key in order]


def _hit_key(hit: dict) -> Tuple[str, str]:
    return (str(hit.get("_index", "")), str(hit.get("_id", "")))


# ---------------------------------------------------------------------
# coordinator-side hybrid execution
# ---------------------------------------------------------------------

def run_hybrid(body: dict, run_sub: Callable[[dict], dict],
               q: Optional[dsl.HybridQuery] = None) -> dict:
    """Execute one hybrid search: run every sub-body through `run_sub`
    (single-node `search_shards` or the distnode scatter — whatever arm
    owns this request), fuse the ranked sub-pages, and assemble the
    fused response. The fused page's hit documents are reused from the
    first sub-page (by sub-query order) that retrieved each winner, with
    `_score` replaced by the fused score."""
    if q is None:
        q = parse_hybrid(body)
    assert q is not None
    t0 = time.monotonic()
    fusion = q.fusion
    frm = int(body.get("from", 0))
    size = int(body.get("size", 10))
    STATS.inc("searches")
    STATS.inc("sub_queries", len(q.queries))
    STATS.inc("rrf_fused" if fusion["method"] == "rrf" else "linear_fused")

    # Every sub-retrieval is an independent leg: latency is the MAX of
    # the legs, not the SUM, and fusion below is a pure function of the
    # ranked sub-pages so the fused bytes cannot depend on the arm.
    # Errors propagate first-by-sub-query-index — exactly the error the
    # serial loop would have raised.
    with TRACER.span("hybrid.sub_queries", n=len(q.queries)), \
            METRICS.timer("hybrid.sub_queries"):
        ls = _legs.LegSet("hybrid.sub")
        for i, sb in enumerate(sub_bodies(body, q)):
            ls.add_leg(lambda sb=sb: run_sub(sb), name=str(i))
        sub_resps: List[dict] = [leg.result() for leg in ls.join()]

    lists = []
    by_key: Dict[Tuple[str, str], dict] = {}
    for resp in sub_resps:
        hits = resp.get("hits", {}).get("hits", [])
        lst = []
        for h in hits:
            key = _hit_key(h)
            sc = h.get("_score")
            lst.append((key, float(sc) if sc is not None else 0.0))
            if key not in by_key:
                by_key[key] = h
        lists.append(lst)
    with TRACER.span("hybrid.fuse"), METRICS.timer("hybrid.fuse"):
        fused = fuse_ranked_lists(lists, fusion)
    if _fr.RECORDER.enabled and _fr.current():
        _fr.RECORDER.record(_fr.current(), "hybrid.fuse",
                            method=fusion["method"], subs=len(lists),
                            candidates=len(fused))

    # aggregations over fused results: one extra sub-search constrained
    # to the fused candidate window (an `ids` query over the union of
    # sub-retrieval windows, size 0). The bucket/metric domain is the
    # fused candidate set — a pure function of the sub-pages, so agg
    # bytes are arm-invariant exactly like the fused page. Ids are
    # sorted for compiled-program cache stability.
    agg_spec = body.get("aggs") or body.get("aggregations")
    agg_resp = None
    if agg_spec:
        STATS.inc("agg_over_fusion")
        agg_body = {"query": {"ids": {"values":
                                      sorted({key[1] for key, _ in fused})}},
                    "from": 0, "size": 0, "aggs": agg_spec}
        for k in ("timeout", "preference", "allow_partial_search_results"):
            if k in body:
                agg_body[k] = body[k]
        with TRACER.span("hybrid.aggs", candidates=len(fused)), \
                METRICS.timer("hybrid.aggs"):
            agg_resp = run_sub(agg_body)

    selected = fused[frm: frm + size]
    page = []
    for key, score in selected:
        h = dict(by_key[key])
        h["_score"] = round(float(score), 7)
        page.append(h)

    # honest union bound: the true |set-union| of N sub-result sets is
    # unknowable from their top windows
    totals = [r.get("hits", {}).get("total", {}) for r in sub_resps]
    tvals = [int(t.get("value", 0)) for t in totals if isinstance(t, dict)]
    total = max(tvals) if tvals else 0
    if len(sub_resps) == 1:
        rel = totals[0].get("relation", "eq") if totals else "eq"
    else:
        rel = "gte" if total else "eq"
    if any(isinstance(t, dict) and t.get("relation") == "gte"
           for t in totals):
        rel = "gte" if total else rel

    # shard bookkeeping: every sub-query scattered over the same shard
    # set; report that set once with the worst failure story any sub saw
    shards = dict(sub_resps[0].get("_shards",
                                   {"total": 0, "successful": 0,
                                    "skipped": 0, "failed": 0}))
    for r in sub_resps[1:]:
        s = r.get("_shards", {})
        if int(s.get("failed", 0)) > int(shards.get("failed", 0)):
            shards = dict(s)
    took_ms = (time.monotonic() - t0) * 1000.0
    METRICS.histogram("hybrid.total").record(took_ms)
    resp = {
        "took": int(took_ms),
        "timed_out": any(r.get("timed_out") for r in sub_resps),
        "_shards": shards,
        "hits": {"total": {"value": total, "relation": rel},
                 "max_score": (round(float(fused[0][1]), 7) if fused
                               else None),
                 "hits": page},
    }
    if agg_resp is not None:
        resp["aggregations"] = agg_resp.get("aggregations", {})
        if agg_resp.get("timed_out"):
            resp["timed_out"] = True
        s = agg_resp.get("_shards", {})
        if int(s.get("failed", 0)) > int(resp["_shards"].get("failed", 0)):
            resp["_shards"] = dict(s)
    if any(r.get("terminated_early") for r in sub_resps):
        resp["terminated_early"] = True
    if body.get("profile"):
        # per-sub-query attribution: which retrieval family produced
        # which candidates at what cost (each sub resp carries its own
        # profile/cost block — the query-cost bytes of the whole hybrid
        # request are the sum of its sub-query accumulators)
        resp["profile"] = {
            "hybrid": {
                "fusion": {k: fusion[k] for k in
                           ("method", "rank_constant", "weights",
                            "normalization", "window_size")},
                "sub_queries": [
                    {"query": q.queries[i],
                     "took": r.get("took"),
                     "total": r.get("hits", {}).get("total"),
                     "max_score": r.get("hits", {}).get("max_score"),
                     "candidates": len(lists[i]),
                     "profile": r.get("profile")}
                    for i, r in enumerate(sub_resps)],
            }}
    if body.get("explain") == "device_plan":
        plans = [r.get("device_plan") for r in sub_resps]
        if any(p is not None for p in plans):
            resp["device_plan"] = {"hybrid": plans}
    return resp
