"""Geo shapes: parsing (GeoJSON + a WKT subset) and exact spatial relations.

Reference analog: `index/mapper/GeoShapeFieldMapper.java` +
`index/query/GeoShapeQueryBuilder.java`, which delegate to Lucene's BKD
tesselation. The TPU-first split here is different: per-doc bounding boxes
live in columns for a vectorized prefilter, and the EXACT relation math
(this module) runs on the host over the bbox survivors at plan-prepare
time, producing a per-(segment, query) boolean mask that is uploaded as a
plan parameter — so the device plan stays static-shape and the mask rides
the (segment, plan) filter cache like any other filter.

Coordinates are (lon, lat) internally, GeoJSON order. Dateline-crossing
shapes are not split (documents near ±180° should use two shapes);
`circle` is approximated by a 64-gon (the reference requires explicit
tesselation for circles too).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field as dc_field
from typing import List, Optional, Tuple

import numpy as np

Ring = np.ndarray          # f64[k, 2] closed implicitly (last != first ok)
Poly = Tuple[Ring, List[Ring]]   # (outer, holes)


@dataclass
class Shape:
    points: np.ndarray = None          # f64[n, 2]
    lines: List[Ring] = dc_field(default_factory=list)
    polys: List[Poly] = dc_field(default_factory=list)
    bbox: Tuple[float, float, float, float] = (0, 0, 0, 0)  # minx,miny,maxx,maxy

    def __post_init__(self):
        if self.points is None:
            self.points = np.zeros((0, 2), np.float64)

    def finish(self) -> "Shape":
        xs, ys = [], []
        for arr in ([self.points] + self.lines
                    + [r for o, hs in self.polys for r in [o] + hs]):
            if len(arr):
                xs += [arr[:, 0].min(), arr[:, 0].max()]
                ys += [arr[:, 1].min(), arr[:, 1].max()]
        if xs:
            self.bbox = (min(xs), min(ys), max(xs), max(ys))
        return self

    @property
    def empty(self) -> bool:
        return not (len(self.points) or self.lines or self.polys)


class ShapeParseError(ValueError):
    pass


def _ring(coords) -> Ring:
    a = np.asarray(coords, np.float64)
    if a.ndim != 2 or a.shape[1] < 2 or len(a) < 2:
        raise ShapeParseError(f"bad ring/line coordinates (shape {a.shape})")
    return a[:, :2]


def _circle_poly(lon: float, lat: float, radius_m: float, n: int = 64) -> Ring:
    # small-circle approximation in degrees (fine for the filter use case)
    dlat = radius_m / 111_195.0
    dlon = dlat / max(math.cos(math.radians(lat)), 1e-6)
    t = np.linspace(0, 2 * math.pi, n, endpoint=False)
    return np.stack([lon + dlon * np.cos(t), lat + dlat * np.sin(t)], axis=1)


def parse_distance_m(v) -> float:
    """Delegates to the one DistanceUnit table (query_dsl._parse_distance)
    so circle radii accept exactly what geo_distance accepts."""
    from .query_dsl import _parse_distance
    try:
        return _parse_distance(v)
    except (ValueError, TypeError) as e:
        raise ShapeParseError(f"cannot parse distance [{v}]: {e}")


def parse_shape(spec) -> Shape:
    """GeoJSON dict or WKT string -> Shape. Any malformation (missing/
    ragged coordinates included) surfaces as ShapeParseError so the REST
    layer can 400 it."""
    try:
        return _parse_shape_inner(spec)
    except ShapeParseError:
        raise
    except (TypeError, KeyError, IndexError, ValueError) as e:
        raise ShapeParseError(f"malformed shape [{spec!r}]: {e}")


def _parse_shape_inner(spec) -> Shape:  # noqa: C901
    if isinstance(spec, str):
        return _parse_wkt(spec)
    if not isinstance(spec, dict):
        raise ShapeParseError(f"cannot parse shape [{spec!r}]")
    t = str(spec.get("type", "")).lower()
    co = spec.get("coordinates")
    s = Shape()
    if t == "point":
        s.points = np.asarray([co[:2]], np.float64)
    elif t == "multipoint":
        s.points = _ring(co)
    elif t == "linestring":
        s.lines = [_ring(co)]
    elif t == "multilinestring":
        s.lines = [_ring(c) for c in co]
    elif t == "polygon":
        s.polys = [(_ring(co[0]), [_ring(h) for h in co[1:]])]
    elif t == "multipolygon":
        s.polys = [(_ring(p[0]), [_ring(h) for h in p[1:]]) for p in co]
    elif t == "envelope":
        # GeoJSON-extension order: [[minlon, maxlat], [maxlon, minlat]]
        (x1, y2), (x2, y1) = co
        s.polys = [(np.asarray([[x1, y1], [x2, y1], [x2, y2], [x1, y2]],
                               np.float64), [])]
    elif t == "circle":
        lon, lat = spec["coordinates"][:2]
        s.polys = [(_circle_poly(lon, lat,
                                 parse_distance_m(spec.get("radius", "1km"))),
                    [])]
    elif t == "geometrycollection":
        for g in spec.get("geometries", []):
            sub = parse_shape(g)
            s.points = np.concatenate([s.points, sub.points])
            s.lines += sub.lines
            s.polys += sub.polys
    else:
        raise ShapeParseError(f"unknown shape type [{spec.get('type')}]")
    return s.finish()


_WKT_NUM = r"[-+]?\d*\.?\d+(?:[eE][-+]?\d+)?"


def _wkt_coords(body: str) -> list:
    """'(a b, c d)' nested parens -> nested lists of [x, y]."""
    body = body.strip()
    if body.startswith("("):
        out, depth, start = [], 0, None
        for i, ch in enumerate(body):
            if ch == "(":
                if depth == 0:
                    start = i + 1
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    out.append(_wkt_coords(body[start:i]))
        return out
    return [[float(x) for x in re.findall(_WKT_NUM, pt)][:2]
            for pt in body.split(",")]


def _parse_wkt(s: str) -> Shape:
    m = re.match(r"\s*([A-Za-z]+)\s*(\(.*\)|EMPTY)\s*$", s, re.S)
    if not m:
        raise ShapeParseError(f"cannot parse WKT [{s[:80]}]")
    kind = m.group(1).upper()
    if m.group(2) == "EMPTY":
        return Shape().finish()
    # the outermost WKT paren pair is pure wrapping — unwrap one level
    co = _wkt_coords(m.group(2))[0]
    sh = Shape()
    if kind == "POINT":
        sh.points = np.asarray(co, np.float64)
    elif kind == "MULTIPOINT":
        pts = [c[0] if isinstance(c, list) and c and isinstance(c[0], list)
               else c for c in co]
        sh.points = np.asarray(pts, np.float64)
    elif kind == "LINESTRING":
        sh.lines = [_ring(co)]
    elif kind == "MULTILINESTRING":
        sh.lines = [_ring(c) for c in co]
    elif kind == "POLYGON":
        sh.polys = [(_ring(co[0]), [_ring(h) for h in co[1:]])]
    elif kind == "MULTIPOLYGON":
        sh.polys = [(_ring(p[0]), [_ring(h) for h in p[1:]]) for p in co]
    elif kind in ("ENVELOPE", "BBOX"):  # ENVELOPE(minx, maxx, maxy, miny)
        flat = [float(x) for x in re.findall(_WKT_NUM, m.group(2))]
        x1, x2, y2, y1 = flat[:4]
        sh.polys = [(np.asarray([[x1, y1], [x2, y1], [x2, y2], [x1, y2]],
                                np.float64), [])]
    else:
        raise ShapeParseError(f"unknown WKT type [{kind}]")
    return sh.finish()


# ---------------------------------------------------------------------------
# exact predicates (host, vectorized numpy)
# ---------------------------------------------------------------------------

def points_in_ring(pts: np.ndarray, ring: Ring) -> np.ndarray:
    """Ray-cast: bool[n] — strict interior wins; boundary points count as
    inside (matches Lucene's 'contains includes boundary' behavior)."""
    if len(pts) == 0:
        return np.zeros(0, bool)
    x, y = pts[:, 0][:, None], pts[:, 1][:, None]
    rx, ry = ring[:, 0], ring[:, 1]
    x1, y1 = rx[None, :], ry[None, :]
    x2 = np.roll(rx, -1)[None, :]
    y2 = np.roll(ry, -1)[None, :]
    cond = ((y1 <= y) & (y < y2)) | ((y2 <= y) & (y < y1))
    denom = np.where(y2 == y1, 1e-300, y2 - y1)
    xin = x1 + (y - y1) / denom * (x2 - x1)
    inside = (np.sum(cond & (x < xin), axis=1) % 2) == 1
    # boundary: point on any edge segment
    on = _points_on_segments(pts, np.stack([x1[0], y1[0]], 1),
                             np.stack([x2[0], y2[0]], 1))
    return inside | on


def _points_on_segments(pts, a, b, eps=1e-9) -> np.ndarray:
    """bool[n]: pt collinear with and between a[j]..b[j] for some j."""
    if len(pts) == 0 or len(a) == 0:
        return np.zeros(len(pts), bool)
    p = pts[:, None, :]
    ab = (b - a)[None, :, :]
    ap = p - a[None, :, :]
    cross = ab[..., 0] * ap[..., 1] - ab[..., 1] * ap[..., 0]
    dot = ab[..., 0] * ap[..., 0] + ab[..., 1] * ap[..., 1]
    sq = (ab ** 2).sum(-1)
    on = ((np.abs(cross) <= eps * np.maximum(np.sqrt(sq), 1.0))
          & (dot >= -eps) & (dot <= sq + eps))
    # zero-length edges (e.g. the duplicated ring-closing vertex) match only
    # the vertex itself, not every point
    degenerate = sq <= eps * eps
    at_vertex = (ap ** 2).sum(-1) <= eps * eps
    return np.where(degenerate, at_vertex, on).any(axis=1)


def points_in_poly(pts: np.ndarray, poly: Poly) -> np.ndarray:
    outer, holes = poly
    m = points_in_ring(pts, outer)
    for h in holes:
        # boundary of a hole still counts as inside the polygon
        m &= ~(points_in_ring(pts, h) & ~_ring_boundary(pts, h))
    return m


def _ring_boundary(pts, ring) -> np.ndarray:
    a = ring
    b = np.roll(ring, -1, axis=0)
    return _points_on_segments(pts, a, b)


def points_in_shape(pts: np.ndarray, shape: Shape) -> np.ndarray:
    m = np.zeros(len(pts), bool)
    for poly in shape.polys:
        m |= points_in_poly(pts, poly)
    return m


def _shape_edges(shape: Shape) -> Tuple[np.ndarray, np.ndarray]:
    """All boundary edges (polygon rings incl. holes + lines) as (a, b)."""
    av, bv = [], []
    for o, hs in shape.polys:
        for r in [o] + hs:
            av.append(r)
            bv.append(np.roll(r, -1, axis=0))
    for ln in shape.lines:
        av.append(ln[:-1])
        bv.append(ln[1:])
    if not av:
        z = np.zeros((0, 2), np.float64)
        return z, z
    return np.concatenate(av), np.concatenate(bv)


def _segments_cross(a1, b1, a2, b2) -> bool:
    """Any segment of set 1 properly or improperly intersects any of set 2."""
    if len(a1) == 0 or len(a2) == 0:
        return False
    # orientation tests, broadcast [n1, n2]
    d1 = (b1 - a1)[:, None, :]
    d2 = (b2 - a2)[None, :, :]
    w = a2[None, :, :] - a1[:, None, :]
    den = d1[..., 0] * d2[..., 1] - d1[..., 1] * d2[..., 0]
    t_num = w[..., 0] * d2[..., 1] - w[..., 1] * d2[..., 0]
    u_num = w[..., 0] * d1[..., 1] - w[..., 1] * d1[..., 0]
    eps = 1e-12
    nonpar = np.abs(den) > eps
    with np.errstate(divide="ignore", invalid="ignore"):
        t = np.where(nonpar, t_num / np.where(nonpar, den, 1.0), np.inf)
        u = np.where(nonpar, u_num / np.where(nonpar, den, 1.0), np.inf)
    hit = nonpar & (t >= -eps) & (t <= 1 + eps) & (u >= -eps) & (u <= 1 + eps)
    if hit.any():
        return True
    # collinear overlap: endpoints of one lying on the other
    par = ~nonpar & (np.abs(t_num) <= eps)
    if not par.any():
        return False
    ep = np.concatenate([a1, b1])
    return bool(_points_on_segments(ep, a2, b2).any()
                or _points_on_segments(np.concatenate([a2, b2]), a1, b1).any())


def _bbox_overlap(b1, b2) -> bool:
    return not (b1[2] < b2[0] or b2[2] < b1[0]
                or b1[3] < b2[1] or b2[3] < b1[1])


def intersects(a: Shape, b: Shape) -> bool:
    if a.empty or b.empty or not _bbox_overlap(a.bbox, b.bbox):
        return False
    # point tests both directions
    if len(a.points) and (points_in_shape(a.points, b).any()
                          or _points_on_edges(a.points, b).any()):
        return True
    if len(b.points) and (points_in_shape(b.points, a).any()
                          or _points_on_edges(b.points, a).any()):
        return True
    if len(a.points) and len(b.points):
        # shared coordinates
        aset = {tuple(p) for p in np.round(a.points, 9).tolist()}
        if any(tuple(p) in aset for p in np.round(b.points, 9).tolist()):
            return True
    ea, eb = _shape_edges(a), _shape_edges(b)
    if _segments_cross(ea[0], ea[1], eb[0], eb[1]):
        return True
    # full containment (no edge crossings): one representative vertex PER
    # CONNECTED PART — a non-first part can sit wholly inside the other
    # shape while the first part is far away
    va = _part_representatives(a)
    if len(va) and points_in_shape(va, b).any():
        return True
    vb = _part_representatives(b)
    if len(vb) and points_in_shape(vb, a).any():
        return True
    return False


def _part_representatives(shape: Shape) -> np.ndarray:
    """First vertex of each connected component (every poly, every line)."""
    parts = [o[:1] for o, _hs in shape.polys] + [ln[:1] for ln in shape.lines]
    if len(shape.points):
        parts.append(shape.points)
    return np.concatenate(parts) if parts else np.zeros((0, 2), np.float64)


def _points_on_edges(pts, shape: Shape) -> np.ndarray:
    a, b = _shape_edges(shape)
    return _points_on_segments(pts, a, b)


def _all_vertices(shape: Shape) -> np.ndarray:
    parts = [shape.points] + shape.lines + \
        [r for o, hs in shape.polys for r in [o] + hs]
    parts = [p for p in parts if len(p)]
    return np.concatenate(parts) if parts else np.zeros((0, 2), np.float64)


def _segments_cross_proper(a1, b1, a2, b2) -> bool:
    """Transversal interior-to-interior crossing only: touching at
    endpoints or collinear overlap does NOT count."""
    if len(a1) == 0 or len(a2) == 0:
        return False
    d1 = (b1 - a1)[:, None, :]
    d2 = (b2 - a2)[None, :, :]
    w = a2[None, :, :] - a1[:, None, :]
    den = d1[..., 0] * d2[..., 1] - d1[..., 1] * d2[..., 0]
    t_num = w[..., 0] * d2[..., 1] - w[..., 1] * d2[..., 0]
    u_num = w[..., 0] * d1[..., 1] - w[..., 1] * d1[..., 0]
    eps = 1e-12
    nonpar = np.abs(den) > eps
    with np.errstate(divide="ignore", invalid="ignore"):
        t = np.where(nonpar, t_num / np.where(nonpar, den, 1.0), np.inf)
        u = np.where(nonpar, u_num / np.where(nonpar, den, 1.0), np.inf)
    return bool((nonpar & (t > eps) & (t < 1 - eps)
                 & (u > eps) & (u < 1 - eps)).any())


def within(a: Shape, b: Shape) -> bool:
    """a within b: b must be areal; every part of a inside b's polygons.
    Touching b's boundary is allowed; properly crossing it is not."""
    if a.empty or not b.polys:
        return False
    va = _all_vertices(a)
    if not points_in_shape(va, b).all():
        return False
    ea = _shape_edges(a)
    eb = _shape_edges(b)
    # a boundary edge of `a` transversally crossing b's boundary (outer
    # rings OR holes) means part of a's interior escapes b — this is what
    # catches a region protruding into a hole whose vertices/midpoints all
    # sample inside b
    if _segments_cross_proper(ea[0], ea[1], eb[0], eb[1]):
        return False
    if len(ea[0]):
        mids = (ea[0] + ea[1]) / 2.0
        if not points_in_shape(mids, b).all():
            return False
    # a hole of b strictly inside a would break containment
    for o, hs in b.polys:
        for h in hs:
            if a.polys and points_in_shape(h, a).all() \
                    and not _points_on_edges(h, a).all():
                return False
    return True


def relation_matches(doc: Shape, query: Shape, relation: str) -> bool:
    if relation == "intersects":
        return intersects(doc, query)
    if relation == "disjoint":
        return not intersects(doc, query)
    if relation == "within":
        return within(doc, query)
    if relation == "contains":
        return within(query, doc)
    raise ShapeParseError(f"unknown geo_shape relation [{relation}]")
