"""Derived (runtime) fields: query-time fields computed by painless-lite
scripts over `_source` and doc values.

Reference analog: `index/mapper/DerivedFieldMapper.java` + the `derived`
mapping/search-body sections. The reference evaluates the script per doc
inside each query's iterator; the TPU design instead MATERIALIZES the
derived field once per (segment, script) into ordinary columns (+ a
postings block for keyword types), then lets every query, sort, agg, and
fetch run the normal device path at full speed — per-segment scripts are
host work, query execution stays vectorized. Materializations are cached
on the immutable segment and never persisted (flush skips derived names;
a changed script definition rebuilds).

Script convention: `emit(value)` (single emit) or a plain `return`; doc
values are reachable as `doc['field'].value` and the raw document as
`params._source` / `_source` (reference derived-field script contexts).
"""

from __future__ import annotations

import hashlib
import re
from typing import Any, Dict, List, Optional

import numpy as np

from ..index.mappings import _parse_date
from ..script import painless_lite as pl

_EMIT_RE = re.compile(r"\bemit\s*\(")

DERIVED_TYPES = {"keyword", "long", "date", "double", "boolean"}


class DerivedField:
    __slots__ = ("name", "type", "source", "fmt")

    def __init__(self, name: str, type_: str, source: str,
                 fmt: Optional[str] = None):
        if type_ not in DERIVED_TYPES:
            raise ValueError(
                f"unsupported derived field type [{type_}] for [{name}] "
                f"(supported: {sorted(DERIVED_TYPES)})")
        self.name = name
        self.type = type_
        self.source = source
        self.fmt = fmt

    @property
    def digest(self) -> str:
        return hashlib.blake2b(
            f"{self.type}\x00{self.source}\x00{self.fmt}".encode(),
            digest_size=12).hexdigest()


class MappingsOverlay:
    """Per-request view of an index's Mappings with extra (search-body)
    derived definitions — shared Mappings are never mutated."""

    def __init__(self, base, extra_defs: Dict[str, "DerivedField"]):
        self._base = base
        self.derived = {**base.derived, **extra_defs}

    def resolve_field(self, name: str):
        from ..index.mappings import Mappings
        return Mappings.resolve_field(self, name)

    def __getattr__(self, k):
        return getattr(self._base, k)


def parse_defs(section: Optional[dict]) -> Dict[str, DerivedField]:
    """A mapping/search-body `derived` section -> DerivedField defs."""
    out: Dict[str, DerivedField] = {}
    for name, cfg in (section or {}).items():
        script = cfg.get("script", {})
        src = script.get("source", script) if isinstance(script, dict) \
            else script
        if not isinstance(src, str) or not src:
            raise ValueError(f"derived field [{name}] needs a script source")
        out[name] = DerivedField(name, cfg.get("type", "keyword"), src,
                                 cfg.get("format"))
    return out


def _emit_to_return(src: str) -> str:
    """Single-`emit(v)` scripts become return-style for the host
    interpreter (multi-emit arrays are not supported — documented)."""
    return _EMIT_RE.sub("return (", src)


def check_conflicts(mappings, defs: Dict[str, DerivedField]) -> None:
    """A derived field must not shadow a mapped field — materialization
    would clobber the real column on the shared segment (and flush would
    then skip persisting it)."""
    from ..index.mappings import Mappings
    for name in defs:
        base = mappings._base if isinstance(mappings, MappingsOverlay) \
            else mappings
        if name in base.fields:
            raise ValueError(
                f"derived field [{name}] conflicts with a mapped field")
        if "." in name:
            parent, sub = name.rsplit(".", 1)
            pft = base.fields.get(base.aliases.get(parent, parent))
            if pft is not None and sub in pft.subfields:
                raise ValueError(
                    f"derived field [{name}] conflicts with a mapped field")


def referenced(defs: Dict[str, DerivedField], body: dict) -> List[str]:
    """Derived names that appear anywhere in the request body — a cheap
    over-approximation; materializing an unreferenced field is only wasted
    host work, never a correctness issue."""
    import json
    blob = json.dumps(body, default=str)
    return [n for n in defs if n in blob]


# msearch's per-body fallback runs searches on a thread pool; materialization
# mutates segment postings/column dicts, so two bodies referencing the same
# derived field must not interleave (coarse lock: it's a once-per-(segment,
# digest) cost)
_ENSURE_LOCK = __import__("threading").RLock()


def ensure(seg, mappings, defs: Dict[str, DerivedField],
           names: List[str]) -> None:
    """Materialize the named derived fields on one segment (idempotent per
    script digest)."""
    with _ENSURE_LOCK:
        built: Dict[str, str] = seg.__dict__.setdefault("_derived_built", {})
        derived_names: set = seg.__dict__.setdefault("_derived_names", set())
        changed = False
        for name in names:
            df = defs[name]
            if built.get(name) == df.digest:
                continue
            _materialize(seg, mappings, df)
            built[name] = df.digest
            derived_names.add(name)
            changed = True
        if changed:
            _purge_query_caches(seg, names)


def _purge_query_caches(seg, names: List[str]) -> None:
    """A rematerialized derived field invalidates every cache derived from
    its old column: the device pytree, per-field device arrays, cached
    filter masks and fastpath filter lists/aligned layouts, sort ordinals,
    and date buckets."""
    from . import compiler as C
    from . import fastpath as FP

    # SWAP, don't clear in place: Segment.device_arrays readers hold a
    # snapshot reference to the dict and rely on its entries staying put
    # (same contract as drop_device / pressure eviction). Release the
    # dropped caches' ledger charges NOW, like drop_device does — the
    # rebuild registers a fresh set, and stale live charges would read
    # as ~2x the segment's footprint to the breaker, driving premature
    # pressure eviction (or trips) of other tenants
    from ..obs.hbm_ledger import LEDGER
    seg._device_cache = {}
    seg._device_live_dirty = {}
    seg.__dict__.pop("_field_device_cache", None)
    for allocs in seg.__dict__.pop("_hbm_allocs", {}).values():
        for alloc in allocs:
            LEDGER.release(alloc)
    for alloc in seg.__dict__.pop("_field_device_allocs", {}).values():
        LEDGER.release(alloc)
    C._purge_masks_for_uid(seg.uid)
    FP._purge_filtered_for_uid(seg.uid)
    seg.__dict__.get("_fastpath_filters", {}).clear()
    for name in names:
        seg.__dict__.get("_fastpath_aligned", {}).pop(name, None)
        seg.__dict__.get("_sort_dev_cache", {}).pop(name, None)
        for cache_name in ("_date_bucket_cache", "_nested_sort_cache"):
            c = seg.__dict__.get(cache_name)
            if c:
                for k in [k for k in c if k[0] == name]:
                    del c[k]


class _LazyDocCols(dict):
    """doc['field'] view materialized on access — scripts usually read one
    or two fields, so per-doc eager extraction of every column would
    dominate materialization time."""

    def __init__(self, seg, doc: int):
        super().__init__()
        self._seg = seg
        self._doc = doc

    def get(self, f, default=None):
        # the host interpreter reads dicts via .get(), which skips
        # __missing__ — route it through item access
        try:
            return self[f]
        except KeyError:
            return default

    def __missing__(self, f):
        seg, d = self._seg, self._doc
        col = seg.numeric_cols.get(f)
        if col is not None:
            vals = ([] if not col.present[d] else
                    [float(col.values[d]) if col.kind == "float"
                     else int(col.values[d])])
            v = self[f] = pl.HostDocValue(vals)
            return v
        kcol = seg.keyword_cols.get(f)
        if kcol is not None:
            a, b = int(kcol.starts[d]), int(kcol.starts[d + 1])
            v = self[f] = pl.HostDocValue(
                [kcol.vocab[o] for o in kcol.ords[a:b]])
            return v
        raise KeyError(f)


def _doc_env(seg, doc: int, src: dict) -> Dict[str, Any]:
    return {"doc": _LazyDocCols(seg, doc), "params": {"_source": src},
            "_source": src}


def _materialize(seg, mappings, df: DerivedField) -> None:
    ast = pl.parse(_emit_to_return(df.source))
    n = seg.ndocs
    raw: List[Any] = [None] * n
    for d in range(n):
        if not seg.live[d]:
            continue
        try:
            raw[d] = pl.execute(ast, _doc_env(seg, d, seg.sources[d]))
        except pl.ScriptError as e:
            raise pl.ScriptError(
                f"[{df.name}] failed on doc {d}: {e}") from e
    if df.type == "keyword":
        _install_keyword(seg, df.name, raw)
    else:
        _install_numeric(seg, df, raw)


def _coerce(df: DerivedField, v: Any):
    if v is None:
        return None
    if df.type == "long":
        return int(v)
    if df.type == "double":
        return float(v)
    if df.type == "boolean":
        return 1 if bool(v) else 0
    if df.type == "date":
        return _parse_date(v, df.fmt)
    return v


def _install_numeric(seg, df: DerivedField, raw: List[Any]) -> None:
    from ..index.segment import NumericColumn

    kind = "float" if df.type == "double" else "int"
    values = np.zeros(seg.ndocs,
                      np.float64 if kind == "float" else np.int64)
    present = np.zeros(seg.ndocs, bool)
    for d, v in enumerate(raw):
        cv = _coerce(df, v)
        if cv is None:
            continue
        values[d] = cv
        present[d] = True
    seg.numeric_cols[df.name] = NumericColumn(df.name, kind, values, present)


def _install_keyword(seg, name: str, raw: List[Any]) -> None:
    from ..index.segment import KeywordColumn, PostingsBlock

    svals = [None if v is None else str(v) for v in raw]
    vocab = sorted({v for v in svals if v is not None})
    ord_of = {v: i for i, v in enumerate(vocab)}
    n = seg.ndocs
    starts = np.zeros(n + 1, np.int64)
    flat_ords: List[int] = []
    flat_docs: List[int] = []
    min_ord = np.full(n, -1, np.int32)
    for d, v in enumerate(svals):
        starts[d + 1] = starts[d] + (0 if v is None else 1)
        if v is not None:
            o = ord_of[v]
            flat_ords.append(o)
            flat_docs.append(d)
            min_ord[d] = o
    seg.keyword_cols[name] = KeywordColumn(
        field=name, vocab=vocab, starts=starts,
        ords=np.asarray(flat_ords, np.int32),
        doc_of_value=np.asarray(flat_docs, np.int32), min_ord=min_ord)
    # postings so term/terms/match/exists queries ride the normal path:
    # one row per vocab value, doc-sorted (values appended doc-ascending)
    by_term: Dict[int, List[int]] = {}
    for o, d in zip(flat_ords, flat_docs):
        by_term.setdefault(o, []).append(d)
    pstarts = np.zeros(len(vocab) + 1, np.int64)
    docs_parts: List[int] = []
    for o in range(len(vocab)):
        row = by_term.get(o, [])
        pstarts[o + 1] = pstarts[o] + len(row)
        docs_parts.extend(row)
    seg.postings[name] = PostingsBlock(
        field=name, vocab=list(vocab), terms=dict(ord_of),
        starts=pstarts, doc_ids=np.asarray(docs_parts, np.int32),
        tfs=np.ones(len(docs_parts), np.float32))
