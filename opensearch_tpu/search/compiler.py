"""Query compiler: DSL tree -> logical plan -> jitted device program.

The analog of the reference chain QueryBuilder.toQuery -> Query.rewrite ->
Weight/Scorer (`index/query/*`, Lucene createWeight), redesigned for XLA:

1. `rewrite(query, ctx)` runs once per query on the host: analysis,
   multi-term expansion, index-wide idf/avgdl statistics -> a LogicalNode
   tree whose *structure* is static and whose numeric inputs are arrays.
2. `prepare(node, segment)` binds the plan to one segment: term -> CSR row
   lookups, pow2 bucket selection (from host row pointers — no device sync),
   producing a `spec` (hashable static structure) + `params` (traced arrays).
3. `build_executor(spec)` constructs the traced function interpreting the
   spec; it is jitted once per spec and cached — segments with equal padded
   shapes and queries with equal structure all reuse the same XLA program.

Every node evaluates to a dense ScoredMask over ndocs_pad; scoring leaves are
gather->scatter passes (ops.scoring), predicates are vectorized column
compares, and combinators are elementwise VPU ops that XLA fuses.
"""

from __future__ import annotations

import fnmatch as _fnmatch
import re
import time as _time_mod
from bisect import bisect_left, bisect_right
from dataclasses import dataclass, field as dc_field
from functools import lru_cache, wraps
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..index.mappings import (FLOAT_TYPES, INT_TYPES, KEYWORD_TYPES,
                              RANGE_MEMBER, RANGE_TYPES, TEXT_TYPES,
                              Mappings, coerce_value, _parse_range_value)
from ..index.segment import (CODEC_V1, CODEC_V2, Segment, next_pow2,
                             split_i64)
from ..models.similarity import Similarity, resolve_similarity
from ..ops import aggs as agg_ops
from ..ops import scoring as ops
from ..script import painless_lite as pl
from . import query_dsl as dsl
from .aggregations import AggNode

INT32_SENTINEL = np.int32(2**31 - 1)
HLL_LOG2M = 14

# ---------------------------------------------------------------------
# jit program-cache + compile-vs-execute attribution (utils/metrics.py)
# ---------------------------------------------------------------------
#
# Every jitted program builder in this module is lru_cache'd per
# canonical spec; the instrumented wrapper mirrors cache traffic into the
# registry and times the programs themselves. Attribution model: a
# program's FIRST python-side invocation runs trace + lower + XLA compile
# inline, so its wall lands in `search.jit.<family>.compile_ms`;
# steady-state calls land in `.execute_ms` (dispatch wall — XLA execution
# itself is async, so this is launch cost, not device busy time;
# RESCORE_STATS carries the synced device walls). Programs whose input
# shapes vary per segment can recompile on later calls — first-call
# attribution is the bounded, zero-sync approximation the reference's
# per-phase breakdowns also make.

_JIT_FAMILIES = ("executor", "mask", "gather", "agg", "rescore", "join")


class _TimedProgram:
    __slots__ = ("_fn", "_family", "_shape", "_compiled", "__weakref__")

    def __init__(self, family: str, fn, shape: Optional[str] = None):
        self._fn = fn
        self._family = family
        self._shape = shape
        self._compiled = False

    def __call__(self, *a, **kw):
        from ..utils.metrics import METRICS
        if not METRICS.enabled:
            return self._fn(*a, **kw)
        t0 = _time_mod.perf_counter()
        out = self._fn(*a, **kw)
        dt = (_time_mod.perf_counter() - t0) * 1e3
        base = f"search.jit.{self._family}"
        if not self._compiled:
            # benign race: two threads can both attribute their first
            # call as a compile — the histogram stays honest enough and
            # a lock here would tax every launch
            self._compiled = True
            METRICS.histogram(f"{base}.compile_ms").record(dt)
            if self._shape:
                METRICS.histogram(
                    f"{base}.shape.{self._shape}.compile_ms").record(dt)
        else:
            METRICS.counter(f"{base}.launches").inc()
            METRICS.histogram(f"{base}.execute_ms").record(dt)
            if self._shape:
                METRICS.histogram(
                    f"{base}.shape.{self._shape}.execute_ms").record(dt)
        return out


# every program-builder lru cache in the process, for
# `clear_program_caches` — the jitted wrappers these hold pin mmap'd
# JIT-code regions for as long as they live
_PROGRAM_CACHE_CLEARERS: List[Callable] = []


def clear_program_caches() -> None:
    """Drop every compiled-program cache in the process: the engine's
    lru program builders AND JAX's internal jit caches. Each XLA-CPU
    executable pins a triplet of mmap'd JIT-code regions; a process
    that compiles unboundedly many program shapes (full test suites,
    multi-corpus bench runs) accumulates tens of thousands of maps and
    can cross the kernel's `vm.max_map_count` ceiling — the same limit
    the reference engine's bootstrap check guards (Elasticsearch/
    OpenSearch demand vm.max_map_count >= 262144) — after which the
    next mmap inside a compile fails as a SIGSEGV. Everything
    recompiles on demand; counters and telemetry are untouched."""
    import gc

    import jax
    for clear in list(_PROGRAM_CACHE_CLEARERS):
        clear()
    jax.clear_caches()
    gc.collect()


def _instrumented_program_cache(family: str, maxsize: int,
                                shape_of: Optional[Callable] = None):
    """lru_cache a program builder with registry attribution: requests
    and misses count per family (hits = requests - misses), and the built
    program is wrapped in `_TimedProgram` for compile-vs-execute walls.
    `cache_info`/`cache_clear` keep functools semantics — tests ratchet
    on them."""

    def deco(build):
        @lru_cache(maxsize=maxsize)
        def cached(*key):
            from ..obs.hbm_ledger import LEDGER
            from ..utils.metrics import METRICS
            if METRICS.enabled:
                METRICS.counter(f"search.jit.{family}.cache_miss").inc()
            prog = _TimedProgram(family, build(*key),
                                 shape_of(*key) if shape_of else None)
            # per-shape compiled-program footprint tenant: ADVISORY
            # (bytes=0, uncharged) — XLA owns the executable's true HBM
            # cost and the ledger's device cross-check covers the
            # aggregate; the registration attributes program COUNT per
            # family and releases on lru eviction / cache_clear
            LEDGER.register("program", 0, owner=prog, charge=False,
                            label=f"jit[{family}]"
                                  f"{'.' + prog._shape if prog._shape else ''}")
            return prog

        @wraps(build)
        def wrapper(*key):
            # disabled-mode contract: no name formatting / registry lock
            # on the per-launch hot path when telemetry is off
            from ..utils.metrics import METRICS
            if METRICS.enabled:
                METRICS.counter(f"search.jit.{family}.requests").inc()
            return cached(*key)

        wrapper.cache_info = cached.cache_info
        wrapper.cache_clear = cached.cache_clear
        _PROGRAM_CACHE_CLEARERS.append(cached.cache_clear)
        return wrapper

    return deco


def jit_attribution() -> Dict[str, dict]:
    """Per-family program-cache and compile-vs-execute rollup (consumed
    by `_nodes/stats` and the enriched `profile` response)."""
    from ..utils.metrics import METRICS
    snap = METRICS.snapshot()
    cnt, hist = snap["counters"], snap["histograms"]
    out: Dict[str, dict] = {}
    for fam in _JIT_FAMILIES:
        base = f"search.jit.{fam}"
        requests = cnt.get(f"{base}.requests", 0)
        if not requests:
            continue
        misses = cnt.get(f"{base}.cache_miss", 0)
        comp = hist.get(f"{base}.compile_ms", {})
        ex = hist.get(f"{base}.execute_ms", {})
        out[fam] = {
            "cache": {"requests": requests, "hits": requests - misses,
                      "misses": misses},
            "compile": {"count": comp.get("count", 0),
                        "total_ms": comp.get("sum_ms", 0.0),
                        "p50_ms": comp.get("p50_ms")},
            "execute": {"count": ex.get("count", 0),
                        "total_ms": ex.get("sum_ms", 0.0),
                        "p50_ms": ex.get("p50_ms"),
                        "p99_ms": ex.get("p99_ms")},
        }
    return out

# reference PercentilesAggregationBuilder defaults — shared with the mesh
# service so host and mesh never drift
DEFAULT_PERCENTS = (1.0, 5.0, 25.0, 50.0, 75.0, 95.0, 99.0)
PCTL_BINS = 4096


# =====================================================================
# shard context (index-wide statistics)
# =====================================================================

class ShardContext:
    """Index-wide view used during rewrite (reference QueryShardContext)."""

    def __init__(self, mappings: Mappings, segments: List[Segment],
                 similarity=None, field_similarities: Optional[dict] = None):
        self.mappings = mappings
        self.segments = segments
        self.default_sim = resolve_similarity(similarity)
        self.field_sims = {f: resolve_similarity(s)
                           for f, s in (field_similarities or {}).items()}

    def sim_for(self, field: str) -> Similarity:
        return self.field_sims.get(field, self.default_sim)

    @property
    def num_docs(self) -> int:
        return sum(s.ndocs for s in self.segments)  # incl. deleted, like Lucene maxDoc

    def doc_freq(self, field: str, term: str) -> int:
        return sum(s.postings[field].doc_freq(term)
                   for s in self.segments if field in s.postings)

    def collection_tf(self, field: str, term: str) -> float:
        total = 0.0
        for s in self.segments:
            pb = s.postings.get(field)
            if pb is None:
                continue
            r = pb.row(term)
            if r >= 0:
                a, b = pb.row_slice(r)
                total += float(pb.tfs[a:b].sum())
        return total

    def field_stats(self, field: str) -> Tuple[int, int]:
        doc_count, sum_dl = 0, 0
        for s in self.segments:
            st = s.text_stats.get(field)
            if st:
                doc_count += st.doc_count
                sum_dl += st.sum_dl
        return doc_count, sum_dl

    def avgdl(self, field: str) -> float:
        dc, sdl = self.field_stats(field)
        return (sdl / dc) if dc > 0 else 1.0

    def total_tf(self, field: str) -> float:
        _, sdl = self.field_stats(field)
        return float(max(sdl, 1))


# =====================================================================
# logical plan nodes
# =====================================================================

_node_counter = [0]


def _nid() -> int:
    _node_counter[0] += 1
    return _node_counter[0]


@dataclass
class LNode:
    nid: int = dc_field(default_factory=_nid)
    name: Optional[str] = None  # _name


@dataclass
class LTerms(LNode):
    """One weighted term group over a field — the fused scoring leaf."""

    field: str = ""
    terms: List[str] = dc_field(default_factory=list)
    weights: Optional[np.ndarray] = None   # f32[T] idf*boost
    aux: Optional[np.ndarray] = None       # f32[T] (LM collection prob)
    msm: int = 1
    mode: str = "score"                    # score | filter
    sim: Optional[Similarity] = None
    has_norms: bool = True
    boost: float = 1.0                     # filter-mode constant score


@dataclass
class LExpandTerms(LNode):
    """Multi-term expansion (prefix/wildcard/fuzzy/regexp/keyword-range):
    rows resolved per segment via `expander(segment) -> np.ndarray[rows]`.
    Constant-score like Lucene's MultiTermQuery CONSTANT_SCORE rewrite."""

    field: str = ""
    expander: Optional[Callable[[Segment], np.ndarray]] = None
    boost: float = 1.0


@dataclass
class LPhrase(LNode):
    """Positional phrase/span-near: device pair-join over positional postings
    (ops/positions.py). `weight` is the summed idf*boost of the terms (Lucene
    PhraseWeight convention); the last term may expand by prefix
    (match_phrase_prefix)."""

    field: str = ""
    terms: List[str] = dc_field(default_factory=list)
    slop: int = 0
    weight: float = 0.0
    sim: Optional[Similarity] = None
    has_norms: bool = True
    prefix_last: bool = False
    max_expansions: int = 50
    ordered: bool = False              # span_near in_order / intervals ordered
    gap_cost: bool = False             # intervals max_gaps (span gaps, not moves)
    boost: float = 1.0


@dataclass
class LMatchAll(LNode):
    boost: float = 1.0


@dataclass
class LMatchNone(LNode):
    pass


@dataclass
class LRange(LNode):
    field: str = ""
    kind: str = "int"                      # int | float
    lo: Any = None                         # i64/f64 or None
    hi: Any = None
    include_lo: bool = True
    include_hi: bool = True
    boost: float = 1.0


@dataclass
class LExists(LNode):
    field: str = ""
    boost: float = 1.0


@dataclass
class LIds(LNode):
    ids: List[str] = dc_field(default_factory=list)
    boost: float = 1.0


@dataclass
class LBool(LNode):
    musts: List[LNode] = dc_field(default_factory=list)
    shoulds: List[LNode] = dc_field(default_factory=list)
    must_nots: List[LNode] = dc_field(default_factory=list)
    filters: List[LNode] = dc_field(default_factory=list)
    msm: int = 0
    boost: float = 1.0


@dataclass
class LConstScore(LNode):
    child: Optional[LNode] = None
    boost: float = 1.0


@dataclass
class LDisMax(LNode):
    children: List[LNode] = dc_field(default_factory=list)
    tie_breaker: float = 0.0
    boost: float = 1.0


@dataclass
class LBoosting(LNode):
    positive: Optional[LNode] = None
    negative: Optional[LNode] = None
    negative_boost: float = 0.5
    boost: float = 1.0


@dataclass
class LFuncScore(LNode):
    child: Optional[LNode] = None
    functions: List[dsl.ScoreFunction] = dc_field(default_factory=list)
    fn_filters: List[Optional[LNode]] = dc_field(default_factory=list)
    score_mode: str = "multiply"
    boost_mode: str = "multiply"
    min_score: Optional[float] = None
    boost: float = 1.0


@dataclass
class LNested(LNode):
    """Block-join to-parent query: the child subtree executes in the nested
    path's child doc space (its own CSR arrays), then scores reduce to the
    parent space via scatter-add/max over the child->parent map (reference
    ToParentBlockJoinQuery; design per SURVEY §2.2 nested = doc-block)."""

    path: str = ""
    child: Optional[LNode] = None
    child_ctx: Optional["ShardContext"] = None
    score_mode: str = "avg"
    boost: float = 1.0


@dataclass
class LHasChild(LNode):
    """Parents with matching children. Two device passes over the shard's
    join slot space (search/join.py): pass 1 scatters child-query scores into
    parent slots across ALL segments; pass 2 (emit) slices each segment's
    window out of the slot vectors. Reference modules/parent-join
    HasChildQueryBuilder + ToParentBlockJoin-style score modes."""

    join_field: str = ""
    child_rel: str = ""
    child: Optional[LNode] = None          # inner query AND join==child_rel
    parent_filter: Optional[LNode] = None  # join==parent_rel
    score_mode: str = "none"
    min_children: int = 1
    max_children: int = 2**31 - 1
    boost: float = 1.0
    join_index: Any = None
    pre: Any = None                        # lazily-computed slot vectors


@dataclass
class LHasParent(LNode):
    """Children whose parent matches (reference HasParentQueryBuilder):
    pass 1 places parent-query scores at the parents' own slots; pass 2
    gathers through each child's `parent_slot`."""

    join_field: str = ""
    parent_rel: str = ""
    child: Optional[LNode] = None          # inner query AND join==parent_rel
    child_filter: Optional[LNode] = None   # join in child relations
    use_score: bool = False
    boost: float = 1.0
    join_index: Any = None
    pre: Any = None


@dataclass
class LRankFeature(LNode):
    """rank_feature scoring: a single feature row of a feature-postings block
    (gather→fn→scatter) or a dense rank_feature numeric column."""

    field: str = ""
    feature: Optional[str] = None   # None = numeric rank_feature column
    fn: str = "saturation"
    p1: float = 1.0
    p2: float = 1.0
    positive: bool = True
    boost: float = 1.0


@dataclass
class LSparseDot(LNode):
    """Learned-sparse dot product: sum of query-token weight × stored feature
    weight over a rank_features/sparse_vector block."""

    field: str = ""
    tokens: List[str] = dc_field(default_factory=list)
    weights: Optional[np.ndarray] = None
    boost: float = 1.0


@dataclass
class LDistanceFeature(LNode):
    field: str = ""
    kind: str = "date"     # date | geo
    origin: Any = None     # i64 epoch-ms | (lat, lon)
    pivot: float = 0.0     # ms | meters
    boost: float = 1.0


@dataclass
class LPercolate(LNode):
    """Stored-query reverse match: per segment, a host-computed f32 mask of
    which percolator docs' queries match the candidate mini-segment
    (search/percolate.py); the device plan just consumes the mask."""

    field: str = ""
    mini_seg: Any = None
    mini_ctx: Any = None
    boost: float = 1.0


@dataclass
class LScriptFilter(LNode):
    """`script` query: filter where the traced expression is truthy. The AST
    (hashable tuples) lives in the jit-static spec; numeric script params are
    traced scalars, so param changes reuse the XLA program."""

    ast: tuple = ()
    params: dict = dc_field(default_factory=dict)
    boost: float = 1.0


@dataclass
class LScriptScore(LNode):
    """`script_score` query (reference ScriptScoreQueryBuilder): the script
    replaces the child's score; `_score` binds to the child's score vector."""

    child: Optional[LNode] = None
    ast: tuple = ()
    params: dict = dc_field(default_factory=dict)
    min_score: Optional[float] = None
    boost: float = 1.0


@dataclass
class LKnn(LNode):
    field: str = ""
    vector: Optional[np.ndarray] = None
    k: int = 10
    filter: Optional[LNode] = None
    similarity: str = "cosine"
    boost: float = 1.0
    # ANN: None = exact scan; int = IVF nprobe request (clamped to the
    # segment's actual nlist at prepare time)
    nprobe: Optional[int] = None
    exact: bool = False


@dataclass
class LSpanHost(LNode):
    """Span/interval algebra evaluated host-side (search/spans.py): prepare
    computes the per-segment sloppy-frequency vector; the device scores it
    like a phrase pseudo-term."""

    field: str = ""
    query: Any = None           # dsl span tree, or ("intervals", field, rule)
    weight: float = 0.0         # Σ idf(term)·boost, host-computed
    boost: float = 1.0
    has_norms: bool = True
    sim: Any = None


@dataclass
class LGeoDist(LNode):
    field: str = ""
    lat: float = 0.0
    lon: float = 0.0
    radius_m: float = 0.0
    boost: float = 1.0
    inclusive: bool = True


@dataclass
class LGeoBox(LNode):
    field: str = ""
    top: float = 0.0
    left: float = 0.0
    bottom: float = 0.0
    right: float = 0.0
    boost: float = 1.0


@dataclass
class LTermsSet(LNode):
    """terms_set: the child LTerms counts matching terms per doc; the
    per-DOC minimum comes from a numeric column or a host-evaluated
    script vector (reference TermsSetQueryBuilder / Lucene CoveringQuery)."""

    field: str = ""
    child: Optional[LNode] = None
    msm_field: Optional[str] = None
    script: Optional[Tuple[str, dict]] = None   # (source, params)
    num_terms: int = 0
    boost: float = 1.0


@dataclass
class LPinned(LNode):
    """pinned: listed ids rank first (descending by list order), organic
    results follow (reference PinnedQueryBuilder)."""

    ids: Tuple[str, ...] = ()
    organic: Optional[LNode] = None
    boost: float = 1.0


@dataclass
class LCombined(LNode):
    """combined_fields: true BM25F — per-term tf combined across weighted
    fields BEFORE saturation, idf from the union doc frequency, combined
    dl/avgdl (reference CombinedFieldsQueryBuilder over Lucene
    CombinedFieldQuery)."""

    fields: Tuple[Tuple[str, float], ...] = ()
    terms: Tuple[str, ...] = ()
    msm: int = 1
    boost: float = 1.0
    idf: Optional[np.ndarray] = None   # per-term union-df idf (rewrite-time)


@dataclass
class LGeoPolygon(LNode):
    """geo_polygon on geo_point columns: device ray-cast, vertex arrays are
    query params (static length per jit key)."""

    field: str = ""
    lats: Tuple[float, ...] = ()
    lons: Tuple[float, ...] = ()
    boost: float = 1.0


@dataclass
class LGeoShape(LNode):
    """geo_shape relation filter. The mask is computed EXACTLY on the host
    at prepare time (bbox-column prefilter -> search/geo.py refinement over
    survivors) and uploaded as a bool[ndocs_pad] plan param — see
    ShapeColumn for why that is the TPU-shaped split."""

    field: str = ""
    shape: Any = None             # parsed geo.Shape
    relation: str = "intersects"
    boost: float = 1.0


# =====================================================================
# rewrite: DSL tree -> logical plan (host, index-wide stats)
# =====================================================================

def rewrite(q: dsl.Query, ctx: ShardContext, scoring: bool = True) -> LNode:
    out = _rewrite(q, ctx, scoring)
    out.name = getattr(q, "name", None) or out.name
    return out


def _weighted_terms(field: str, terms: List[str], boosts: List[float],
                    ctx: ShardContext, msm: int, mode: str, boost: float) -> LTerms:
    ft = ctx.mappings.resolve_field(field)
    sim = ctx.sim_for(field)
    has_norms = bool(ft is not None and ft.has_norms and sim.uses_norms)
    n = ctx.num_docs
    weights = np.zeros(len(terms), dtype=np.float32)
    aux = np.zeros(len(terms), dtype=np.float32)
    for i, t in enumerate(terms):
        df = ctx.doc_freq(field, t)
        weights[i] = sim.term_weight(boosts[i] * boost, n, max(df, 0)) if df > 0 else 0.0
        if sim.sim_id == ops.SIM_LM_DIRICHLET:
            aux[i] = sim.term_aux(ctx.collection_tf(field, t), ctx.total_tf(field))
    node = LTerms(field=field, terms=terms, weights=weights, aux=aux, msm=msm,
                  mode=mode, sim=sim, has_norms=has_norms, boost=boost)
    # raw (pre-idf) per-term boosts: the SPMD mesh path recomputes idf on
    # device from psum'd global stats (parallel/spmd.py DFS phase)
    node.raw_boosts = np.asarray([bi * boost for bi in boosts], np.float32)
    return node


def _prefix_rows(pb, term: str, cap: Optional[int] = None) -> range:
    """Vocab row range whose terms start with `term`, optionally capped at
    `cap` expansions (reference MultiTermQuery maxExpansions)."""
    lo = bisect_left(pb.vocab, term)
    hi = bisect_left(pb.vocab, term + "￿")
    if cap is not None:
        hi = min(hi, lo + cap)
    return range(lo, hi)


def _range_field_node(ft, q: "dsl.RangeQuery") -> LNode:
    """Range query AGAINST a range field (reference RangeFieldMapper
    relation semantics): the query bounds normalize to a closed [a, b] in
    column space exactly like index-time values, then
    intersects: lo <= b AND hi >= a; within: lo >= a AND hi <= b;
    contains: lo <= a AND hi >= b. Constant score (like the reference)."""
    member = RANGE_MEMBER[ft.type]
    kind = "float" if member in ("float", "double") else "int"
    bounds = {k: v for k, v in (("gte", q.gte), ("gt", q.gt),
                                ("lte", q.lte), ("lt", q.lt))
              if v is not None}
    a, b = _parse_range_value(ft, bounds)
    lo_f, hi_f = f"{ft.name}#lo", f"{ft.name}#hi"
    rel = q.relation
    if rel == "within":
        parts = [LRange(field=lo_f, kind=kind, lo=a),
                 LRange(field=hi_f, kind=kind, hi=b)]
    elif rel == "contains":
        parts = [LRange(field=lo_f, kind=kind, hi=a),
                 LRange(field=hi_f, kind=kind, lo=b)]
    else:                           # intersects (default)
        parts = [LRange(field=lo_f, kind=kind, hi=b),
                 LRange(field=hi_f, kind=kind, lo=a)]
    return LConstScore(child=LBool(filters=parts), boost=q.boost)


@dataclass
class LSourcePhrase(LNode):
    """Phrase over a positions-less `match_only_text` field: candidates from
    the term postings conjunction, phrase verified by re-analyzing _source
    (reference MatchOnlyTextFieldMapper phrase queries via
    SourceConfirmedTextQuery). Documented deviation: hits score the constant
    phrase weight rather than a sloppy-freq BM25 (freqs are not indexed)."""

    field: str = ""
    terms: List[str] = dc_field(default_factory=list)
    slop: int = 0
    weight: float = 1.0


def _phrase_node(field: str, terms: List[str], slop: int, ctx: ShardContext,
                 boost: float, prefix_last: bool = False,
                 max_expansions: int = 50, ordered: bool = False,
                 gap_cost: bool = False) -> LPhrase:
    """Phrase weight = sum of per-term idf (Lucene PhraseWeight: the phrase
    scores as one pseudo-term whose idf is the terms' idf sum)."""
    ft = ctx.mappings.resolve_field(field)
    if ft is not None and ft.type == "match_only_text":
        n = ctx.num_docs
        sim = ctx.sim_for(field)
        w = sum(sim.term_weight(1.0, n, min(ctx.doc_freq(field, t), n))
                for t in terms if ctx.doc_freq(field, t) > 0)
        return LSourcePhrase(field=field, terms=terms, slop=slop,
                             weight=(w or 1.0) * boost)
    sim = ctx.sim_for(field)
    has_norms = bool(ft is not None and ft.has_norms and sim.uses_norms)
    n = ctx.num_docs
    w = 0.0
    last = len(terms) - 1
    for i, t in enumerate(terms):
        if prefix_last and i == last:
            # expansion union df (capped) stands in for the prefix "term"
            df = 0
            for s in ctx.segments:
                pb = s.postings.get(field)
                if pb is None:
                    continue
                for r in _prefix_rows(pb, t, max_expansions):
                    df += int(pb.starts[r + 1] - pb.starts[r])
        else:
            df = ctx.doc_freq(field, t)
        if df > 0:
            # prefix-union df can exceed maxDoc; Lucene never sees df > N
            # (negative idf would break ranking invariants)
            w += sim.term_weight(1.0, n, min(df, n))
    return LPhrase(field=field, terms=terms, slop=slop, weight=w * boost,
                   sim=sim, has_norms=has_norms, prefix_last=prefix_last,
                   max_expansions=max_expansions, ordered=ordered,
                   gap_cost=gap_cost, boost=boost)


def _analyze_query_text(field: str, text: Any, ctx: ShardContext,
                        analyzer_override: Optional[str] = None) -> List[str]:
    ft = ctx.mappings.resolve_field(field)
    if ft is None:
        return [str(text)]
    if analyzer_override:
        return ctx.mappings.analysis.get(analyzer_override).terms(str(text))
    return ctx.mappings.search_analyzer_for(ft).terms(str(text))


def _index_term(field: str, value: Any, ctx: ShardContext) -> str:
    """Single exact term for term/terms queries: keyword normalizer applies,
    text fields match the raw token (reference TermQueryBuilder semantics).
    flat_object leaves match their "path=value" composite terms."""
    ft = ctx.mappings.resolve_field(field)
    if ft is not None and ft.flat_prefix:
        return f"{ft.flat_prefix}={value}"
    if ft is not None and ft.type in KEYWORD_TYPES:
        norm = ctx.mappings.index_analyzer(ft).terms(str(value))
        return norm[0] if norm else str(value)
    return str(value)


def _ip_cidr_node(field: str, mask: str, boost: float) -> LNode:
    """CIDR -> exact 64-bit ip range (reference IpFieldMapper prefix query)."""
    import ipaddress

    from ..index.mappings import _ip_to_int
    try:
        net = ipaddress.ip_network(mask, strict=False)
    except ValueError as e:
        raise dsl.QueryParseError(f"invalid IP mask [{mask}]: {e}")
    return LRange(field=field, kind="int",
                  lo=_ip_to_int(str(net.network_address)),
                  hi=_ip_to_int(str(net.broadcast_address)),
                  include_lo=True, include_hi=True, boost=boost)


def _numeric_eq_node(ft, field: str, value: Any, boost: float) -> LNode:
    cv = coerce_value(ft, value)
    kind = "float" if ft.type in FLOAT_TYPES else "int"
    return LRange(field=field, kind=kind, lo=cv, hi=cv,
                  include_lo=True, include_hi=True, boost=boost)


def _rewrite(q: dsl.Query, ctx: ShardContext, scoring: bool) -> LNode:  # noqa: C901
    m = ctx.mappings

    if isinstance(q, dsl.HybridQuery):
        # hybrid is a COORDINATOR construct (search/fusion.py): the
        # top-level interceptors (search_shards, distnode) consume it
        # before any per-shard plan exists. Reaching the rewriter means
        # it was nested inside another query — a structural 400.
        raise dsl.QueryParseError(
            "[hybrid] must be the top-level query — sub-queries fuse at "
            "the coordinator merge and cannot nest inside other queries")

    if isinstance(q, dsl.MatchAllQuery):
        return LMatchAll(boost=q.boost)
    if isinstance(q, dsl.MatchNoneQuery):
        return LMatchNone()

    if isinstance(q, dsl.TermQuery):
        ft = m.resolve_field(q.field)
        if ft is not None and ft.type in RANGE_TYPES:
            # containment: stored [lo, hi] covers the value (reference
            # RangeType.termQuery = intersects on a point)
            from ..index.mappings import (RANGE_MEMBER, _range_member_coerce)
            member = RANGE_MEMBER[ft.type]
            cv = _range_member_coerce(member, q.value, ft)
            kind = "float" if member in ("float", "double") else "int"
            return LConstScore(child=LBool(filters=[
                LRange(field=f"{ft.name}#lo", kind=kind, hi=cv),
                LRange(field=f"{ft.name}#hi", kind=kind, lo=cv)]),
                boost=q.boost)
        if (ft is not None and ft.type == "ip" and isinstance(q.value, str)
                and "/" in q.value):
            return _ip_cidr_node(ft.name, q.value, q.boost)
        if ft is not None and ft.type in (INT_TYPES | FLOAT_TYPES) and ft.type != "date":
            return _numeric_eq_node(ft, ft.name, q.value, q.boost)
        if ft is not None and ft.type == "date":
            return _numeric_eq_node(ft, ft.name, q.value, q.boost)
        field = ft.name if ft else q.field
        term = _index_term(q.field, q.value, ctx)
        if q.case_insensitive:
            term = term.lower()
        mode = "score" if scoring else "filter"
        return _weighted_terms(field, [term], [1.0], ctx, 1, mode, q.boost)

    if isinstance(q, dsl.TermsQuery):
        ft = m.resolve_field(q.field)
        if ft is not None and ft.type == "ip" and any(
                isinstance(v, str) and "/" in v for v in q.values):
            # CIDR members expand to ranges; exact ips stay term matches
            # (reference IpFieldMapper.termsQuery)
            children = [
                _ip_cidr_node(ft.name, v, 1.0)
                if isinstance(v, str) and "/" in v else
                _weighted_terms(ft.name, [_index_term(ft.name, v, ctx)],
                                [1.0], ctx, 1, "filter", 1.0)
                for v in q.values]
            return LBool(shoulds=children, msm=1, boost=q.boost)
        if ft is not None and ft.type in (INT_TYPES | FLOAT_TYPES):
            children = [_numeric_eq_node(ft, ft.name, v, 1.0) for v in q.values]
            return LBool(shoulds=children, msm=1, boost=q.boost)
        field = ft.name if ft else q.field
        terms = [_index_term(q.field, v, ctx) for v in q.values]
        # terms query is constant-score (reference TermInSetQuery)
        return _weighted_terms(field, terms, [1.0] * len(terms), ctx, 1, "filter", q.boost)

    if isinstance(q, dsl.MatchQuery):
        ft = m.resolve_field(q.field)
        if ft is not None and ft.type in (INT_TYPES | FLOAT_TYPES) and ft.type != "date":
            return _numeric_eq_node(ft, ft.name, q.query, q.boost)
        field = ft.name if ft else q.field
        terms = _analyze_query_text(field, q.query, ctx, q.analyzer)
        if not terms:
            return LMatchNone()
        if q.fuzziness is not None:
            expanded: List[LNode] = []
            for t in terms:
                expanded.append(LExpandTerms(field=field,
                                             expander=_fuzzy_expander(field, t, q.fuzziness, 0),
                                             boost=q.boost))
            msm = len(expanded) if q.operator == "and" else \
                dsl.parse_minimum_should_match(q.minimum_should_match, len(expanded)) or 1
            return LBool(shoulds=expanded, msm=msm, boost=1.0)
        msm = len(terms) if q.operator == "and" else \
            dsl.parse_minimum_should_match(q.minimum_should_match, len(terms)) or 1
        mode = "score" if scoring else "score"  # scores also drive msm counts
        return _weighted_terms(field, terms, [1.0] * len(terms), ctx, msm, mode, q.boost)

    if isinstance(q, dsl.MatchBoolPrefixQuery):
        ft = m.resolve_field(q.field)
        field = ft.name if ft else q.field
        terms = _analyze_query_text(field, q.query, ctx, q.analyzer)
        if not terms:
            return LMatchNone()
        children: List[LNode] = [
            _weighted_terms(field, [t], [1.0], ctx, 1, "score", q.boost)
            for t in terms[:-1]]
        children.append(LExpandTerms(
            field=field,
            expander=_prefix_expander(field, terms[-1], False, cap=50),
            boost=q.boost))
        msm = len(children) if q.operator == "and" else 1
        return LBool(shoulds=children, msm=msm, boost=1.0)

    if isinstance(q, dsl.TermsSetQuery):
        ft = m.resolve_field(q.field)
        field = ft.name if ft else q.field
        terms = [str(t) for t in q.terms]
        if not terms:
            return LMatchNone()
        child = _weighted_terms(field, terms, [1.0] * len(terms), ctx, 0,
                                "score", q.boost)
        script = None
        if q.minimum_should_match_script is not None:
            src, prm = dsl.parse_script_spec(q.minimum_should_match_script)
            try:
                pl.parse(src)
            except pl.ScriptError as e:
                raise dsl.QueryParseError(f"[terms_set] bad script: {e}")
            script = (src, prm or {})
        return LTermsSet(field=field, child=child,
                         msm_field=q.minimum_should_match_field,
                         script=script, num_terms=len(terms), boost=q.boost)

    if isinstance(q, dsl.CombinedFieldsQuery):
        fspecs = []
        for f in q.fields:
            name, w = (f.rsplit("^", 1) if "^" in f else (f, "1"))
            ftc = m.resolve_field(name)
            try:
                wf = float(w)
            except ValueError:
                raise dsl.QueryParseError(
                    f"[combined_fields] bad field boost [{f}]")
            fspecs.append((ftc.name if ftc else name, wf))
        # analyze with the first field's analyzer (reference requires all
        # combined fields share one analyzer and errors otherwise)
        terms = _analyze_query_text(fspecs[0][0], q.query, ctx, None)
        if not terms:
            return LMatchNone()
        msm = len(terms) if q.operator == "and" else \
            dsl.parse_minimum_should_match(q.minimum_should_match,
                                           len(terms)) or 1
        node = LCombined(fields=tuple(fspecs), terms=tuple(terms), msm=msm,
                         boost=q.boost)
        # union-df idf depends only on shard-wide stats: compute ONCE at
        # rewrite (like LTerms.weights), not per segment in prepare
        n = max(ctx.num_docs, 1)
        idf = np.zeros(len(terms), np.float32)
        for i, t in enumerate(terms):
            # segments have disjoint doc-id spaces: union WITHIN each
            # segment across fields, then sum the sizes
            df = 0
            for s2 in ctx.segments:
                seg_lists = []
                for fname, _w in node.fields:
                    pb = s2.postings.get(fname)
                    r = pb.row(t) if pb is not None else -1
                    if r >= 0:
                        a, b2 = pb.row_slice(r)
                        seg_lists.append(pb.doc_ids[a:b2])
                if len(seg_lists) == 1:
                    df += len(seg_lists[0])
                elif seg_lists:
                    df += len(np.unique(np.concatenate(seg_lists)))
            if df > 0:
                idf[i] = q.boost * float(
                    np.log(1.0 + (n - df + 0.5) / (df + 0.5)))
        node.idf = idf
        return node

    if isinstance(q, dsl.PinnedQuery):
        return LPinned(ids=tuple(q.ids),
                       organic=(rewrite(q.organic, ctx, scoring)
                                if q.organic else None), boost=q.boost)

    if isinstance(q, dsl.MultiMatchQuery):
        if q.type in ("phrase", "phrase_prefix"):
            children = [rewrite(dsl.MatchPhraseQuery(
                            field=f.split("^")[0], query=q.query,
                            prefix=q.type == "phrase_prefix",
                            boost=float(f.split("^")[1]) if "^" in f else 1.0),
                        ctx, scoring) for f in q.fields]
        else:
            children = [rewrite(dsl.MatchQuery(field=f.split("^")[0], query=q.query,
                                               operator=q.operator,
                                               minimum_should_match=q.minimum_should_match,
                                               boost=float(f.split("^")[1]) if "^" in f else 1.0),
                        ctx, scoring) for f in q.fields]
        if q.type in ("best_fields", "phrase", "phrase_prefix"):
            return LDisMax(children=children, tie_breaker=q.tie_breaker, boost=q.boost)
        return LBool(shoulds=children, msm=1, boost=q.boost)  # most_fields

    if isinstance(q, dsl.MatchPhraseQuery):
        ft = m.resolve_field(q.field)
        field = ft.name if ft else q.field
        terms = _analyze_query_text(field, q.query, ctx, q.analyzer)
        if not terms:
            return LMatchNone()
        if len(terms) == 1 and not q.prefix:
            # Lucene rewrites a single-term phrase to a TermQuery
            return _weighted_terms(field, terms, [1.0], ctx, 1, "score", q.boost)
        if len(terms) == 1 and q.prefix:
            return LExpandTerms(field=field,
                                expander=_prefix_expander(field, terms[0], False,
                                                          cap=q.max_expansions),
                                boost=q.boost)
        return _phrase_node(field, terms, q.slop, ctx, q.boost,
                            prefix_last=q.prefix, max_expansions=q.max_expansions)

    if isinstance(q, dsl.SpanTermQuery):
        field = q.field
        term = _index_term(field, q.value, ctx)
        return _weighted_terms(field, [term], [1.0], ctx, 1, "score", q.boost)

    if isinstance(q, dsl.SpanNearQuery):
        if not all(isinstance(c, dsl.SpanTermQuery) for c in q.clauses) or \
                len({c.field for c in q.clauses}) > 1:
            # nested span algebra inside near -> host span engine
            return _span_host_node(q, None, ctx, q.boost)
        flat_terms: List[str] = []
        field = None
        for c in q.clauses:
            if field is None:
                field = c.field
            flat_terms.append(_index_term(c.field, c.value, ctx))
        if not flat_terms or field is None:
            return LMatchNone()
        if len(flat_terms) == 1:
            return _weighted_terms(field, flat_terms, [1.0], ctx, 1, "score", q.boost)
        # Lucene SpanNearQuery slop counts intervening unmatched positions
        # (gaps), not term movement
        return _phrase_node(field, flat_terms, q.slop, ctx, q.boost,
                            ordered=q.in_order, gap_cost=True)

    if isinstance(q, (dsl.SpanOrQuery, dsl.SpanNotQuery, dsl.SpanFirstQuery,
                      dsl.SpanContainingQuery, dsl.SpanWithinQuery,
                      dsl.SpanMultiQuery, dsl.FieldMaskingSpanQuery)):
        return _span_host_node(q, None, ctx, q.boost)

    if isinstance(q, dsl.IntervalsQuery) and q.rule is not None:
        ft = m.resolve_field(q.field)
        field = ft.name if ft else q.field
        r = q.rule
        if r.kind == "match" and r.filter_kind is None:
            # hot path: single match rule rides the device pair-join below
            q = dsl.IntervalsQuery(field=q.field, query=r.query,
                                   max_gaps=r.max_gaps, ordered=r.ordered,
                                   analyzer=r.analyzer, boost=q.boost)
        else:
            return _span_host_node(("intervals", field, r), field, ctx,
                                   q.boost)

    if isinstance(q, dsl.IntervalsQuery):
        ft = m.resolve_field(q.field)
        field = ft.name if ft else q.field
        terms = _analyze_query_text(field, q.query, ctx, q.analyzer)
        if not terms:
            return LMatchNone()
        if len(terms) == 1:
            return _weighted_terms(field, terms, [1.0], ctx, 1, "score", q.boost)
        # max_gaps=-1 means unbounded; bound by a large window (the device
        # join needs a finite slop). For ordered matches the median-centered
        # movement cost equals the total gap count, so max_gaps maps 1:1.
        slop = q.max_gaps if q.max_gaps >= 0 else 1 << 20
        return _phrase_node(field, terms, slop, ctx, q.boost, ordered=q.ordered,
                            gap_cost=True)

    if isinstance(q, dsl.BoolQuery):
        musts = [rewrite(c, ctx, scoring) for c in q.must]
        shoulds = [rewrite(c, ctx, scoring) for c in q.should]
        must_nots = [rewrite(c, ctx, False) for c in q.must_not]
        filters = [rewrite(c, ctx, False) for c in q.filter]
        n_should = len(shoulds)
        if q.minimum_should_match is not None:
            msm = dsl.parse_minimum_should_match(q.minimum_should_match, n_should)
        else:
            msm = 1 if (n_should and not musts and not filters) else 0
        return LBool(musts=musts, shoulds=shoulds, must_nots=must_nots,
                     filters=filters, msm=msm, boost=q.boost)

    if isinstance(q, dsl.RangeQuery):
        ft = m.resolve_field(q.field)
        if ft is None:
            return LMatchNone()
        if ft.type in RANGE_TYPES:
            return _range_field_node(ft, q)
        if ft.type in KEYWORD_TYPES and ft.type != "ip":
            return LExpandTerms(field=ft.name,
                                expander=_keyword_range_expander(ft.name, q),
                                boost=q.boost)
        kind = "float" if ft.type in FLOAT_TYPES else "int"
        lo = hi = None
        inc_lo = inc_hi = True
        if q.gte is not None:
            lo, inc_lo = coerce_value(ft, q.gte), True
        if q.gt is not None:
            lo, inc_lo = coerce_value(ft, q.gt), False
        if q.lte is not None:
            hi, inc_hi = coerce_value(ft, q.lte), True
        if q.lt is not None:
            hi, inc_hi = coerce_value(ft, q.lt), False
        return LRange(field=ft.name, kind=kind, lo=lo, hi=hi,
                      include_lo=inc_lo, include_hi=inc_hi, boost=q.boost)

    if isinstance(q, dsl.ExistsQuery):
        ft = m.resolve_field(q.field)
        if ft is not None and ft.type in RANGE_TYPES:
            return LExists(field=f"{ft.name}#lo", boost=q.boost)
        if ft is not None and ft.flat_prefix:
            # flat_object leaf exists = any "path=..." term under #paths
            return LExpandTerms(
                field=ft.name,
                expander=_prefix_expander(ft.name, f"{ft.flat_prefix}=",
                                          False),
                boost=q.boost)
        return LExists(field=ft.name if ft else q.field, boost=q.boost)

    if isinstance(q, dsl.IdsQuery):
        return LIds(ids=list(q.values), boost=q.boost)

    if isinstance(q, dsl.ConstantScoreQuery):
        return LConstScore(child=rewrite(q.filter, ctx, False), boost=q.boost)

    if isinstance(q, dsl.BoostingQuery):
        return LBoosting(positive=rewrite(q.positive, ctx, scoring),
                         negative=rewrite(q.negative, ctx, False),
                         negative_boost=q.negative_boost, boost=q.boost)

    if isinstance(q, dsl.DisMaxQuery):
        return LDisMax(children=[rewrite(c, ctx, scoring) for c in q.queries],
                       tie_breaker=q.tie_breaker, boost=q.boost)

    if isinstance(q, dsl.PrefixQuery):
        return LExpandTerms(field=q.field, expander=_prefix_expander(q.field, q.value,
                                                                     q.case_insensitive),
                            boost=q.boost)
    if isinstance(q, dsl.WildcardQuery):
        return LExpandTerms(field=q.field, expander=_wildcard_expander(q.field, q.value,
                                                                       q.case_insensitive),
                            boost=q.boost)
    if isinstance(q, dsl.RegexpQuery):
        return LExpandTerms(field=q.field, expander=_regexp_expander(q.field, q.value),
                            boost=q.boost)
    if isinstance(q, dsl.FuzzyQuery):
        return LExpandTerms(field=q.field,
                            expander=_fuzzy_expander(q.field, q.value, q.fuzziness,
                                                     q.prefix_length),
                            boost=q.boost)

    if isinstance(q, (dsl.QueryStringQuery, dsl.SimpleQueryStringQuery)):
        return _rewrite_query_string(q, ctx, scoring)

    if isinstance(q, dsl.KnnQuery):
        ft = m.resolve_field(q.field)
        sim = ft.vector_similarity if ft is not None else "cosine"
        vec = np.asarray(q.vector, np.float32)
        if sim == "cosine":
            vec = vec / max(float(np.linalg.norm(vec)), 1e-12)
        return LKnn(field=q.field, vector=vec, k=q.k,
                    filter=rewrite(q.filter, ctx, False) if q.filter else None,
                    similarity=sim, boost=q.boost,
                    nprobe=q.nprobe, exact=q.exact)

    if isinstance(q, dsl.GeoDistanceQuery):
        return LGeoDist(field=q.field, lat=q.lat, lon=q.lon, radius_m=q.distance_m,
                        boost=q.boost, inclusive=q.inclusive)
    if isinstance(q, dsl.GeoBoundingBoxQuery):
        return LGeoBox(field=q.field, top=q.top, left=q.left, bottom=q.bottom,
                       right=q.right, boost=q.boost)

    if isinstance(q, dsl.GeoPolygonQuery):
        return LGeoPolygon(field=q.field, lats=tuple(q.lats),
                           lons=tuple(q.lons), boost=q.boost)

    if isinstance(q, dsl.GeoShapeQuery):
        from .geo import ShapeParseError, parse_shape
        ft = m.resolve_field(q.field)
        if ft is None:
            if q.ignore_unmapped:
                return LMatchNone()
            raise dsl.QueryParseError(
                f"[geo_shape] failed to find geo field [{q.field}]")
        if ft.type not in ("geo_shape", "geo_point"):
            raise dsl.QueryParseError(
                f"[geo_shape] field [{q.field}] is of type [{ft.type}], "
                f"not geo_shape/geo_point")
        try:
            shape = parse_shape(q.shape)
        except ShapeParseError as e:
            raise dsl.QueryParseError(f"[geo_shape] {e}")
        return LGeoShape(field=q.field, shape=shape, relation=q.relation,
                         boost=q.boost)

    if isinstance(q, dsl.ScriptQuery):
        try:
            ast = pl.validate_device_script(q.source)
        except pl.ScriptError as e:
            raise dsl.QueryParseError(f"[script] compile error: {e}")
        return LScriptFilter(ast=ast, params=q.params or {}, boost=q.boost)

    if isinstance(q, dsl.ScriptScoreQuery):
        try:
            ast = pl.validate_device_script(q.source)
        except pl.ScriptError as e:
            raise dsl.QueryParseError(f"[script_score] compile error: {e}")
        return LScriptScore(child=rewrite(q.query or dsl.MatchAllQuery(), ctx, scoring),
                            ast=ast, params=q.params or {},
                            min_score=q.min_score, boost=q.boost)

    if isinstance(q, dsl.FunctionScoreQuery):
        child = rewrite(q.query or dsl.MatchAllQuery(), ctx, scoring)
        fn_filters = [rewrite(f.filter, ctx, False) if f.filter else None
                      for f in q.functions]
        for f in q.functions:
            if f.kind == "script_score":
                try:
                    pl.validate_device_script(f.script or "")
                except pl.ScriptError as e:
                    raise dsl.QueryParseError(f"[script_score] compile error: {e}")
        return LFuncScore(child=child, functions=q.functions, fn_filters=fn_filters,
                          score_mode=q.score_mode, boost_mode=q.boost_mode,
                          min_score=q.min_score, boost=q.boost)

    if isinstance(q, dsl.MoreLikeThisQuery):
        return _rewrite_mlt(q, ctx, scoring)

    if isinstance(q, dsl.NestedQuery):
        if q.path not in m.nested_paths:
            if q.ignore_unmapped:
                return LMatchNone()
            raise dsl.QueryParseError(
                f"[nested] failed to find nested object under path [{q.path}]")
        # multi-level path queried from an outer level: blocks live on the
        # intermediate child segments, so route through the nested chain
        # (nested(a, nested(a.b, q)) — reference resolves the chain the same
        # way via parent filters)
        if not any(q.path in s.nested for s in ctx.segments):
            parts = q.path.split(".")
            for cut in range(len(parts) - 1, 0, -1):
                pfx = ".".join(parts[:cut])
                if pfx in m.nested_paths and any(pfx in s.nested
                                                 for s in ctx.segments):
                    inner_q = dsl.NestedQuery(path=q.path, query=q.query,
                                              score_mode=q.score_mode,
                                              ignore_unmapped=q.ignore_unmapped)
                    outer = dsl.NestedQuery(path=pfx, query=inner_q,
                                            score_mode=q.score_mode,
                                            boost=q.boost)
                    return _rewrite(outer, ctx, scoring)
        child_ctx = nested_context(ctx, q.path)
        inner = rewrite(q.query, child_ctx, scoring)
        return LNested(path=q.path, child=inner, child_ctx=child_ctx,
                       score_mode=q.score_mode, boost=q.boost)

    if isinstance(q, dsl.RankFeatureQuery):
        return _rewrite_rank_feature(q, ctx)

    if isinstance(q, dsl.NeuralSparseQuery):
        ft = m.resolve_field(q.field)
        if ft is None or ft.type not in ("rank_features", "sparse_vector"):
            raise dsl.QueryParseError(
                f"[neural_sparse] field [{q.field}] is not a rank_features/"
                f"sparse_vector field")
        toks = sorted(q.tokens)
        return LSparseDot(field=ft.name, tokens=toks,
                          weights=np.asarray([q.tokens[t] for t in toks],
                                             np.float32),
                          boost=q.boost)

    if isinstance(q, dsl.DistanceFeatureQuery):
        ft = m.resolve_field(q.field)
        if ft is None:
            raise dsl.QueryParseError(
                f"[distance_feature] unknown field [{q.field}]")
        if ft.type == "date":
            from ..index.mappings import _parse_date
            origin = _parse_date(q.origin, ft.date_format)
            pivot = float(parse_interval_ms(q.pivot))
            return LDistanceFeature(field=ft.name, kind="date", origin=origin,
                                    pivot=pivot, boost=q.boost)
        if ft.type in ("geo_point",):
            origin = dsl._parse_point(q.origin)
            pivot = dsl._parse_distance(q.pivot)
            return LDistanceFeature(field=ft.name, kind="geo", origin=origin,
                                    pivot=pivot, boost=q.boost)
        raise dsl.QueryParseError(
            f"[distance_feature] field [{q.field}] must be a date or "
            f"geo_point field")

    if isinstance(q, (dsl.HasChildQuery, dsl.HasParentQuery, dsl.ParentIdQuery)):
        return _rewrite_join(q, ctx, scoring)

    if isinstance(q, dsl.PercolateQuery):
        from .percolate import build_mini

        ft = m.resolve_field(q.field)
        if ft is None or ft.type != "percolator":
            raise dsl.QueryParseError(
                f"[percolate] field [{q.field}] is not a percolator field")
        if not q.documents:
            raise dsl.QueryParseError(
                "[percolate] document reference was not resolved "
                "(use the REST layer, or inline `document`)")
        try:
            mini_seg, mini_ctx = build_mini(m, q.documents)
        except ValueError as e:
            raise dsl.QueryParseError(f"[percolate] cannot parse document: {e}")
        return LPercolate(field=ft.name, mini_seg=mini_seg, mini_ctx=mini_ctx,
                          boost=q.boost)

    raise dsl.QueryParseError(f"cannot compile query {type(q).__name__}")


def _span_host_node(query, field: Optional[str], ctx: ShardContext,
                    boost: float) -> LNode:
    """Evaluate a span/interval algebra tree host-side over every segment
    (search/spans.py) and wrap the per-segment frequency vectors in an
    LSpanHost scored on device. Evaluation is eager at rewrite so the
    pseudo-term weight (Σ idf over involved terms) is identical across
    segments (global statistics, like the DFS phase)."""
    from . import spans as SP

    # structural validation first: shape/field errors must surface even on
    # an empty index (data-independent, like the reference's parse phase);
    # span evaluation itself is LAZY per segment (prepare) so a multi-shard
    # coordinator doesn't evaluate every shard's segments once per shard
    if isinstance(query, tuple):
        f = query[1]
    else:
        f = SP.span_query_field(query, ctx) or field
    if f is None:
        return LMatchNone()
    terms_seen = SP.collect_terms(query, ctx)
    sim = ctx.sim_for(f)
    n = ctx.num_docs
    weight = 0.0
    for t in dict.fromkeys(terms_seen):
        df = ctx.doc_freq(f, t)
        if df > 0:
            weight += sim.term_weight(1.0, n, df)
    ft = ctx.mappings.resolve_field(f)
    has_norms = bool(ft is not None and ft.has_norms and sim.uses_norms)
    node = LSpanHost(field=f, query=query, weight=weight * boost,
                     boost=boost, has_norms=has_norms, sim=sim)
    node._freqs = {}
    return node


def _rewrite_mlt(q: dsl.MoreLikeThisQuery, ctx: ShardContext,
                 scoring: bool) -> LNode:
    """more_like_this (reference `index/query/MoreLikeThisQueryBuilder.java`,
    Lucene MoreLikeThis): gather term frequencies from the liked texts/docs,
    rank candidate terms by tf·idf, keep the top `max_query_terms`, and
    search them as a weighted OR (device term-group). Liked docs are excluded
    via must_not ids unless `include`."""
    fields = list(q.fields)
    if not fields:
        fields = [name for name, ft in ctx.mappings.fields.items()
                  if ft.type == "text"]
        if not fields:
            return LMatchNone()
    stop = set(q.stop_words)

    def texts_of(like_item, liked_ids):
        if isinstance(like_item, str):
            return {f: [like_item] for f in fields}
        # {"_id": ...} / {"doc": {...}} document reference
        if isinstance(like_item, dict):
            if "doc" in like_item:
                src = like_item["doc"]
            else:
                did = like_item.get("_id")
                if did is None:
                    raise dsl.QueryParseError(
                        "[more_like_this] like item needs text, [_id] or [doc]")
                liked_ids.append(str(did))
                src = None
                for seg in ctx.segments:
                    d = seg.id2doc.get(str(did))
                    if d is not None and seg.live[d]:
                        src = seg.sources[d]
                        break
                if src is None:
                    return {}
            out = {}
            for f in fields:
                v = src.get(f)
                if isinstance(v, str):
                    out[f] = [v]
                elif isinstance(v, list):
                    out[f] = [str(x) for x in v]
            return out
        raise dsl.QueryParseError("[more_like_this] invalid like item")

    liked_ids: List[str] = []
    tf_counts: Dict[Tuple[str, str], int] = {}
    for item in q.like:
        for f, texts in texts_of(item, liked_ids).items():
            for text in texts:
                for t in _analyze_query_text(f, text, ctx):
                    tf_counts[(f, t)] = tf_counts.get((f, t), 0) + 1
    skip: set = set()
    for item in q.unlike:
        for f, texts in texts_of(item, []).items():
            for text in texts:
                for t in _analyze_query_text(f, text, ctx):
                    skip.add((f, t))

    n = max(ctx.num_docs, 1)
    scored = []
    for (f, t), tf in tf_counts.items():
        if (f, t) in skip or t in stop or tf < q.min_term_freq:
            continue
        if len(t) < q.min_word_length:
            continue
        if q.max_word_length and len(t) > q.max_word_length:
            continue
        df = ctx.doc_freq(f, t)
        if df < q.min_doc_freq or df > q.max_doc_freq or df <= 0:
            continue
        idf = ops.bm25_idf(n, df)
        scored.append((tf * idf, f, t))
    scored.sort(key=lambda x: (-x[0], x[1], x[2]))
    scored = scored[: q.max_query_terms]
    if not scored:
        return LMatchNone()
    best = scored[0][0]
    by_field: Dict[str, List[Tuple[str, float]]] = {}
    for s, f, t in scored:
        boost = (q.boost_terms * s / best) if q.boost_terms > 0 else 1.0
        by_field.setdefault(f, []).append((t, boost))
    msm_total = dsl.parse_minimum_should_match(q.minimum_should_match,
                                               len(scored))
    mode = "score" if scoring else "filter"
    if len(by_field) == 1:
        ((f, pairs),) = by_field.items()
        node = _weighted_terms(f, [t for t, _ in pairs],
                               [b for _, b in pairs], ctx,
                               msm=max(msm_total, 1), mode=mode,
                               boost=q.boost)
    else:
        # multi-field: one single-term group per clause so msm counts terms
        # across fields exactly like the reference boolean query
        shoulds = [
            _weighted_terms(f, [t], [b], ctx, msm=1, mode=mode, boost=1.0)
            for f, pairs in by_field.items() for t, b in pairs]
        node = LBool(shoulds=shoulds, msm=max(msm_total, 1), boost=q.boost)
    if liked_ids and not q.include:
        return LBool(musts=[node], must_nots=[LIds(ids=liked_ids)],
                     boost=1.0)
    return node


def _rewrite_rank_feature(q: dsl.RankFeatureQuery, ctx: ShardContext) -> LNode:
    m = ctx.mappings
    ft = m.resolve_field(q.field)
    if ft is not None and ft.type == "rank_feature":
        field, feature, positive = ft.name, None, ft.positive_score_impact
    else:
        # "features.pagerank": longest mapped prefix typed rank_features
        parts = q.field.split(".")
        field = feature = None
        for cut in range(len(parts) - 1, 0, -1):
            pft = m.resolve_field(".".join(parts[:cut]))
            if pft is not None and pft.type in ("rank_features", "sparse_vector"):
                field, feature = pft.name, ".".join(parts[cut:])
                positive = pft.positive_score_impact
                break
        if field is None:
            raise dsl.QueryParseError(
                f"[rank_feature] field [{q.field}] is not a rank_feature or "
                f"rank_features feature")

    fn, p1, p2 = q.function, 1.0, 1.0
    if not positive and fn in ("log", "linear"):
        raise dsl.QueryParseError(
            f"[rank_feature] [{fn}] is incompatible with "
            f"positive_score_impact=false fields")
    if fn == "saturation":
        p1 = q.pivot if q.pivot is not None else _default_pivot(ctx, field, feature)
    elif fn == "log":
        p1 = float(q.scaling_factor)
    elif fn == "sigmoid":
        p1, p2 = float(q.pivot), float(q.exponent)
    return LRankFeature(field=field, feature=feature, fn=fn, p1=float(p1),
                        p2=float(p2), positive=positive, boost=q.boost)


def _default_pivot(ctx: ShardContext, field: str, feature: Optional[str]) -> float:
    """Default saturation pivot ≈ mean feature value over the index
    (reference computes an approximate geometric mean from the index stats)."""
    total, count = 0.0, 0
    for s in ctx.segments:
        if feature is None:
            col = s.numeric_cols.get(field)
            if col is not None and col.present.any():
                total += float(col.values[col.present].sum())
                count += int(col.present.sum())
        else:
            pb = s.postings.get(field)
            if pb is not None:
                r = pb.row(feature)
                if r >= 0:
                    a, b = pb.row_slice(r)
                    total += float(pb.tfs[a:b].sum())
                    count += b - a
    return (total / count) if count else 1.0


def _rewrite_join(q, ctx: ShardContext, scoring: bool) -> LNode:
    from .join import get_join_index

    m = ctx.mappings
    jf = m.join_field
    kind = {dsl.HasChildQuery: "has_child", dsl.HasParentQuery: "has_parent",
            dsl.ParentIdQuery: "parent_id"}[type(q)]
    relations = m.fields[jf].relations if jf else {}
    child_rels_all = {c for cs in relations.values() for c in cs}

    def unmapped(msg: str) -> LNode:
        if q.ignore_unmapped:
            return LMatchNone()
        raise dsl.QueryParseError(f"[{kind}] {msg}")

    if jf is None:
        return unmapped("no [join] field is mapped on this index")

    if kind == "parent_id":
        if q.type not in child_rels_all:
            return unmapped(f"[{q.type}] is not a child relation")
        inner = LBool(filters=[
            _weighted_terms(f"{jf}#parent", [q.id], [1.0], ctx, 1, "filter", 1.0),
            _weighted_terms(jf, [q.type], [1.0], ctx, 1, "filter", 1.0)])
        return LConstScore(child=inner, boost=q.boost)

    ji = get_join_index(ctx.segments, jf)
    if kind == "has_child":
        parent_rel = next((p for p, cs in relations.items() if q.type in cs), None)
        if parent_rel is None:
            return unmapped(f"[{q.type}] is not a child relation of the join field")
        inner = rewrite(q.query or dsl.MatchAllQuery(), ctx, scoring)
        child = LBool(musts=[inner], filters=[
            _weighted_terms(jf, [q.type], [1.0], ctx, 1, "filter", 1.0)])
        pf = _weighted_terms(jf, [parent_rel], [1.0], ctx, 1, "filter", 1.0)
        return LHasChild(join_field=jf, child_rel=q.type, child=child,
                         parent_filter=pf, score_mode=q.score_mode,
                         min_children=q.min_children, max_children=q.max_children,
                         boost=q.boost, join_index=ji)

    # has_parent
    if q.parent_type not in relations:
        return unmapped(f"[{q.parent_type}] is not a parent relation")
    inner = rewrite(q.query or dsl.MatchAllQuery(), ctx, scoring)
    parent_plan = LBool(musts=[inner], filters=[
        _weighted_terms(jf, [q.parent_type], [1.0], ctx, 1, "filter", 1.0)])
    cf = _weighted_terms(jf, sorted(relations[q.parent_type]),
                         [1.0] * len(relations[q.parent_type]), ctx, 1,
                         "filter", 1.0)
    return LHasParent(join_field=jf, parent_rel=q.parent_type, child=parent_plan,
                      child_filter=cf, use_score=q.score, boost=q.boost,
                      join_index=ji)


def nested_context(ctx: ShardContext, path: str) -> ShardContext:
    """Child-space statistics context: BM25 idf/avgdl over the nested path's
    child docs (Lucene computes stats over child Lucene docs the same way)."""
    child_segs = [s.nested[path].child for s in ctx.segments if path in s.nested]
    return ShardContext(ctx.mappings, child_segs,
                        similarity=ctx.default_sim,
                        field_similarities=ctx.field_sims)


def _rewrite_query_string(q, ctx: ShardContext, scoring: bool) -> LNode:
    """Full Lucene query_string / lenient simple_query_string grammars
    (search/querystring.py) -> DSL tree -> this rewriter. The string
    grammar therefore compiles to exactly the same device plans as native
    JSON DSL."""
    from . import querystring as qsmod
    default_fields = q.fields or ([q.default_field] if getattr(q, "default_field", None)
                                  else ["*"])
    if list(default_fields) == ["*"]:
        default_fields = [f for f, ft in ctx.mappings.fields.items()
                          if ft.type in TEXT_TYPES]
        if not default_fields:
            default_fields = list(ctx.mappings.fields)[:1] or ["_all"]
    if isinstance(q, dsl.SimpleQueryStringQuery):
        tree = qsmod.parse_simple_query_string(q.query, list(default_fields),
                                               q.default_operator)
    else:
        tree = qsmod.parse_query_string(
            q.query, list(default_fields), q.default_operator,
            phrase_slop=int(getattr(q, "phrase_slop", 0) or 0))
    tree.boost = tree.boost * q.boost
    return rewrite(tree, ctx, scoring)


# ---------------- multi-term expanders (host, per segment vocab) ----------------

def _prefix_expander(field: str, prefix: str, ci: bool, cap: Optional[int] = None):
    def expand(seg: Segment) -> np.ndarray:
        pb = seg.postings.get(field)
        if pb is None:
            return np.empty(0, np.int32)
        if ci:
            rows = [i for i, t in enumerate(pb.vocab) if t.lower().startswith(prefix.lower())]
            rows = rows[:cap] if cap is not None else rows
            return np.asarray(rows, np.int32)
        r = _prefix_rows(pb, prefix, cap)
        return np.arange(r.start, r.stop, dtype=np.int32)
    return expand


def _wildcard_expander(field: str, pattern: str, ci: bool):
    def expand(seg: Segment) -> np.ndarray:
        pb = seg.postings.get(field)
        if pb is None:
            return np.empty(0, np.int32)
        pat = pattern.lower() if ci else pattern
        rows = [i for i, t in enumerate(pb.vocab)
                if _fnmatch.fnmatchcase(t.lower() if ci else t, pat)]
        return np.asarray(rows, np.int32)
    return expand


def _regexp_expander(field: str, pattern: str):
    """Full Lucene regexp syntax (search/regexp.py DFA engine, incl. ~ & @
    <m-n>); the whole term dictionary is matched in one vectorized DFA run
    over a cached per-(segment, field) codepoint matrix."""
    from .regexp import RegexpError, compile_regexp, match_vocab
    try:
        compile_regexp(pattern)   # validate once -> 400, not per segment
    except RegexpError as e:
        raise dsl.QueryParseError(f"[regexp] {e}")

    def expand(seg: Segment) -> np.ndarray:
        pb = seg.postings.get(field)
        if pb is None:
            return np.empty(0, np.int32)
        hits = match_vocab(pattern, pb.vocab, cache_key=(seg.uid, field))
        return np.nonzero(hits)[0].astype(np.int32)
    return expand


def _edit_distance_le(a: str, b: str, k: int) -> bool:
    """Optimal-string-alignment distance <= k (transpositions count 1, like
    Lucene FuzzyQuery's default transpositions=true)."""
    if abs(len(a) - len(b)) > k:
        return False
    prev2: Optional[list] = None
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        lo = len(b) + 1
        for j, cb in enumerate(b, 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (ca != cb))
            if (prev2 is not None and i > 1 and j > 1
                    and ca == b[j - 2] and a[i - 2] == cb):
                cur[j] = min(cur[j], prev2[j - 2] + 1)
            lo = min(lo, cur[j])
        if lo > k:
            return False
        prev2, prev = prev, cur
    return prev[-1] <= k


def _auto_fuzz(term: str, fuzziness) -> int:
    if fuzziness in ("AUTO", "auto", None):
        # reference Fuzziness.AUTO: 0 for <3 chars, 1 for 3-5, 2 for >5
        return 0 if len(term) < 3 else (1 if len(term) <= 5 else 2)
    return int(fuzziness)


def _fuzzy_expander(field: str, term: str, fuzziness, prefix_length: int):
    k = None
    def expand(seg: Segment) -> np.ndarray:
        nonlocal k
        if k is None:
            k = _auto_fuzz(term, fuzziness)
        pb = seg.postings.get(field)
        if pb is None:
            return np.empty(0, np.int32)
        pre = term[:prefix_length]
        rows = [i for i, t in enumerate(pb.vocab)
                if t.startswith(pre) and _edit_distance_le(t, term, k)]
        return np.asarray(rows, np.int32)
    return expand


def _keyword_range_expander(field: str, q: dsl.RangeQuery):
    def expand(seg: Segment) -> np.ndarray:
        pb = seg.postings.get(field)
        if pb is None:
            return np.empty(0, np.int32)
        lo = 0
        hi = len(pb.vocab)
        if q.gte is not None:
            lo = bisect_left(pb.vocab, str(q.gte))
        if q.gt is not None:
            lo = bisect_right(pb.vocab, str(q.gt))
        if q.lte is not None:
            hi = bisect_right(pb.vocab, str(q.lte))
        if q.lt is not None:
            hi = bisect_left(pb.vocab, str(q.lt))
        return np.arange(lo, max(hi, lo), dtype=np.int32)
    return expand


# =====================================================================
# prepare: bind logical plan to one segment -> (spec, params)
# =====================================================================

F32_MIN = np.float32(-3.4e38)
F32_MAX_HOST = np.float32(3.4e38)


def _p(params: dict, key: str, value) -> str:
    params[key] = value
    return key


def _scalar_f32(params, key, v) -> str:
    return _p(params, key, np.float32(v))


def _scalar_i32(params, key, v) -> str:
    return _p(params, key, np.int32(v))


def _i64_bounds(params, nid: int, lo, hi) -> Tuple[str, str, str, str]:
    lo = -(2**63) if lo is None else int(lo)
    hi = 2**63 - 1 if hi is None else int(hi)
    lo_hi, lo_lo = split_i64(np.asarray([lo]))
    hi_hi, hi_lo = split_i64(np.asarray([hi]))
    return (_p(params, f"q{nid}_lohi", lo_hi[0]), _p(params, f"q{nid}_lolo", lo_lo[0]),
            _p(params, f"q{nid}_hihi", hi_hi[0]), _p(params, f"q{nid}_hilo", hi_lo[0]))


def _phrase_pairs(seg: Segment, pb, rows: Tuple[int, ...]):
    """Unshifted (doc, position) pairs for a term's postings (union over
    `rows` for prefix expansion), lex-sorted; cached per segment and shared
    across query positions (the caller subtracts the phrase offset when
    padding — a constant shift keeps lex order)."""
    cache = getattr(seg, "_phrase_pair_cache", None)
    if cache is None:
        cache = seg._phrase_pair_cache = {}
    key = (pb.field, rows)
    if key in cache:
        return cache[key]
    docs_parts, pos_parts = [], []
    for r in rows:
        a, b = pb.row_slice(r)
        counts = pb.pos_starts[a + 1: b + 1] - pb.pos_starts[a: b]
        docs_parts.append(np.repeat(pb.doc_ids[a:b], counts))
        pos_parts.append(pb.positions[pb.pos_starts[a]: pb.pos_starts[b]])
    d = np.concatenate(docs_parts) if docs_parts else np.empty(0, np.int32)
    p = np.concatenate(pos_parts) if pos_parts else np.empty(0, np.int32)
    if len(rows) > 1 and len(d):
        order = np.lexsort((p, d))
        d, p = d[order], p[order]
    res = (d.astype(np.int32), p.astype(np.int32))
    cache[key] = res
    return res


def _source_phrase_match(seg: Segment, doc: int, field: str,
                         terms: List[str], slop: int, analyzer) -> bool:
    """Re-analyze one doc's _source value(s) for `field` and test the
    phrase with the same median-offset total-movement slop cost the device
    path uses (ops/positions.py phrase_freqs)."""
    if analyzer is None:
        return False
    src = seg.sources[doc]
    node = src
    for part in field.split("."):
        if not isinstance(node, dict) or part not in node:
            return False
        node = node[part]
    values = node if isinstance(node, list) else [node]
    base = 0
    positions: Dict[str, List[int]] = {}
    for v in values:
        toks = analyzer.analyze(str(v))
        last = 0
        for t in toks:
            positions.setdefault(t.text, []).append(base + t.position)
            last = t.position
        base += last + 100          # value gap, matching index-time
    per_term = [positions.get(t) for t in terms]
    if any(p is None for p in per_term):
        return False
    for p0 in per_term[0]:
        deltas = [0.0]
        for i, plist in enumerate(per_term[1:], start=1):
            # nearest adjusted position to the anchor
            best = min((p - i - p0 for p in plist), key=abs)
            deltas.append(float(best))
        med = sorted(deltas)[len(deltas) // 2]
        cost = sum(abs(d - med) for d in deltas)
        if cost <= slop:
            return True
    return False


def _pad_to_sentinel(arr: np.ndarray, size: int) -> np.ndarray:
    out = np.full(size, INT32_SENTINEL, dtype=np.int32)
    out[: len(arr)] = arr
    return out


def prepare(node: LNode, seg: Segment, ctx: ShardContext, params: dict):  # noqa: C901
    """-> hashable spec tree; fills `params` with this segment's arrays."""
    nid = node.nid

    if isinstance(node, LTerms):
        pb = seg.postings.get(node.field)
        T_pad = next_pow2(len(node.terms), floor=1)
        rows = np.full(T_pad, -1, dtype=np.int32)
        total = 0
        if pb is not None:
            for i, t in enumerate(node.terms):
                r = pb.row(t)
                rows[i] = r
                if r >= 0:
                    a, b = pb.row_slice(r)
                    total += b - a
        bucket = ops.pick_bucket(total)
        # codec-version branch (consults Segment.codec_version, OSL507):
        # v2 fields carry no resident f32 tf plane. Filter-mode programs
        # run the tf-free gather (layout tag below); exact-scoring
        # programs still need tf/dl math, so prepare promotes the plane
        # back onto the device once per (segment, field) — the eager
        # impact hot path (search/impactpath.py) never does.
        v2 = (getattr(seg, "codec_version", CODEC_V1) >= CODEC_V2
              and pb is not None and pb.impact is not None)
        layout = "impact" if v2 else "tf"
        if v2 and node.mode != "filter":
            seg.ensure_device_tfs(node.field)
        w = np.zeros(T_pad, dtype=np.float32)
        w[: len(node.terms)] = node.weights
        a = np.zeros(T_pad, dtype=np.float32)
        a[: len(node.terms)] = node.aux
        _p(params, f"q{nid}_rows", rows)
        _p(params, f"q{nid}_w", w)
        _p(params, f"q{nid}_aux", a)
        _scalar_f32(params, f"q{nid}_msm", node.msm)
        _scalar_f32(params, f"q{nid}_avgdl", ctx.avgdl(node.field))
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        sim = node.sim
        b_eff = sim.b if node.has_norms else 0.0
        return ("terms", nid, node.field, T_pad, bucket, sim.sim_id,
                float(sim.k1), float(b_eff), node.mode, layout)

    if isinstance(node, LSourcePhrase):
        pb = seg.postings.get(node.field)
        if pb is None:
            return ("match_none", nid)
        rows = [pb.row(t) for t in node.terms]
        if any(r < 0 for r in rows):
            return ("match_none", nid)
        cand = None
        for r in rows:
            a, b = pb.row_slice(r)
            d = pb.doc_ids[a:b]
            cand = d if cand is None else np.intersect1d(
                cand, d, assume_unique=True)
            if len(cand) == 0:
                break
        ft = ctx.mappings.resolve_field(node.field)
        analyzer = ctx.mappings.index_analyzer(ft) if ft is not None else None
        docs = [int(d) for d in (cand if cand is not None else ())
                if _source_phrase_match(seg, int(d), node.field, node.terms,
                                        node.slop, analyzer)]
        pad = next_pow2(max(len(docs), 1), floor=8)
        arr = np.full(pad, INT32_SENTINEL, dtype=np.int32)
        arr[: len(docs)] = np.asarray(docs, np.int32)
        _p(params, f"q{nid}_docs", arr)
        _scalar_f32(params, f"q{nid}_boost", node.weight)
        return ("ids", nid, pad)

    if isinstance(node, LPhrase):
        pb = seg.postings.get(node.field)
        if pb is None or pb.pos_starts is None:
            return ("match_none", nid)
        m_terms = len(node.terms)
        last = m_terms - 1
        arrays = []
        term_rows = []
        for i, t in enumerate(node.terms):
            if node.prefix_last and i == last:
                rows = list(_prefix_rows(pb, t, node.max_expansions))
            else:
                r = pb.row(t)
                rows = [r] if r >= 0 else []
            if not rows:
                return ("match_none", nid)  # phrase needs every term
            arrays.append(_phrase_pairs(seg, pb, tuple(rows)))
            term_rows.append(tuple(rows))
        buckets = []
        # pair arrays are RAW and DEVICE-RESIDENT per (segment, term,
        # bucket): the query position rides as a scalar shift, so repeated
        # phrase queries never re-upload megabytes of positions (the
        # positional analog of the resident CSR postings)
        dev_cache = seg.__dict__.setdefault("_phrase_dev_cache", {})
        for i, (d, p) in enumerate(arrays):
            # coarse pow4 buckets: pair-array pads land on 1 of ~6 sizes so
            # phrase programs compile once per coarse shape, not per df
            bucket = next_pow2(max(len(d), 1), floor=64)
            if bucket.bit_length() % 2 == 0:   # odd exponent -> round up
                bucket <<= 1
            ck = (node.field, term_rows[i], bucket)
            dev = dev_cache.get(ck)
            if dev is None:
                import jax

                from ..obs.hbm_ledger import LEDGER
                d_dev = jax.device_put(_pad_to_sentinel(d, bucket))
                p_dev = jax.device_put(_pad_to_sentinel(p, bucket))
                alloc = LEDGER.register(
                    "phrase_pairs", int(d_dev.nbytes + p_dev.nbytes),
                    owner=seg, segment=seg,
                    label=f"phrase-pairs[{seg.name}][{node.field}]")
                dev = (d_dev, p_dev, alloc)
                while len(dev_cache) >= 1024:
                    evicted = dev_cache.pop(next(iter(dev_cache)))
                    LEDGER.release(evicted[2])
                dev_cache[ck] = dev
            _p(params, f"q{nid}_d{i}", dev[0])
            _p(params, f"q{nid}_p{i}", dev[1])
            _scalar_i32(params, f"q{nid}_shift{i}", i)
            buckets.append(bucket)
        sim = node.sim
        b_eff = sim.b if node.has_norms else 0.0
        _scalar_f32(params, f"q{nid}_w", node.weight)
        _scalar_f32(params, f"q{nid}_slop", node.slop)
        _scalar_f32(params, f"q{nid}_avgdl", ctx.avgdl(node.field))
        return ("phrase", nid, node.field, m_terms, tuple(buckets),
                float(sim.k1), float(b_eff), node.ordered, node.gap_cost)

    if isinstance(node, LExpandTerms):
        rows_np = node.expander(seg)
        pb = seg.postings.get(node.field)
        total = 0
        if pb is not None and len(rows_np):
            lens = pb.starts[rows_np + 1] - pb.starts[rows_np]
            total = int(lens.sum())
        T_pad = next_pow2(max(len(rows_np), 1), floor=1)
        rows = np.full(T_pad, -1, dtype=np.int32)
        rows[: len(rows_np)] = rows_np
        bucket = ops.pick_bucket(total)
        _p(params, f"q{nid}_rows", rows)
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        layout = ("impact" if getattr(seg, "codec_version",
                                      CODEC_V1) >= CODEC_V2
                  and pb is not None and pb.impact is not None else "tf")
        return ("xterms", nid, node.field, T_pad, bucket, layout)

    if isinstance(node, LMatchAll):
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        return ("match_all", nid)

    if isinstance(node, LMatchNone):
        return ("match_none", nid)

    if isinstance(node, LRange):
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        if node.kind == "int":
            _i64_bounds(params, nid, node.lo, node.hi)
        else:
            _scalar_f32(params, f"q{nid}_flo",
                        -np.inf if node.lo is None else node.lo)
            _scalar_f32(params, f"q{nid}_fhi",
                        np.inf if node.hi is None else node.hi)
        return ("range", nid, node.field, node.kind, node.include_lo, node.include_hi,
                node.field in seg.numeric_cols)

    if isinstance(node, LExists):
        src = ("numeric" if node.field in seg.numeric_cols else
               "keyword" if node.field in seg.keyword_cols else
               "geo" if node.field in seg.geo_cols else
               "dl" if node.field in seg.doc_lens else
               "none")
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        return ("exists", nid, node.field, src)

    if isinstance(node, LIds):
        docs = [seg.id2doc[i] for i in node.ids if i in seg.id2doc]
        pad = next_pow2(max(len(docs), 1), floor=8)
        arr = np.full(pad, INT32_SENTINEL, dtype=np.int32)
        arr[: len(docs)] = docs
        _p(params, f"q{nid}_docs", arr)
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        return ("ids", nid, pad)

    if isinstance(node, LBool):
        _scalar_f32(params, f"q{nid}_msm", node.msm)
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        return ("bool", nid,
                tuple(prepare(c, seg, ctx, params) for c in node.musts),
                tuple(prepare(c, seg, ctx, params) for c in node.shoulds),
                tuple(prepare(c, seg, ctx, params) for c in node.must_nots),
                tuple(_prepare_cached_filter(c, seg, ctx, params)
                      for c in node.filters))

    if isinstance(node, LConstScore):
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        return ("const", nid, prepare(node.child, seg, ctx, params))

    if isinstance(node, LDisMax):
        _scalar_f32(params, f"q{nid}_tie", node.tie_breaker)
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        return ("dismax", nid, tuple(prepare(c, seg, ctx, params) for c in node.children))

    if isinstance(node, LBoosting):
        _scalar_f32(params, f"q{nid}_nb", node.negative_boost)
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        return ("boosting", nid, prepare(node.positive, seg, ctx, params),
                prepare(node.negative, seg, ctx, params))

    if isinstance(node, LFuncScore):
        child_spec = prepare(node.child, seg, ctx, params)
        fn_specs = []
        for i, (fn, filt) in enumerate(zip(node.functions, node.fn_filters)):
            fspec = prepare(filt, seg, ctx, params) if filt is not None else None
            _scalar_f32(params, f"q{nid}_fn{i}_w", fn.weight)
            if fn.kind == "field_value_factor":
                _scalar_f32(params, f"q{nid}_fn{i}_factor", fn.factor)
                _scalar_f32(params, f"q{nid}_fn{i}_missing",
                            fn.missing if fn.missing is not None else 1.0)
                fn_specs.append(("fvf", i, fn.field, fn.modifier,
                                 fn.field in seg.numeric_cols, fspec))
            elif fn.kind == "random_score":
                _scalar_i32(params, f"q{nid}_fn{i}_seed", fn.seed)
                fn_specs.append(("random", i, fspec))
            elif fn.kind == "script_score":
                ast = pl.parse(fn.script or "")
                field_srcs, pkeys = _prepare_script(ast, fn.script_params or {},
                                                    seg, params, nid, f"fn{i}s")
                fn_specs.append(("fnscript", i, ast, field_srcs, pkeys, fspec))
            elif fn.kind == "decay":
                fn_specs.append(_prepare_decay(fn, i, nid, seg, ctx, params,
                                               fspec))
            else:
                fn_specs.append(("weight", i, fspec))
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        _scalar_f32(params, f"q{nid}_minscore",
                    node.min_score if node.min_score is not None else -3.4e38)
        return ("fnscore", nid, child_spec, tuple(fn_specs),
                node.score_mode, node.boost_mode)

    if isinstance(node, LNested):
        blk = seg.nested.get(node.path)
        if blk is None or blk.child.ndocs == 0:
            return ("match_none", nid)
        child_spec = prepare(node.child, blk.child, node.child_ctx, params)
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        return ("nested", nid, node.path, node.score_mode, child_spec)

    if isinstance(node, LHasChild):
        if node.pre is None:
            need = {"cnt"}
            if node.score_mode in ("sum", "avg"):
                need.add("sum")
            elif node.score_mode in ("max", "min"):
                need.add(node.score_mode)
            node.pre = _join_prepass(node.child, node.join_index, tuple(sorted(need)), ctx)
        for k, v in node.pre.items():
            params[f"q{nid}_{k}"] = v
        pf_spec = prepare(node.parent_filter, seg, ctx, params)
        _scalar_i32(params, f"q{nid}_base", node.join_index.seg_base(seg))
        # at least one matching child is always required (reference semantics)
        _scalar_f32(params, f"q{nid}_minc", max(node.min_children, 1))
        _scalar_f32(params, f"q{nid}_maxc", min(node.max_children, 2**31 - 1))
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        return ("has_child", nid, node.score_mode, pf_spec)

    if isinstance(node, LHasParent):
        if node.pre is None:
            # parents occupy their own slot (base + doc): reuse the scatter
            # with identity slots — "cnt" is the match vector, "sum" the score
            node.pre = _join_prepass(node.child, node.join_index, ("cnt", "sum"),
                                     ctx, self_slots=True)
        params[f"q{nid}_match"] = node.pre["cnt"]
        params[f"q{nid}_score"] = node.pre["sum"]
        params[f"q{nid}_pslot"] = node.join_index.pslot(seg)
        cf_spec = prepare(node.child_filter, seg, ctx, params)
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        return ("has_parent", nid, node.use_score, cf_spec)

    if isinstance(node, LRankFeature):
        _scalar_f32(params, f"q{nid}_p1", node.p1)
        _scalar_f32(params, f"q{nid}_p2", node.p2)
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        if node.feature is None:
            return ("rank_feature_col", nid, node.field, node.fn, node.positive,
                    node.field in seg.numeric_cols)
        pb = seg.postings.get(node.field)
        if pb is not None and pb.impact is not None:
            # feature-impact field: rank_feature's monotone functions
            # need the exact f32 weights (see LSparseDot above)
            seg.ensure_device_tfs(node.field)
        row = pb.row(node.feature) if pb is not None else -1
        df = pb.doc_freq(node.feature) if pb is not None else 0
        _p(params, f"q{nid}_rows", np.asarray([row], np.int32))
        return ("rank_feature_post", nid, node.field, ops.pick_bucket(df, 16),
                node.fn, node.positive, pb is not None)

    if isinstance(node, LSparseDot):
        pb = seg.postings.get(node.field)
        if pb is None:
            return ("match_none", nid)
        if pb.impact is not None:
            # feature-impact field (index_impacts): the v2 device layout
            # ships the quantized plane without the f32 weight plane; the
            # generic sparse_dot program (bool-embedded neural_sparse,
            # mesh-attached nodes, dense escalation of the sparse impact
            # ladder) still scores from exact weights — promote lazily
            seg.ensure_device_tfs(node.field)
        T_pad = next_pow2(len(node.tokens), floor=8)
        rows = np.full(T_pad, -1, np.int32)
        rows[: len(node.tokens)] = [pb.row(t) for t in node.tokens]
        _p(params, f"q{nid}_rows", rows)
        w = np.zeros(T_pad, np.float32)
        w[: len(node.tokens)] = node.weights
        _p(params, f"q{nid}_w", w)
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        total = sum(pb.doc_freq(t) for t in node.tokens)
        return ("sparse_dot", nid, node.field, T_pad, ops.pick_bucket(total))

    if isinstance(node, LDistanceFeature):
        _scalar_f32(params, f"q{nid}_pivot", node.pivot)
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        if node.kind == "date":
            hi, lo = split_i64(np.asarray([node.origin], np.int64))
            _scalar_i32(params, f"q{nid}_ohi", int(hi[0]))
            _scalar_i32(params, f"q{nid}_olo", int(lo[0]))
            return ("distfeat_date", nid, node.field,
                    node.field in seg.numeric_cols)
        _scalar_f32(params, f"q{nid}_lat", node.origin[0])
        _scalar_f32(params, f"q{nid}_lon", node.origin[1])
        return ("distfeat_geo", nid, node.field, node.field in seg.geo_cols)

    if isinstance(node, LPercolate):
        from .percolate import segment_mask

        _p(params, f"q{nid}_mask",
           segment_mask(node.field, node.mini_seg, node.mini_ctx, seg))
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        return ("percolate", nid)

    if isinstance(node, LScriptFilter):
        field_srcs, pkeys = _prepare_script(node.ast, node.params, seg, params,
                                            nid, "s")
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        return ("script", nid, node.ast, field_srcs, pkeys)

    if isinstance(node, LScriptScore):
        child_spec = prepare(node.child, seg, ctx, params)
        field_srcs, pkeys = _prepare_script(node.ast, node.params, seg, params,
                                            nid, "s")
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        _scalar_f32(params, f"q{nid}_minscore",
                    node.min_score if node.min_score is not None else F32_MIN)
        return ("scriptscore", nid, child_spec, node.ast, field_srcs, pkeys)

    if isinstance(node, LKnn):
        col_exists = node.field in seg.vector_cols
        if col_exists:
            dims = seg.vector_cols[node.field].values.shape[1]
            dpad = ((dims + 127) // 128) * 128
            v = np.zeros(dpad, np.float32)
            v[:dims] = node.vector[:dims]
            _p(params, f"q{nid}_vec", v)
            _scalar_f32(params, f"q{nid}_qsq", float(np.dot(node.vector, node.vector)))
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        fspec = prepare(node.filter, seg, ctx, params) if node.filter else None
        # ANN route: mapping opted into IVF and the query didn't force
        # exact -> static nprobe (jit-key) clamped to this segment's nlist.
        # Building here (host, once, cached on the column) keeps emit pure.
        ann_nprobe = None
        if col_exists and not node.exact:
            ivf = seg.vector_cols[node.field].ivf()
            if ivf is not None:
                ann_nprobe = int(min(node.nprobe or ivf.default_nprobe,
                                     ivf.nlist))
        return ("knn", nid, node.field, col_exists, node.similarity, fspec,
                ann_nprobe)

    if isinstance(node, LTermsSet):
        child_spec = prepare(node.child, seg, ctx, params)
        msm = np.full(seg.ndocs_pad, np.inf, np.float32)  # missing -> no hit
        if node.msm_field is not None:
            col = seg.numeric_cols.get(node.msm_field)
            if col is not None:
                msm[: seg.ndocs][col.present] = \
                    col.values[col.present].astype(np.float32)
        else:
            src, prm = node.script
            ast = pl.parse(src)
            variables = {"params": {**prm, "num_terms": node.num_terms}}
            flds = pl.referenced_doc_fields(ast)
            if not flds:
                # constant script ("params.num_terms - 1"): evaluate once
                msm[:] = float(pl.execute(ast, variables))
            else:
                for d in range(seg.ndocs):
                    dv = {f: pl.doc_view_for(seg, d, f) for f in flds}
                    msm[d] = float(pl.execute(ast, {**variables, "doc": dv}))
        _p(params, f"q{nid}_ts_msm", msm)
        return ("terms_set", nid, child_spec)

    if isinstance(node, LPinned):
        organic_spec = (prepare(node.organic, seg, ctx, params)
                        if node.organic is not None else None)
        docs = []
        ranks = []
        for rank, i in enumerate(node.ids):
            d = seg.id2doc.get(i)
            if d is not None:
                docs.append(d)
                ranks.append(rank)
        pad = next_pow2(max(len(docs), 1), floor=8)
        darr = np.full(pad, INT32_SENTINEL, np.int32)
        rarr = np.zeros(pad, np.float32)
        darr[: len(docs)] = docs
        rarr[: len(ranks)] = ranks
        _p(params, f"q{nid}_pin_docs", darr)
        _p(params, f"q{nid}_pin_ranks", rarr)
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        return ("pinned", nid, organic_spec, pad)

    if isinstance(node, LCombined):
        T = len(node.terms)
        T_pad = next_pow2(T, floor=1)
        sim = ctx.sim_for(node.fields[0][0])
        idf = np.zeros(T_pad, np.float32)
        idf[:T] = node.idf          # computed once at rewrite time
        fspecs = []
        avgdl_c = 0.0
        for fi, (fname, w) in enumerate(node.fields):
            pb = seg.postings.get(fname)
            if pb is not None and pb.impact is not None:
                # BM25F needs raw tf BEFORE saturation: promote the tf
                # plane on codec-v2 segments (once per segment/field)
                seg.ensure_device_tfs(fname)
            rows = np.full(T_pad, -1, np.int32)
            total = 0
            if pb is not None:
                for i, t in enumerate(node.terms):
                    r = pb.row(t)
                    rows[i] = r
                    if r >= 0:
                        a, b2 = pb.row_slice(r)
                        total += b2 - a
            _p(params, f"q{nid}_cf_rows{fi}", rows)
            _scalar_f32(params, f"q{nid}_cf_w{fi}", w)
            fspecs.append((fname, ops.pick_bucket(total), pb is not None))
            avgdl_c += w * ctx.avgdl(fname)
        _p(params, f"q{nid}_cf_idf", idf)
        _scalar_f32(params, f"q{nid}_cf_avgdl", max(avgdl_c, 1e-6))
        _scalar_f32(params, f"q{nid}_cf_msm", node.msm)
        k1 = getattr(sim, "k1", 1.2)
        b_p = getattr(sim, "b", 0.75)
        return ("combined", nid, tuple(fspecs), T_pad, float(k1), float(b_p))

    if isinstance(node, LGeoDist):
        _scalar_f32(params, f"q{nid}_lat", node.lat)
        _scalar_f32(params, f"q{nid}_lon", node.lon)
        _scalar_f32(params, f"q{nid}_rad", node.radius_m)
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        return ("geodist", nid, node.field, node.field in seg.geo_cols,
                node.inclusive)

    if isinstance(node, LGeoBox):
        for k, v in (("top", node.top), ("left", node.left),
                     ("bottom", node.bottom), ("right", node.right)):
            _scalar_f32(params, f"q{nid}_{k}", v)
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        return ("geobox", nid, node.field, node.field in seg.geo_cols)

    if isinstance(node, LGeoPolygon):
        # closed ring, padded to a pow2 vertex bucket with copies of the
        # FIRST vertex: position n closes the ring and every pad edge after
        # it is v0->v0, degenerate, contributing zero ray crossings
        nv = len(node.lats) + 1
        vpad = next_pow2(max(nv, 2), floor=8)
        lats = np.full(vpad, node.lats[0], np.float32)
        lons = np.full(vpad, node.lons[0], np.float32)
        lats[: len(node.lats)] = node.lats
        lons[: len(node.lons)] = node.lons
        _p(params, f"q{nid}_plat", lats)
        _p(params, f"q{nid}_plon", lons)
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        return ("geopoly", nid, node.field, node.field in seg.geo_cols, vpad)

    if isinstance(node, LGeoShape):
        from . import geo as G
        mask = np.zeros(seg.ndocs_pad, bool)
        col = seg.shape_cols.get(node.field)
        if col is not None:
            if node.relation == "disjoint":
                # disjoint = present & !intersects: bbox survivors need the
                # exact test; non-overlapping bboxes are disjoint for free
                cands = np.nonzero(col.bbox_candidates(node.shape.bbox))[0]
                mask[: seg.ndocs][col.present] = True
                for d in cands:
                    if G.intersects(col.shape(int(d)), node.shape):
                        mask[d] = False
            else:
                cands = np.nonzero(col.bbox_candidates(node.shape.bbox))[0]
                for d in cands:
                    if G.relation_matches(col.shape(int(d)), node.shape,
                                          node.relation):
                        mask[d] = True
        elif node.field in seg.geo_cols:
            # geo_point docs are point shapes: fully vectorized
            gc = seg.geo_cols[node.field]
            pts = np.stack([gc.lon.astype(np.float64),
                            gc.lat.astype(np.float64)], axis=1)
            if node.relation in ("intersects", "within"):
                m = G.points_in_shape(pts, node.shape) | \
                    G._points_on_edges(pts, node.shape)
                mask[: seg.ndocs] = m & gc.present
            elif node.relation == "disjoint":
                m = G.points_in_shape(pts, node.shape) | \
                    G._points_on_edges(pts, node.shape)
                mask[: seg.ndocs] = (~m) & gc.present
            else:  # contains: a point only contains a point query at the
                # same location
                if len(node.shape.points) == 1 and not node.shape.polys \
                        and not node.shape.lines:
                    qx, qy = node.shape.points[0]
                    mask[: seg.ndocs] = ((gc.lon == np.float32(qx))
                                         & (gc.lat == np.float32(qy))
                                         & gc.present)
        _p(params, f"q{nid}_shapemask", mask)
        _scalar_f32(params, f"q{nid}_boost", node.boost)
        return ("geoshape", nid)

    if isinstance(node, LSpanHost):
        from . import spans as SP
        freq = node._freqs.get(seg.uid)
        if freq is None:
            if isinstance(node.query, tuple):
                s, _ts = SP.eval_interval_rule(node.query[2], node.query[1],
                                               seg, ctx)
            else:
                _f, s, _ts = SP.eval_span_query(node.query, seg, ctx)
            freq = SP.freq_vector(s, seg.ndocs_pad)
            node._freqs[seg.uid] = freq
        if not freq.any():
            return ("match_none", nid)
        _p(params, f"q{nid}_freq", freq)
        _scalar_f32(params, f"q{nid}_w", node.weight)
        _scalar_f32(params, f"q{nid}_avgdl", ctx.avgdl(node.field))
        sim = node.sim
        b_eff = sim.b if node.has_norms else 0.0
        return ("span_host", nid, node.field, float(sim.k1), float(b_eff))

    raise TypeError(f"cannot prepare node {type(node).__name__}")


def parse_distance_m(s) -> float:
    """'10km' / '500m' / plain number (meters) -> meters (reference
    `common/unit/DistanceUnit.java`); shares query_dsl's unit table."""
    try:
        return dsl._parse_distance(s)
    except (ValueError, TypeError):
        raise dsl.QueryParseError(f"invalid distance [{s}]")


def _parse_time_ms(s) -> float:
    """'10d' / '3h' / number (ms) -> milliseconds (decay scale/offset);
    extends parse_interval_ms with fractional amounts and weeks."""
    if isinstance(s, (int, float)):
        return float(s)
    mm = re.fullmatch(r"\s*([\d.]+)\s*(ms|s|m|h|d|w)\s*", str(s))
    if not mm:
        raise dsl.QueryParseError(f"invalid time value [{s}]")
    mult = {"ms": 1, "w": 7 * 86_400_000}.get(mm.group(2)) or \
        _FIXED_MS[mm.group(2)]
    return float(mm.group(1)) * mult


def _prepare_decay(fn, i: int, nid: int, seg: Segment, ctx: ShardContext,
                   params: dict, fspec):
    """Host-side resolution of a gauss/exp/linear decay function: parse
    origin/scale/offset per field family and bake the shape constant so the
    device evaluates one exp()/mul per doc (reference
    `functionscore/DecayFunctionBuilder.java`). Missing values decay to 1."""
    import math as _math
    import time as _time

    from ..index.mappings import _parse_date

    field = ctx.mappings.aliases.get(fn.field, fn.field)
    ft = ctx.mappings.resolve_field(field)
    ftype = ft.type if ft is not None else "float"
    shape = fn.decay_shape
    try:
        if field in seg.geo_cols or ftype == "geo_point":
            kind = "geo"
            if fn.origin is None:
                raise dsl.QueryParseError("[decay] geo requires [origin]")
            lat, lon = dsl._parse_point(fn.origin)
            scale = parse_distance_m(fn.scale)
            offset = parse_distance_m(fn.offset or 0)
            _scalar_f32(params, f"q{nid}_fn{i}_olat", lat)
            _scalar_f32(params, f"q{nid}_fn{i}_olon", lon)
        elif ftype == "date":
            kind = "num"
            origin = (float(_time.time() * 1000)
                      if fn.origin in (None, "now")
                      else float(_parse_date(fn.origin, ft.date_format
                                             if ft is not None else None)))
            scale = _parse_time_ms(fn.scale)
            offset = _parse_time_ms(fn.offset or 0)
            _scalar_f32(params, f"q{nid}_fn{i}_origin", origin)
        else:
            kind = "num"
            if fn.origin is None:
                raise dsl.QueryParseError("[decay] numeric requires [origin]")
            scale = float(fn.scale)
            offset = float(fn.offset or 0)
            _scalar_f32(params, f"q{nid}_fn{i}_origin", float(fn.origin))
    except (ValueError, TypeError, KeyError) as e:
        # malformed origin/scale/offset is a client error (HTTP 400)
        raise dsl.QueryParseError(f"[{shape}] decay on [{field}]: {e}")
    if scale <= 0:
        raise dsl.QueryParseError("[decay] scale must be > 0")
    decay = min(max(float(fn.decay), 1e-12), 1.0 - 1e-12)
    if shape == "gauss":
        a = _math.log(decay) / (scale * scale)     # factor = exp(a * d^2)
    elif shape == "exp":
        a = _math.log(decay) / scale               # factor = exp(a * d)
    else:                                          # linear
        a = scale / (1.0 - decay)                  # factor = max(0, (a-d)/a)
    _scalar_f32(params, f"q{nid}_fn{i}_a", a)
    _scalar_f32(params, f"q{nid}_fn{i}_offset", offset)
    col_map = seg.geo_cols if kind == "geo" else seg.numeric_cols
    return ("decay", i, shape, kind, field, field in col_map, fspec)


@_instrumented_program_cache("join", maxsize=64)
def _build_join_scatter(gsize: int, need: Tuple[str, ...]):
    """Pass-1 kernel: scatter one segment's matched scores into the shard's
    join slot space (padding/unresolved slots are -1 -> sentinel -> dropped)."""
    import jax

    def run(gslot, scores, matched):
        import jax.numpy as jnp

        ok = (gslot >= 0) & (matched > 0)
        idx = jnp.where(ok, gslot, INT32_SENTINEL)
        sc = jnp.where(ok, scores, 0.0)
        out = {}
        if "cnt" in need:
            out["cnt"] = jnp.zeros(gsize, jnp.float32).at[idx].add(
                ok.astype(jnp.float32), mode="drop")
        if "sum" in need:
            out["sum"] = jnp.zeros(gsize, jnp.float32).at[idx].add(sc, mode="drop")
        if "max" in need:
            out["max"] = jnp.full(gsize, -3.4e38, jnp.float32).at[idx].max(
                jnp.where(ok, scores, -3.4e38), mode="drop")
        if "min" in need:
            out["min"] = jnp.full(gsize, 3.4e38, jnp.float32).at[idx].min(
                jnp.where(ok, scores, 3.4e38), mode="drop")
        return out

    return jax.jit(run)


def _join_prepass(child: LNode, ji, need: Tuple[str, ...], ctx: ShardContext,
                  self_slots: bool = False) -> dict:
    """Run the inner plan densely over every segment of the join index and
    accumulate slot-space vectors on device (no host round trip — the result
    arrays feed pass 2 as traced params)."""
    import jax.numpy as jnp

    acc: Dict[str, Any] = {}
    for seg in ji.segments:
        if seg.live_count == 0:
            continue
        cparams: Dict[str, Any] = {}
        cspec = prepare(child, seg, ctx, cparams)
        docs = np.arange(seg.ndocs_pad, dtype=np.int32)
        scores, matched = run_gather_scores(cspec, seg.device_arrays(), cparams, docs)
        if self_slots:
            base = ji.seg_base(seg)
            gslot = np.arange(base, base + seg.ndocs_pad, dtype=np.int32)
            gslot[seg.ndocs:] = -1
        else:
            gslot = ji.pslot(seg)
        vecs = _build_join_scatter(ji.gsize, need)(gslot, scores, matched)
        for k, v in vecs.items():
            if k not in acc:
                acc[k] = v
            elif k == "max":
                acc[k] = jnp.maximum(acc[k], v)
            elif k == "min":
                acc[k] = jnp.minimum(acc[k], v)
            else:
                acc[k] = acc[k] + v
    if not acc:
        fill = {"cnt": 0.0, "sum": 0.0, "max": -3.4e38, "min": 3.4e38}
        acc = {k: jnp.full(ji.gsize, fill[k], jnp.float32) for k in need}
    return acc


def _prepare_script(ast: tuple, script_params: dict, seg: Segment, params: dict,
                    nid: int, tag: str):
    """Bind a device script to one segment: resolve doc['f'] columns and
    trace numeric params (date epochs ride the f32 column view — ms-epoch
    precision ~2min at f32, fine for scoring)."""
    fields = pl.referenced_doc_fields(ast)
    field_srcs = tuple((f, "numeric" if f in seg.numeric_cols else "none")
                       for f in fields)
    pkeys = []
    for k in sorted(script_params):
        v = script_params[k]
        if isinstance(v, bool):
            v = float(v)
        if not isinstance(v, (int, float)):
            raise dsl.QueryParseError(
                f"script param [{k}] must be numeric in score/filter scripts")
        _scalar_f32(params, f"q{nid}_{tag}p_{k}", v)
        pkeys.append(k)
    return field_srcs, tuple(pkeys)


def _script_env(jnp, field_srcs, pkeys, nid: int, tag: str, seg_arrays: dict,
                params: dict, score, ndocs_pad: int) -> pl.DeviceEnv:
    cols: Dict[str, Any] = {}
    present: Dict[str, Any] = {}
    for f, src in field_srcs:
        if src == "numeric":
            cols[f] = seg_arrays["numeric"][f]["f32"]
            present[f] = seg_arrays["numeric"][f]["present"]
    sparams = {k: params[f"q{nid}_{tag}p_{k}"] for k in pkeys}
    return pl.DeviceEnv(jnp, cols, present, score, sparams, ndocs_pad)


def describe_plan(node: Optional[LNode]) -> dict:
    """Logical-plan tree for the profile API (reference search/profile/
    ProfileResult): type + human description + children. Times live on the
    root only — the whole tree executes as ONE fused XLA program."""
    if node is None:
        return {"type": "MatchAll", "description": "*:*"}
    t = type(node).__name__.lstrip("L")
    desc = ""
    if isinstance(node, LTerms):
        desc = f"{node.field}:{list(node.terms)[:8]}"
    elif isinstance(node, LPhrase):
        desc = f"{node.field}:\"{' '.join(node.terms)}\""
    elif isinstance(node, (LRange,)):
        desc = f"{node.field}:[{node.lo} TO {node.hi}]"
    elif hasattr(node, "field") and getattr(node, "field", ""):
        desc = str(getattr(node, "field"))
    children = []
    for attr in ("musts", "shoulds", "must_nots", "filters", "children"):
        for c in getattr(node, attr, ()) or ():
            children.append(describe_plan(c))
    for attr in ("child", "positive", "negative", "filter", "organic"):
        c = getattr(node, attr, None)
        if isinstance(c, LNode):
            children.append(describe_plan(c))
    out = {"type": t, "description": desc, "time_in_nanos": 0,
           "fused": True}
    if children:
        out["children"] = children
    return out


def can_match(node: LNode, seg: Segment) -> bool:
    """Shard/segment pre-filter (reference CanMatchPreFilterSearchPhase):
    cheaply prove a segment has zero hits."""
    if isinstance(node, LTerms):
        pb = seg.postings.get(node.field)
        if pb is None:
            return False
        if node.msm >= len(node.terms):
            return all(pb.row(t) >= 0 for t in node.terms)
        return any(pb.row(t) >= 0 for t in node.terms)
    if isinstance(node, LPhrase):
        pb = seg.postings.get(node.field)
        if pb is None or pb.pos_starts is None:
            return False
        last = len(node.terms) - 1
        for i, t in enumerate(node.terms):
            if node.prefix_last and i == last:
                if not _prefix_rows(pb, t, node.max_expansions):
                    return False
            elif pb.row(t) < 0:
                return False
        return True
    if isinstance(node, LRange):
        col = seg.numeric_cols.get(node.field)
        if col is None:
            return False
        mn, mx = col.min_max
        if node.lo is not None and float(node.lo) > mx:
            return False
        if node.hi is not None and float(node.hi) < mn:
            return False
        return True
    if isinstance(node, LBool):
        for c in node.musts + node.filters:
            if not can_match(c, seg):
                return False
        if node.shoulds and not node.musts and not node.filters:
            return any(can_match(c, seg) for c in node.shoulds)
        return True
    if isinstance(node, LConstScore):
        return can_match(node.child, seg)
    if isinstance(node, LNested):
        blk = seg.nested.get(node.path)
        if blk is None or blk.child.ndocs == 0:
            return False
        return can_match(node.child, blk.child)
    if isinstance(node, LPercolate):
        return (f"{node.field}#terms" in seg.keyword_cols
                or f"{node.field}#flags" in seg.keyword_cols)
    if isinstance(node, LHasChild):
        # pass 2 only reads parent docs of this segment; the child pre-pass
        # spans all segments regardless
        return can_match(node.parent_filter, seg)
    if isinstance(node, LHasParent):
        return can_match(node.child_filter, seg)
    if isinstance(node, LMatchNone):
        return False
    if isinstance(node, LExists):
        f = node.field
        return (f in seg.postings or f in seg.numeric_cols
                or f in seg.keyword_cols or f in seg.geo_cols
                or f in seg.vector_cols or f in seg.shape_cols
                or f in seg.doc_lens)
    if isinstance(node, LIds):
        return any(i in seg.id2doc for i in node.ids)
    if isinstance(node, LKnn):
        return node.field in seg.vector_cols
    if isinstance(node, (LGeoDist, LGeoBox, LGeoPolygon)):
        return node.field in seg.geo_cols
    if isinstance(node, LGeoShape):
        return (node.field in seg.shape_cols or node.field in seg.geo_cols)
    if isinstance(node, LDisMax):
        return any(can_match(c, seg) for c in node.children)
    if isinstance(node, LBoosting):
        return node.positive is None or can_match(node.positive, seg)
    if isinstance(node, LFuncScore):
        return node.child is None or can_match(node.child, seg)
    if isinstance(node, LTermsSet):
        return node.child is None or can_match(node.child, seg)
    if isinstance(node, LCombined):
        return any(seg.postings.get(f) is not None
                   and seg.postings[f].row(t) >= 0
                   for f, _w in node.fields for t in node.terms)
    if isinstance(node, (LRankFeature, LSparseDot)):
        # feature CSRs live in seg.postings; rank_feature on a numeric
        # column falls back to numeric_cols
        return node.field in seg.postings or node.field in seg.numeric_cols
    return True


# =====================================================================
# emit: spec -> traced device computation (runs under jit trace)
# =====================================================================

def _emit_seg_helpers(seg_arrays: dict):
    import jax.numpy as jnp

    ndocs_pad = seg_arrays["live"].shape[0]
    live = seg_arrays["live"]
    zeros = jnp.zeros(ndocs_pad, jnp.float32)
    return jnp, ndocs_pad, live, zeros


def emit(spec, seg_arrays: dict, params: dict) -> ops.ScoredMask:  # noqa: C901
    import jax.numpy as jnp

    kind = spec[0]
    nid = spec[1]
    ndocs_pad = seg_arrays["live"].shape[0]
    live = seg_arrays["live"]
    zeros = jnp.zeros(ndocs_pad, jnp.float32)

    if kind == "terms":
        _, _, field, T_pad, bucket, sim_id, k1, b, mode, layout = spec
        post = seg_arrays["postings"].get(field)
        if post is None:
            return ops.ScoredMask(zeros, zeros)
        dl = seg_arrays["doc_lens"].get(field, zeros)
        if mode == "filter":
            # codec-v2 layout: no resident tf plane — the tf-free gather
            # moves half the bytes for identical mask semantics
            if layout == "impact":
                mask = ops.term_match_mask(post, live,
                                           params[f"q{nid}_rows"], bucket,
                                           ndocs_pad)
            else:
                mask = ops.term_filter_mask(post, live, params[f"q{nid}_rows"], bucket, ndocs_pad)
            boost = params[f"q{nid}_boost"]
            m = mask.astype(jnp.float32)
            return ops.ScoredMask(m * boost, m)
        sm = ops.score_term_group(post, dl, live, params[f"q{nid}_rows"],
                                  params[f"q{nid}_w"], params[f"q{nid}_aux"],
                                  bucket, ndocs_pad, sim_id, k1, b,
                                  params[f"q{nid}_avgdl"])
        msm = params[f"q{nid}_msm"]
        ok = sm.count >= msm
        return ops.ScoredMask(jnp.where(ok, sm.scores, 0.0),
                              jnp.where(ok, sm.count, 0.0))

    if kind == "phrase":
        from ..ops import positions as pos_ops

        _, _, field, m_terms, buckets, k1, b, ordered, gap_cost = spec
        dl = seg_arrays["doc_lens"].get(field, zeros)
        anchor_d = params[f"q{nid}_d0"]
        anchor_p = params[f"q{nid}_p0"]
        others = [(params[f"q{nid}_d{i}"], params[f"q{nid}_p{i}"])
                  for i in range(1, m_terms)]
        shifts = [params[f"q{nid}_shift{i}"] for i in range(1, m_terms)]
        freq = pos_ops.phrase_freqs(anchor_d, anchor_p, others,
                                    params[f"q{nid}_slop"], ndocs_pad,
                                    ordered=ordered, gap_cost=gap_cost,
                                    shifts=shifts)
        scores, matched = pos_ops.phrase_score(freq, dl, live, params[f"q{nid}_w"],
                                               k1, b, params[f"q{nid}_avgdl"])
        return ops.ScoredMask(scores, matched.astype(jnp.float32))

    if kind == "cached_mask":
        m = params[f"q{nid}_cached_mask"]
        return ops.ScoredMask(zeros, m.astype(jnp.float32))

    if kind == "span_host":
        from ..ops import positions as pos_ops

        _, _, field, k1, b = spec
        dl = seg_arrays["doc_lens"].get(field, zeros)
        freq = params[f"q{nid}_freq"]
        scores, matched = pos_ops.phrase_score(freq, dl, live,
                                               params[f"q{nid}_w"], k1, b,
                                               params[f"q{nid}_avgdl"])
        return ops.ScoredMask(scores, matched.astype(jnp.float32))

    if kind == "xterms":
        _, _, field, T_pad, bucket, layout = spec
        post = seg_arrays["postings"].get(field)
        if post is None:
            return ops.ScoredMask(zeros, zeros)
        if layout == "impact":
            mask = ops.term_match_mask(post, live, params[f"q{nid}_rows"],
                                       bucket, ndocs_pad)
        else:
            mask = ops.term_filter_mask(post, live, params[f"q{nid}_rows"], bucket, ndocs_pad)
        m = mask.astype(jnp.float32)
        return ops.ScoredMask(m * params[f"q{nid}_boost"], m)

    if kind == "match_all":
        m = (live > 0).astype(jnp.float32)
        return ops.ScoredMask(m * params[f"q{nid}_boost"], m)

    if kind == "match_none":
        return ops.ScoredMask(zeros, zeros)

    if kind == "range":
        _, _, field, ckind, inc_lo, inc_hi, col_exists = spec
        if not col_exists:
            return ops.ScoredMask(zeros, zeros)
        col = seg_arrays["numeric"][field]
        if ckind == "int":
            mask = ops.int64_range_mask(col, params[f"q{nid}_lohi"], params[f"q{nid}_lolo"],
                                        params[f"q{nid}_hihi"], params[f"q{nid}_hilo"],
                                        inc_lo, inc_hi)
        else:
            mask = ops.float_range_mask(col, params[f"q{nid}_flo"], params[f"q{nid}_fhi"],
                                        inc_lo, inc_hi)
        mask = mask & (live > 0)
        m = mask.astype(jnp.float32)
        return ops.ScoredMask(m * params[f"q{nid}_boost"], m)

    if kind == "exists":
        _, _, field, src = spec
        if src == "numeric":
            present = seg_arrays["numeric"][field]["present"]
        elif src == "keyword":
            present = seg_arrays["keyword"][field]["min_ord"] >= 0
        elif src == "geo":
            present = seg_arrays["geo"][field]["present"]
        elif src == "dl":
            present = seg_arrays["doc_lens"][field] > 0
        else:
            return ops.ScoredMask(zeros, zeros)
        mask = present & (live > 0)
        m = mask.astype(jnp.float32)
        return ops.ScoredMask(m * params[f"q{nid}_boost"], m)

    if kind == "ids":
        mask = ops.docs_mask(params[f"q{nid}_docs"], ndocs_pad) & (live > 0)
        m = mask.astype(jnp.float32)
        return ops.ScoredMask(m * params[f"q{nid}_boost"], m)

    if kind == "bool":
        _, _, musts, shoulds, must_nots, filters = spec
        m_sms = [emit(s, seg_arrays, params) for s in musts]
        s_sms = [emit(s, seg_arrays, params) for s in shoulds]
        n_sms = [emit(s, seg_arrays, params) for s in must_nots]
        f_sms = [emit(s, seg_arrays, params) for s in filters]
        scores = zeros
        for sm in m_sms + s_sms:
            scores = scores + sm.scores
        matched = live > 0
        for sm in m_sms:
            matched = matched & sm.matched
        for sm in f_sms:
            matched = matched & sm.matched
        for sm in n_sms:
            matched = matched & (~sm.matched)
        if s_sms:
            s_count = zeros
            for sm in s_sms:
                s_count = s_count + sm.matched.astype(jnp.float32)
            matched = matched & (s_count >= params[f"q{nid}_msm"])
        scores = jnp.where(matched, scores * params[f"q{nid}_boost"], 0.0)
        return ops.ScoredMask(scores, matched.astype(jnp.float32))

    if kind == "const":
        sm = emit(spec[2], seg_arrays, params)
        m = sm.matched.astype(jnp.float32)
        return ops.ScoredMask(m * params[f"q{nid}_boost"], m)

    if kind == "dismax":
        children = [emit(s, seg_arrays, params) for s in spec[2]]
        tie = params[f"q{nid}_tie"]
        best = zeros
        total = zeros
        matched = jnp.zeros_like(live, dtype=bool)
        for sm in children:
            best = jnp.maximum(best, sm.scores)
            total = total + sm.scores
            matched = matched | sm.matched
        scores = best + tie * (total - best)
        scores = jnp.where(matched, scores * params[f"q{nid}_boost"], 0.0)
        return ops.ScoredMask(scores, matched.astype(jnp.float32))

    if kind == "boosting":
        pos = emit(spec[2], seg_arrays, params)
        neg = emit(spec[3], seg_arrays, params)
        nb = params[f"q{nid}_nb"]
        scores = pos.scores * jnp.where(neg.matched, nb, 1.0) * params[f"q{nid}_boost"]
        return ops.ScoredMask(jnp.where(pos.matched, scores, 0.0), pos.count)

    if kind == "fnscore":
        _, _, child_spec, fn_specs, score_mode, boost_mode = spec
        child = emit(child_spec, seg_arrays, params)
        factors = []
        for fs in fn_specs:
            fkind = fs[0]
            i = fs[1]
            if fkind == "fvf":
                _, _, ffield, modifier, col_exists, fspec = fs
                if col_exists:
                    col = seg_arrays["numeric"][ffield]
                    v = jnp.where(col["present"],
                                  col["f32"] * params[f"q{nid}_fn{i}_factor"],
                                  params[f"q{nid}_fn{i}_missing"])
                else:
                    v = jnp.full(ndocs_pad, params[f"q{nid}_fn{i}_missing"])
                v = _apply_modifier(jnp, v, modifier)
            elif fkind == "random":
                _, _, fspec = fs
                seed = params[f"q{nid}_fn{i}_seed"]
                h = (jnp.arange(ndocs_pad, dtype=jnp.uint32) * jnp.uint32(2654435761)
                     ^ seed.astype(jnp.uint32))
                h = h ^ (h >> 16)
                h = h * jnp.uint32(0x45D9F3B)
                h = h ^ (h >> 16)
                v = h.astype(jnp.float32) / jnp.float32(2**32)
            elif fkind == "fnscript":
                _, _, s_ast, s_fields, s_pkeys, fspec = fs
                env = _script_env(jnp, s_fields, s_pkeys, nid, f"fn{i}s",
                                  seg_arrays, params, child.scores, ndocs_pad)
                v = pl.eval_device(s_ast, env)
            elif fkind == "decay":
                _, _, shape, dk, dfield, col_exists, fspec = fs
                a = params[f"q{nid}_fn{i}_a"]
                off = params[f"q{nid}_fn{i}_offset"]
                if not col_exists:
                    v = jnp.ones(ndocs_pad, jnp.float32)
                    present = jnp.zeros(ndocs_pad, bool)
                elif dk == "geo":
                    g = seg_arrays["geo"][dfield]
                    r = 6371008.8
                    p1 = jnp.deg2rad(params[f"q{nid}_fn{i}_olat"])
                    p2 = jnp.deg2rad(g["lat"])
                    dphi = p2 - p1
                    dlmb = jnp.deg2rad(g["lon"] - params[f"q{nid}_fn{i}_olon"])
                    h = (jnp.sin(dphi / 2) ** 2
                         + jnp.cos(p1) * jnp.cos(p2) * jnp.sin(dlmb / 2) ** 2)
                    d = 2 * r * jnp.arcsin(jnp.sqrt(jnp.clip(h, 0.0, 1.0)))
                    present = g["present"]
                else:
                    col = seg_arrays["numeric"][dfield]
                    d = jnp.abs(col["f32"] - params[f"q{nid}_fn{i}_origin"])
                    present = col["present"]
                if col_exists:
                    d = jnp.maximum(d - off, 0.0)
                    if shape == "gauss":
                        v = jnp.exp(a * d * d)
                    elif shape == "exp":
                        v = jnp.exp(a * d)
                    else:  # linear
                        v = jnp.maximum((a - d) / a, 0.0)
                    # docs without a value don't decay (factor 1)
                    v = jnp.where(present, v, 1.0)
            else:  # weight
                _, _, fspec = fs
                v = jnp.ones(ndocs_pad, jnp.float32)
            v = v * params[f"q{nid}_fn{i}_w"]
            if fspec is not None:
                fmask = emit(fspec, seg_arrays, params).matched
                neutral = _score_mode_neutral(score_mode)
                v = jnp.where(fmask, v, neutral)
            factors.append(v)
        if factors:
            fac = _combine_factors(jnp, factors, score_mode, ndocs_pad)
        else:
            fac = jnp.ones(ndocs_pad, jnp.float32)
        scores = _combine_boost(jnp, child.scores, fac, boost_mode)
        scores = scores * params[f"q{nid}_boost"]
        matched = child.matched & (scores >= params[f"q{nid}_minscore"])
        scores = jnp.where(matched, scores, 0.0)
        return ops.ScoredMask(scores, matched.astype(jnp.float32))

    if kind == "nested":
        _, _, path, score_mode, child_spec = spec
        carr = dict(seg_arrays["nested"][path])
        parent = carr["parent"]
        # child liveness inherits the parent's delete mask via a gather
        carr["live"] = carr["live"] * live[parent]
        sm = emit(child_spec, carr, params)
        cmatch = sm.matched
        cscore = jnp.where(cmatch, sm.scores, 0.0)
        cnt = zeros.at[parent].add(cmatch.astype(jnp.float32))
        pmatch = cnt > 0
        if score_mode == "none":
            pscores = pmatch.astype(jnp.float32)
        elif score_mode == "max":
            neg_inf = jnp.full(ndocs_pad, -jnp.inf, jnp.float32)
            mx = neg_inf.at[parent].max(jnp.where(cmatch, sm.scores, -jnp.inf))
            pscores = jnp.where(pmatch, mx, 0.0)
        elif score_mode == "min":
            pos_inf = jnp.full(ndocs_pad, jnp.inf, jnp.float32)
            mn = pos_inf.at[parent].min(jnp.where(cmatch, sm.scores, jnp.inf))
            pscores = jnp.where(pmatch, mn, 0.0)
        else:
            total = zeros.at[parent].add(cscore)
            pscores = total / jnp.maximum(cnt, 1.0) if score_mode == "avg" else total
        pmatch = pmatch & (live > 0)
        pscores = jnp.where(pmatch, pscores * params[f"q{nid}_boost"], 0.0)
        return ops.ScoredMask(pscores, pmatch.astype(jnp.float32))

    if kind == "has_child":
        from jax import lax

        _, _, score_mode, pf_spec = spec
        base = params[f"q{nid}_base"]
        cnt = lax.dynamic_slice(params[f"q{nid}_cnt"], (base,), (ndocs_pad,))
        pmask = emit(pf_spec, seg_arrays, params).matched
        ok = ((cnt >= params[f"q{nid}_minc"]) & (cnt <= params[f"q{nid}_maxc"])
              & (pmask > 0) & (live > 0))
        if score_mode == "none":
            sc = jnp.ones(ndocs_pad, jnp.float32)
        elif score_mode in ("sum", "avg"):
            sc = lax.dynamic_slice(params[f"q{nid}_sum"], (base,), (ndocs_pad,))
            if score_mode == "avg":
                sc = sc / jnp.maximum(cnt, 1.0)
        else:  # max | min
            sc = lax.dynamic_slice(params[f"q{nid}_{score_mode}"], (base,),
                                   (ndocs_pad,))
        sc = jnp.where(ok, sc * params[f"q{nid}_boost"], 0.0)
        return ops.ScoredMask(sc, ok.astype(jnp.float32))

    if kind == "has_parent":
        _, _, use_score, cf_spec = spec
        pslot = params[f"q{nid}_pslot"]
        gmatch = params[f"q{nid}_match"]
        gscore = params[f"q{nid}_score"]
        valid = pslot >= 0
        idx = jnp.clip(pslot, 0, gmatch.shape[0] - 1)
        cmask = emit(cf_spec, seg_arrays, params).matched
        ok = valid & (gmatch[idx] > 0) & (cmask > 0) & (live > 0)
        sc = gscore[idx] if use_score else jnp.ones(ndocs_pad, jnp.float32)
        sc = jnp.where(ok, sc * params[f"q{nid}_boost"], 0.0)
        return ops.ScoredMask(sc, ok.astype(jnp.float32))

    if kind == "rank_feature_post":
        _, _, field, bucket, fn, positive, pb_exists = spec
        post = seg_arrays["postings"].get(field)
        if not pb_exists or post is None:
            return ops.ScoredMask(zeros, zeros)
        p1, p2 = params[f"q{nid}_p1"], params[f"q{nid}_p2"]
        sm = ops.feature_score(
            post, live, params[f"q{nid}_rows"], bucket, ndocs_pad,
            lambda w, ti: ops.rank_feature_value(w, fn, p1, p2, positive))
        return ops.ScoredMask(sm.scores * params[f"q{nid}_boost"], sm.count)

    if kind == "rank_feature_col":
        _, _, field, fn, positive, col_exists = spec
        if not col_exists:
            return ops.ScoredMask(zeros, zeros)
        col = seg_arrays["numeric"][field]
        v = ops.rank_feature_value(col["f32"], fn, params[f"q{nid}_p1"],
                                   params[f"q{nid}_p2"], positive)
        mask = col["present"] & (live > 0)
        return ops.ScoredMask(jnp.where(mask, v * params[f"q{nid}_boost"], 0.0),
                              mask.astype(jnp.float32))

    if kind == "sparse_dot":
        _, _, field, T_pad, bucket = spec
        post = seg_arrays["postings"].get(field)
        if post is None:
            return ops.ScoredMask(zeros, zeros)
        qw = params[f"q{nid}_w"]
        sm = ops.feature_score(post, live, params[f"q{nid}_rows"], bucket,
                               ndocs_pad, lambda w, ti: qw[ti] * w)
        return ops.ScoredMask(sm.scores * params[f"q{nid}_boost"], sm.count)

    if kind == "distfeat_date":
        _, _, field, col_exists = spec
        if not col_exists:
            return ops.ScoredMask(zeros, zeros)
        col = seg_arrays["numeric"][field]
        dhi = (col["hi"] - params[f"q{nid}_ohi"]).astype(jnp.float32)
        dlo = col["lo"].astype(jnp.float32) - jnp.float32(params[f"q{nid}_olo"])
        dist = jnp.abs(dhi * 4294967296.0 + dlo)
        pivot = params[f"q{nid}_pivot"]
        mask = col["present"] & (live > 0)
        sc = params[f"q{nid}_boost"] * pivot / (pivot + dist)
        return ops.ScoredMask(jnp.where(mask, sc, 0.0), mask.astype(jnp.float32))

    if kind == "distfeat_geo":
        _, _, field, col_exists = spec
        if not col_exists:
            return ops.ScoredMask(zeros, zeros)
        geo = seg_arrays["geo"][field]
        r = 6371008.8
        p1r = jnp.deg2rad(geo["lat"])
        p2r = jnp.deg2rad(params[f"q{nid}_lat"])
        dphi = p2r - p1r
        dlmb = jnp.deg2rad(params[f"q{nid}_lon"] - geo["lon"])
        a = jnp.sin(dphi / 2) ** 2 + jnp.cos(p1r) * jnp.cos(p2r) * jnp.sin(dlmb / 2) ** 2
        dist = 2 * r * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))
        pivot = params[f"q{nid}_pivot"]
        mask = geo["present"] & (live > 0)
        sc = params[f"q{nid}_boost"] * pivot / (pivot + dist)
        return ops.ScoredMask(jnp.where(mask, sc, 0.0), mask.astype(jnp.float32))

    if kind == "percolate":
        mask = (params[f"q{nid}_mask"] > 0) & (live > 0)
        m = mask.astype(jnp.float32)
        return ops.ScoredMask(m * params[f"q{nid}_boost"], m)

    if kind == "script":
        _, _, ast, field_srcs, pkeys = spec
        env = _script_env(jnp, field_srcs, pkeys, nid, "s", seg_arrays, params,
                          None, ndocs_pad)
        vec = pl.eval_device(ast, env)
        mask = (vec != 0) & (live > 0)
        m = mask.astype(jnp.float32)
        return ops.ScoredMask(m * params[f"q{nid}_boost"], m)

    if kind == "scriptscore":
        _, _, child_spec, ast, field_srcs, pkeys = spec
        child = emit(child_spec, seg_arrays, params)
        env = _script_env(jnp, field_srcs, pkeys, nid, "s", seg_arrays, params,
                          child.scores, ndocs_pad)
        scores = pl.eval_device(ast, env) * params[f"q{nid}_boost"]
        matched = child.matched & (scores >= params[f"q{nid}_minscore"])
        return ops.ScoredMask(jnp.where(matched, scores, 0.0),
                              matched.astype(jnp.float32))

    if kind == "knn":
        from jax import lax as _lax
        _, _, field, col_exists, simkind, fspec, ann_nprobe = spec
        if not col_exists:
            return ops.ScoredMask(zeros, zeros)
        vc = seg_arrays["vector"][field]
        qvec = params[f"q{nid}_vec"]

        def _sim_score(raw, vecs_sq):
            if simkind == "cosine":
                return (1.0 + raw) / 2.0
            if simkind in ("dot_product", "innerproduct"):
                return jnp.where(raw > 0, raw + 1.0, 1.0 / (1.0 - raw))
            d2 = jnp.maximum(vecs_sq + params[f"q{nid}_qsq"] - 2.0 * raw, 0.0)
            return 1.0 / (1.0 + d2)

        if ann_nprobe is not None and "ivf_centroids" in vc:
            # balanced-IVF probe (ops/ann.py): centroid matvec -> static
            # top-nprobe -> dense [nprobe, cap] list gather -> candidate
            # matvec -> scatter back into doc space. Everything static-shape;
            # candidate count = nprobe*cap regardless of data.
            cents, lists = vc["ivf_centroids"], vc["ivf_lists"]
            cdot = jnp.dot(cents, qvec, preferred_element_type=jnp.float32)
            if simkind in ("cosine", "dot_product", "innerproduct"):
                caff = cdot
            else:  # l2: nearest centroid = max of 2c.q - ||c||^2
                caff = 2.0 * cdot - jnp.sum(cents * cents, axis=1)
            caff = jnp.where(vc["ivf_cvalid"], caff, -jnp.inf)
            _, pids = _lax.top_k(caff, ann_nprobe)
            cand = lists[pids].reshape(-1)            # i32[nprobe*cap]
            valid = cand >= 0
            cidx = jnp.where(valid, cand, ndocs_pad)  # OOB -> dropped scatter
            vecs = vc["mat"][jnp.where(valid, cand, 0)]
            raw = jnp.dot(vecs, qvec, preferred_element_type=jnp.float32)
            s = _sim_score(raw, jnp.sum(vecs * vecs, axis=1))
            s = jnp.where(valid, s, 0.0)
            # each doc lives in exactly one list -> max==set, but max is
            # insensitive to the padding sentinel collisions
            score = zeros.at[cidx].max(s, mode="drop")
            cmask = zeros.at[cidx].max(valid.astype(jnp.float32), mode="drop")
            matched = (cmask > 0) & vc["present"] & (live > 0)
        else:
            # one MXU matvec per segment: exact brute-force kNN (the
            # reference k-NN plugin approximates with HNSW; at HBM bandwidth
            # the dense scan is the TPU-native answer for exact)
            raw = jnp.dot(vc["mat"], qvec, preferred_element_type=jnp.float32)
            score = _sim_score(raw, jnp.sum(vc["mat"] * vc["mat"], axis=1))
            matched = vc["present"] & (live > 0)
        if fspec is not None:
            matched = matched & emit(fspec, seg_arrays, params).matched
        score = jnp.where(matched, score * params[f"q{nid}_boost"], 0.0)
        return ops.ScoredMask(score, matched.astype(jnp.float32))

    if kind == "terms_set":
        _, _, child_spec = spec
        sm = emit(child_spec, seg_arrays, params)   # child msm=0: raw counts
        need = jnp.maximum(params[f"q{nid}_ts_msm"], 1.0)
        ok = (sm.count >= need) & (live > 0)
        return ops.ScoredMask(jnp.where(ok, sm.scores, 0.0),
                              ok.astype(jnp.float32))

    if kind == "pinned":
        _, _, organic_spec, _pad = spec
        org = (emit(organic_spec, seg_arrays, params) if organic_spec
               is not None else ops.ScoredMask(zeros, zeros))
        docs = params[f"q{nid}_pin_docs"]
        ranks = params[f"q{nid}_pin_ranks"]
        valid = (docs >= 0) & (docs < ndocs_pad)
        didx = jnp.where(valid, docs, ndocs_pad)
        # pinned scores sit far above any organic BM25 score, descending in
        # list order (reference PinnedQueryBuilder MAX_ORGANIC_SCORE). Base
        # chosen so a rank step of 1 survives f32 (ulp(1e6) = 0.0625; at
        # 1e9 it would be 64 and all pins would tie)
        pin_score = jnp.where(valid, 1e6 - ranks, 0.0)
        pins = zeros.at[didx].max(pin_score, mode="drop")
        pinned_mask = (pins > 0) & (live > 0)
        score = jnp.where(pinned_mask, pins,
                          org.scores * params[f"q{nid}_boost"])
        matched = pinned_mask | (org.matched > 0)
        return ops.ScoredMask(jnp.where(matched, score, 0.0),
                              matched.astype(jnp.float32))

    if kind == "combined":
        _, _, fspecs, T_pad, k1, b_p = spec
        tfc = jnp.zeros((T_pad, ndocs_pad), jnp.float32)
        dlc = zeros
        any_field = False
        for fi, (fname, bucket, has_post) in enumerate(fspecs):
            if not has_post:
                continue
            any_field = True
            post = seg_arrays["postings"][fname]
            w = params[f"q{nid}_cf_w{fi}"]
            tfc = tfc + w * ops.gather_tf_dense(post,
                                                params[f"q{nid}_cf_rows{fi}"],
                                                bucket, ndocs_pad, T_pad)
            dlc = dlc + w * seg_arrays["doc_lens"].get(fname, zeros)
        if not any_field:
            return ops.ScoredMask(zeros, zeros)
        norm = k1 * (1.0 - b_p + b_p * dlc / params[f"q{nid}_cf_avgdl"])
        # LUCENE-8563 form (no (k1+1) factor) — every other scoring path
        # here uses it, so combined_fields stays rank-commensurate in
        # mixed bool queries
        sat = tfc / (tfc + norm[None, :])
        idf = params[f"q{nid}_cf_idf"]
        scores = jnp.sum(jnp.where(tfc > 0, idf[:, None] * sat, 0.0), axis=0)
        counts = jnp.sum((tfc > 0).astype(jnp.float32), axis=0)
        ok = (counts >= params[f"q{nid}_cf_msm"]) & (live > 0)
        return ops.ScoredMask(jnp.where(ok, scores, 0.0),
                              ok.astype(jnp.float32))

    if kind == "geodist":
        _, _, field, col_exists, inclusive = spec
        if not col_exists:
            return ops.ScoredMask(zeros, zeros)
        geo = seg_arrays["geo"][field]
        mask = ops.geo_distance_mask(geo, params[f"q{nid}_lat"], params[f"q{nid}_lon"],
                                     params[f"q{nid}_rad"],
                                     inclusive=inclusive) & (live > 0)
        m = mask.astype(jnp.float32)
        return ops.ScoredMask(m * params[f"q{nid}_boost"], m)

    if kind == "geobox":
        _, _, field, col_exists = spec
        if not col_exists:
            return ops.ScoredMask(zeros, zeros)
        geo = seg_arrays["geo"][field]
        lat, lon = geo["lat"], geo["lon"]
        mask = ((lat <= params[f"q{nid}_top"]) & (lat >= params[f"q{nid}_bottom"]) &
                (lon >= params[f"q{nid}_left"]) & (lon <= params[f"q{nid}_right"]) &
                geo["present"] & (live > 0))
        m = mask.astype(jnp.float32)
        return ops.ScoredMask(m * params[f"q{nid}_boost"], m)

    if kind == "geopoly":
        _, _, field, col_exists, _vpad = spec
        if not col_exists:
            return ops.ScoredMask(zeros, zeros)
        mask = ops.point_in_polygon_mask(seg_arrays["geo"][field],
                                         params[f"q{nid}_plat"],
                                         params[f"q{nid}_plon"]) & (live > 0)
        m = mask.astype(jnp.float32)
        return ops.ScoredMask(m * params[f"q{nid}_boost"], m)

    if kind == "geoshape":
        mask = params[f"q{nid}_shapemask"] & (live > 0)
        m = mask.astype(jnp.float32)
        return ops.ScoredMask(m * params[f"q{nid}_boost"], m)

    raise ValueError(f"cannot emit spec kind [{kind}]")


def _apply_modifier(jnp, v, modifier: str):
    if modifier == "none":
        return v
    if modifier == "log":
        return jnp.log10(jnp.maximum(v, 1e-9))
    if modifier == "log1p":
        return jnp.log10(v + 1.0)
    if modifier == "log2p":
        return jnp.log10(v + 2.0)
    if modifier == "ln":
        return jnp.log(jnp.maximum(v, 1e-9))
    if modifier == "ln1p":
        return jnp.log1p(v)
    if modifier == "ln2p":
        return jnp.log(v + 2.0)
    if modifier == "square":
        return v * v
    if modifier == "sqrt":
        return jnp.sqrt(jnp.maximum(v, 0.0))
    if modifier == "reciprocal":
        return 1.0 / jnp.maximum(v, 1e-9)
    raise ValueError(f"unknown modifier [{modifier}]")


def _score_mode_neutral(mode: str) -> float:
    return 1.0 if mode == "multiply" else 0.0


def _combine_factors(jnp, factors, mode: str, ndocs_pad: int):
    if mode == "multiply":
        out = factors[0]
        for f in factors[1:]:
            out = out * f
        return out
    if mode in ("sum", "avg"):
        out = factors[0]
        for f in factors[1:]:
            out = out + f
        return out / len(factors) if mode == "avg" else out
    if mode == "max":
        out = factors[0]
        for f in factors[1:]:
            out = jnp.maximum(out, f)
        return out
    if mode == "min":
        out = factors[0]
        for f in factors[1:]:
            out = jnp.minimum(out, f)
        return out
    if mode == "first":
        return factors[0]
    raise ValueError(f"unknown score_mode [{mode}]")


def _combine_boost(jnp, score, factor, mode: str):
    if mode == "multiply":
        return score * factor
    if mode == "sum":
        return score + factor
    if mode == "replace":
        return factor
    if mode == "avg":
        return (score + factor) / 2.0
    if mode == "max":
        return jnp.maximum(score, factor)
    if mode == "min":
        return jnp.minimum(score, factor)
    raise ValueError(f"unknown boost_mode [{mode}]")


# =====================================================================
# sort
# =====================================================================

def _nested_sort_values(seg: Segment, field: str, path: str, mode: str):
    """Per-parent aggregate of a nested child numeric column (reference
    NestedSortBuilder): min/max/sum/avg over each parent's block children.
    Cached per (field, path, mode). -> (values f64[ndocs], present bool) or
    (None, None). The per-segment lock keeps concurrent first computations
    of one key from double-charging the breaker (only one cache write
    wins, but both finalizers would release)."""
    cache = seg.__dict__.setdefault("_nested_sort_cache", {})
    key = (field, path, mode)
    if key in cache:
        return cache[key]
    lock = seg.__dict__.setdefault("_nested_sort_lock",
                                   __import__("threading").Lock())
    with lock:
        if key in cache:
            return cache[key]
        return _nested_sort_values_build(seg, cache, key, field, path,
                                         mode)


def _nested_sort_values_build(seg: Segment, cache: dict, key, field: str,
                              path: str, mode: str):
    blk = seg.nested.get(path)
    col = blk.child.numeric_cols.get(field) if blk is not None else None
    if col is None:
        cache[key] = (None, None)
        return cache[key]
    n = seg.ndocs
    parent = blk.parent_of[: blk.child.ndocs]
    pres_child = col.present[: blk.child.ndocs] & blk.child.live[: blk.child.ndocs]
    vals_child = col.values[: blk.child.ndocs].astype(np.float64)
    out = np.full(n, np.inf if mode == "min" else
                  (-np.inf if mode == "max" else 0.0), np.float64)
    present = np.zeros(n, bool)
    p = parent[pres_child]
    v = vals_child[pres_child]
    if mode == "min":
        np.minimum.at(out, p, v)
    elif mode == "max":
        np.maximum.at(out, p, v)
    else:                              # sum / avg
        np.add.at(out, p, v)
    present[np.unique(p)] = True
    if mode == "avg":
        cnt = np.zeros(n, np.float64)
        np.add.at(cnt, p, 1.0)
        out = np.divide(out, np.maximum(cnt, 1.0))
    out = np.where(present, out, 0.0)
    # parent-docs-scale columns cached for the segment's lifetime:
    # register with the HBM ledger (same fielddata budget the fastpath
    # layouts charge, derived by the ledger), released when the
    # (immutable) segment is GC'd — the cache dict lives on it
    from ..obs.hbm_ledger import LEDGER
    LEDGER.register("nested_sort", out.nbytes + present.nbytes, owner=seg,
                    segment=seg,
                    label=f"nested-sort[{seg.name}][{path}.{field}]")
    cache[key] = (out, present)
    return cache[key]


def prepare_sort(sort_specs: List[dict], seg: Segment, params: dict):
    """Bind sort to a segment. Device ranks by the PRIMARY key exactly (rank
    ordinals for numerics — see NumericColumn.sort_ords); the executor
    re-orders the k-window on the host with the full key tuple."""
    import jax.numpy as jnp

    if not sort_specs:
        return ("score",)
    primary = sort_specs[0]
    field = primary["field"]
    if field == "_score":
        return ("score",) if primary.get("order", "desc") == "desc" else ("score_asc",)
    if field == "_doc":
        return ("doc",)
    desc = primary.get("order", "asc") == "desc"
    missing = primary.get("missing", "_last")
    missing_last = missing == "_last"
    if field == "_geo_distance":
        # device primary key = f32 haversine meters (host re-orders the
        # window exactly); reference GeoDistanceSortBuilder
        gfield = primary["geo_field"]
        if gfield not in seg.geo_cols:
            return ("missing_field", desc, missing_last)
        lat, lon = primary["origin"]
        _p(params, "sort_geo_olat", np.float32(lat))
        _p(params, "sort_geo_olon", np.float32(lon))
        return ("geo_dist", gfield, desc, missing_last)
    nspec = primary.get("nested")
    if nspec and nspec.get("path"):
        vals, present = _nested_sort_values(seg, field, nspec["path"],
                                            primary.get("mode",
                                                        "max" if desc
                                                        else "min"))
        if vals is None:
            return ("missing_field", desc, missing_last)
        ords = np.full(seg.ndocs, -1, np.int32)
        if present.any():
            uniq = np.unique(vals[present])
            ords[present] = np.searchsorted(uniq, vals[present]).astype(np.int32)
        import jax.numpy as _jnp
        pad = np.full(seg.ndocs_pad, -1, dtype=np.int32)
        pad[: seg.ndocs] = ords
        params["sort_ords"] = _jnp.asarray(pad)
        return ("field_ord", desc, missing_last)
    if field in seg.numeric_cols:
        cache = getattr(seg, "_sort_dev_cache", None)
        if cache is None:
            cache = seg._sort_dev_cache = {}
        if field not in cache:
            ords = seg.numeric_cols[field].sort_ords()
            pad = np.full(seg.ndocs_pad, -1, dtype=np.int32)
            pad[: seg.ndocs] = ords
            cache[field] = jnp.asarray(pad)
        params["sort_ords"] = cache[field]
        return ("field_ord", desc, missing_last)
    if field in seg.keyword_cols:
        return ("kw_ord", field, desc, missing_last)
    return ("missing_field", desc, missing_last)


def emit_sort_key(sort_spec, seg_arrays: dict, params: dict, scores):
    import jax.numpy as jnp

    kind = sort_spec[0]
    ndocs_pad = seg_arrays["live"].shape[0]
    if kind == "score":
        return scores
    if kind == "score_asc":
        return -scores
    if kind == "doc":
        return -jnp.arange(ndocs_pad, dtype=jnp.float32)
    big = jnp.float32(2.0**30)
    if kind == "geo_dist":
        _, gfield, desc, missing_last = sort_spec
        g = seg_arrays["geo"][gfield]
        dist = ops.geo_distance_vec(g, params["sort_geo_olat"],
                                    params["sort_geo_olon"])
        key = dist if desc else -dist
        missing_key = -big if missing_last else big
        return jnp.where(g["present"], key, missing_key)
    if kind == "field_ord":
        _, desc, missing_last = sort_spec
        ords = params["sort_ords"].astype(jnp.float32)
        present = params["sort_ords"] >= 0
    elif kind == "kw_ord":
        _, field, desc, missing_last = sort_spec
        mo = seg_arrays["keyword"][field]["min_ord"]
        ords = mo.astype(jnp.float32)
        present = mo >= 0
    else:
        _, desc, missing_last = sort_spec
        ords = jnp.zeros(ndocs_pad, jnp.float32)
        present = jnp.zeros(ndocs_pad, bool)
    key = ords if desc else -ords
    missing_key = -big if missing_last else big
    return jnp.where(present, key, missing_key)


# =====================================================================
# aggregations: prepare + emit
# =====================================================================

def _host_date_buckets(seg: Segment, field: str, interval_ms: int, offset_ms: int,
                       calendar: Optional[str]) -> Tuple[np.ndarray, int, int]:
    """Exact date bucketing on host i64 (cached per segment): returns
    (bucket_id i32[ndocs], min_bucket, nbuckets). Calendar intervals walk real
    calendars (reference Rounding.Builder)."""
    cache = getattr(seg, "_date_bucket_cache", None)
    if cache is None:
        cache = seg._date_bucket_cache = {}
    key = (field, interval_ms, offset_ms, calendar)
    if key in cache:
        return cache[key]
    col = seg.numeric_cols.get(field)
    if col is None or not col.present.any():
        res = (np.full(seg.ndocs, -1, np.int32), 0, 1)
        cache[key] = res
        return res
    vals = col.values.astype(np.int64)
    if calendar is None:
        b = np.floor_divide(vals - offset_ms, interval_ms)
    else:
        b = _calendar_bucket_ids(vals, calendar)
    b = np.where(col.present, b, np.int64(-(1 << 40)))
    bp = b[col.present]
    mn, mx = int(bp.min()), int(bp.max())
    out = (b - mn).astype(np.int64)
    out = np.where(col.present, out, -1).astype(np.int32)
    res = (out, mn, int(mx - mn + 1))
    cache[key] = res
    return res


def _calendar_bucket_ids(ms: np.ndarray, calendar: str) -> np.ndarray:
    import datetime as dt

    out = np.empty(len(ms), dtype=np.int64)
    for i, v in enumerate(ms):
        d = dt.datetime.fromtimestamp(int(v) / 1000.0, dt.timezone.utc)
        if calendar in ("month", "1M"):
            out[i] = (d.year - 1970) * 12 + (d.month - 1)
        elif calendar in ("year", "1y"):
            out[i] = d.year - 1970
        elif calendar in ("quarter", "1q"):
            out[i] = (d.year - 1970) * 4 + (d.month - 1) // 3
        elif calendar in ("week", "1w"):
            out[i] = (int(v) // 86400000 + 3) // 7  # epoch day 0 = Thursday
        elif calendar in ("day", "1d"):
            out[i] = int(v) // 86400000
        elif calendar in ("hour", "1h"):
            out[i] = int(v) // 3600000
        elif calendar in ("minute", "1m"):
            out[i] = int(v) // 60000
        else:
            raise ValueError(f"unknown calendar_interval [{calendar}]")
    return out


_CAL_MS = {"month": None, "1M": None, "year": None, "1y": None, "quarter": None,
           "1q": None, "week": None, "1w": None}

_FIXED_MS = {"ms": 1, "s": 1000, "m": 60000, "h": 3600000, "d": 86400000}


def parse_interval_ms(s, allow_negative: bool = False) -> int:
    if isinstance(s, (int, float)):
        return int(s)
    # sign is legal only where the caller says so (date_histogram `offset`
    # accepts "+6h"/"-3h"; a negative fixed_interval must stay an error)
    sign_re = r"([+-]?)" if allow_negative else r"()"
    mm = re.fullmatch(sign_re + r"(\d+)(ms|s|m|h|d)", str(s))
    if not mm:
        raise ValueError(f"invalid fixed_interval [{s}]")
    v = int(mm.group(2)) * _FIXED_MS[mm.group(3)]
    return -v if mm.group(1) == "-" else v


def crc32_vocab_hashes(vocab, pad: int) -> np.ndarray:
    """crc32 of each vocab string, zero-padded to `pad` — the HLL value
    hashes; shared by the host segment path and the mesh service so the
    two register sets merge bit-identically."""
    import zlib
    out = np.zeros(pad, dtype=np.uint32)
    out[: len(vocab)] = np.fromiter(
        (zlib.crc32(v.encode()) for v in vocab), np.uint32,
        count=len(vocab))
    return out


def _kw_hash_cache(seg: Segment, field: str) -> np.ndarray:
    cache = getattr(seg, "_kw_hash_cache", None)
    if cache is None:
        cache = seg._kw_hash_cache = {}
    if field not in cache:
        col = seg.keyword_cols[field]
        cache[field] = crc32_vocab_hashes(
            col.vocab, next_pow2(max(len(col.vocab), 1)))
    return cache[field]


_B32 = "0123456789bcdefghjkmnpqrstuvwxyz"


def _geohash_strings(codes: np.ndarray, precision: int) -> List[str]:
    out = []
    for c in codes.tolist():
        s = []
        for i in range(precision):
            shift = 5 * (precision - 1 - i)
            s.append(_B32[(c >> shift) & 31])
        out.append("".join(s))
    return out


def _geo_grid_cache(seg: Segment, field: str, kind: str, precision: int):
    """(vocab cell keys, per-doc cell ordinal i32[ndocs_pad], -1 missing) —
    computed once per (segment, field, kind, precision) on the host; the
    device then bincounts ordinals exactly like the terms agg. (Reference
    GeoHashGridAggregator/GeoTileGridAggregator bucket by cell the same way,
    via doc-value cell ids.)"""
    cache = getattr(seg, "_geo_grid_cells", None)
    if cache is None:
        cache = seg._geo_grid_cells = {}
    key = (field, kind, precision)
    if key in cache:
        return cache[key]
    col = seg.geo_cols.get(field)
    ords = np.full(seg.ndocs_pad, -1, np.int32)
    vocab: List[str] = []
    if col is not None and col.present.any():
        lat = col.lat[: seg.ndocs].astype(np.float64)
        lon = col.lon[: seg.ndocs].astype(np.float64)
        if kind == "geotile_grid":
            z = precision
            n = 1 << z
            x = np.clip(np.floor((lon + 180.0) / 360.0 * n), 0, n - 1)
            latc = np.clip(lat, -85.05112878, 85.05112878)
            latr = np.deg2rad(latc)
            y = np.clip(np.floor(
                (1.0 - np.log(np.tan(latr) + 1.0 / np.cos(latr)) / np.pi)
                / 2.0 * n), 0, n - 1)
            codes = (x.astype(np.int64) * n + y.astype(np.int64))
            uniq, inv = np.unique(codes, return_inverse=True)
            vocab = [f"{z}/{int(c) // n}/{int(c) % n}" for c in uniq]
        else:  # geohash
            nbits = 5 * precision
            lonb = (nbits + 1) // 2
            latb = nbits // 2
            li = np.clip(np.floor((lon + 180.0) / 360.0 * (1 << lonb)),
                         0, (1 << lonb) - 1).astype(np.uint64)
            la = np.clip(np.floor((lat + 90.0) / 180.0 * (1 << latb)),
                         0, (1 << latb) - 1).astype(np.uint64)
            codes = np.zeros(len(lat), np.uint64)
            # interleave, lon first (standard geohash bit order)
            for b in range(nbits):
                if b % 2 == 0:
                    src, idx = li, lonb - 1 - b // 2
                else:
                    src, idx = la, latb - 1 - b // 2
                bit = (src >> np.uint64(idx)) & np.uint64(1)
                codes = (codes << np.uint64(1)) | bit
            uniq, inv = np.unique(codes, return_inverse=True)
            vocab = _geohash_strings(uniq, precision)
        o = np.where(col.present[: seg.ndocs], inv.astype(np.int32), -1)
        ords[: seg.ndocs] = o
    cache[key] = (vocab, ords)
    return cache[key]


# auto_date_histogram rounding ladder (fixed-interval approximation of the
# reference's calendar ladder — months/years as 30/365 days)
_AUTO_LADDER = [
    (1_000, "1s"), (5_000, "5s"), (10_000, "10s"), (30_000, "30s"),
    (60_000, "1m"), (300_000, "5m"), (600_000, "10m"), (1_800_000, "30m"),
    (3_600_000, "1h"), (10_800_000, "3h"), (43_200_000, "12h"),
    (86_400_000, "1d"), (604_800_000, "7d"), (2_592_000_000, "1M"),
    (7_776_000_000, "3M"), (31_536_000_000, "1y"), (157_680_000_000, "5y"),
    (315_360_000_000, "10y"), (3_153_600_000_000, "100y"),
]


def _auto_interval(col, target: int) -> int:
    """Smallest ladder interval giving <= target buckets over the column's
    span (reference AutoDateHistogramAggregator rounding prepare)."""
    if col is None or not col.present.any():
        return _AUTO_LADDER[0][0]
    mn, mx = col.min_max
    span = max(mx - mn, 1.0)
    for ms, _name in _AUTO_LADDER:
        if span / ms <= target:
            return ms
    return _AUTO_LADDER[-1][0]


def auto_interval_name(interval_ms: int) -> str:
    for ms, name in _AUTO_LADDER:
        if ms == interval_ms:
            return name
    return f"{interval_ms}ms"


def _multi_terms_cache(seg: Segment, ctx: ShardContext, node, fields: Tuple[str, ...]):
    """(vocab of key tuples, combined doc-major ordinal i32[ndocs_pad]) for a
    multi_terms source list; docs missing ANY source are excluded (-1),
    matching reference MultiTermsAggregator."""
    cache = getattr(seg, "_multi_terms_cache", None)
    if cache is None:
        cache = seg._multi_terms_cache = {}
    if fields in cache:
        return cache[fields]
    per_field = []
    for f in fields:
        f = ctx.mappings.aliases.get(f, f)
        kcol = seg.keyword_cols.get(f)
        if kcol is not None:
            per_field.append(("kw", kcol.min_ord[: seg.ndocs], kcol.vocab))
            continue
        ncol = seg.numeric_cols.get(f)
        if ncol is not None:
            ords = ncol.sort_ords()[: seg.ndocs]
            vals = sorted({(float(v) if ncol.kind == "float" else int(v))
                           for v in ncol.values[ncol.present]})
            per_field.append(("num", ords, vals))
            continue
        per_field.append(("none", np.full(seg.ndocs, -1, np.int32), []))
    combined = np.zeros(seg.ndocs, np.int64)
    valid = np.ones(seg.ndocs, bool)
    mult = 1
    for kind_, ords, vocab in reversed(per_field):
        valid &= ords >= 0
        combined += np.maximum(ords, 0).astype(np.int64) * mult
        mult *= max(len(vocab), 1)
    uniq, inv = np.unique(combined[valid], return_inverse=True)
    ords_out = np.full(next_pow2(seg.ndocs), -1, np.int32)
    ords_out[: seg.ndocs][valid] = inv.astype(np.int32)
    # decode each unique combined ordinal back to its key tuple
    mults = []
    m = 1
    for _kind, _o, vocab in reversed(per_field):
        mults.append(m)
        m *= max(len(vocab), 1)
    mults.reverse()
    vocab_out = []
    for code in uniq:
        key = []
        rem = int(code)
        for (_kind, _o, vocab), mm in zip(per_field, mults):
            idx = rem // mm
            rem = rem % mm
            key.append(vocab[idx] if idx < len(vocab) else None)
        vocab_out.append(tuple(key))
    cache[fields] = (vocab_out, ords_out)
    return cache[fields]


def _col_sum(seg: Segment, field: str) -> Tuple[float, int]:
    """(Σ values, present count) of a numeric column, f64, cached per segment
    (segments are immutable apart from deletes, which don't need to perturb a
    scoring shift)."""
    cache = getattr(seg, "_col_sum_cache", None)
    if cache is None:
        cache = seg._col_sum_cache = {}
    if field not in cache:
        col = seg.numeric_cols.get(field)
        if col is None or not col.present.any():
            cache[field] = (0.0, 0)
        else:
            cache[field] = (float(col.values[col.present].astype(np.float64).sum()),
                            int(col.present.sum()))
    return cache[field]


def _kw_doc_counts(seg: Segment, field: str) -> Dict[str, int]:
    """Background per-value doc counts over the segment's live docs
    (significant_terms superset statistics); invalidated by deletes via
    `live_gen`."""
    cache = getattr(seg, "_kw_doc_count_cache", None)
    if cache is None or cache.get("__gen") != seg.live_gen:
        cache = seg._kw_doc_count_cache = {"__gen": seg.live_gen}
    if field in cache:
        return cache[field]
    col = seg.keyword_cols.get(field)
    out: Dict[str, int] = {}
    if col is not None and len(col.vocab):
        live_vals = seg.live[col.doc_of_value]
        counts = np.bincount(col.ords[live_vals], minlength=len(col.vocab))
        out = {col.vocab[i]: int(c) for i, c in enumerate(counts) if c > 0}
    cache[field] = out
    return out


def coerce_agg_ranges(kind: str, body: dict, field: str,
                      mappings) -> list:
    """Shared host/mesh range-agg bounds: date_range coerces from/to
    through the field type (date math/formats -> epoch ms) before the
    f32 bound construction. Single source of truth for both paths."""
    ranges = body.get("ranges", [])
    if kind != "date_range":
        return ranges
    ft = mappings.resolve_field(field)
    coerced = []
    for r in ranges:
        r2 = dict(r)
        for end in ("from", "to"):
            if r.get(end) is not None:
                r2[end] = coerce_value(ft, r[end])
        coerced.append(r2)
    return coerced


def filters_agg_items(body: dict) -> list:
    """Shared host/mesh normalization of a `filters` agg body to
    (key, clause) pairs (dict keys, or "0"/"1"/... for the anonymous list
    form). Single source of truth — mesh bucket keys must match the host
    coordinator merge exactly."""
    raw = body.get("filters", {})
    return (list(raw.items()) if isinstance(raw, dict)
            else [(str(i), f) for i, f in enumerate(raw)])


def grid_agg_precision(kind: str, body: dict) -> int:
    """Shared host/mesh geo-grid precision resolution (geohash default 5,
    geotile default 7). Single source of truth — the mesh keys its device
    program cache on this and must never drift from the cell binning."""
    return int(body.get("precision", 5 if kind == "geohash_grid" else 7))


def hist_agg_interval(kind: str, body: dict) -> Tuple[float, float]:
    """Shared host/mesh resolution of a histogram-family agg's (interval,
    offset) in value space (ms for dates; fixed_interval preferred).
    Single source of truth — the mesh service keys its device-program cache
    on this and must never drift from the binning itself."""
    if kind == "date_histogram":
        interval = float(parse_interval_ms(
            body.get("fixed_interval", body.get("interval", "1d"))))
        offset = (float(parse_interval_ms(body.get("offset", 0),
                                          allow_negative=True))
                  if body.get("offset") else 0.0)
    else:
        interval = float(body["interval"])
        offset = float(body.get("offset", 0.0))
    return interval, offset


def range_agg_spec(ranges: List[dict]) -> tuple:
    """Shared host/mesh construction of a plain `range` agg's f32 bounds,
    bucket keys, and from/to response meta (f32-roundtripped so host and
    mesh responses are bit-identical). Single source of truth: the mesh
    service (`parallel/service.py`) serves the same aggs and must never
    drift from this formatting."""
    nr = len(ranges)
    lows = np.full(nr, -np.inf, dtype=np.float32)
    highs = np.full(nr, np.inf, dtype=np.float32)
    keys, metas = [], []
    for i, r in enumerate(ranges):
        frm, to = r.get("from"), r.get("to")
        if frm is not None:
            lows[i] = float(frm)
        if to is not None:
            highs[i] = float(to)
        keys.append(r.get("key", f"{frm if frm is not None else '*'}-"
                                 f"{to if to is not None else '*'}"))
        meta = {}
        if frm is not None:
            meta["from"] = float(np.float32(frm))
        if to is not None:
            meta["to"] = float(np.float32(to))
        metas.append(meta)
    return lows, highs, keys, metas


def prepare_agg(node: AggNode, seg: Segment, ctx: ShardContext, params: dict,
                prefix: str, nest_stack: Tuple = ()):  # noqa: C901
    """-> hashable agg spec; params filled per segment. `prefix` keys params.
    `nest_stack` is the nesting path down to `seg`: ((path, segment), ...)
    root-first, empty at root — reverse_nested climbs it."""
    kind = node.kind
    body = node.body

    if kind == "terms":
        field = _resolve_agg_field(node, ctx)
        if field not in seg.keyword_cols:
            return ("terms_missing", prefix)
        nvocab_pad = next_pow2(max(len(seg.keyword_cols[field].vocab), 1))
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        return ("terms", prefix, field, nvocab_pad, subs)

    if kind == "histogram":
        field = _resolve_agg_field(node, ctx)
        interval = float(body["interval"])
        offset = float(body.get("offset", 0.0))
        col = seg.numeric_cols.get(field)
        if col is None or not col.present.any():
            return ("hist_missing", prefix, interval, offset)
        mn, mx = col.min_max
        min_b = int(np.floor((mn - offset) / interval))
        max_b = int(np.floor((mx - offset) / interval))
        nb = max_b - min_b + 1
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        return ("hist", prefix, field, interval, offset, min_b, nb, subs)

    if kind == "date_histogram":
        field = _resolve_agg_field(node, ctx)
        calendar = body.get("calendar_interval")
        if calendar is not None:
            interval_ms = 0
        else:
            interval_ms = parse_interval_ms(body.get("fixed_interval",
                                                     body.get("interval", "1d")))
        offset_ms = (parse_interval_ms(body.get("offset", 0),
                                       allow_negative=True)
                     if body.get("offset") else 0)
        bucket_ids, min_b, nb = _host_date_buckets(seg, field, max(interval_ms, 1),
                                                   offset_ms, calendar)
        pad = np.full(next_pow2(len(bucket_ids)), -1, dtype=np.int32)
        pad[: len(bucket_ids)] = bucket_ids
        params[f"{prefix}_dbuckets"] = pad
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        return ("date_hist", prefix, field, interval_ms, offset_ms, calendar,
                min_b, nb, subs)

    if kind in ("range", "date_range"):
        field = _resolve_agg_field(node, ctx)
        ranges = coerce_agg_ranges(kind, node.body, field, ctx.mappings)
        lows, highs, keys, _metas = range_agg_spec(ranges)
        params[f"{prefix}_lows"] = lows
        params[f"{prefix}_highs"] = highs
        col_exists = field in seg.numeric_cols
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        return ("range", prefix, field, tuple(keys), col_exists, subs,
                tuple((float(lows[i]), float(highs[i])) for i in range(len(ranges))))

    if kind == "geo_distance":
        # distance-ring buckets from an origin (reference bucket/range/
        # GeoDistanceAggregationBuilder): haversine vector on device, then
        # the same range-count pass as the numeric range agg
        field = _resolve_agg_field(node, ctx)
        if "origin" not in body:
            raise dsl.QueryParseError(
                "[geo_distance] aggregation requires [origin]")
        try:
            olat, olon = dsl._parse_point(body["origin"])
            unit_m = dsl._parse_distance(f"1{body.get('unit', 'm')}")
        except (ValueError, TypeError, KeyError) as e:
            raise dsl.QueryParseError(f"[geo_distance] {e}")
        ranges = body.get("ranges", [])
        lows = np.full(len(ranges), -np.inf, dtype=np.float32)
        highs = np.full(len(ranges), np.inf, dtype=np.float32)
        keys = []
        disp = []
        for i, r in enumerate(ranges):
            frm, to = r.get("from"), r.get("to")
            if frm is not None:
                lows[i] = float(frm) * unit_m
            if to is not None:
                highs[i] = float(to) * unit_m
            keys.append(r.get("key", f"{frm if frm is not None else '*'}-"
                                     f"{to if to is not None else '*'}"))
            disp.append((float(frm) if frm is not None else None,
                         float(to) if to is not None else None))
        params[f"{prefix}_lows"] = lows
        params[f"{prefix}_highs"] = highs
        _scalar_f32(params, f"{prefix}_olat", olat)
        _scalar_f32(params, f"{prefix}_olon", olon)
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        return ("geo_range", prefix, field, tuple(keys),
                field in seg.geo_cols, subs,
                tuple((lo if lo is not None else float("-inf"),
                       hi if hi is not None else float("inf"))
                      for lo, hi in disp))

    if kind == "filter":
        lnode = rewrite(dsl.parse_query(body), ctx, scoring=False)
        fspec = prepare(lnode, seg, ctx, params)
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        return ("filter", prefix, fspec, subs)

    if kind == "filters":
        items = filters_agg_items(body)
        fspecs = []
        for key, f in items:
            lnode = rewrite(dsl.parse_query(f), ctx, scoring=False)
            fspecs.append((key, prepare(lnode, seg, ctx, params)))
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        return ("filters", prefix, tuple(fspecs), subs)

    if kind == "global":
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        return ("global", prefix, subs)

    if kind == "missing":
        field = _resolve_agg_field(node, ctx)
        src = ("numeric" if field in seg.numeric_cols else
               "keyword" if field in seg.keyword_cols else "none")
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        return ("missing", prefix, field, src, subs)

    if kind in ("min", "max", "sum", "avg", "stats", "extended_stats", "value_count"):
        field = _resolve_agg_field(node, ctx)
        if kind == "value_count" and field in seg.keyword_cols:
            return ("vc_keyword", prefix, field)
        return ("stats", prefix, field, field in seg.numeric_cols)

    if kind == "cardinality":
        field = _resolve_agg_field(node, ctx)
        if field in seg.keyword_cols:
            params[f"{prefix}_hashes"] = _kw_hash_cache(seg, field)
            nvocab_pad = next_pow2(max(len(seg.keyword_cols[field].vocab), 1))
            return ("card_kw", prefix, field, nvocab_pad)
        return ("card_num", prefix, field, field in seg.numeric_cols)

    if kind == "percentiles":
        field = _resolve_agg_field(node, ctx)
        col = seg.numeric_cols.get(field)
        percents = tuple(body.get("percents", DEFAULT_PERCENTS))
        return ("pctl", prefix, field, col is not None, percents)

    if kind == "percentile_ranks":
        field = _resolve_agg_field(node, ctx)
        col = seg.numeric_cols.get(field)
        values = tuple(float(v) for v in body.get("values", ()))
        return ("pctl_ranks", prefix, field, col is not None, values)

    if kind == "top_hits":
        return ("top_hits", prefix, int(body.get("size", 3)))

    if kind == "significant_terms":
        field = _resolve_agg_field(node, ctx)
        if field not in seg.keyword_cols:
            # still contributes its live docs to the background total —
            # supersetSize spans the whole shard (reference semantics)
            return ("sig_missing", prefix)
        nvocab_pad = next_pow2(max(len(seg.keyword_cols[field].vocab), 1))
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        return ("sig_terms", prefix, field, nvocab_pad, subs)

    if kind == "sampler":
        shard_size = max(int(body.get("shard_size", 100)), 1)
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        # pass 2 of the shard-wide resample (executor._resample_samplers)
        # supplies a global score threshold instead of a per-segment top-k
        thr = getattr(node, "_global_thr", None)
        if thr is not None:
            _scalar_f32(params, f"{prefix}_thr", thr)
        return ("sampler", prefix, shard_size, thr is not None, subs)

    if kind == "diversified_sampler":
        shard_size = max(int(body.get("shard_size", 100)), 1)
        maxper = max(int(body.get("max_docs_per_value", 1)), 1)
        field = ctx.mappings.aliases.get(body.get("field", ""),
                                        body.get("field", ""))
        use_kw = field in seg.keyword_cols
        if not use_kw and field in seg.numeric_cols:
            ords = seg.numeric_cols[field].sort_ords()
            params[f"{prefix}_dords"] = np.pad(
                ords, (0, seg.ndocs_pad - len(ords)), constant_values=-1)
            n_ord_pad = next_pow2(seg.ndocs + 1)
        elif use_kw:
            n_ord_pad = next_pow2(len(seg.keyword_cols[field].vocab) + 1)
        else:
            params[f"{prefix}_dords"] = np.full(seg.ndocs_pad, -1, np.int32)
            n_ord_pad = 2
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        return ("dsampler", prefix, shard_size, field, maxper, use_kw,
                n_ord_pad, subs)

    if kind in ("geohash_grid", "geotile_grid"):
        field = _resolve_agg_field(node, ctx)
        precision = grid_agg_precision(kind, body)
        vocab, ords = _geo_grid_cache(seg, field, kind, precision)
        params[f"{prefix}_gords"] = ords
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        return ("geo_grid", prefix, kind, field, precision,
                next_pow2(max(len(vocab), 1)), subs)

    if kind == "nested":
        path = body.get("path")
        blk = seg.nested.get(path)
        if blk is None or blk.child.ndocs == 0:
            return ("terms_missing", prefix)
        new_stack = (nest_stack or ((None, seg),)) + ((path, blk.child),)
        subs = tuple(prepare_agg(s, blk.child, ctx, params, f"{prefix}_{i}",
                                 new_stack)
                     for i, s in enumerate(node.subs))
        return ("nested_agg", prefix, path, subs)

    if kind == "reverse_nested":
        if len(nest_stack) < 2:
            raise dsl.QueryParseError(
                "[reverse_nested] must be nested inside a [nested] aggregation")
        rpath = body.get("path")
        if rpath is None:
            ti = 0  # default: all the way back to the root document
        else:
            ti = next((i for i, (p, _) in enumerate(nest_stack) if p == rpath),
                      None)
            if ti is None:
                raise dsl.QueryParseError(
                    f"[reverse_nested] path [{rpath}] is not an enclosing "
                    f"nested level")
        up_k = len(nest_stack) - 1 - ti
        if up_k <= 0:
            raise dsl.QueryParseError(
                "[reverse_nested] path must point above the current level")
        target_seg = nest_stack[ti][1]
        subs = tuple(prepare_agg(s, target_seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack[: ti + 1] if ti > 0 else ())
                     for i, s in enumerate(node.subs))
        return ("reverse_nested", prefix, up_k, subs)

    if kind in ("children", "parent"):
        return _prepare_join_agg(node, seg, ctx, params, prefix)

    if kind == "composite":
        return _prepare_composite(node, seg, ctx, params, prefix, nest_stack)

    if kind == "weighted_avg":
        vspec = body.get("value", {})
        wspec = body.get("weight", {})
        vfield = ctx.mappings.aliases.get(vspec.get("field", ""),
                                          vspec.get("field", ""))
        wfield = ctx.mappings.aliases.get(wspec.get("field", ""),
                                          wspec.get("field", ""))
        _scalar_f32(params, f"{prefix}_vmiss", float(vspec.get("missing", 0.0)
                                                     or 0.0))
        _scalar_f32(params, f"{prefix}_wmiss", float(wspec.get("missing", 0.0)
                                                     or 0.0))
        return ("wavg", prefix, vfield, wfield,
                vfield in seg.numeric_cols, wfield in seg.numeric_cols,
                vspec.get("missing") is not None,
                wspec.get("missing") is not None)

    if kind == "median_absolute_deviation":
        field = _resolve_agg_field(node, ctx)
        return ("mad", prefix, field, field in seg.numeric_cols)

    if kind in ("geo_bounds", "geo_centroid"):
        field = _resolve_agg_field(node, ctx)
        return ("geo_stat", prefix, kind, field, field in seg.geo_cols)

    if kind == "ip_range":
        from ..index.mappings import _ip_to_int
        field = _resolve_agg_field(node, ctx)
        ranges = body.get("ranges", [])
        bounds = []
        keys = []
        for r in ranges:
            if "mask" in r:
                import ipaddress
                net = ipaddress.ip_network(r["mask"], strict=False)
                lo = _ip_to_int(str(net.network_address))
                hi = _ip_to_int(str(net.broadcast_address)) + 1
                keys.append(r.get("key", r["mask"]))
                bounds.append((lo, hi, str(net.network_address),
                               str(net.broadcast_address)))
            else:
                lo = _ip_to_int(r["from"]) if r.get("from") else None
                hi = _ip_to_int(r["to"]) if r.get("to") else None
                keys.append(r.get("key",
                                  f"{r.get('from', '*')}-{r.get('to', '*')}"))
                bounds.append((lo, hi, r.get("from"), r.get("to")))
        lo_hi = np.zeros(len(bounds), np.int32)
        lo_lo = np.zeros(len(bounds), np.int32)
        hi_hi = np.zeros(len(bounds), np.int32)
        hi_lo = np.zeros(len(bounds), np.int32)
        open_lo = np.zeros(len(bounds), bool)
        open_hi = np.zeros(len(bounds), bool)
        for i, (lo, hi, _f, _t) in enumerate(bounds):
            if lo is None:
                open_lo[i] = True
            else:
                h, l = split_i64(np.array([lo], np.int64))
                lo_hi[i], lo_lo[i] = h[0], l[0]
            if hi is None:
                open_hi[i] = True
            else:
                h, l = split_i64(np.array([hi], np.int64))
                hi_hi[i], hi_lo[i] = h[0], l[0]
        params[f"{prefix}_iplo"] = np.stack([lo_hi, lo_lo])
        params[f"{prefix}_iphi"] = np.stack([hi_hi, hi_lo])
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        return ("ip_range", prefix, field, tuple(keys),
                tuple((b[2], b[3]) for b in bounds),
                tuple(bool(x) for x in open_lo), tuple(bool(x) for x in open_hi),
                field in seg.numeric_cols, subs)

    if kind == "rare_terms":
        field = _resolve_agg_field(node, ctx)
        if field not in seg.keyword_cols:
            return ("terms_missing", prefix)
        nvocab_pad = next_pow2(max(len(seg.keyword_cols[field].vocab), 1))
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        return ("terms", prefix, field, nvocab_pad, subs)

    if kind == "multi_terms":
        sources = body.get("terms", [])
        if len(sources) < 2:
            raise dsl.QueryParseError(
                "[multi_terms] requires at least two [terms] sources")
        vocab, ords = _multi_terms_cache(seg, ctx, node, tuple(
            s["field"] for s in sources))
        params[f"{prefix}_mords"] = ords
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        return ("multi_terms", prefix, next_pow2(max(len(vocab), 1)),
                len(vocab), subs)

    if kind == "adjacency_matrix":
        raw = body.get("filters", {})
        sep = body.get("separator", "&")
        fspecs = []
        for key in sorted(raw):
            lnode = rewrite(dsl.parse_query(raw[key]), ctx, scoring=False)
            fspecs.append((key, prepare(lnode, seg, ctx, params)))
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        return ("adjacency", prefix, tuple(fspecs), sep, subs)

    if kind == "auto_date_histogram":
        field = _resolve_agg_field(node, ctx)
        target = max(int(body.get("buckets", 10)), 1)
        col = seg.numeric_cols.get(field)
        interval_ms = _auto_interval(col, target)
        bucket_ids, min_b, nb = _host_date_buckets(seg, field, interval_ms,
                                                   0, None)
        pad = np.full(next_pow2(len(bucket_ids)), -1, dtype=np.int32)
        pad[: len(bucket_ids)] = bucket_ids
        params[f"{prefix}_dbuckets"] = pad
        subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}",
                                 nest_stack)
                     for i, s in enumerate(node.subs))
        return ("auto_date_hist", prefix, field, interval_ms, target,
                min_b, nb, subs)

    if kind == "scripted_metric":
        return ("scripted", prefix)

    if kind == "significant_text":
        # resolved host-side from the top sampled hits (executor)
        return ("sig_text", prefix)

    if kind == "matrix_stats":
        fields = tuple(body.get("fields", []))
        exists = tuple(f in seg.numeric_cols for f in fields)
        # index-wide per-field shift: device power sums run CENTERED about it
        # so f32 accumulation doesn't catastrophically cancel (the reference
        # keeps running central moments in double for the same reason)
        shift = getattr(node, "_ms_shift", None)
        if shift is None:
            shift = np.zeros(len(fields), np.float64)
            for i, f in enumerate(fields):
                sums = [_col_sum(s, f) for s in ctx.segments]
                tot = sum(t for t, _ in sums)
                cnt = sum(c for _, c in sums)
                shift[i] = tot / cnt if cnt else 0.0
            node._ms_shift = shift
        params[f"{prefix}_shift"] = shift.astype(np.float32)
        return ("matrix_stats", prefix, fields, exists)

    raise ValueError(f"cannot prepare aggregation [{kind}]")


def _prepare_join_agg(node: AggNode, seg: Segment, ctx: ShardContext,
                      params: dict, prefix: str):
    """children / parent aggregations (reference modules/parent-join
    ChildrenAggregator / ParentAggregator). The cross-segment join rides the
    same slot-space pre-pass as has_child/has_parent; the bucket context is
    the TOP-LEVEL query (`ctx._current_lroot`) — like the reference, these
    only make sense directly under the query context."""
    from .join import get_join_index

    kind = node.kind
    jf = ctx.mappings.join_field
    if jf is None:
        return ("terms_missing", prefix)
    relations = ctx.mappings.fields[jf].relations
    child_rel = node.body.get("type")
    parent_rel = next((p for p, cs in relations.items() if child_rel in cs), None)
    if parent_rel is None:
        raise dsl.QueryParseError(
            f"[{kind}] [{child_rel}] is not a child relation of the join field")
    ji = get_join_index(ctx.segments, jf)
    lroot = getattr(ctx, "_current_lroot", None) or LMatchAll()
    pre = getattr(node, "_agg_pre", None)
    if pre is None:
        # filter nodes are built ONCE per agg node so their nids (and thus
        # the jit spec) stay stable across segments
        node._rel_filters = {
            "child": _weighted_terms(jf, [child_rel], [1.0], ctx, 1, "filter", 1.0),
            "parent": _weighted_terms(jf, [parent_rel], [1.0], ctx, 1, "filter", 1.0)}
        if kind == "children":
            # global mask of context-matched PARENT docs at their own slots
            plan = LBool(musts=[lroot], filters=[node._rel_filters["parent"]])
            pre = _join_prepass(plan, ji, ("cnt",), ctx, self_slots=True)
        else:
            # global mask of parents having context-matched CHILD docs
            plan = LBool(musts=[lroot], filters=[node._rel_filters["child"]])
            pre = _join_prepass(plan, ji, ("cnt",), ctx, self_slots=False)
        node._agg_pre = pre
    params[f"{prefix}_gmatch"] = pre["cnt"]
    subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}")
                 for i, s in enumerate(node.subs))
    if kind == "children":
        params[f"{prefix}_pslot"] = ji.pslot(seg)
        cf = prepare(node._rel_filters["child"], seg, ctx, params)
        return ("children_agg", prefix, cf, subs)
    _scalar_i32(params, f"{prefix}_base", ji.seg_base(seg))
    pf = prepare(node._rel_filters["parent"], seg, ctx, params)
    return ("parent_agg", prefix, pf, subs)


def _prepare_composite(node: AggNode, seg: Segment, ctx: ShardContext,
                       params: dict, prefix: str, nest_stack):
    """Composite agg: each doc maps to one combined ordinal over the product
    of per-source value spaces; one device bincount yields every composite
    bucket of the segment, the coordinator pages with after_key (reference
    CompositeAggregator builds the same slot machinery per leaf)."""
    from .aggregations import composite_sources

    sources = composite_sources(node)
    infos = []
    total = 1
    for si, (nm, stype, scfg, order) in enumerate(sources):
        field = scfg.get("field", "")
        ft = ctx.mappings.resolve_field(field)
        field = ft.name if ft else field
        if stype == "terms":
            col = seg.keyword_cols.get(field)
            if col is None:
                return ("terms_missing", prefix)
            multi = (len(col.ords) > 0 and
                     int(np.max(col.starts[1:] - col.starts[:-1])) > 1)
            if multi:
                # a doc contributes one composite key per value (reference
                # behavior); supported for a single-source composite, where
                # it degenerates to an ordinal bincount
                if len(sources) > 1:
                    raise dsl.QueryParseError(
                        "[composite] a multi-valued terms source cannot be "
                        "combined with other sources")
                subs_mv = tuple(prepare_agg(s, seg, ctx, params,
                                            f"{prefix}_{i}", nest_stack)
                                for i, s in enumerate(node.subs))
                return ("composite_mv", prefix, field,
                        next_pow2(max(len(col.vocab), 1)), subs_mv)
            infos.append(("terms", field, len(col.vocab), 0, 0.0, 0.0))
        elif stype == "histogram":
            interval = float(scfg["interval"])
            col = seg.numeric_cols.get(field)
            if col is None or not col.present.any():
                return ("terms_missing", prefix)
            mn, mx = col.min_max
            min_b = int(np.floor(mn / interval))
            nb = int(np.floor(mx / interval)) - min_b + 1
            infos.append(("hist", field, nb, min_b, interval, 0.0))
        elif stype == "date_histogram":
            calendar = scfg.get("calendar_interval")
            interval_ms = (0 if calendar else
                           parse_interval_ms(scfg.get("fixed_interval",
                                                      scfg.get("interval", "1d"))))
            bucket_ids, min_b, nb = _host_date_buckets(
                seg, field, max(interval_ms, 1), 0, calendar)
            if nb <= 0:
                return ("terms_missing", prefix)
            pad = np.full(next_pow2(len(bucket_ids)), -1, dtype=np.int32)
            pad[: len(bucket_ids)] = bucket_ids
            params[f"{prefix}_s{si}"] = pad
            infos.append(("date", field, nb, min_b,
                          float(max(interval_ms, 1)), calendar or ""))
        else:
            raise dsl.QueryParseError(
                f"[composite] unsupported source type [{stype}]")
        total *= max(infos[-1][2], 1)
    if total > (1 << 22):
        raise dsl.QueryParseError(
            f"[composite] too many composite buckets [{total}] "
            f"(limit {1 << 22})")
    subs = tuple(prepare_agg(s, seg, ctx, params, f"{prefix}_{i}", nest_stack)
                 for i, s in enumerate(node.subs))
    return ("composite", prefix, tuple(infos), total, subs)


def _resolve_agg_field(node: AggNode, ctx: ShardContext) -> str:
    field = node.body.get("field", "")
    ft = ctx.mappings.resolve_field(field)
    return ft.name if ft else field


def emit_agg(spec, seg_arrays: dict, params: dict, match, scores=None):  # noqa: C901
    """-> nested dict of device arrays (this segment's partial)."""
    import jax
    import jax.numpy as jnp

    kind = spec[0]
    ndocs_pad = seg_arrays["live"].shape[0]

    if kind in ("terms_missing", "hist_missing"):
        return {}

    if kind == "sig_missing":
        return {"marker": jnp.float32(0)}

    if kind == "sig_terms":
        _, prefix, field, nvocab_pad, subs = spec
        kw = seg_arrays["keyword"][field]
        out = {"counts": agg_ops.terms_counts(kw, match, nvocab_pad),
               "fg_total": jnp.sum(match)}
        for i, sub in enumerate(subs):
            if sub and sub[0] == "stats":
                _, sprefix, sfield, col_exists = sub
                if col_exists:
                    col = seg_arrays["numeric"][sfield]
                    out[f"sub{i}"] = agg_ops.terms_sub_metric(
                        kw, match, col["f32"], col["present"], nvocab_pad)
        return out

    if kind == "sampler":
        _, prefix, shard_size, use_thr, subs = spec
        out = {}
        if scores is None:
            sel = match
        elif use_thr:
            masked = jnp.where(match > 0, scores, -jnp.inf)
            sel = match * (masked >= params[f"{prefix}_thr"]).astype(jnp.float32)
        else:
            # best-scoring shard_size matching docs (reference
            # SamplerAggregator); score ties at the threshold may admit a few
            # extra docs. The per-segment top scores also go back to the host
            # so multi-segment shards can re-threshold shard-wide (pass 2).
            masked = jnp.where(match > 0, scores, -jnp.inf)
            k = min(shard_size, ndocs_pad)
            vals, _ = jax.lax.top_k(masked, k)
            thr = vals[k - 1]
            thr = jnp.where(jnp.isfinite(thr), thr, -jnp.inf)
            sel = match * (masked >= thr).astype(jnp.float32)
            out["topscores"] = vals
        out["doc_count"] = jnp.sum(sel)
        for i, sub in enumerate(subs):
            res = emit_agg(sub, seg_arrays, params, sel, scores)
            if res:
                out[f"sub{i}"] = res
        return out

    if kind == "geo_grid":
        _, prefix, gkind, field, precision, nb, subs = spec
        ords = params[f"{prefix}_gords"][:ndocs_pad]
        w = match * (ords >= 0).astype(jnp.float32)
        b = jnp.where(w > 0, ords, nb)
        out = {"counts": jnp.zeros(nb, jnp.float32).at[b].add(w, mode="drop")}
        for i, sub in enumerate(subs):
            out.update(_emit_bucketed_sub(jnp, sub, i, b, nb, seg_arrays, match))
        return out

    if kind == "nested_agg":
        _, prefix, path, subs = spec
        carr = dict(seg_arrays["nested"][path])
        parent = carr["parent"]
        live_p = seg_arrays["live"]
        carr["live"] = carr["live"] * live_p[parent]
        carr["__chain"] = ((seg_arrays, parent),) + seg_arrays.get("__chain", ())
        cmatch = match[parent] * jnp.where(carr["live"] > 0, 1.0, 0.0)
        out = {"doc_count": jnp.sum(cmatch)}
        for i, sub in enumerate(subs):
            res = emit_agg(sub, carr, params, cmatch, None)
            if res:
                out[f"sub{i}"] = res
        return out

    if kind == "reverse_nested":
        _, prefix, up_k, subs = spec
        chain = seg_arrays["__chain"]
        pmask, parent_arrays = match, seg_arrays
        for lvl in range(up_k):
            parent_arrays, parent_map = chain[lvl]
            npad_p = parent_arrays["live"].shape[0]
            pm = jnp.zeros(npad_p, jnp.float32).at[parent_map].add(pmask,
                                                                   mode="drop")
            pmask = ((pm > 0) & (parent_arrays["live"] > 0)).astype(jnp.float32)
        out = {"doc_count": jnp.sum(pmask)}
        for i, sub in enumerate(subs):
            res = emit_agg(sub, parent_arrays, params, pmask, None)
            if res:
                out[f"sub{i}"] = res
        return out

    if kind == "children_agg":
        _, prefix, cf, subs = spec
        g = params[f"{prefix}_gmatch"]
        pslot = params[f"{prefix}_pslot"]
        valid = pslot >= 0
        idx = jnp.clip(pslot, 0, g.shape[0] - 1)
        cfm = emit(cf, seg_arrays, params).matched
        cmask = (valid & (g[idx] > 0) & (cfm > 0)
                 & (seg_arrays["live"] > 0)).astype(jnp.float32)
        out = {"doc_count": jnp.sum(cmask)}
        for i, sub in enumerate(subs):
            res = emit_agg(sub, seg_arrays, params, cmask, None)
            if res:
                out[f"sub{i}"] = res
        return out

    if kind == "parent_agg":
        from jax import lax

        _, prefix, pf, subs = spec
        base = params[f"{prefix}_base"]
        cnt = lax.dynamic_slice(params[f"{prefix}_gmatch"], (base,), (ndocs_pad,))
        pfm = emit(pf, seg_arrays, params).matched
        pmask = ((cnt > 0) & (pfm > 0)
                 & (seg_arrays["live"] > 0)).astype(jnp.float32)
        out = {"doc_count": jnp.sum(pmask)}
        for i, sub in enumerate(subs):
            res = emit_agg(sub, seg_arrays, params, pmask, None)
            if res:
                out[f"sub{i}"] = res
        return out

    if kind == "composite_mv":
        _, prefix, field, nb, subs = spec
        kw = seg_arrays["keyword"][field]
        out = {"counts": agg_ops.terms_counts(kw, match, nb)}
        for i, sub in enumerate(subs):
            if sub and sub[0] == "stats":
                _, sprefix, sfield, col_exists = sub
                if col_exists:
                    col = seg_arrays["numeric"][sfield]
                    out[f"sub{i}"] = agg_ops.terms_sub_metric(
                        kw, match, col["f32"], col["present"], nb)
        return out

    if kind == "composite":
        _, prefix, infos, total, subs = spec
        combined = jnp.zeros(ndocs_pad, jnp.int32)
        valid = (match > 0) & (seg_arrays["live"] > 0)
        for si, (stype, field, n, min_b, interval, cal) in enumerate(infos):
            if stype == "terms":
                o = seg_arrays["keyword"][field]["min_ord"]
            elif stype == "hist":
                col = seg_arrays["numeric"][field]
                o = jnp.floor(col["f32"] / interval).astype(jnp.int32) - min_b
                o = jnp.where(col["present"] & (o >= 0) & (o < n), o, -1)
            else:  # date
                o = params[f"{prefix}_s{si}"][:ndocs_pad]
            valid = valid & (o >= 0)
            combined = combined * n + jnp.maximum(o, 0)
        w = valid.astype(jnp.float32)
        b = jnp.where(valid, combined, total)
        out = {"counts": jnp.zeros(total, jnp.float32).at[b].add(w, mode="drop")}
        for i, sub in enumerate(subs):
            out.update(_emit_bucketed_sub(jnp, sub, i, b, total, seg_arrays,
                                          match * w))
        return out

    if kind == "matrix_stats":
        _, prefix, fields, exists = spec
        if not fields or not all(exists):
            return {"count": jnp.float32(0)}
        cols = [seg_arrays["numeric"][f] for f in fields]
        present_all = match > 0
        for c in cols:
            present_all = present_all & c["present"]
        w = present_all.astype(jnp.float32)
        X = jnp.stack([c["f32"] for c in cols])          # [k, ndocs]
        X = X - params[f"{prefix}_shift"][:, None]       # center (see prepare)
        Xw = X * w[None, :]
        out = {"count": jnp.sum(w),
               "s1": Xw.sum(axis=1),
               "s2": (Xw * X).sum(axis=1),
               "s3": (Xw * X * X).sum(axis=1),
               "s4": (Xw * X * X * X).sum(axis=1),
               # pairwise Σ w·x_i·x_j rides the MXU
               "xy": jnp.dot(Xw, X.T, preferred_element_type=jnp.float32),
               "shift": params[f"{prefix}_shift"]}
        return out

    if kind == "terms":
        _, prefix, field, nvocab_pad, subs = spec
        kw = seg_arrays["keyword"][field]
        out = {"counts": agg_ops.terms_counts(kw, match, nvocab_pad)}
        for i, sub in enumerate(subs):
            if sub and sub[0] == "stats":
                _, sprefix, sfield, col_exists = sub
                if col_exists:
                    col = seg_arrays["numeric"][sfield]
                    out[f"sub{i}"] = agg_ops.terms_sub_metric(
                        kw, match, col["f32"], col["present"], nvocab_pad)
        return out

    if kind == "hist":
        _, prefix, field, interval, offset, min_b, nb, subs = spec
        col = seg_arrays["numeric"][field]
        w = match * jnp.where(col["present"], 1.0, 0.0)
        b = jnp.floor((col["f32"] - offset) / interval).astype(jnp.int32) - min_b
        b = jnp.where((b >= 0) & (b < nb) & (w > 0), b, nb)
        out = {"counts": jnp.zeros(nb, jnp.float32).at[b].add(w, mode="drop")}
        for i, sub in enumerate(subs):
            out.update(_emit_bucketed_sub(jnp, sub, i, b, nb, seg_arrays, match))
        return out

    if kind == "date_hist":
        _, prefix, field, interval_ms, offset_ms, calendar, min_b, nb, subs = spec
        b_all = params[f"{prefix}_dbuckets"][:ndocs_pad]
        w = match * jnp.where(b_all >= 0, 1.0, 0.0)
        b = jnp.where((b_all >= 0) & (w > 0), b_all, nb)
        out = {"counts": jnp.zeros(nb, jnp.float32).at[b].add(w, mode="drop")}
        for i, sub in enumerate(subs):
            out.update(_emit_bucketed_sub(jnp, sub, i, b, nb, seg_arrays, match))
        return out

    if kind == "range":
        _, prefix, field, keys, col_exists, subs, bounds = spec
        if not col_exists:
            return {}
        col = seg_arrays["numeric"][field]
        out = {"counts": agg_ops.range_counts(col["f32"], col["present"], match,
                                              params[f"{prefix}_lows"],
                                              params[f"{prefix}_highs"])}
        for ri in range(len(keys)):
            rmask = agg_ops.float_range_mask if False else None
            lo = params[f"{prefix}_lows"][ri]
            hi = params[f"{prefix}_highs"][ri]
            bucket_match = match * ((col["f32"] >= lo) & (col["f32"] < hi) &
                                    col["present"]).astype(jnp.float32)
            for i, sub in enumerate(subs):
                res = emit_agg(sub, seg_arrays, params, bucket_match, scores)
                if res:
                    out[f"r{ri}_sub{i}"] = res
        return out

    if kind == "geo_range":
        _, prefix, field, keys, col_exists, subs, _disp = spec
        if not col_exists:
            return {}
        geo = seg_arrays["geo"][field]
        dist = ops.geo_distance_vec(geo, params[f"{prefix}_olat"],
                                    params[f"{prefix}_olon"])
        out = {"counts": agg_ops.range_counts(dist, geo["present"], match,
                                              params[f"{prefix}_lows"],
                                              params[f"{prefix}_highs"])}
        for ri in range(len(keys)):
            lo = params[f"{prefix}_lows"][ri]
            hi = params[f"{prefix}_highs"][ri]
            bucket_match = match * ((dist >= lo) & (dist < hi) &
                                    geo["present"]).astype(jnp.float32)
            for i, sub in enumerate(subs):
                res = emit_agg(sub, seg_arrays, params, bucket_match, scores)
                if res:
                    out[f"r{ri}_sub{i}"] = res
        return out

    if kind == "filter":
        _, prefix, fspec, subs = spec
        fmask = emit(fspec, seg_arrays, params).matched
        bucket_match = match * fmask.astype(jnp.float32)
        out = {"count": jnp.sum(bucket_match)}
        for i, sub in enumerate(subs):
            res = emit_agg(sub, seg_arrays, params, bucket_match, scores)
            if res:
                out[f"sub{i}"] = res
        return out

    if kind == "filters":
        _, prefix, fspecs, subs = spec
        out = {}
        for ki, (key, fspec) in enumerate(fspecs):
            fmask = emit(fspec, seg_arrays, params).matched
            bucket_match = match * fmask.astype(jnp.float32)
            entry = {"count": jnp.sum(bucket_match)}
            for i, sub in enumerate(subs):
                res = emit_agg(sub, seg_arrays, params, bucket_match, scores)
                if res:
                    entry[f"sub{i}"] = res
            out[f"k{ki}"] = entry
        return out

    if kind == "global":
        _, prefix, subs = spec
        gmatch = seg_arrays["live"]
        out = {"count": jnp.sum(gmatch)}
        for i, sub in enumerate(subs):
            res = emit_agg(sub, seg_arrays, params, gmatch, scores)
            if res:
                out[f"sub{i}"] = res
        return out

    if kind == "missing":
        _, prefix, field, src, subs = spec
        if src == "numeric":
            present = seg_arrays["numeric"][field]["present"]
        elif src == "keyword":
            present = seg_arrays["keyword"][field]["min_ord"] >= 0
        else:
            present = jnp.zeros(ndocs_pad, bool)
        bucket_match = match * (~present).astype(jnp.float32)
        out = {"count": jnp.sum(bucket_match)}
        for i, sub in enumerate(subs):
            res = emit_agg(sub, seg_arrays, params, bucket_match, scores)
            if res:
                out[f"sub{i}"] = res
        return out

    if kind == "stats":
        _, prefix, field, col_exists = spec
        if not col_exists:
            return {"empty": jnp.float32(0)}
        col = seg_arrays["numeric"][field]
        count, s, mn, mx, ssq = agg_ops.stats_agg(col["f32"], col["present"], match)
        return {"count": count, "sum": s, "min": mn, "max": mx, "sumsq": ssq}

    if kind == "vc_keyword":
        _, prefix, field = spec
        return {"count": agg_ops.value_count_keyword(seg_arrays["keyword"][field], match)}

    if kind == "card_kw":
        _, prefix, field, nvocab_pad = spec
        return {"registers": agg_ops.cardinality_keyword_registers(
            seg_arrays["keyword"][field], match, nvocab_pad,
            params[f"{prefix}_hashes"], HLL_LOG2M)}

    if kind == "card_num":
        _, prefix, field, col_exists = spec
        if not col_exists:
            return {"registers": jnp.zeros(1 << HLL_LOG2M, jnp.int32)}
        col = seg_arrays["numeric"][field]
        return {"registers": agg_ops.cardinality_numeric_registers(
            col["f32"], col["present"], match, HLL_LOG2M)}

    if kind in ("pctl", "pctl_ranks"):
        _, prefix, field, col_exists, _pv = spec
        if not col_exists:
            return {"hist": jnp.zeros(agg_ops.DD_NBINS, jnp.float32)}
        col = seg_arrays["numeric"][field]
        return {"hist": agg_ops.ddsketch_hist(col["f32"], col["present"], match)}

    if kind == "top_hits":
        _, prefix, size = spec
        return {"top_hits_marker": jnp.float32(size)}  # resolved host-side

    if kind == "dsampler":
        _, prefix, shard_size, dfield, maxper, use_kw, n_ord_pad, subs = spec
        # pass 1: the plain sampler's best-scoring shard_size matched docs
        if scores is None:
            sel = match
        else:
            masked = jnp.where(match > 0, scores, -jnp.inf)
            k = min(shard_size, ndocs_pad)
            vals, _ = jax.lax.top_k(masked, k)
            thr = vals[k - 1]
            thr = jnp.where(jnp.isfinite(thr), thr, -jnp.inf)
            sel = match * (masked >= thr).astype(jnp.float32)
        # pass 2: de-bias — keep at most max_docs_per_value docs per key
        # (reference DiversifiedAggregator): `maxper` rounds of per-key
        # argmax selection, ties to the lowest doc id (collapse machinery)
        if use_kw:
            ords = seg_arrays["keyword"][dfield]["min_ord"]
        else:
            ords = params[f"{prefix}_dords"][:ndocs_pad]
        g = jnp.where(ords >= 0, ords, n_ord_pad - 1).astype(jnp.int32)
        g = jnp.clip(g, 0, n_ord_pad - 1)
        sc = scores if scores is not None else jnp.zeros(ndocs_pad, jnp.float32)
        # docs without a key are each their own group (reference: only keyed
        # docs dedup); they bypass the rounds and stay selected
        keyed = ords >= 0
        remaining = jnp.where((sel > 0) & keyed, sc, -jnp.inf)
        doc_iota = jnp.arange(ndocs_pad, dtype=jnp.int32)
        chosen = sel * (~keyed).astype(jnp.float32)
        for _round in range(maxper):
            gbest = jnp.full(n_ord_pad, -jnp.inf, jnp.float32).at[g].max(remaining)
            cand = jnp.where(jnp.isfinite(remaining)
                             & (remaining == gbest[g]),
                             doc_iota, jnp.int32(2**31 - 1))
            gdoc = jnp.full(n_ord_pad, 2**31 - 1, jnp.int32).at[g].min(cand)
            pick = (doc_iota == gdoc[g]) & jnp.isfinite(remaining)
            chosen = chosen + pick.astype(jnp.float32)
            remaining = jnp.where(pick, -jnp.inf, remaining)
        out = {"doc_count": jnp.sum(chosen)}
        for i, sub in enumerate(subs):
            res = emit_agg(sub, seg_arrays, params, chosen, scores)
            if res:
                out[f"sub{i}"] = res
        return out

    if kind == "wavg":
        _, prefix, vf, wf, v_ok, w_ok, has_vm, has_wm = spec
        if (not v_ok and not has_vm) or (not w_ok and not has_wm):
            return {"vwsum": jnp.float32(0), "wsum": jnp.float32(0),
                    "count": jnp.float32(0)}
        if v_ok:
            vcol = seg_arrays["numeric"][vf]
            v, vp = vcol["f32"], vcol["present"]
        else:  # absent column + configured missing default: all docs default
            v = jnp.zeros(ndocs_pad, jnp.float32)
            vp = jnp.zeros(ndocs_pad, bool)
        if w_ok:
            wcol = seg_arrays["numeric"][wf]
            w, wp = wcol["f32"], wcol["present"]
        else:
            w = jnp.zeros(ndocs_pad, jnp.float32)
            wp = jnp.zeros(ndocs_pad, bool)
        vw, ws, cnt = agg_ops.weighted_avg_agg(
            v, vp, w, wp, match,
            params[f"{prefix}_vmiss"], params[f"{prefix}_wmiss"],
            has_vm, has_wm)
        return {"vwsum": vw, "wsum": ws, "count": cnt}

    if kind == "mad":
        _, prefix, field, col_exists = spec
        if not col_exists:
            return {"hist": jnp.zeros(agg_ops.DD_NBINS, jnp.float32)}
        col = seg_arrays["numeric"][field]
        return {"hist": agg_ops.ddsketch_hist(col["f32"], col["present"], match)}

    if kind == "geo_stat":
        _, prefix, gkind, field, col_exists = spec
        if not col_exists:
            return {"count": jnp.float32(0)}
        g = seg_arrays["geo"][field]
        if gkind == "geo_bounds":
            top, bottom, left, right, count = agg_ops.geo_bounds_agg(
                g["lat"], g["lon"], g["present"], match)
            return {"top": top, "bottom": bottom, "left": left,
                    "right": right, "count": count}
        slat, slon, count = agg_ops.geo_centroid_agg(
            g["lat"], g["lon"], g["present"], match)
        return {"slat": slat, "slon": slon, "count": count}

    if kind == "ip_range":
        _, prefix, field, keys, bounds, open_lo, open_hi, col_exists, subs = spec
        nr = len(keys)
        if not col_exists:
            out = {"counts": jnp.zeros(nr, jnp.float32)}
            return out
        col = seg_arrays["numeric"][field]
        iplo = params[f"{prefix}_iplo"]
        iphi = params[f"{prefix}_iphi"]
        out = {}
        counts = []
        for ri in range(nr):
            m = col["present"]
            if not open_lo[ri]:
                ge = ops.int64_range_mask(col, iplo[0, ri], iplo[1, ri],
                                          jnp.int32(2**31 - 1),
                                          jnp.int32(2**31 - 1), True, True)
                m = m & ge
            if not open_hi[ri]:
                lt = ops.int64_range_mask(col, jnp.int32(-2**31),
                                          jnp.int32(-2**31),
                                          iphi[0, ri], iphi[1, ri],
                                          True, False)
                m = m & lt
            sel = match * m.astype(jnp.float32)
            counts.append(jnp.sum(sel))
            for i, sub in enumerate(subs):
                res = emit_agg(sub, seg_arrays, params, sel, scores)
                if res:
                    out[f"r{ri}_sub{i}"] = res
        out["counts"] = jnp.stack(counts)
        return out

    if kind == "multi_terms":
        _, prefix, nord_pad, nvocab, subs = spec
        ords = params[f"{prefix}_mords"][:ndocs_pad]
        out = {"counts": agg_ops.ord_counts(ords, match, nord_pad)}
        b = jnp.where(ords >= 0, ords, nord_pad)
        for i, sub in enumerate(subs):
            out.update(_emit_bucketed_sub(jnp, sub, i, b, nord_pad,
                                          seg_arrays, match))
        return out

    if kind == "adjacency":
        _, prefix, fspecs, sep, subs = spec
        masks = []
        out = {}
        for key, fs in fspecs:
            masks.append((key, emit(fs, seg_arrays, params).matched))
        idx = 0
        for ai, (ka, ma) in enumerate(masks):
            sel = match * ma.astype(jnp.float32)
            out[f"c{idx}"] = jnp.sum(sel)
            for i, sub in enumerate(subs):
                res = emit_agg(sub, seg_arrays, params, sel, scores)
                if res:
                    out[f"c{idx}_sub{i}"] = res
            idx += 1
        for ai, (ka, ma) in enumerate(masks):
            for bi in range(ai + 1, len(masks)):
                kb, mb = masks[bi]
                sel = match * (ma & mb).astype(jnp.float32)
                out[f"c{idx}"] = jnp.sum(sel)
                for i, sub in enumerate(subs):
                    res = emit_agg(sub, seg_arrays, params, sel, scores)
                    if res:
                        out[f"c{idx}_sub{i}"] = res
                idx += 1
        return out

    if kind == "auto_date_hist":
        _, prefix, field, interval_ms, target, min_b, nb, subs = spec
        bucket_ids = params[f"{prefix}_dbuckets"][:ndocs_pad]
        w = match * (bucket_ids >= 0).astype(jnp.float32)
        b = jnp.where(w > 0, bucket_ids, nb)
        out = {"counts": jnp.zeros(nb, jnp.float32).at[b].add(w, mode="drop")}
        for i, sub in enumerate(subs):
            out.update(_emit_bucketed_sub(jnp, sub, i, b, nb, seg_arrays,
                                          match))
        return out

    if kind in ("scripted", "sig_text"):
        # host-resolved: the partial needs the dense match mask
        return {"match_mask": match, "score_vec": (scores if scores is not None
                                                   else jnp.zeros_like(match))}

    raise ValueError(f"cannot emit aggregation spec [{kind}]")


def _emit_bucketed_sub(jnp, sub, i: int, bucket_ids, nb: int, seg_arrays, match):
    """Metric sub-agg under an ordinal bucket agg: scatter into per-bucket
    accumulators."""
    if not sub or sub[0] != "stats":
        return {}
    _, sprefix, sfield, col_exists = sub
    if not col_exists:
        return {}
    col = seg_arrays["numeric"][sfield]
    w = match * jnp.where(col["present"], 1.0, 0.0)
    v = col["f32"]
    b = jnp.where(w > 0, bucket_ids, nb)
    sums = jnp.zeros(nb, jnp.float32).at[b].add(w * v, mode="drop")
    cnts = jnp.zeros(nb, jnp.float32).at[b].add(w, mode="drop")
    mins = jnp.full(nb, 3.4e38, jnp.float32).at[b].min(
        jnp.where(w > 0, v, 3.4e38), mode="drop")
    maxs = jnp.full(nb, -3.4e38, jnp.float32).at[b].max(
        jnp.where(w > 0, v, -3.4e38), mode="drop")
    sumsq = jnp.zeros(nb, jnp.float32).at[b].add(w * v * v, mode="drop")
    return {f"sub{i}": (sums, cnts, mins, maxs, sumsq)}


# =====================================================================
# executor: jitted per-spec program
# =====================================================================

# filter-context mask cache (reference IndicesQueryCache: bitsets cached per
# (segment, filter)): dense bool masks keyed by (segment uid, live_gen,
# filter spec, param digest), device-resident, LRU-evicted
_FILTER_MASK_CACHE: "OrderedDict[tuple, Any]" = __import__(
    "collections").OrderedDict()
_FILTER_MASK_MAX_BYTES = 256 << 20   # byte-bounded like IndicesQueryCache
_FILTER_MASK_BYTES = [0]
_FILTER_HASH_BYTE_CAP = 1 << 20   # don't hash megabyte param sets
# msearch's per-body fallback searches on a thread pool; LRU mutation and
# the byte counter must not interleave (RLock: build path can re-enter via
# nested cached filters)
_FILTER_MASK_LOCK = __import__("threading").RLock()


def filter_mask_cache_stats() -> dict:
    return {"entries": len(_FILTER_MASK_CACHE),
            "bytes": _FILTER_MASK_BYTES[0]}


def _purge_masks_for_uid(uid: int) -> None:
    """Weakref finalizer: a dropped segment's masks can never hit again."""
    with _FILTER_MASK_LOCK:
        for k in [k for k in _FILTER_MASK_CACHE if k[0] == uid]:
            _FILTER_MASK_BYTES[0] -= _FILTER_MASK_CACHE[k].nbytes
            del _FILTER_MASK_CACHE[k]


@_instrumented_program_cache("mask", maxsize=256)
def _build_mask_executor(spec):
    import jax

    def run(seg_arrays, params):
        return emit(spec, seg_arrays, params).matched

    return jax.jit(run)


# =====================================================================
# device phase-2 rescore programs (search/fastpath.py escalation rung)
# =====================================================================
#
# The candidate-union rescore launches with a dynamic candidate count per
# query (anything from a few head hits to the full T*4*L_HEAD tier-2
# union). Shapes are canonicalized HERE — pow2 candidate bucket with a
# floor, pow2 query batch in the caller — so the jit cache sees a bounded
# spec space (~10 C buckets x 4 T buckets per similarity) instead of one
# program per candidate count: the same recompile-storm discipline as the
# scoring executors above.

RESCORE_C_MIN = 1 << 8          # pad floor: tiny unions share one program
RESCORE_C_MAX = 1 << 17         # == MAX_T * 4 * L_HEAD (deepest tier-2
                                # union); beyond -> caller's host fallback


def rescore_cand_bucket(n: int) -> Optional[int]:
    """Candidate-axis pow2 bucket for a union of `n` ids; None when the
    union exceeds every compiled variant (host pass instead)."""
    if n <= 0 or n > RESCORE_C_MAX:
        return None
    return min(max(next_pow2(n), RESCORE_C_MIN), RESCORE_C_MAX)


@_instrumented_program_cache(
    "rescore", maxsize=64,
    shape_of=lambda T, C, k1, b: f"T{T}xC{C}")
def build_rescore_program(T: int, C: int, k1: float, b: float):
    """Cached callable for one (term-slot, candidate-bucket, similarity)
    shape of ops/rescore.exact_rescore_batch."""
    from ..ops.rescore import exact_rescore_batch

    def run(d_docs, d_tfdl, starts, lens, weights, avgdl, cand):
        return exact_rescore_batch(d_docs, d_tfdl, starts, lens, weights,
                                   avgdl, cand, T=T, C=C, k1=k1, b=b)

    return run


# ---------------------------------------------------------------------
# codec-v2 impact program (search/impactpath.py first pass)
# ---------------------------------------------------------------------
#
# Program variants are KEYED BY CODEC layout: (impact bit width, block
# slot bucket, gather bucket, candidate window). The program is the
# whole eager hot loop — integer impact gather over the host-pruned
# block windows, one dequant multiply, scatter-add, masked top-C — with
# no tf/doclen math anywhere in the trace.


@_instrumented_program_cache(
    "impact", maxsize=128,
    shape_of=lambda B, bucket, C, bits: f"B{B}xG{bucket}xC{C}u{bits}")
def build_impact_program(B: int, bucket: int, C: int, bits: int):
    import jax

    def run(d_docs, d_impacts, live, bstart, blen, bweight, msm):
        import jax.numpy as jnp
        ndocs_pad = live.shape[0]
        sm = ops.impact_score_blocks(d_docs, d_impacts, live, bstart,
                                     blen, bweight, bucket, ndocs_pad)
        ok = (sm.count >= msm) & (live > 0)
        masked = jnp.where(ok, sm.scores, ops.NEG_INF)
        total = jnp.sum(ok.astype(jnp.int32))
        kk = min(C, ndocs_pad)
        vals, idx = jax.lax.top_k(masked, kk)
        return vals, idx, total

    return jax.jit(run)


# spec kinds whose second element is a node id (everything `prepare`
# returns with a nid head). Only these are renumbered — other (str, int)
# tuples (e.g. function-score sub-specs ("fvf", i, ...)) keep their ints.
_NID_KINDS = frozenset({
    "terms", "xterms", "phrase", "match_all", "match_none", "range",
    "exists", "ids", "bool", "const", "dismax", "boosting", "fnscore",
    "nested", "has_child", "has_parent", "rank_feature_col",
    "rank_feature_post", "sparse_dot", "distfeat_date", "distfeat_geo",
    "percolate", "script", "scriptscore", "knn", "span_host", "geodist",
    "geobox", "terms_set", "pinned", "combined", "geopoly", "geoshape",
    "cached_mask",
})


def _canon_spec(spec, mapping: Dict[int, int]):
    """Renumber node ids by first appearance so structurally identical
    specs hash equal across queries (nids are a global counter)."""
    if (isinstance(spec, tuple) and len(spec) >= 2
            and isinstance(spec[0], str) and isinstance(spec[1], int)
            and spec[0] in _NID_KINDS):
        cid = mapping.setdefault(spec[1], len(mapping))
        return (spec[0], cid) + tuple(_canon_spec(x, mapping)
                                      for x in spec[2:])
    if isinstance(spec, tuple):
        return tuple(_canon_spec(x, mapping) for x in spec)
    return spec


def _canon_param_key(key: str, mapping: Dict[int, int]) -> str:
    if key.startswith("q"):
        head, _, rest = key.partition("_")
        try:
            nid = int(head[1:])
        except ValueError:
            return key
        if nid in mapping:
            return f"q{mapping[nid]}_{rest}"
    return key


def filter_mask_for(node: LNode, seg: Segment, ctx: ShardContext):
    """Dense bool match mask for a filter-context clause, through the mask
    cache. Returns (mask np.bool_[ndocs_pad], cache_key, spec, local_params);
    mask/key are None when the clause's params are too big to hash cheaply
    (caller falls back to inlining spec+params into its own program)."""
    local: Dict[str, Any] = {}
    spec = prepare(node, seg, ctx, local)
    key, mapping = _filter_cache_key(spec, local, seg)
    if key is None:
        return None, None, spec, local
    mask = _mask_for_key(key, spec, local, mapping, seg,
                         needs=node_needs(node))
    return mask, key, spec, local


def node_needs(node: LNode) -> Optional[Dict[str, set]]:
    """Per-group field sets a filter node's program reads — the mask
    executor then ships ONLY those columns to device (Segment.pruned_arrays)
    instead of the whole segment. None = unknown node kind, use the full
    arrays."""
    needs: Dict[str, set] = {"postings": set(), "numeric": set(),
                             "keyword": set(), "geo": set(),
                             "doc_lens": set()}

    def walk(n) -> bool:
        if n is None:
            return True
        if isinstance(n, (LMatchAll, LMatchNone, LIds)):
            return True
        if isinstance(n, (LTerms, LExpandTerms)):
            needs["postings"].add(n.field)
            needs["doc_lens"].add(n.field)
            return True
        if isinstance(n, LRange):
            needs["numeric"].add(n.field)
            return True
        if isinstance(n, LExists):
            for g in ("postings", "numeric", "keyword", "geo"):
                needs[g].add(n.field)
            return True
        if isinstance(n, (LGeoDist, LGeoBox)):
            needs["geo"].add(n.field)
            return True
        if isinstance(n, LConstScore):
            return walk(n.child)
        if isinstance(n, LBool):
            return all(walk(c) for c in
                       n.musts + n.shoulds + n.must_nots + n.filters)
        return False     # unknown kind: caller ships the full arrays

    return needs if walk(node) else None


def _filter_cache_key(spec, local: dict, seg: Segment):
    """-> ((uid, live_gen, digest), nid-mapping) or (None, mapping)."""
    import hashlib

    # hash the nid-canonicalized spec + this segment's param payload
    mapping: Dict[int, int] = {}
    h = hashlib.blake2b(repr(_canon_spec(spec, mapping)).encode(),
                        digest_size=16)
    total = 0
    for k0 in sorted(local, key=lambda k: _canon_param_key(k, mapping)):
        v = local[k0]
        arr = np.asarray(v)
        total += arr.nbytes
        if total > _FILTER_HASH_BYTE_CAP:
            return None, mapping   # too big to hash cheaply: no caching
        h.update(_canon_param_key(k0, mapping).encode())
        h.update(arr.tobytes())
    return (seg.uid, seg.live_gen, h.hexdigest()), mapping


def _prepare_cached_filter(node: LNode, seg: Segment, ctx: ShardContext,
                           params: dict):
    """Prepare a filter-context clause through the mask cache: repeated
    filters (the classic "status:published + range" guardrails) reuse one
    device-resident bool mask instead of re-running their program."""
    mask, key, spec, local = filter_mask_for(node, seg, ctx)
    if mask is None:
        params.update(local)
        return spec
    nid = node.nid
    params[f"q{nid}_cached_mask"] = mask
    return ("cached_mask", nid)


def _mask_for_key(key, spec, local: dict, mapping: Dict[int, int],
                  seg: Segment, needs: Optional[Dict[str, set]] = None
                  ) -> np.ndarray:
    with _FILTER_MASK_LOCK:
        mask = _FILTER_MASK_CACHE.get(key)
        if mask is not None:
            _FILTER_MASK_CACHE.move_to_end(key)
            return mask
    if mask is None:
        # use whichever device already hosts this segment (replica copies
        # must not trigger a default-device re-host just for the cache)
        dev_key = None
        dc = seg._device_cache   # snapshot: pressure eviction swaps the dict
        if dc and None not in dc:
            dev_key = next(iter(dc))
        # jit against the CANONICAL spec/params so structurally identical
        # filters share one compiled program across requests
        canon = _canon_spec(spec, dict(mapping))
        canon_local = {_canon_param_key(k, mapping): v
                       for k, v in local.items()}
        exe = _build_mask_executor(canon)
        arrays = (seg.pruned_arrays(dev_key, needs) if needs is not None
                  else seg.device_arrays(dev_key))
        # host-resident bools: safe to feed executors on ANY device
        mask = np.asarray(exe(arrays, canon_local))
        with _FILTER_MASK_LOCK:
            # two threads can race the same miss: keep the winner's entry so
            # the byte counter never double-counts one key
            prev = _FILTER_MASK_CACHE.get(key)
            if prev is not None:
                _FILTER_MASK_CACHE.move_to_end(key)
                return prev
            _FILTER_MASK_CACHE[key] = mask
            _FILTER_MASK_BYTES[0] += mask.nbytes
            if not hasattr(seg, "_mask_fin"):
                import weakref
                seg._mask_fin = weakref.finalize(seg, _purge_masks_for_uid,
                                                 seg.uid)
            while _FILTER_MASK_BYTES[0] > _FILTER_MASK_MAX_BYTES:
                _k, _v = _FILTER_MASK_CACHE.popitem(last=False)
                _FILTER_MASK_BYTES[0] -= _v.nbytes
    return mask


def prepare_collapse(collapse: Optional[dict], seg: Segment, ctx: ShardContext,
                     params: dict):
    """-> hashable collapse spec for _build_executor, or None. Keyword fields
    collapse on the device-resident min-ord column; numeric fields on the
    host-built per-segment value-rank ords (exact for 64-bit values)."""
    if not collapse:
        return None
    field = ctx.mappings.aliases.get(collapse["field"], collapse["field"])
    if field in seg.keyword_cols:
        n_ord_pad = next_pow2(len(seg.keyword_cols[field].vocab) + 1)
        return ("collapse", field, n_ord_pad, True)
    if field in seg.numeric_cols:
        col = seg.numeric_cols[field]
        ords = col.sort_ords()
        _p(params, "collapse_ords",
           np.pad(ords, (0, seg.ndocs_pad - len(ords)), constant_values=-1))
        n_ord_pad = next_pow2(seg.ndocs + 1)
        return ("collapse", field, n_ord_pad, False)
    # unmapped in this segment: every doc falls into the null group
    _p(params, "collapse_ords", np.full(seg.ndocs_pad, -1, np.int32))
    return ("collapse", field, 2, False)


@_instrumented_program_cache("executor", maxsize=512)
def _build_executor(full_spec):
    import jax

    return jax.jit(_executor_run_fn(full_spec))


def _executor_run_fn(full_spec):
    """The raw (unjitted) per-segment executor body, jitted by
    `_build_executor` — the ONE program both the direct path and the
    coalesced knn batch (`launch_segment_batch`) invoke, which is what
    makes a batched page byte-identical to its direct sibling."""
    (query_spec, sort_spec, agg_specs, k_pad, named_specs, has_after,
     collapse_spec) = full_spec

    def run(seg_arrays, params):
        import jax.numpy as jnp

        sm = emit(query_spec, seg_arrays, params)
        live = seg_arrays["live"]
        key = emit_sort_key(sort_spec, seg_arrays, params, sm.scores)
        matched = sm.matched
        if has_after:
            # search_after: strictly below the cursor in ranking order
            matched = matched & (key < params["after_key"])
        sm = ops.ScoredMask(sm.scores, matched.astype(jnp.float32))
        if collapse_spec is not None:
            _, cfield, n_ord_pad, use_kw = collapse_spec
            if use_kw:
                ords = seg_arrays["keyword"][cfield]["min_ord"]
            else:
                ords = params["collapse_ords"]
            vals, idx = ops.collapse_topk(key, sm.matched, live, ords,
                                          n_ord_pad, k_pad)
        else:
            vals, idx = ops.topk_docs(key, sm.matched, live, k_pad)
        out = {
            "topk_key": vals,
            "topk_idx": idx,
            "topk_scores": sm.scores[idx],
            "total": ops.total_hits(sm.matched, live),
            "max_score": jnp.max(jnp.where(sm.matched & (live > 0), sm.scores, -jnp.inf)),
        }
        match_f = sm.matched.astype(jnp.float32) * jnp.where(live > 0, 1.0, 0.0)
        aggs = {}
        for name, aspec in agg_specs:
            res = emit_agg(aspec, seg_arrays, params, match_f, sm.scores)
            if res:  # oslint: disable=OSL201 -- host dict truthiness, trace-static
                aggs[name] = res
        if aggs:  # oslint: disable=OSL201 -- host dict truthiness, trace-static
            out["aggs"] = aggs
        named = {}
        for nm, nspec in named_specs:
            nsm = emit(nspec, seg_arrays, params)
            named[nm] = nsm.matched[idx]
        if named:  # oslint: disable=OSL201 -- host dict truthiness, trace-static
            out["named"] = named
        return out

    return run


def launch_segment_batch(prepared: list, seg_arrays: dict):
    """LAUNCH a coalesced batch of per-query executor programs over one
    segment: every query's invocation of THE direct-path program
    (`_build_executor`, shared jit cache — structurally identical
    queries compile once) enqueues here UNFETCHED; the returned closure
    performs one deferred `device_get` sweep for the whole batch
    (oslint OSL504). `prepared` is a list of `(full_spec, params)`
    already canonicalized via `canon_query`.

    Deliberately NOT a vmapped mega-program: vmap's batched dot_general
    lands ~1 ULP away from the scalar program's contraction on real
    backends, and a scheduler-coalesced page must stay BYTE-identical
    to its scheduler-off sibling (the f32 single-domain serving
    contract, docs/FASTPATH.md) — the batching win here is cross-request
    coalescing + async launch pipelining, with the score domain pinned
    by construction."""
    import jax

    pending = []
    for full_spec, cparams in prepared:
        exe = _build_executor(full_spec)
        pending.append(exe(seg_arrays, cparams))   # invocation, no sync

    def _fetch():
        return jax.device_get(pending)

    return _fetch


def canon_query(query_spec, sort_spec, k_pad: int, params: dict):
    """Canonicalize one prepared (query, sort, k_pad) triple + params the
    way `run_segment` does — the grouping key for batched launches."""
    mapping: Dict[int, int] = {}
    full = _canon_spec((query_spec, sort_spec, (), k_pad, (), False,
                        None), mapping)
    return full, {_canon_param_key(k, mapping): v
                  for k, v in params.items()}


def run_segment(query_spec, sort_spec, agg_specs, named_specs, k_pad: int,
                seg_arrays: dict, params: dict, has_after: bool = False,
                collapse_spec=None) -> dict:
    # canonicalize node ids (nids come from a global counter) so
    # structurally identical queries hit the same compiled executor instead
    # of recompiling per request — the XLA analog of Lucene's per-shape
    # query plan reuse
    mapping: Dict[int, int] = {}
    full = _canon_spec((query_spec, sort_spec, tuple(agg_specs), k_pad,
                        tuple(named_specs), has_after, collapse_spec),
                       mapping)
    cparams = {_canon_param_key(k, mapping): v for k, v in params.items()}
    exe = _build_executor(full)
    return exe(seg_arrays, cparams)


@_instrumented_program_cache("gather", maxsize=256)
def _build_gather_executor(query_spec):
    """Scores of a query at an explicit doc list (rescore second pass,
    reference `search/rescore/QueryRescorer.java`)."""
    import jax

    def run(seg_arrays, params):
        sm = emit(query_spec, seg_arrays, params)
        docs = params["gather_docs"]
        return sm.scores[docs], sm.matched[docs]

    return jax.jit(run)


def run_gather_scores(query_spec, seg_arrays: dict, params: dict, docs: np.ndarray):
    mapping: Dict[int, int] = {}
    canon = _canon_spec(query_spec, mapping)
    exe = _build_gather_executor(canon)
    params = {_canon_param_key(k, mapping): v for k, v in params.items()}
    params["gather_docs"] = docs
    return exe(seg_arrays, params)


@_instrumented_program_cache("agg", maxsize=128)
def _build_agg_executor(key):
    """Aggs-only program (no top-k): the shard-wide sampler re-threshold
    pass re-runs just the agg tree with a global threshold param."""
    import jax

    query_spec, agg_spec = key

    def run(seg_arrays, params):
        import jax.numpy as jnp

        sm = emit(query_spec, seg_arrays, params)
        live = seg_arrays["live"]
        match_f = sm.matched.astype(jnp.float32) * jnp.where(live > 0, 1.0, 0.0)
        return emit_agg(agg_spec, seg_arrays, params, match_f, sm.scores)

    return jax.jit(run)


def run_agg_only(query_spec, agg_spec, seg_arrays: dict, params: dict):
    mapping: Dict[int, int] = {}
    canon = _canon_spec((query_spec, agg_spec), mapping)
    cparams = {_canon_param_key(k, mapping): v for k, v in params.items()}
    return _build_agg_executor(canon)(seg_arrays, cparams)
