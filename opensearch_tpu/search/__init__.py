from .executor import ShardSearcher, search_shards
from .query_dsl import parse_query

__all__ = ["ShardSearcher", "search_shards", "parse_query"]
