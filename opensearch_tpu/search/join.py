"""Parent-child join index (reference: modules/parent-join, esp.
ParentJoinFieldMapper + ParentIdFieldMapper global ordinals).

The reference joins parent and child Lucene docs through global ordinals of
the parent-id field, rebuilt per index reader. Here the shard-level join is a
flat **global doc-slot space**: every segment gets a base offset (multiples of
`ndocs_pad`, so per-segment views are static slices), a doc's own slot is
`base + doc`, and each child doc stores the slot of its parent
(`parent_slot`, -1 when the parent id is unresolved). Query execution then
becomes two device passes (compiler.py): scatter child scores into slot space
(`.at[slot].add/max/min`), then per segment slice/gather the slot vectors —
no host loops in the scoring path.
"""

from __future__ import annotations

import weakref
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..index.segment import Segment, next_pow2


class JoinIndex:
    """Shard-level parent→slot maps over one immutable segment list.

    Segments are held via weakref so a cached JoinIndex never pins replaced
    segments' device arrays in HBM after a refresh/merge — the engine holds
    the strong refs for the segments any in-flight query actually uses."""

    def __init__(self, segments: List[Segment], join_field: str):
        self._seg_refs = [weakref.ref(s) for s in segments]
        self.join_field = join_field
        self.base: Dict[int, int] = {}
        off = 0
        for s in segments:
            self.base[s.uid] = off
            off += s.ndocs_pad
        self.gsize = next_pow2(max(off, 16))

        def locate(pid: str) -> int:
            # latest live copy of the parent wins (updates leave dead copies
            # in older segments, same as Lucene liveDocs)
            fallback = -1
            for s in segments:
                d = s.id2doc.get(pid)
                if d is not None:
                    if s.live[d]:
                        return self.base[s.uid] + d
                    if fallback < 0:
                        fallback = self.base[s.uid] + d
            return fallback

        self.parent_slot: Dict[int, np.ndarray] = {}
        for s in segments:
            arr = np.full(s.ndocs_pad, -1, np.int32)
            pcol = s.keyword_cols.get(f"{join_field}#parent")
            if pcol is not None and pcol.vocab:
                # resolve each distinct parent id once, then fan out by ordinal
                slot_of_ord = np.fromiter((locate(p) for p in pcol.vocab),
                                          np.int32, count=len(pcol.vocab))
                present = pcol.min_ord >= 0
                vals = np.where(present, pcol.min_ord, 0)
                arr[: s.ndocs] = np.where(present, slot_of_ord[vals], -1)
            self.parent_slot[s.uid] = arr
        self._children_sorted: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None

    @property
    def segments(self) -> List[Segment]:
        return [s for s in (r() for r in self._seg_refs) if s is not None]

    def seg_base(self, seg: Segment) -> int:
        return self.base.get(seg.uid, 0)

    def pslot(self, seg: Segment) -> np.ndarray:
        arr = self.parent_slot.get(seg.uid)
        if arr is None:
            arr = np.full(seg.ndocs_pad, -1, np.int32)
        return arr

    def slot_to_doc(self, slot: int) -> Optional[Tuple[Segment, int]]:
        for s in self.segments:
            b = self.base[s.uid]
            if b <= slot < b + s.ndocs_pad:
                d = slot - b
                return (s, d) if d < s.ndocs else None
        return None

    def children_of(self, gslot: int) -> List[Tuple[Segment, int]]:
        """All child docs whose parent occupies `gslot` (host reverse lookup
        for inner_hits/explain; the scoring path never calls this)."""
        if self._children_sorted is None:
            snapshot = self.segments  # fixed positional order for sg below
            slots, segi, docs = [], [], []
            for i, s in enumerate(snapshot):
                arr = self.parent_slot[s.uid][: s.ndocs]
                nz = np.nonzero(arr >= 0)[0]
                slots.append(arr[nz])
                segi.append(np.full(len(nz), i, np.int32))
                docs.append(nz.astype(np.int32))
            sl = np.concatenate(slots) if slots else np.empty(0, np.int32)
            sg = np.concatenate(segi) if segi else np.empty(0, np.int32)
            dc = np.concatenate(docs) if docs else np.empty(0, np.int32)
            order = np.argsort(sl, kind="stable")
            self._children_sorted = (sl[order], sg[order], dc[order],
                                     [weakref.ref(s) for s in snapshot])
        sl, sg, dc, refs = self._children_sorted
        a = int(np.searchsorted(sl, gslot, "left"))
        b = int(np.searchsorted(sl, gslot, "right"))
        out = []
        for i in range(a, b):
            s = refs[int(sg[i])]()
            if s is not None:
                out.append((s, int(dc[i])))
        return out


_cache: Dict[Tuple, JoinIndex] = {}


def get_join_index(segments: List[Segment], join_field: str) -> JoinIndex:
    key = (join_field, tuple((s.uid, s.live_gen) for s in segments))
    ji = _cache.get(key)
    if ji is None:
        ji = JoinIndex(segments, join_field)
        if len(_cache) >= 8:
            _cache.pop(next(iter(_cache)))
        _cache[key] = ji
    return ji
