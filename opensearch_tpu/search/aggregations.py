"""Aggregations: DSL parsing + cross-segment/shard merge + response shaping.
Analog of reference `search/aggregations/` (AggregatorFactories parse tree,
InternalAggregation#reduce, and the response XContent shapes).

Device emission lives in `compiler.py` (same jitted program as scoring);
this module is host-only: it defines the agg tree, merges per-segment
partials (the analog of InternalAggregation.reduce), and renders the
OpenSearch-shaped response JSON.

Design notes vs the reference:
- terms aggs are exact per shard (full ordinal bincount on device — no
  shard_size truncation error; doc_count_error_upper_bound is honestly 0).
- cardinality is device-side HyperLogLog (log2m=14) over value hashes —
  mergeable across segments and shards like the reference's HLL++.
- percentiles use a mergeable 4096-bin histogram sketch between index-wide
  column bounds instead of TDigest.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional

import numpy as np

BUCKET_KINDS = {"terms", "histogram", "date_histogram", "range", "date_range",
                "geo_distance",
                "filter", "filters", "global", "missing", "significant_terms",
                "sampler", "geohash_grid", "geotile_grid", "nested",
                "reverse_nested", "children", "parent", "composite",
                "ip_range", "rare_terms", "multi_terms", "adjacency_matrix",
                "auto_date_histogram", "significant_text",
                "diversified_sampler"}
METRIC_KINDS = {"min", "max", "sum", "avg", "stats", "extended_stats",
                "value_count", "cardinality", "percentiles",
                "percentile_ranks", "top_hits",
                "matrix_stats", "weighted_avg", "median_absolute_deviation",
                "geo_bounds", "geo_centroid", "scripted_metric"}
PIPELINE_KINDS = {"avg_bucket", "sum_bucket", "min_bucket", "max_bucket",
                  "stats_bucket", "cumulative_sum", "derivative", "bucket_script",
                  "bucket_selector", "moving_avg", "moving_fn", "serial_diff",
                  "percentiles_bucket", "bucket_sort"}


@dataclass
class AggNode:
    name: str
    kind: str
    body: dict
    subs: List["AggNode"] = dc_field(default_factory=list)
    pipelines: List["AggNode"] = dc_field(default_factory=list)
    # pipeline nodes whose buckets_path targets a refinement-resolved sub-agg
    # are deferred: the coordinator applies them AFTER bucket refinement
    # (executor._mark_deferred_pipelines / _apply_deferred_tree)
    deferred: bool = False


def parse_aggs(aggs: Optional[dict]) -> List[AggNode]:
    out: List[AggNode] = []
    if not aggs:
        return out
    for name, spec in aggs.items():
        sub_specs = spec.get("aggs", spec.get("aggregations"))
        kinds = [k for k in spec if k not in ("aggs", "aggregations", "meta")]
        if len(kinds) != 1:
            raise ValueError(f"aggregation [{name}] must define exactly one type")
        kind = kinds[0]
        if kind not in BUCKET_KINDS | METRIC_KINDS | PIPELINE_KINDS:
            raise ValueError(f"unknown aggregation type [{kind}]")
        node = AggNode(name, kind, spec[kind])
        children = parse_aggs(sub_specs)
        node.subs = [c for c in children if c.kind not in PIPELINE_KINDS]
        node.pipelines = [c for c in children if c.kind in PIPELINE_KINDS]
        if kind in METRIC_KINDS and node.subs:
            raise ValueError(f"metric aggregation [{name}] cannot have sub-aggregations")
        out.append(node)
    return out


# ---------------- merge (reduce) ----------------

def merge_partials(node: AggNode, partials: List[dict]) -> dict:
    """Merge per-segment/per-shard partials for one agg node (reference:
    InternalAggregation#reduce). Each partial is a host dict produced by the
    compiler's device run + segment context."""
    parts = [p for p in partials if p is not None]
    if not parts:
        return {}
    kind = node.kind
    if kind in ("terms", "geohash_grid", "geotile_grid", "rare_terms",
                "multi_terms"):
        return {"buckets": _acc_buckets(node, parts)}
    if kind in ("histogram", "date_histogram"):
        acc = {}
        for p in parts:
            for b, rec in p["buckets"].items():
                slot = acc.setdefault(b, {"doc_count": 0, "subs": []})
                slot["doc_count"] += rec["doc_count"]
                slot["subs"].append(rec.get("subs"))
        for b, slot in acc.items():
            slot["subs"] = _merge_sub_metrics(node.subs, slot["subs"])
        return {"buckets": acc, "interval": parts[0]["interval"],
                "offset": parts[0].get("offset", 0.0), "keyed_fmt": parts[0].get("keyed_fmt")}
    if kind in ("range", "date_range", "geo_distance", "filters", "ip_range",
                "adjacency_matrix"):
        acc = {}
        for p in parts:
            for key, rec in p["buckets"].items():
                slot = acc.setdefault(key, {"doc_count": 0, "subs": [], "meta": rec.get("meta")})
                slot["doc_count"] += rec["doc_count"]
                slot["subs"].append(rec.get("subs"))
        for key, slot in acc.items():
            slot["subs"] = _merge_subtrees(node.subs, slot["subs"])
        return {"buckets": acc}
    if kind in ("filter", "global", "missing", "sampler", "nested",
                "reverse_nested", "children", "parent",
                "diversified_sampler"):
        total = sum(p["doc_count"] for p in parts)
        subs = _merge_subtrees(node.subs, [p.get("subs") for p in parts])
        return {"doc_count": total, "subs": subs}
    if kind in ("significant_terms", "significant_text"):
        bg: Dict[Any, int] = {}
        for p in parts:
            for key, c in p["bg"].items():
                bg[key] = bg.get(key, 0) + c
        return {"buckets": _acc_buckets(node, parts), "bg": bg,
                "fg_total": sum(p["fg_total"] for p in parts),
                "bg_total": sum(p["bg_total"] for p in parts)}
    if kind == "weighted_avg":
        return {"vwsum": sum(p["vwsum"] for p in parts),
                "wsum": sum(p["wsum"] for p in parts),
                "count": sum(p["count"] for p in parts)}
    if kind == "median_absolute_deviation":
        hist = parts[0]["hist"].copy()
        for p in parts[1:]:
            hist += p["hist"]
        return {"hist": hist}
    if kind == "geo_bounds":
        live = [p for p in parts if p["count"] > 0]
        if not live:
            return {"count": 0}
        return {"count": sum(p["count"] for p in live),
                "top": max(p["top"] for p in live),
                "bottom": min(p["bottom"] for p in live),
                "left": min(p["left"] for p in live),
                "right": max(p["right"] for p in live)}
    if kind == "geo_centroid":
        return {"count": sum(p["count"] for p in parts),
                "slat": sum(p.get("slat", 0.0) for p in parts),
                "slon": sum(p.get("slon", 0.0) for p in parts)}
    if kind == "scripted_metric":
        return {"states": [s for p in parts for s in p["states"]]}
    if kind == "auto_date_histogram":
        # shards may have rounded at different intervals: coarsen everything
        # to the widest before accumulating (reference
        # InternalAutoDateHistogram#reduce)
        interval = max(p["interval_ms"] for p in parts)
        acc: Dict[Any, dict] = {}
        for p in parts:
            for key, rec in p["buckets"].items():
                ck = (int(key) // interval) * interval
                slot = acc.setdefault(ck, {"doc_count": 0, "subs": []})
                slot["doc_count"] += rec["doc_count"]
                slot["subs"].append(rec.get("subs"))
        for slot in acc.values():
            slot["subs"] = _merge_sub_metrics(node.subs, slot["subs"])
        return {"buckets": acc, "interval_ms": interval}
    if kind == "composite":
        return {"buckets": _acc_buckets(node, parts)}
    if kind == "matrix_stats":
        count = sum(p["count"] for p in parts)
        # the shift is index-wide and identical for every non-empty partial;
        # empty (missing-field) partials carry zeros and must not win
        shift = next((p["shift"] for p in parts
                      if p["count"] > 0 and p.get("shift") is not None), None)
        out = {"count": count, "fields": parts[0]["fields"], "shift": shift}
        for key in ("s1", "s2", "s3", "s4"):
            out[key] = np.sum([p[key] for p in parts], axis=0)
        out["xy"] = np.sum([p["xy"] for p in parts], axis=0)
        return out
    if kind in ("min", "max", "sum", "avg", "stats", "extended_stats", "value_count"):
        return _merge_stats(parts)
    if kind == "cardinality":
        regs = parts[0]["registers"]
        for p in parts[1:]:
            regs = np.maximum(regs, p["registers"])
        return {"registers": regs}
    if kind in ("percentiles", "percentile_ranks"):
        # DDSketch bins are global constants, so histogram addition IS the
        # cross-segment/shard reduce; ranks carries the queried values
        # where percentiles carries the queried percents
        hist = parts[0]["hist"].copy()
        for p in parts[1:]:
            hist += p["hist"]
        key = "percents" if kind == "percentiles" else "values"
        return {"hist": hist, key: parts[0][key]}
    if kind == "top_hits":
        rows = [r for p in parts for r in p["hits"]]
        rows.sort(key=lambda r: -r["_score"] if r["_score"] is not None else 0)
        return {"hits": rows[: parts[0]["size"]], "total": sum(p["total"] for p in parts)}
    raise ValueError(f"cannot merge aggregation kind [{kind}]")


def _acc_buckets(node: AggNode, parts: List[dict]) -> Dict[Any, dict]:
    """Accumulate keyed buckets + their sub-metric partials across segments
    (shared by terms / significant_terms / geo grids)."""
    acc: Dict[Any, dict] = {}
    for p in parts:
        for key, rec in p["buckets"].items():
            slot = acc.setdefault(key, {"doc_count": 0, "subs": []})
            slot["doc_count"] += rec["doc_count"]
            slot["subs"].append(rec.get("subs"))
    for key, slot in acc.items():
        slot["subs"] = _merge_sub_metrics(node.subs, slot["subs"])
    return acc


def _merge_stats(parts: List[dict]) -> dict:
    count = sum(p["count"] for p in parts)
    s = sum(p["sum"] for p in parts)
    ssq = sum(p.get("sumsq", 0.0) for p in parts)
    mn = min((p["min"] for p in parts if p["count"] > 0), default=float("inf"))
    mx = max((p["max"] for p in parts if p["count"] > 0), default=float("-inf"))
    return {"count": count, "sum": s, "min": mn, "max": mx, "sumsq": ssq}


def _merge_sub_metrics(subs: List[AggNode], partial_lists: List[Optional[dict]]) -> dict:
    out = {}
    for sub in subs:
        parts = [pl.get(sub.name) for pl in partial_lists if pl]
        out[sub.name] = merge_partials(sub, parts)
    return out


def _merge_subtrees(subs: List[AggNode], partial_lists: List[Optional[dict]]) -> dict:
    return _merge_sub_metrics(subs, partial_lists)


# ---------------- finalize (response shaping) ----------------

def finalize(node: AggNode, merged: dict, pipelines: bool = True) -> dict:
    """`pipelines=True` applies every pipeline agg; `pipelines=False` applies
    only non-deferred ones — the coordinator applies deferred pipelines after
    bucket refinement (executor._apply_deferred_tree), so a buckets_path
    targeting a refined sub-agg sees post-refinement values."""
    kind = node.kind
    if not merged:
        return _empty_result(node)
    if kind == "terms":
        size = int(node.body.get("size", 10))
        order = node.body.get("order", {"_count": "desc"})
        (okey, odir), = order.items() if isinstance(order, dict) else [("_count", "desc")]
        items = [(k, v) for k, v in merged["buckets"].items() if v["doc_count"] > 0]
        min_doc_count = int(node.body.get("min_doc_count", 1))
        items = [(k, v) for k, v in items if v["doc_count"] >= min_doc_count]
        if okey == "_key":
            items.sort(key=lambda kv: kv[0], reverse=(odir == "desc"))
        else:
            items.sort(key=lambda kv: (-kv[1]["doc_count"], kv[0])
                       if odir == "desc" else (kv[1]["doc_count"], kv[0]))
        total_count = sum(v["doc_count"] for _, v in items)
        buckets = []
        for k, v in items[:size]:
            b = {"key": k, "doc_count": int(v["doc_count"])}
            for sub in node.subs:
                b[sub.name] = finalize(sub, v["subs"].get(sub.name, {}), pipelines)
            _apply_pipelines(node, buckets_ref=None)
            buckets.append(b)
        shown = sum(b["doc_count"] for b in buckets)
        result = {"doc_count_error_upper_bound": 0,
                  "sum_other_doc_count": int(total_count - shown),
                  "buckets": buckets}
        _apply_bucket_pipelines(node, result, "all" if pipelines else "early")
        return result
    if kind in ("histogram", "date_histogram"):
        buckets = []
        for b in sorted(merged["buckets"]):
            rec = merged["buckets"][b]
            if rec["doc_count"] <= 0 and int(node.body.get("min_doc_count", 0)) > 0:
                continue
            key = b * merged["interval"] + merged.get("offset", 0.0)
            entry = {"key": key, "doc_count": int(rec["doc_count"])}
            if kind == "date_histogram":
                entry["key"] = int(key)
                entry["key_as_string"] = _format_epoch_ms(int(key))
            for sub in node.subs:
                entry[sub.name] = finalize(sub, rec["subs"].get(sub.name, {}), pipelines)
            buckets.append(entry)
        result = {"buckets": buckets}
        _apply_bucket_pipelines(node, result, "all" if pipelines else "early")
        return result
    if kind in ("range", "date_range", "geo_distance"):
        buckets = []
        for key in merged["buckets"]:
            rec = merged["buckets"][key]
            entry = {"key": key, "doc_count": int(rec["doc_count"])}
            if rec.get("meta"):
                entry.update(rec["meta"])
            for sub in node.subs:
                entry[sub.name] = finalize(sub, rec["subs"].get(sub.name, {}), pipelines)
            buckets.append(entry)
        result = {"buckets": buckets}
        _apply_bucket_pipelines(node, result, "all" if pipelines else "early")
        return result
    if kind == "filters":
        buckets = {}
        for key in merged["buckets"]:
            rec = merged["buckets"][key]
            entry = {"doc_count": int(rec["doc_count"])}
            for sub in node.subs:
                entry[sub.name] = finalize(sub, rec["subs"].get(sub.name, {}), pipelines)
            buckets[key] = entry
        return {"buckets": buckets}
    if kind in ("filter", "global", "missing", "sampler", "nested",
                "reverse_nested", "children", "parent",
                "diversified_sampler"):
        out = {"doc_count": int(merged["doc_count"])}
        for sub in node.subs:
            out[sub.name] = finalize(sub, merged["subs"].get(sub.name, {}), pipelines)
        return out
    if kind == "significant_terms":
        return _finalize_significant(node, merged, pipelines)
    if kind in ("geohash_grid", "geotile_grid"):
        size = int(node.body.get("size", 10000))
        items = sorted(((k, v) for k, v in merged["buckets"].items()
                        if v["doc_count"] > 0),
                       key=lambda kv: (-kv[1]["doc_count"], kv[0]))
        buckets = []
        for k, v in items[:size]:
            b = {"key": k, "doc_count": int(v["doc_count"])}
            for sub in node.subs:
                b[sub.name] = finalize(sub, v["subs"].get(sub.name, {}), pipelines)
            buckets.append(b)
        result = {"buckets": buckets}
        _apply_bucket_pipelines(node, result, "all" if pipelines else "early")
        return result
    if kind == "matrix_stats":
        return _finalize_matrix_stats(merged)
    if kind == "composite":
        return _finalize_composite(node, merged, pipelines)
    if kind == "value_count":
        return {"value": int(merged["count"])}
    if kind == "min":
        return {"value": None if merged["count"] == 0 else merged["min"]}
    if kind == "max":
        return {"value": None if merged["count"] == 0 else merged["max"]}
    if kind == "sum":
        return {"value": merged["sum"]}
    if kind == "avg":
        return {"value": None if merged["count"] == 0 else merged["sum"] / merged["count"]}
    if kind == "stats":
        c = merged["count"]
        return {"count": int(c), "min": None if c == 0 else merged["min"],
                "max": None if c == 0 else merged["max"], "sum": merged["sum"],
                "avg": None if c == 0 else merged["sum"] / c}
    if kind == "extended_stats":
        c = merged["count"]
        if c == 0:
            return {"count": 0, "min": None, "max": None, "sum": 0.0, "avg": None,
                    "sum_of_squares": 0.0, "variance": None, "std_deviation": None}
        var = max(merged["sumsq"] / c - (merged["sum"] / c) ** 2, 0.0)
        return {"count": int(c), "min": merged["min"], "max": merged["max"],
                "sum": merged["sum"], "avg": merged["sum"] / c,
                "sum_of_squares": merged["sumsq"], "variance": var,
                "std_deviation": math.sqrt(var)}
    if kind == "cardinality":
        return {"value": int(round(_hll_estimate(merged["registers"])))}
    if kind == "percentiles":
        return {"values": _hist_percentiles(merged)}
    if kind == "percentile_ranks":
        return {"values": _hist_percentile_ranks(merged)}
    if kind == "top_hits":
        return {"hits": {"total": {"value": int(merged["total"]), "relation": "eq"},
                         "max_score": merged["hits"][0]["_score"] if merged["hits"] else None,
                         "hits": merged["hits"]}}
    if kind == "weighted_avg":
        w = merged.get("wsum", 0.0)
        return {"value": None if not w else merged["vwsum"] / w}
    if kind == "median_absolute_deviation":
        return {"value": _mad_from_hist(merged["hist"])}
    if kind == "geo_bounds":
        if not merged or merged.get("count", 0) == 0:
            return {}
        return {"bounds": {
            "top_left": {"lat": float(merged["top"]),
                         "lon": float(merged["left"])},
            "bottom_right": {"lat": float(merged["bottom"]),
                             "lon": float(merged["right"])}}}
    if kind == "geo_centroid":
        c = merged.get("count", 0)
        if not c:
            return {"count": 0}
        return {"location": {"lat": float(merged["slat"] / c),
                             "lon": float(merged["slon"] / c)},
                "count": int(c)}
    if kind == "scripted_metric":
        from ..script.painless_lite import execute
        body = node.body
        states = merged.get("states", [])
        reduce_src = body.get("reduce_script")
        if reduce_src:
            src, prm = _script_src(reduce_src)
            val = execute(src, {"states": states, "params": prm})
        else:
            val = states
        return {"value": val}
    if kind == "ip_range":
        buckets = []
        for key in merged["buckets"]:
            rec = merged["buckets"][key]
            entry = {"key": key, "doc_count": int(rec["doc_count"])}
            if rec.get("meta"):
                entry.update(rec["meta"])
            for sub in node.subs:
                entry[sub.name] = finalize(sub, rec["subs"].get(sub.name, {}),
                                           pipelines)
            buckets.append(entry)
        result = {"buckets": buckets}
        _apply_bucket_pipelines(node, result, "all" if pipelines else "early")
        return result
    if kind == "rare_terms":
        max_dc = int(node.body.get("max_doc_count", 1))
        items = sorted(((k, v) for k, v in merged["buckets"].items()
                        if 0 < v["doc_count"] <= max_dc),
                       key=lambda kv: (kv[1]["doc_count"], kv[0]))
        buckets = []
        for k, v in items:
            b = {"key": k, "doc_count": int(v["doc_count"])}
            for sub in node.subs:
                b[sub.name] = finalize(sub, v["subs"].get(sub.name, {}),
                                       pipelines)
            buckets.append(b)
        result = {"buckets": buckets}
        _apply_bucket_pipelines(node, result, "all" if pipelines else "early")
        return result
    if kind == "multi_terms":
        size = int(node.body.get("size", 10))
        items = sorted(((k, v) for k, v in merged["buckets"].items()
                        if v["doc_count"] > 0),
                       key=lambda kv: (-kv[1]["doc_count"], kv[0]))
        buckets = []
        for k, v in items[:size]:
            b = {"key": list(k),
                 "key_as_string": "|".join(str(x) for x in k),
                 "doc_count": int(v["doc_count"])}
            for sub in node.subs:
                b[sub.name] = finalize(sub, v["subs"].get(sub.name, {}),
                                       pipelines)
            buckets.append(b)
        total = sum(v["doc_count"] for _, v in items)
        shown = sum(b["doc_count"] for b in buckets)
        result = {"buckets": buckets,
                  "sum_other_doc_count": int(total - shown)}
        _apply_bucket_pipelines(node, result, "all" if pipelines else "early")
        return result
    if kind == "adjacency_matrix":
        buckets = []
        for key in sorted(merged["buckets"]):
            rec = merged["buckets"][key]
            if rec["doc_count"] <= 0:
                continue
            entry = {"key": key, "doc_count": int(rec["doc_count"])}
            for sub in node.subs:
                entry[sub.name] = finalize(sub, rec["subs"].get(sub.name, {}),
                                           pipelines)
            buckets.append(entry)
        result = {"buckets": buckets}
        _apply_bucket_pipelines(node, result, "all" if pipelines else "early")
        return result
    if kind == "auto_date_histogram":
        target = max(int(node.body.get("buckets", 10)), 1)
        interval = merged.get("interval_ms", 1000)
        buckets = dict(merged.get("buckets", {}))
        # coarsen until the bucket count fits the target (coordinator-side
        # final rounding step of the reference)
        from .compiler import _AUTO_LADDER, auto_interval_name
        ladder = [ms for ms, _ in _AUTO_LADDER]
        li = next((i for i, ms in enumerate(ladder) if ms >= interval), 0)
        while buckets and len(buckets) > target and li + 1 < len(ladder):
            li += 1
            interval = ladder[li]
            acc: Dict[Any, dict] = {}
            for key, rec in buckets.items():
                ck = (int(key) // interval) * interval
                slot = acc.setdefault(ck, {"doc_count": 0, "subs": []})
                slot["doc_count"] += rec["doc_count"]
                slot["subs"].append(rec.get("subs"))
            for slot in acc.values():
                slot["subs"] = _merge_sub_metrics(node.subs, slot["subs"])
            buckets = acc
        out_buckets = []
        for key in sorted(buckets):
            rec = buckets[key]
            entry = {"key": int(key),
                     "key_as_string": _format_epoch_ms(int(key)),
                     "doc_count": int(rec["doc_count"])}
            for sub in node.subs:
                entry[sub.name] = finalize(sub, rec["subs"].get(sub.name, {}),
                                           pipelines)
            out_buckets.append(entry)
        result = {"buckets": out_buckets,
                  "interval": auto_interval_name(interval)}
        _apply_bucket_pipelines(node, result, "all" if pipelines else "early")
        return result
    if kind == "significant_text":
        return _finalize_significant(node, merged, pipelines)
    raise ValueError(f"cannot finalize aggregation kind [{kind}]")


def _script_src(spec):
    """script spec (str or {"source", "params"}) -> (source, params)."""
    if isinstance(spec, str):
        return spec, {}
    return spec.get("source", ""), spec.get("params", {})


def _mad_from_hist(hist: np.ndarray) -> Optional[float]:
    """Median absolute deviation from the mergeable DDSketch histogram
    (reference MedianAbsoluteDeviationAggregator over TDigest): median of
    |bin center - median| weighted by bin counts."""
    from ..ops.aggs import ddsketch_value
    total = float(hist.sum())
    if total == 0:
        return None
    nz = np.nonzero(hist)[0]
    centers = np.array([ddsketch_value(int(b)) for b in nz])
    weights = hist[nz].astype(np.float64)

    def weighted_median(vals, ws):
        order = np.argsort(vals)
        v, w = vals[order], ws[order]
        cum = np.cumsum(w)
        half = cum[-1] / 2.0
        i = int(np.searchsorted(cum, half))
        if cum[i] == half and i + 1 < len(v):
            # even split: interpolate like numpy.median / TDigest
            return float((v[i] + v[i + 1]) / 2.0)
        return float(v[i])

    med = weighted_median(centers, weights)
    return weighted_median(np.abs(centers - med), weights)


def composite_sources(node: AggNode) -> List[tuple]:
    """[(name, source_type, config, order)] from the composite body."""
    out = []
    for s in node.body.get("sources", []):
        ((nm, spec),) = s.items()
        ((stype, scfg),) = spec.items()
        out.append((nm, stype, scfg, scfg.get("order", "asc")))
    return out


class _CompVal:
    """Per-source comparable honoring its order direction."""

    __slots__ = ("v", "desc")

    def __init__(self, v, desc: bool):
        self.v = v
        self.desc = desc

    def __lt__(self, other):
        return (self.v > other.v) if self.desc else (self.v < other.v)

    def __eq__(self, other):
        return self.v == other.v


def _finalize_composite(node: AggNode, merged: dict, pipelines: bool = True) -> dict:
    sources = composite_sources(node)
    size = int(node.body.get("size", 10))
    after = node.body.get("after")

    def comp(key_tuple):
        return tuple(_CompVal(v, o == "desc")
                     for v, (_, _, _, o) in zip(key_tuple, sources))

    items = [(k, v) for k, v in merged["buckets"].items() if v["doc_count"] > 0]
    items.sort(key=lambda kv: comp(kv[0]))
    if after is not None:
        after_tuple = tuple(after[nm] for nm, _, _, _ in sources)
        ac = comp(after_tuple)
        items = [kv for kv in items if comp(kv[0]) > ac]
    buckets = []
    for key, rec in items[:size]:
        b = {"key": {nm: v for (nm, _, _, _), v in zip(sources, key)},
             "doc_count": int(rec["doc_count"])}
        for sub in node.subs:
            b[sub.name] = finalize(sub, rec["subs"].get(sub.name, {}), pipelines)
        buckets.append(b)
    out = {"buckets": buckets}
    if buckets:
        out["after_key"] = buckets[-1]["key"]
    _apply_bucket_pipelines(node, out, "all" if pipelines else "early")
    return out


def _significance_score(fg: float, fg_total: float, bg: float, bg_total: float,
                        heuristic: str) -> float:
    """Reference significance heuristics (JLH default, chi_square,
    percentage) over foreground vs background frequencies."""
    if fg_total == 0 or bg_total == 0 or bg == 0:
        return 0.0
    fgp = fg / fg_total
    bgp = bg / bg_total
    if heuristic == "percentage":
        return fg / bg
    if heuristic == "chi_square":
        num = (fgp - bgp) ** 2
        den = bgp * (1 - bgp)
        return (num / den) * bg_total if den > 0 else 0.0
    # JLH: absolute change * relative change
    return (fgp - bgp) * (fgp / bgp) if fgp > bgp else 0.0


def _finalize_significant(node: AggNode, merged: dict, pipelines: bool = True) -> dict:
    body = node.body
    heuristic = next((h for h in ("jlh", "chi_square", "percentage")
                      if h in body), "jlh")
    size = int(body.get("size", 10))
    min_doc_count = int(body.get("min_doc_count", 3))
    fg_total, bg_total = merged["fg_total"], merged["bg_total"]
    scored = []
    for key, rec in merged["buckets"].items():
        fg = rec["doc_count"]
        bg = merged["bg"].get(key, fg)
        if fg < min_doc_count:
            continue
        score = _significance_score(fg, fg_total, bg, bg_total, heuristic)
        if score > 0:
            scored.append((score, key, fg, bg, rec))
    scored.sort(key=lambda t: (-t[0], t[1]))
    buckets = []
    for score, key, fg, bg, rec in scored[:size]:
        b = {"key": key, "doc_count": int(fg), "score": score,
             "bg_count": int(bg)}
        for sub in node.subs:
            b[sub.name] = finalize(sub, rec["subs"].get(sub.name, {}), pipelines)
        buckets.append(b)
    out = {"doc_count": int(fg_total), "bg_count": int(bg_total),
           "buckets": buckets}
    _apply_bucket_pipelines(node, out, "all" if pipelines else "early")
    return out


def _finalize_matrix_stats(merged: dict) -> dict:
    n = float(merged["count"])
    fields = merged["fields"]
    if n == 0:
        return {"doc_count": 0, "fields": []}
    s1, s2, s3, s4 = (np.asarray(merged[k], np.float64)
                      for k in ("s1", "s2", "s3", "s4"))
    xy = np.asarray(merged["xy"], np.float64)
    shift = np.asarray(merged.get("shift", np.zeros(len(fields))), np.float64)
    # device sums are centered about `shift`; `mean` below is the small
    # residual d = Σ(x-shift)/n, so the central-moment differences don't cancel
    mean = s1 / n
    m2 = s2 / n - mean ** 2
    var = m2 * n / max(n - 1, 1)  # unbiased, like the reference
    out_fields = []
    for i, f in enumerate(fields):
        m2i = max(m2[i], 0.0)
        m3 = s3[i] / n - 3 * mean[i] * s2[i] / n + 2 * mean[i] ** 3
        m4 = (s4[i] / n - 4 * mean[i] * s3[i] / n
              + 6 * mean[i] ** 2 * s2[i] / n - 3 * mean[i] ** 4)
        skew = m3 / m2i ** 1.5 if m2i > 0 else 0.0
        kurt = m4 / m2i ** 2 if m2i > 0 else 0.0
        cov = {}
        corr = {}
        for j, g in enumerate(fields):
            c = (xy[i, j] - s1[i] * s1[j] / n) / max(n - 1, 1)
            cov[g] = c
            denom = math.sqrt(var[i] * var[j])
            corr[g] = c / denom if denom > 0 else 0.0
        out_fields.append({"name": f, "count": int(n),
                           "mean": shift[i] + mean[i],
                           "variance": var[i], "skewness": skew,
                           "kurtosis": kurt, "covariance": cov,
                           "correlation": corr})
    return {"doc_count": int(n), "fields": out_fields}


def _empty_result(node: AggNode) -> dict:
    if node.kind in ("terms", "histogram", "date_histogram", "range",
                     "date_range", "filters", "geohash_grid", "geotile_grid",
                     "composite", "ip_range", "rare_terms", "multi_terms",
                     "adjacency_matrix", "auto_date_histogram"):
        return {"buckets": [] if node.kind != "filters" else {}}
    if node.kind in ("significant_terms", "significant_text"):
        return {"doc_count": 0, "bg_count": 0, "buckets": []}
    if node.kind in ("weighted_avg", "median_absolute_deviation"):
        return {"value": None}
    if node.kind == "geo_bounds":
        return {}
    if node.kind == "geo_centroid":
        return {"count": 0}
    if node.kind == "scripted_metric":
        return {"value": None}
    if node.kind == "matrix_stats":
        return {"doc_count": 0, "fields": []}
    if node.kind in ("filter", "global", "missing", "sampler", "nested",
                     "reverse_nested", "children", "parent",
                     "diversified_sampler"):
        return {"doc_count": 0}
    if node.kind in ("min", "max", "avg"):
        return {"value": None}
    if node.kind in ("sum", "value_count", "cardinality"):
        return {"value": 0}
    if node.kind == "stats":
        return {"count": 0, "min": None, "max": None, "sum": 0.0, "avg": None}
    if node.kind in ("percentiles", "percentile_ranks"):
        return {"values": {}}
    return {}


def _hll_estimate(regs: np.ndarray) -> float:
    m = len(regs)
    z = float(np.sum(np.exp2(-regs.astype(np.float64))))
    alpha = 0.7213 / (1.0 + 1.079 / m)
    est = alpha * m * m / z
    zeros = int(np.sum(regs == 0))
    if est <= 2.5 * m and zeros > 0:
        return m * math.log(m / zeros)
    return est


def _hist_percentiles(merged: dict) -> Dict[str, float]:
    from ..ops.aggs import ddsketch_value

    hist = merged["hist"].astype(np.float64)
    total = hist.sum()
    out: Dict[str, float] = {}
    if total == 0:
        return {f"{p:.1f}": None for p in merged["percents"]}
    cum = np.cumsum(hist)
    nb = len(hist)
    for p in merged["percents"]:
        target = max(p / 100.0 * total, 1e-9)
        b = int(np.searchsorted(cum, target, side="left"))
        out[f"{p:.1f}"] = ddsketch_value(min(b, nb - 1))
    return out


def _hist_percentile_ranks(merged: dict) -> Dict[str, float]:
    """percentile_ranks: the INVERSE of `_hist_percentiles` over the same
    DDSketch histogram (reference PercentileRanksAggregationBuilder,
    SearchModule.java:441) — for each requested value, the percentage of
    observations <= it. Inclusive cumulative count of the value's own bin,
    so rank(percentile(p)) round-trips to p within one bin's resolution."""
    from ..ops.aggs import ddsketch_bin

    hist = merged["hist"].astype(np.float64)
    total = hist.sum()
    out: Dict[str, float] = {}
    # keys are the full-precision value strings (reference
    # String.valueOf(double)): a fixed .1f format would collide distinct
    # sub-0.05 values like 0.01 and 0.04 onto one key
    if total == 0:
        return {str(float(v)): None for v in merged["values"]}
    cum = np.cumsum(hist)
    for v in merged["values"]:
        b = ddsketch_bin(float(v))
        out[str(float(v))] = float(cum[b] / total * 100.0)
    return out


def _format_epoch_ms(ms: int) -> str:
    import datetime as dt

    return dt.datetime.fromtimestamp(ms / 1000.0, dt.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"


def apply_pipelines_tree(node: AggNode, result) -> None:
    """Post-order application of DEFERRED pipelines over a finalized agg
    subtree — used for subtrees the refinement walk never reached (their
    early pipelines already ran in finalize; deferred ones run here with the
    same values). The coordinator's refinement-aware walk is
    executor._apply_deferred_tree."""
    if not isinstance(result, dict):
        return
    buckets = result.get("buckets")
    if isinstance(buckets, list):
        for b in buckets:
            for s in node.subs:
                apply_pipelines_tree(s, b.get(s.name))
    elif isinstance(buckets, dict):
        for bd in buckets.values():
            for s in node.subs:
                apply_pipelines_tree(s, bd.get(s.name))
    else:
        for s in node.subs:
            apply_pipelines_tree(s, result.get(s.name))
    _apply_bucket_pipelines(node, result, "deferred")


# ---------------- pipeline aggregations (host post-processing) ----------------

def _apply_pipelines(node: AggNode, buckets_ref) -> None:  # placeholder hook
    return


def _bucket_path_value(b: dict, path: str):
    """Resolve one buckets_path against a finalized bucket (reference
    BucketHelpers.resolveBucketValue): `_count`, `sub.value`, `sub.avg`,
    `sub>nested.value` chains."""
    if path == "_count":
        return float(b["doc_count"])
    node: Any = b
    parts = path.replace(">", ".").split(".")
    for part in parts:
        if not isinstance(node, dict):
            return None
        node = node.get(part)
    if isinstance(node, dict):
        node = node.get("value")
    return node


def _moving_fn_eval(script: str, values: List[float], params: dict):
    """moving_fn scripts: the reference MovingFunctions helpers, plus
    arbitrary painless-lite expressions over `values`."""
    fns = {"max": lambda v: max(v) if v else None,
           "min": lambda v: min(v) if v else None,
           "sum": lambda v: sum(v),
           "unweightedAvg": lambda v: sum(v) / len(v) if v else None,
           "stdDev": None,
           "linearWeightedAvg": lambda v: (sum((i + 1) * x for i, x in enumerate(v))
                                           / sum(range(1, len(v) + 1))) if v else None}
    import re as _re
    m = _re.match(r"\s*MovingFunctions\.(\w+)\(values(?:,\s*[\w.()]+)?\)\s*$", script)
    if m and m.group(1) in fns:
        name = m.group(1)
        if name == "stdDev":
            if not values:
                return None
            avg = sum(values) / len(values)
            return math.sqrt(sum((x - avg) ** 2 for x in values) / len(values))
        return fns[name](values)
    from ..script import painless_lite as pl
    return pl.execute(script, {"values": list(values), "params": params})


def _apply_bucket_pipelines(node: AggNode, result: dict,
                            which: str = "all") -> None:
    """Sibling pipeline aggs over this bucket agg's finalized buckets
    (reference `search/aggregations/pipeline/`): cumulative_sum, derivative,
    moving_avg/fn, serial_diff, bucket_script attach per-bucket;
    bucket_selector/bucket_sort mutate the bucket list; *_bucket /
    percentiles_bucket attach as sibling values.

    `which` selects the phase: "all" every pipeline, "early" only
    non-deferred, "deferred" only deferred (see AggNode.deferred)."""
    buckets = result.get("buckets")
    if not isinstance(buckets, list):
        return
    for p in node.pipelines:
        if which == "early" and p.deferred:
            continue
        if which == "deferred" and not p.deferred:
            continue
        raw_path = p.body.get("buckets_path", "_count")

        if p.kind in ("bucket_script", "bucket_selector"):
            from ..script import painless_lite as pl
            from .query_dsl import parse_script_spec
            src, sparams = parse_script_spec(p.body.get("script"))
            paths = raw_path if isinstance(raw_path, dict) else {"_value": raw_path}
            keep = []
            for b in buckets:
                variables = {"params": dict(sparams)}
                missing = False
                for var, pth in paths.items():
                    v = _bucket_path_value(b, pth)
                    if v is None:
                        missing = True
                    variables["params"][var] = v
                    variables[var] = v
                if missing:
                    # gap_policy=skip: retain the bucket unevaluated
                    # (reference BucketSelector/BucketScript PipelineAggregator)
                    if p.kind == "bucket_script":
                        b[p.name] = {"value": None}
                    keep.append(b)
                    continue
                try:
                    val = pl.execute(src, variables)
                except pl.ScriptError as e:
                    raise ValueError(f"[{p.name}] script error: {e}")
                if p.kind == "bucket_script":
                    b[p.name] = {"value": float(val) if val is not None else None}
                    keep.append(b)
                elif val:
                    keep.append(b)
            if p.kind == "bucket_selector":
                result["buckets"] = buckets = keep
            continue

        if p.kind == "bucket_sort":
            sorts = p.body.get("sort", [])
            frm = int(p.body.get("from", 0))
            size = p.body.get("size")

            def sort_key(b):
                key = []
                for s in sorts:
                    ((pth, spec),) = s.items() if isinstance(s, dict) else [(s, "asc")]
                    order = spec.get("order", "asc") if isinstance(spec, dict) else spec
                    v = _bucket_path_value(b, pth)
                    v = float("-inf") if v is None else v
                    key.append(-v if order == "desc" else v)
                return tuple(key)

            if sorts:
                buckets.sort(key=sort_key)
            end = frm + int(size) if size is not None else None
            result["buckets"] = buckets = buckets[frm:end]
            continue

        series = [_bucket_path_value(b, raw_path) for b in buckets]
        vals = [v for v in series if v is not None]
        if p.kind == "cumulative_sum":
            run = 0.0
            for b, v in zip(buckets, series):
                run += (v or 0.0)
                b[p.name] = {"value": run}
        elif p.kind == "derivative":
            prev = None
            for b, v in zip(buckets, series):
                b[p.name] = {"value": None if prev is None or v is None else v - prev}
                prev = v
        elif p.kind == "serial_diff":
            lag = int(p.body.get("lag", 1))
            for i, b in enumerate(series):
                cur = series[i]
                ref = series[i - lag] if i >= lag else None
                buckets[i][p.name] = {
                    "value": None if cur is None or ref is None else cur - ref}
        elif p.kind in ("moving_avg", "moving_fn"):
            window = int(p.body.get("window", 5))
            shift = int(p.body.get("shift", 0))
            # moving_avg includes the current bucket (reference
            # MovAvgPipelineAggregator); moving_fn's shift=0 excludes it
            if p.kind == "moving_avg":
                shift += 1
            for i, b in enumerate(buckets):
                lo = max(0, i - window + shift)
                hi = max(0, i + shift)
                win = [v for v in series[lo:hi] if v is not None]
                if p.kind == "moving_avg":
                    model = p.body.get("model", "simple")
                    if not win:
                        out = None
                    elif model == "linear":
                        wsum = sum(range(1, len(win) + 1))
                        out = sum((j + 1) * x for j, x in enumerate(win)) / wsum
                    else:
                        out = sum(win) / len(win)
                else:
                    src, sparams = None, {}
                    from .query_dsl import parse_script_spec
                    src, sparams = parse_script_spec(p.body.get("script"))
                    out = _moving_fn_eval(src, win, sparams)
                b[p.name] = {"value": out}
        elif p.kind == "percentiles_bucket":
            percents = p.body.get("percents", [1.0, 5.0, 25.0, 50.0, 75.0, 95.0, 99.0])
            svals = sorted(vals)
            out = {}
            for pc in percents:
                if not svals:
                    out[f"{pc:.1f}"] = None
                else:
                    idx = min(int(round(pc / 100.0 * len(svals) + 0.5)) - 1,
                              len(svals) - 1)
                    out[f"{pc:.1f}"] = svals[max(idx, 0)]
            result[p.name] = {"values": out}
        elif p.kind in ("avg_bucket", "sum_bucket", "min_bucket", "max_bucket", "stats_bucket"):
            if p.kind == "avg_bucket":
                result[p.name] = {"value": sum(vals) / len(vals) if vals else None}
            elif p.kind == "sum_bucket":
                result[p.name] = {"value": sum(vals)}
            elif p.kind == "min_bucket":
                result[p.name] = {"value": min(vals) if vals else None}
            elif p.kind == "max_bucket":
                result[p.name] = {"value": max(vals) if vals else None}
            else:
                result[p.name] = {"count": len(vals), "sum": sum(vals),
                                  "min": min(vals) if vals else None,
                                  "max": max(vals) if vals else None,
                                  "avg": sum(vals) / len(vals) if vals else None}
