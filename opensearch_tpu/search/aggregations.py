"""Aggregations: DSL parsing + cross-segment/shard merge + response shaping.
Analog of reference `search/aggregations/` (AggregatorFactories parse tree,
InternalAggregation#reduce, and the response XContent shapes).

Device emission lives in `compiler.py` (same jitted program as scoring);
this module is host-only: it defines the agg tree, merges per-segment
partials (the analog of InternalAggregation.reduce), and renders the
OpenSearch-shaped response JSON.

Design notes vs the reference:
- terms aggs are exact per shard (full ordinal bincount on device — no
  shard_size truncation error; doc_count_error_upper_bound is honestly 0).
- cardinality is device-side HyperLogLog (log2m=14) over value hashes —
  mergeable across segments and shards like the reference's HLL++.
- percentiles use a mergeable 4096-bin histogram sketch between index-wide
  column bounds instead of TDigest.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional

import numpy as np

BUCKET_KINDS = {"terms", "histogram", "date_histogram", "range", "date_range",
                "filter", "filters", "global", "missing"}
METRIC_KINDS = {"min", "max", "sum", "avg", "stats", "extended_stats",
                "value_count", "cardinality", "percentiles", "top_hits"}
PIPELINE_KINDS = {"avg_bucket", "sum_bucket", "min_bucket", "max_bucket",
                  "stats_bucket", "cumulative_sum", "derivative", "bucket_script",
                  "bucket_selector"}


@dataclass
class AggNode:
    name: str
    kind: str
    body: dict
    subs: List["AggNode"] = dc_field(default_factory=list)
    pipelines: List["AggNode"] = dc_field(default_factory=list)


def parse_aggs(aggs: Optional[dict]) -> List[AggNode]:
    out: List[AggNode] = []
    if not aggs:
        return out
    for name, spec in aggs.items():
        sub_specs = spec.get("aggs", spec.get("aggregations"))
        kinds = [k for k in spec if k not in ("aggs", "aggregations", "meta")]
        if len(kinds) != 1:
            raise ValueError(f"aggregation [{name}] must define exactly one type")
        kind = kinds[0]
        if kind not in BUCKET_KINDS | METRIC_KINDS | PIPELINE_KINDS:
            raise ValueError(f"unknown aggregation type [{kind}]")
        node = AggNode(name, kind, spec[kind])
        children = parse_aggs(sub_specs)
        node.subs = [c for c in children if c.kind not in PIPELINE_KINDS]
        node.pipelines = [c for c in children if c.kind in PIPELINE_KINDS]
        if kind in METRIC_KINDS and node.subs:
            raise ValueError(f"metric aggregation [{name}] cannot have sub-aggregations")
        out.append(node)
    return out


# ---------------- merge (reduce) ----------------

def merge_partials(node: AggNode, partials: List[dict]) -> dict:
    """Merge per-segment/per-shard partials for one agg node (reference:
    InternalAggregation#reduce). Each partial is a host dict produced by the
    compiler's device run + segment context."""
    parts = [p for p in partials if p is not None]
    if not parts:
        return {}
    kind = node.kind
    if kind == "terms":
        acc: Dict[Any, dict] = {}
        for p in parts:
            for key, rec in p["buckets"].items():
                slot = acc.setdefault(key, {"doc_count": 0, "subs": []})
                slot["doc_count"] += rec["doc_count"]
                slot["subs"].append(rec.get("subs"))
        for key, slot in acc.items():
            slot["subs"] = _merge_sub_metrics(node.subs, slot["subs"])
        return {"buckets": acc}
    if kind in ("histogram", "date_histogram"):
        acc = {}
        for p in parts:
            for b, rec in p["buckets"].items():
                slot = acc.setdefault(b, {"doc_count": 0, "subs": []})
                slot["doc_count"] += rec["doc_count"]
                slot["subs"].append(rec.get("subs"))
        for b, slot in acc.items():
            slot["subs"] = _merge_sub_metrics(node.subs, slot["subs"])
        return {"buckets": acc, "interval": parts[0]["interval"],
                "offset": parts[0].get("offset", 0.0), "keyed_fmt": parts[0].get("keyed_fmt")}
    if kind in ("range", "date_range", "filters"):
        acc = {}
        for p in parts:
            for key, rec in p["buckets"].items():
                slot = acc.setdefault(key, {"doc_count": 0, "subs": [], "meta": rec.get("meta")})
                slot["doc_count"] += rec["doc_count"]
                slot["subs"].append(rec.get("subs"))
        for key, slot in acc.items():
            slot["subs"] = _merge_subtrees(node.subs, slot["subs"])
        return {"buckets": acc}
    if kind in ("filter", "global", "missing"):
        total = sum(p["doc_count"] for p in parts)
        subs = _merge_subtrees(node.subs, [p.get("subs") for p in parts])
        return {"doc_count": total, "subs": subs}
    if kind in ("min", "max", "sum", "avg", "stats", "extended_stats", "value_count"):
        return _merge_stats(parts)
    if kind == "cardinality":
        regs = parts[0]["registers"]
        for p in parts[1:]:
            regs = np.maximum(regs, p["registers"])
        return {"registers": regs}
    if kind == "percentiles":
        hist = parts[0]["hist"].copy()
        for p in parts[1:]:
            hist += p["hist"]
        return {"hist": hist, "percents": parts[0]["percents"]}
    if kind == "top_hits":
        rows = [r for p in parts for r in p["hits"]]
        rows.sort(key=lambda r: -r["_score"] if r["_score"] is not None else 0)
        return {"hits": rows[: parts[0]["size"]], "total": sum(p["total"] for p in parts)}
    raise ValueError(f"cannot merge aggregation kind [{kind}]")


def _merge_stats(parts: List[dict]) -> dict:
    count = sum(p["count"] for p in parts)
    s = sum(p["sum"] for p in parts)
    ssq = sum(p.get("sumsq", 0.0) for p in parts)
    mn = min((p["min"] for p in parts if p["count"] > 0), default=float("inf"))
    mx = max((p["max"] for p in parts if p["count"] > 0), default=float("-inf"))
    return {"count": count, "sum": s, "min": mn, "max": mx, "sumsq": ssq}


def _merge_sub_metrics(subs: List[AggNode], partial_lists: List[Optional[dict]]) -> dict:
    out = {}
    for sub in subs:
        parts = [pl.get(sub.name) for pl in partial_lists if pl]
        out[sub.name] = merge_partials(sub, parts)
    return out


def _merge_subtrees(subs: List[AggNode], partial_lists: List[Optional[dict]]) -> dict:
    return _merge_sub_metrics(subs, partial_lists)


# ---------------- finalize (response shaping) ----------------

def finalize(node: AggNode, merged: dict) -> dict:
    kind = node.kind
    if not merged:
        return _empty_result(node)
    if kind == "terms":
        size = int(node.body.get("size", 10))
        order = node.body.get("order", {"_count": "desc"})
        (okey, odir), = order.items() if isinstance(order, dict) else [("_count", "desc")]
        items = [(k, v) for k, v in merged["buckets"].items() if v["doc_count"] > 0]
        min_doc_count = int(node.body.get("min_doc_count", 1))
        items = [(k, v) for k, v in items if v["doc_count"] >= min_doc_count]
        if okey == "_key":
            items.sort(key=lambda kv: kv[0], reverse=(odir == "desc"))
        else:
            items.sort(key=lambda kv: (-kv[1]["doc_count"], kv[0])
                       if odir == "desc" else (kv[1]["doc_count"], kv[0]))
        total_count = sum(v["doc_count"] for _, v in items)
        buckets = []
        for k, v in items[:size]:
            b = {"key": k, "doc_count": int(v["doc_count"])}
            for sub in node.subs:
                b[sub.name] = finalize(sub, v["subs"].get(sub.name, {}))
            _apply_pipelines(node, buckets_ref=None)
            buckets.append(b)
        shown = sum(b["doc_count"] for b in buckets)
        result = {"doc_count_error_upper_bound": 0,
                  "sum_other_doc_count": int(total_count - shown),
                  "buckets": buckets}
        _apply_bucket_pipelines(node, result)
        return result
    if kind in ("histogram", "date_histogram"):
        buckets = []
        for b in sorted(merged["buckets"]):
            rec = merged["buckets"][b]
            if rec["doc_count"] <= 0 and int(node.body.get("min_doc_count", 0)) > 0:
                continue
            key = b * merged["interval"] + merged.get("offset", 0.0)
            entry = {"key": key, "doc_count": int(rec["doc_count"])}
            if kind == "date_histogram":
                entry["key"] = int(key)
                entry["key_as_string"] = _format_epoch_ms(int(key))
            for sub in node.subs:
                entry[sub.name] = finalize(sub, rec["subs"].get(sub.name, {}))
            buckets.append(entry)
        result = {"buckets": buckets}
        _apply_bucket_pipelines(node, result)
        return result
    if kind in ("range", "date_range"):
        buckets = []
        for key in merged["buckets"]:
            rec = merged["buckets"][key]
            entry = {"key": key, "doc_count": int(rec["doc_count"])}
            if rec.get("meta"):
                entry.update(rec["meta"])
            for sub in node.subs:
                entry[sub.name] = finalize(sub, rec["subs"].get(sub.name, {}))
            buckets.append(entry)
        return {"buckets": buckets}
    if kind == "filters":
        buckets = {}
        for key in merged["buckets"]:
            rec = merged["buckets"][key]
            entry = {"doc_count": int(rec["doc_count"])}
            for sub in node.subs:
                entry[sub.name] = finalize(sub, rec["subs"].get(sub.name, {}))
            buckets[key] = entry
        return {"buckets": buckets}
    if kind in ("filter", "global", "missing"):
        out = {"doc_count": int(merged["doc_count"])}
        for sub in node.subs:
            out[sub.name] = finalize(sub, merged["subs"].get(sub.name, {}))
        return out
    if kind == "value_count":
        return {"value": int(merged["count"])}
    if kind == "min":
        return {"value": None if merged["count"] == 0 else merged["min"]}
    if kind == "max":
        return {"value": None if merged["count"] == 0 else merged["max"]}
    if kind == "sum":
        return {"value": merged["sum"]}
    if kind == "avg":
        return {"value": None if merged["count"] == 0 else merged["sum"] / merged["count"]}
    if kind == "stats":
        c = merged["count"]
        return {"count": int(c), "min": None if c == 0 else merged["min"],
                "max": None if c == 0 else merged["max"], "sum": merged["sum"],
                "avg": None if c == 0 else merged["sum"] / c}
    if kind == "extended_stats":
        c = merged["count"]
        if c == 0:
            return {"count": 0, "min": None, "max": None, "sum": 0.0, "avg": None,
                    "sum_of_squares": 0.0, "variance": None, "std_deviation": None}
        var = max(merged["sumsq"] / c - (merged["sum"] / c) ** 2, 0.0)
        return {"count": int(c), "min": merged["min"], "max": merged["max"],
                "sum": merged["sum"], "avg": merged["sum"] / c,
                "sum_of_squares": merged["sumsq"], "variance": var,
                "std_deviation": math.sqrt(var)}
    if kind == "cardinality":
        return {"value": int(round(_hll_estimate(merged["registers"])))}
    if kind == "percentiles":
        return {"values": _hist_percentiles(merged)}
    if kind == "top_hits":
        return {"hits": {"total": {"value": int(merged["total"]), "relation": "eq"},
                         "max_score": merged["hits"][0]["_score"] if merged["hits"] else None,
                         "hits": merged["hits"]}}
    raise ValueError(f"cannot finalize aggregation kind [{kind}]")


def _empty_result(node: AggNode) -> dict:
    if node.kind in ("terms", "histogram", "date_histogram", "range", "date_range", "filters"):
        return {"buckets": [] if node.kind != "filters" else {}}
    if node.kind in ("filter", "global", "missing"):
        return {"doc_count": 0}
    if node.kind in ("min", "max", "avg"):
        return {"value": None}
    if node.kind in ("sum", "value_count", "cardinality"):
        return {"value": 0}
    if node.kind == "stats":
        return {"count": 0, "min": None, "max": None, "sum": 0.0, "avg": None}
    if node.kind == "percentiles":
        return {"values": {}}
    return {}


def _hll_estimate(regs: np.ndarray) -> float:
    m = len(regs)
    z = float(np.sum(np.exp2(-regs.astype(np.float64))))
    alpha = 0.7213 / (1.0 + 1.079 / m)
    est = alpha * m * m / z
    zeros = int(np.sum(regs == 0))
    if est <= 2.5 * m and zeros > 0:
        return m * math.log(m / zeros)
    return est


def _hist_percentiles(merged: dict) -> Dict[str, float]:
    from ..ops.aggs import ddsketch_value

    hist = merged["hist"].astype(np.float64)
    total = hist.sum()
    out: Dict[str, float] = {}
    if total == 0:
        return {f"{p:.1f}": None for p in merged["percents"]}
    cum = np.cumsum(hist)
    nb = len(hist)
    for p in merged["percents"]:
        target = max(p / 100.0 * total, 1e-9)
        b = int(np.searchsorted(cum, target, side="left"))
        out[f"{p:.1f}"] = ddsketch_value(min(b, nb - 1))
    return out


def _format_epoch_ms(ms: int) -> str:
    import datetime as dt

    return dt.datetime.fromtimestamp(ms / 1000.0, dt.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"


# ---------------- pipeline aggregations (host post-processing) ----------------

def _apply_pipelines(node: AggNode, buckets_ref) -> None:  # placeholder hook
    return


def _apply_bucket_pipelines(node: AggNode, result: dict) -> None:
    """Sibling pipeline aggs over this bucket agg's finalized buckets
    (reference `search/aggregations/pipeline/`): cumulative_sum, derivative
    attach per-bucket; *_bucket kinds attach as sibling values."""
    buckets = result.get("buckets")
    if not isinstance(buckets, list):
        return
    for p in node.pipelines:
        path = p.body.get("buckets_path", "_count")
        series = []
        for b in buckets:
            if path == "_count":
                series.append(float(b["doc_count"]))
            else:
                head = path.split(">")[0].split(".")[0]
                sub = b.get(head, {})
                leaf = path.split(".")[-1] if "." in path else "value"
                series.append(sub.get(leaf) if isinstance(sub, dict) else None)
        vals = [v for v in series if v is not None]
        if p.kind == "cumulative_sum":
            run = 0.0
            for b, v in zip(buckets, series):
                run += (v or 0.0)
                b[p.name] = {"value": run}
        elif p.kind == "derivative":
            prev = None
            for b, v in zip(buckets, series):
                b[p.name] = {"value": None if prev is None or v is None else v - prev}
                prev = v
        elif p.kind in ("avg_bucket", "sum_bucket", "min_bucket", "max_bucket", "stats_bucket"):
            if p.kind == "avg_bucket":
                result[p.name] = {"value": sum(vals) / len(vals) if vals else None}
            elif p.kind == "sum_bucket":
                result[p.name] = {"value": sum(vals)}
            elif p.kind == "min_bucket":
                result[p.name] = {"value": min(vals) if vals else None}
            elif p.kind == "max_bucket":
                result[p.name] = {"value": max(vals) if vals else None}
            else:
                result[p.name] = {"count": len(vals), "sum": sum(vals),
                                  "min": min(vals) if vals else None,
                                  "max": max(vals) if vals else None,
                                  "avg": sum(vals) / len(vals) if vals else None}
