"""Scripting subsystem: painless-lite (reference `modules/lang-painless`,
`script/ScriptService.java`), re-designed so score-context scripts trace to
XLA and host contexts interpret the same AST."""

from .painless_lite import (ScriptError, execute, parse, run_field_script,
                            run_ingest_script, run_update_script,
                            validate_device_script)

__all__ = ["ScriptError", "execute", "parse", "run_field_script",
           "run_ingest_script", "run_update_script", "validate_device_script"]
