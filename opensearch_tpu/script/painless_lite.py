"""painless-lite: a safe expression/statement language compiled to either a
host interpreter or a vectorized JAX evaluator.

The analog of the reference's Painless scripting engine
(`modules/lang-painless`, reference ScriptService / Script contexts in
`script/ScriptService.java`), re-designed for XLA: score-context scripts are
*traced* over dense per-document columns — `doc['f'].value` becomes a f32
vector over the whole segment, operators become VPU elementwise ops, and the
whole script fuses into the surrounding query program. Host contexts (update,
ingest processors, script_fields, sort) interpret the same AST per document.

Grammar (subset of Painless; r5 widened to the reference test-corpus
statement shapes):
  program   := stmt (';' stmt)* ';'?
  stmt      := type ID '=' expr | 'if' '(' expr ')' block ('else' (block|if))?
             | 'for' '(' [type] ID (in|':') expr ')' block
             | 'for' '(' init ';' cond ';' update ')' block
             | 'while' '(' expr ')' block | 'break' | 'continue'
             | 'return' expr | lvalue ('='|'+='|'-='|'*='|'/=') expr | expr
  expr      := ternary with ||, &&, ==/!=, </<=/>/>=, +/-, */ /%, unary -/!,
               ++/-- (pre/post), postfix .member, [index], call(args),
               lambda: ID '->' body | '(' params ')' '->' body, f(args)
Literals: numbers, 'str'/"str", true/false/null, [a,b] lists, [:] maps.
Collections carry the whitelisted java.util surface incl. sort(cmp),
removeIf(f), stream() pipelines (filter/map/sorted/distinct/limit/skip/
count/sum/average/min/max/anyMatch/allMatch/noneMatch/collect/findFirst),
String.splitOnToken, array .length. Device (score-context) tracing remains
arithmetic-only — loops/collections are host contexts, documented contract.

ASTs are nested tuples — hashable, so a device script can live inside a jit
static spec and share the XLA program cache across segments.
"""

from __future__ import annotations

import math
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

MAX_LOOP_ITERS = 100_000


class ScriptError(ValueError):
    """Analog of reference ScriptException (HTTP 400)."""


# =====================================================================
# lexer
# =====================================================================

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<comment>//[^\n]*|/\*.*?\*/)
  | (?P<num>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?[fFdDlL]?)
  | (?P<str>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
  | (?P<id>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op>==|!=|<=|>=|&&|\|\||->|\+=|-=|\*=|/=|%=|\+\+|--|[-+*/%!<>=?:.,()\[\]{};])
""", re.VERBOSE | re.DOTALL)

_KEYWORDS = {"def", "if", "else", "for", "in", "return", "true", "false", "null",
             "int", "long", "float", "double", "boolean", "String", "var",
             "while", "break", "continue"}

_TYPE_KWS = ("def", "var", "int", "long", "float", "double", "boolean",
             "String")


def _lex(src: str) -> List[Tuple[str, Any]]:
    toks: List[Tuple[str, Any]] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise ScriptError(f"unexpected character {src[pos]!r} at {pos}")
        pos = m.end()
        if m.lastgroup in ("ws", "comment"):
            continue
        if m.lastgroup == "num":
            t = m.group("num")
            if t[-1] in "fFdDlL":
                t = t[:-1]
            toks.append(("num", float(t) if ("." in t or "e" in t or "E" in t)
                         else int(t)))
        elif m.lastgroup == "str":
            raw = m.group("str")[1:-1]
            toks.append(("str", re.sub(
                r"\\(.)",
                lambda mm: {"n": "\n", "t": "\t", "r": "\r"}.get(mm.group(1),
                                                                mm.group(1)),
                raw)))
        elif m.lastgroup == "id":
            name = m.group("id")
            toks.append(("kw" if name in _KEYWORDS else "id", name))
        else:
            toks.append(("op", m.group("op")))
    toks.append(("eof", None))
    return toks


# =====================================================================
# parser -> tuple AST
# =====================================================================

class _Parser:
    def __init__(self, toks: List[Tuple[str, Any]]):
        self.toks = toks
        self.i = 0

    def peek(self) -> Tuple[str, Any]:
        return self.toks[self.i]

    def next(self) -> Tuple[str, Any]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind: str, val=None) -> bool:
        k, v = self.peek()
        if k == kind and (val is None or v == val):
            self.i += 1
            return True
        return False

    def expect(self, kind: str, val=None) -> Any:
        k, v = self.next()
        if k != kind or (val is not None and v != val):
            raise ScriptError(f"expected {val or kind}, got {v!r}")
        return v

    # ---- statements ----

    def program(self) -> tuple:
        stmts = []
        while self.peek()[0] != "eof":
            if self.accept("op", ";"):
                continue
            stmts.append(self.stmt())
        return ("block", tuple(stmts))

    def block(self) -> tuple:
        if self.accept("op", "{"):
            stmts = []
            while not self.accept("op", "}"):
                if self.accept("op", ";"):
                    continue
                stmts.append(self.stmt())
            return ("block", tuple(stmts))
        return ("block", (self.stmt(),))

    def stmt(self) -> tuple:
        k, v = self.peek()
        if k == "kw" and v in _TYPE_KWS:
            self.next()
            name = self.expect("id")
            self.expect("op", "=")
            return ("decl", name, self.expr())
        if k == "kw" and v == "if":
            return self._if()
        if k == "kw" and v == "while":
            self.next()
            self.expect("op", "(")
            cond = self.expr()
            self.expect("op", ")")
            return ("while", cond, self.block())
        if k == "kw" and v == "break":
            self.next()
            return ("break",)
        if k == "kw" and v == "continue":
            self.next()
            return ("continue",)
        if k == "kw" and v == "for":
            return self._for()
        if k == "kw" and v == "return":
            self.next()
            if self.peek() in (("op", ";"), ("eof", None)):
                return ("return", ("null",))
            return ("return", self.expr())
        expr = self.expr()
        kk, vv = self.peek()
        if kk == "op" and vv in ("=", "+=", "-=", "*=", "/=", "%="):
            self.next()
            rhs = self.expr()
            if expr[0] not in ("var", "member", "index"):
                raise ScriptError("invalid assignment target")
            return ("assign", vv, expr, rhs)
        return ("exprstmt", expr)

    def _for(self) -> tuple:
        """All three reference for-forms:
        `for (x in e)` / `for ([type] x : e)` (for-each) and the C-style
        `for (init; cond; update)` (the dominant shape in the reference's
        painless test corpus)."""
        self.expect("kw", "for")
        self.expect("op", "(")
        save = self.i
        # try for-each: optional type keyword, id, then `in` or `:`
        k, v = self.peek()
        if k == "kw" and v in _TYPE_KWS:
            self.next()
        if self.peek()[0] == "id":
            name = self.next()[1]
            if self.accept("kw", "in") or self.accept("op", ":"):
                it = self.expr()
                self.expect("op", ")")
                return ("for", name, it, self.block())
        # C-style: rewind and parse init; cond; update
        self.i = save
        init = None if self.peek() == ("op", ";") else self.stmt()
        self.expect("op", ";")
        cond = (("bool", True) if self.peek() == ("op", ";")
                else self.expr())
        self.expect("op", ";")
        update = None if self.peek() == ("op", ")") else self.stmt()
        self.expect("op", ")")
        return ("cfor", init, cond, update, self.block())

    def _if(self) -> tuple:
        self.expect("kw", "if")
        self.expect("op", "(")
        cond = self.expr()
        self.expect("op", ")")
        then = self.block()
        if self.accept("kw", "else"):
            if self.peek() == ("kw", "if"):
                return ("if", cond, then, ("block", (self._if(),)))
            return ("if", cond, then, self.block())
        return ("if", cond, then, ("block", ()))

    # ---- expressions (precedence climbing) ----

    def expr(self) -> tuple:
        return self.ternary()

    def ternary(self) -> tuple:
        c = self.or_()
        if self.accept("op", "?"):
            t = self.expr()
            self.expect("op", ":")
            f = self.expr()
            return ("cond", c, t, f)
        return c

    def _binop(self, sub, ops) -> tuple:
        left = sub()
        while True:
            k, v = self.peek()
            if k == "op" and v in ops:
                self.next()
                left = ("bin", v, left, sub())
            else:
                return left

    def or_(self):
        return self._binop(self.and_, ("||",))

    def and_(self):
        return self._binop(self.eq, ("&&",))

    def eq(self):
        return self._binop(self.cmp, ("==", "!="))

    def cmp(self):
        return self._binop(self.add, ("<", "<=", ">", ">="))

    def add(self):
        return self._binop(self.mul, ("+", "-"))

    def mul(self):
        return self._binop(self.unary, ("*", "/", "%"))

    def unary(self) -> tuple:
        if self.accept("op", "++"):
            return ("incdec", self.unary(), 1, True)
        if self.accept("op", "--"):
            return ("incdec", self.unary(), -1, True)
        if self.accept("op", "-"):
            return ("un", "-", self.unary())
        if self.accept("op", "!"):
            return ("un", "!", self.unary())
        if self.accept("op", "+"):
            return self.unary()
        return self.postfix()

    def postfix(self) -> tuple:
        e = self.primary()
        while True:
            if self.accept("op", "."):
                name = self.next()
                if name[0] not in ("id", "kw"):
                    raise ScriptError(f"expected member name, got {name[1]!r}")
                if self.accept("op", "("):
                    args = self._args()
                    e = ("call", e, name[1], tuple(args))
                else:
                    e = ("member", e, name[1])
            elif self.accept("op", "["):
                idx = self.expr()
                self.expect("op", "]")
                e = ("index", e, idx)
            elif self.accept("op", "++"):
                e = ("incdec", e, 1, False)
            elif self.accept("op", "--"):
                e = ("incdec", e, -1, False)
            elif e[0] in ("var", "lambda") and self.accept("op", "("):
                e = ("invoke", e, tuple(self._args()))   # f(x): lambda call
            else:
                return e

    def _args(self) -> List[tuple]:
        args: List[tuple] = []
        if self.accept("op", ")"):
            return args
        args.append(self.expr())
        while self.accept("op", ","):
            args.append(self.expr())
        self.expect("op", ")")
        return args

    def _peek_lambda_params(self) -> Optional[tuple]:
        """Called with '(' already consumed: scan ahead for the
        `id (, id)* ) ->` (or `) ->`) pattern WITHOUT consuming; on match,
        consume through '->' and return the parameter tuple."""
        j = self.i
        params = []
        if self.toks[j][0] == "id":
            params.append(self.toks[j][1])
            j += 1
            while self.toks[j] == ("op", ","):
                if self.toks[j + 1][0] != "id":
                    return None
                params.append(self.toks[j + 1][1])
                j += 2
        if self.toks[j] != ("op", ")") or self.toks[j + 1] != ("op", "->"):
            return None
        self.i = j + 2
        return tuple(params)

    def _lambda_body(self) -> tuple:
        if self.peek() == ("op", "{"):
            return self.block()
        return ("block", (("return", self.expr()),))

    def primary(self) -> tuple:
        k, v = self.next()
        if k == "num":
            return ("num", v)
        if k == "str":
            return ("strlit", v)
        if k == "kw" and v == "true":
            return ("bool", True)
        if k == "kw" and v == "false":
            return ("bool", False)
        if k == "kw" and v == "null":
            return ("null",)
        if k == "id":
            if self.peek() == ("op", "->"):        # x -> expr
                self.next()
                return ("lambda", (v,), self._lambda_body())
            return ("var", v)
        if k == "op" and v == "(":
            params = self._peek_lambda_params()
            if params is not None:                 # (a, b) -> expr
                return ("lambda", params, self._lambda_body())
            e = self.expr()
            self.expect("op", ")")
            return e
        if k == "op" and v == "[":
            if self.accept("op", ":"):  # [:] empty map
                self.expect("op", "]")
                return ("maplit", ())
            items = []
            if not self.accept("op", "]"):
                first = self.expr()
                if self.accept("op", ":"):  # map literal
                    pairs = [(first, self.expr())]
                    while self.accept("op", ","):
                        pk = self.expr()
                        self.expect("op", ":")
                        pairs.append((pk, self.expr()))
                    self.expect("op", "]")
                    return ("maplit", tuple(pairs))
                items.append(first)
                while self.accept("op", ","):
                    items.append(self.expr())
                self.expect("op", "]")
            return ("listlit", tuple(items))
        raise ScriptError(f"unexpected token {v!r}")


def parse(source: str) -> tuple:
    """Parse script source -> hashable tuple AST (cached)."""
    return _parse_cached(source)


_parse_cache: Dict[str, tuple] = {}


def _parse_cached(source: str) -> tuple:
    ast = _parse_cache.get(source)
    if ast is None:
        ast = _Parser(_lex(source)).program()
        if len(_parse_cache) > 4096:
            _parse_cache.clear()
        _parse_cache[source] = ast
    return ast


def referenced_doc_fields(ast: tuple) -> Tuple[str, ...]:
    """Fields read via doc['f'] / doc.f — drives per-segment column binding."""
    out: List[str] = []

    def walk(n):
        if not isinstance(n, tuple) or not n:
            return
        if n[0] == "index" and n[1] == ("var", "doc") \
                and isinstance(n[2], tuple) and n[2][0] == "strlit":
            out.append(n[2][1])
        elif n[0] == "member" and n[1] == ("var", "doc") and isinstance(n[2], str):
            out.append(n[2])
        for c in n:
            walk(c)
    walk(ast)
    seen, uniq = set(), []
    for f in out:
        if f not in seen:
            seen.add(f)
            uniq.append(f)
    return tuple(uniq)


# =====================================================================
# host interpreter (update / ingest / script_fields / sort contexts)
# =====================================================================

class _Return(Exception):
    def __init__(self, value):
        self.value = value


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


class _Lambda:
    """A painless lambda closing over the enclosing scope (reference
    lambdas capture effectively-final locals; we shadow + restore)."""

    __slots__ = ("params", "body", "env")

    def __init__(self, params, body, env):
        self.params = params
        self.body = body
        self.env = env

    def __call__(self, *args):
        saved = {p: self.env.vars.get(p, _MISSING) for p in self.params}
        self.env.vars.update(dict(zip(self.params, args)))
        try:
            return _exec_block(self.body, self.env)
        except _Return as r:
            return r.value
        except (_Break, _Continue):
            # real Painless rejects break/continue inside a lambda at
            # compile time; it must never unwind into the CALLER's loop
            raise ScriptError("break/continue not allowed in a lambda")
        finally:
            for p, old in saved.items():
                if old is _MISSING:
                    self.env.vars.pop(p, None)
                else:
                    self.env.vars[p] = old


_MISSING = object()


_MATH_FNS: Dict[str, Callable] = {
    "log": math.log, "log10": math.log10, "sqrt": math.sqrt, "abs": abs,
    "exp": math.exp, "pow": math.pow, "min": min, "max": max,
    "floor": math.floor, "ceil": math.ceil, "round": round,
    "sin": math.sin, "cos": math.cos, "tan": math.tan, "atan2": math.atan2,
}
_MATH_CONSTS = {"PI": math.pi, "E": math.e}


class _DocValuesView:
    """Host `doc['field']` — mimics reference ScriptDocValues."""

    def __init__(self, values: list):
        self.values = values

    @property
    def value(self):
        if not self.values:
            raise ScriptError("A document doesn't have a value for a field")
        return self.values[0]

    def size(self):
        return len(self.values)

    @property
    def empty(self):
        return not self.values

    @property
    def length(self):
        return len(self.values)

    def get(self, i):
        return self.values[int(i)]

    def contains(self, v):
        return v in self.values


HostDocValue = _DocValuesView     # public alias (search/derived.py)


class HostEnv:
    """Variable scope + builtins for the host interpreter."""

    def __init__(self, variables: Dict[str, Any]):
        self.vars = dict(variables)

    def lookup(self, name: str):
        if name in self.vars:
            return self.vars[name]
        if name == "Math":
            return "__Math__"
        raise ScriptError(f"unknown variable [{name}]")


def execute(ast_or_src, variables: Dict[str, Any]) -> Any:
    """Run a script on the host; returns the `return` value or the value of
    the final expression statement (Painless's implicit return)."""
    ast = parse(ast_or_src) if isinstance(ast_or_src, str) else ast_or_src
    env = HostEnv(variables)
    try:
        return _exec_block(ast, env)
    except _Return as r:
        return r.value
    except (_Break, _Continue):
        raise ScriptError("break/continue outside of a loop")
    except ScriptError:
        raise
    except (ZeroDivisionError, IndexError, TypeError, KeyError, ValueError,
            OverflowError, AttributeError, RecursionError) as e:
        # runtime faults keep the ScriptError contract (callers map it to 400)
        raise ScriptError(f"runtime error: {type(e).__name__}: {e}")


def _exec_block(block: tuple, env: HostEnv) -> Any:
    last = None
    for st in block[1]:
        last = _exec_stmt(st, env)
    return last


def _exec_stmt(st: tuple, env: HostEnv) -> Any:  # noqa: C901
    op = st[0]
    if op == "decl":
        env.vars[st[1]] = _eval(st[2], env)
        return None
    if op == "if":
        if _truthy(_eval(st[1], env)):
            return _exec_block(st[2], env)
        return _exec_block(st[3], env)
    if op == "for":
        _, name, it_expr, body = st
        it = _eval(it_expr, env)
        if isinstance(it, _DocValuesView):
            it = it.values
        if not isinstance(it, (list, tuple, dict)):
            raise ScriptError("for-in requires a list or map")
        if isinstance(it, dict):
            it = list(it.keys())
        for i, item in enumerate(it):
            if i >= MAX_LOOP_ITERS:
                raise ScriptError("loop iteration limit exceeded")
            env.vars[name] = item
            try:
                _exec_block(body, env)
            except _Break:
                break
            except _Continue:
                continue
        return None
    if op == "cfor":
        _, init, cond, update, body = st
        if init is not None:
            _exec_stmt(init, env)
        n = 0
        while _truthy(_eval(cond, env)):
            if n >= MAX_LOOP_ITERS:
                raise ScriptError("loop iteration limit exceeded")
            n += 1
            try:
                _exec_block(body, env)
            except _Break:
                break
            except _Continue:
                pass
            if update is not None:
                _exec_stmt(update, env)
        return None
    if op == "while":
        _, cond, body = st
        n = 0
        while _truthy(_eval(cond, env)):
            if n >= MAX_LOOP_ITERS:
                raise ScriptError("loop iteration limit exceeded")
            n += 1
            try:
                _exec_block(body, env)
            except _Break:
                break
            except _Continue:
                continue
        return None
    if op == "break":
        raise _Break()
    if op == "continue":
        raise _Continue()
    if op == "return":
        raise _Return(_eval(st[1], env))
    if op == "assign":
        _, aop, target, rhs = st
        val = _eval(rhs, env)
        if aop != "=":
            cur = _eval(target, env)
            val = _apply_binop(aop[0], cur, val)
        _assign(target, val, env)
        return None
    if op == "exprstmt":
        return _eval(st[1], env)
    raise ScriptError(f"unknown statement {op}")


def _assign(target: tuple, val, env: HostEnv) -> None:
    kind = target[0]
    if kind == "var":
        env.vars[target[1]] = val
        return
    if kind == "member":
        obj = _eval(target[1], env)
        if isinstance(obj, dict):
            obj[target[2]] = val
            return
        raise ScriptError(f"cannot assign member [{target[2]}]")
    if kind == "index":
        obj = _eval(target[1], env)
        key = _eval(target[2], env)
        if isinstance(obj, dict):
            obj[key] = val
            return
        if isinstance(obj, list):
            obj[int(key)] = val
            return
        raise ScriptError("cannot index-assign")
    raise ScriptError("invalid assignment target")


def _truthy(v) -> bool:
    if isinstance(v, _DocValuesView):
        return not v.empty
    return bool(v)


def _apply_binop(op: str, a, b):  # noqa: C901
    if op == "+":
        if isinstance(a, str) or isinstance(b, str):
            return _to_str(a) + _to_str(b)
        if isinstance(a, list) and isinstance(b, list):
            return a + b
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    if op == "/":
        if isinstance(a, int) and isinstance(b, int):
            if b == 0:
                raise ScriptError("/ by zero")
            q = a // b
            if q < 0 and q * b != a:
                q += 1  # Java integer division truncates toward zero
            return q
        return a / b
    if op == "%":
        if isinstance(a, int) and isinstance(b, int):
            r = abs(a) % abs(b)
            return -r if a < 0 else r  # Java remainder semantics
        return math.fmod(a, b)
    if op == "==":
        return _unwrap(a) == _unwrap(b)
    if op == "!=":
        return _unwrap(a) != _unwrap(b)
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    if op == ">=":
        return a >= b
    raise ScriptError(f"unknown operator {op}")


def _unwrap(v):
    return v.value if isinstance(v, _DocValuesView) else v


def _to_str(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(v)
    if v is None:
        return "null"
    return str(v)


def _eval(e: tuple, env: HostEnv) -> Any:  # noqa: C901
    kind = e[0]
    if kind == "num":
        return e[1]
    if kind == "strlit":
        return e[1]
    if kind == "bool":
        return e[1]
    if kind == "null":
        return None
    if kind == "var":
        return env.lookup(e[1])
    if kind == "listlit":
        return [_eval(x, env) for x in e[1]]
    if kind == "maplit":
        return {_eval(k, env): _eval(v, env) for k, v in e[1]}
    if kind == "cond":
        return _eval(e[2], env) if _truthy(_eval(e[1], env)) else _eval(e[3], env)
    if kind == "lambda":
        return _Lambda(e[1], e[2], env)
    if kind == "invoke":
        fn = _eval(e[1], env)
        if not callable(fn):
            raise ScriptError("not a function")
        return fn(*[_eval(a, env) for a in e[2]])
    if kind == "incdec":
        _, target, delta, pre = e
        cur = _eval(target, env)
        new = cur + delta
        _assign(target, new, env)
        return new if pre else cur
    if kind == "un":
        v = _eval(e[2], env)
        return (not _truthy(v)) if e[1] == "!" else -v
    if kind == "bin":
        op = e[1]
        if op == "&&":
            return _truthy(_eval(e[2], env)) and _truthy(_eval(e[3], env))
        if op == "||":
            return _truthy(_eval(e[2], env)) or _truthy(_eval(e[3], env))
        return _apply_binop(op, _eval(e[2], env), _eval(e[3], env))
    if kind == "member":
        return _member(_eval(e[1], env), e[2])
    if kind == "index":
        obj = _eval(e[1], env)
        key = _eval(e[2], env)
        if isinstance(obj, _LazyDoc):
            return obj.get(key)
        if isinstance(obj, dict):
            return obj.get(key)
        if isinstance(obj, (list, str)):
            return obj[int(key)]
        if isinstance(obj, _DocValuesView):
            return obj.get(key)
        raise ScriptError(f"cannot index {type(obj).__name__}")
    if kind == "call":
        return _call(e, env)
    raise ScriptError(f"cannot evaluate {kind}")


def _member(obj, name: str):  # noqa: C901
    if isinstance(obj, _LazyDoc):
        return obj.get(name)
    if obj == "__Math__":
        if name in _MATH_CONSTS:
            return _MATH_CONSTS[name]
        raise ScriptError(f"unknown Math member [{name}]")
    if isinstance(obj, dict):
        return obj.get(name)
    if isinstance(obj, _DocValuesView):
        if name == "value":
            return obj.value
        if name == "empty":
            return obj.empty
        if name == "length":
            return obj.length
        if name == "values":
            return obj.values
    if isinstance(obj, str) and name == "length":
        return len(obj)
    if isinstance(obj, list) and name == "length":
        return len(obj)     # Java array .length (splitOnToken results)
    raise ScriptError(f"unknown member [{name}] on {type(obj).__name__}")


def _call(e: tuple, env: HostEnv):  # noqa: C901
    _, obj_expr, name, arg_exprs = e
    if obj_expr == ("var", "Math"):
        fn = _MATH_FNS.get(name)
        if fn is None:
            raise ScriptError(f"unknown Math function [{name}]")
        return fn(*[_eval(a, env) for a in arg_exprs])
    obj = _eval(obj_expr, env)
    args = [_eval(a, env) for a in arg_exprs]
    if isinstance(obj, _DocValuesView):
        if name == "size":
            return obj.size()
        if name == "contains":
            return obj.contains(args[0])
        if name == "get":
            return obj.get(args[0])
        if name == "isEmpty":
            return obj.empty
    if isinstance(obj, _Stream):
        return obj.method(name, args)
    if isinstance(obj, str):
        return _str_method(obj, name, args)
    if isinstance(obj, list):
        return _list_method(obj, name, args)
    if isinstance(obj, dict):
        return _map_method(obj, name, args)
    if isinstance(obj, (int, float)):
        if name == "intValue":
            return int(obj)
        if name == "doubleValue" or name == "floatValue":
            return float(obj)
        if name == "longValue":
            return int(obj)
        if name == "toString":
            return _to_str(obj)
    raise ScriptError(f"unknown method [{name}] on {type(obj).__name__}")


def _str_method(s: str, name: str, args: list):  # noqa: C901
    if name == "contains":
        return args[0] in s
    if name == "startsWith":
        return s.startswith(args[0])
    if name == "endsWith":
        return s.endswith(args[0])
    if name == "toLowerCase":
        return s.lower()
    if name == "toUpperCase":
        return s.upper()
    if name == "trim":
        return s.strip()
    if name == "length":
        return len(s)
    if name == "substring":
        return s[int(args[0]):] if len(args) == 1 else s[int(args[0]): int(args[1])]
    if name == "replace":
        return s.replace(args[0], args[1])
    if name == "split":
        return re.split(args[0], s)
    if name == "splitOnToken":
        # Java limit = max number of RESULT pieces (Python maxsplit + 1)
        return s.split(args[0], int(args[1]) - 1) if len(args) == 2 \
            and int(args[1]) > 0 else s.split(args[0])
    if name == "indexOf":
        return s.find(args[0])
    if name == "equals":
        return s == args[0]
    if name == "equalsIgnoreCase":
        return s.lower() == str(args[0]).lower()
    if name == "isEmpty":
        return len(s) == 0
    if name == "charAt":
        return s[int(args[0])]
    if name == "toString":
        return s
    raise ScriptError(f"unknown String method [{name}]")


def _cmp_key(fn):
    """Painless comparator -> sort key. int() truncation matches Java's
    def-to-int cast of the comparator return."""
    import functools
    return functools.cmp_to_key(lambda a, b: int(fn(a, b)))


def _list_method(lst: list, name: str, args: list):  # noqa: C901
    if name == "add":
        if len(args) == 2:
            lst.insert(int(args[0]), args[1])
        else:
            lst.append(args[0])
        return None
    if name == "remove":
        v = args[0]
        if isinstance(v, int):
            return lst.pop(v)
        lst.remove(v)
        return None
    if name == "removeIf":
        keep = [x for x in lst if not _truthy(args[0](x))]
        changed = len(keep) != len(lst)
        lst[:] = keep
        return changed
    if name == "size":
        return len(lst)
    if name == "contains":
        return args[0] in lst
    if name == "get":
        return lst[int(args[0])]
    if name == "indexOf":
        return lst.index(args[0]) if args[0] in lst else -1
    if name == "isEmpty":
        return len(lst) == 0
    if name == "addAll":
        lst.extend(args[0])
        return None
    if name == "sort":
        if args and callable(args[0]):
            lst.sort(key=_cmp_key(args[0]))
        else:
            lst.sort()
        return None
    if name == "stream":
        return _Stream(list(lst))
    if name == "each":
        for x in list(lst):
            args[0](x)
        return None
    raise ScriptError(f"unknown List method [{name}]")


class _Stream:
    """Painless stream pipeline over a host list (the java.util.stream
    subset the reference's painless whitelist exposes; terminal ops
    materialize eagerly — scripts are bounded by MAX_LOOP_ITERS anyway)."""

    __slots__ = ("items",)

    def __init__(self, items):
        self.items = items

    def method(self, name, args):
        if name == "filter":
            return _Stream([x for x in self.items if _truthy(args[0](x))])
        if name == "map" or name == "mapToDouble" or name == "mapToInt" \
                or name == "mapToLong":
            out = [args[0](x) for x in self.items]
            if name == "mapToInt" or name == "mapToLong":
                out = [int(x) for x in out]
            elif name == "mapToDouble":
                out = [float(x) for x in out]
            return _Stream(out)
        if name == "sorted":
            if args and callable(args[0]):
                return _Stream(sorted(self.items, key=_cmp_key(args[0])))
            return _Stream(sorted(self.items))
        if name == "distinct":
            # equals()-based like Java (lists/maps compare by value):
            # O(n^2) contains scan for unhashables, set for primitives
            seen, out = set(), []
            for x in self.items:
                if isinstance(x, (int, float, str, bool, type(None))):
                    k = (type(x).__name__, x)
                    if k in seen:
                        continue
                    seen.add(k)
                elif x in out:
                    continue
                out.append(x)
            return _Stream(out)
        if name == "limit":
            return _Stream(self.items[: int(args[0])])
        if name == "skip":
            return _Stream(self.items[int(args[0]):])
        if name == "count":
            return len(self.items)
        if name == "sum":
            return sum(self.items)
        if name == "average":
            return (sum(self.items) / len(self.items)) if self.items else None
        if name == "min":
            return min(self.items) if self.items else None
        if name == "max":
            return max(self.items) if self.items else None
        if name == "anyMatch":
            return any(_truthy(args[0](x)) for x in self.items)
        if name == "allMatch":
            return all(_truthy(args[0](x)) for x in self.items)
        if name == "noneMatch":
            return not any(_truthy(args[0](x)) for x in self.items)
        if name == "forEach":
            for x in self.items:
                args[0](x)
            return None
        if name == "collect" or name == "toList":
            return list(self.items)
        if name == "findFirst" or name == "findAny":
            return self.items[0] if self.items else None
        raise ScriptError(f"unknown Stream method [{name}]")


def _map_method(m: dict, name: str, args: list):  # noqa: C901
    if name == "containsKey":
        return args[0] in m
    if name == "get":
        return m.get(args[0])
    if name == "getOrDefault":
        return m.get(args[0], args[1])
    if name == "put":
        prev = m.get(args[0])
        m[args[0]] = args[1]
        return prev
    if name == "remove":
        return m.pop(args[0], None)
    if name == "keySet":
        return list(m.keys())
    if name == "values":
        return list(m.values())
    if name == "size":
        return len(m)
    if name == "isEmpty":
        return len(m) == 0
    if name == "entrySet":
        return [{"key": k, "value": v} for k, v in m.items()]
    raise ScriptError(f"unknown Map method [{name}]")


# =====================================================================
# script contexts (host)
# =====================================================================

def run_update_script(source: str, params: Optional[dict], src: dict,
                      doc_meta: dict) -> Tuple[dict, str]:
    """Update-context: mutate ctx._source; ctx.op in {index,none,delete}
    (reference UpdateHelper.executeScriptedUpsert)."""
    ctx = {"_source": src, "op": "index", **doc_meta}
    execute(source, {"ctx": ctx, "params": params or {}})
    op = ctx.get("op", "index")
    if op == "noop":
        op = "none"
    if op not in ("index", "none", "delete", "create"):
        raise ScriptError(f"invalid ctx.op [{op}]")
    return ctx["_source"], op


def run_ingest_script(source: str, params: Optional[dict], doc: dict) -> None:
    """Ingest-processor context: the document IS ctx (flat mutation)."""
    execute(source, {"ctx": doc, "params": params or {}})


def doc_view_for(seg, doc: int, field: str) -> _DocValuesView:
    """Build `doc['field']` for one stored doc from segment columns."""
    col = seg.numeric_cols.get(field)
    if col is not None:
        if col.present[doc]:
            v = col.values[doc]
            return _DocValuesView([float(v) if col.kind == "float" else int(v)])
        return _DocValuesView([])
    kcol = seg.keyword_cols.get(field)
    if kcol is not None:
        a, b = int(kcol.starts[doc]), int(kcol.starts[doc + 1])
        return _DocValuesView([kcol.vocab[o] for o in kcol.ords[a:b]])
    gcol = seg.geo_cols.get(field) if hasattr(seg, "geo_cols") else None
    if gcol is not None and gcol.present[doc]:
        return _DocValuesView([{"lat": float(gcol.lat[doc]),
                                "lon": float(gcol.lon[doc])}])
    return _DocValuesView([])


class _LazyDoc:
    """Lazy doc map: only referenced fields materialize views."""

    def __init__(self, seg, doc: int):
        self.seg = seg
        self.doc = doc
        self._cache: Dict[str, _DocValuesView] = {}

    def get(self, field):
        v = self._cache.get(field)
        if v is None:
            v = self._cache[field] = doc_view_for(self.seg, self.doc, field)
        return v

    def __contains__(self, field):
        return True


def run_field_script(source: str, params: Optional[dict], seg, doc: int,
                     score: Optional[float] = None,
                     extra: Optional[dict] = None) -> Any:
    """script_fields / script-sort / field-context evaluation for one doc."""
    variables: Dict[str, Any] = {"doc": _LazyDoc(seg, doc), "params": params or {},
                                 "_score": 0.0 if score is None else float(score)}
    ast = parse(source)
    if _references_source(ast):
        variables["_source"] = seg.sources[doc] if hasattr(seg, "sources") else {}
    if extra:
        variables.update(extra)
    return execute(ast, variables)


def _references_source(ast: tuple) -> bool:
    def walk(n) -> bool:
        if not isinstance(n, tuple) or not n:
            return False
        if n == ("var", "_source"):
            return True
        return any(walk(c) for c in n if isinstance(c, tuple))
    return walk(ast)


# =====================================================================
# device (vectorized JAX) evaluator — score/filter contexts
# =====================================================================

def validate_device_script(source: str) -> tuple:
    """Parse + check the script is expressible as a traced computation:
    decls + if-less expressions + final return/expression. Returns the AST."""
    ast = parse(source)
    for st in ast[1]:
        if st[0] not in ("decl", "return", "exprstmt", "assign"):
            raise ScriptError(
                f"score scripts support expressions and `def` locals; "
                f"got a `{st[0]}` statement (use ternaries instead of if)")
    return ast


class DeviceEnv:
    """Bindings for the traced evaluator. `columns[f]` is the per-doc value
    vector for doc['f'].value; `present[f]` the existence mask."""

    def __init__(self, jnp, columns: Dict[str, Any], present: Dict[str, Any],
                 score, params: Dict[str, Any], ndocs: int):
        self.jnp = jnp
        self.columns = columns
        self.present = present
        self.score = score
        self.params = params
        self.ndocs = ndocs
        self.locals: Dict[str, Any] = {}


def eval_device(ast: tuple, env: DeviceEnv):
    """Trace the script over dense columns -> f32[ndocs] vector."""
    result = None
    for st in ast[1]:
        if st[0] == "decl":
            env.locals[st[1]] = _dev_expr(st[2], env)
        elif st[0] == "assign":
            if st[2][0] != "var":
                raise ScriptError("device scripts only assign local variables")
            val = _dev_expr(st[3], env)
            if st[1] != "=":
                if st[2][1] not in env.locals:
                    raise ScriptError(f"unknown variable [{st[2][1]}]")
                val = _dev_binop(env, st[1][0], env.locals[st[2][1]], val)
            env.locals[st[2][1]] = val
        elif st[0] == "return":
            return _as_vec(_dev_expr(st[1], env), env)
        else:  # exprstmt
            result = _dev_expr(st[1], env)
    if result is None:
        raise ScriptError("script has no result expression")
    return _as_vec(result, env)


def _as_vec(v, env: DeviceEnv):
    jnp = env.jnp
    arr = jnp.asarray(v, jnp.float32)
    if arr.ndim == 0:
        arr = jnp.full(env.ndocs, arr)
    return arr


_DEV_MATH = {"log": "log", "log10": "log10", "sqrt": "sqrt", "abs": "abs",
             "exp": "exp", "floor": "floor", "ceil": "ceil", "round": "round",
             "sin": "sin", "cos": "cos", "tan": "tan"}


def _dev_binop(env: DeviceEnv, op: str, a, b):  # noqa: C901
    jnp = env.jnp
    if op == "+":
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    if op == "/":
        return a / b
    if op == "%":
        return jnp.where(jnp.asarray(a) < 0, -(jnp.abs(a) % jnp.abs(b)),
                         jnp.abs(a) % jnp.abs(b))
    if op == "==":
        return a == b
    if op == "!=":
        return a != b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    if op == ">=":
        return a >= b
    raise ScriptError(f"unsupported device operator {op}")


def _dev_expr(e: tuple, env: DeviceEnv):  # noqa: C901
    jnp = env.jnp
    kind = e[0]
    if kind == "num":
        return e[1]
    if kind == "bool":
        return e[1]
    if kind == "null":
        return 0.0
    if kind == "var":
        name = e[1]
        if name == "_score":
            if env.score is None:
                raise ScriptError("_score unavailable in this context")
            return env.score
        if name in env.locals:
            return env.locals[name]
        raise ScriptError(f"unknown variable [{name}] in score script")
    if kind == "member":
        obj, name = e[1], e[2]
        if obj == ("var", "Math"):
            if name in _MATH_CONSTS:
                return _MATH_CONSTS[name]
            raise ScriptError(f"unknown Math member [{name}]")
        if obj == ("var", "params"):
            if name not in env.params:
                raise ScriptError(f"unknown param [{name}]")
            return env.params[name]
        dv = _dev_docvalues(obj, env)
        if dv is not None:
            col, present = dv
            if name == "value":
                return col
            if name == "empty":
                return ~present
            if name == "length":
                return present.astype(jnp.float32)
        raise ScriptError(f"unsupported member [{name}] in score script")
    if kind == "index":
        if e[1] == ("var", "params") and e[2][0] == "strlit":
            key = e[2][1]
            if key not in env.params:
                raise ScriptError(f"unknown param [{key}]")
            return env.params[key]
        raise ScriptError("only params['k'] / doc['f'].value indexing on device")
    if kind == "call":
        _, obj, name, args = e
        if obj == ("var", "Math"):
            vals = [_dev_expr(a, env) for a in args]
            if name == "pow":
                return jnp.power(vals[0], vals[1])
            if name == "min":
                return jnp.minimum(vals[0], vals[1])
            if name == "max":
                return jnp.maximum(vals[0], vals[1])
            fn = _DEV_MATH.get(name)
            if fn is None:
                raise ScriptError(f"unknown Math function [{name}]")
            return getattr(jnp, fn)(*vals)
        dv = _dev_docvalues(obj, env)
        if dv is not None:
            col, present = dv
            if name == "size":
                return present.astype(jnp.float32)
            if name == "isEmpty":
                return ~present
        raise ScriptError(f"unsupported call [{name}] in score script")
    if kind == "cond":
        c = _dev_expr(e[1], env)
        t = _dev_expr(e[2], env)
        f = _dev_expr(e[3], env)
        return jnp.where(c, t, f)
    if kind == "un":
        v = _dev_expr(e[2], env)
        if e[1] == "!":
            return ~jnp.asarray(v, bool)
        return -v if not isinstance(v, (int, float)) else -v
    if kind == "bin":
        op = e[1]
        if op == "&&":
            return (jnp.asarray(_dev_expr(e[2], env), bool)
                    & jnp.asarray(_dev_expr(e[3], env), bool))
        if op == "||":
            return (jnp.asarray(_dev_expr(e[2], env), bool)
                    | jnp.asarray(_dev_expr(e[3], env), bool))
        return _dev_binop(env, op, _dev_expr(e[2], env), _dev_expr(e[3], env))
    raise ScriptError(f"cannot trace {kind} on device")


def _dev_docvalues(obj: tuple, env: DeviceEnv):
    """Match doc['f'] / doc.f -> (values vector, present mask) or None."""
    field = None
    if obj[0] == "index" and obj[1] == ("var", "doc") and obj[2][0] == "strlit":
        field = obj[2][1]
    elif obj[0] == "member" and obj[1] == ("var", "doc"):
        field = obj[2]
    if field is None:
        return None
    jnp = env.jnp
    if field not in env.columns:
        return (jnp.zeros(env.ndocs, jnp.float32),
                jnp.zeros(env.ndocs, bool))
    return env.columns[field], env.present[field]
