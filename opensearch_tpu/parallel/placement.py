"""Shard→device placement: the allocation decider layer.

Reference `cluster/routing/allocation/` (BalancedShardsAllocator +
SameShardAllocationDecider): copies of the same shard never share a device,
load balances by copy count per device, and failed devices trigger
re-allocation of their copies.

In the TPU runtime a "node" is a device (chip): primaries and replicas are
re-hosted immutable segment arrays on their assigned device
(Segment.device_arrays(device)), so placement == where those arrays live and
which chip serves that copy's searches.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional, Tuple


@dataclass
class ShardCopy:
    shard: int
    replica: int          # 0 = primary
    device: Optional[int] # device ordinal, None = unassigned
    state: str = "STARTED"  # STARTED | UNASSIGNED

    @property
    def primary(self) -> bool:
        return self.replica == 0


@dataclass
class AllocationTable:
    copies: List[ShardCopy] = dc_field(default_factory=list)

    def for_shard(self, shard: int) -> List[ShardCopy]:
        return [c for c in self.copies if c.shard == shard]

    def assigned(self) -> List[ShardCopy]:
        return [c for c in self.copies if c.device is not None]

    def unassigned(self) -> List[ShardCopy]:
        return [c for c in self.copies if c.device is None]


class ShardAllocator:
    """Round-robin with same-shard awareness over a set of live devices."""

    def __init__(self, n_devices: int):
        self.n_devices = n_devices
        self.failed: set = set()

    def live_devices(self) -> List[int]:
        return [d for d in range(self.n_devices) if d not in self.failed]

    def allocate(self, n_shards: int, n_replicas: int) -> AllocationTable:
        table = AllocationTable()
        load: Dict[int, int] = {d: 0 for d in self.live_devices()}
        for s in range(n_shards):
            used: set = set()
            for r in range(n_replicas + 1):
                dev = self._pick(load, used)
                table.copies.append(ShardCopy(s, r, dev,
                                              "STARTED" if dev is not None
                                              else "UNASSIGNED"))
                if dev is not None:
                    used.add(dev)
                    load[dev] += 1
        return table

    def _pick(self, load: Dict[int, int], exclude: set) -> Optional[int]:
        """Least-loaded live device not already holding a copy of this shard
        (SameShardAllocationDecider: a replica never lands with its
        primary)."""
        cands = [d for d in load if d not in exclude]
        if not cands:
            return None
        return min(cands, key=lambda d: (load[d], d))

    def fail_device(self, device: int, table: AllocationTable
                    ) -> List[ShardCopy]:
        """Mark a device failed and re-allocate its copies elsewhere.
        Returns the copies that changed (new device or UNASSIGNED)."""
        self.failed.add(device)
        load: Dict[int, int] = {d: 0 for d in self.live_devices()}
        for c in table.copies:
            if c.device is not None and c.device in load:
                load[c.device] += 1
        changed = []
        for c in table.copies:
            if c.device != device:
                continue
            peers = {p.device for p in table.for_shard(c.shard)
                     if p is not c and p.device is not None}
            dev = self._pick(load, peers | {device})
            c.device = dev
            c.state = "STARTED" if dev is not None else "UNASSIGNED"
            if dev is not None:
                load[dev] += 1
            changed.append(c)
        return changed
