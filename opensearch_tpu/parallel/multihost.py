"""Multi-host design: jax.distributed process groups under the existing
coordination state machine.

Reference analogs: `discovery/` + `transport-netty4` (node-to-node wire) and
`cluster/coordination/Coordinator.java` (membership). The TPU translation:

- **Wire layer**: there is none to write. `jax.distributed.initialize(
  coordinator_address, num_processes, process_id)` brings up the XLA
  runtime's cross-host world; collectives (psum/all_gather in
  `parallel/spmd.py`) then ride ICI within a slice and DCN across slices —
  the NCCL/MPI substitute is the compiler, not sockets.
- **Mesh**: `jax.devices()` after initialize returns ALL hosts' devices.
  `make_global_mesh` lays the (replica, shard) axes over them with shard
  axes packed host-local first, so a shard's per-segment scoring never
  crosses DCN and only the final all_gather top-k merge does.
- **Membership**: `cluster/coordination.py`'s election/publish state
  machine runs unchanged with one peer per process; its transport hooks
  (`send_publish`, `send_ack`) map onto host-to-host RPC which, in the
  jax.distributed world, is the coordinator service the runtime already
  maintains. Each process's Node owns the PRIMARY shards whose mesh slot
  lands on its local devices (shard_owner below).

Single-process environments cannot exercise initialize() itself; what IS
tested (tests/test_multihost.py) is the pure planning layer: config
validation, global device-count math, host-local shard packing, and
shard-ownership assignment — the parts a real two-host bringup consumes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple


@dataclass(frozen=True)
class MultiHostConfig:
    coordinator_address: str          # "host0:port" (reference discovery seed)
    num_processes: int
    process_id: int
    local_device_count: int = 8       # chips per host (v5e host = 8)

    def validate(self) -> None:
        if self.num_processes < 1:
            raise ValueError("num_processes must be >= 1")
        if not 0 <= self.process_id < self.num_processes:
            raise ValueError(
                f"process_id [{self.process_id}] out of range "
                f"[0, {self.num_processes})")
        if ":" not in self.coordinator_address:
            raise ValueError(
                "coordinator_address must be host:port "
                f"(got [{self.coordinator_address}])")
        if self.local_device_count < 1:
            raise ValueError("local_device_count must be >= 1")

    @property
    def global_device_count(self) -> int:
        return self.num_processes * self.local_device_count


def initialize(cfg: MultiHostConfig) -> None:
    """Bring up the cross-host XLA world. Call ONCE per process before any
    jax operation (reference: node bootstrap + discovery join)."""
    import jax

    cfg.validate()
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id)


def shard_layout(cfg: MultiHostConfig, n_shards: int
                 ) -> List[Tuple[int, int]]:
    """Shard slot -> (process, local_device). Shards pack host-local first
    so one shard's segments (and its scoring collectives) stay on one
    host's ICI; only the coordinator's top-k all_gather crosses DCN."""
    cfg.validate()
    if n_shards > cfg.global_device_count:
        raise ValueError(
            f"{n_shards} shards need more than the "
            f"{cfg.global_device_count} global devices")
    out = []
    for s in range(n_shards):
        proc = s // cfg.local_device_count
        local = s % cfg.local_device_count
        out.append((proc, local))
    return out


def shard_owner(cfg: MultiHostConfig, n_shards: int) -> List[int]:
    """Primary ownership per shard: the process whose local device hosts
    it (the analog of reference allocation deciders pinning primaries)."""
    return [p for p, _ in shard_layout(cfg, n_shards)]


def local_shards(cfg: MultiHostConfig, n_shards: int) -> List[int]:
    """The shard ids THIS process indexes/serves."""
    return [s for s, (p, _) in enumerate(shard_layout(cfg, n_shards))
            if p == cfg.process_id]


def make_global_mesh(cfg: MultiHostConfig, n_shards: int,
                     devices: Optional[list] = None):
    """(replica=1, shard=n_shards) mesh over the global device list in
    shard_layout order. `devices` defaults to jax.devices() (which is
    already globally ordered after initialize); tests pass the virtual
    CPU devices."""
    import jax
    from jax.sharding import Mesh
    import numpy as np

    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < n_shards:
        raise ValueError("not enough devices for the shard mesh")
    picked = np.array(devs[:n_shards]).reshape(1, n_shards)
    return Mesh(picked, axis_names=("replica", "shard"))


def put_global(arr, mesh, spec) -> "jax.Array":
    """Place a host array onto a (possibly multi-process) mesh sharding.

    Single process: plain device_put. Multi process: each process
    contributes only its ADDRESSABLE portion via
    `jax.make_array_from_process_local_data` — for `P("shard", ...)` that
    is the block of leading-axis rows whose mesh slot lands on this
    process's local devices; for replicated specs it is the full array.
    The host array is the same on every process (deterministic build), so
    the assembled global array is consistent without any host exchange."""
    import jax
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        # placement helper: callers own and register the resulting
        # residency (stacked-index builds)
        return jax.device_put(arr, sharding)  # oslint: disable=OSL506
    return jax.make_array_from_process_local_data(
        sharding, _local_block(arr, mesh, spec), global_shape=arr.shape)


def _local_block(arr, mesh, spec):
    """This process's addressable slice of `arr` under (mesh, spec):
    leading-axis block for shard-sharded arrays, whole array when
    replicated (replica axis has size 1 in our meshes)."""
    import jax
    import numpy as np

    names = list(getattr(spec, "_partitions", spec))
    if not names or names[0] != "shard":
        return arr
    n_shard = mesh.shape["shard"]
    shard_devs = mesh.devices.reshape(-1)[:n_shard]
    mine = [i for i, d in enumerate(shard_devs)
            if d.process_index == jax.process_index()]
    rows = arr.shape[0] // n_shard
    if not mine:
        # this process owns no shard slot (n_shards < global devices):
        # contribute an empty block
        return np.asarray(arr[:0])
    lo, hi = min(mine), max(mine) + 1
    assert mine == list(range(lo, hi)), "shard axis must be process-major"
    return np.asarray(arr[lo * rows: hi * rows])
