from .service import MeshSearchService
from .spmd import (StackedShardIndex, build_distributed_search,
                   build_term_sharded_score, make_mesh, pack_query_batch)

__all__ = ["MeshSearchService", "StackedShardIndex",
           "build_distributed_search", "build_term_sharded_score",
           "make_mesh", "pack_query_batch"]
