"""MeshSearchService: the SPMD mesh path wired into the Node's REST search.

When a multi-device mesh is available (real TPU pod slice, or the virtual
8-CPU-device test mesh), eligible term-group queries dispatch over
`parallel/spmd.py`'s distributed program instead of the host shard loop:
per-shard scoring runs SPMD over the `shard` mesh axis, collection stats
(df, N, sum_dl) psum over ICI (device-side DFS phase), and per-shard top-ks
merge with an all_gather — the reference's coordinator fan-out
(`action/search/TransportSearchAction.java`,
`action/search/SearchPhaseController.java`) without the transport layer.

Fallback contract: `try_search` returns None whenever the query shape or
index layout isn't mesh-ready (complex plans, multi-segment shards, window
too deep), and the Node falls back to the host loop — identical results
either way (asserted by tests/test_distributed.py).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..index.segment import next_pow2
from .spmd import (StackedShardIndex, build_distributed_metrics,
                   build_distributed_search, make_mesh)

MAX_WINDOW = 1024

# metric agg kinds the mesh can reduce with psum/pmin/pmax (plain
# {"field": ...} bodies only — anything fancier takes the host loop)
_MESH_METRICS = ("min", "max", "sum", "avg", "value_count", "stats")


class MeshSearchService:
    def __init__(self, devices: Optional[list] = None):
        import jax
        self.devices = list(devices) if devices is not None else jax.devices()
        self._meshes: Dict[int, object] = {}
        self._stacked: Dict[Tuple[str, str], Tuple[int, StackedShardIndex]] = {}
        self._programs: Dict[Tuple, object] = {}
        import collections
        self._metric_programs: Dict[Tuple, object] = {}
        # (index, field) -> (generation, arrays-or-None, nbytes); LRU
        self._stacked_cols: "collections.OrderedDict" = \
            collections.OrderedDict()
        self.dispatched = 0      # searches served by the mesh
        self.fallbacks = 0       # searches declined -> host loop

    # ---------------- caches ----------------

    def _mesh_for(self, n_shard: int):
        if n_shard > len(self.devices):
            return None
        m = self._meshes.get(n_shard)
        if m is None:
            m = make_mesh(n_replica=1, n_shard=n_shard,
                          devices=self.devices[:n_shard])
            self._meshes[n_shard] = m
        return m

    def _stacked_for(self, name: str, svc, field: str, segments
                     ) -> Optional[StackedShardIndex]:
        key = (name, field)
        cached = self._stacked.get(key)
        if cached is not None and cached[0] == svc.generation:
            return cached[1]
        mesh = self._mesh_for(len(segments))
        if mesh is None:
            return None
        stacked = StackedShardIndex.build(segments, field, mesh)
        self._stacked[key] = (svc.generation, stacked)
        return stacked

    def _program_for(self, mesh, bucket: int, ndocs_pad: int, k: int,
                     k1: float, b: float):
        key = (id(mesh), bucket, ndocs_pad, k, k1, b)
        fn = self._programs.get(key)
        if fn is None:
            fn = build_distributed_search(mesh, bucket=bucket,
                                          ndocs_pad=ndocs_pad, k=k,
                                          k1=k1, b=b)
            self._programs[key] = fn
        return fn

    def _metric_program_for(self, mesh, bucket: int, ndocs_pad: int,
                            k1: float, b: float):
        key = (id(mesh), bucket, ndocs_pad, k1, b)
        fn = self._metric_programs.get(key)
        if fn is None:
            fn = build_distributed_metrics(mesh, bucket=bucket,
                                           ndocs_pad=ndocs_pad, k1=k1, b=b)
            self._metric_programs[key] = fn
        return fn

    _COLS_MAX_BYTES = 1 << 30   # device budget for stacked agg columns

    def _col_for(self, name: str, svc, field: str, shard_segs,
                 d_pad: int, mesh) -> Optional[tuple]:
        """Stacked numeric column + presence mask [S, d_pad] sharded over
        the mesh, in the SAME per-shard concatenated doc space as the
        stacked postings; None when no segment has the column. Cached
        (incl. negative results) per generation under a byte-bounded LRU."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = (name, field)
        cached = self._stacked_cols.get(key)
        if cached is not None and cached[0] == svc.generation:
            self._stacked_cols.move_to_end(key)
            return cached[1]
        # cheap membership test BEFORE any allocation: declining a text/
        # missing field must not zero megabytes per request
        if not any(field in seg.numeric_cols
                   for segs in shard_segs for seg in segs):
            self._stacked_cols[key] = (svc.generation, None, 0)
            return None
        S = len(shard_segs)
        col = np.zeros((S, d_pad), np.float32)
        pres = np.zeros((S, d_pad), np.float32)
        for si, segs in enumerate(shard_segs):
            off = 0
            for seg in segs:
                nc = seg.numeric_cols.get(field)
                if nc is not None:
                    col[si, off: off + seg.ndocs] = \
                        nc.values.astype(np.float32)
                    pres[si, off: off + seg.ndocs] = \
                        nc.present.astype(np.float32)
                off += seg.ndocs
        sharding = NamedSharding(mesh, P("shard"))
        out = (jax.device_put(col, sharding),
               jax.device_put(pres, sharding))
        self._stacked_cols[key] = (svc.generation, out,
                                   col.nbytes + pres.nbytes)
        # byte-bounded LRU so long-lived nodes aggregating over many
        # fields/indices can't pin device columns forever
        while sum(v[2] for v in self._stacked_cols.values()) \
                > self._COLS_MAX_BYTES and len(self._stacked_cols) > 1:
            self._stacked_cols.popitem(last=False)
        return out

    # ---------------- dispatch ----------------

    def try_search(self, name: str, svc, body: dict) -> Optional[dict]:
        """One index, one term-group query -> full search response via the
        mesh, or None to fall back to the host shard loop."""
        return self.try_msearch(name, svc, [body])[0]

    def try_msearch(self, name: str, svc, bodies) -> list:
        """A BATCH of search bodies over one index through the SPMD mesh:
        eligible bodies group by (similarity, window class) and run as ONE
        program invocation each — the query axis of the distributed
        program is the batch (replica-sharded on a pod), so an msearch of
        N term-group queries pays one dispatch, one DFS psum, and one
        all_gather merge for the whole group. Ineligible bodies come back
        as None for the host loop. Served shapes: scoring term groups
        (term/terms/match, any minimum_should_match) and filter-context
        groups (`terms`, constant score); multi-segment and empty shards;
        windows to MAX_WINDOW."""
        from ..search import compiler as C
        from ..search import query_dsl as dsl
        from ..search.executor import (_global_stats_contexts,
                                       _norm_sort_specs, parse_aggs,
                                       _collect_named)

        out: list = [None] * len(bodies)
        searchers = svc.searchers
        # the mesh program earns its keep on SHARDED indices (per-shard
        # SPMD scoring + device DFS/merge); a single-shard index would pay
        # compile + dispatch overhead for zero parallelism
        if svc.meta.num_shards < 2:
            self.fallbacks += len(bodies)
            return out
        # a shard may hold any number of segments (incl. zero for routing
        # holes) — the stacked index concatenates them per shard
        shard_segs = [[g for g in s.engine.segments if g.live_count > 0]
                      for s in searchers]
        stats = _global_stats_contexts(searchers)
        ctx = stats[0]

        parsed = []   # (qi, lt, sort_specs, window, const_score, aggs)
        for qi, body in enumerate(bodies):
            try:
                query = dsl.parse_query(body.get("query"))
            except dsl.QueryParseError:
                self.fallbacks += 1
                continue
            lroot = C.rewrite(query, ctx, scoring=True)
            sort_specs = _norm_sort_specs(body)
            agg_nodes = parse_aggs(body.get("aggs",
                                            body.get("aggregations")))
            window = int(body.get("from", 0)) + int(body.get("size", 10))
            if not self._eligible(lroot, sort_specs, agg_nodes,
                                  _collect_named(lroot), body, window):
                self.fallbacks += 1
                continue
            const = (float(getattr(lroot, "boost", 1.0) or 1.0)
                     if lroot.mode == "filter" else 0.0)
            parsed.append((qi, lroot, sort_specs, max(window, 1), const,
                           agg_nodes or []))
        if not parsed:
            return out

        # group by program parameters: field (via the stacked index), sim,
        # and the pow2 WINDOW CLASS — co-batching a size=10 body with a
        # from+size=1000 body would force K=1024 merge slots on everyone
        # and every distinct K is its own compiled program
        groups: dict = {}
        for item in parsed:
            qi, lt, sort_specs, window, const, aggs = item
            sim = lt.sim
            k1 = float(sim.k1) if sim is not None else 1.2
            b_eff = (float(sim.b)
                     if sim is not None and lt.has_norms else 0.0)
            k_class = min(next_pow2(max(window, 16)), MAX_WINDOW)
            groups.setdefault((lt.field, k1, b_eff, k_class),
                              []).append(item)
        for (field, k1, b_eff, k_class), items in groups.items():
            self._run_mesh_group(name, svc, bodies, out, shard_segs, stats,
                                 searchers, field, k1, b_eff, k_class,
                                 items)
        return out

    def _run_mesh_group(self, name, svc, bodies, out, shard_segs, stats,
                        searchers, field, k1, b_eff, k_class,
                        items) -> None:
        from ..search.executor import (Candidate, ShardQueryResult,
                                       _finish_search, _host_sort_values)

        t0 = time.monotonic()
        stacked = self._stacked_for(name, svc, field, shard_segs)
        if stacked is None:
            self.fallbacks += len(items)
            return
        S = len(shard_segs)
        K = min(k_class, stacked.ndocs_pad)
        keep = []
        for it in items:
            if it[3] > K:
                # deeper page than the program's merged top-k capacity
                # (tiny shards): that body takes the host loop
                self.fallbacks += 1
                continue
            # metric aggs need their stacked columns; a missing column
            # means the host loop serves that body
            agg_ok = True
            for an in it[5]:
                if self._col_for(name, svc, an.body["field"], shard_segs,
                                 stacked.ndocs_pad,
                                 self._mesh_for(S)) is None:
                    agg_ok = False
                    break
            if not agg_ok:
                self.fallbacks += 1
                continue
            keep.append(it)
        items = keep
        if not items:
            return
        # pad the query axis to pow2 so batch size never mints new program
        # shapes (dummy slots: all rows -1 -> every score -inf)
        QB = next_pow2(len(items), floor=1)
        T_pad = max(next_pow2(len(it[1].terms), floor=1) for it in items)
        rows = np.full((S, QB, T_pad), -1, np.int32)
        boosts = np.zeros((QB, T_pad), np.float32)
        msm = np.ones(QB, np.float32)
        cscore = np.zeros(QB, np.float32)
        total_max = 1
        for bi, (qi, lt, sort_specs, window, const, aggs) in \
                enumerate(items):
            nt = len(lt.terms)
            boosts[bi, :nt] = lt.raw_boosts[:nt]
            msm[bi] = float(lt.msm)
            cscore[bi] = const
            for si in range(S):
                tot = 0
                for ti, t in enumerate(lt.terms):
                    r = stacked.row(si, t)
                    rows[si, bi, ti] = r
                    tot += stacked.row_size(si, r)
                total_max = max(total_max, tot)
        bucket = next_pow2(total_max, floor=256)
        mesh = self._mesh_for(S)
        if mesh is None:
            self.fallbacks += len(items)
            return
        fn = self._program_for(mesh, bucket, stacked.ndocs_pad, K, k1,
                               b_eff)
        gdocs_b, gvals_b, totals_b = fn(stacked.tree(), rows, boosts, msm,
                                        cscore)
        import jax

        # metric aggs: one psum/pmin/pmax reduce per distinct field over
        # the whole batch (items without that agg just ignore its column)
        agg_fields = sorted({an.body["field"] for it in items
                             for an in it[5]})
        metrics_by_field = {}
        if agg_fields:
            mfn = self._metric_program_for(mesh, bucket, stacked.ndocs_pad,
                                           k1, b_eff)
            for f in agg_fields:
                col, pres = self._col_for(name, svc, f, shard_segs,
                                          stacked.ndocs_pad, mesh)
                metrics_by_field[f] = mfn(stacked.tree(), rows, boosts,
                                          msm, cscore, col, pres)
        fetched = jax.device_get((gdocs_b, gvals_b, totals_b,
                                  metrics_by_field))
        gdocs_b, gvals_b, totals_b, metrics_by_field = fetched

        doc_base = np.asarray(stacked.doc_base)
        seg_bases = [np.cumsum([0] + ndocs[:-1])
                     for ndocs in stacked.seg_ndocs]
        for bi, (qi, lt, sort_specs, window, const, aggs) in \
                enumerate(items):
            gdocs = gdocs_b[bi]
            gvals = gvals_b[bi]
            total = int(totals_b[bi])
            results = [ShardQueryResult(shard=i,
                                        segments=list(shard_segs[i]))
                       for i in range(S)]
            results[0].total = total
            results[0].max_score = (float(gvals[0]) if total > 0
                                    and np.isfinite(gvals[0]) else -np.inf)
            for j in range(len(gdocs)):
                if not np.isfinite(gvals[j]) or gdocs[j] < 0:
                    continue
                si = int(np.searchsorted(doc_base, gdocs[j], "right") - 1)
                in_shard = int(gdocs[j] - doc_base[si])
                seg_ord = int(np.searchsorted(seg_bases[si], in_shard,
                                              "right") - 1)
                local = in_shard - int(seg_bases[si][seg_ord])
                seg = shard_segs[si][seg_ord]
                if local >= seg.ndocs:
                    continue
                sc = float(gvals[j])
                sort_vals, raw_vals = _host_sort_values(sort_specs, seg,
                                                        local, sc)
                results[si].candidates.append(
                    Candidate(si, seg_ord, local, sc, sort_vals, raw_vals))
            # attach the globally-reduced metric partials to shard 0 (the
            # values are already psum'd across the mesh; the coordinator
            # merge sees exactly one partial per agg)
            for an in aggs:
                m = metrics_by_field[an.body["field"]][bi]
                cnt = float(m[0])
                results[0].agg_partials[an.name] = [{
                    "count": cnt, "sum": float(m[1]),
                    "min": float(m[2]) if cnt > 0 else float("inf"),
                    "max": float(m[3]) if cnt > 0 else float("-inf"),
                    "sumsq": float(m[4])}]
            for r in results:
                r.took_ms = (time.monotonic() - t0) * 1000.0
            self.dispatched += 1
            body = dict(bodies[qi])
            body["_index_name"] = name
            out[qi] = _finish_search(searchers, results, body, stats, name,
                                     t0, aggs)

    def _eligible(self, lt, sort_specs, agg_nodes, named_nodes, body,
                  window: int) -> bool:
        """Mesh-servable shapes: a single term group (scoring OR filter
        mode), plain relevance order, no secondary features."""
        from ..search import compiler as C
        from ..search.fastpath import MAX_T
        from ..ops import scoring as ops

        if body.get("knn") or body.get("rescore") or body.get("min_score") \
                is not None or body.get("profile") or body.get("collapse") \
                or body.get("suggest") or body.get("search_after") is not None:
            return False
        if named_nodes:
            return False
        # metric-only aggregations reduce over the mesh (psum/pmin/pmax);
        # anything bucketed or scripted takes the host loop
        for an in (agg_nodes or []):
            if an.kind not in _MESH_METRICS or an.subs \
                    or set(an.body) != {"field"}:
                return False
        if window > MAX_WINDOW or (window < 1 and not agg_nodes):
            return False
        if sort_specs and not (len(sort_specs) == 1
                               and sort_specs[0]["field"] == "_score"
                               and sort_specs[0].get("order", "desc")
                               == "desc"):
            return False
        if not isinstance(lt, C.LTerms):
            return False
        if lt.mode not in ("score", "filter"):
            return False
        if lt.mode == "score" and (lt.sim is None
                                   or lt.sim.sim_id != ops.SIM_BM25):
            return False
        nt = len(lt.terms)
        if nt < 1 or next_pow2(nt, floor=1) > MAX_T:
            return False
        if getattr(lt, "raw_boosts", None) is None:
            return False
        if lt.aux is not None and np.any(np.asarray(lt.aux)[:nt] != 0.0):
            return False
        return True

    def stats(self) -> dict:
        return {"devices": len(self.devices), "dispatched": self.dispatched,
                "fallbacks": self.fallbacks,
                "stacked_indices": len(self._stacked)}
