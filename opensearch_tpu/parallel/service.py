"""MeshSearchService: the SPMD mesh path wired into the Node's REST search.

When a multi-device mesh is available (real TPU pod slice, or the virtual
8-CPU-device test mesh), eligible term-group queries dispatch over
`parallel/spmd.py`'s distributed program instead of the host shard loop:
per-shard scoring runs SPMD over the `shard` mesh axis, collection stats
(df, N, sum_dl) psum over ICI (device-side DFS phase), and per-shard top-ks
merge with an all_gather — the reference's coordinator fan-out
(`action/search/TransportSearchAction.java`,
`action/search/SearchPhaseController.java`) without the transport layer.

Fallback contract: `try_search` returns None whenever the query shape or
index layout isn't mesh-ready (complex plans, multi-segment shards, window
too deep), and the Node falls back to the host loop — identical results
either way (asserted by tests/test_distributed.py).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..index.segment import next_pow2
from .spmd import StackedShardIndex, build_distributed_search, make_mesh

MAX_WINDOW = 1024


class MeshSearchService:
    def __init__(self, devices: Optional[list] = None):
        import jax
        self.devices = list(devices) if devices is not None else jax.devices()
        self._meshes: Dict[int, object] = {}
        self._stacked: Dict[Tuple[str, str], Tuple[int, StackedShardIndex]] = {}
        self._programs: Dict[Tuple, object] = {}
        self.dispatched = 0      # searches served by the mesh
        self.fallbacks = 0       # searches declined -> host loop

    # ---------------- caches ----------------

    def _mesh_for(self, n_shard: int):
        if n_shard > len(self.devices):
            return None
        m = self._meshes.get(n_shard)
        if m is None:
            m = make_mesh(n_replica=1, n_shard=n_shard,
                          devices=self.devices[:n_shard])
            self._meshes[n_shard] = m
        return m

    def _stacked_for(self, name: str, svc, field: str, segments
                     ) -> Optional[StackedShardIndex]:
        key = (name, field)
        cached = self._stacked.get(key)
        if cached is not None and cached[0] == svc.generation:
            return cached[1]
        mesh = self._mesh_for(len(segments))
        if mesh is None:
            return None
        stacked = StackedShardIndex.build(segments, field, mesh)
        self._stacked[key] = (svc.generation, stacked)
        return stacked

    def _program_for(self, mesh, bucket: int, ndocs_pad: int, k: int,
                     k1: float, b: float):
        key = (id(mesh), bucket, ndocs_pad, k, k1, b)
        fn = self._programs.get(key)
        if fn is None:
            fn = build_distributed_search(mesh, bucket=bucket,
                                          ndocs_pad=ndocs_pad, k=k,
                                          k1=k1, b=b)
            self._programs[key] = fn
        return fn

    # ---------------- dispatch ----------------

    def try_search(self, name: str, svc, body: dict) -> Optional[dict]:
        """One index, one term-group query -> full search response via the
        mesh, or None to fall back to the host shard loop. Served shapes:
        scoring term groups (term/terms/match, any minimum_should_match)
        AND filter-context groups (`terms`, constant_score term sets) via
        the program's constant-score flag; shards may hold several
        segments (stacked as one concatenated CSR per shard); windows up
        to MAX_WINDOW."""
        from ..search import compiler as C
        from ..search import query_dsl as dsl
        from ..search.executor import (Candidate, ShardQueryResult,
                                       _finish_search, _global_stats_contexts,
                                       _host_sort_values, _norm_sort_specs,
                                       parse_aggs, _collect_named)
        t0 = time.monotonic()
        searchers = svc.searchers
        # the mesh program earns its keep on SHARDED indices (per-shard
        # SPMD scoring + device DFS/merge); a single-shard index would pay
        # compile + dispatch overhead for zero parallelism
        if svc.meta.num_shards < 2:
            self.fallbacks += 1
            return None
        # a shard may hold any number of segments (incl. zero for routing
        # holes) — the stacked index concatenates them per shard
        shard_segs = [[g for g in s.engine.segments if g.live_count > 0]
                      for s in searchers]

        stats = _global_stats_contexts(searchers)
        ctx = stats[0]
        try:
            query = dsl.parse_query(body.get("query"))
        except dsl.QueryParseError:
            self.fallbacks += 1
            return None
        lroot = C.rewrite(query, ctx, scoring=True)
        sort_specs = _norm_sort_specs(body)
        agg_nodes = parse_aggs(body.get("aggs", body.get("aggregations")))
        window = int(body.get("from", 0)) + int(body.get("size", 10))
        lt = lroot
        if not self._eligible(lt, sort_specs, agg_nodes,
                              _collect_named(lroot), body, window):
            self.fallbacks += 1
            return None
        field = lt.field
        const_score = 0.0
        if lt.mode == "filter":
            # filter-context term group (`terms` query): constant score,
            # doc-id tie order — handled inside the SPMD program
            const_score = float(getattr(lt, "boost", 1.0) or 1.0)

        stacked = self._stacked_for(name, svc, field, shard_segs)
        if stacked is None:
            self.fallbacks += 1
            return None

        S = len(shard_segs)
        nt = len(lt.terms)
        T_pad = next_pow2(nt, floor=1)
        rows = np.full((S, 1, T_pad), -1, np.int32)
        total_max = 1
        for si in range(S):
            tot = 0
            for ti, t in enumerate(lt.terms):
                r = stacked.row(si, t)
                rows[si, 0, ti] = r
                tot += stacked.row_size(si, r)
            total_max = max(total_max, tot)
        bucket = next_pow2(total_max, floor=256)
        boosts = np.zeros((1, T_pad), np.float32)
        boosts[0, :nt] = lt.raw_boosts[:nt]
        msm = np.full(1, float(lt.msm), np.float32)
        cscore = np.full(1, const_score, np.float32)
        K = min(next_pow2(max(window, 16)), MAX_WINDOW, stacked.ndocs_pad)
        if window > K:
            # the program's merged output has only K slots; a deeper page
            # than K (tiny shards) must take the host loop or the page
            # would silently truncate
            self.fallbacks += 1
            return None
        sim = lt.sim
        k1 = float(sim.k1) if sim is not None else 1.2
        b_eff = (float(sim.b)
                 if sim is not None and lt.has_norms else 0.0)

        mesh = self._mesh_for(S)
        if mesh is None:
            self.fallbacks += 1
            return None
        fn = self._program_for(mesh, bucket, stacked.ndocs_pad, K, k1, b_eff)
        gdocs, gvals, totals = fn(stacked.tree(), rows, boosts, msm, cscore)
        import jax
        gdocs, gvals, totals = jax.device_get((gdocs, gvals, totals))
        gdocs = gdocs[0]
        gvals = gvals[0]
        total = int(totals[0])

        # global doc ids -> (shard, segment, local doc) -> candidates
        doc_base = np.asarray(stacked.doc_base)
        seg_bases = [np.cumsum([0] + ndocs[:-1])
                     for ndocs in stacked.seg_ndocs]
        results = [ShardQueryResult(shard=i, segments=list(shard_segs[i]))
                   for i in range(S)]
        results[0].total = total
        max_score = float(gvals[0]) if total > 0 and np.isfinite(gvals[0]) \
            else -np.inf
        results[0].max_score = max_score
        for j in range(len(gdocs)):
            if not np.isfinite(gvals[j]) or gdocs[j] < 0:
                continue
            si = int(np.searchsorted(doc_base, gdocs[j], "right") - 1)
            in_shard = int(gdocs[j] - doc_base[si])
            seg_ord = int(np.searchsorted(seg_bases[si], in_shard,
                                          "right") - 1)
            local = in_shard - int(seg_bases[si][seg_ord])
            seg = shard_segs[si][seg_ord]
            if local >= seg.ndocs:
                continue
            sc = float(gvals[j])
            sort_vals, raw_vals = _host_sort_values(sort_specs, seg, local, sc)
            results[si].candidates.append(
                Candidate(si, seg_ord, local, sc, sort_vals, raw_vals))
        for r in results:
            r.took_ms = (time.monotonic() - t0) * 1000.0
        self.dispatched += 1
        body = dict(body)
        body["_index_name"] = name
        return _finish_search(searchers, results, body, stats, name, t0, [])

    def _eligible(self, lt, sort_specs, agg_nodes, named_nodes, body,
                  window: int) -> bool:
        """Mesh-servable shapes: a single term group (scoring OR filter
        mode), plain relevance order, no secondary features."""
        from ..search import compiler as C
        from ..search.fastpath import MAX_T
        from ..ops import scoring as ops

        if body.get("knn") or body.get("rescore") or body.get("min_score") \
                is not None or body.get("profile") or body.get("collapse") \
                or body.get("suggest") or body.get("search_after") is not None:
            return False
        if agg_nodes or named_nodes:
            return False
        if window > MAX_WINDOW or window < 1:
            return False
        if sort_specs and not (len(sort_specs) == 1
                               and sort_specs[0]["field"] == "_score"
                               and sort_specs[0].get("order", "desc")
                               == "desc"):
            return False
        if not isinstance(lt, C.LTerms):
            return False
        if lt.mode not in ("score", "filter"):
            return False
        if lt.mode == "score" and (lt.sim is None
                                   or lt.sim.sim_id != ops.SIM_BM25):
            return False
        nt = len(lt.terms)
        if nt < 1 or next_pow2(nt, floor=1) > MAX_T:
            return False
        if getattr(lt, "raw_boosts", None) is None:
            return False
        if lt.aux is not None and np.any(np.asarray(lt.aux)[:nt] != 0.0):
            return False
        return True

    def stats(self) -> dict:
        return {"devices": len(self.devices), "dispatched": self.dispatched,
                "fallbacks": self.fallbacks,
                "stacked_indices": len(self._stacked)}
