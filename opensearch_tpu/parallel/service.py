"""MeshSearchService: the SPMD mesh path wired into the Node's REST search.

When a multi-device mesh is available (real TPU pod slice, or the virtual
8-CPU-device test mesh), eligible term-group queries dispatch over
`parallel/spmd.py`'s distributed program instead of the host shard loop:
per-shard scoring runs SPMD over the `shard` mesh axis, collection stats
(df, N, sum_dl) psum over ICI (device-side DFS phase), and per-shard top-ks
merge with an all_gather — the reference's coordinator fan-out
(`action/search/TransportSearchAction.java`,
`action/search/SearchPhaseController.java`) without the transport layer.

Fallback contract: `try_search` returns None whenever the query shape or
index layout isn't mesh-ready (complex plans, multi-segment shards, window
too deep), and the Node falls back to the host loop — identical results
either way (asserted by tests/test_distributed.py).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..index.segment import next_pow2
from .spmd import StackedShardIndex, build_distributed_search, make_mesh

MAX_WINDOW = 128


class MeshSearchService:
    def __init__(self, devices: Optional[list] = None):
        import jax
        self.devices = list(devices) if devices is not None else jax.devices()
        self._meshes: Dict[int, object] = {}
        self._stacked: Dict[Tuple[str, str], Tuple[int, StackedShardIndex]] = {}
        self._programs: Dict[Tuple, object] = {}
        self.dispatched = 0      # searches served by the mesh
        self.fallbacks = 0       # searches declined -> host loop

    # ---------------- caches ----------------

    def _mesh_for(self, n_shard: int):
        if n_shard > len(self.devices):
            return None
        m = self._meshes.get(n_shard)
        if m is None:
            m = make_mesh(n_replica=1, n_shard=n_shard,
                          devices=self.devices[:n_shard])
            self._meshes[n_shard] = m
        return m

    def _stacked_for(self, name: str, svc, field: str, segments
                     ) -> Optional[StackedShardIndex]:
        key = (name, field)
        cached = self._stacked.get(key)
        if cached is not None and cached[0] == svc.generation:
            return cached[1]
        mesh = self._mesh_for(len(segments))
        if mesh is None:
            return None
        stacked = StackedShardIndex.build(segments, field, mesh)
        self._stacked[key] = (svc.generation, stacked)
        return stacked

    def _program_for(self, mesh, bucket: int, ndocs_pad: int, k: int,
                     k1: float, b: float):
        key = (id(mesh), bucket, ndocs_pad, k, k1, b)
        fn = self._programs.get(key)
        if fn is None:
            fn = build_distributed_search(mesh, bucket=bucket,
                                          ndocs_pad=ndocs_pad, k=k,
                                          k1=k1, b=b)
            self._programs[key] = fn
        return fn

    # ---------------- dispatch ----------------

    def try_search(self, name: str, svc, body: dict) -> Optional[dict]:
        """One index, one term-group query -> full search response via the
        mesh, or None to fall back to the host shard loop."""
        from ..search import compiler as C
        from ..search import fastpath
        from ..search import query_dsl as dsl
        from ..search.executor import (Candidate, ShardQueryResult,
                                       _finish_search, _global_stats_contexts,
                                       _host_sort_values, _norm_sort_specs,
                                       parse_aggs, _collect_named)

        t0 = time.monotonic()
        searchers = svc.searchers
        # the mesh program earns its keep on SHARDED indices (per-shard
        # SPMD scoring + device DFS/merge); a single-shard index would pay
        # compile + dispatch overhead for zero parallelism
        if svc.meta.num_shards < 2:
            self.fallbacks += 1
            return None
        # mesh-ready layout: every shard exactly one segment (steady state
        # after refresh+merge; reference analog: one Lucene reader per shard)
        segments = []
        for s in searchers:
            if len(s.engine.segments) != 1:
                self.fallbacks += 1
                return None
            segments.append(s.engine.segments[0])
        if not segments:
            self.fallbacks += 1
            return None

        stats = _global_stats_contexts(searchers)
        ctx = stats[0]
        try:
            query = dsl.parse_query(body.get("query"))
        except dsl.QueryParseError:
            self.fallbacks += 1
            return None
        if body.get("knn") or body.get("rescore") or body.get("min_score") \
                is not None or body.get("profile"):
            self.fallbacks += 1
            return None
        lroot = C.rewrite(query, ctx, scoring=True)
        sort_specs = _norm_sort_specs(body)
        agg_nodes = parse_aggs(body.get("aggs", body.get("aggregations")))
        window = int(body.get("from", 0)) + int(body.get("size", 10))
        if not fastpath.query_eligible(lroot, sort_specs, agg_nodes,
                                       _collect_named(lroot),
                                       body.get("search_after"), window,
                                       body):
            self.fallbacks += 1
            return None
        lt = lroot
        field = lt.field
        if getattr(lt, "raw_boosts", None) is None:
            self.fallbacks += 1
            return None

        stacked = self._stacked_for(name, svc, field, segments)
        if stacked is None:
            self.fallbacks += 1
            return None

        S = len(segments)
        nt = len(lt.terms)
        T_pad = next_pow2(nt, floor=1)
        rows = np.full((S, 1, T_pad), -1, np.int32)
        total_max = 1
        for si, seg in enumerate(segments):
            pb = seg.postings.get(field)
            tot = 0
            for ti, t in enumerate(lt.terms):
                r = pb.row(t) if pb is not None else -1
                rows[si, 0, ti] = r
                if r >= 0:
                    a, bnd = pb.row_slice(r)
                    tot += bnd - a
            total_max = max(total_max, tot)
        bucket = next_pow2(total_max, floor=256)
        boosts = np.zeros((1, T_pad), np.float32)
        boosts[0, :nt] = lt.raw_boosts[:nt]
        msm = np.full(1, float(lt.msm), np.float32)
        K = min(next_pow2(max(window, 16)), MAX_WINDOW, stacked.ndocs_pad)
        sim = lt.sim
        b_eff = float(sim.b) if lt.has_norms else 0.0

        mesh = self._mesh_for(S)
        fn = self._program_for(mesh, bucket, stacked.ndocs_pad, K,
                               float(sim.k1), b_eff)
        gdocs, gvals, totals = fn(stacked.tree(), rows, boosts, msm)
        gdocs = np.asarray(gdocs)[0]
        gvals = np.asarray(gvals)[0]
        total = int(np.asarray(totals)[0])

        # global doc ids -> (shard, local doc) -> candidates
        doc_base = np.asarray(stacked.doc_base)
        results = [ShardQueryResult(shard=i, segments=[segments[i]])
                   for i in range(S)]
        results[0].total = total
        max_score = float(gvals[0]) if total > 0 and np.isfinite(gvals[0]) \
            else -np.inf
        results[0].max_score = max_score
        for j in range(len(gdocs)):
            if not np.isfinite(gvals[j]) or gdocs[j] < 0:
                continue
            si = int(np.searchsorted(doc_base, gdocs[j], "right") - 1)
            local = int(gdocs[j] - doc_base[si])
            seg = segments[si]
            if local >= seg.ndocs:
                continue
            sc = float(gvals[j])
            sort_vals, raw_vals = _host_sort_values(sort_specs, seg, local, sc)
            results[si].candidates.append(
                Candidate(si, 0, local, sc, sort_vals, raw_vals))
        for r in results:
            r.took_ms = (time.monotonic() - t0) * 1000.0
        self.dispatched += 1
        body = dict(body)
        body["_index_name"] = name
        return _finish_search(searchers, results, body, stats, name, t0, [])

    def stats(self) -> dict:
        return {"devices": len(self.devices), "dispatched": self.dispatched,
                "fallbacks": self.fallbacks,
                "stacked_indices": len(self._stacked)}
