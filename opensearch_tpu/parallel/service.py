"""MeshSearchService: the SPMD mesh path wired into the Node's REST search.

When a multi-device mesh is available (real TPU pod slice, or the virtual
8-CPU-device test mesh), eligible term-group queries dispatch over
`parallel/spmd.py`'s distributed program instead of the host shard loop:
per-shard scoring runs SPMD over the `shard` mesh axis, collection stats
(df, N, sum_dl) psum over ICI (device-side DFS phase), and per-shard top-ks
merge with an all_gather — the reference's coordinator fan-out
(`action/search/TransportSearchAction.java`,
`action/search/SearchPhaseController.java`) without the transport layer.

Fallback contract: `try_search` returns None whenever the query shape or
index layout isn't mesh-ready (complex plans, multi-segment shards, window
too deep), and the Node falls back to the host loop — identical results
either way (asserted by tests/test_distributed.py).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..index.segment import next_pow2
from ..obs import flight_recorder as _fr
from ..search.compiler import (coerce_agg_ranges, grid_agg_precision,
                               hist_agg_interval, range_agg_spec)
from ..utils.metrics import METRICS
from ..utils.trace import TRACER
from .spmd import (INT32_SENTINEL, StackedPhrasePairs, StackedShardIndex,
                   build_distributed_bincount,
                   build_distributed_cardinality,
                   build_distributed_ddsketch,
                   build_distributed_geo_stat,
                   build_distributed_metrics,
                   build_distributed_pair_metrics, build_distributed_phrase,
                   build_distributed_range_counts,
                   build_distributed_range_metrics,
                   build_distributed_search, build_distributed_terms_agg,
                   build_distributed_weighted_avg, make_mesh)

MAX_WINDOW = 1024

# metric agg kinds the mesh can reduce with psum/pmin/pmax (plain
# {"field": ...} bodies only — anything fancier takes the host loop)
_MESH_METRICS = ("min", "max", "sum", "avg", "value_count", "stats")

# keyword `terms` aggs run as an exact device bincount + psum when the
# field's global ordinal space fits this cap (counts array is [QB, vpad])
MAX_TERMS_VOCAB = 8192

# phrase queries: max terms the mesh serves (host loop beyond), and the
# cap on the positional pair bucket (a stopword-anchored phrase on a huge
# shard would blow the scatter working set)
MAX_PHRASE_T = 8
MAX_PHRASE_BUCKET = 1 << 22

# histogram-family aggs: bin-count cap for the mesh bincount program (a
# pathological interval over a wide value range -> host loop) and the max
# `range` agg ranges served as per-range masked sums
MAX_MESH_BINS = 4096
MAX_MESH_RANGES = 16

# adjacency_matrix builds N + N(N-1)/2 device masks (one metric launch
# each) — quadratic, so the mesh serves small matrices only (host loop
# beyond; the reference's own default cap is 100 filters)
MAX_MESH_ADJ_FILTERS = 8


class _ByteLRU:
    """Byte-budgeted LRU over an OrderedDict: one eviction policy for every
    device/host cache the service keeps (stacked agg columns, global
    ordinals, filter masks). Keeps a running byte total so eviction is O(1)
    per evicted entry.

    `kind`: when set, every nonzero-byte entry is registered with the HBM
    ledger (obs/hbm_ledger.py) under that tenant kind — eviction and
    replacement release the allocation, so `_nodes/stats` "hbm" and the
    breaker-derived charges track the mesh's device caches exactly."""

    def __init__(self, max_bytes: int, kind: Optional[str] = None):
        import collections
        import threading
        self._od: "collections.OrderedDict" = collections.OrderedDict()
        self._bytes = 0
        self._max = max_bytes
        self._kind = kind
        # concurrent searches (HTTP threads with the serving scheduler
        # off, msearch's per-body fallback pool) race move_to_end/popitem
        # without this; the lock is uncontended in the scheduler-on
        # steady state where one dispatcher thread owns the mesh
        self._lock = threading.Lock()

    def get(self, key):
        with self._lock:
            hit = self._od.get(key)
            if hit is not None:
                self._od.move_to_end(key)
                return hit[0]
            return None

    def put(self, key, value, nbytes: int) -> None:
        from ..obs.hbm_ledger import LEDGER
        alloc = None
        if self._kind is not None and nbytes:
            # register BEFORE taking the LRU lock (the ledger may raise
            # the breaker's CircuitBreakingException on an over-budget
            # build — nothing is cached in that case)
            alloc = LEDGER.register(self._kind, nbytes,
                                    label=f"mesh-lru{key!r}"[:160])
        released = []
        with self._lock:
            old = self._od.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
                released.append(old[2])
            self._od[key] = (value, nbytes, alloc)
            self._bytes += nbytes
            while self._bytes > self._max and len(self._od) > 1:
                _k, (_v, nb, al) = self._od.popitem(last=False)
                self._bytes -= nb
                released.append(al)
        for al in released:
            LEDGER.release(al)

    def __len__(self) -> int:
        return len(self._od)


class MeshSearchService:
    def __init__(self, devices: Optional[list] = None):
        import jax
        self.devices = list(devices) if devices is not None else jax.devices()
        self._meshes: Dict[int, object] = {}
        self._stacked: Dict[Tuple[str, str], Tuple[int, StackedShardIndex]] = {}
        self._programs: Dict[Tuple, object] = {}
        self._metric_programs: Dict[Tuple, object] = {}
        self._terms_programs: Dict[Tuple, object] = {}
        self._phrase_programs: Dict[Tuple, object] = {}
        self._hist_programs: Dict[Tuple, object] = {}
        self._range_programs: Dict[Tuple, object] = {}
        self._pair_metrics_programs: Dict[Tuple, object] = {}
        self._range_metrics_programs: Dict[Tuple, object] = {}
        self._card_programs: Dict[Tuple, object] = {}
        self._card_hashes = _ByteLRU(64 << 20)
        self._ddsketch_programs: Dict[Tuple, object] = {}
        self._wavg_programs: Dict[Tuple, object] = {}
        self._geo_programs: Dict[Tuple, object] = {}
        # (index, field) -> (generation, arrays-or-None); device caches
        # carry an HBM-ledger tenant kind so residency is attributed and
        # breaker-charged through the ledger (host-side caches stay
        # untracked — they hold RAM, not HBM)
        self._stacked_cols = _ByteLRU(self._COLS_MAX_BYTES,
                                      kind="mesh_columns")
        # (index, field) -> (generation, (val_doc, val_ord, vocab, vpad)
        #                    -or-None); smaller caps for the r5 caches so
        #        the aggregate device budget stays bounded near the original
        #        1 GiB rather than quadrupling
        self._stacked_ords = _ByteLRU(self._COLS_MAX_BYTES // 4,
                                      kind="mesh_columns")
        # filter-combo key -> per-shard host masks / device stacked mask
        self._host_masks = _ByteLRU(self._COLS_MAX_BYTES // 4)
        self._dev_masks = _ByteLRU(self._COLS_MAX_BYTES // 4,
                                   kind="mesh_columns")
        # (index, field) -> (generation, StackedPhrasePairs-or-None)
        self._stacked_pairs = _ByteLRU(self._COLS_MAX_BYTES // 2,
                                       kind="mesh_postings")
        # (index, field, kind, interval, offset) ->
        #     (generation, (bins_dev, min_b, nb)-or-None)
        self._stacked_bins = _ByteLRU(self._COLS_MAX_BYTES // 4,
                                      kind="mesh_columns")
        # SPMD program invocations must not interleave: two concurrent
        # runs of a collective program cross-join their per-device
        # participants at the XLA rendezvous and deadlock (observed on
        # the CPU backend under scheduler-off concurrent REST traffic).
        # One launch at a time is also the physical truth — the chip
        # serializes programs; the serving scheduler makes this lock
        # uncontended (a single dispatcher thread owns the mesh).
        # Everything this lock may nest over (ledger, stats, metrics,
        # tracer) is committed in lock_order.json and ratcheted by
        # tier-1 — and OSL702 rejects holding it across a device sync,
        # which is the shape of the original deadlock
        import threading
        self._dispatch_lock = threading.Lock()
        # counter mutations can now come from several threads at once
        # (the scheduler's completion worker fetches batch N while the
        # dispatcher launches N+1, and direct request threads decline in
        # parallel) — a GIL-sized lock keeps the tallies exact
        self._stats_lock = threading.Lock()
        self.dispatched = 0      # searches served by the mesh
        self.launches = 0        # scoring-program invocations (group = 1)
        self.fallbacks = 0       # searches declined -> host loop
        self.filtered_dispatched = 0   # of dispatched: bool-with-filters
        self.terms_agg_dispatched = 0  # of dispatched: with a terms agg
        self.phrase_dispatched = 0     # of dispatched: match_phrase
        # WHY each declined search host-looped, by decline site — surfaced
        # in _nodes/stats so a dispatch-share measurement (MESH_SHARE)
        # can't silently flatter: a flat `fallbacks` total hides whether
        # the misses are benign (single-shard index) or a served shape
        # regressing (e.g. agg columns failing to stack)
        self.fallback_shapes: Dict[str, int] = {}

    def _fall(self, shape: str, n: int = 1) -> None:
        with self._stats_lock:
            self.fallbacks += n
            self.fallback_shapes[shape] = \
                self.fallback_shapes.get(shape, 0) + n
        # registry mirror: every decline site attributed by shape, so the
        # Prometheus exposition carries the same why-did-it-host-loop
        # breakdown _nodes/stats does
        METRICS.counter("mesh.fallbacks").inc(n)
        METRICS.counter(f"mesh.fallback.{shape}").inc(n)
        if _fr.RECORDER.enabled:
            tl = _fr.current()
            if tl:
                _fr.RECORDER.record(tl, "mesh.decline", shape=shape)

    # ---------------- caches ----------------

    def _mesh_for(self, n_shard: int):
        if n_shard > len(self.devices):
            return None
        m = self._meshes.get(n_shard)
        if m is None:
            m = make_mesh(n_replica=1, n_shard=n_shard,
                          devices=self.devices[:n_shard])
            self._meshes[n_shard] = m
        return m

    def _stacked_for(self, name: str, svc, field: str, segments
                     ) -> Optional[StackedShardIndex]:
        key = (name, field)
        cached = self._stacked.get(key)
        if cached is not None and cached[0] == svc.generation:
            return cached[1]
        mesh = self._mesh_for(len(segments))
        if mesh is None:
            return None
        stacked = StackedShardIndex.build(segments, field, mesh)
        # attribute the stacked per-shard postings (the mesh's dominant
        # HBM tenant) to the ledger; a generation bump replaces the dict
        # entry and the old index's GC releases the charge
        from ..obs.hbm_ledger import LEDGER
        LEDGER.register(
            "mesh_postings",
            sum(int(getattr(a, "nbytes", 0)) for a in
                (stacked.starts, stacked.doc_ids, stacked.tfs,
                 stacked.dl, stacked.live)),
            owner=stacked, label=f"mesh-stacked[{name}][{field}]")
        self._stacked[key] = (svc.generation, stacked)
        return stacked

    def _program_for(self, mesh, bucket: int, ndocs_pad: int, k: int,
                     k1: float, b: float, filtered: bool = False):
        key = (id(mesh), bucket, ndocs_pad, k, k1, b, filtered)
        fn = self._programs.get(key)
        if fn is None:
            fn = build_distributed_search(mesh, bucket=bucket,
                                          ndocs_pad=ndocs_pad, k=k,
                                          k1=k1, b=b, filtered=filtered)
            self._programs[key] = fn
        return fn

    def _metric_program_for(self, mesh, bucket: int, ndocs_pad: int,
                            k1: float, b: float, filtered: bool = False):
        key = (id(mesh), bucket, ndocs_pad, k1, b, filtered)
        fn = self._metric_programs.get(key)
        if fn is None:
            fn = build_distributed_metrics(mesh, bucket=bucket,
                                           ndocs_pad=ndocs_pad, k1=k1, b=b,
                                           filtered=filtered)
            self._metric_programs[key] = fn
        return fn

    def _terms_program_for(self, mesh, bucket: int, ndocs_pad: int,
                           vpad: int, k1: float, b: float,
                           filtered: bool = False):
        key = (id(mesh), bucket, ndocs_pad, vpad, k1, b, filtered)
        fn = self._terms_programs.get(key)
        if fn is None:
            fn = build_distributed_terms_agg(mesh, bucket=bucket,
                                             ndocs_pad=ndocs_pad, vpad=vpad,
                                             k1=k1, b=b, filtered=filtered)
            self._terms_programs[key] = fn
        return fn

    _COLS_MAX_BYTES = 1 << 30   # device budget for stacked agg columns

    def _pairs_for(self, name: str, svc, field: str, shard_segs, stacked,
                   mesh) -> Optional[StackedPhrasePairs]:
        """Stacked positional pair arrays for `field` (phrase program
        input), cached per generation incl. negative results (fields
        without positions decline once, not per query)."""
        key = ("pairs", name, field)
        cached = self._stacked_pairs.get(key)
        if cached is not None and cached[0] == svc.generation:
            return cached[1]
        pairs = StackedPhrasePairs.build(shard_segs, field, stacked, mesh)
        self._stacked_pairs.put(key, (svc.generation, pairs),
                                pairs.nbytes if pairs is not None else 0)
        return pairs

    def _phrase_program_for(self, mesh, bucket: int, ndocs_pad: int,
                            k: int, n_terms: int, k1: float, b: float,
                            filtered: bool = False):
        key = (id(mesh), bucket, ndocs_pad, k, n_terms, k1, b, filtered)
        fn = self._phrase_programs.get(key)
        if fn is None:
            fn = build_distributed_phrase(mesh, bucket=bucket,
                                          ndocs_pad=ndocs_pad, k=k,
                                          n_terms=n_terms, k1=k1, b=b,
                                          filtered=filtered)
            self._phrase_programs[key] = fn
        return fn

    def _hist_program_for(self, mesh, bucket: int, ndocs_pad: int,
                          nb: int, k1: float, b: float,
                          filtered: bool = False):
        key = (id(mesh), bucket, ndocs_pad, nb, k1, b, filtered)
        fn = self._hist_programs.get(key)
        if fn is None:
            fn = build_distributed_bincount(mesh, bucket=bucket,
                                            ndocs_pad=ndocs_pad, nb=nb,
                                            k1=k1, b=b, filtered=filtered)
            self._hist_programs[key] = fn
        return fn

    def _range_program_for(self, mesh, bucket: int, ndocs_pad: int,
                           nr: int, k1: float, b: float,
                           filtered: bool = False):
        key = (id(mesh), bucket, ndocs_pad, nr, k1, b, filtered)
        fn = self._range_programs.get(key)
        if fn is None:
            fn = build_distributed_range_counts(mesh, bucket=bucket,
                                                ndocs_pad=ndocs_pad, nr=nr,
                                                k1=k1, b=b,
                                                filtered=filtered)
            self._range_programs[key] = fn
        return fn

    def _geo_for(self, name: str, svc, field: str, shard_segs,
                 d_pad: int, mesh) -> Optional[tuple]:
        """Stacked geo lat/lon/presence [S, d_pad] sharded over the mesh;
        None when no segment has the geo column. Cached per generation."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = ("geo", name, field)
        cached = self._stacked_cols.get(key)
        if cached is not None and cached[0] == svc.generation:
            return cached[1]
        if not any(field in seg.geo_cols
                   for segs in shard_segs for seg in segs):
            self._stacked_cols.put(key, (svc.generation, None), 0)
            return None
        S = len(shard_segs)
        lat = np.zeros((S, d_pad), np.float32)
        lon = np.zeros((S, d_pad), np.float32)
        pres = np.zeros((S, d_pad), np.float32)
        for si, segs in enumerate(shard_segs):
            off = 0
            for seg in segs:
                gc = seg.geo_cols.get(field)
                if gc is not None:
                    lat[si, off: off + seg.ndocs] = gc.lat
                    lon[si, off: off + seg.ndocs] = gc.lon
                    pres[si, off: off + seg.ndocs] = \
                        gc.present.astype(np.float32)
                off += seg.ndocs
        sh = NamedSharding(mesh, P("shard"))
        out = (jax.device_put(lat, sh), jax.device_put(lon, sh),  # oslint: disable=OSL506 -- _ByteLRU kind registers at put()
               jax.device_put(pres, sh))  # oslint: disable=OSL506 -- _ByteLRU kind registers at put()
        self._stacked_cols.put(key, (svc.generation, out),
                               lat.nbytes * 3)
        return out

    def _grid_for(self, name: str, svc, field: str, kind: str,
                  precision: int, shard_segs, d_pad: int, mesh
                  ) -> Optional[tuple]:
        """Stacked GLOBAL geo-grid cell ordinals [S, d_pad] (-1 = no
        value) + the cell-key vocab union — per-segment cell ords from
        the host grid cache remapped into one index-wide ordinal space,
        so the device bincount program buckets globally. Cached per
        generation."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..search.compiler import _geo_grid_cache

        key = ("grid", name, field, kind, precision)
        cached = self._stacked_cols.get(key)
        if cached is not None and cached[0] == svc.generation:
            return cached[1]
        per_seg = [[_geo_grid_cache(seg, field, kind, precision)
                    for seg in segs] for segs in shard_segs]
        return self._stack_global_ords(key, svc, per_seg, shard_segs,
                                       d_pad, mesh)

    def _stack_global_ords(self, key: tuple, svc, per_seg, shard_segs,
                           d_pad: int, mesh) -> Optional[tuple]:
        """Shared remap of per-segment (vocab, doc-major ords) pairs into
        one index-wide ordinal space, stacked [S, d_pad] and sharded (-1 =
        missing). Used by the geo grids and multi_terms; cached per
        generation including negative results."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        vocab = sorted({v for srow in per_seg for (vs, _o) in srow
                        for v in vs})
        if not vocab or len(vocab) > MAX_TERMS_VOCAB:
            self._stacked_cols.put(key, (svc.generation, None), 0)
            return None
        gord = {v: i for i, v in enumerate(vocab)}
        S = len(shard_segs)
        bins = np.full((S, d_pad), -1, np.int32)
        for si, segs in enumerate(shard_segs):
            off = 0
            for seg, (vs, ords) in zip(segs, per_seg[si]):
                remap = np.full(max(len(vs), 1) + 1, -1, np.int32)
                for li, v in enumerate(vs):
                    remap[li] = gord[v]
                local = ords[: seg.ndocs]
                bins[si, off: off + seg.ndocs] = np.where(
                    local >= 0, remap[np.minimum(local, len(vs))], -1)
                off += seg.ndocs
        sh = NamedSharding(mesh, P("shard"))
        out = (jax.device_put(bins, sh), vocab)  # oslint: disable=OSL506 -- _ByteLRU kind registers at put()
        self._stacked_cols.put(key, (svc.generation, out), bins.nbytes)
        return out

    def _mterms_for(self, name: str, svc, fields: tuple, an, shard_segs,
                    stats, d_pad: int, mesh) -> Optional[tuple]:
        """Stacked GLOBAL combined multi_terms ordinals [S, d_pad]
        (-1 = doc missing any source) + the key-tuple vocab union — the
        per-segment combined ords from the host cache remapped into one
        index-wide ordinal space. Cached per generation."""
        from ..search.compiler import _multi_terms_cache

        key = ("mterms", name, fields)
        cached = self._stacked_cols.get(key)
        if cached is not None and cached[0] == svc.generation:
            return cached[1]
        per_seg = []
        for si, segs in enumerate(shard_segs):
            row = []
            for seg in segs:
                try:
                    row.append(_multi_terms_cache(seg, stats[si], an,
                                                  fields))
                except Exception:
                    self._stacked_cols.put(key, (svc.generation, None), 0)
                    return None
            per_seg.append(row)
        return self._stack_global_ords(key, svc, per_seg, shard_segs,
                                       d_pad, mesh)

    def _composite_fields(self, an) -> tuple:
        return tuple(next(iter(src.values()))["terms"]["field"]
                     for src in an.body["sources"])

    def _composite_for(self, an, name: str, svc, shard_segs, stats,
                       d_pad: int, mesh) -> Optional[tuple]:
        """Stacked combined ordinals for a composite over single-valued
        keyword terms sources — the per-doc key tuple equals the
        multi_terms combined key, so the multi_terms per-segment cache
        feeds the shared global-ordinal stacker. Declines (host loop)
        when any source field is multi-valued anywhere: the host pages
        per-value there, and a min-ord mapping would silently drop
        values."""
        fields = self._composite_fields(an)
        key = ("composite-ok", name, fields)
        cached = self._stacked_cols.get(key)
        if cached is not None and cached[0] == svc.generation:
            ok = cached[1]
        else:
            # every source must resolve (through aliases, like the host
            # prepare does) to a SINGLE-valued keyword column present in
            # EVERY segment: the host emits zero buckets per segment
            # lacking the column, and a min-ord mapping of a multi-valued
            # field would silently drop values — both decline
            mp = stats[0].mappings
            resolved = tuple(mp.aliases.get(f, f) for f in fields)
            ok = True
            for segs in shard_segs:
                for seg in segs:
                    for f in resolved:
                        col = seg.keyword_cols.get(f)
                        if col is None or (len(col.ords) and int(np.max(
                                col.starts[1:] - col.starts[:-1])) > 1):
                            ok = False
            self._stacked_cols.put(key, (svc.generation, ok), 0)
        if not ok:
            return None
        return self._mterms_for(name, svc, fields, an, shard_segs, stats,
                                d_pad, mesh)

    def _resolve_filters_aggs(self, agg_nodes, shard_segs, stats) -> bool:
        """Resolve every `filters` agg's named clauses to cached per-shard
        masks (same machinery as the query-level guardrail filters).
        Returns False when any clause can't be masked (caller falls back);
        resolved (key, combo, masks) lists ride on the AggNode."""
        from ..search import compiler as C
        from ..search import query_dsl as dsl

        for an in (agg_nodes or []):
            if an.kind not in ("filters", "adjacency_matrix", "filter",
                               "missing"):
                continue
            if an.kind == "adjacency_matrix":
                raw = an.body.get("filters", {})
                items = [(k, raw[k]) for k in sorted(raw)]
            elif an.kind == "filter":
                items = [("_f", an.body)]
            elif an.kind == "missing":
                items = [("_f", {"exists": {"field": an.body["field"]}})]
            else:
                items = C.filters_agg_items(an.body)
            nodes = []
            for fname, f in items:
                try:
                    lnode = C.rewrite(dsl.parse_query(f), stats[0],
                                      scoring=False)
                except dsl.QueryParseError:
                    return False
                if not self._maskable(lnode):
                    return False
                nodes.append((fname, lnode))
            resolved = []
            if an.kind == "missing":
                # parity guard: the host missing aggregator recognizes
                # ONLY numeric/keyword columns (text/geo fields count all
                # docs as missing there), while the exists mask sees
                # text/geo presence — serve only fields that are
                # numeric/keyword-backed in EVERY segment
                mp = stats[0].mappings
                f = mp.aliases.get(an.body["field"], an.body["field"])
                for segs in shard_segs:
                    for seg in segs:
                        if f not in seg.numeric_cols \
                                and f not in seg.keyword_cols:
                            return False
                # the wrapper mask is NOT exists(field)
                fp = self._fmask_resolve(shard_segs, stats, [],
                                         [nodes[0][1]])
                if fp is None:
                    return False
                an._mesh_filters = [("_f", fp[0], fp[1])]
                continue
            combos = [(fname, [ln]) for fname, ln in nodes]
            if an.kind == "adjacency_matrix":
                # plus the pairwise intersections, host label order
                sep = an.body.get("separator", "&")
                for ai in range(len(nodes)):
                    for bi in range(ai + 1, len(nodes)):
                        combos.append((
                            f"{nodes[ai][0]}{sep}{nodes[bi][0]}",
                            [nodes[ai][1], nodes[bi][1]]))
            for fname, lns in combos:
                fp = self._fmask_resolve(shard_segs, stats, lns, [])
                if fp is None:
                    return False
                resolved.append((fname, fp[0], fp[1]))
            an._mesh_filters = resolved
        return True

    def _sig_background(self, name: str, svc, field: str, shard_segs
                        ) -> tuple:
        """significant_terms superset stats summed over every segment of
        every shard (segments WITHOUT the column still contribute their
        live docs — reference supersetSize semantics). Cached per
        generation; the host path computes the same per segment."""
        from ..search.compiler import _kw_doc_counts

        key = ("sigbg", name, field)
        cached = self._stacked_cols.get(key)
        if cached is not None and cached[0] == svc.generation:
            return cached[1]
        bg: Dict[str, int] = {}
        bg_total = 0
        for segs in shard_segs:
            for seg in segs:
                bg_total += seg.live_count
                if field in seg.keyword_cols:
                    for k, c in _kw_doc_counts(seg, field).items():
                        bg[k] = bg.get(k, 0) + c
        out = (bg, bg_total)
        self._stacked_cols.put(key, (svc.generation, out),
                               64 * max(len(bg), 1))
        return out

    def _geo_program_for(self, mesh, bucket: int, ndocs_pad: int,
                         k1: float, b: float, filtered: bool = False):
        key = (id(mesh), bucket, ndocs_pad, k1, b, filtered)
        fn = self._geo_programs.get(key)
        if fn is None:
            fn = build_distributed_geo_stat(
                mesh, bucket=bucket, ndocs_pad=ndocs_pad, k1=k1, b=b,
                filtered=filtered)
            self._geo_programs[key] = fn
        return fn

    def _card_program_for(self, mesh, bucket: int, ndocs_pad: int,
                          keyword: bool, vpad: int, k1: float, b: float,
                          filtered: bool = False):
        key = (id(mesh), bucket, ndocs_pad, keyword, vpad, k1, b, filtered)
        fn = self._card_programs.get(key)
        if fn is None:
            fn = build_distributed_cardinality(
                mesh, bucket=bucket, ndocs_pad=ndocs_pad, keyword=keyword,
                vpad=vpad, k1=k1, b=b, filtered=filtered)
            self._card_programs[key] = fn
        return fn

    def _ddsketch_program_for(self, mesh, bucket: int, ndocs_pad: int,
                              k1: float, b: float, filtered: bool = False):
        key = (id(mesh), bucket, ndocs_pad, k1, b, filtered)
        fn = self._ddsketch_programs.get(key)
        if fn is None:
            fn = build_distributed_ddsketch(
                mesh, bucket=bucket, ndocs_pad=ndocs_pad, k1=k1, b=b,
                filtered=filtered)
            self._ddsketch_programs[key] = fn
        return fn

    def _wavg_program_for(self, mesh, bucket: int, ndocs_pad: int,
                          k1: float, b: float, filtered: bool = False):
        key = (id(mesh), bucket, ndocs_pad, k1, b, filtered)
        fn = self._wavg_programs.get(key)
        if fn is None:
            fn = build_distributed_weighted_avg(
                mesh, bucket=bucket, ndocs_pad=ndocs_pad, k1=k1, b=b,
                filtered=filtered)
            self._wavg_programs[key] = fn
        return fn

    def _pair_metrics_program_for(self, mesh, bucket: int, ndocs_pad: int,
                                  vpad: int, k1: float, b: float,
                                  filtered: bool = False):
        key = (id(mesh), bucket, ndocs_pad, vpad, k1, b, filtered)
        fn = self._pair_metrics_programs.get(key)
        if fn is None:
            fn = build_distributed_pair_metrics(
                mesh, bucket=bucket, ndocs_pad=ndocs_pad, vpad=vpad,
                k1=k1, b=b, filtered=filtered)
            self._pair_metrics_programs[key] = fn
        return fn

    def _range_metrics_program_for(self, mesh, bucket: int, ndocs_pad: int,
                                   nr: int, k1: float, b: float,
                                   filtered: bool = False):
        key = (id(mesh), bucket, ndocs_pad, nr, k1, b, filtered)
        fn = self._range_metrics_programs.get(key)
        if fn is None:
            fn = build_distributed_range_metrics(
                mesh, bucket=bucket, ndocs_pad=ndocs_pad, nr=nr,
                k1=k1, b=b, filtered=filtered)
            self._range_metrics_programs[key] = fn
        return fn

    def _bins_for(self, name: str, svc, an, shard_segs, d_pad: int, mesh
                  ) -> Optional[tuple]:
        """Host-precomputed per-doc GLOBAL bin ids for a histogram /
        fixed-interval date_histogram (-1 = no value), stacked and
        shard-sharded — the mesh analog of the host 'hist' bin compute,
        done in one vectorized pass per (field, interval, offset) and
        cached per generation. Returns (bins_dev, min_b, nb, interval,
        offset) or None (missing column / too many bins)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        field = an.body["field"]
        interval, offset = hist_agg_interval(an.kind, an.body)
        if interval <= 0:
            return None
        key = (name, field, an.kind, interval, offset)
        cached = self._stacked_bins.get(key)
        if cached is not None and cached[0] == svc.generation:
            return cached[1]
        if not any(field in seg.numeric_cols
                   for segs in shard_segs for seg in segs):
            self._stacked_bins.put(key, (svc.generation, None), 0)
            return None
        S = len(shard_segs)
        raw = np.full((S, d_pad), np.iinfo(np.int64).min, np.int64)
        for si, segs in enumerate(shard_segs):
            off = 0
            for seg in segs:
                nc = seg.numeric_cols.get(field)
                if nc is not None:
                    if an.kind == "date_histogram":
                        # exact i64 floor-div — the host date path
                        # (`compiler._host_date_buckets`) is integer, and
                        # epoch-ms values exceed f32 precision
                        bins = np.floor_divide(
                            nc.values.astype(np.int64) - np.int64(offset),
                            np.int64(max(interval, 1)))
                    else:
                        # f32 arithmetic to MATCH the host 'hist' kernel
                        # bit-for-bit (it bins the f32 column on device)
                        bins = np.floor(
                            (nc.values.astype(np.float32)
                             - np.float32(offset)) / np.float32(interval)
                        ).astype(np.int64)
                    bins = np.where(nc.present, bins,
                                    np.iinfo(np.int64).min).astype(np.int64)
                    raw[si, off: off + seg.ndocs] = bins
                off += seg.ndocs
        present = raw > np.iinfo(np.int64).min
        if not present.any():
            self._stacked_bins.put(key, (svc.generation, None), 0)
            return None
        min_b = int(raw[present].min())
        nb = int(raw[present].max()) - min_b + 1
        if nb > MAX_MESH_BINS:
            self._stacked_bins.put(key, (svc.generation, None), 0)
            return None
        bins32 = np.where(present, raw - min_b, -1).astype(np.int32)
        dev = jax.device_put(bins32, NamedSharding(mesh, P("shard")))  # oslint: disable=OSL506 -- _ByteLRU kind registers at put()
        out = (dev, min_b, nb, interval, offset)
        self._stacked_bins.put(key, (svc.generation, out), bins32.nbytes)
        return out

    def _col_for(self, name: str, svc, field: str, shard_segs,
                 d_pad: int, mesh) -> Optional[tuple]:
        """Stacked numeric column + presence mask [S, d_pad] sharded over
        the mesh, in the SAME per-shard concatenated doc space as the
        stacked postings; None when no segment has the column. Cached
        (incl. negative results) per generation under a byte-bounded LRU."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = (name, field)
        cached = self._stacked_cols.get(key)
        if cached is not None and cached[0] == svc.generation:
            return cached[1]
        # cheap membership test BEFORE any allocation: declining a text/
        # missing field must not zero megabytes per request
        if not any(field in seg.numeric_cols
                   for segs in shard_segs for seg in segs):
            self._stacked_cols.put(key, (svc.generation, None), 0)
            return None
        S = len(shard_segs)
        col = np.zeros((S, d_pad), np.float32)
        pres = np.zeros((S, d_pad), np.float32)
        for si, segs in enumerate(shard_segs):
            off = 0
            for seg in segs:
                nc = seg.numeric_cols.get(field)
                if nc is not None:
                    col[si, off: off + seg.ndocs] = \
                        nc.values.astype(np.float32)
                    pres[si, off: off + seg.ndocs] = \
                        nc.present.astype(np.float32)
                off += seg.ndocs
        sharding = NamedSharding(mesh, P("shard"))
        out = (jax.device_put(col, sharding),  # oslint: disable=OSL506 -- _ByteLRU kind registers at put()
               jax.device_put(pres, sharding))  # oslint: disable=OSL506 -- _ByteLRU kind registers at put()
        # byte-bounded LRU so long-lived nodes aggregating over many
        # fields/indices can't pin device columns forever
        self._stacked_cols.put(key, (svc.generation, out),
                               col.nbytes + pres.nbytes)
        return out

    def _ord_for(self, name: str, svc, field: str, shard_segs, d_pad: int,
                 mesh) -> Optional[tuple]:
        """Stacked keyword GLOBAL-ordinal values for a `terms` agg:
        (val_doc i32[S, NV], val_ord i32[S, NV], vocab) where val_doc is the
        per-shard concatenated doc index of each flat keyword value and
        val_ord its ordinal in the index-wide sorted vocab union — the mesh
        analog of the reference's global ordinals build
        (GlobalOrdinalsBuilder). Cached per generation; None when the field
        has no keyword column or its vocab exceeds MAX_TERMS_VOCAB."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = (name, field)
        cached = self._stacked_ords.get(key)
        if cached is not None and cached[0] == svc.generation:
            return cached[1]
        cols = [[seg.keyword_cols.get(field) for seg in segs]
                for segs in shard_segs]
        if not any(c is not None for cs in cols for c in cs):
            self._stacked_ords.put(key, (svc.generation, None), 0)
            return None
        vocab = sorted({v for cs in cols for c in cs if c is not None
                        for v in c.vocab})
        if len(vocab) > MAX_TERMS_VOCAB:
            self._stacked_ords.put(key, (svc.generation, None), 0)
            return None
        gord = {v: i for i, v in enumerate(vocab)}
        S = len(shard_segs)
        nv = max(max(sum(len(c.ords) for c in cs if c is not None)
                     for cs in cols), 1)
        nv_pad = next_pow2(nv, floor=8)
        val_doc = np.full((S, nv_pad), INT32_SENTINEL, np.int32)
        val_ord = np.zeros((S, nv_pad), np.int32)
        for si, (segs, cs) in enumerate(zip(shard_segs, cols)):
            off = 0      # doc offset of this segment within the shard
            pos = 0      # flat value write position
            for seg, c in zip(segs, cs):
                if c is not None and len(c.ords):
                    n = len(c.ords)
                    val_doc[si, pos: pos + n] = \
                        c.doc_of_value.astype(np.int32) + off
                    remap = np.array([gord[v] for v in c.vocab], np.int32)
                    val_ord[si, pos: pos + n] = remap[c.ords]
                    pos += n
                off += seg.ndocs
        sharding = NamedSharding(mesh, P("shard"))
        out = (jax.device_put(val_doc, sharding),  # oslint: disable=OSL506 -- _ByteLRU kind registers at put()
               jax.device_put(val_ord, sharding), vocab,  # oslint: disable=OSL506 -- _ByteLRU kind registers at put()
               next_pow2(len(vocab), floor=8))
        self._stacked_ords.put(key, (svc.generation, out),
                               val_doc.nbytes + val_ord.nbytes)
        return out

    def _fmask_resolve(self, shard_segs, stats, fnodes, notnodes
                       ) -> Optional[tuple]:
        """Resolve a bool query's filter/must_not clauses to per-segment
        cached masks (compiler filter-mask cache) and combine them into one
        per-shard host mask. Returns (combo_key, masks_by_shard) — the key
        is the sorted per-clause cache keys, each already encoding segment
        uid + live_gen + spec digest, so index mutations mint new keys —
        or None when any clause's mask is unavailable (caller falls back to
        the host loop). The AND-combine only runs on a combo-cache miss;
        repeated guardrail combos pay just the per-clause cache hits."""
        from ..search import compiler as C

        # pass 1: per-clause cache keys (masks come along from the
        # compiler's own cache; the per-body cost on a hit is ~zero)
        clause_keys = []
        clause_masks = []   # aligned [(si, seg, mask, positive), ...]
        for si, segs in enumerate(shard_segs):
            for seg in segs:
                for node, positive in ([(n, True) for n in fnodes]
                                       + [(n, False) for n in notnodes]):
                    mask, mkey, _spec, _local = C.filter_mask_for(
                        node, seg, stats[si])
                    if mask is None:
                        return None
                    clause_keys.append((mkey, positive))
                    clause_masks.append((si, seg, mask, positive))
        combo = tuple(sorted(clause_keys))
        cached = self._host_masks.get(combo)
        if cached is not None:
            return combo, cached
        masks_by_shard = [[np.ones(seg.ndocs, bool) for seg in segs]
                          for segs in shard_segs]
        seg_pos = [{id(seg): j for j, seg in enumerate(segs)}
                   for segs in shard_segs]
        for si, seg, mask, positive in clause_masks:
            m = np.asarray(mask[: seg.ndocs], bool)
            tgt = masks_by_shard[si][seg_pos[si][id(seg)]]
            tgt &= m if positive else ~m
        self._host_masks.put(combo, masks_by_shard,
                             sum(m.nbytes for ms in masks_by_shard
                                 for m in ms))
        return combo, masks_by_shard

    def _dev_mask_for(self, combo, masks_by_shard, shard_segs, d_pad: int,
                      mesh):
        """Device-resident stacked f32[S, d_pad] filter mask for a resolved
        combo (shard-sharded); built once and LRU-cached — the
        guardrail-filter reuse the reference gets from its query cache
        (`indices/IndicesQueryCache.java`), as device-resident masks. The
        host masks travel WITH the call (not re-read from a cache that may
        have evicted them between parse and run)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = (combo, d_pad)
        cached = self._dev_masks.get(key)
        if cached is not None:
            return cached
        S = len(shard_segs)
        fmask = np.zeros((S, d_pad), np.float32)
        for si, (segs, masks) in enumerate(zip(shard_segs, masks_by_shard)):
            off = 0
            for seg, m in zip(segs, masks):
                fmask[si, off: off + seg.ndocs] = m.astype(np.float32)
                off += seg.ndocs
        out = jax.device_put(fmask, NamedSharding(mesh, P("shard")))  # oslint: disable=OSL506 -- _ByteLRU kind registers at put()
        self._dev_masks.put(key, out, fmask.nbytes)
        return out

    # ---------------- dispatch ----------------

    def try_search(self, name: str, svc, body: dict) -> Optional[dict]:
        """One index, one term-group query -> full search response via the
        mesh, or None to fall back to the host shard loop."""
        return self.try_msearch(name, svc, [body])[0]

    def try_msearch(self, name: str, svc, bodies) -> list:
        """Synchronous msearch through the SPMD mesh: launch + fetch
        back-to-back (see `launch_msearch` for the split)."""
        return self.launch_msearch(name, svc, bodies).fetch()

    def launch_msearch(self, name: str, svc, bodies) -> "LaunchHandle":
        """A BATCH of search bodies over one index through the SPMD mesh:
        eligible bodies group by (similarity, window class) and run as ONE
        program invocation each — the query axis of the distributed
        program is the batch (replica-sharded on a pod), so an msearch of
        N term-group queries pays one dispatch, one DFS psum, and one
        all_gather merge for the whole group. Ineligible bodies come back
        as None for the host loop. Served shapes: scoring term groups
        (term/terms/match, any minimum_should_match) and filter-context
        groups (`terms`, constant score); multi-segment and empty shards;
        windows to MAX_WINDOW.

        LAUNCH stage: parse/eligibility, program build, and every program
        invocation run here — invocations serialized under
        `_dispatch_lock` (concurrent collective invocations cross-join
        their XLA rendezvous participants and deadlock), which is
        RELEASED before any device sync. The returned handle's `fetch()`
        performs the one-`device_get`-per-group transfer plus
        coordinator-side result assembly and returns the per-body
        response list (None entries -> host loop)."""
        from ..search.launch import LaunchHandle
        from ..search import compiler as C
        from ..search import query_dsl as dsl
        from ..search.executor import (_global_stats_contexts,
                                       _norm_sort_specs, parse_aggs,
                                       _collect_named)

        out: list = [None] * len(bodies)
        searchers = svc.searchers
        # the mesh program earns its keep on SHARDED indices (per-shard
        # SPMD scoring + device DFS/merge); a single-shard index would pay
        # compile + dispatch overhead for zero parallelism
        if svc.meta.num_shards < 2:
            self._fall("single_shard", len(bodies))
            return LaunchHandle(
                lambda: self._mark_declined(bodies, out), kind="mesh")
        # a shard may hold any number of segments (incl. zero for routing
        # holes) — the stacked index concatenates them per shard
        # ALL segments, including fully-deleted ones: the host's Lucene
        # maxDoc stats (N, df) count their docs, so excluding them skews
        # mesh idf; their live mask already zeroes every match
        shard_segs = [list(s.engine.segments) for s in searchers]
        stats = _global_stats_contexts(searchers)
        ctx = stats[0]

        parsed = []  # (qi, lt, sort_specs, window, const_score, aggs, fkey)
        for qi, body in enumerate(bodies):
            try:
                query = dsl.parse_query(body.get("query"))
            except dsl.QueryParseError:
                self._fall("parse_error")
                continue
            if isinstance(query, dsl.HybridQuery):
                # hybrid fuses at the coordinator AFTER N independent
                # retrievals (search/fusion.py) — declined BEFORE rewrite
                # (the rewriter 400s on nested hybrid) with its own
                # attributed shape, never the flat query_shape bucket
                self._fall("query_hybrid")
                continue
            lroot = C.rewrite(query, ctx, scoring=True)
            sort_specs = _norm_sort_specs(body)
            agg_nodes = parse_aggs(body.get("aggs",
                                            body.get("aggregations")))
            window = int(body.get("from", 0)) + int(body.get("size", 10))
            shape = self._eligible(lroot, sort_specs, agg_nodes,
                                   _collect_named(lroot), body, window)
            if shape is None:
                self._fall(self._host_loop_shape(body, agg_nodes))
                continue
            lt, fnodes, notnodes, qboost, msm_eff = shape
            fpair = None            # (combo_key, per-shard host masks)
            if fnodes or notnodes:
                fpair = self._fmask_resolve(shard_segs, stats, fnodes,
                                            notnodes)
                if fpair is None:
                    self._fall("filter_unmaskable")
                    continue
            const = (float(getattr(lt, "boost", 1.0) or 1.0) * qboost
                     if getattr(lt, "mode", None) == "filter" else 0.0)
            # `filters` aggs: resolve each named filter to cached masks
            # now (parse-time ctx); any unmaskable clause -> host loop.
            # The resolved list rides on the AggNode (fresh per request)
            if not self._resolve_filters_aggs(agg_nodes, shard_segs,
                                              stats):
                self._fall("filters_agg_unmaskable")
                continue
            parsed.append((qi, lt, sort_specs, max(window, 1), const,
                           agg_nodes or [], fpair, qboost, msm_eff))
        if not parsed:
            return LaunchHandle(
                lambda: self._mark_declined(bodies, out), kind="mesh")

        # group by program parameters: field (via the stacked index), sim,
        # the pow2 WINDOW CLASS — co-batching a size=10 body with a
        # from+size=1000 body would force K=1024 merge slots on everyone
        # and every distinct K is its own compiled program — and the filter
        # combo (one device mask argument serves the whole group; guardrail
        # filters repeat heavily so batching survives the split)
        groups: dict = {}
        for item in parsed:
            (qi, lt, sort_specs, window, const, aggs, fpair, qboost,
             msm_eff) = item
            sim = lt.sim
            k1 = float(sim.k1) if sim is not None else 1.2
            b_eff = (float(sim.b)
                     if sim is not None and lt.has_norms else 0.0)
            k_class = min(next_pow2(max(window, 16)), MAX_WINDOW)
            fkey = fpair[0] if fpair is not None else None
            is_phrase = isinstance(lt, C.LPhrase)
            nt_key = len(lt.terms) if is_phrase else 0
            groups.setdefault((is_phrase, nt_key, lt.field, k1, b_eff,
                               k_class, fkey), []).append(item)
        # LAUNCH: every group's program invocation runs here, serialized
        # under the dispatch lock; each returns a fetch closure capturing
        # its unfetched device arrays. The lock is released before ANY
        # fetch — the whole point of the split (the pipelined dispatcher
        # launches batch N+1 while a completion worker fetches batch N)
        fetchers = []
        # the lock-wait is a first-class forensic signal: under the
        # serving scheduler it should be ~0 (one dispatcher owns the
        # mesh); a growing wait means direct traffic is contending with
        # the scheduler for program invocation
        t_lock = time.monotonic()
        self._dispatch_lock.acquire()
        try:
            lock_wait_ms = (time.monotonic() - t_lock) * 1000.0
            METRICS.histogram("mesh.dispatch_lock_wait").record(
                lock_wait_ms)
            progs0 = len(self._programs)
            for (is_phrase, nt_key, field, k1, b_eff, k_class,
                 _fkey), items in groups.items():
                with TRACER.span("mesh.dispatch_group", field=field,
                                 k_class=k_class, queries=len(items),
                                 phrase=is_phrase):
                    if is_phrase:
                        fg = self._launch_phrase_group(
                            name, svc, bodies, out, shard_segs, stats,
                            searchers, field, nt_key, k1, b_eff, k_class,
                            items)
                    else:
                        fg = self._launch_mesh_group(
                            name, svc, bodies, out, shard_segs, stats,
                            searchers, field, k1, b_eff, k_class, items)
                    if fg is not None:
                        fetchers.append(fg)
            # delta read under the lock: a concurrent launch's compiles
            # must not be misattributed to this launch's forensics
            new_programs = len(self._programs) - progs0
        finally:
            self._dispatch_lock.release()

        info = None
        if _fr.RECORDER.enabled:
            info = {"path": "mesh", "bodies": len(parsed),
                    "groups": len(fetchers),
                    "lock_wait_ms": round(lock_wait_ms, 3),
                    "new_programs": new_programs}
            tl = _fr.current()
            if tl:
                # direct (non-scheduler) path: the request thread owns
                # the ambient timeline — stamp the launch boundary here;
                # scheduler-path launches are stamped per entry by the
                # dispatcher using handle.info
                _fr.RECORDER.record(tl, "mesh.launch", **info)

        def _finish():
            t_fetch = time.monotonic()
            for fg in fetchers:
                with TRACER.span("mesh.fetch_group"):
                    fg()
            if _fr.RECORDER.enabled:
                tl = _fr.current()
                if tl:
                    _fr.RECORDER.record(
                        tl, "mesh.fetch", groups=len(fetchers),
                        fetch_ms=round(
                            (time.monotonic() - t_fetch) * 1000.0, 3))
            return self._mark_declined(bodies, out)

        return LaunchHandle(_finish, kind="mesh", info=info)

    def _mark_declined(self, bodies, out) -> list:
        """Tag every body this call declined so the caller's per-body retry
        skips the mesh instead of re-declining it (Node.search pops the
        tag) — one logical search counts at most one fallback."""
        for body, resp in zip(bodies, out):
            if resp is None and isinstance(body, dict):
                body["_mesh_declined"] = True
        return out

    def _launch_mesh_group(self, name, svc, bodies, out, shard_segs,
                           stats, searchers, field, k1, b_eff, k_class,
                           items):
        """LAUNCH stage of one term-group program batch: agg-column
        staging, program build, and every program invocation (scoring +
        per-agg reduces) — returns a fetch closure over the unfetched
        device arrays, or None when the whole group declined. Must not
        block on device results (oslint OSL504); the single `device_get`
        lives in the returned closure."""
        t0 = time.monotonic()
        stacked = self._stacked_for(name, svc, field, shard_segs)
        if stacked is None:
            self._fall("no_stacked_index", len(items))
            return
        S = len(shard_segs)
        mesh = self._mesh_for(S)
        if mesh is None:
            self._fall("no_mesh", len(items))
            return
        # every item in the group shares one filter combo (the group key)
        fpair = items[0][6]
        K = min(k_class, stacked.ndocs_pad)
        keep = []
        for it in items:
            if it[3] > K:
                # deeper page than the program's merged top-k capacity
                # (tiny shards): that body takes the host loop
                self._fall("deep_window")
                continue
            # aggs need their stacked columns (metric) or global-ordinal
            # values (terms); a missing/oversized one -> host loop
            agg_ok = True
            for an in it[5]:
                if an.kind in ("geohash_grid", "geotile_grid"):
                    got = self._grid_for(name, svc, an.body["field"],
                                         an.kind,
                                         grid_agg_precision(an.kind,
                                                            an.body),
                                         shard_segs, stacked.ndocs_pad,
                                         mesh)
                elif an.kind in ("terms", "significant_terms",
                                 "rare_terms"):
                    got = self._ord_for(name, svc, an.body["field"],
                                        shard_segs, stacked.ndocs_pad, mesh)
                    if an.kind == "significant_terms" and got is not None \
                            and not all(an.body["field"] in seg.keyword_cols
                                        for segs in shard_segs
                                        for seg in segs):
                        # host fg_total EXCLUDES matches in segments
                        # lacking the column (sig_missing partials);
                        # the mesh total is global — mixed presence
                        # takes the host loop to keep parity exact
                        got = None
                elif an.kind in ("histogram", "date_histogram"):
                    got = self._bins_for(name, svc, an, shard_segs,
                                         stacked.ndocs_pad, mesh)
                elif an.kind == "multi_terms":
                    got = self._mterms_for(
                        name, svc,
                        tuple(src["field"] for src in an.body["terms"]),
                        an, shard_segs, stats, stacked.ndocs_pad, mesh)
                elif an.kind == "composite":
                    got = self._composite_for(an, name, svc, shard_segs,
                                              stats, stacked.ndocs_pad,
                                              mesh)
                elif an.kind == "cardinality":
                    # keyword fields ride global ordinals, numeric the
                    # stacked column; neither -> host loop
                    got = (self._ord_for(name, svc, an.body["field"],
                                         shard_segs, stacked.ndocs_pad,
                                         mesh)
                           or self._col_for(name, svc, an.body["field"],
                                            shard_segs, stacked.ndocs_pad,
                                            mesh))
                elif an.kind in ("filters", "adjacency_matrix",
                                 "filter", "missing"):
                    got = getattr(an, "_mesh_filters", None)
                elif an.kind == "weighted_avg":
                    got = self._col_for(
                        name, svc, an.body["value"]["field"], shard_segs,
                        stacked.ndocs_pad, mesh) and self._col_for(
                        name, svc, an.body["weight"]["field"], shard_segs,
                        stacked.ndocs_pad, mesh)
                elif an.kind in ("geo_bounds", "geo_centroid"):
                    got = self._geo_for(name, svc, an.body["field"],
                                        shard_segs, stacked.ndocs_pad,
                                        mesh)
                else:
                    got = self._col_for(name, svc, an.body["field"],
                                        shard_segs, stacked.ndocs_pad, mesh)
                for sub in an.subs:
                    if got is None:
                        break
                    got = self._col_for(name, svc, sub.body["field"],
                                        shard_segs, stacked.ndocs_pad,
                                        mesh)
                if got is None:
                    agg_ok = False
                    break
            if not agg_ok:
                self._fall("agg_column")
                continue
            keep.append(it)
        items = keep
        if not items:
            return
        # pad the query axis to pow2 so batch size never mints new program
        # shapes (dummy slots: all rows -1 -> every score -inf)
        QB = next_pow2(len(items), floor=1)
        T_pad = max(next_pow2(len(it[1].terms), floor=1) for it in items)
        rows = np.full((S, QB, T_pad), -1, np.int32)
        boosts = np.zeros((QB, T_pad), np.float32)
        msm = np.ones(QB, np.float32)
        cscore = np.zeros(QB, np.float32)
        total_max = 1
        for bi, (qi, lt, sort_specs, window, const, aggs, _fk, qboost,
                 msm_eff) in enumerate(items):
            nt = len(lt.terms)
            # a wrapping bool's boost folds into the term weights: BM25 is
            # linear in the per-term weight, so boost*score == sum of
            # boost-scaled contributions (constant-score goes via cscore)
            boosts[bi, :nt] = lt.raw_boosts[:nt] * qboost
            msm[bi] = float(lt.msm) if msm_eff is None else float(msm_eff)
            cscore[bi] = const
            for si in range(S):
                tot = 0
                for ti, t in enumerate(lt.terms):
                    r = stacked.row(si, t)
                    rows[si, bi, ti] = r
                    tot += stacked.row_size(si, r)
                total_max = max(total_max, tot)
        bucket = next_pow2(total_max, floor=256)
        filtered = fpair is not None
        fmask = (self._dev_mask_for(fpair[0], fpair[1], shard_segs,
                                    stacked.ndocs_pad, mesh)
                 if filtered else None)
        fn = self._program_for(mesh, bucket, stacked.ndocs_pad, K, k1,
                               b_eff, filtered)
        # one scoring-program invocation serves the whole query group —
        # THE denominator for the serving scheduler's coalescing win
        # (scripts/measure_concurrency.py: invocations per query)
        self.launches += 1
        METRICS.counter("mesh.launches").inc()
        gdocs_b, gvals_b, totals_b = fn(stacked.tree(), rows, boosts, msm,
                                        cscore, fmask)
        import jax

        # metric aggs: one psum/pmin/pmax reduce per distinct field over
        # the whole batch (items without that agg just ignore its column);
        # terms aggs: one exact bincount+psum per distinct keyword field
        metric_fields = sorted({
            an.body["field"] for it in items for an in it[5]
            if an.kind not in ("terms", "histogram", "date_histogram",
                               "range", "cardinality", "percentiles",
                               "percentile_ranks",
                               "median_absolute_deviation",
                               "weighted_avg", "geo_bounds",
                               "geo_centroid", "significant_terms",
                               "rare_terms", "geohash_grid",
                               "geotile_grid", "filters", "date_range",
                               "multi_terms", "adjacency_matrix",
                               "composite", "filter", "missing")})
        terms_fields = sorted({an.body["field"] for it in items
                               for an in it[5]
                               if an.kind in ("terms", "significant_terms",
                                              "rare_terms")})
        metrics_by_field = {}
        if metric_fields:
            mfn = self._metric_program_for(mesh, bucket, stacked.ndocs_pad,
                                           k1, b_eff, filtered)
            for f in metric_fields:
                col, pres = self._col_for(name, svc, f, shard_segs,
                                          stacked.ndocs_pad, mesh)
                margs = (stacked.tree(), rows, boosts, msm, cscore, col,
                         pres) + ((fmask,) if filtered else ())
                metrics_by_field[f] = mfn(*margs)
        tcounts_by_field = {}
        tvocab_by_field = {}
        # (parent key, metric field) -> (i32[QB, nb] counts,
        #                                f32[QB, nb, 4] moments)
        tsub_results = {}

        def _launch_pair_subs(an, parent_key, vpad_b, pvd, pvo,
                              sub_results):
            """One pair-metrics launch per (bucket parent, metric field),
            shared by every body in the batch nesting that metric."""
            for s in an.subs:
                skey = (parent_key, s.body["field"])
                if skey in sub_results:
                    continue
                mcol, mpres = self._col_for(name, svc, s.body["field"],
                                            shard_segs, stacked.ndocs_pad,
                                            mesh)
                pmfn = self._pair_metrics_program_for(
                    mesh, bucket, stacked.ndocs_pad, vpad_b, k1, b_eff,
                    filtered)
                pmargs = (stacked.tree(), rows, boosts, msm, cscore,
                          pvd, pvo, mcol, mpres) \
                    + ((fmask,) if filtered else ())
                sub_results[skey] = pmfn(*pmargs)

        terms_subs = [an for it in items for an in it[5]
                      if an.kind in ("terms", "rare_terms") and an.subs]
        for f in terms_fields:
            val_doc, val_ord, vocab, vpad = self._ord_for(
                name, svc, f, shard_segs, stacked.ndocs_pad, mesh)
            tfn = self._terms_program_for(mesh, bucket, stacked.ndocs_pad,
                                          vpad, k1, b_eff, filtered)
            targs = (stacked.tree(), rows, boosts, msm, cscore, val_doc,
                     val_ord) + ((fmask,) if filtered else ())
            tcounts_by_field[f] = tfn(*targs)
            tvocab_by_field[f] = vocab
            for an in terms_subs:
                if an.body["field"] == f:
                    _launch_pair_subs(an, f, vpad, val_doc, val_ord,
                                      tsub_results)
        # histogram family: one bincount program per distinct
        # (field, interval, offset); range: per-range masked sums
        def _hist_key(an):
            # key on the PARSED (interval, offset) floats, via the same
            # shared resolver _bins_for uses: semantically equal aggs share
            # one device run, distinct aggs never alias one cache entry
            interval, offset = hist_agg_interval(an.kind, an.body)
            return (an.kind, an.body["field"], interval, offset)

        def _norm_ranges(an):
            # date_range coerces from/to (date math/formats -> ms) through
            # the shared host helper before bound construction; memoized
            # per AggNode (fresh per request) — attach and the sub-launch
            # loop re-enter this per body
            got = getattr(an, "_mesh_ranges", None)
            if got is None:
                got = coerce_agg_ranges(an.kind, an.body,
                                        an.body["field"],
                                        stats[0].mappings)
                an._mesh_ranges = got
            return got

        def _range_key(an):
            # bucket keys are part of the RESPONSE, so custom "key" labels
            # must be part of the cache key too
            _, _, rkeys, metas = range_agg_spec(_norm_ranges(an))
            return (an.kind, an.body["field"], tuple(rkeys),
                    tuple((m.get("from"), m.get("to")) for m in metas))

        # cardinality: shard-local HLL registers + pmax (bit-identical to
        # the host's per-segment registers merged by max)
        card_results = {}
        card_fields = sorted({an.body["field"] for it in items
                              for an in it[5] if an.kind == "cardinality"})
        for f in card_fields:
            got = self._ord_for(name, svc, f, shard_segs,
                                stacked.ndocs_pad, mesh)
            if got is not None:
                val_doc, val_ord, vocab, vpad = got
                # vocab hashes cached per generation (the O(vocab) python
                # crc32 loop must not run per request), byte-bounded like
                # every other per-(index, field) cache here
                from ..search.compiler import crc32_vocab_hashes
                hkey = (name, f)
                hcached = self._card_hashes.get(hkey)
                if hcached is not None and hcached[0] == svc.generation:
                    hashes = hcached[1]
                else:
                    hashes = crc32_vocab_hashes(vocab, vpad)
                    self._card_hashes.put(hkey,
                                          (svc.generation, hashes),
                                          hashes.nbytes)
                cfn = self._card_program_for(
                    mesh, bucket, stacked.ndocs_pad, True, vpad, k1,
                    b_eff, filtered)
                cargs = (stacked.tree(), rows, boosts, msm, cscore,
                         val_doc, val_ord, hashes) \
                    + ((fmask,) if filtered else ())
            else:
                col, pres = self._col_for(name, svc, f, shard_segs,
                                          stacked.ndocs_pad, mesh)
                cfn = self._card_program_for(
                    mesh, bucket, stacked.ndocs_pad, False, 0, k1, b_eff,
                    filtered)
                cargs = (stacked.tree(), rows, boosts, msm, cscore, col,
                         pres) + ((fmask,) if filtered else ())
            card_results[f] = cfn(*cargs)

        # DDSketch histograms (percentiles + percentile_ranks +
        # median_absolute_deviation share one program run per field) and
        # weighted_avg moments
        dd_results = {}
        dd_fields = sorted({an.body["field"] for it in items
                            for an in it[5]
                            if an.kind in ("percentiles",
                                           "percentile_ranks",
                                           "median_absolute_deviation")})
        for f in dd_fields:
            col, pres = self._col_for(name, svc, f, shard_segs,
                                      stacked.ndocs_pad, mesh)
            dfn = self._ddsketch_program_for(mesh, bucket,
                                             stacked.ndocs_pad, k1, b_eff,
                                             filtered)
            dargs = (stacked.tree(), rows, boosts, msm, cscore, col,
                     pres) + ((fmask,) if filtered else ())
            dd_results[f] = dfn(*dargs)
        wavg_results = {}
        wavg_pairs = sorted({(an.body["value"]["field"],
                              an.body["weight"]["field"])
                             for it in items for an in it[5]
                             if an.kind == "weighted_avg"})
        for vf, wf in wavg_pairs:
            vcol, vpres = self._col_for(name, svc, vf, shard_segs,
                                        stacked.ndocs_pad, mesh)
            wcol, wpres = self._col_for(name, svc, wf, shard_segs,
                                        stacked.ndocs_pad, mesh)
            wfn = self._wavg_program_for(mesh, bucket, stacked.ndocs_pad,
                                         k1, b_eff, filtered)
            wargs = (stacked.tree(), rows, boosts, msm, cscore, vcol,
                     vpres, wcol, wpres) + ((fmask,) if filtered else ())
            wavg_results[(vf, wf)] = wfn(*wargs)

        # geo grids: bincount over stacked global cell ordinals (the hist
        # program), one run per (field, kind, precision)
        grid_results = {}

        def _grid_key(an):
            return (an.body["field"], an.kind,
                    grid_agg_precision(an.kind, an.body))

        for it in items:
            for an in it[5]:
                if an.kind not in ("geohash_grid", "geotile_grid"):
                    continue
                gk = _grid_key(an)
                if gk in grid_results:
                    continue
                bins_dev, gvocab = self._grid_for(
                    name, svc, gk[0], gk[1], gk[2], shard_segs,
                    stacked.ndocs_pad, mesh)
                nbp = next_pow2(max(len(gvocab), 1))
                gfn_ = self._hist_program_for(
                    mesh, bucket, stacked.ndocs_pad, nbp, k1, b_eff,
                    filtered)
                gargs_ = (stacked.tree(), rows, boosts, msm, cscore,
                          bins_dev) + ((fmask,) if filtered else ())
                grid_results[gk] = (gfn_(*gargs_), gvocab)

        # `filters` agg: one metric-program count per named clause mask
        # (col == pres == the mask, so m[0] counts matched docs in it)
        fagg_results = {}
        fsub_results = {}     # (combo, metric field) ->
        #                       (i32[QB] counts, f32[QB, 4] moments)
        for it in items:
            for an in it[5]:
                if an.kind not in ("filters", "adjacency_matrix",
                                   "filter", "missing"):
                    continue
                mfn = self._metric_program_for(
                    mesh, bucket, stacked.ndocs_pad, k1, b_eff, filtered)
                for fname, combo, masks in an._mesh_filters:
                    dev = self._dev_mask_for(combo, masks, shard_segs,
                                             stacked.ndocs_pad, mesh)
                    if combo not in fagg_results:
                        margs = (stacked.tree(), rows, boosts, msm,
                                 cscore, dev, dev) \
                            + ((fmask,) if filtered else ())
                        fagg_results[combo] = mfn(*margs)
                    # metric subs under a `filter` wrapper: presence
                    # composes with the wrapper's mask on device
                    for sub in an.subs:
                        skey = (combo, sub.body["field"])
                        if skey in fsub_results:
                            continue
                        scol, spres = self._col_for(
                            name, svc, sub.body["field"], shard_segs,
                            stacked.ndocs_pad, mesh)
                        sargs = (stacked.tree(), rows, boosts, msm,
                                 cscore, scol, spres * dev) \
                            + ((fmask,) if filtered else ())
                        fsub_results[skey] = mfn(*sargs)

        # multi_terms + composite: combined global ordinals through the
        # bincount (a composite's key tuple IS the multi_terms key)
        mterms_results = {}
        for it in items:
            for an in it[5]:
                if an.kind not in ("multi_terms", "composite"):
                    continue
                if an.kind == "composite":
                    mk = ("composite",) + self._composite_fields(an)
                    bins_dev, mvocab = self._composite_for(
                        an, name, svc, shard_segs, stats,
                        stacked.ndocs_pad, mesh)
                else:
                    mk = tuple(src["field"] for src in an.body["terms"])
                    bins_dev, mvocab = self._mterms_for(
                        name, svc, mk, an, shard_segs, stats,
                        stacked.ndocs_pad, mesh)
                if mk in mterms_results:
                    continue
                nbp = next_pow2(max(len(mvocab), 1))
                mfn_ = self._hist_program_for(
                    mesh, bucket, stacked.ndocs_pad, nbp, k1, b_eff,
                    filtered)
                margs_ = (stacked.tree(), rows, boosts, msm, cscore,
                          bins_dev) + ((fmask,) if filtered else ())
                mterms_results[mk] = (mfn_(*margs_), mvocab)

        geo_results = {}
        geo_fields = sorted({an.body["field"] for it in items
                             for an in it[5]
                             if an.kind in ("geo_bounds", "geo_centroid")})
        for f in geo_fields:
            glat, glon, gpres = self._geo_for(name, svc, f, shard_segs,
                                              stacked.ndocs_pad, mesh)
            gfn = self._geo_program_for(mesh, bucket, stacked.ndocs_pad,
                                        k1, b_eff, filtered)
            gargs = (stacked.tree(), rows, boosts, msm, cscore, glat,
                     glon, gpres) + ((fmask,) if filtered else ())
            geo_results[f] = gfn(*gargs)

        hist_results = {}
        hist_bins = {}        # hist key -> device bins (sub-agg pair input)
        hist_pairs = {}       # hist key -> (val_doc, val_ord) device pairs
        range_results = {}
        hsub_results = {}     # (hist key, metric field) -> [QB, nb, 5]
        rsub_results = {}     # (range key, metric field) -> [QB, nr, 5]
        for it in items:
            for an in it[5]:
                if an.kind in ("histogram", "date_histogram"):
                    hk = _hist_key(an)
                    if hk not in hist_results:
                        (bins_dev, min_b, nb, interval,
                         offset) = self._bins_for(name, svc, an, shard_segs,
                                                  stacked.ndocs_pad, mesh)
                        hfn = self._hist_program_for(
                            mesh, bucket, stacked.ndocs_pad, nb, k1, b_eff,
                            filtered)
                        hargs = (stacked.tree(), rows, boosts, msm, cscore,
                                 bins_dev) + ((fmask,) if filtered else ())
                        hist_results[hk] = (hfn(*hargs), min_b, nb,
                                            interval, offset)
                        hist_bins[hk] = bins_dev
                    if an.subs:
                        if hk not in hist_pairs:
                            # bin-id pairs reused by every metric sub
                            # under this histogram: (local doc, bin) with
                            # sentinel docs for unbinned slots
                            import jax.numpy as jnp
                            bins_dev = hist_bins[hk]
                            hist_pairs[hk] = (
                                jnp.where(
                                    bins_dev >= 0,
                                    jnp.arange(stacked.ndocs_pad,
                                               dtype=jnp.int32)[None, :],
                                    INT32_SENTINEL),
                                jnp.maximum(bins_dev, 0))
                        hvd, hvo = hist_pairs[hk]
                        _launch_pair_subs(an, hk, hist_results[hk][2],
                                          hvd, hvo, hsub_results)
                elif an.kind in ("range", "date_range"):
                    rk = _range_key(an)
                    needed_subs = [s for s in an.subs
                                   if (rk, s.body["field"])
                                   not in rsub_results]
                    if rk in range_results and not needed_subs:
                        continue
                    lows, highs, rkeys, metas = range_agg_spec(
                        _norm_ranges(an))
                    col, pres = self._col_for(name, svc, an.body["field"],
                                              shard_segs,
                                              stacked.ndocs_pad, mesh)
                    if rk not in range_results:
                        rfn = self._range_program_for(
                            mesh, bucket, stacked.ndocs_pad, len(rkeys),
                            k1, b_eff, filtered)
                        rargs = (stacked.tree(), rows, boosts, msm, cscore,
                                 col, pres, lows, highs) \
                            + ((fmask,) if filtered else ())
                        range_results[rk] = (rfn(*rargs), rkeys, metas)
                    for s in needed_subs:
                        mcol, mpres = self._col_for(
                            name, svc, s.body["field"], shard_segs,
                            stacked.ndocs_pad, mesh)
                        rmfn = self._range_metrics_program_for(
                            mesh, bucket, stacked.ndocs_pad, len(rkeys),
                            k1, b_eff, filtered)
                        rmargs = (stacked.tree(), rows, boosts, msm,
                                  cscore, col, pres, lows, highs, mcol,
                                  mpres) + ((fmask,) if filtered else ())
                        rsub_results[(rk, s.body["field"])] = rmfn(*rmargs)

        # unfetched device outputs, captured for the deferred fetch (the
        # tuple is the closure's only handle on them; names shadowed
        # below so the outer bindings can be dropped with the handle)
        _pending = (gdocs_b, gvals_b, totals_b, metrics_by_field,
                    tcounts_by_field, hist_results, range_results,
                    tsub_results, hsub_results, rsub_results, card_results,
                    dd_results, wavg_results, geo_results, grid_results,
                    fagg_results, mterms_results, fsub_results)

        def _fetch_group():
            # ONE device->host transfer for the whole group's outputs —
            # the same single-device_get discipline the synchronous path
            # always had, just moved to the fetch stage
            fetched = jax.device_get(_pending)
            (gdocs_b, gvals_b, totals_b, metrics_by_field,
             tcounts_by_field, hist_results, range_results,
             tsub_results, hsub_results, rsub_results,
             card_results, dd_results, wavg_results,
             geo_results, grid_results, fagg_results,
             mterms_results, fsub_results) = fetched

            # attach the globally-reduced agg partials to shard 0 (the values
            # are already psum'd across the mesh; the coordinator merge sees
            # exactly one partial per agg)
            def _stat_partial(cnt, m4):
                # the host metric partial shape (`_merge_stats` input): count,
                # sum, sumsq always; extrema only meaningful when count > 0
                cnt = float(cnt)
                return {"count": cnt, "sum": float(m4[0]),
                        "min": float(m4[1]) if cnt > 0 else float("inf"),
                        "max": float(m4[2]) if cnt > 0 else float("-inf"),
                        "sumsq": float(m4[3])}

            def _ordinal_partial(counts, vocab, subs_of=None):
                # shared ordinal-bucket partial shape (terms / rare_terms /
                # significant_terms / geo grids)
                return {vocab[o]: {"doc_count": int(c),
                                   "subs": subs_of(o) if subs_of else {}}
                        for o, c in enumerate(counts[: len(vocab)]) if c > 0}

            def _bucket_subs(an, sub_results, parent_key, bi, j):
                out = {}
                for s in an.subs:
                    cnts, m4 = sub_results[(parent_key, s.body["field"])]
                    out[s.name] = _stat_partial(cnts[bi][j], m4[bi][j])
                return out

            def attach_aggs(results, bi, aggs):
                for an in aggs:
                    if an.kind in ("histogram", "date_histogram"):
                        hk = _hist_key(an)
                        counts, min_b, _nb, interval, offset = hist_results[hk]
                        buckets = {min_b + j: {
                            "doc_count": int(c),
                            "subs": _bucket_subs(an, hsub_results, hk, bi, j)}
                            for j, c in enumerate(counts[bi]) if c > 0}
                        results[0].agg_partials[an.name] = [{
                            "buckets": buckets, "interval": interval,
                            "offset": offset}]
                        continue
                    if an.kind in ("range", "date_range"):
                        rk = _range_key(an)
                        counts, rkeys, metas = range_results[rk]
                        buckets = {key: {
                            "doc_count": int(counts[bi][ri]),
                            "meta": metas[ri],
                            "subs": _bucket_subs(an, rsub_results, rk, bi, ri)}
                            for ri, key in enumerate(rkeys)}
                        results[0].agg_partials[an.name] = [{
                            "buckets": buckets}]
                        continue
                    if an.kind in ("terms", "rare_terms"):
                        f = an.body["field"]
                        buckets = _ordinal_partial(
                            tcounts_by_field[f][bi], tvocab_by_field[f],
                            (lambda o, _a=an, _f=f: _bucket_subs(
                                _a, tsub_results, _f, bi, o))
                            if an.subs else None)
                        results[0].agg_partials[an.name] = [{"buckets":
                                                             buckets}]
                        continue
                    if an.kind in ("geohash_grid", "geotile_grid"):
                        counts, gvocab = grid_results[_grid_key(an)]
                        buckets = _ordinal_partial(counts[bi], gvocab)
                        results[0].agg_partials[an.name] = [{"buckets":
                                                             buckets}]
                        continue
                    if an.kind in ("multi_terms", "composite"):
                        mk = (("composite",) + self._composite_fields(an)
                              if an.kind == "composite"
                              else tuple(src["field"]
                                         for src in an.body["terms"]))
                        counts, mvocab = mterms_results[mk]
                        buckets = _ordinal_partial(counts[bi], mvocab)
                        results[0].agg_partials[an.name] = [{"buckets":
                                                             buckets}]
                        continue
                    if an.kind in ("filter", "missing"):
                        _fn, combo, _m = an._mesh_filters[0]
                        subs = {}
                        for sub in an.subs:
                            sc, sm4 = fsub_results[(combo, sub.body["field"])]
                            subs[sub.name] = _stat_partial(sc[bi], sm4[bi])
                        # doc_count rides the program's int32 count plane:
                        # exact past the 2^24 f32 ceiling, no rounding
                        results[0].agg_partials[an.name] = [{
                            "doc_count": int(fagg_results[combo][0][bi]),
                            "subs": subs}]
                        continue
                    if an.kind in ("filters", "adjacency_matrix"):
                        buckets = {
                            fname: {"doc_count":
                                    int(fagg_results[combo][0][bi]),
                                    "subs": {}}
                            for fname, combo, _m in an._mesh_filters}
                        results[0].agg_partials[an.name] = [{"buckets":
                                                             buckets}]
                        continue
                    if an.kind == "significant_terms":
                        f = an.body["field"]
                        buckets = _ordinal_partial(tcounts_by_field[f][bi],
                                                   tvocab_by_field[f])
                        bg, bg_total = self._sig_background(name, svc, f,
                                                            shard_segs)
                        results[0].agg_partials[an.name] = [{
                            "buckets": buckets, "bg": bg,
                            "fg_total": int(totals_b[bi]),
                            "bg_total": bg_total}]
                        continue
                    if an.kind == "cardinality":
                        results[0].agg_partials[an.name] = [{
                            "registers": card_results[an.body["field"]][bi]}]
                        continue
                    if an.kind == "percentiles":
                        from ..search.compiler import DEFAULT_PERCENTS
                        percents = list(an.body.get("percents",
                                                    DEFAULT_PERCENTS))
                        results[0].agg_partials[an.name] = [{
                            "hist": dd_results[an.body["field"]][bi],
                            "percents": percents}]
                        continue
                    if an.kind == "percentile_ranks":
                        results[0].agg_partials[an.name] = [{
                            "hist": dd_results[an.body["field"]][bi],
                            "values": [float(v) for v in
                                       an.body.get("values", ())]}]
                        continue
                    if an.kind == "median_absolute_deviation":
                        results[0].agg_partials[an.name] = [{
                            "hist": dd_results[an.body["field"]][bi]}]
                        continue
                    if an.kind == "weighted_avg":
                        wv = wavg_results[(an.body["value"]["field"],
                                           an.body["weight"]["field"])][bi]
                        results[0].agg_partials[an.name] = [{
                            "vwsum": float(wv[0]), "wsum": float(wv[1]),
                            "count": float(wv[2])}]
                        continue
                    if an.kind in ("geo_bounds", "geo_centroid"):
                        g = geo_results[an.body["field"]][bi]
                        if an.kind == "geo_bounds":
                            results[0].agg_partials[an.name] = [{
                                "count": float(g[0]), "top": float(g[1]),
                                "bottom": float(g[2]), "left": float(g[3]),
                                "right": float(g[4])}]
                        else:
                            results[0].agg_partials[an.name] = [{
                                "count": float(g[0]), "slat": float(g[5]),
                                "slon": float(g[6])}]
                        continue
                    mc, m4 = metrics_by_field[an.body["field"]]
                    results[0].agg_partials[an.name] = [
                        _stat_partial(mc[bi], m4[bi])]

            self._emit_mesh_results(name, bodies, out, shard_segs, stats,
                                    searchers, stacked, items, gdocs_b,
                                    gvals_b, totals_b, t0,
                                    attach_aggs=attach_aggs)

        return _fetch_group


    def _emit_mesh_results(self, name, bodies, out, shard_segs, stats,
                           searchers, stacked, items, gdocs_b, gvals_b,
                           totals_b, t0, attach_aggs=None,
                           phrase=False) -> None:
        """Shared coordinator-side result assembly for every mesh program:
        decode global doc ids back to (shard, segment, local), build the
        candidate pool (host final selection keeps tie-breaks identical to
        the shard loop), attach agg partials via `attach_aggs`, count
        dispatch telemetry, and finish each body through the normal search
        epilogue."""
        from ..search.executor import (Candidate, ShardQueryResult,
                                       _finish_search, _host_sort_values)

        S = len(shard_segs)
        doc_base = np.asarray(stacked.doc_base)
        seg_bases = [np.cumsum([0] + ndocs[:-1])
                     for ndocs in stacked.seg_ndocs]
        for bi, (qi, lt, sort_specs, window, _const, aggs, _fk, qboost,
                 _msm_eff) in enumerate(items):
            gdocs = gdocs_b[bi]
            gvals = gvals_b[bi]
            total = int(totals_b[bi])
            results = [ShardQueryResult(shard=i,
                                        segments=list(shard_segs[i]))
                       for i in range(S)]
            finite = np.isfinite(gvals)
            results[0].total = total
            results[0].max_score = (float(gvals[finite].max())
                                    if total > 0 and finite.any()
                                    else -np.inf)
            for j in range(len(gdocs)):
                if not np.isfinite(gvals[j]) or gdocs[j] < 0:
                    continue
                si = int(np.searchsorted(doc_base, gdocs[j], "right") - 1)
                in_shard = int(gdocs[j] - doc_base[si])
                seg_ord = int(np.searchsorted(seg_bases[si], in_shard,
                                              "right") - 1)
                local = in_shard - int(seg_bases[si][seg_ord])
                seg = shard_segs[si][seg_ord]
                if local >= seg.ndocs:
                    continue
                sc = float(gvals[j])
                sort_vals, raw_vals = _host_sort_values(sort_specs, seg,
                                                        local, sc)
                results[si].candidates.append(
                    Candidate(si, seg_ord, local, sc, sort_vals, raw_vals))
            if attach_aggs is not None:
                attach_aggs(results, bi, aggs)
            for r in results:
                r.took_ms = (time.monotonic() - t0) * 1000.0
            # fetch-stage counters: taken on whichever thread completes
            # the request (completion worker vs direct callers), so the
            # tallies need the stats lock
            with self._stats_lock:
                self.dispatched += 1
                if phrase:
                    self.phrase_dispatched += 1
                if _fk is not None:
                    self.filtered_dispatched += 1
                if any(an.kind == "terms" for an in aggs):
                    self.terms_agg_dispatched += 1
            METRICS.counter("mesh.dispatched").inc()
            METRICS.histogram("mesh.dispatch").record(
                (time.monotonic() - t0) * 1000.0)
            body = dict(bodies[qi])
            body["_index_name"] = name
            out[qi] = _finish_search(searchers, results, body, stats,
                                     name, t0, [] if phrase else aggs)

    def _launch_phrase_group(self, name, svc, bodies, out, shard_segs,
                             stats, searchers, field, n_terms, k1, b_eff,
                             k_class, items):
        """LAUNCH stage of one match_phrase program batch: shard-local
        positional pair-join + BM25 pseudo-term scoring + all_gather merge
        (spmd.build_distributed_phrase). Returns a fetch closure over the
        unfetched device arrays, or None when the group declined."""
        import jax

        t0 = time.monotonic()
        stacked = self._stacked_for(name, svc, field, shard_segs)
        if stacked is None:
            self._fall("no_stacked_index", len(items))
            return
        S = len(shard_segs)
        mesh = self._mesh_for(S)
        if mesh is None:
            self._fall("no_mesh", len(items))
            return
        pairs = self._pairs_for(name, svc, field, shard_segs, stacked,
                                mesh)
        if pairs is None:         # field has no positional postings
            self._fall("no_positions", len(items))
            return
        fpair = items[0][6]
        K = min(k_class, stacked.ndocs_pad)
        keep = []
        for it in items:
            if it[3] > K:
                self._fall("deep_window")
                continue
            keep.append(it)
        items = keep
        if not items:
            return
        ctx = stats[0]
        QB = next_pow2(len(items), floor=1)
        rows = np.full((S, QB, n_terms), -1, np.int32)
        weights = np.zeros(QB, np.float32)
        slops = np.zeros(QB, np.float32)
        avgdl = np.full(QB, max(float(ctx.avgdl(field)), 1e-9), np.float32)
        max_pairs = 1
        for bi, (qi, lt, sort_specs, window, _const, _aggs, _fk, qboost,
                 _msm_eff) in enumerate(items):
            weights[bi] = float(lt.weight) * float(qboost)
            slops[bi] = float(lt.slop)
            for si in range(S):
                for ti, t in enumerate(lt.terms):
                    r = stacked.row(si, t)
                    rows[si, bi, ti] = r
                    max_pairs = max(max_pairs, pairs.row_size(si, r))
        bucket = next_pow2(max_pairs, floor=64)
        if bucket > MAX_PHRASE_BUCKET:
            self._fall("phrase_bucket_cap", len(items))
            return
        filtered = fpair is not None
        fmask = (self._dev_mask_for(fpair[0], fpair[1], shard_segs,
                                    stacked.ndocs_pad, mesh)
                 if filtered else None)
        fn = self._phrase_program_for(mesh, bucket, stacked.ndocs_pad, K,
                                      n_terms, k1, b_eff, filtered)
        args = (stacked.tree(), pairs.tree(), rows, weights, slops,
                avgdl) + ((fmask,) if filtered else ())
        self.launches += 1
        METRICS.counter("mesh.launches").inc()
        _pending = fn(*args)            # invocation NOW, sync deferred

        def _fetch_group():
            gdocs_b, gvals_b, totals_b = jax.device_get(_pending)
            self._emit_mesh_results(name, bodies, out, shard_segs, stats,
                                    searchers, stacked, items, gdocs_b,
                                    gvals_b, totals_b, t0, phrase=True)

        return _fetch_group

    # agg kinds that today ALWAYS host-loop (VERDICT weak #4: the honest
    # remaining-host-loop list must carry per-shape counters so a
    # mesh-share measurement can't silently flatter). A declined body
    # carrying one of these is attributed `agg_<kind>`, not the flat
    # `query_shape` bucket.
    _HOST_LOOP_AGGS = frozenset((
        "nested", "reverse_nested", "global", "top_hits",
        "scripted_metric", "matrix_stats", "ip_range",
        "auto_date_histogram", "sampler", "diversified_sampler",
        "multi_terms", "variable_width_histogram", "children", "parent",
        "geo_distance"))

    # body keys that statically force the host loop (checked first in
    # `_eligible`); attributing them beats lumping them into query_shape.
    # The truthiness split mirrors _eligible EXACTLY — a falsy-present
    # key (e.g. `"profile": false`) did NOT cause the decline and must
    # not be blamed for it
    _HOST_LOOP_KEYS_TRUTHY = ("knn", "rescore", "profile", "collapse",
                              "suggest", "terminate_after")
    _HOST_LOOP_KEYS_PRESENT = ("min_score", "search_after", "timeout")

    def _host_loop_shape(self, body: dict, agg_nodes) -> str:
        """Finer decline attribution for `_eligible`-rejected bodies:
        which statically-host-loop feature sent this search to the host
        loop. Falls back to the generic `query_shape` when the decline
        came from the query tree itself."""

        def walk(nodes):
            for an in nodes or []:
                if an.kind in self._HOST_LOOP_AGGS:
                    return f"agg_{an.kind}"
                got = walk(an.subs)
                if got:
                    return got
            return None

        hit = walk(agg_nodes)
        if hit:
            return hit
        for k in self._HOST_LOOP_KEYS_TRUTHY:
            if body.get(k):
                return f"body_{k}"
        # vector/hybrid retrieval families decline by QUERY kind, not a
        # body key: a pure-knn / neural_sparse / hybrid query must show
        # up attributed in fallback_shapes (ISSUE 15 satellite — a
        # vector flood the remediator can shed needs a name), never as
        # the flat query_shape bucket
        q = body.get("query")
        if isinstance(q, dict) and len(q) == 1:
            qk = next(iter(q))
            if qk in ("knn", "hybrid", "neural_sparse"):
                return f"query_{qk}"
        for k in self._HOST_LOOP_KEYS_PRESENT:
            if body.get(k) is not None:
                return f"body_{k}"
        for an in (agg_nodes or []):
            if an.pipelines:
                return "agg_pipeline"
            for s in an.subs:
                if s.subs or s.pipelines or s.kind not in _MESH_METRICS:
                    return "agg_deep_subagg"
        return "query_shape"

    def _eligible(self, lroot, sort_specs, agg_nodes, named_nodes, body,
                  window: int) -> Optional[tuple]:
        """Mesh-servable shapes: a single term group (scoring OR filter
        mode), optionally wrapped in a bool with mask-computable
        filter/must_not clauses, plain relevance order, metric or keyword
        `terms` aggregations. Returns (lt, filter_nodes, must_not_nodes,
        bool_boost) or None (-> host loop)."""
        from ..search import compiler as C
        from ..search.fastpath import MAX_T
        from ..ops import scoring as ops

        if body.get("knn") or body.get("rescore") or body.get("min_score") \
                is not None or body.get("profile") or body.get("collapse") \
                or body.get("suggest") or body.get("search_after") is not None \
                or body.get("explain") == "device_plan" \
                or body.get("terminate_after"):
            # terminate_after is a per-segment collection budget — only
            # the host shard loop can stop between segment programs
            return None
        if body.get("timeout") is not None:
            # a LIVE deadline budget needs the deadline-aware host loop
            # too (a mesh launch cannot stop mid-program); the reference
            # no-timeout sentinel (-1) parses to no budget and stays
            # mesh-eligible
            from ..utils.deadline import parse_timeout_s
            try:
                if parse_timeout_s(body.get("timeout")) is not None:
                    return None
            except ValueError:
                return None          # junk -> host loop raises the 400
        if named_nodes:
            return None
        # metric aggs reduce over the mesh (psum/pmin/pmax); keyword terms
        # aggs as an exact device bincount; anything else -> host loop.
        # r5: bucket parents may carry plain {field} METRIC sub-aggs —
        # per-bucket moments scatter on device (pair/range metrics
        # programs) exactly like the reference's nested collectors
        def _subs_ok(an):
            return all(s.kind in _MESH_METRICS
                       and set(s.body) == {"field"}
                       and not s.subs and not s.pipelines
                       for s in an.subs) and not an.pipelines

        for an in (agg_nodes or []):
            if an.subs and not (
                    an.kind in ("terms", "rare_terms", "histogram",
                                "date_histogram", "range", "date_range",
                                "filter", "missing")
                    and _subs_ok(an)):
                return None
            # r5: single `filter` wrapper — the clause becomes a device
            # mask (query-filter machinery); metric subs compose their
            # presence with it. `missing` is the same wrapper with a
            # negated exists mask
            if an.kind == "filter":
                continue
            if an.kind == "missing" and set(an.body) == {"field"}:
                continue
            if an.kind in _MESH_METRICS and set(an.body) == {"field"} \
                    and not an.subs:
                continue
            # r5: cardinality as shard-local HLL registers + pmax (the
            # registers ARE the mergeable form, bit-identical to host)
            if an.kind == "cardinality" and set(an.body) == {"field"}:
                continue
            # r5: sketch metrics — DDSketch histograms merge by addition
            # (psum), weighted_avg by summed moments
            if an.kind == "percentiles" and set(an.body) <= \
                    {"field", "percents", "keyed"}:
                continue
            if an.kind == "percentile_ranks" and set(an.body) <= \
                    {"field", "values", "keyed"}:
                continue
            if an.kind == "median_absolute_deviation" \
                    and set(an.body) == {"field"}:
                continue
            if an.kind == "weighted_avg" \
                    and set(an.body) <= {"value", "weight"} \
                    and set(an.body.get("value") or {}) == {"field"} \
                    and set(an.body.get("weight") or {}) == {"field"}:
                continue
            # r5: geo_bounds/geo_centroid — masked lat/lon extremes and
            # centroid moments, pmax/pmin/psum over the shard axis
            if an.kind in ("geo_bounds", "geo_centroid") \
                    and set(an.body) == {"field"}:
                continue
            # r5: significant_terms — foreground counts are the exact
            # terms bincount; background stats are static per field
            if an.kind == "significant_terms" and set(an.body) <= \
                    {"field", "size", "min_doc_count", "shard_size"} \
                    and not an.subs:
                continue
            # r5: `filters` agg — each named maskable clause becomes a
            # per-shard device mask; counts via the metric program
            if an.kind == "filters" and set(an.body) <= {"filters"} \
                    and 1 <= len(an.body.get("filters") or ()) \
                    <= MAX_MESH_RANGES and not an.subs:
                continue
            # r5: adjacency_matrix — singles + pairwise AND masks through
            # the same filter-mask machinery as the `filters` agg
            if an.kind == "adjacency_matrix" and set(an.body) <= \
                    {"filters", "separator"} \
                    and 1 <= len(an.body.get("filters") or {}) \
                    <= MAX_MESH_ADJ_FILTERS and not an.subs:
                continue
            # r5: rare_terms rides the same exact bincount (our host path
            # is exact, not bloom-approximated, so parity is exact too)
            if an.kind == "rare_terms" and set(an.body) <= \
                    {"field", "max_doc_count"}:
                continue
            # r5: geo grids — host-precomputed per-doc cell ordinals
            # through the same device bincount as histograms
            if an.kind in ("geohash_grid", "geotile_grid") \
                    and set(an.body) <= {"field", "precision", "size"} \
                    and not an.subs:
                continue
            if an.kind == "terms" and set(an.body) <= \
                    {"field", "size", "min_doc_count", "order"}:
                order = an.body.get("order", {"_count": "desc"})
                if isinstance(order, dict) and len(order) == 1 and \
                        next(iter(order)) in ("_count", "_key"):
                    continue
            # r5: histogram family as a device bincount over host-built
            # global bin ids; `range` as per-range masked sums (ranges
            # may overlap). Calendar date intervals -> host loop.
            if an.kind == "histogram" and set(an.body) <= \
                    {"field", "interval", "offset", "min_doc_count"} \
                    and float(an.body.get("interval", 0)) > 0:
                continue
            if an.kind == "date_histogram" \
                    and not an.body.get("calendar_interval") \
                    and set(an.body) <= {"field", "fixed_interval",
                                         "interval", "offset",
                                         "min_doc_count"}:
                continue
            if an.kind in ("range", "date_range") and set(an.body) <= \
                    {"field", "ranges", "keyed", "format"} \
                    and 1 <= len(an.body.get("ranges") or []) \
                    <= MAX_MESH_RANGES:
                continue
            # r5: composite over single-valued keyword terms sources —
            # per-doc combined key == the multi_terms combined ordinal,
            # so it rides the same stacker + bincount; paging (after/
            # size/order) happens in the shared finalize
            if an.kind == "composite" and set(an.body) <= \
                    {"sources", "size", "after"} \
                    and an.body.get("sources") and not an.subs:
                ok = True
                for src in an.body["sources"]:
                    if len(src) != 1:
                        ok = False
                        break
                    (nm, scfg), = src.items()
                    if set(scfg) != {"terms"} \
                            or "field" not in scfg["terms"] \
                            or set(scfg["terms"]) - {"field", "order"}:
                        ok = False
                        break
                if ok:
                    continue
                return None
            # r5: multi_terms — per-doc combined ordinals through the
            # same device bincount as the geo grids
            if an.kind == "multi_terms" and set(an.body) <= \
                    {"terms", "size", "min_doc_count", "order"} \
                    and len(an.body.get("terms") or []) >= 2 \
                    and all(set(src) == {"field"}
                            for src in an.body["terms"]) \
                    and not an.subs:
                order = an.body.get("order", {"_count": "desc"})
                if isinstance(order, dict) and len(order) == 1 and \
                        next(iter(order)) in ("_count", "_key"):
                    continue
                return None
            return None
        if window > MAX_WINDOW or (window < 1 and not agg_nodes):
            return None
        if sort_specs and not (len(sort_specs) == 1
                               and sort_specs[0]["field"] == "_score"
                               and sort_specs[0].get("order", "desc")
                               == "desc"):
            return None

        # unwrap a bool: one scoring clause + maskable filters/must_nots.
        # msm_eff: the program-level minimum term matches — 0 when the bool
        # makes its single should OPTIONAL (filter-context bool, compiler
        # msm=0: docs matching only the filters still hit, scoring 0.0)
        fnodes: list = []
        notnodes: list = []
        qboost = 1.0
        msm_eff = None           # None -> use the term group's own msm
        lt = lroot
        if isinstance(lroot, C.LBool):
            if lroot.shoulds:
                if lroot.musts or len(lroot.shoulds) != 1 or lroot.msm > 1:
                    return None
                lt = lroot.shoulds[0]
                if lroot.msm == 0:
                    # optional should: only sound with real filters (the
                    # match set is the filter set) and a scoring group
                    # (constant-score cscore would stamp non-matching docs)
                    if not lroot.filters or getattr(lt, "mode", None) \
                            != "score":
                        return None
                    msm_eff = 0.0
            elif len(lroot.musts) == 1:
                lt = lroot.musts[0]
            else:
                return None
            fnodes = list(lroot.filters)
            notnodes = list(lroot.must_nots)
            qboost = float(lroot.boost or 1.0)
            if not all(self._maskable(n) for n in fnodes + notnodes):
                return None
        if isinstance(lt, C.LPhrase):
            # plain/filtered match_phrase on the mesh: the positional
            # pair-join program (spmd.build_distributed_phrase). Span
            # family (ordered/gap_cost), prefix expansion, and agg
            # combinations take the host loop; a bool-wrapped phrase must
            # be the REQUIRED clause (msm_eff None).
            if agg_nodes or msm_eff is not None:
                return None
            if lt.prefix_last or lt.ordered or lt.gap_cost:
                return None
            if lt.sim is None or lt.sim.sim_id != ops.SIM_BM25:
                return None
            if not 2 <= len(lt.terms) <= MAX_PHRASE_T:
                return None
            return (lt, fnodes, notnodes, qboost, msm_eff)
        if not isinstance(lt, C.LTerms):
            return None
        if lt.mode not in ("score", "filter"):
            return None
        if lt.mode == "score" and (lt.sim is None
                                   or lt.sim.sim_id != ops.SIM_BM25):
            return None
        nt = len(lt.terms)
        if nt < 1 or next_pow2(nt, floor=1) > MAX_T:
            return None
        if getattr(lt, "raw_boosts", None) is None:
            return None
        if lt.aux is not None and np.any(np.asarray(lt.aux)[:nt] != 0.0):
            return None
        return (lt, fnodes, notnodes, qboost, msm_eff)

    def _maskable(self, node) -> bool:
        """Filter-context clauses the mesh serves via cached dense masks
        (compiler filter-mask cache) — the common guardrail kinds. Unknown
        kinds decline to the host loop, never guess."""
        from ..search import compiler as C

        if isinstance(node, (C.LRange, C.LExists, C.LMatchAll,
                             C.LMatchNone, C.LIds, C.LExpandTerms)):
            return True
        if isinstance(node, C.LTerms):
            return True
        if isinstance(node, C.LConstScore):
            return self._maskable(node.child)
        if isinstance(node, C.LBool):
            return all(self._maskable(c) for c in
                       node.musts + node.shoulds + node.must_nots
                       + node.filters)
        return False

    def stats(self) -> dict:
        return {"devices": len(self.devices), "dispatched": self.dispatched,
                "launches": self.launches,
                "fallbacks": self.fallbacks,
                "fallback_shapes": dict(self.fallback_shapes),
                "filtered_dispatched": self.filtered_dispatched,
                "terms_agg_dispatched": self.terms_agg_dispatched,
                "phrase_dispatched": self.phrase_dispatched,
                "stacked_indices": len(self._stacked)}
