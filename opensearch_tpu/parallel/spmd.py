"""SPMD distributed search over a `jax.sharding.Mesh`.

The TPU-native replacement for the reference's coordinator/transport fan-out
(`action/search/TransportSearchAction` + `SearchPhaseController` over
netty/NCCL-style point-to-point): shards live as the leading axis of stacked
device arrays, `shard_map` runs the per-shard query program, and the
coordinator reduce becomes XLA collectives over ICI:

- `psum` over the shard axis aggregates collection statistics (global df,
  ndocs, avgdl) — the device-side analog of the reference DFS_QUERY_THEN_FETCH
  phase (`search/dfs/DfsSearchResult.java`), so BM25 idf is identical no
  matter how documents are partitioned.
- `all_gather` over the shard axis merges per-shard top-k into a global top-k
  — the reduce in `SearchPhaseController#sortDocs`, minus the host round-trip.
- a second mesh axis (`replica`) data-parallelizes a *batch of queries*, the
  throughput scaling the reference gets from replica fan-out.
- `score_term_sharded` partitions the postings of huge terms across devices
  and `psum`s partial score vectors — the sequence/context-parallel analog
  (the reduction dimension — postings — is sharded, like ring attention
  shards the KV sequence).

Mesh axes are ordered (replica, shard): put `shard` innermost so the hot
all_gather/psum ride ICI within a host; `replica` can span DCN.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..index.segment import Segment, next_pow2

INT32_SENTINEL = np.int32(2**31 - 1)


def _shard_map(f, mesh, in_specs, out_specs, check_vma=False):
    """`jax.shard_map` across jax versions: older releases only ship
    `jax.experimental.shard_map` whose replication check is spelled
    `check_rep` instead of `check_vma`."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as esm
    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def make_mesh(n_replica: int = 1, n_shard: Optional[int] = None,
              devices: Optional[list] = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if n_shard is None:
        n_shard = len(devices) // n_replica
    dev = np.asarray(devices[: n_replica * n_shard]).reshape(n_replica, n_shard)
    return Mesh(dev, axis_names=("replica", "shard"))


@dataclass
class StackedShardIndex:
    """N doc-shards of one field's postings + norms, padded to common shapes
    and stacked on a leading axis sharded over the mesh `shard` axis. This is
    the device-resident form the SPMD query program consumes.

    A shard may hold SEVERAL segments: their postings concatenate into one
    per-shard CSR on the host (term dict = union, doc ids offset by the
    segment's base within the shard) — the mesh analog of the reference's
    per-shard multi-leaf reader, built once per index generation and cached
    by the MeshSearchService."""

    field: str
    starts: jnp.ndarray     # i32[S, R_pad]
    doc_ids: jnp.ndarray    # i32[S, P_pad]
    tfs: jnp.ndarray        # f32[S, P_pad]
    dl: jnp.ndarray         # f32[S, D_pad]
    live: jnp.ndarray       # f32[S, D_pad]
    doc_base: jnp.ndarray   # i32[S] global doc id offset per shard
    doc_count: jnp.ndarray  # f32[S] maxDoc per shard (deleted INCLUDED)
    sum_dl: jnp.ndarray     # f32[S]
    field_dc: jnp.ndarray   # f32[S] docs WITH this field (text_stats doc_count)
    n_shards: int
    ndocs_pad: int
    # host-side query-resolution metadata (term -> per-shard CSR row, and
    # row sizes for DMA bucket sizing)
    host_terms: Optional[List[Dict[str, int]]] = None
    host_starts: Optional[List[np.ndarray]] = None
    # (shard, segment) decomposition for mapping global ids back to
    # (segment, local doc) at fetch: per shard, the ndocs of each segment
    seg_ndocs: Optional[List[List[int]]] = None

    def row(self, shard: int, term: str) -> int:
        return self.host_terms[shard].get(term, -1)

    def row_size(self, shard: int, row: int) -> int:
        st = self.host_starts[shard]
        return int(st[row + 1] - st[row]) if 0 <= row < len(st) - 1 else 0

    @classmethod
    def build(cls, shards, field: str,
              mesh: Optional[Mesh] = None) -> "StackedShardIndex":
        """`shards`: List[Segment] (one per shard) or List[List[Segment]]."""
        shard_segs: List[List[Segment]] = [
            list(s) if isinstance(s, (list, tuple)) else [s] for s in shards]
        S = len(shard_segs)
        merged = [_concat_shard(segs, field) for segs in shard_segs]
        r_pad = max(next_pow2(len(m["starts"]) + 1) for m in merged)
        p_pad = max(next_pow2(max(len(m["doc_ids"]), 1)) for m in merged)
        d_pad = next_pow2(max(max(m["ndocs"] for m in merged), 1))
        starts = np.zeros((S, r_pad), np.int32)
        doc_ids = np.full((S, p_pad), INT32_SENTINEL, np.int32)
        tfs = np.zeros((S, p_pad), np.float32)
        dl = np.zeros((S, d_pad), np.float32)
        live = np.zeros((S, d_pad), np.float32)
        doc_base = np.zeros(S, np.int32)
        doc_count = np.zeros(S, np.float32)
        sum_dl = np.zeros(S, np.float32)
        field_dc = np.zeros(S, np.float32)
        host_terms, host_starts, seg_ndocs = [], [], []
        base = 0
        for i, m in enumerate(merged):
            n = len(m["starts"]) - 1
            starts[i, : n + 1] = m["starts"]
            starts[i, n + 1:] = m["starts"][-1]
            np_ = len(m["doc_ids"])
            doc_ids[i, :np_] = m["doc_ids"]
            tfs[i, :np_] = m["tfs"]
            dl[i, : m["ndocs"]] = m["dl"]
            live[i, : m["ndocs"]] = m["live"]
            doc_base[i] = base
            base += m["ndocs"]
            # idf N follows host ShardContext.num_docs = Lucene maxDoc
            # (deleted docs INCLUDED — the host rewrite and every scorer
            # use it; psumming live counts instead skewed idf on indexes
            # with deletes, hidden while parity tests compared mesh to
            # its own mesh)
            doc_count[i] = float(m["ndocs"])
            sum_dl[i] = m["sum_dl"]
            field_dc[i] = m["field_dc"]
            host_terms.append(m["terms"])
            host_starts.append(m["starts"])
            seg_ndocs.append([s.ndocs for s in shard_segs[i]])
        arrays = dict(starts=starts, doc_ids=doc_ids, tfs=tfs, dl=dl, live=live,
                      doc_base=doc_base, doc_count=doc_count, sum_dl=sum_dl,
                      field_dc=field_dc)
        if mesh is not None:
            sharding = NamedSharding(mesh, P("shard"))
            # MeshSearchService._stacked_for registers the built
            # index with the HBM ledger
            arrays = {k: jax.device_put(v, sharding)  # oslint: disable=OSL506
                      for k, v in arrays.items()}
        else:
            arrays = {k: jnp.asarray(v) for k, v in arrays.items()}
        return cls(field=field, n_shards=S, ndocs_pad=d_pad,
                   host_terms=host_terms, host_starts=host_starts,
                   seg_ndocs=seg_ndocs, **arrays)

    def tree(self) -> dict:
        return {"starts": self.starts, "doc_ids": self.doc_ids, "tfs": self.tfs,
                "dl": self.dl, "live": self.live, "doc_base": self.doc_base,
                "doc_count": self.doc_count, "sum_dl": self.sum_dl,
                "field_dc": self.field_dc}


def _concat_shard(segs: List[Segment], field: str) -> dict:
    """One shard's segments -> a single host CSR view: union term dict,
    per-term postings concatenated segment-by-segment with doc offsets.
    An empty shard yields a zero-doc entry (all terms absent)."""
    if not segs:
        return {"terms": {}, "starts": np.zeros(1, np.int64),
                "doc_ids": np.zeros(0, np.int32),
                "tfs": np.zeros(0, np.float32),
                "dl": np.zeros(0, np.float32),
                "live": np.zeros(0, np.float32), "ndocs": 0,
                "sum_dl": 0.0, "field_dc": 0.0}
    ndocs = sum(s.ndocs for s in segs)
    live = np.zeros(ndocs, np.float32)
    dl = np.zeros(ndocs, np.float32)
    off = 0
    sum_dl = 0.0
    field_dc = 0.0
    for s in segs:
        live[off: off + s.ndocs] = s.live.astype(np.float32)
        sdl = s.doc_lens.get(field)
        if sdl is not None:
            dl[off: off + s.ndocs] = sdl
        st = s.text_stats.get(field)
        if st:
            sum_dl += st.sum_dl
            field_dc += st.doc_count
        off += s.ndocs
    pbs = [s.postings.get(field) for s in segs]
    if len(segs) == 1 and pbs[0] is not None:
        pb = pbs[0]
        return {"terms": pb.terms, "starts": pb.starts.astype(np.int64),
                "doc_ids": pb.doc_ids, "tfs": pb.tfs, "dl": dl, "live": live,
                "ndocs": ndocs, "sum_dl": sum_dl,
                "field_dc": field_dc}
    vocab: Dict[str, int] = {}
    for pb in pbs:
        if pb is None:
            continue
        for t in pb.vocab:
            vocab.setdefault(t, len(vocab))
    nterms = len(vocab)
    # vectorized merge: per-posting (target row, offset doc) keys, one
    # stable argsort — no per-term Python loop (a vocabulary can be 10^5+)
    trows_parts, docs_parts, tfs_parts = [], [], []
    off = 0
    for s, pb in zip(segs, pbs):
        if pb is not None and pb.size:
            rows = np.array([vocab[t] for t in pb.vocab], np.int64)
            trows_parts.append(np.repeat(rows, np.diff(pb.starts)))
            docs_parts.append(pb.doc_ids.astype(np.int64) + off)
            tfs_parts.append(pb.tfs)
        off += s.ndocs
    if trows_parts:
        trows = np.concatenate(trows_parts)
        docs_all = np.concatenate(docs_parts)
        tfs_all = np.concatenate(tfs_parts)
        order = np.lexsort((docs_all, trows))
        doc_ids = docs_all[order].astype(np.int32)
        tfs = tfs_all[order]
        lens = np.bincount(trows, minlength=nterms)
    else:
        doc_ids = np.zeros(0, np.int32)
        tfs = np.zeros(0, np.float32)
        lens = np.zeros(nterms, np.int64)
    starts = np.zeros(nterms + 1, np.int64)
    np.cumsum(lens, out=starts[1:])
    return {"terms": vocab, "starts": starts, "doc_ids": doc_ids, "tfs": tfs,
            "dl": dl, "live": live, "ndocs": ndocs,
            "sum_dl": sum_dl, "field_dc": field_dc}


def _local_gather(starts, doc_ids, tfs, rows, bucket: int):
    """Same flat CSR gather as ops.scoring.gather_postings, shard-local."""
    nrows_pad = starts.shape[0]
    rows = jnp.where(rows < 0, nrows_pad - 2, rows)
    row_start = starts[rows]
    lens = starts[rows + 1] - row_start
    cum = jnp.cumsum(lens)
    total = cum[-1]
    i = jnp.arange(bucket, dtype=jnp.int32)
    t_idx = jnp.minimum(jnp.searchsorted(cum, i, side="right").astype(jnp.int32),
                        rows.shape[0] - 1)
    prev = jnp.where(t_idx > 0, cum[jnp.maximum(t_idx - 1, 0)], 0)
    src = jnp.clip(row_start[t_idx] + (i - prev), 0, doc_ids.shape[0] - 1)
    valid = i < total
    docs = jnp.where(valid, doc_ids[src], INT32_SENTINEL)
    tf = jnp.where(valid, tfs[src], 0.0)
    return docs, tf, t_idx, valid


def _score_one_query(starts, doc_ids, tfs, dl, live, rows, boosts, msm,
                     cscore, n_global, df_global, avgdl, bucket: int,
                     ndocs_pad: int, k1: float, b: float, fmask=None):
    """Shard-local BM25 scoring of one query with *global* statistics.
    `cscore > 0` switches the query to constant-score semantics (filter
    context / `terms` queries): every doc matching >= msm terms scores
    exactly `cscore`, so top-k tie-breaks by doc id like the host path.
    `fmask` (f32[ndocs_pad] or None) is a pre-combined filter-context match
    mask (bool filters + must_nots): docs outside it can't hit."""
    idf = jnp.log1p((n_global - df_global + 0.5) / (df_global + 0.5))
    w = jnp.where(df_global > 0, boosts * idf, 0.0)
    docs, tf, t_idx, valid = _local_gather(starts, doc_ids, tfs, rows, bucket)
    dsafe = jnp.minimum(docs, ndocs_pad - 1)
    # avgdl is pre-guarded > 0 by the caller (normless fields -> 1.0, matching
    # the host StatsContext.avgdl default); keep a floor so 0/0 can never
    # NaN-poison the whole shard's scores (silent-zero-hits bug, round 3).
    k = k1 * (1.0 - b + b * dl[dsafe] / jnp.maximum(avgdl, 1e-9))
    contrib = jnp.where(valid, w[t_idx] * tf / (tf + k), 0.0)
    scores = jnp.zeros(ndocs_pad, jnp.float32).at[docs].add(contrib, mode="drop")
    counts = jnp.zeros(ndocs_pad, jnp.float32).at[docs].add(
        jnp.where(valid & (tf > 0), 1.0, 0.0), mode="drop")
    ok = (counts >= msm) & (live > 0)
    if fmask is not None:
        ok = ok & (fmask > 0)
    scores = jnp.where(cscore > 0.0, cscore, scores)
    return jnp.where(ok, scores, -jnp.inf)


def _global_dfs_stats(tree, rows):
    """Device-side DFS phase shared by every distributed program: psum the
    collection statistics over the `shard` axis. Returns
    (df_global [QBl,T], n_global, avgdl). avgdl follows the host
    StatsContext semantics: mean doc length over docs that HAVE the field,
    1.0 when none (normless fields — 0/0 was the r3 NaN poison)."""
    starts = tree["starts"][0]
    nrows_pad = starts.shape[0]
    safe_rows = jnp.where(rows < 0, nrows_pad - 2, rows)
    local_df = (starts[safe_rows + 1] - starts[safe_rows]).astype(jnp.float32)
    df_global = jax.lax.psum(local_df, "shard")
    n_global = jax.lax.psum(tree["doc_count"][0], "shard")
    sum_dl_g = jax.lax.psum(tree["sum_dl"][0], "shard")
    fdc_g = jax.lax.psum(tree["field_dc"][0], "shard")
    avgdl = jnp.where(fdc_g > 0, sum_dl_g / jnp.maximum(fdc_g, 1.0), 1.0)
    return df_global, n_global, avgdl


def build_distributed_search(mesh: Mesh, bucket: int, ndocs_pad: int, k: int,
                             k1: float = 1.2, b: float = 0.75,
                             filtered: bool = False):
    """Returns a jitted SPMD function:
        (index_tree, rows [S,QB,T], boosts [QB,T], msm [QB], cscore [QB]
         [, fmask [S, ndocs_pad]]) ->
        (global_doc_ids [QB,k], scores [QB,k], total_hits [QB])
    Queries are sharded over `replica`, docs over `shard`; `rows` carries the
    per-shard term-dict resolution so it is sharded over BOTH axes. `cscore`
    (optional; zeros = BM25) switches a query to constant-score semantics.
    `filtered=True` adds a per-shard filter-context mask argument (the
    device-cached AND of a bool query's filter/must_not clauses): the mesh
    analog of the reference's filtered BulkScorer
    (`search/query/QueryPhase.java` with a filter bitset) — one mask serves
    every query in the batch that shares the filter combo."""

    def per_device(tree, rows, boosts, msm, cscore, fmask=None):
        # leading stacked-shard axis is size-1 inside the shard_map block
        rows = rows[0]
        starts = tree["starts"][0]
        doc_ids = tree["doc_ids"][0]
        tfs = tree["tfs"][0]
        dl = tree["dl"][0]
        live = tree["live"][0]
        doc_base = tree["doc_base"][0]
        fm = fmask[0] if fmask is not None else None

        # --- DFS phase on device: global collection stats via psum over ICI ---
        df_global, n_global, avgdl = _global_dfs_stats(tree, rows)

        # --- QUERY phase: vmap over the local query batch ---
        scores = jax.vmap(
            lambda r, w, m, cs, dfg: _score_one_query(
                starts, doc_ids, tfs, dl, live, r, w, m, cs, n_global, dfg,
                avgdl, bucket, ndocs_pad, k1, b, fm)
        )(rows, boosts, msm, cscore, df_global)                       # [QBl, D]

        totals_local = jnp.sum(scores > -jnp.inf, axis=1)
        totals = jax.lax.psum(totals_local, "shard")

        kk = min(k, ndocs_pad)
        vals, idx = jax.lax.top_k(scores, kk)                         # [QBl, kk]
        gids = jnp.where(vals > -jnp.inf, idx + doc_base, -1)

        # --- coordinator merge on device: all_gather the per-shard top-ks.
        # The UNION of every shard's top-kk goes back to the host — the
        # same candidate pool the host shard loop builds — so the final
        # selection (host reduce, tie-break by (-score, doc id)) is
        # IDENTICAL to the host path even on deep score ties. A device
        # top_k over the flattened gather would instead tie-break by flat
        # position (shard-major), silently reordering tied keyword hits.
        all_vals = jax.lax.all_gather(vals, "shard", axis=1)          # [QBl, S, kk]
        all_gids = jax.lax.all_gather(gids, "shard", axis=1)
        S = all_vals.shape[1]
        gvals = all_vals.reshape(all_vals.shape[0], S * kk)
        gdocs = all_gids.reshape(all_gids.shape[0], S * kk)
        return gdocs, gvals, totals

    shard_map = _shard_map

    tree_spec = {k_: P("shard") for k_ in
                 ("starts", "doc_ids", "tfs", "dl", "live", "doc_base",
                  "doc_count", "sum_dl", "field_dc")}
    in_specs = (tree_spec, P("shard", "replica"), P("replica"),
                P("replica"), P("replica"))
    if filtered:
        in_specs = in_specs + (P("shard"),)
    fn = shard_map(per_device, mesh=mesh, in_specs=in_specs,
                   out_specs=(P("replica"), P("replica"), P("replica")),
                   check_vma=False)
    jitted = jax.jit(fn)

    def call(tree, rows, boosts, msm, cscore=None, fmask=None):
        if cscore is None:
            cscore = jnp.zeros_like(jnp.asarray(msm))
        if filtered:
            return jitted(tree, rows, boosts, msm, cscore, fmask)
        return jitted(tree, rows, boosts, msm, cscore)

    return call


def build_distributed_metrics(mesh: Mesh, bucket: int, ndocs_pad: int,
                              k1: float = 1.2, b: float = 0.75,
                              filtered: bool = False):
    """Metric aggregations over the mesh: re-evaluate each query's match
    mask shard-locally (same scoring program shape), then psum/pmin/pmax
    the masked column moments over the `shard` axis — the device-side
    analog of the reference's per-shard metric collectors + coordinator
    InternalAggregation#reduce. Returns a callable:
        (tree, rows [S,QB,T], boosts [QB,T], msm [QB], cscore [QB],
         col [S,D_pad], present [S,D_pad] [, fmask [S,D_pad]]) ->
        (i32[QB] counts, f32[QB, 4] = (sum, min, max, sumsq)),
        already global. The count plane is int32 (same rule as the
        terms/pair programs): f32 sums stop counting exactly at 2^24
        matching docs, and filters/adjacency doc_counts ride this plane."""

    def per_device(tree, rows, boosts, msm, cscore, col, present,
                   fmask=None):
        rows = rows[0]
        starts = tree["starts"][0]
        doc_ids = tree["doc_ids"][0]
        tfs = tree["tfs"][0]
        dl = tree["dl"][0]
        live = tree["live"][0]
        colv = col[0]
        pres = present[0]
        fm = fmask[0] if fmask is not None else None

        df_global, n_global, avgdl = _global_dfs_stats(tree, rows)

        def one(r, w, m, cs, dfg):
            scores = _score_one_query(starts, doc_ids, tfs, dl, live, r, w,
                                      m, cs, n_global, dfg, avgdl, bucket,
                                      ndocs_pad, k1, b, fm)
            ok = (scores > -jnp.inf) & (pres > 0)
            cnt = jnp.sum(ok.astype(jnp.int32))
            s = jnp.sum(jnp.where(ok, colv, 0.0))
            ssq = jnp.sum(jnp.where(ok, colv * colv, 0.0))
            mn = jnp.min(jnp.where(ok, colv, jnp.inf))
            mx = jnp.max(jnp.where(ok, colv, -jnp.inf))
            return cnt, jnp.stack([s, mn, mx, ssq])

        cnts, part = jax.vmap(one)(rows, boosts, msm, cscore,
                                   df_global)  # i32[QB], f32[QB,4]
        return (jax.lax.psum(cnts, "shard"),
                jnp.stack([
                    jax.lax.psum(part[:, 0], "shard"),
                    jax.lax.pmin(part[:, 1], "shard"),
                    jax.lax.pmax(part[:, 2], "shard"),
                    jax.lax.psum(part[:, 3], "shard"),
                ], axis=1))

    shard_map = _shard_map
    tree_spec = {k_: P("shard") for k_ in
                 ("starts", "doc_ids", "tfs", "dl", "live", "doc_base",
                  "doc_count", "sum_dl", "field_dc")}
    in_specs = (tree_spec, P("shard", "replica"), P("replica"),
                P("replica"), P("replica"), P("shard"), P("shard"))
    if filtered:
        in_specs = in_specs + (P("shard"),)
    fn = shard_map(per_device, mesh=mesh, in_specs=in_specs,
                   out_specs=P("replica"), check_vma=False)
    return jax.jit(fn)


def build_distributed_terms_agg(mesh: Mesh, bucket: int, ndocs_pad: int,
                                vpad: int, k1: float = 1.2, b: float = 0.75,
                                filtered: bool = False):
    """Keyword `terms` aggregation over the mesh: re-evaluate each query's
    match mask shard-locally, scatter-add it over the shard's flat
    (doc, global-ordinal) value pairs, and psum the per-ordinal counts over
    the `shard` axis — an EXACT global bincount (no per-shard size
    truncation, so doc_count_error_upper_bound is genuinely 0), the
    device-side analog of the reference's GlobalOrdinalsStringTermsAggregator
    + coordinator reduce. Returns a callable:
        (tree, rows [S,QB,T], boosts [QB,T], msm [QB], cscore [QB],
         val_doc [S,NV], val_ord [S,NV] [, fmask [S,D_pad]]) ->
        f32[QB, vpad] global doc counts per ordinal."""

    def per_device(tree, rows, boosts, msm, cscore, val_doc, val_ord,
                   fmask=None):
        rows = rows[0]
        starts = tree["starts"][0]
        doc_ids = tree["doc_ids"][0]
        tfs = tree["tfs"][0]
        dl = tree["dl"][0]
        live = tree["live"][0]
        vd = val_doc[0]
        vo = val_ord[0]
        fm = fmask[0] if fmask is not None else None

        df_global, n_global, avgdl = _global_dfs_stats(tree, rows)

        vvalid = vd < INT32_SENTINEL
        vd_safe = jnp.minimum(vd, ndocs_pad - 1)

        def one(r, w, m, cs, dfg):
            scores = _score_one_query(starts, doc_ids, tfs, dl, live, r, w,
                                      m, cs, n_global, dfg, avgdl, bucket,
                                      ndocs_pad, k1, b, fm)
            # int32 accumulation: f32 scatter-adds stop counting exactly at
            # 2^24 docs/bucket, which ClueWeb-class corpora exceed — the
            # "doc_count_error_upper_bound: 0" contract requires integers
            matched = (scores > -jnp.inf).astype(jnp.int32)
            contrib = jnp.where(vvalid, matched[vd_safe], 0)
            return jnp.zeros(vpad, jnp.int32).at[vo].add(contrib,
                                                         mode="drop")

        part = jax.vmap(one)(rows, boosts, msm, cscore, df_global)  # [QB,V]
        return jax.lax.psum(part, "shard")

    shard_map = _shard_map
    tree_spec = {k_: P("shard") for k_ in
                 ("starts", "doc_ids", "tfs", "dl", "live", "doc_base",
                  "doc_count", "sum_dl", "field_dc")}
    in_specs = (tree_spec, P("shard", "replica"), P("replica"),
                P("replica"), P("replica"), P("shard"), P("shard"))
    if filtered:
        in_specs = in_specs + (P("shard"),)
    fn = shard_map(per_device, mesh=mesh, in_specs=in_specs,
                   out_specs=P("replica"), check_vma=False)
    return jax.jit(fn)


def build_distributed_bincount(mesh: Mesh, bucket: int, ndocs_pad: int,
                               nb: int, k1: float = 1.2, b: float = 0.75,
                               filtered: bool = False):
    """Histogram / fixed-interval date_histogram over the mesh: re-evaluate
    each query's match mask shard-locally, scatter-add it over a
    host-precomputed per-doc bin-id array (global bin space; -1 = no value
    or out of range), and psum the counts — the distributed analog of the
    host 'hist' kernel (`search/compiler.py` emit_agg "hist") + the
    coordinator reduce. Returns a callable:
        (tree, rows [S,QB,T], boosts [QB,T], msm [QB], cscore [QB],
         bins i32[S, D_pad] [, fmask]) -> i32[QB, nb] global counts."""

    def per_device(tree, rows, boosts, msm, cscore, bins, fmask=None):
        rows = rows[0]
        starts = tree["starts"][0]
        doc_ids = tree["doc_ids"][0]
        tfs = tree["tfs"][0]
        dl = tree["dl"][0]
        live = tree["live"][0]
        bn = bins[0]
        fm = fmask[0] if fmask is not None else None

        df_global, n_global, avgdl = _global_dfs_stats(tree, rows)
        b_safe = jnp.where(bn >= 0, bn, nb)

        def one(r, w, m, cs, dfg):
            scores = _score_one_query(starts, doc_ids, tfs, dl, live, r, w,
                                      m, cs, n_global, dfg, avgdl, bucket,
                                      ndocs_pad, k1, b, fm)
            matched = (scores > -jnp.inf).astype(jnp.int32)
            contrib = jnp.where(bn >= 0, matched, 0)
            return jnp.zeros(nb, jnp.int32).at[b_safe].add(contrib,
                                                           mode="drop")

        part = jax.vmap(one)(rows, boosts, msm, cscore, df_global)
        return jax.lax.psum(part, "shard")

    shard_map = _shard_map
    tree_spec = {k_: P("shard") for k_ in
                 ("starts", "doc_ids", "tfs", "dl", "live", "doc_base",
                  "doc_count", "sum_dl", "field_dc")}
    in_specs = (tree_spec, P("shard", "replica"), P("replica"),
                P("replica"), P("replica"), P("shard"))
    if filtered:
        in_specs = in_specs + (P("shard"),)
    fn = shard_map(per_device, mesh=mesh, in_specs=in_specs,
                   out_specs=P("replica"), check_vma=False)
    return jax.jit(fn)


def build_distributed_pair_metrics(mesh: Mesh, bucket: int, ndocs_pad: int,
                                   vpad: int, k1: float = 1.2,
                                   b: float = 0.75,
                                   filtered: bool = False):
    """Per-BUCKET metric moments over the mesh — the device analog of the
    reference's sub-aggregation collectors under a bucketing parent
    (terms/histogram), `InternalTerms` buckets carrying nested
    `InternalStats`: re-evaluate each query's match mask shard-locally,
    scatter the metric column's (count, sum, min, max, sumsq) over the
    (doc, bucket-ordinal) pair arrays, and psum/pmin/pmax per ordinal over
    the `shard` axis. The pair form serves BOTH parents: keyword terms use
    the global-ordinal value pairs, histogram families use
    (arange, bin-id). Returns a callable:
        (tree, rows [S,QB,T], boosts [QB,T], msm [QB], cscore [QB],
         val_doc [S,NV], val_ord [S,NV], mcol [S,D_pad], mpres [S,D_pad]
         [, fmask]) -> (i32[QB, vpad] counts,
                        f32[QB, vpad, 4] = (sum, min, max, sumsq)),
        already global."""

    def per_device(tree, rows, boosts, msm, cscore, val_doc, val_ord,
                   mcol, mpres, fmask=None):
        rows = rows[0]
        starts = tree["starts"][0]
        doc_ids = tree["doc_ids"][0]
        tfs = tree["tfs"][0]
        dl = tree["dl"][0]
        live = tree["live"][0]
        vd = val_doc[0]
        vo = val_ord[0]
        mc = mcol[0]
        mp = mpres[0]
        fm = fmask[0] if fmask is not None else None

        df_global, n_global, avgdl = _global_dfs_stats(tree, rows)

        vvalid = vd < INT32_SENTINEL
        vd_safe = jnp.minimum(vd, ndocs_pad - 1)

        def one(r, w, m, cs, dfg):
            scores = _score_one_query(starts, doc_ids, tfs, dl, live, r, w,
                                      m, cs, n_global, dfg, avgdl, bucket,
                                      ndocs_pad, k1, b, fm)
            matched = scores > -jnp.inf
            ok = vvalid & matched[vd_safe] & (mp[vd_safe] > 0)
            v = mc[vd_safe]
            # int32 count plane: f32 scatter-adds stop counting exactly at
            # 2^24 docs/bucket (same rule as the terms bincount program)
            cnt = jnp.zeros(vpad, jnp.int32).at[vo].add(
                ok.astype(jnp.int32), mode="drop")
            s = jnp.zeros(vpad, jnp.float32).at[vo].add(
                jnp.where(ok, v, 0.0), mode="drop")
            ssq = jnp.zeros(vpad, jnp.float32).at[vo].add(
                jnp.where(ok, v * v, 0.0), mode="drop")
            mn = jnp.full(vpad, jnp.inf, jnp.float32).at[vo].min(
                jnp.where(ok, v, jnp.inf), mode="drop")
            mx = jnp.full(vpad, -jnp.inf, jnp.float32).at[vo].max(
                jnp.where(ok, v, -jnp.inf), mode="drop")
            return cnt, jnp.stack([s, mn, mx, ssq], axis=1)

        cnts, part = jax.vmap(one)(rows, boosts, msm, cscore, df_global)
        # counts i32[QB, vpad] exact; moments f32[QB, vpad, 4]
        return (jax.lax.psum(cnts, "shard"),
                jnp.stack([
                    jax.lax.psum(part[:, :, 0], "shard"),
                    jax.lax.pmin(part[:, :, 1], "shard"),
                    jax.lax.pmax(part[:, :, 2], "shard"),
                    jax.lax.psum(part[:, :, 3], "shard"),
                ], axis=2))

    shard_map = _shard_map
    tree_spec = {k_: P("shard") for k_ in
                 ("starts", "doc_ids", "tfs", "dl", "live", "doc_base",
                  "doc_count", "sum_dl", "field_dc")}
    in_specs = (tree_spec, P("shard", "replica"), P("replica"),
                P("replica"), P("replica"), P("shard"), P("shard"),
                P("shard"), P("shard"))
    if filtered:
        in_specs = in_specs + (P("shard"),)
    fn = shard_map(per_device, mesh=mesh, in_specs=in_specs,
                   out_specs=P("replica"), check_vma=False)
    return jax.jit(fn)


def build_distributed_range_metrics(mesh: Mesh, bucket: int, ndocs_pad: int,
                                    nr: int, k1: float = 1.2,
                                    b: float = 0.75,
                                    filtered: bool = False):
    """Per-RANGE metric moments over the mesh (sub-aggregations under a
    `range` parent; ranges may overlap so this is nr masked reductions, not
    a scatter). Returns a callable:
        (tree, rows, boosts, msm, cscore, col [S,D], pres [S,D],
         lows f32[nr], highs f32[nr], mcol [S,D], mpres [S,D] [, fmask])
        -> (i32[QB, nr] counts, f32[QB, nr, 4] = (sum, min, max, sumsq)),
        global."""

    def per_device(tree, rows, boosts, msm, cscore, col, pres, lows, highs,
                   mcol, mpres, fmask=None):
        rows = rows[0]
        starts = tree["starts"][0]
        doc_ids = tree["doc_ids"][0]
        tfs = tree["tfs"][0]
        dl = tree["dl"][0]
        live = tree["live"][0]
        cv = col[0]
        pr = pres[0]
        mc = mcol[0]
        mp = mpres[0]
        fm = fmask[0] if fmask is not None else None

        df_global, n_global, avgdl = _global_dfs_stats(tree, rows)

        def one(r, w, m, cs, dfg):
            scores = _score_one_query(starts, doc_ids, tfs, dl, live, r, w,
                                      m, cs, n_global, dfg, avgdl, bucket,
                                      ndocs_pad, k1, b, fm)
            matched = (scores > -jnp.inf) & (pr > 0) & (mp > 0)
            cnts, stats = [], []
            for ri in range(nr):
                ok = matched & (cv >= lows[ri]) & (cv < highs[ri])
                cnts.append(jnp.sum(ok.astype(jnp.int32)))
                stats.append(jnp.stack([
                    jnp.sum(jnp.where(ok, mc, 0.0)),
                    jnp.min(jnp.where(ok, mc, jnp.inf)),
                    jnp.max(jnp.where(ok, mc, -jnp.inf)),
                    jnp.sum(jnp.where(ok, mc * mc, 0.0))]))
            return jnp.stack(cnts), jnp.stack(stats)

        cnts, part = jax.vmap(one)(rows, boosts, msm, cscore, df_global)
        return (jax.lax.psum(cnts, "shard"),
                jnp.stack([
                    jax.lax.psum(part[:, :, 0], "shard"),
                    jax.lax.pmin(part[:, :, 1], "shard"),
                    jax.lax.pmax(part[:, :, 2], "shard"),
                    jax.lax.psum(part[:, :, 3], "shard"),
                ], axis=2))

    shard_map = _shard_map
    tree_spec = {k_: P("shard") for k_ in
                 ("starts", "doc_ids", "tfs", "dl", "live", "doc_base",
                  "doc_count", "sum_dl", "field_dc")}
    in_specs = (tree_spec, P("shard", "replica"), P("replica"),
                P("replica"), P("replica"), P("shard"), P("shard"),
                P(), P(), P("shard"), P("shard"))
    if filtered:
        in_specs = in_specs + (P("shard"),)
    fn = shard_map(per_device, mesh=mesh, in_specs=in_specs,
                   out_specs=P("replica"), check_vma=False)
    return jax.jit(fn)


def build_distributed_cardinality(mesh: Mesh, bucket: int, ndocs_pad: int,
                                  keyword: bool, vpad: int = 0,
                                  k1: float = 1.2,
                                  b: float = 0.75, filtered: bool = False):
    """`cardinality` over the mesh with EXACT host parity: per shard,
    build the same HyperLogLog registers the host segment path builds
    (ops/aggs.py hll_registers over crc32 ordinal hashes / fmix32 value
    hashes), then reduce with pmax — HLL registers merge by elementwise
    max, which is precisely the collective the mesh has. The estimate is
    therefore bit-identical to the host shard loop's.

    keyword=True: (tree, rows, boosts, msm, cscore, val_doc [S,NV],
        val_ord [S,NV], ord_hashes u32[vpad] [, fmask])
    keyword=False: (tree, rows, boosts, msm, cscore, col [S,D],
        pres [S,D] [, fmask])
    -> i32[QB, 2^HLL_LOG2M] registers, already global."""
    from ..ops import aggs as agg_ops
    # the ONE precision constant: mesh registers must stay the same
    # shape/precision as the host's or the max-merge silently drifts
    from ..search.compiler import HLL_LOG2M as log2m

    def per_device(tree, rows, boosts, msm, cscore, *rest):
        fmask = rest[-1] if filtered else None
        rest = rest[:-1] if filtered else rest
        rows = rows[0]
        starts = tree["starts"][0]
        doc_ids = tree["doc_ids"][0]
        tfs = tree["tfs"][0]
        dl = tree["dl"][0]
        live = tree["live"][0]
        fm = fmask[0] if fmask is not None else None

        df_global, n_global, avgdl = _global_dfs_stats(tree, rows)

        if keyword:
            val_doc, val_ord, ord_hashes = rest
            vd = val_doc[0]
            vo = val_ord[0]
            vvalid = vd < INT32_SENTINEL
            vd_safe = jnp.minimum(vd, ndocs_pad - 1)

            def one(r, w, m, cs, dfg):
                scores = _score_one_query(starts, doc_ids, tfs, dl, live,
                                          r, w, m, cs, n_global, dfg,
                                          avgdl, bucket, ndocs_pad, k1, b,
                                          fm)
                matched = (scores > -jnp.inf).astype(jnp.int32)
                contrib = jnp.where(vvalid, matched[vd_safe], 0)
                counts = jnp.zeros(vpad, jnp.int32).at[vo].add(
                    contrib, mode="drop")
                return agg_ops.hll_registers(ord_hashes, counts > 0,
                                             log2m)
        else:
            col, pres = rest
            cv = col[0]
            pr = pres[0]
            hashes = agg_ops._hash_f32(cv)

            def one(r, w, m, cs, dfg):
                scores = _score_one_query(starts, doc_ids, tfs, dl, live,
                                          r, w, m, cs, n_global, dfg,
                                          avgdl, bucket, ndocs_pad, k1, b,
                                          fm)
                valid = (scores > -jnp.inf) & (pr > 0)
                return agg_ops.hll_registers(hashes, valid, log2m)

        part = jax.vmap(one)(rows, boosts, msm, cscore, df_global)
        return jax.lax.pmax(part, "shard")

    shard_map = _shard_map
    tree_spec = {k_: P("shard") for k_ in
                 ("starts", "doc_ids", "tfs", "dl", "live", "doc_base",
                  "doc_count", "sum_dl", "field_dc")}
    if keyword:
        in_specs = (tree_spec, P("shard", "replica"), P("replica"),
                    P("replica"), P("replica"), P("shard"), P("shard"),
                    P())
    else:
        in_specs = (tree_spec, P("shard", "replica"), P("replica"),
                    P("replica"), P("replica"), P("shard"), P("shard"))
    if filtered:
        in_specs = in_specs + (P("shard"),)
    fn = shard_map(per_device, mesh=mesh, in_specs=in_specs,
                   out_specs=P("replica"), check_vma=False)
    return jax.jit(fn)


def build_distributed_ddsketch(mesh: Mesh, bucket: int, ndocs_pad: int,
                               k1: float = 1.2, b: float = 0.75,
                               filtered: bool = False):
    """DDSketch histogram over the mesh (serves BOTH `percentiles` and
    `median_absolute_deviation`): bins are value-independent global
    constants, so per-shard histograms merge by plain addition — psum IS
    the reference's TDigest-merge analog. Returns a callable:
        (tree, rows, boosts, msm, cscore, col [S,D], pres [S,D] [, fmask])
        -> f32[QB, DD_NBINS], already global."""
    from ..ops import aggs as agg_ops

    def per_device(tree, rows, boosts, msm, cscore, col, pres, fmask=None):
        rows = rows[0]
        starts = tree["starts"][0]
        doc_ids = tree["doc_ids"][0]
        tfs = tree["tfs"][0]
        dl = tree["dl"][0]
        live = tree["live"][0]
        cv = col[0]
        pr = pres[0]
        fm = fmask[0] if fmask is not None else None

        df_global, n_global, avgdl = _global_dfs_stats(tree, rows)

        def one(r, w, m, cs, dfg):
            scores = _score_one_query(starts, doc_ids, tfs, dl, live, r, w,
                                      m, cs, n_global, dfg, avgdl, bucket,
                                      ndocs_pad, k1, b, fm)
            matchf = (scores > -jnp.inf).astype(jnp.float32)
            return agg_ops.ddsketch_hist(cv, pr > 0, matchf)

        part = jax.vmap(one)(rows, boosts, msm, cscore, df_global)
        return jax.lax.psum(part, "shard")

    shard_map = _shard_map
    tree_spec = {k_: P("shard") for k_ in
                 ("starts", "doc_ids", "tfs", "dl", "live", "doc_base",
                  "doc_count", "sum_dl", "field_dc")}
    in_specs = (tree_spec, P("shard", "replica"), P("replica"),
                P("replica"), P("replica"), P("shard"), P("shard"))
    if filtered:
        in_specs = in_specs + (P("shard"),)
    fn = shard_map(per_device, mesh=mesh, in_specs=in_specs,
                   out_specs=P("replica"), check_vma=False)
    return jax.jit(fn)


def build_distributed_weighted_avg(mesh: Mesh, bucket: int, ndocs_pad: int,
                                   k1: float = 1.2, b: float = 0.75,
                                   filtered: bool = False):
    """`weighted_avg` over the mesh: psum of (value*weight sum, weight
    sum, count) over docs present in BOTH columns — the host's
    weighted_avg_agg moments, reduced once. Returns a callable:
        (tree, rows, boosts, msm, cscore, vcol, vpres, wcol, wpres
         [, fmask]) -> f32[QB, 3] = (vwsum, wsum, count), global."""

    def per_device(tree, rows, boosts, msm, cscore, vcol, vpres, wcol,
                   wpres, fmask=None):
        rows = rows[0]
        starts = tree["starts"][0]
        doc_ids = tree["doc_ids"][0]
        tfs = tree["tfs"][0]
        dl = tree["dl"][0]
        live = tree["live"][0]
        vv = vcol[0]
        vp = vpres[0]
        wv = wcol[0]
        wp = wpres[0]
        fm = fmask[0] if fmask is not None else None

        df_global, n_global, avgdl = _global_dfs_stats(tree, rows)

        def one(r, w, m, cs, dfg):
            scores = _score_one_query(starts, doc_ids, tfs, dl, live, r, w,
                                      m, cs, n_global, dfg, avgdl, bucket,
                                      ndocs_pad, k1, b, fm)
            ok = (scores > -jnp.inf) & (vp > 0) & (wp > 0)
            okf = ok.astype(jnp.float32)
            return jnp.stack([jnp.sum(okf * vv * wv),
                              jnp.sum(okf * wv),
                              jnp.sum(okf)])

        part = jax.vmap(one)(rows, boosts, msm, cscore, df_global)
        return jax.lax.psum(part, "shard")

    shard_map = _shard_map
    tree_spec = {k_: P("shard") for k_ in
                 ("starts", "doc_ids", "tfs", "dl", "live", "doc_base",
                  "doc_count", "sum_dl", "field_dc")}
    in_specs = (tree_spec, P("shard", "replica"), P("replica"),
                P("replica"), P("replica"), P("shard"), P("shard"),
                P("shard"), P("shard"))
    if filtered:
        in_specs = in_specs + (P("shard"),)
    fn = shard_map(per_device, mesh=mesh, in_specs=in_specs,
                   out_specs=P("replica"), check_vma=False)
    return jax.jit(fn)


def build_distributed_geo_stat(mesh: Mesh, bucket: int, ndocs_pad: int,
                               k1: float = 1.2, b: float = 0.75,
                               filtered: bool = False):
    """geo_bounds + geo_centroid over the mesh in one program (the two
    kinds share every input): per shard, masked lat/lon extremes and
    centroid moments, reduced with pmax/pmin/psum — the same collectives
    the host merge applies across segments. Returns a callable:
        (tree, rows, boosts, msm, cscore, glat [S,D], glon [S,D],
         gpres [S,D] [, fmask]) ->
        f32[QB, 7] = (count, top, bottom, left, right, slat, slon)."""
    F32_MAX = np.float32(np.finfo(np.float32).max)

    def per_device(tree, rows, boosts, msm, cscore, glat, glon, gpres,
                   fmask=None):
        rows = rows[0]
        starts = tree["starts"][0]
        doc_ids = tree["doc_ids"][0]
        tfs = tree["tfs"][0]
        dl = tree["dl"][0]
        live = tree["live"][0]
        la = glat[0]
        lo = glon[0]
        pr = gpres[0]
        fm = fmask[0] if fmask is not None else None

        df_global, n_global, avgdl = _global_dfs_stats(tree, rows)

        def one(r, w, m, cs, dfg):
            scores = _score_one_query(starts, doc_ids, tfs, dl, live, r, w,
                                      m, cs, n_global, dfg, avgdl, bucket,
                                      ndocs_pad, k1, b, fm)
            ok = (scores > -jnp.inf) & (pr > 0)
            okf = ok.astype(jnp.float32)
            return jnp.stack([
                jnp.sum(okf),
                jnp.max(jnp.where(ok, la, -F32_MAX)),
                jnp.min(jnp.where(ok, la, F32_MAX)),
                jnp.min(jnp.where(ok, lo, F32_MAX)),
                jnp.max(jnp.where(ok, lo, -F32_MAX)),
                jnp.sum(okf * la),
                jnp.sum(okf * lo)])

        part = jax.vmap(one)(rows, boosts, msm, cscore, df_global)
        return jnp.stack([
            jax.lax.psum(part[:, 0], "shard"),
            jax.lax.pmax(part[:, 1], "shard"),
            jax.lax.pmin(part[:, 2], "shard"),
            jax.lax.pmin(part[:, 3], "shard"),
            jax.lax.pmax(part[:, 4], "shard"),
            jax.lax.psum(part[:, 5], "shard"),
            jax.lax.psum(part[:, 6], "shard"),
        ], axis=1)

    shard_map = _shard_map
    tree_spec = {k_: P("shard") for k_ in
                 ("starts", "doc_ids", "tfs", "dl", "live", "doc_base",
                  "doc_count", "sum_dl", "field_dc")}
    in_specs = (tree_spec, P("shard", "replica"), P("replica"),
                P("replica"), P("replica"), P("shard"), P("shard"),
                P("shard"))
    if filtered:
        in_specs = in_specs + (P("shard"),)
    fn = shard_map(per_device, mesh=mesh, in_specs=in_specs,
                   out_specs=P("replica"), check_vma=False)
    return jax.jit(fn)


def build_distributed_range_counts(mesh: Mesh, bucket: int, ndocs_pad: int,
                                   nr: int, k1: float = 1.2,
                                   b: float = 0.75,
                                   filtered: bool = False):
    """`range` aggregation over the mesh: per-range [lo, hi) masked count
    of matching docs (ranges may OVERLAP, so this is nr masked sums, not a
    bincount), psum'd over the shard axis. Returns a callable:
        (tree, rows, boosts, msm, cscore, col [S,D], pres [S,D],
         lows f32[nr], highs f32[nr] [, fmask]) -> i32[QB, nr]."""

    def per_device(tree, rows, boosts, msm, cscore, col, pres, lows, highs,
                   fmask=None):
        rows = rows[0]
        starts = tree["starts"][0]
        doc_ids = tree["doc_ids"][0]
        tfs = tree["tfs"][0]
        dl = tree["dl"][0]
        live = tree["live"][0]
        cv = col[0]
        pr = pres[0]
        fm = fmask[0] if fmask is not None else None

        df_global, n_global, avgdl = _global_dfs_stats(tree, rows)

        def one(r, w, m, cs, dfg):
            scores = _score_one_query(starts, doc_ids, tfs, dl, live, r, w,
                                      m, cs, n_global, dfg, avgdl, bucket,
                                      ndocs_pad, k1, b, fm)
            matched = (scores > -jnp.inf) & (pr > 0)
            counts = []
            for ri in range(nr):
                sel = matched & (cv >= lows[ri]) & (cv < highs[ri])
                counts.append(jnp.sum(sel.astype(jnp.int32)))
            return jnp.stack(counts)

        part = jax.vmap(one)(rows, boosts, msm, cscore, df_global)
        return jax.lax.psum(part, "shard")

    shard_map = _shard_map
    tree_spec = {k_: P("shard") for k_ in
                 ("starts", "doc_ids", "tfs", "dl", "live", "doc_base",
                  "doc_count", "sum_dl", "field_dc")}
    in_specs = (tree_spec, P("shard", "replica"), P("replica"),
                P("replica"), P("replica"), P("shard"), P("shard"),
                P(), P())
    if filtered:
        in_specs = in_specs + (P("shard"),)
    fn = shard_map(per_device, mesh=mesh, in_specs=in_specs,
                   out_specs=P("replica"), check_vma=False)
    return jax.jit(fn)


@dataclass
class StackedPhrasePairs:
    """Per-shard positional (doc, position) pair arrays in the SAME
    term-row space as a StackedShardIndex — the mesh-resident form of the
    host path's per-segment `_phrase_pair_cache` (search/compiler.py
    `_phrase_pairs`). Rows are the stacked index's shard term-union rows;
    each row's pairs concatenate the shard's segments (doc ids offset by
    segment base) and are lex-sorted by (doc, position), sentinel padded."""

    field: str
    pair_starts: jnp.ndarray   # i32[S, R_pad]  (stacked.starts row space)
    pair_d: jnp.ndarray        # i32[S, PP_pad]
    pair_p: jnp.ndarray        # i32[S, PP_pad]
    host_pair_starts: Optional[List[np.ndarray]] = None
    nbytes: int = 0

    def row_size(self, shard: int, row: int) -> int:
        st = self.host_pair_starts[shard]
        return int(st[row + 1] - st[row]) if 0 <= row < len(st) - 1 else 0

    def tree(self) -> dict:
        return {"pair_starts": self.pair_starts, "pair_d": self.pair_d,
                "pair_p": self.pair_p}

    @classmethod
    def build(cls, shard_segs, field: str, stacked: StackedShardIndex,
              mesh: Mesh) -> Optional["StackedPhrasePairs"]:
        S = len(shard_segs)
        per = []
        any_positional = False
        for si, segs in enumerate(shard_segs):
            union = stacked.host_terms[si]
            nterms = len(union)
            trows_parts, d_parts, p_parts = [], [], []
            off = 0
            for seg in segs:
                pb = seg.postings.get(field)
                if pb is not None and pb.pos_starts is not None and pb.size:
                    any_positional = True
                    # vectorized: per-position (union row, offset doc, pos)
                    rows_map = np.array([union[t] for t in pb.vocab],
                                        np.int64)
                    per_post = np.repeat(rows_map, np.diff(pb.starts))
                    counts = np.diff(pb.pos_starts)
                    trows_parts.append(np.repeat(per_post, counts))
                    d_parts.append(np.repeat(
                        pb.doc_ids.astype(np.int64) + off, counts))
                    p_parts.append(pb.positions.astype(np.int64))
                off += seg.ndocs
            if trows_parts:
                trows = np.concatenate(trows_parts)
                d = np.concatenate(d_parts)
                p = np.concatenate(p_parts)
                order = np.lexsort((p, d, trows))
                trows, d, p = trows[order], d[order], p[order]
                lens = np.bincount(trows, minlength=nterms)
            else:
                d = p = np.zeros(0, np.int64)
                lens = np.zeros(max(nterms, 1), np.int64)
            starts = np.zeros(len(lens) + 1, np.int64)
            np.cumsum(lens, out=starts[1:])
            per.append((starts, d, p))
        if not any_positional:
            return None
        r_pad = int(stacked.starts.shape[1])
        pp_pad = max(next_pow2(max(len(d), 1)) for _st, d, _p in per)
        pair_starts = np.zeros((S, r_pad), np.int32)
        pair_d = np.full((S, pp_pad), INT32_SENTINEL, np.int32)
        pair_p = np.full((S, pp_pad), INT32_SENTINEL, np.int32)
        host_ps = []
        for si, (starts, d, p) in enumerate(per):
            n = min(len(starts), r_pad)
            pair_starts[si, :n] = starts[:n]
            pair_starts[si, n:] = starts[-1]
            pair_d[si, : len(d)] = d
            pair_p[si, : len(p)] = p
            host_ps.append(starts)
        sharding = NamedSharding(mesh, P("shard"))
        return cls(field=field,
                   pair_starts=jax.device_put(pair_starts, sharding),  # oslint: disable=OSL506 -- _ByteLRU kind registers at put()
                   pair_d=jax.device_put(pair_d, sharding),  # oslint: disable=OSL506 -- _ByteLRU kind registers at put()
                   pair_p=jax.device_put(pair_p, sharding),  # oslint: disable=OSL506 -- _ByteLRU kind registers at put()
                   host_pair_starts=host_ps,
                   nbytes=pair_starts.nbytes + pair_d.nbytes
                   + pair_p.nbytes)


def build_distributed_phrase(mesh: Mesh, bucket: int, ndocs_pad: int,
                             k: int, n_terms: int, k1: float = 1.2,
                             b: float = 0.75, filtered: bool = False):
    """Distributed match_phrase over the mesh: each shard runs the
    vectorized positional pair-join (ops/positions.py phrase_freqs — the
    device replacement for Lucene's ExactPhrase/SloppyPhraseMatcher) over
    its own positional pairs, scores the phrase as one BM25 pseudo-term
    with the HOST-computed global weight (same `LPhrase.weight` the host
    shard loop uses, so scores are bit-identical), and the per-shard
    top-ks merge with an all_gather — completing the coordinator fan-out
    (`action/search/SearchPhaseController.java:1`) for the phrase-shaped
    traffic the mesh previously declined. Returns a callable:
        (tree, ptree, rows [S,QB,T], weights [QB], slops [QB],
         avgdl [QB] [, fmask [S,D_pad]]) ->
        (global_doc_ids [QB, S*k], scores [QB, S*k], totals [QB])"""
    from ..ops import positions as pos_ops

    def gather_pairs(pstarts, pair_d, pair_p, r):
        rsafe = jnp.maximum(r, 0)
        a = jnp.where(r >= 0, pstarts[rsafe], 0)
        e = jnp.where(r >= 0, pstarts[rsafe + 1], 0)
        idx = a + jnp.arange(bucket, dtype=jnp.int32)
        valid = idx < e
        safe = jnp.minimum(idx, pair_d.shape[0] - 1)
        d = jnp.where(valid, pair_d[safe], INT32_SENTINEL)
        p = jnp.where(valid, pair_p[safe], INT32_SENTINEL)
        return d, p

    def per_device(tree, ptree, rows, weights, slops, avgdl, fmask=None):
        rows = rows[0]
        pstarts = ptree["pair_starts"][0]
        pair_d = ptree["pair_d"][0]
        pair_p = ptree["pair_p"][0]
        dl = tree["dl"][0]
        live = tree["live"][0]
        doc_base = tree["doc_base"][0]
        fm = fmask[0] if fmask is not None else None
        lv = live * fm if fm is not None else live

        def one(r, w, slop, ad):
            anchor_d, anchor_p = gather_pairs(pstarts, pair_d, pair_p,
                                              r[0])
            others = [gather_pairs(pstarts, pair_d, pair_p, r[i])
                      for i in range(1, n_terms)]
            freq = pos_ops.phrase_freqs(
                anchor_d, anchor_p, others, slop, ndocs_pad,
                shifts=list(range(1, n_terms)))
            sc, matched = pos_ops.phrase_score(freq, dl, lv, w, k1, b, ad)
            return jnp.where(matched, sc, -jnp.inf)

        scores = jax.vmap(one)(rows, weights, slops, avgdl)       # [QB, D]
        totals = jax.lax.psum(jnp.sum(scores > -jnp.inf, axis=1), "shard")
        kk = min(k, ndocs_pad)
        vals, idx = jax.lax.top_k(scores, kk)
        gids = jnp.where(vals > -jnp.inf, idx + doc_base, -1)
        all_vals = jax.lax.all_gather(vals, "shard", axis=1)
        all_gids = jax.lax.all_gather(gids, "shard", axis=1)
        S = all_vals.shape[1]
        return (all_gids.reshape(all_gids.shape[0], S * kk),
                all_vals.reshape(all_vals.shape[0], S * kk), totals)

    shard_map = _shard_map
    tree_spec = {k_: P("shard") for k_ in
                 ("starts", "doc_ids", "tfs", "dl", "live", "doc_base",
                  "doc_count", "sum_dl", "field_dc")}
    ptree_spec = {k_: P("shard") for k_ in
                  ("pair_starts", "pair_d", "pair_p")}
    in_specs = (tree_spec, ptree_spec, P("shard", "replica"), P("replica"),
                P("replica"), P("replica"))
    if filtered:
        in_specs = in_specs + (P("shard"),)
    fn = shard_map(per_device, mesh=mesh, in_specs=in_specs,
                   out_specs=(P("replica"), P("replica"), P("replica")),
                   check_vma=False)
    return jax.jit(fn)


def build_term_sharded_score(mesh: Mesh, bucket: int, ndocs_pad: int, k: int,
                             k1: float = 1.2, b: float = 0.75):
    """Sequence-parallel analog: ONE doc space replicated, posting rows of the
    query terms partitioned across the `shard` axis (each device scores a
    slice of the postings); partial dense score vectors are `psum`med. Use for
    pathologically hot terms whose posting lists dwarf a shard (the long-
    context regime: the reduction dimension is sharded, not the batch)."""

    def per_device(starts, doc_ids, tfs, dl, live, rows, boosts, df, n_docs, avgdl, msm):
        starts = starts[0]
        doc_ids = doc_ids[0]
        tfs = tfs[0]
        # dl/live replicated
        idf = jnp.log1p((n_docs - df + 0.5) / (df + 0.5))
        w = jnp.where(df > 0, boosts * idf, 0.0)
        docs, tf, t_idx, valid = _local_gather(starts, doc_ids, tfs, rows, bucket)
        dsafe = jnp.minimum(docs, ndocs_pad - 1)
        kfac = k1 * (1.0 - b + b * dl[dsafe] / jnp.maximum(avgdl, 1e-9))
        contrib = jnp.where(valid, w[t_idx] * tf / (tf + kfac), 0.0)
        part = jnp.zeros(ndocs_pad, jnp.float32).at[docs].add(contrib, mode="drop")
        cnt = jnp.zeros(ndocs_pad, jnp.float32).at[docs].add(
            jnp.where(valid & (tf > 0), 1.0, 0.0), mode="drop")
        scores = jax.lax.psum(part, "shard")
        counts = jax.lax.psum(cnt, "shard")
        masked = jnp.where((counts >= msm) & (live > 0), scores, -jnp.inf)
        vals, idx = jax.lax.top_k(masked, min(k, ndocs_pad))
        return vals, idx

    shard_map = _shard_map

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(P("shard"), P("shard"), P("shard"),
                             P(), P(), P(), P(), P(), P(), P(), P()),
                   out_specs=(P(), P()),
                   check_vma=False)
    return jax.jit(fn)


def route_docs_to_shards(ids: List[str], n_shards: int) -> List[int]:
    """Host-side murmur3 doc routing (same as cluster.routing.shard_for)."""
    from ..cluster.routing import shard_for

    return [shard_for(i, n_shards) for i in ids]


def pad_queries(term_rows: List[List[int]], term_boosts: List[List[float]],
                msms: List[int], qb_pad: int, t_pad: int):
    """Host packing of a query batch into [QB,T] arrays for the SPMD program.
    NOTE: rows must be PER-SHARD (each shard has its own term dict); use
    `pack_query_batch` which resolves terms against every shard."""
    rows = np.full((qb_pad, t_pad), -1, np.int32)
    boosts = np.zeros((qb_pad, t_pad), np.float32)
    msm = np.zeros(qb_pad, np.float32)
    for i, (r, bst, m) in enumerate(zip(term_rows, term_boosts, msms)):
        rows[i, : len(r)] = r
        boosts[i, : len(bst)] = bst
        msm[i] = m
    return rows, boosts, msm


def pack_query_batch(segments: List[Segment], field: str,
                     queries: List[List[str]], qb_pad: int, t_pad: int,
                     mesh: Optional[Mesh] = None):
    """Resolve analyzed query terms against every shard's term dict ->
    rows [S, QB, T] (sharded over `shard`), boosts/msm [QB, ...] (replicated
    over shard, sharded over replica). For the doc-sharded program, rows must
    differ per shard; we stack them and let shard_map slice its block."""
    S = len(segments)
    rows = np.full((S, qb_pad, t_pad), -1, np.int32)
    boosts = np.zeros((qb_pad, t_pad), np.float32)
    msm = np.ones(qb_pad, np.float32)
    for qi, terms in enumerate(queries):
        for ti, t in enumerate(terms[:t_pad]):
            boosts[qi, ti] = 1.0
            for si, seg in enumerate(segments):
                pb = seg.postings.get(field)
                rows[si, qi, ti] = pb.row(t) if pb is not None else -1
    return rows, boosts, msm
