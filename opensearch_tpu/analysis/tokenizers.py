"""Tokenizers. Analog of reference `modules/analysis-common/.../CommonAnalysisModulePlugin.java`
tokenizer registrations (standard, whitespace, keyword, letter, ngram,
edge_ngram, pattern, lowercase).

Tokenizers run on the host during the write path; the device never sees
strings, only term ids. Each tokenizer maps `str -> list[Token]`.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable, Dict, List


@dataclass
class Token:
    """A single token with position + offsets (offsets power highlighting;
    positions power phrase queries — analog of Lucene's PackedTokenAttributeImpl).
    `keyword` mirrors Lucene's KeywordAttribute: set by keyword_marker /
    stemmer_override, honored (skipped) by stemmers, and it SURVIVES
    intervening text transforms because filters rebuild via with_text."""

    text: str
    position: int
    start_offset: int
    end_offset: int
    keyword: bool = False

    def with_text(self, text: str) -> "Token":
        """Rebuild with new text, preserving position/offsets/flags."""
        return Token(text, self.position, self.start_offset,
                     self.end_offset, self.keyword)


# UAX#29-lite: runs of word characters incl. digits; keeps unicode letters.
_STANDARD_RE = re.compile(r"[\w][\w']*", re.UNICODE)
_LETTER_RE = re.compile(r"[^\W\d_]+", re.UNICODE)


def _re_tokenize(text: str, pattern: re.Pattern) -> List[Token]:
    out = []
    for pos, m in enumerate(pattern.finditer(text)):
        out.append(Token(m.group(0), pos, m.start(), m.end()))
    return out


def standard_tokenizer(text: str) -> List[Token]:
    """Word-boundary tokenizer (simplified UAX#29, like Lucene StandardTokenizer)."""
    return _re_tokenize(text, _STANDARD_RE)


def whitespace_tokenizer(text: str) -> List[Token]:
    out, pos = [], 0
    for m in re.finditer(r"\S+", text):
        out.append(Token(m.group(0), pos, m.start(), m.end()))
        pos += 1
    return out


def letter_tokenizer(text: str) -> List[Token]:
    return _re_tokenize(text, _LETTER_RE)


def keyword_tokenizer(text: str) -> List[Token]:
    """Whole input as a single token (reference KeywordTokenizer)."""
    if not text:
        return []
    return [Token(text, 0, 0, len(text))]


def lowercase_tokenizer(text: str) -> List[Token]:
    return [Token(t.text.lower(), t.position, t.start_offset, t.end_offset)
            for t in letter_tokenizer(text)]


def make_pattern_tokenizer(pattern: str = r"\W+", group: int = -1) -> Callable[[str], List[Token]]:
    """Reference PatternTokenizer: pattern splits (group=-1) or captures (group>=0)."""
    compiled = re.compile(pattern)

    def tokenize(text: str) -> List[Token]:
        out: List[Token] = []
        if group >= 0:
            for pos, m in enumerate(compiled.finditer(text)):
                g = m.group(group)
                if g:
                    out.append(Token(g, pos, m.start(group), m.end(group)))
            return out
        pos = 0
        prev = 0
        for m in compiled.finditer(text):
            if m.start() > prev:
                out.append(Token(text[prev:m.start()], pos, prev, m.start()))
                pos += 1
            prev = m.end()
        if prev < len(text):
            out.append(Token(text[prev:], pos, prev, len(text)))
        return out

    return tokenize


def _ngrams(text: str, min_gram: int, max_gram: int, edge: bool) -> List[Token]:
    out: List[Token] = []
    pos = 0
    n = len(text)
    starts = [0] if edge else range(n)
    for i in starts:
        for g in range(min_gram, max_gram + 1):
            if i + g <= n:
                out.append(Token(text[i:i + g], pos, i, i + g))
                pos += 1
    return out


def make_ngram_tokenizer(min_gram: int = 1, max_gram: int = 2) -> Callable[[str], List[Token]]:
    return lambda text: _ngrams(text, min_gram, max_gram, edge=False)


def make_edge_ngram_tokenizer(min_gram: int = 1, max_gram: int = 2) -> Callable[[str], List[Token]]:
    return lambda text: _ngrams(text, min_gram, max_gram, edge=True)


TOKENIZERS: Dict[str, Callable] = {
    "standard": standard_tokenizer,
    "whitespace": whitespace_tokenizer,
    "letter": letter_tokenizer,
    "keyword": keyword_tokenizer,
    "lowercase": lowercase_tokenizer,
}


def resolve_tokenizer(name: str, params: dict | None = None) -> Callable[[str], List[Token]]:
    params = params or {}
    if name in TOKENIZERS:
        return TOKENIZERS[name]
    if name == "pattern":
        return make_pattern_tokenizer(params.get("pattern", r"\W+"), params.get("group", -1))
    if name == "ngram":
        return make_ngram_tokenizer(params.get("min_gram", 1), params.get("max_gram", 2))
    if name == "edge_ngram":
        return make_edge_ngram_tokenizer(params.get("min_gram", 1), params.get("max_gram", 2))
    raise ValueError(f"unknown tokenizer [{name}]")
