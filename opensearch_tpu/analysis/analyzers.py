"""Analyzers and the analysis registry. Analog of reference
`server/src/main/java/org/opensearch/index/analysis/AnalysisRegistry.java` and
the built-in analyzers wired in `AnalysisModule`.

An Analyzer = [char filters] -> tokenizer -> [token filters]. Custom analyzers
are declared in index settings exactly like the reference:

    {"analysis": {"analyzer": {"my": {"type": "custom", "tokenizer": "standard",
                                       "filter": ["lowercase", "stop"]}}}}
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List

from .filters import (CharFilter, TokenFilter, lowercase_filter, make_stop_filter,
                      porter_stem_filter, resolve_char_filter, resolve_token_filter)
from .tokenizers import Token, keyword_tokenizer, resolve_tokenizer, standard_tokenizer, whitespace_tokenizer


@dataclass
class Analyzer:
    name: str
    tokenizer: Callable[[str], List[Token]]
    token_filters: List[TokenFilter] = field(default_factory=list)
    char_filters: List[CharFilter] = field(default_factory=list)

    def _std_fast(self) -> bool:
        """True when this chain is exactly standard-tokenize + lowercase with
        no char filters — the shape the native ASCII tokenizer implements."""
        cached = getattr(self, "_std_fast_cache", None)
        if cached is None:
            cached = (self.tokenizer is standard_tokenizer
                      and self.token_filters == [lowercase_filter]
                      and not self.char_filters)
            if cached:
                from .. import native
                cached = native.available()
            object.__setattr__(self, "_std_fast_cache", cached)
        return cached

    def analyze(self, text: str) -> List[Token]:
        if self._std_fast() and text.isascii():
            from .. import native
            low = text.lower()
            return [Token(low[s:e], i, int(s), int(e))
                    for i, (s, e) in enumerate(native.tokenize_ascii(text))]
        for cf in self.char_filters:
            text = cf(text)
        tokens = self.tokenizer(text)
        for tf in self.token_filters:
            tokens = tf(tokens)
        return tokens

    def terms(self, text: str) -> List[str]:
        return [t.text for t in self.analyze(text)]


def _builtin(name: str) -> Analyzer:
    if name == "standard":
        return Analyzer(name, standard_tokenizer, [lowercase_filter])
    if name == "simple":
        return Analyzer(name, resolve_tokenizer("lowercase"), [])
    if name == "whitespace":
        return Analyzer(name, whitespace_tokenizer, [])
    if name == "keyword":
        return Analyzer(name, keyword_tokenizer, [])
    if name == "stop":
        return Analyzer(name, resolve_tokenizer("lowercase"), [make_stop_filter()])
    if name == "english":
        # reference EnglishAnalyzerProvider: std -> lowercase -> stop -> porter
        return Analyzer(name, standard_tokenizer,
                        [lowercase_filter, make_stop_filter(), porter_stem_filter])
    if name == "cjk":
        # reference CjkAnalyzerProvider: width fold -> lowercase -> bigram
        # -> stop (std tokenizer keeps CJK runs; the bigram filter splits)
        from .unicode_plugins import cjk_bigram_filter, cjk_width_filter
        return Analyzer(name, standard_tokenizer,
                        [cjk_width_filter, lowercase_filter,
                         cjk_bigram_filter, make_stop_filter()])
    if name == "smartcn":
        # reference plugins/analysis-smartcn: dictionary segmentation
        # (jieba-backed here — its dictionary ships in the wheel)
        from .cjk_morph import smartcn_tokenizer
        return Analyzer(name, smartcn_tokenizer, [lowercase_filter])
    if name == "kuromoji":
        # reference plugins/analysis-kuromoji: script-run segmentation +
        # kanji-compound bigrams (dictionary-free approximation; see
        # cjk_morph module docstring for the documented contract)
        from .cjk_morph import (kanji_compound_bigram_filter,
                                kuromoji_lite_tokenizer)
        from .unicode_plugins import cjk_width_filter
        return Analyzer(name, kuromoji_lite_tokenizer,
                        [cjk_width_filter, lowercase_filter,
                         kanji_compound_bigram_filter])
    if name == "nori":
        # reference plugins/analysis-nori: word segmentation + josa strip
        from .cjk_morph import nori_lite_tokenizer
        return Analyzer(name, nori_lite_tokenizer, [lowercase_filter])
    if name == "icu_analyzer":
        # reference plugins/analysis-icu IcuAnalyzerProvider:
        # nfkc_cf normalization + folding over the standard tokenizer
        from .unicode_plugins import (icu_folding_filter,
                                      icu_normalizer_char_filter)
        return Analyzer(name, standard_tokenizer, [icu_folding_filter],
                        [icu_normalizer_char_filter])
    if name == "polish":
        # reference plugins/analysis-stempel PolishAnalyzerProvider
        # (rule-based approximation; see slavic.py module contract)
        from .slavic import make_polish_analyzer
        return make_polish_analyzer()
    if name == "ukrainian":
        # reference plugins/analysis-ukrainian UkrainianAnalyzerProvider
        from .slavic import make_ukrainian_analyzer
        return make_ukrainian_analyzer()
    raise ValueError(f"unknown analyzer [{name}]")


class AnalysisRegistry:
    """Per-index analyzer registry built from index settings."""

    def __init__(self, analysis_settings: dict | None = None):
        self._settings = analysis_settings or {}
        self._cache: dict[str, Analyzer] = {}

    def get(self, name: str) -> Analyzer:
        if name in self._cache:
            return self._cache[name]
        custom = self._settings.get("analyzer", {}).get(name)
        if custom is not None:
            ana = self._build_custom(name, custom)
        else:
            ana = _builtin(name)
        self._cache[name] = ana
        return ana

    def normalizer(self, name: str | None) -> Analyzer:
        """Keyword-field normalizers (reference: keyword normalizers are
        analyzers without a tokenizer). `lowercase` builtin supported."""
        if name is None:
            return Analyzer("identity", keyword_tokenizer, [])
        if name == "lowercase":
            return Analyzer("lowercase", keyword_tokenizer, [lowercase_filter])
        if name.startswith("_icu_collation:"):
            # internal: icu_collation_keyword fields normalize values to
            # collation sort keys (strength encoded in the name)
            from .unicode_plugins import make_collation_key_filter
            return Analyzer(name, keyword_tokenizer,
                            [make_collation_key_filter(
                                name.split(":", 1)[1])])
        custom = self._settings.get("normalizer", {}).get(name)
        if custom is not None:
            filters = [self._resolve_filter(f) for f in custom.get("filter", [])]
            chars = [self._resolve_char(f) for f in custom.get("char_filter", [])]
            return Analyzer(name, keyword_tokenizer, filters, chars)
        raise ValueError(f"unknown normalizer [{name}]")

    def ensure_sayt_chains(self, max_shingle: int) -> None:
        """Register the search_as_you_type analyzer chains (reference
        SearchAsYouTypeFieldMapper): `__sayt_{n}gram` = standard + lowercase
        + fixed-size shingles; `__sayt_prefix` = the same plus edge ngrams
        for the bool_prefix last-term match."""
        ana = self._settings.setdefault("analyzer", {})
        flt = self._settings.setdefault("filter", {})
        for n in range(2, max_shingle + 1):
            flt.setdefault(f"__sayt_shingle{n}", {
                "type": "shingle", "min_shingle_size": n,
                "max_shingle_size": n, "output_unigrams": False})
            ana.setdefault(f"__sayt_{n}gram", {
                "type": "custom", "tokenizer": "standard",
                "filter": ["lowercase", f"__sayt_shingle{n}"]})
        flt.setdefault("__sayt_edge", {
            "type": "edge_ngram", "min_gram": 1, "max_gram": 20})
        ana.setdefault("__sayt_prefix", {
            "type": "custom", "tokenizer": "standard",
            "filter": ["lowercase", "__sayt_edge"]})

    def _resolve_filter(self, name: str) -> TokenFilter:
        custom = self._settings.get("filter", {}).get(name)
        if custom is not None:
            return resolve_token_filter(custom["type"], custom)
        return resolve_token_filter(name)

    def _resolve_char(self, name: str) -> CharFilter:
        custom = self._settings.get("char_filter", {}).get(name)
        if custom is not None:
            return resolve_char_filter(custom["type"], custom)
        return resolve_char_filter(name)

    def _build_custom(self, name: str, cfg: dict) -> Analyzer:
        if cfg.get("type", "custom") != "custom":
            return _builtin(cfg["type"])
        tok_name = cfg.get("tokenizer", "standard")
        tok_custom = self._settings.get("tokenizer", {}).get(tok_name)
        if tok_custom is not None:
            tokenizer = resolve_tokenizer(tok_custom["type"], tok_custom)
        else:
            tokenizer = resolve_tokenizer(tok_name)
        filters = [self._resolve_filter(f) for f in cfg.get("filter", [])]
        chars = [self._resolve_char(f) for f in cfg.get("char_filter", [])]
        return Analyzer(name, tokenizer, filters, chars)
