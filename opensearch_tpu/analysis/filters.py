"""Token filters and char filters. Analog of reference
`modules/analysis-common` filter factories (lowercase, stop, stemmer,
asciifolding, trim, length, shingle, synonym, unique, reverse, truncate) and
char filters (html_strip, mapping, pattern_replace).
"""

from __future__ import annotations

import re
import unicodedata
from typing import Callable, Dict, List, Optional

from .porter import porter_stem
from .tokenizers import Token

TokenFilter = Callable[[List[Token]], List[Token]]
CharFilter = Callable[[str], str]

# Lucene EnglishAnalyzer.ENGLISH_STOP_WORDS_SET
ENGLISH_STOPWORDS = frozenset(
    "a an and are as at be but by for if in into is it no not of on or such that "
    "the their then there these they this to was will with".split()
)


def lowercase_filter(tokens: List[Token]) -> List[Token]:
    return [t.with_text(t.text.lower()) for t in tokens]


def uppercase_filter(tokens: List[Token]) -> List[Token]:
    return [t.with_text(t.text.upper()) for t in tokens]


def make_stop_filter(stopwords=ENGLISH_STOPWORDS) -> TokenFilter:
    """Removes stopwords but preserves position gaps (like Lucene StopFilter
    with enablePositionIncrements), so phrase queries stay correct."""
    stopset = frozenset(stopwords)

    def f(tokens: List[Token]) -> List[Token]:
        return [t for t in tokens if t.text not in stopset]

    return f


def porter_stem_filter(tokens: List[Token]) -> List[Token]:
    # keyword-flagged tokens (keyword_marker / stemmer_override) skip
    # stemming, like Lucene stemmers honoring KeywordAttribute
    return [t if t.keyword else t.with_text(porter_stem(t.text))
            for t in tokens]


def asciifolding_filter(tokens: List[Token]) -> List[Token]:
    def fold(s: str) -> str:
        return unicodedata.normalize("NFKD", s).encode("ascii", "ignore").decode("ascii") or s

    return [t.with_text(fold(t.text)) for t in tokens]


def trim_filter(tokens: List[Token]) -> List[Token]:
    return [t.with_text(t.text.strip()) for t in tokens]


def unique_filter(tokens: List[Token]) -> List[Token]:
    seen, out = set(), []
    for t in tokens:
        if t.text not in seen:
            seen.add(t.text)
            out.append(t)
    return out


def reverse_filter(tokens: List[Token]) -> List[Token]:
    return [t.with_text(t.text[::-1]) for t in tokens]


def make_length_filter(min_len: int = 0, max_len: int = 1 << 30) -> TokenFilter:
    return lambda tokens: [t for t in tokens if min_len <= len(t.text) <= max_len]


def make_truncate_filter(length: int = 10) -> TokenFilter:
    return lambda tokens: [t.with_text(t.text[:length])
                           for t in tokens]


def make_shingle_filter(min_size: int = 2, max_size: int = 2,
                        separator: str = " ", output_unigrams: bool = True) -> TokenFilter:
    def f(tokens: List[Token]) -> List[Token]:
        out = list(tokens) if output_unigrams else []
        for n in range(min_size, max_size + 1):
            for i in range(len(tokens) - n + 1):
                grp = tokens[i:i + n]
                out.append(Token(separator.join(t.text for t in grp),
                                 grp[0].position, grp[0].start_offset,
                                 grp[-1].end_offset))
        out.sort(key=lambda t: (t.position, t.end_offset))
        return out

    return f


def make_synonym_filter(synonyms: List[str]) -> TokenFilter:
    """Solr-format synonym rules: "a, b => c" (replace) or "a, b, c" (expand).
    Expansion emits extra tokens at the same position (like Lucene SynonymGraphFilter
    for single-word synonyms; multi-word synonym graphs are a later round)."""
    replace: Dict[str, List[str]] = {}
    expand: Dict[str, List[str]] = {}
    for rule in synonyms:
        if "=>" in rule:
            lhs, rhs = rule.split("=>")
            targets = [w.strip() for w in rhs.split(",") if w.strip()]
            for w in lhs.split(","):
                replace[w.strip()] = targets
        else:
            group = [w.strip() for w in rule.split(",") if w.strip()]
            for w in group:
                expand[w] = group

    def f(tokens: List[Token]) -> List[Token]:
        out: List[Token] = []
        for t in tokens:
            if t.text in replace:
                for w in replace[t.text]:
                    out.append(t.with_text(w))
            elif t.text in expand:
                for w in expand[t.text]:
                    out.append(t.with_text(w))
            else:
                out.append(t)
        return out

    return f


# ---------------- char filters ----------------

_HTML_TAG_RE = re.compile(r"<[^>]*>")


def html_strip_char_filter(text: str) -> str:
    import html

    return html.unescape(_HTML_TAG_RE.sub(" ", text))


def make_mapping_char_filter(mappings: List[str]) -> CharFilter:
    """Rules like "ph => f"."""
    pairs = []
    for rule in mappings:
        lhs, rhs = rule.split("=>")
        pairs.append((lhs.strip(), rhs.strip()))

    def f(text: str) -> str:
        for a, b in pairs:
            text = text.replace(a, b)
        return text

    return f


def make_pattern_replace_char_filter(pattern: str, replacement: str = "") -> CharFilter:
    compiled = re.compile(pattern)
    return lambda text: compiled.sub(replacement, text)


def make_word_delimiter_filter(generate_word_parts: bool = True,
                               generate_number_parts: bool = True,
                               catenate_words: bool = False,
                               catenate_numbers: bool = False,
                               catenate_all: bool = False,
                               preserve_original: bool = False,
                               split_on_case_change: bool = True,
                               split_on_numerics: bool = True) -> TokenFilter:
    """word_delimiter(_graph): split on intra-word delimiters, case
    transitions and letter/number transitions (reference analysis-common
    WordDelimiterGraphFilterFactory; graph vs non-graph is a position
    bookkeeping difference — both forms split identically here)."""

    def split(text: str) -> List[str]:
        runs: List[str] = []
        cur = ""
        prev_kind = ""
        for ch in text:
            if ch.isalpha():
                kind = "u" if ch.isupper() else "l"
            elif ch.isdigit():
                kind = "d"
            else:
                kind = ""
            if not kind:
                if cur:
                    runs.append(cur)
                cur = ""
                prev_kind = ""
                continue
            boundary = False
            if cur:
                if split_on_case_change and prev_kind == "l" and kind == "u":
                    boundary = True
                if split_on_numerics and prev_kind != kind \
                        and "d" in (prev_kind, kind):
                    boundary = True
            if boundary:
                runs.append(cur)
                cur = ch
            else:
                cur += ch
            prev_kind = kind
        if cur:
            runs.append(cur)
        return runs

    def f(tokens: List[Token]) -> List[Token]:
        out: List[Token] = []
        for t in tokens:
            parts = split(t.text)
            kept = [p for p in parts
                    if (generate_word_parts and not p.isdigit())
                    or (generate_number_parts and p.isdigit())]
            emitted = []
            if preserve_original or not kept:
                emitted.append(t.text)
            emitted.extend(kept)
            if catenate_all and len(parts) > 1:
                emitted.append("".join(parts))
            elif catenate_words and len(parts) > 1 \
                    and all(not p.isdigit() for p in parts):
                emitted.append("".join(parts))
            elif catenate_numbers and len(parts) > 1 \
                    and all(p.isdigit() for p in parts):
                emitted.append("".join(parts))
            seen = set()
            for e in emitted:
                if e and e not in seen:
                    seen.add(e)
                    out.append(t.with_text(e))
        return out
    return f


def make_pattern_capture_filter(patterns: List[str],
                                preserve_original: bool = True
                                ) -> TokenFilter:
    compiled = [re.compile(p) for p in patterns]

    def f(tokens: List[Token]) -> List[Token]:
        out: List[Token] = []
        for t in tokens:
            emitted = [t.text] if preserve_original else []
            for pat in compiled:
                for m in pat.finditer(t.text):
                    if m.groups():
                        emitted.extend(g for g in m.groups() if g)
                    else:
                        emitted.append(m.group(0))
            seen = set()
            for e in emitted:
                if e and e not in seen:
                    seen.add(e)
                    out.append(t.with_text(e))
        return out
    return f


_ELISION_DEFAULT = ["l", "m", "t", "qu", "n", "s", "j"]


def make_elision_filter(articles=None) -> TokenFilter:
    arts = tuple(a.lower() + "'" for a in (articles or _ELISION_DEFAULT))

    def f(tokens: List[Token]) -> List[Token]:
        out = []
        for t in tokens:
            text = t.text
            low = text.lower().replace("’", "'")
            for a in arts:
                if low.startswith(a):
                    text = text[len(a):]
                    break
            if text:
                out.append(t.with_text(text))
        return out
    return f


def make_ngram_token_filter(min_gram: int = 1, max_gram: int = 2
                            ) -> TokenFilter:
    def f(tokens: List[Token]) -> List[Token]:
        out = []
        for t in tokens:
            for n in range(min_gram, max_gram + 1):
                for i in range(0, max(len(t.text) - n + 1, 0)):
                    out.append(t.with_text(t.text[i:i + n]))
        return out
    return f


def make_edge_ngram_token_filter(min_gram: int = 1, max_gram: int = 2
                                 ) -> TokenFilter:
    def f(tokens: List[Token]) -> List[Token]:
        out = []
        for t in tokens:
            for n in range(min_gram, min(max_gram, len(t.text)) + 1):
                out.append(t.with_text(t.text[:n]))
        return out
    return f


def make_keyword_marker_filter(keywords: List[str],
                               ignore_case: bool = False) -> TokenFilter:
    """Sets the token keyword flag (Lucene KeywordMarkerFilter): the flag
    survives later text transforms and stemmers skip flagged tokens."""
    kw = frozenset(k.lower() for k in keywords) if ignore_case \
        else frozenset(keywords)

    def f(tokens: List[Token]) -> List[Token]:
        out = []
        for t in tokens:
            probe = t.text.lower() if ignore_case else t.text
            if probe in kw and not t.keyword:
                nt = t.with_text(t.text)
                nt.keyword = True
                out.append(nt)
            else:
                out.append(t)
        return out
    return f


def make_stemmer_override_filter(rules) -> TokenFilter:
    """"running => run" rules (list of strings or a parsed {src: dst}
    dict) applied before/instead of the stemmer."""
    if isinstance(rules, dict):
        table = dict(rules)
    else:
        table = {}
        for r in rules:
            if "=>" in r:
                src, dst = r.split("=>", 1)
                table[src.strip()] = dst.strip()

    def f(tokens: List[Token]) -> List[Token]:
        out = []
        for t in tokens:
            if t.text in table:
                nt = t.with_text(table[t.text])
                nt.keyword = True    # overridden => later stemmers skip
                out.append(nt)
            else:
                out.append(t)
        return out
    return f


def make_limit_filter(max_token_count: int = 1) -> TokenFilter:
    return lambda tokens: tokens[:max_token_count]


def decimal_digit_filter(tokens: List[Token]) -> List[Token]:
    """Fold unicode digits to latin 0-9 (reference DecimalDigitFilter)."""
    def fold(s: str) -> str:
        return "".join(str(unicodedata.digit(c)) if c.isdigit() else c
                       for c in s)
    return [t.with_text(fold(t.text))
            for t in tokens]


def apostrophe_filter(tokens: List[Token]) -> List[Token]:
    """Strip everything after an apostrophe (reference ApostropheFilter)."""
    out = []
    for t in tokens:
        text = t.text.split("'")[0].split("’")[0]
        if text:
            out.append(t.with_text(text))
    return out


def resolve_token_filter(name: str, params: dict | None = None) -> TokenFilter:
    params = params or {}
    simple: Dict[str, TokenFilter] = {
        "lowercase": lowercase_filter,
        "uppercase": uppercase_filter,
        "porter_stem": porter_stem_filter,
        "stemmer": porter_stem_filter,
        "asciifolding": asciifolding_filter,
        "trim": trim_filter,
        "unique": unique_filter,
        "reverse": reverse_filter,
        "decimal_digit": decimal_digit_filter,
        "apostrophe": apostrophe_filter,
        "flatten_graph": lambda tokens: tokens,  # positions already linear
    }
    if name in simple:
        return simple[name]
    if name in ("word_delimiter", "word_delimiter_graph"):
        return make_word_delimiter_filter(
            generate_word_parts=params.get("generate_word_parts", True),
            generate_number_parts=params.get("generate_number_parts", True),
            catenate_words=params.get("catenate_words", False),
            catenate_numbers=params.get("catenate_numbers", False),
            catenate_all=params.get("catenate_all", False),
            preserve_original=params.get("preserve_original", False),
            split_on_case_change=params.get("split_on_case_change", True),
            split_on_numerics=params.get("split_on_numerics", True))
    if name == "pattern_capture":
        return make_pattern_capture_filter(
            params.get("patterns", []),
            params.get("preserve_original", True))
    if name == "elision":
        return make_elision_filter(params.get("articles"))
    if name == "ngram":
        return make_ngram_token_filter(int(params.get("min_gram", 1)),
                                       int(params.get("max_gram", 2)))
    if name == "edge_ngram":
        return make_edge_ngram_token_filter(int(params.get("min_gram", 1)),
                                            int(params.get("max_gram", 2)))
    if name == "keyword_marker":
        return make_keyword_marker_filter(params.get("keywords", []),
                                          bool(params.get("ignore_case",
                                                          False)))
    if name == "stemmer_override":
        return make_stemmer_override_filter(params.get("rules", []))
    if name == "limit":
        return make_limit_filter(int(params.get("max_token_count", 1)))
    if name == "synonym_graph":
        return make_synonym_filter(params.get("synonyms", []))
    if name == "stop":
        sw = params.get("stopwords", "_english_")
        return make_stop_filter(ENGLISH_STOPWORDS if sw == "_english_" else sw)
    if name == "length":
        return make_length_filter(params.get("min", 0), params.get("max", 1 << 30))
    if name == "truncate":
        return make_truncate_filter(params.get("length", 10))
    if name == "shingle":
        return make_shingle_filter(params.get("min_shingle_size", 2),
                                   params.get("max_shingle_size", 2),
                                   params.get("token_separator", " "),
                                   params.get("output_unigrams", True))
    if name == "synonym":
        return make_synonym_filter(params.get("synonyms", []))
    if name in ("icu_folding", "icu_normalizer", "cjk_width", "cjk_bigram"):
        from .unicode_plugins import (cjk_bigram_filter, cjk_width_filter,
                                      icu_folding_filter,
                                      icu_normalizer_filter)
        return {"icu_folding": icu_folding_filter,
                "icu_normalizer": icu_normalizer_filter,
                "cjk_width": cjk_width_filter,
                "cjk_bigram": cjk_bigram_filter}[name]
    if name == "icu_transform":
        from .unicode_plugins import make_icu_transform_filter
        return make_icu_transform_filter(params.get("id", "Any-Latin"))
    if name == "phonetic":
        from .phonetic import make_phonetic_filter
        return make_phonetic_filter(params.get("encoder", "metaphone"),
                                    bool(params.get("replace", True)))
    if name == "polish_stem":
        from .slavic import polish_stem_filter
        return polish_stem_filter
    if name == "ukrainian_stem":
        from .slavic import ukrainian_stem_filter
        return ukrainian_stem_filter
    raise ValueError(f"unknown token filter [{name}]")


def resolve_char_filter(name: str, params: dict | None = None) -> CharFilter:
    params = params or {}
    if name == "html_strip":
        return html_strip_char_filter
    if name == "mapping":
        return make_mapping_char_filter(params.get("mappings", []))
    if name == "pattern_replace":
        return make_pattern_replace_char_filter(params.get("pattern", ""),
                                                params.get("replacement", ""))
    if name == "icu_normalizer":
        from .unicode_plugins import icu_normalizer_char_filter
        return icu_normalizer_char_filter
    raise ValueError(f"unknown char filter [{name}]")
