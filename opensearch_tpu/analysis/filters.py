"""Token filters and char filters. Analog of reference
`modules/analysis-common` filter factories (lowercase, stop, stemmer,
asciifolding, trim, length, shingle, synonym, unique, reverse, truncate) and
char filters (html_strip, mapping, pattern_replace).
"""

from __future__ import annotations

import re
import unicodedata
from typing import Callable, Dict, List

from .porter import porter_stem
from .tokenizers import Token

TokenFilter = Callable[[List[Token]], List[Token]]
CharFilter = Callable[[str], str]

# Lucene EnglishAnalyzer.ENGLISH_STOP_WORDS_SET
ENGLISH_STOPWORDS = frozenset(
    "a an and are as at be but by for if in into is it no not of on or such that "
    "the their then there these they this to was will with".split()
)


def lowercase_filter(tokens: List[Token]) -> List[Token]:
    return [Token(t.text.lower(), t.position, t.start_offset, t.end_offset) for t in tokens]


def uppercase_filter(tokens: List[Token]) -> List[Token]:
    return [Token(t.text.upper(), t.position, t.start_offset, t.end_offset) for t in tokens]


def make_stop_filter(stopwords=ENGLISH_STOPWORDS) -> TokenFilter:
    """Removes stopwords but preserves position gaps (like Lucene StopFilter
    with enablePositionIncrements), so phrase queries stay correct."""
    stopset = frozenset(stopwords)

    def f(tokens: List[Token]) -> List[Token]:
        return [t for t in tokens if t.text not in stopset]

    return f


def porter_stem_filter(tokens: List[Token]) -> List[Token]:
    return [Token(porter_stem(t.text), t.position, t.start_offset, t.end_offset) for t in tokens]


def asciifolding_filter(tokens: List[Token]) -> List[Token]:
    def fold(s: str) -> str:
        return unicodedata.normalize("NFKD", s).encode("ascii", "ignore").decode("ascii") or s

    return [Token(fold(t.text), t.position, t.start_offset, t.end_offset) for t in tokens]


def trim_filter(tokens: List[Token]) -> List[Token]:
    return [Token(t.text.strip(), t.position, t.start_offset, t.end_offset) for t in tokens]


def unique_filter(tokens: List[Token]) -> List[Token]:
    seen, out = set(), []
    for t in tokens:
        if t.text not in seen:
            seen.add(t.text)
            out.append(t)
    return out


def reverse_filter(tokens: List[Token]) -> List[Token]:
    return [Token(t.text[::-1], t.position, t.start_offset, t.end_offset) for t in tokens]


def make_length_filter(min_len: int = 0, max_len: int = 1 << 30) -> TokenFilter:
    return lambda tokens: [t for t in tokens if min_len <= len(t.text) <= max_len]


def make_truncate_filter(length: int = 10) -> TokenFilter:
    return lambda tokens: [Token(t.text[:length], t.position, t.start_offset, t.end_offset)
                           for t in tokens]


def make_shingle_filter(min_size: int = 2, max_size: int = 2,
                        separator: str = " ", output_unigrams: bool = True) -> TokenFilter:
    def f(tokens: List[Token]) -> List[Token]:
        out = list(tokens) if output_unigrams else []
        for n in range(min_size, max_size + 1):
            for i in range(len(tokens) - n + 1):
                grp = tokens[i:i + n]
                out.append(Token(separator.join(t.text for t in grp), grp[0].position,
                                 grp[0].start_offset, grp[-1].end_offset))
        out.sort(key=lambda t: (t.position, t.end_offset))
        return out

    return f


def make_synonym_filter(synonyms: List[str]) -> TokenFilter:
    """Solr-format synonym rules: "a, b => c" (replace) or "a, b, c" (expand).
    Expansion emits extra tokens at the same position (like Lucene SynonymGraphFilter
    for single-word synonyms; multi-word synonym graphs are a later round)."""
    replace: Dict[str, List[str]] = {}
    expand: Dict[str, List[str]] = {}
    for rule in synonyms:
        if "=>" in rule:
            lhs, rhs = rule.split("=>")
            targets = [w.strip() for w in rhs.split(",") if w.strip()]
            for w in lhs.split(","):
                replace[w.strip()] = targets
        else:
            group = [w.strip() for w in rule.split(",") if w.strip()]
            for w in group:
                expand[w] = group

    def f(tokens: List[Token]) -> List[Token]:
        out: List[Token] = []
        for t in tokens:
            if t.text in replace:
                for w in replace[t.text]:
                    out.append(Token(w, t.position, t.start_offset, t.end_offset))
            elif t.text in expand:
                for w in expand[t.text]:
                    out.append(Token(w, t.position, t.start_offset, t.end_offset))
            else:
                out.append(t)
        return out

    return f


# ---------------- char filters ----------------

_HTML_TAG_RE = re.compile(r"<[^>]*>")


def html_strip_char_filter(text: str) -> str:
    import html

    return html.unescape(_HTML_TAG_RE.sub(" ", text))


def make_mapping_char_filter(mappings: List[str]) -> CharFilter:
    """Rules like "ph => f"."""
    pairs = []
    for rule in mappings:
        lhs, rhs = rule.split("=>")
        pairs.append((lhs.strip(), rhs.strip()))

    def f(text: str) -> str:
        for a, b in pairs:
            text = text.replace(a, b)
        return text

    return f


def make_pattern_replace_char_filter(pattern: str, replacement: str = "") -> CharFilter:
    compiled = re.compile(pattern)
    return lambda text: compiled.sub(replacement, text)


def resolve_token_filter(name: str, params: dict | None = None) -> TokenFilter:
    params = params or {}
    simple: Dict[str, TokenFilter] = {
        "lowercase": lowercase_filter,
        "uppercase": uppercase_filter,
        "porter_stem": porter_stem_filter,
        "stemmer": porter_stem_filter,
        "asciifolding": asciifolding_filter,
        "trim": trim_filter,
        "unique": unique_filter,
        "reverse": reverse_filter,
    }
    if name in simple:
        return simple[name]
    if name == "stop":
        sw = params.get("stopwords", "_english_")
        return make_stop_filter(ENGLISH_STOPWORDS if sw == "_english_" else sw)
    if name == "length":
        return make_length_filter(params.get("min", 0), params.get("max", 1 << 30))
    if name == "truncate":
        return make_truncate_filter(params.get("length", 10))
    if name == "shingle":
        return make_shingle_filter(params.get("min_shingle_size", 2),
                                   params.get("max_shingle_size", 2),
                                   params.get("token_separator", " "),
                                   params.get("output_unigrams", True))
    if name == "synonym":
        return make_synonym_filter(params.get("synonyms", []))
    raise ValueError(f"unknown token filter [{name}]")


def resolve_char_filter(name: str, params: dict | None = None) -> CharFilter:
    params = params or {}
    if name == "html_strip":
        return html_strip_char_filter
    if name == "mapping":
        return make_mapping_char_filter(params.get("mappings", []))
    if name == "pattern_replace":
        return make_pattern_replace_char_filter(params.get("pattern", ""),
                                                params.get("replacement", ""))
    raise ValueError(f"unknown char filter [{name}]")
