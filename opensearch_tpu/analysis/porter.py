"""Classic Porter stemming algorithm (Porter 1980), implemented from the
published algorithm description. Analog of reference
`modules/analysis-common/.../StemmerTokenFilterFactory.java` ("porter"/"english").
"""

from __future__ import annotations

_VOWELS = "aeiou"


def _is_cons(word: str, i: int) -> bool:
    c = word[i]
    if c in _VOWELS:
        return False
    if c == "y":
        return i == 0 or not _is_cons(word, i - 1)
    return True


def _measure(stem: str) -> int:
    """Number of VC sequences in [C](VC)^m[V]."""
    m, i, n = 0, 0, len(stem)
    while i < n and _is_cons(stem, i):
        i += 1
    while i < n:
        while i < n and not _is_cons(stem, i):
            i += 1
        if i >= n:
            break
        m += 1
        while i < n and _is_cons(stem, i):
            i += 1
    return m


def _has_vowel(stem: str) -> bool:
    return any(not _is_cons(stem, i) for i in range(len(stem)))


def _ends_double_cons(word: str) -> bool:
    return len(word) >= 2 and word[-1] == word[-2] and _is_cons(word, len(word) - 1)


def _cvc(word: str) -> bool:
    if len(word) < 3:
        return False
    if not (_is_cons(word, len(word) - 3) and not _is_cons(word, len(word) - 2)
            and _is_cons(word, len(word) - 1)):
        return False
    return word[-1] not in "wxy"


def porter_stem(word: str) -> str:  # noqa: C901 — the algorithm is a rule cascade
    if len(word) <= 2:
        return word
    w = word

    # Step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif w.endswith("ss"):
        pass
    elif w.endswith("s"):
        w = w[:-1]

    # Step 1b
    flag = False
    if w.endswith("eed"):
        if _measure(w[:-3]) > 0:
            w = w[:-1]
    elif w.endswith("ed"):
        if _has_vowel(w[:-2]):
            w, flag = w[:-2], True
    elif w.endswith("ing"):
        if _has_vowel(w[:-3]):
            w, flag = w[:-3], True
    if flag:
        if w.endswith(("at", "bl", "iz")):
            w += "e"
        elif _ends_double_cons(w) and not w.endswith(("l", "s", "z")):
            w = w[:-1]
        elif _measure(w) == 1 and _cvc(w):
            w += "e"

    # Step 1c
    if w.endswith("y") and _has_vowel(w[:-1]):
        w = w[:-1] + "i"

    # Step 2
    step2 = [("ational", "ate"), ("tional", "tion"), ("enci", "ence"), ("anci", "ance"),
             ("izer", "ize"), ("bli", "ble"), ("alli", "al"), ("entli", "ent"), ("eli", "e"),
             ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"), ("ator", "ate"),
             ("alism", "al"), ("iveness", "ive"), ("fulness", "ful"), ("ousness", "ous"),
             ("aliti", "al"), ("iviti", "ive"), ("biliti", "ble"), ("logi", "log")]
    for suf, rep in step2:
        if w.endswith(suf):
            if _measure(w[: -len(suf)]) > 0:
                w = w[: -len(suf)] + rep
            break

    # Step 3
    step3 = [("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
             ("ical", "ic"), ("ful", ""), ("ness", "")]
    for suf, rep in step3:
        if w.endswith(suf):
            if _measure(w[: -len(suf)]) > 0:
                w = w[: -len(suf)] + rep
            break

    # Step 4
    step4 = ["al", "ance", "ence", "er", "ic", "able", "ible", "ant", "ement", "ment",
             "ent", "ou", "ism", "ate", "iti", "ous", "ive", "ize"]
    for suf in sorted(step4, key=len, reverse=True):
        if w.endswith(suf):
            stem = w[: -len(suf)]
            if _measure(stem) > 1:
                w = stem
            break
        if w.endswith("ion") and len(w) > 3 and w[-4] in "st" and _measure(w[:-3]) > 1:
            w = w[:-3]
            break

    # Step 5a
    if w.endswith("e"):
        m = _measure(w[:-1])
        if m > 1 or (m == 1 and not _cvc(w[:-1])):
            w = w[:-1]
    # Step 5b
    if _measure(w) > 1 and _ends_double_cons(w) and w.endswith("l"):
        w = w[:-1]

    return w
