"""Polish and Ukrainian analysis — the rule-based rebuild of the
reference's `plugins/analysis-stempel` (StempelPolishStemTokenFilterFactory)
and `plugins/analysis-ukrainian` (UkrainianAnalyzerProvider over
morfologik).

The real plugins are table-driven (Egothor stemmer tables / morfologik
dictionaries) — neither data set exists in this image, so these are
DOCUMENTED APPROXIMATIONS: longest-suffix stemmers over the productive
inflection paradigms plus the standard stopword lists. Same class of
contract as the kuromoji/nori approximations in `cjk_morph.py`: correct
conflation on the regular morphology, no claim of dictionary-level
accuracy on irregulars.
"""

from __future__ import annotations

from typing import List

from .tokenizers import Token

# Productive Polish inflectional suffixes, longest-match-first (noun case
# endings, adjective agreement, verb conjugation, diminutives).
_PL_SUFFIXES = [
    "iesz", "iecie", "iemy", "iłem", "iłam", "iłes", "iłaś", "ałem",
    "ałam", "ałes", "ałaś", "owie", "owych", "owymi", "owego", "owemu",
    "owej", "owym", "ować", "acji", "acja", "acją", "acje", "ość",
    "ości", "ościa", "oscią", "ysta", "ami", "ach", "iej", "ymi", "ego",
    "emu", "ych", "ów", "om", "ow", "em", "ie", "ia", "ią", "ię", "yc",
    "ej", "ym", "im", "ą", "ę", "y", "i", "e", "a", "u", "o",
]

# Productive Ukrainian endings (noun cases, adjective agreement, verbs).
_UK_SUFFIXES = [
    "ювати", "ювання", "ування", "еннями", "очками", "увати", "ення",
    "еням", "ятами", "ості", "істю", "ання", "яння", "ами", "ями",
    "ові", "еві", "ого", "ому", "ими", "іми", "ій", "ів", "ом", "ем",
    "ам", "ям", "ах", "ях", "ою", "ею", "ий", "ій", "ї", "є", "у",
    "ю", "а", "я", "и", "і", "о", "е",
]

_PL_STOPWORDS = frozenset("""
a aby ale by być co czy dla do i jak jest jego jej już lub ma na nie o od
po pod przez się są tak ten to w we z za że
""".split())

_UK_STOPWORDS = frozenset("""
а але б би в від він вона вони воно до з за і й його її як що це та ти ми
ви на не ні по при про у
""".split())


def _suffix_stem(text: str, suffixes: List[str], min_stem: int = 3) -> str:
    low = text.lower()
    for suf in suffixes:
        if low.endswith(suf) and len(low) - len(suf) >= min_stem:
            return text[: len(text) - len(suf)]
    return text


def polish_stem_filter(tokens: List[Token]) -> List[Token]:
    """reference: StempelPolishStemTokenFilterFactory
    (plugins/analysis-stempel) — longest-suffix approximation."""
    return [t if getattr(t, "keyword", False)
            else t.with_text(_suffix_stem(t.text, _PL_SUFFIXES))
            for t in tokens]


def ukrainian_stem_filter(tokens: List[Token]) -> List[Token]:
    """reference: UkrainianAnalyzerProvider's morfologik stemming
    (plugins/analysis-ukrainian) — longest-suffix approximation."""
    return [t if getattr(t, "keyword", False)
            else t.with_text(_suffix_stem(t.text, _UK_SUFFIXES))
            for t in tokens]


def make_polish_analyzer():
    from .analyzers import Analyzer
    from .filters import lowercase_filter, make_stop_filter
    from .tokenizers import standard_tokenizer
    return Analyzer("polish", standard_tokenizer,
                    [lowercase_filter,
                     make_stop_filter(sorted(_PL_STOPWORDS)),
                     polish_stem_filter])


def make_ukrainian_analyzer():
    from .analyzers import Analyzer
    from .filters import lowercase_filter, make_stop_filter
    from .tokenizers import standard_tokenizer
    return Analyzer("ukrainian", standard_tokenizer,
                    [lowercase_filter,
                     make_stop_filter(sorted(_UK_STOPWORDS)),
                     ukrainian_stem_filter])
