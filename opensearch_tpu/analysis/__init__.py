from .analyzers import AnalysisRegistry, Analyzer
from .tokenizers import Token

__all__ = ["AnalysisRegistry", "Analyzer", "Token"]
