"""ICU-class and CJK analysis — the stdlib-unicodedata rebuild of the
reference's language-analysis plugins.

Reference: `plugins/analysis-icu/` (ICUNormalizerCharFilterFactory,
ICUFoldingTokenFilterFactory, ICUNormalizer2TokenFilterFactory) and the
CJK pieces of `modules/analysis-common` (CJKWidthFilterFactory,
CJKBigramFilterFactory, CjkAnalyzerProvider). The real plugins wrap ICU4J;
Python's `unicodedata` provides the same Unicode database operations this
engine needs: NFKC/NFKD normalization, case folding, combining-mark
stripping, and width folding (NFKC subsumes half/full-width mapping).
Transliteration (icu_transform) is out of scope.

All functions are host-side string/token transforms — the device only ever
sees term ids, so language analysis composes with every query/agg path
unchanged.
"""

from __future__ import annotations

import unicodedata
from typing import List

from .tokenizers import Token


# ---------------------------------------------------------------------
# ICU analogs
# ---------------------------------------------------------------------

def icu_normalizer_char_filter(text: str) -> str:
    """nfkc_cf: NFKC normalization + Unicode case folding (the ICU
    plugin's default normalizer) applied BEFORE tokenization."""
    return unicodedata.normalize("NFKC", text).casefold()


def _fold(term: str) -> str:
    """ICU folding: NFKD-decompose, drop combining marks (diacritics in
    any script), recompose, case fold. Broader than asciifolding, which
    only maps the Latin-1/Latin-A supplement."""
    decomposed = unicodedata.normalize("NFKD", term)
    stripped = "".join(ch for ch in decomposed
                       if not unicodedata.combining(ch))
    return unicodedata.normalize("NFKC", stripped).casefold()


def icu_folding_filter(tokens: List[Token]) -> List[Token]:
    return [t.with_text(_fold(t.text)) for t in tokens]


def icu_normalizer_filter(tokens: List[Token]) -> List[Token]:
    """Token-filter form of nfkc_cf (ICUNormalizer2TokenFilterFactory)."""
    return [t.with_text(unicodedata.normalize("NFKC", t.text).casefold())
            for t in tokens]


# ---------------------------------------------------------------------
# CJK analogs
# ---------------------------------------------------------------------

def cjk_width_filter(tokens: List[Token]) -> List[Token]:
    """Full-width ASCII -> half-width, half-width katakana -> full-width:
    exactly the NFKC mapping restricted to width variants; NFKC itself is
    a superset and matches the reference filter on its test corpus."""
    return [t.with_text(unicodedata.normalize("NFKC", t.text))
            for t in tokens]


def _is_cjk(ch: str) -> bool:
    cp = ord(ch)
    return (0x4E00 <= cp <= 0x9FFF or     # CJK unified
            0x3400 <= cp <= 0x4DBF or     # ext A
            0xF900 <= cp <= 0xFAFF or     # compat ideographs
            0x3040 <= cp <= 0x30FF or     # hiragana + katakana
            0xAC00 <= cp <= 0xD7AF)       # hangul syllables


def cjk_bigram_filter(tokens: List[Token]) -> List[Token]:
    """Split runs of CJK characters into overlapping bigrams (reference
    CJKBigramFilter): 'こんにちは' -> こん んに にち ちは. Non-CJK tokens
    pass through; a single CJK char emits as a unigram. Position
    INCREMENTS from the input stream are preserved (a stopword gap stays a
    gap, like Lucene's posIncAtt handling); each extra bigram of one token
    advances the position by 1, shifting everything after it."""
    out: List[Token] = []
    prev_in = None     # previous input token position
    prev_out = -1      # last emitted position
    for t in tokens:
        inc = t.position - prev_in if prev_in is not None else t.position + 1
        prev_in = t.position
        pos = prev_out + max(inc, 1)
        text = t.text
        if len(text) >= 2 and all(_is_cjk(c) for c in text):
            for i in range(len(text) - 1):
                out.append(Token(text[i: i + 2], pos + i,
                                 t.start_offset + i,
                                 t.start_offset + i + 2, t.keyword))
            prev_out = pos + len(text) - 2
        else:
            out.append(Token(text, pos, t.start_offset, t.end_offset,
                             t.keyword))
            prev_out = pos
    return out


# ---------------------------------------------------------------------
# icu_transform (subset) — reference: ICUTransformTokenFilterFactory
# (plugins/analysis-icu). The real plugin exposes arbitrary ICU transliterator
# ids; this rebuild supports the ids seen in practice, composed with ";".
# Unknown ids raise — never silently pass text through.
# ---------------------------------------------------------------------

_CYR2LAT = {
    "а": "a", "б": "b", "в": "v", "г": "g", "д": "d", "е": "e", "ё": "e",
    "ж": "zh", "з": "z", "и": "i", "й": "j", "к": "k", "л": "l", "м": "m",
    "н": "n", "о": "o", "п": "p", "р": "r", "с": "s", "т": "t", "у": "u",
    "ф": "f", "х": "h", "ц": "c", "ч": "ch", "ш": "sh", "щ": "shch",
    "ъ": "", "ы": "y", "ь": "", "э": "e", "ю": "ju", "я": "ja",
    "є": "je", "і": "i", "ї": "ji", "ґ": "g",
}

_GRK2LAT = {
    "α": "a", "β": "b", "γ": "g", "δ": "d", "ε": "e", "ζ": "z", "η": "e",
    "θ": "th", "ι": "i", "κ": "k", "λ": "l", "μ": "m", "ν": "n",
    "ξ": "x", "ο": "o", "π": "p", "ρ": "r", "σ": "s", "ς": "s",
    "τ": "t", "υ": "y", "φ": "ph", "χ": "kh", "ψ": "ps", "ω": "o",
}


def _translit(text: str, table: dict) -> str:
    out = []
    for ch in text:
        low = ch.lower()
        rep = table.get(low)
        if rep is None:
            # accented forms fall back to their decomposed base letter
            # (ICU transliterates e.g. ή the same as η)
            base = unicodedata.normalize("NFD", low)[0]
            rep = table.get(base)
        if rep is None:
            out.append(ch)
        elif ch.isupper():
            out.append(rep.capitalize())
        else:
            out.append(rep)
    return "".join(out)


def _strip_marks(text: str) -> str:
    return unicodedata.normalize("NFC", "".join(
        c for c in unicodedata.normalize("NFD", text)
        if unicodedata.category(c) != "Mn"))


def _latin_ascii(text: str) -> str:
    return "".join(c for c in unicodedata.normalize("NFKD", text)
                   if ord(c) < 128)


_TRANSFORMS = {
    "any-latin": lambda s: _translit(_translit(s, _CYR2LAT), _GRK2LAT),
    "cyrillic-latin": lambda s: _translit(s, _CYR2LAT),
    "greek-latin": lambda s: _translit(s, _GRK2LAT),
    "latin-ascii": _latin_ascii,
    "any-lower": str.lower,
    "any-upper": str.upper,
    "nfd; [:nonspacing mark:] remove; nfc": _strip_marks,
    "nfd": lambda s: unicodedata.normalize("NFD", s),
    "nfc": lambda s: unicodedata.normalize("NFC", s),
    "nfkd": lambda s: unicodedata.normalize("NFKD", s),
    "nfkc": lambda s: unicodedata.normalize("NFKC", s),
    "[:nonspacing mark:] remove": lambda s: "".join(
        c for c in s if unicodedata.category(c) != "Mn"),
}


def make_icu_transform_filter(transform_id: str = "Any-Latin"):
    """Compose the ";"-separated transform id into one token transform.
    The full literal id is tried first (so the canonical accent-strip
    chain "NFD; [:Nonspacing Mark:] Remove; NFC" matches as one unit)."""
    tid = transform_id.strip().lower()
    if tid in _TRANSFORMS:
        steps = [_TRANSFORMS[tid]]
    else:
        steps = []
        for part in tid.split(";"):
            part = part.strip()
            if not part:
                continue
            fn = _TRANSFORMS.get(part)
            if fn is None:
                raise ValueError(
                    f"icu_transform id [{transform_id}] not supported; "
                    f"supported ids: {sorted(_TRANSFORMS)}")
            steps.append(fn)

    def icu_transform(tokens: List[Token]) -> List[Token]:
        out = []
        for t in tokens:
            text = t.text
            for fn in steps:
                text = fn(text)
            out.append(t.with_text(text))
        return out

    return icu_transform


# ---------------------------------------------------------------------
# icu_collation_keyword (plugins/analysis-icu ICUCollationKeywordFieldMapper)
# — collation SORT KEYS approximating the ICU strength cascade: primary
# (base letters) > secondary (accents) > tertiary (case). Within-level
# ordering uses codepoint order rather than DUCET weights (documented
# approximation; the image has no ICU collation tables). Keys are what
# gets indexed and stored in doc values, so term queries, sorting, and
# aggregations all operate in collation space, like the reference.
# ---------------------------------------------------------------------

def collation_key(s: str, strength: str = "tertiary") -> str:
    nfkd = unicodedata.normalize("NFKD", s)
    base = "".join(c for c in nfkd
                   if unicodedata.category(c) != "Mn").casefold()
    if strength == "primary":
        return base
    marks = "".join(c for c in nfkd if unicodedata.category(c) == "Mn")
    if strength == "secondary":
        return f"{base}\x01{marks}"
    case_sig = "".join("1" if c.isupper() else "0" for c in nfkd
                       if unicodedata.category(c) != "Mn")
    return f"{base}\x01{marks}\x01{case_sig}"


def make_collation_key_filter(strength: str = "tertiary"):
    def collation_filter(tokens: List[Token]) -> List[Token]:
        return [t.with_text(collation_key(t.text, strength))
                for t in tokens]
    return collation_filter
