"""CJK morphological analysis — the in-image rebuild of the reference's
smartcn / kuromoji / nori plugins.

Reference: `plugins/analysis-smartcn/.../SmartChineseAnalyzerProvider.java`,
`plugins/analysis-kuromoji/.../KuromojiTokenizerFactory.java`,
`plugins/analysis-nori/.../NoriTokenizerFactory.java`. Those wrap
dictionary-backed morphological analyzers (SmartCN's HMM model, UniDic/
mecab-ko dictionaries). This environment ships no Japanese/Korean
dictionaries, so each language gets the strongest analyzer the image can
support, with the contract documented per analyzer:

- **Chinese (`smartcn`)**: REAL dictionary segmentation via the bundled
  `jieba` package (its dict.txt ships inside the wheel — no downloads).
  Accuracy class matches the reference's SmartCN HMM for search use.
- **Japanese (`kuromoji`)**: dictionary-free SCRIPT-RUN segmentation.
  Japanese interleaves scripts (kanji stems, hiragana inflection/particles,
  katakana loanwords, latin/digits), and script transitions are true word
  boundaries with high precision; long kanji compounds additionally emit
  sliding bigrams so 観光案内 matches 観光 and 案内 queries. This is an
  approximation of morphological analysis (documented; UniDic-class
  accuracy needs a dictionary the image lacks).
- **Korean (`nori`)**: Korean text is space-delimited; the analyzer
  segments on word boundaries, then strips the CLOSED CLASS of trailing
  case particles (josa) and a few copular endings by longest match —
  한국어를 indexes as 한국어, matching nori's default POS-filtered output
  for nominals. Verbal morphology beyond the copula is out of scope.

All are host-side string transforms; the device only sees term ids.
"""

from __future__ import annotations

from typing import List, Optional

from .tokenizers import Token

# ---------------------------------------------------------------------
# Chinese: jieba-backed dictionary segmentation
# ---------------------------------------------------------------------

_JIEBA = None
_JIEBA_FAILED = False


def _jieba():
    global _JIEBA, _JIEBA_FAILED
    if _JIEBA is None and not _JIEBA_FAILED:
        try:
            import jieba
            jieba.setLogLevel(60)          # silence init logging
            _JIEBA = jieba
        except Exception:                   # pragma: no cover - image has it
            _JIEBA_FAILED = True
    return _JIEBA


def smartcn_tokenizer(text: str) -> List[Token]:
    """Dictionary-based Chinese word segmentation (reference smartcn).
    Falls back to script-run tokens if jieba is ever unavailable."""
    jb = _jieba()
    if jb is None:                          # pragma: no cover
        return kuromoji_lite_tokenizer(text)
    out: List[Token] = []
    pos = 0
    # search mode also emits sub-words of long entities (北京故宮博物院 ->
    # 北京/故宮/博物/博物院/北京故宮博物院) so entity-component queries
    # match — the same index-time granularity call smartcn makes
    for word, start, end in jb.tokenize(text, mode="search"):
        w = word.strip()
        if not w or all(not ch.isalnum() for ch in w):
            continue
        out.append(Token(w, pos, start, end))
        pos += 1
    return out


# ---------------------------------------------------------------------
# Japanese: script-run segmentation + kanji-compound bigrams
# ---------------------------------------------------------------------

def _script(ch: str) -> str:
    cp = ord(ch)
    if 0x3040 <= cp <= 0x309F:
        return "hira"
    if 0x30A0 <= cp <= 0x30FF or cp == 0xFF70 or 0xFF66 <= cp <= 0xFF9F:
        # incl. U+FF9E/FF9F halfwidth voiced marks: they continue a
        # halfwidth-katakana word (width folding composes them later)
        return "kata"
    if (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0xF900 <= cp <= 0xFAFF):
        return "kanji"
    if 0xAC00 <= cp <= 0xD7AF or 0x1100 <= cp <= 0x11FF:
        return "hangul"
    if ch.isalnum():
        return "latin"
    return "other"


_KATA_JOIN = "ー・"          # prolonged sound / middle dot continue katakana


def kuromoji_lite_tokenizer(text: str) -> List[Token]:
    """Maximal same-script runs as tokens. Script transitions are word
    boundaries in Japanese orthography (kanji stem | hiragana okurigana/
    particle | katakana loanword | latin). Hiragana runs ARE emitted
    (kuromoji emits particles too; stop filtering is a later stage)."""
    out: List[Token] = []
    pos = 0
    i = 0
    n = len(text)
    while i < n:
        s = _script(text[i])
        if s == "other":
            i += 1
            continue
        j = i + 1
        while j < n and (_script(text[j]) == s
                         or (s == "kata" and text[j] in _KATA_JOIN)):
            j += 1
        out.append(Token(text[i:j], pos, i, j))
        pos += 1
        i = j
    return out


def kanji_compound_bigram_filter(tokens: List[Token]) -> List[Token]:
    """Long kanji compounds (>= 4 chars: 観光案内, 東京都庁舎) also emit
    sliding 2-char bigrams at successive positions so compound queries and
    their components both match — the recall half of what a UniDic
    decompound step would give. 2-3 char kanji tokens pass through whole
    (they are overwhelmingly single words)."""
    out: List[Token] = []
    prev_in: Optional[int] = None
    prev_out = -1
    for t in tokens:
        inc = t.position - prev_in if prev_in is not None else t.position + 1
        prev_in = t.position
        pos = prev_out + max(inc, 1)
        text = t.text
        if len(text) >= 4 and all(_script(c) == "kanji" for c in text):
            for i in range(len(text) - 1):
                out.append(Token(text[i: i + 2], pos + i,
                                 t.start_offset + i,
                                 t.start_offset + i + 2, t.keyword))
            prev_out = pos + len(text) - 2
        else:
            out.append(Token(text, pos, t.start_offset, t.end_offset,
                             t.keyword))
            prev_out = pos
    return out


# ---------------------------------------------------------------------
# Korean: word-boundary segmentation + josa stripping
# ---------------------------------------------------------------------

# closed-class trailing case particles (josa) + copular endings, longest
# match first. Reference nori discards these as POS J*/E* by default.
_JOSA = sorted([
    "은", "는", "이", "가", "을", "를", "의", "에", "에서", "에게", "한테",
    "께", "께서", "으로", "로", "와", "과", "랑", "이랑", "도", "만",
    "부터", "까지", "보다", "처럼", "마다", "조차", "마저", "밖에",
    "이나", "나", "이며", "며", "하고", "에게서", "으로서", "로서",
    "으로써", "로써", "이라고", "라고",
], key=len, reverse=True)

_ENDINGS = sorted(["입니다", "습니다", "합니다", "했습니다", "인", "고",
                   "지만", "면서", "세요", "어요", "아요"],
                  key=len, reverse=True)


def _is_hangul(ch: str) -> bool:
    return 0xAC00 <= ord(ch) <= 0xD7AF


def nori_lite_tokenizer(text: str) -> List[Token]:
    """Space/punct word segmentation, then longest-match stripping of one
    trailing josa (or copular ending) per hangul word: 한국어를 -> 한국어.
    The stripped stem keeps the ORIGINAL offsets (highlighting covers the
    surface form, like nori's compound handling)."""
    out: List[Token] = []
    pos = 0
    i = 0
    n = len(text)
    while i < n:
        if not (text[i].isalnum() or _is_hangul(text[i])):
            i += 1
            continue
        j = i + 1
        while j < n and (text[j].isalnum() or _is_hangul(text[j])):
            j += 1
        word = text[i:j]
        if any(_is_hangul(c) for c in word):
            stem = word
            for suf in _ENDINGS:
                if stem.endswith(suf) and len(stem) - len(suf) >= 1:
                    stem = stem[: -len(suf)]
                    break
            for suf in _JOSA:
                if stem.endswith(suf) and len(stem) - len(suf) >= 1:
                    stem = stem[: -len(suf)]
                    break
            out.append(Token(stem, pos, i, j))
        else:
            out.append(Token(word, pos, i, j))
        pos += 1
        i = j
    return out
