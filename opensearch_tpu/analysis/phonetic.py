"""Phonetic analysis — the pure-python rebuild of the reference's
`plugins/analysis-phonetic` (PhoneticTokenFilterFactory over commons-codec
encoders).

Implemented encoders: soundex, refined_soundex, metaphone, nysiis,
caverphone2, cologne (Kölner Phonetik). The statistical/table-driven ones
the image can't carry (beider_morse, daitch_mokotoff) and double_metaphone
are declined with an explicit error — never silently approximated.

Filter contract (reference PhoneticTokenFilter): each token is replaced by
its encoding, or — with `replace: false` — the original token is kept and
the encoding is emitted at the SAME position (a synonym-style stack), so
phrase queries still align.
"""

from __future__ import annotations

import re
from typing import List

from .tokenizers import Token

_VOWELS = set("AEIOU")


def soundex(word: str) -> str:
    """American Soundex (the commons-codec default): first letter + 3
    digits, H/W transparent between same-coded consonants."""
    w = re.sub(r"[^A-Z]", "", word.upper())
    if not w:
        return ""
    codes = {**dict.fromkeys("BFPV", "1"), **dict.fromkeys("CGJKQSXZ", "2"),
             **dict.fromkeys("DT", "3"), "L": "4",
             **dict.fromkeys("MN", "5"), "R": "6"}
    out = w[0]
    last = codes.get(w[0], "")
    for ch in w[1:]:
        c = codes.get(ch, "")
        if ch in "HW":
            continue              # transparent: do not reset `last`
        if c and c != last:
            out += c
            if len(out) == 4:
                break
        last = c
    return (out + "000")[:4]


def refined_soundex(word: str) -> str:
    """Refined Soundex: finer 9-group coding, no length cap, vowels keep
    a 0 marker between consonant groups."""
    w = re.sub(r"[^A-Z]", "", word.upper())
    if not w:
        return ""
    codes = {**dict.fromkeys("AEIOUYHW", "0"),
             **dict.fromkeys("BP", "1"), **dict.fromkeys("FV", "2"),
             **dict.fromkeys("CKS", "3"), **dict.fromkeys("GJ", "4"),
             **dict.fromkeys("QXZ", "5"), **dict.fromkeys("DT", "6"),
             "L": "7", **dict.fromkeys("MN", "8"), "R": "9"}
    out = w[0]
    last = None
    for ch in w:
        c = codes.get(ch)
        if c is None or c == last:
            continue
        out += c
        last = c
    return out


def metaphone(word: str, max_len: int = 4) -> str:
    """Lawrence Philips' original Metaphone (1990), commons-codec
    behavior, default 4-char cap."""
    w = re.sub(r"[^A-Z]", "", word.upper())
    if not w:
        return ""
    # initial-letter exceptions
    if w[:2] in ("AE", "GN", "KN", "PN", "WR"):
        w = w[1:]
    elif w[:1] == "X":
        w = "S" + w[1:]
    elif w[:2] == "WH":
        w = "W" + w[2:]
    n = len(w)
    out = []
    i = 0
    while i < n and len(out) < max_len:
        ch = w[i]
        prev = w[i - 1] if i > 0 else ""
        nxt = w[i + 1] if i + 1 < n else ""
        nxt2 = w[i + 2] if i + 2 < n else ""
        if ch == prev and ch != "C":
            i += 1
            continue
        if ch in _VOWELS:
            if i == 0:
                out.append(ch)
        elif ch == "B":
            if not (i == n - 1 and prev == "M"):
                out.append("B")
        elif ch == "C":
            if nxt == "I" and nxt2 == "A":
                out.append("X")
            elif nxt == "H":
                if prev == "S":
                    out.append("K")
                else:
                    out.append("X")
                i += 1
            elif nxt in "IEY":
                if prev != "S":
                    out.append("S")
            else:
                out.append("K")
        elif ch == "D":
            if nxt == "G" and nxt2 in "EIY":
                out.append("J")
                i += 2
            else:
                out.append("T")
        elif ch == "G":
            if nxt == "H":
                if i + 2 < n and w[i + 2] in _VOWELS:
                    out.append("K")
                    i += 1
                # silent otherwise (nigh, light): skip both
                else:
                    i += 1
            elif nxt == "N":
                pass                      # GN/GNED: silent
            elif nxt in "EIY":
                out.append("J")
            else:
                out.append("K")
        elif ch == "H":
            if prev in _VOWELS and nxt not in _VOWELS:
                pass
            elif prev in "CSPTG":
                pass
            else:
                out.append("H")
        elif ch in "FJLMNR":
            out.append(ch)
        elif ch == "K":
            if prev != "C":
                out.append("K")
        elif ch == "P":
            if nxt == "H":
                out.append("F")
                i += 1
            else:
                out.append("P")
        elif ch == "Q":
            out.append("K")
        elif ch == "S":
            if nxt == "H":
                out.append("X")
                i += 1
            elif nxt == "I" and nxt2 in ("O", "A"):
                out.append("X")
            else:
                out.append("S")
        elif ch == "T":
            if nxt == "H":
                out.append("0")
                i += 1
            elif nxt == "I" and nxt2 in ("O", "A"):
                out.append("X")
            else:
                out.append("T")
        elif ch == "V":
            out.append("F")
        elif ch == "W":
            if nxt in _VOWELS:
                out.append("W")
        elif ch == "X":
            out.append("K")
            if len(out) < max_len:
                out.append("S")
        elif ch == "Y":
            if nxt in _VOWELS:
                out.append("Y")
        elif ch == "Z":
            out.append("S")
        i += 1
    return "".join(out[:max_len])


def nysiis(word: str) -> str:
    """NYSIIS (New York State Identification and Intelligence System)."""
    w = re.sub(r"[^A-Z]", "", word.upper())
    if not w:
        return ""
    for pre, rep in (("MAC", "MCC"), ("KN", "NN"), ("K", "C"),
                     ("PH", "FF"), ("PF", "FF"), ("SCH", "SSS")):
        if w.startswith(pre):
            w = rep + w[len(pre):]
            break
    for suf, rep in (("EE", "Y"), ("IE", "Y"), ("DT", "D"), ("RT", "D"),
                     ("RD", "D"), ("NT", "D"), ("ND", "D")):
        if w.endswith(suf):
            w = w[: -len(suf)] + rep
            break
    if not w:
        return ""
    key = w[0]
    prev = w[0]
    i = 1
    n = len(w)
    while i < n:
        ch = w[i]
        rep = ch
        if ch in "EIOU":
            rep = "A"
        if w[i:i + 2] == "EV":
            rep = "A"             # EV -> AF handled as A then F next loop
        if ch == "Q":
            rep = "G"
        elif ch == "Z":
            rep = "S"
        elif ch == "M":
            rep = "N"
        if w[i:i + 2] == "KN":
            rep = "N"
            i += 1
        elif ch == "K":
            rep = "C"
        if w[i:i + 3] == "SCH":
            rep = "S"
            i += 2
        elif w[i:i + 2] == "PH":
            rep = "F"
            i += 1
        if ch == "H" and (prev not in "AEIOU"
                          or (i + 1 < n and w[i + 1] not in "AEIOU")):
            rep = prev
        if ch == "W" and prev in "AEIOU":
            rep = prev
        if rep and rep[-1] != key[-1]:
            key += rep[-1]
        prev = rep[-1] if rep else prev
        i += 1
    if key.endswith("S") and len(key) > 1:
        key = key[:-1]
    if key.endswith("AY"):
        key = key[:-2] + "Y"
    if key.endswith("A") and len(key) > 1:
        key = key[:-1]
    return key


def caverphone2(word: str) -> str:
    """Caverphone 2.0 (David Hood, Caversham project) — 10-char keys
    padded with 1."""
    w = re.sub(r"[^a-z]", "", word.lower())
    if not w:
        return ""
    if w.endswith("e"):
        w = w[:-1]
    for pre, rep in (("cough", "cou2f"), ("rough", "rou2f"),
                     ("tough", "tou2f"), ("enough", "enou2f"),
                     ("trough", "trou2f"), ("gn", "2n")):
        if w.startswith(pre):
            w = rep + w[len(pre):]
    if w.endswith("mb"):
        w = w[:-2] + "m2"
    subs = [("cq", "2q"), ("ci", "si"), ("ce", "se"), ("cy", "sy"),
            ("tch", "2ch"), ("c", "k"), ("q", "k"), ("x", "k"), ("v", "f"),
            ("dg", "2g"), ("tio", "sio"), ("tia", "sia"), ("d", "t"),
            ("ph", "fh"), ("b", "p"), ("sh", "s2h"), ("z", "s")]
    for a, bb in subs:
        w = w.replace(a, bb)
    w = re.sub(r"^[aeiou]", "A", w)
    w = re.sub(r"[aeiou]", "3", w)
    w = w.replace("j", "y")
    w = re.sub(r"^y3", "Y3", w)
    w = re.sub(r"^y", "A", w)
    w = w.replace("y", "3")
    w = w.replace("3gh3", "3kh3")
    w = w.replace("gh", "22")
    w = w.replace("g", "k")
    for ch in "stpkfmn":
        w = re.sub(ch + "+", ch.upper(), w)
    w = w.replace("w3", "W3")
    w = w.replace("wh3", "Wh3")
    if w.endswith("w"):
        w = w[:-1] + "3"
    w = w.replace("w", "2")
    w = re.sub(r"^h", "A", w)
    w = w.replace("h", "2")
    w = w.replace("r3", "R3")
    if w.endswith("r"):
        w = w[:-1] + "3"
    w = w.replace("r", "2")
    w = w.replace("l3", "L3")
    if w.endswith("l"):
        w = w[:-1] + "3"
    w = w.replace("l", "2")
    w = w.replace("2", "")
    if w.endswith("3"):
        w = w[:-1] + "A"
    w = w.replace("3", "")
    return (w + "1" * 10)[:10]


def cologne(word: str) -> str:
    """Kölner Phonetik (German). commons-codec ColognePhonetic."""
    w = re.sub(r"[^A-ZÄÖÜß]", "", word.upper())
    w = (w.replace("Ä", "A").replace("Ö", "O").replace("Ü", "U")
          .replace("ß", "SS"))
    if not w:
        return ""
    n = len(w)
    raw = []
    for i, ch in enumerate(w):
        prev = w[i - 1] if i > 0 else ""
        nxt = w[i + 1] if i + 1 < n else ""
        if ch in "AEIJOUY":
            code = "0"
        elif ch == "B":
            code = "1"
        elif ch == "P":
            code = "3" if nxt == "H" else "1"
        elif ch in "DT":
            code = "8" if nxt in "CSZ" else "2"
        elif ch in "FVW":
            code = "3"
        elif ch in "GKQ":
            code = "4"
        elif ch == "C":
            if i == 0:
                code = "4" if nxt in "AHKLOQRUX" else "8"
            elif prev in "SZ":
                code = "8"
            else:
                code = "4" if nxt in "AHKOQUX" else "8"
        elif ch == "X":
            code = "8" if prev in "CKQ" else "48"
        elif ch == "L":
            code = "5"
        elif ch in "MN":
            code = "6"
        elif ch == "R":
            code = "7"
        elif ch in "SZ":
            code = "8"
        elif ch == "H":
            code = ""
        else:
            code = ""
        raw.append(code)
    # collapse runs, drop 0s except leading
    out = []
    last = None
    for code in raw:
        for c in code:
            if c != last:
                out.append(c)
            last = c
    key = "".join(out)
    return key[0] + key[1:].replace("0", "") if key else ""


_ENCODERS = {
    "soundex": soundex,
    "refined_soundex": refined_soundex,
    "metaphone": metaphone,
    "nysiis": nysiis,
    "caverphone2": caverphone2,
    "caverphone": caverphone2,     # the plugin's alias points at 2.0
    "cologne": cologne,
    "koelnerphonetik": cologne,
}

_UNSUPPORTED = ("double_metaphone", "beider_morse", "daitch_mokotoff",
                "haasephonetik")


def make_phonetic_filter(encoder: str = "metaphone", replace: bool = True):
    """reference: PhoneticTokenFilterFactory (plugins/analysis-phonetic).
    `replace: false` stacks the encoding at the original token's position."""
    enc = _ENCODERS.get(encoder)
    if enc is None:
        hint = ("statistical tables not available in this build"
                if encoder in _UNSUPPORTED else "unknown encoder")
        raise ValueError(
            f"phonetic encoder [{encoder}] not supported ({hint}); "
            f"supported: {sorted(set(_ENCODERS))}")

    def phonetic_filter(tokens: List[Token]) -> List[Token]:
        out: List[Token] = []
        for t in tokens:
            code = enc(t.text)
            if not code:
                out.append(t)
                continue
            if replace:
                out.append(t.with_text(code))
            else:
                out.append(t)
                out.append(t.with_text(code))
        return out

    return phonetic_filter
