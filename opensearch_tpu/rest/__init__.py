from .client import ApiError, RestClient

__all__ = ["RestClient", "ApiError"]
