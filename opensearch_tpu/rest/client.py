"""RestClient: the user-facing API façade mirroring the OpenSearch REST
surface (reference `rest/action/*`, `action/admin/*`, and the opensearch-py
client method names). Dict-in / dict-out with the same JSON shapes, HTTP-less.

Doc APIs route through the cluster's write index + murmur3 shard routing;
search fans out over shard searchers and reduces like the coordinator node.
"""

from __future__ import annotations

import copy
import json
import time
import uuid
from typing import Any, Dict, List, Optional

from ..cluster.node import Node
from ..cluster.admin import IndexClosedError
from ..cluster.state import IndexNotFoundError
from ..index.engine import VersionConflictError
from ..ingest.pipeline import DropDocument
from ..search.executor import ShardSearcher, explain_doc, search_shards
from ..search import compiler as C
from ..search import fastpath as _fastpath
from ..search import query_dsl as dsl
from ..search.pipeline import SearchPipelineException
from ..obs import ingest_obs as _iobs
from ..utils.breaker import CircuitBreakingException
from ..utils.tasks import TaskCancelledException
from ..utils.wlm import PressureRejectedException


class ApiError(Exception):
    def __init__(self, status: int, err_type: str, reason: str,
                 headers: Optional[dict] = None):
        super().__init__(reason)
        self.status = status
        self.err_type = err_type
        self.reason = reason
        # extra HTTP response headers (e.g. Retry-After on 429s); the
        # wire layer sends them, dict-level callers can read them
        self.headers = dict(headers or {})

    def body(self) -> dict:
        return {"error": {"type": self.err_type, "reason": self.reason},
                "status": self.status}


def _rejected_429(e) -> ApiError:
    """PressureRejectedException -> 429, carrying the rejecting layer's
    Retry-After hint (scheduler queue drain estimate / remediation TTL)
    as an HTTP header — delay-seconds form, ceil'd, min 1."""
    import math
    headers = {}
    ra = getattr(e, "retry_after_s", None)
    if ra is not None and ra > 0:
        headers["Retry-After"] = str(max(int(math.ceil(ra)), 1))
    return ApiError(429, "rejected_execution_exception", str(e),
                    headers=headers)


def _run_update_script_or_400(script_body, src: dict, meta: dict):
    """Deep-copy `src`, run the update script, map ScriptError to 400.
    The deep copy matters: engine.get() hands back the live stored _source,
    and a script that mutates nested state then sets ctx.op='none' must not
    corrupt the segment in place."""
    import copy

    from ..script import ScriptError, run_update_script
    from ..search.query_dsl import parse_script_spec
    src_str, prm = parse_script_spec(script_body)
    try:
        return run_update_script(src_str, prm, copy.deepcopy(src), meta)
    except ScriptError as e:
        raise ApiError(400, "illegal_argument_exception",
                       f"failed to execute script: {e}")


def _parse_keepalive_s(v, default: float = 60.0) -> float:
    """'1m' / '30s' / '500ms' -> seconds (scroll/PIT keep-alives); invalid
    values are client errors (HTTP 400)."""
    if v is None:
        return default
    if isinstance(v, (int, float)):
        return float(v)
    sv = str(v).strip()
    try:
        for suf, mult in (("micros", 1e-6), ("nanos", 1e-9), ("ms", 0.001),
                          ("s", 1.0), ("m", 60.0), ("h", 3600.0),
                          ("d", 86400.0)):
            if sv.endswith(suf):
                return float(sv[: -len(suf)]) * mult
        return float(sv)
    except ValueError:
        raise ApiError(400, "illegal_argument_exception",
                       f"failed to parse time value [{v}]")


class RestClient:
    def __init__(self, node: Optional[Node] = None,
                 data_path: Optional[str] = None,
                 remote_root: Optional[str] = None):
        self.node = node or Node(data_path=data_path, remote_root=remote_root)
        self.indices = IndicesClient(self)
        self.ingest = IngestClient(self)
        self.snapshot = SnapshotClient(self)
        self.cluster = ClusterClient(self)
        self.cat = CatClient(self)
        self._scrolls: Dict[str, dict] = {}
        self._pits: Dict[str, dict] = {}
        self._stored_scripts: Dict[str, Any] = {}

    # ---------------- document APIs ----------------

    def _svc_for_write(self, index: str, auto_create: bool = True):
        try:
            return self.node.index_service_for_write(index, auto_create)
        except IndexClosedError as e:
            raise ApiError(400, "index_closed_exception", str(e))

    def _check_write_block(self, svc) -> None:
        """index.blocks.write / read_only (set by hand, PUT _settings, or
        the ILM read_only action) reject writes like the reference
        ClusterBlockException."""
        blocks = svc.meta.settings.get("index", {}).get("blocks", {})
        if blocks.get("write") or blocks.get("read_only"):
            raise ApiError(403, "cluster_block_exception",
                           f"index [{svc.meta.name}] blocked by: "
                           f"[FORBIDDEN/8/index write (api)]")

    def index(self, index: str, body: dict, id: Optional[str] = None,
              routing: Optional[str] = None, refresh: bool = False,
              op_type: str = "index", pipeline: Optional[str] = None,
              if_seq_no: Optional[int] = None,
              if_primary_term: Optional[int] = None,
              _no_pipeline: bool = False) -> dict:
        if index in self.node.metadata.data_streams:
            from ..cluster import datastream as dstream
            _map_ds_errors(dstream.check_write, self.node, index, op_type,
                           body)
        svc = self._svc_for_write(index)
        self._check_write_block(svc)
        # update()'s internal rewrite (RMW under the index write lock)
        # must land in the SAME index: no pipelines, no _index redirects
        # (this also matches the reference, where the update's final index
        # op does not re-run ingest pipelines on the merged source)
        if not _no_pipeline:
            pipeline = pipeline or svc.meta.settings.get(
                "index", {}).get("default_pipeline")
        if pipeline and not _no_pipeline:
            try:
                body = self.node.ingest.run(pipeline, dict(body))
            except DropDocument:
                body = None
            if body is None:
                return {"_index": index, "_id": id or "", "result": "noop"}
            # date_index_name (and any processor that rewrites _index)
            # redirects the doc — resolve the new target before routing,
            # and re-authorize it against the ambient request subject
            # (the transport authorized only the ORIGINAL request index)
            new_index = body.pop("_index", None)
            if new_index and new_index != index:
                from ..security.context import authorize_index_if_active
                from ..security.identity import AuthorizationError
                try:
                    authorize_index_if_active(new_index, "write")
                except AuthorizationError as e:
                    # ApiError so bulk reports it PER ITEM (committed
                    # siblings stay committed, like the reference's
                    # per-item security failures)
                    raise ApiError(403, "security_exception", str(e))
                index = new_index
                svc = self._svc_for_write(index)
                self._check_write_block(svc)
        doc_id = id if id is not None else uuid.uuid4().hex[:20]
        t0 = time.monotonic()
        # per-index write serialization at the engine boundary, AFTER
        # alias/data-stream/pipeline-_index resolution picked the final
        # svc — so every transport is covered and two request names that
        # resolve to the same engine share one lock
        with svc.write_lock:
            # re-check under the lock: a concurrent index delete may have
            # popped this svc between resolution and acquisition — fail
            # like the doc write arrived after the delete, never write
            # into an orphaned engine
            if self.node.indices.get(svc.meta.name) is not svc:
                raise IndexNotFoundError(
                    f"no such index [{svc.meta.name}]")
            try:
                res = svc.route(doc_id, routing).index_doc(
                    doc_id, body, routing, if_seq_no, if_primary_term,
                    op_type)
            except VersionConflictError as e:
                raise ApiError(409, "version_conflict_engine_exception",
                               str(e))
            except ValueError as e:
                # document parse failures (bad geo shapes/vectors/strict
                # dynamic mapping) are client errors, reference
                # mapper_parsing_exception
                raise ApiError(400, "mapper_parsing_exception", str(e))
            svc.generation += 1
            if refresh:
                svc.refresh()
        took = time.monotonic() - t0
        self.node.op_counters["index_total"] += 1
        self.node.op_counters["index_time_ms"] += took * 1000.0
        svc.index_slowlog.maybe_log(took, {"_id": doc_id})
        res["_index"] = svc.meta.name
        res["_shards"] = {"total": 1, "successful": 1, "failed": 0}
        return res

    def create(self, index: str, id: str, body: dict, **kw) -> dict:
        return self.index(index, body, id=id, op_type="create", **kw)

    def get(self, index: str, id: str, routing: Optional[str] = None) -> dict:
        svc = self.node.get_index(self.node.metadata.write_index(index))
        self.node.op_counters["get_total"] += 1
        res = svc.route(id, routing).get(id)
        if res is None:
            raise ApiError(404, "document_missing_exception",
                           f"[{id}]: document missing")
        res["_index"] = svc.meta.name
        return res

    def exists(self, index: str, id: str, routing: Optional[str] = None) -> bool:
        try:
            self.get(index, id, routing)
            return True
        except (ApiError, IndexNotFoundError):
            return False

    def mget(self, body: dict, index: Optional[str] = None) -> dict:
        docs = []
        for spec in body.get("docs", []):
            idx = spec.get("_index", index)
            try:
                docs.append(self.get(idx, spec["_id"], spec.get("routing")))
            except (ApiError, IndexNotFoundError):
                docs.append({"_index": idx, "_id": spec["_id"], "found": False})
        return {"docs": docs}

    def delete(self, index: str, id: str, routing: Optional[str] = None,
               refresh: bool = False, if_seq_no: Optional[int] = None,
               if_primary_term: Optional[int] = None) -> dict:
        svc = self.node.get_index(self.node.metadata.write_index(index))
        if svc.meta.state == "close":
            raise ApiError(400, "index_closed_exception",
                           f"closed index [{svc.meta.name}]")
        self._check_write_block(svc)
        with svc.write_lock:
            if self.node.indices.get(svc.meta.name) is not svc:
                raise IndexNotFoundError(
                    f"no such index [{svc.meta.name}]")
            try:
                res = svc.route(id, routing).delete_doc(id, if_seq_no,
                                                        if_primary_term)
            except VersionConflictError as e:
                raise ApiError(409, "version_conflict_engine_exception",
                               str(e))
            svc.generation += 1
            if refresh:
                svc.refresh()
        res["_index"] = svc.meta.name
        if res["result"] == "not_found":
            raise ApiError(404, "document_missing_exception", f"[{id}]: not found")
        return res

    def update(self, index: str, id: str, body: dict, routing: Optional[str] = None,
               refresh: bool = False, **kw) -> dict:
        """Partial-doc update / upsert (reference UpdateHelper)."""
        svc = self._svc_for_write(index)
        self._check_write_block(svc)
        # hold the index's write lock across the WHOLE read-modify-write
        # (reentrant: the nested self.index() re-acquires) so concurrent
        # updates of one doc can't lose each other's changes
        with svc.write_lock:
            return self._update_locked(svc, index, id, body, routing,
                                       refresh, **kw)

    def _update_locked(self, svc, index: str, id: str, body: dict,
                       routing: Optional[str], refresh: bool, **kw) -> dict:
        eng = svc.route(id, routing)
        current = eng.get(id)
        if current is None:
            if body.get("doc_as_upsert") and "doc" in body:
                return self.index(index, body["doc"], id=id, routing=routing,
                                  refresh=refresh, _no_pipeline=True)
            if "upsert" in body:
                upsert_src = dict(body["upsert"])
                if body.get("scripted_upsert") and "script" in body:
                    upsert_src, op = _run_update_script_or_400(
                        body["script"], upsert_src,
                        {"_index": svc.meta.name, "_id": id, "op": "create"})
                    if op in ("none", "delete"):
                        return {"_index": svc.meta.name, "_id": id, "result": "noop"}
                return self.index(index, upsert_src, id=id, routing=routing,
                                  refresh=refresh, _no_pipeline=True)
            raise ApiError(404, "document_missing_exception", f"[{id}]: document missing")
        src = dict(current["_source"])
        if "doc" in body:
            merged = _deep_merge(src, body["doc"])
            if body.get("detect_noop", True) and merged == src:
                return {"_index": svc.meta.name, "_id": id, "result": "noop"}
            return self.index(index, merged, id=id, routing=routing,
                              refresh=refresh, _no_pipeline=True)
        if "script" in body:
            meta = {"_index": svc.meta.name, "_id": id,
                    "_version": current.get("_version", 1),
                    "_routing": routing}
            new_src, op = _run_update_script_or_400(body["script"], src, meta)
            if op == "none":
                return {"_index": svc.meta.name, "_id": id, "result": "noop"}
            if op == "delete":
                return self.delete(index, id, routing=routing, refresh=refresh)
            return self.index(index, new_src, id=id, routing=routing,
                              refresh=refresh, _no_pipeline=True)
        raise ApiError(400, "action_request_validation_exception",
                       "update requires doc, upsert or script")

    def bulk(self, body, index: Optional[str] = None, refresh: bool = False) -> dict:
        """Bulk API. Accepts NDJSON string or a list of alternating
        action/source dicts (reference RestBulkAction)."""
        t0 = time.perf_counter()
        if isinstance(body, str):
            lines = [json.loads(ln) for ln in body.splitlines() if ln.strip()]
        else:
            lines = list(body)
        # indexing pressure admission (reference IndexingPressure): budget
        # in-flight bulk bytes, reject with 429 when saturated
        est_bytes = sum(len(repr(ln)) for ln in lines)
        try:
            self.node.wlm.indexing.acquire(est_bytes)
        except PressureRejectedException as e:
            _iobs.count("indexing.bulk.rejected")
            raise ApiError(429, "rejected_execution_exception", str(e))
        try:
            out = self._bulk_inner(lines, index, refresh)
            if _iobs.enabled():
                _iobs.record_bulk(len(out["items"]), est_bytes,
                                  (time.perf_counter() - t0) * 1000.0)
            return out
        finally:
            self.node.wlm.indexing.release(est_bytes)

    def _bulk_inner(self, lines, index: Optional[str], refresh: bool) -> dict:
        items = []
        errors = False
        touched = set()
        i = 0
        while i < len(lines):
            action_line = lines[i]
            ((action, meta),) = action_line.items()
            idx = meta.get("_index", index)
            doc_id = meta.get("_id")
            routing = meta.get("routing", meta.get("_routing"))
            i += 1
            try:
                if action in ("index", "create"):
                    src = lines[i]; i += 1
                    res = self.index(idx, src, id=doc_id, routing=routing,
                                     op_type="create" if action == "create" else "index")
                    status = 201 if res.get("result") == "created" else 200
                    items.append({action: {**res, "status": status}})
                elif action == "delete":
                    try:
                        res = self.delete(idx, doc_id, routing=routing)
                        items.append({"delete": {**res, "status": 200}})
                    except ApiError as e:
                        if e.status != 404:
                            raise
                        items.append({"delete": {"_index": idx, "_id": doc_id,
                                                 "result": "not_found", "status": 404}})
                elif action == "update":
                    src = lines[i]; i += 1
                    res = self.update(idx, doc_id, src, routing=routing)
                    items.append({"update": {**res, "status": 200}})
                else:
                    raise ApiError(400, "illegal_argument_exception",
                                   f"unknown bulk action [{action}]")
                touched.add(idx)
            except ApiError as e:
                errors = True
                # the per-item error is reported in the response but the
                # request as a whole succeeds — count it or bulk failures
                # are invisible to dashboards (swallowed-exception audit)
                _iobs.count("indexing.bulk.item_failed")
                items.append({action: {"_index": idx, "_id": doc_id,
                                       "status": e.status, "error": e.body()["error"]}})
        if refresh:
            for idx in touched:
                try:
                    svc = self.node.get_index(
                        self.node.metadata.write_index(idx))
                except IndexNotFoundError:
                    continue
                with svc.write_lock:
                    svc.refresh()
        return {"took": 0, "errors": errors, "items": items}

    # ---------------- search APIs ----------------

    def search(self, index: str = "_all", body: Optional[dict] = None,
               scroll: Optional[str] = None, **kw) -> dict:
        body = dict(body or {})
        body.update({k: v for k, v in kw.items() if v is not None})
        # request deadline: the budget is anchored HERE, at REST accept,
        # so scheduler queue wait and every downstream stage spend from
        # the same clock (utils/deadline.py; docs/RESILIENCE.md)
        from ..utils import deadline as _ddl
        _dl_token = None
        if _ddl.current() is None:
            try:
                _dl_obj = _ddl.Deadline.from_body(body)
            except ValueError as e:
                raise ApiError(400, "parsing_exception", str(e))
            if _dl_obj is not None:
                _dl_token = _ddl.set_current(_dl_obj)
        try:
            return self._search_deadlined(index, body, scroll)
        except _ddl.PartialResultsUnacceptable as e:
            raise ApiError(503, "search_phase_execution_exception", str(e))
        finally:
            if _dl_token is not None:
                _ddl.reset_current(_dl_token)

    def _search_deadlined(self, index: str, body: dict,
                          scroll: Optional[str]) -> dict:
        # workload-group admission (reference wlm/): token-bucket rate
        # limit + resource-tracking QueryGroup enforcement
        group = body.pop("_workload_group", None)
        wg = self.node.wlm.group(group)
        try:
            # admission cost > 1 while the remediation actuator holds a
            # tighten_admission action (serving/remediator.py): the
            # token bucket contracts without any config mutation
            wg.admit_search(cost=self.node.remediation.wlm_cost())
        except PressureRejectedException as e:
            # a wlm admission 429 never reaches Node.search — record
            # the rejection against the query's shape here so admission
            # pressure is attributable per workload (obs/insights.py),
            # and mirror it into the ONE consistent rejection name
            # every admission layer shares (docs/SERVING.md)
            from ..obs import insights as _ins
            from ..utils.metrics import METRICS as _m
            _lane = getattr(wg, "lane", "interactive")
            _ins.INSIGHTS.record_rejection(body, _lane,
                                           source="wlm_admission")
            _m.counter(f"serving.lane.{_lane}.rejected").inc()
            raise _rejected_429(e)
        _wg_t0 = time.monotonic()
        if body.get("query") is not None:
            body["query"] = self._resolve_percolate_refs(body["query"])
        pit = body.pop("pit", None)
        # search pipeline: request param / inline body > index default
        sp_param = body.pop("search_pipeline", None)
        phase_ctx: dict = {}
        phase_hook = None
        pipeline = None
        try:
            pipeline = self.node.search_pipelines.resolve(
                sp_param, self._default_search_pipeline(index))
            if pipeline is not None:
                body = pipeline.transform_request(body, phase_ctx)
                phase_hook = pipeline.phase_hook()
        except SearchPipelineException as e:
            raise ApiError(400, "search_pipeline_exception", str(e))
        try:
            if pit is not None:
                resp = self._search_pit(pit, body, phase_hook=phase_hook,
                                        phase_ctx=phase_ctx)
                return self._apply_response_pipeline(pipeline, resp,
                                                     phase_ctx, body)
            # serving-scheduler lane: scroll-initiating searches ride the
            # batch lane; everything else inherits its workload group's
            # lane (interactive preempts batch at flush time)
            lane = ("batch" if scroll
                    else getattr(wg, "lane", "interactive"))
            # remediation admission (serving/remediator.py): while the
            # actuator holds shed actions, the body is re-fingerprinted
            # and matched against the alert's offending shapes — a shed
            # batch-lane shape 429s with Retry-After, an interactive
            # match is demoted to the batch lane for SCHEDULING only
            # (SLIs/insights keep the origin lane: deprioritization
            # must never hide a burn from the SLO that fired it).
            # Inert (one attribute read) while no action is engaged.
            sli_lane = lane
            try:
                lane = self.node.remediation.admit(body, lane)
            except PressureRejectedException as e:
                from ..obs import insights as _ins
                _ins.INSIGHTS.record_rejection(body, lane,
                                               source="remediation")
                raise _rejected_429(e)
            # flight recorder: the REST facade is where a request's
            # timeline begins (rest.accept + wlm lane classification);
            # Node.search reuses the ambient timeline and stamps the
            # engine-side events onto it
            from ..obs import flight_recorder as _fr
            _tl_token = None
            if _fr.RECORDER.enabled and not _fr.current():
                _tl = _fr.RECORDER.start("search", index=index,
                                         node=self.node.node_name)
                _tl_token = _fr.set_current(_tl)
                _fr.RECORDER.record(_tl, "rest.accept", index=index,
                                    group=wg.name, lane=lane)
            try:
                resp = self.node.search(
                    index, body, phase_hook=phase_hook,
                    phase_ctx=phase_ctx,
                    copy_protect=bool(pipeline is not None
                                      and pipeline.response_procs),
                    wlm_lane=lane, sli_lane=sli_lane)
            finally:
                if _tl_token is not None:
                    _fr.reset_current(_tl_token)
        except dsl.QueryParseError as e:
            # malformed DSL is a client error, not an engine crash
            raise ApiError(400, "parsing_exception", str(e))
        except CircuitBreakingException as e:
            raise ApiError(429, "circuit_breaking_exception", str(e))
        except TaskCancelledException as e:
            raise ApiError(400, "task_cancelled_exception", str(e))
        except IndexClosedError as e:
            raise ApiError(400, "index_closed_exception", str(e))
        except PressureRejectedException as e:
            # search backpressure admission control (reference
            # ratelimitting/admissioncontrol); scheduler queue-full
            # rejections carry a queue-depth-derived Retry-After
            raise _rejected_429(e)
        finally:
            # charge the group's resource tracker unconditionally — PIT
            # searches and searches that FAIL after consuming device time
            # must not bypass an enforced QueryGroup cap
            wg.record(time.monotonic() - _wg_t0)
        resp = self._apply_response_pipeline(pipeline, resp, phase_ctx, body)
        if scroll:
            sid = uuid.uuid4().hex
            names, remote_parts = self.node._split_remote_expression(index)
            snapshot = {n: [list(s.segments) for s in self.node.indices[n].shards]
                        for n in names}
            for alias, rnode, rnames in remote_parts:
                for rn in rnames:
                    snapshot[f"{alias}:{rn}"] = [
                        list(s.segments) for s in rnode.indices[rn].shards]
            ka = _parse_keepalive_s(scroll if scroll is not True else None)
            self._scrolls[sid] = {"index": index, "body": body,
                                  "offset": int(body.get("from", 0)) + int(body.get("size", 10)),
                                  "snapshot": snapshot,
                                  "keep_alive": ka,
                                  "expires": time.time() + ka}
            resp["_scroll_id"] = sid
        return resp

    def _default_search_pipeline(self, index: str) -> Optional[str]:
        """`index.search.default_pipeline` — applied only when the search
        targets a single concrete index (reference SearchPipelineService)."""
        try:
            names = self.node.metadata.resolve(index)
        except IndexNotFoundError:
            return None
        if len(names) != 1:
            return None
        s = self.node.indices[names[0]].meta.settings.get("index", {})
        return (s.get("search", {}).get("default_pipeline")
                or s.get("search.default_pipeline"))

    def _apply_response_pipeline(self, pipeline, resp: dict, phase_ctx: dict,
                                 body: dict) -> dict:
        """Mutates resp in place; node.search already deep-copied iff the
        response aliases a request-cache entry (copy_protect)."""
        if pipeline is None or not pipeline.response_procs:
            return resp
        try:
            return pipeline.transform_response(resp, phase_ctx, body)
        except SearchPipelineException as e:
            raise ApiError(400, "search_pipeline_exception", str(e))

    # ---------------- search pipeline CRUD (reference _search/pipeline) ----

    def put_search_pipeline(self, id: str, body: dict) -> dict:
        try:
            self.node.search_pipelines.put(id, body)
        except SearchPipelineException as e:
            raise ApiError(400, "search_pipeline_exception", str(e))
        return {"acknowledged": True}

    def get_search_pipeline(self, id: Optional[str] = None) -> dict:
        try:
            return self.node.search_pipelines.get(id)
        except SearchPipelineException as e:
            raise ApiError(404, "resource_not_found_exception", str(e))

    def delete_search_pipeline(self, id: str) -> dict:
        try:
            self.node.search_pipelines.delete(id)
        except SearchPipelineException as e:
            raise ApiError(404, "resource_not_found_exception", str(e))
        return {"acknowledged": True}

    def _resolve_percolate_refs(self, node):
        """Inline stored-document references before parsing:
        - `{"percolate": {"index", "id"}}` fetches the candidate doc
          (reference TransportPercolateQuery GET step);
        - `{"geo_shape": {field: {"indexed_shape": {index, id, path}}}}`
          fetches the pre-indexed shape (reference GeoShapeQueryBuilder
          circuit through the get action).
        Pure: returns a copied tree; never descends into percolate bodies
        (candidate documents are user content, not DSL)."""
        if isinstance(node, dict):
            out = {}
            for k, v in node.items():
                if k == "percolate" and isinstance(v, dict):
                    if ("document" not in v and "documents" not in v
                            and v.get("index") and v.get("id")):
                        got = self.get(v["index"], v["id"],
                                       routing=v.get("routing"))
                        v = dict(v)
                        v["document"] = got.get("_source", {})
                    out[k] = v
                elif k == "geo_shape" and isinstance(v, dict):
                    out[k] = {fk: self._resolve_indexed_shape(fv)
                              for fk, fv in v.items()}
                else:
                    out[k] = self._resolve_percolate_refs(v)
            return out
        if isinstance(node, list):
            return [self._resolve_percolate_refs(v) for v in node]
        return node

    def _resolve_indexed_shape(self, spec):
        if not (isinstance(spec, dict) and isinstance(
                spec.get("indexed_shape"), dict)):
            return spec
        ref = spec["indexed_shape"]
        if not (ref.get("index") and ref.get("id")):
            raise ApiError(400, "parsing_exception",
                           "[geo_shape] indexed_shape needs [index] and [id]")
        try:
            got = self.get(ref["index"], ref["id"],
                           routing=ref.get("routing"))
        except (ApiError, IndexNotFoundError):
            raise ApiError(400, "illegal_argument_exception",
                           f"indexed shape [{ref['index']}/{ref['id']}] "
                           f"not found")
        src = got.get("_source", {})
        shape = src
        for part in str(ref.get("path", "shape")).split("."):
            shape = shape.get(part) if isinstance(shape, dict) else None
        if shape is None:
            raise ApiError(400, "illegal_argument_exception",
                           f"shape path [{ref.get('path', 'shape')}] not "
                           f"found in indexed document")
        out = {fk: fv for fk, fv in spec.items() if fk != "indexed_shape"}
        out["shape"] = shape
        return out

    def _snapshot_searchers(self, snapshot: Dict[str, list]) -> List[ShardSearcher]:
        """Searchers bound to a scroll/PIT segment snapshot ("alias:index"
        keys resolve through the registered remote cluster)."""
        searchers = []
        for n, shard_segs in snapshot.items():
            node = self.node
            name = n
            if ":" in n and n.split(":", 1)[0] in self.node.remote_clusters:
                alias, name = n.split(":", 1)
                node = self.node.remote_clusters[alias]
            svc = node.indices.get(name)
            if svc is None:
                continue
            for sid, segs in enumerate(shard_segs):
                s = ShardSearcher(svc.shards[sid], shard_id=sid,
                                  similarity=svc.default_sim, index_key=n)
                s._snapshot_segments = segs
                searchers.append(s)
        return searchers

    def _expire_contexts(self) -> None:
        """Lazy keep-alive enforcement (reference: reaper thread)."""
        now = time.time()
        for sid in [k for k, v in self._scrolls.items()
                    if v.get("expires", now + 1) <= now]:
            del self._scrolls[sid]
        for pid in [k for k, v in self._pits.items()
                    if v.get("expires", now + 1) <= now]:
            del self._pits[pid]

    def scroll(self, scroll_id: str, scroll: Optional[str] = None) -> dict:
        self._expire_contexts()
        sctx = self._scrolls.get(scroll_id)
        if sctx is None:
            raise ApiError(404, "search_context_missing_exception",
                           f"No search context found for id [{scroll_id}]")
        ka = (_parse_keepalive_s(scroll) if scroll
              else sctx.get("keep_alive", 60.0))
        sctx["keep_alive"] = ka
        sctx["expires"] = time.time() + ka
        body = dict(sctx["body"])
        body["from"] = sctx["offset"]
        searchers = self._snapshot_searchers(sctx["snapshot"])
        resp = _search_snapshot(searchers, body, sctx["index"])
        sctx["offset"] += int(body.get("size", 10))
        resp["_scroll_id"] = scroll_id
        return resp

    def clear_scroll(self, scroll_id=None, body: Optional[dict] = None) -> dict:
        ids = []
        if scroll_id:
            ids = scroll_id if isinstance(scroll_id, list) else [scroll_id]
        if body:
            bid = body.get("scroll_id", [])
            ids.extend(bid if isinstance(bid, list) else [bid])
        if any(sid in ("_all", "*") for sid in ids):
            n = len(self._scrolls)
            self._scrolls.clear()
            return {"succeeded": True, "num_freed": n}
        n = 0
        for sid in ids:
            if self._scrolls.pop(sid, None) is not None:
                n += 1
        return {"succeeded": True, "num_freed": n}

    def create_pit(self, index: str, keep_alive: str = "1m") -> dict:
        """Point-in-time reader: snapshot of the immutable segment lists
        (reference `action/search/CreatePitAction` — free with immutability)."""
        pid = uuid.uuid4().hex
        names = self.node.metadata.resolve(index)
        snapshot = {n: [list(s.segments) for s in self.node.indices[n].shards]
                    for n in names}
        ka = _parse_keepalive_s(keep_alive)
        self._pits[pid] = {"index": index, "snapshot": snapshot,
                           "creation_time": time.time(),
                           "keep_alive": ka,
                           "expires": time.time() + ka}
        return {"pit_id": pid, "creation_time": int(time.time() * 1000)}

    def delete_pit(self, body: dict) -> dict:
        ids = body.get("pit_id", [])
        ids = ids if isinstance(ids, list) else [ids]
        deleted = [p for p in ids if self._pits.pop(p, None) is not None]
        return {"pits": [{"pit_id": p, "successful": True} for p in deleted]}

    def _search_pit(self, pit: dict, body: dict, phase_hook=None,
                    phase_ctx: Optional[dict] = None) -> dict:
        pit_id = pit["id"]
        self._expire_contexts()
        pctx = self._pits.get(pit_id)
        if pctx is None:
            raise ApiError(404, "search_context_missing_exception",
                           f"Point in time [{pit_id}] not found")
        # per-request keep_alive extends the context (reference behavior)
        ka = (_parse_keepalive_s(pit["keep_alive"])
              if pit.get("keep_alive") else pctx.get("keep_alive", 60.0))
        pctx["keep_alive"] = ka
        pctx["expires"] = time.time() + ka
        searchers = self._snapshot_searchers(pctx["snapshot"])
        resp = _search_snapshot(searchers, body, pctx["index"],
                                phase_hook=phase_hook, phase_ctx=phase_ctx)
        resp["pit_id"] = pit_id
        return resp

    def msearch(self, body: List[dict], index: Optional[str] = None) -> dict:
        pairs = []
        i = 0
        while i < len(body):
            header = body[i]; i += 1
            search_body = body[i]; i += 1
            pairs.append((header.get("index", index or "_all"), search_body))
        # batched TPU path: one index expression -> fast-path-eligible
        # bodies fuse into grouped Pallas kernel launches (grid over
        # queries); the rest come back as None and run per-body below.
        # A search pipeline (explicit or index default) forces the
        # per-body path so each body gets its processors applied
        partial: List[Optional[dict]] = [None] * len(pairs)
        if (pairs and len({idx for idx, _ in pairs}) == 1
                and not any("search_pipeline" in b or "_workload_group" in b
                            for _, b in pairs)
                and not self._default_search_pipeline(pairs[0][0])):
            try:
                resps = self.node.msearch(pairs[0][0],
                                          [b for _, b in pairs])
            except (dsl.QueryParseError, IndexNotFoundError, IndexClosedError,
                    KeyError, TypeError, ValueError, CircuitBreakingException):
                # fall back to the per-body path, which maps errors into
                # per-response error objects
                resps = None
            if resps is not None:
                partial = list(resps)
        todo = [i for i, r in enumerate(partial) if r is None]

        def run_one(i: int) -> dict:
            idx, search_body = pairs[i]
            try:
                return self.search(idx, search_body)
            except (ApiError, IndexNotFoundError) as e:
                return {"error": {"type": type(e).__name__,
                                  "reason": str(e)}}

        if len(todo) > 1:
            # concurrent per-body fallback (reference
            # TransportMultiSearchAction runs items concurrently too):
            # device steps serialize but host work and device round trips
            # overlap across bodies. Runs on the node's named "search"
            # pool (utils/threadpool.py) instead of a throwaway executor —
            # bounded node-wide, counted in _nodes/stats, and the pool's
            # contextvars carry the request's trace span into the workers
            futs = [(i, self.node.thread_pools.pool("search").submit(
                run_one, i)) for i in todo]
            for i, fut in futs:
                partial[i] = fut.result()
        else:
            for i in todo:
                partial[i] = run_one(i)
        for _, b in pairs:
            if isinstance(b, dict):
                # internal mesh-decline marker must not leak into the
                # caller's body dicts (bodies served by the batched kernel
                # path never traverse Node.search, which pops it)
                b.pop("_mesh_declined", None)
        return {"took": 0, "responses": partial}

    # ------ _remotestore/_restore (reference RestoreRemoteStoreAction) -----

    def remotestore_restore(self, body: dict) -> dict:
        """POST /_remotestore/_restore analog: re-materialize indices from
        the node's remote-backed storage mirror. Indices must not exist
        locally (delete/lose them first) — mirroring the reference's
        closed-or-absent requirement."""
        from ..cluster.state import (ClusterStateError, IndexNotFoundError,
                                     ResourceAlreadyExistsError)
        names = body.get("indices", [])
        if isinstance(names, str):
            names = [n.strip() for n in names.split(",") if n.strip()]
        if not names:
            raise ApiError(400, "action_request_validation_exception",
                           "indices is required")
        out = []
        for name in names:
            try:
                out.append(self.node.restore_from_remote(name))
            except ResourceAlreadyExistsError as e:
                raise ApiError(400, "illegal_argument_exception", str(e))
            except IndexNotFoundError as e:
                raise ApiError(404, "index_not_found_exception", str(e))
            except ClusterStateError as e:
                raise ApiError(400, "illegal_argument_exception", str(e))
        return {"remote_store": {"accepted": True, "indices": out}}

    # ---------------- _validate/query (reference ValidateQueryAction) ------

    def validate_query(self, index: str = "_all",
                       body: Optional[dict] = None,
                       explain: bool = False,
                       rewrite: bool = False) -> dict:
        """Parse AND rewrite the query against every resolved index without
        executing it — the verdict never depends on the display flags
        (explain/rewrite only add per-index explanation entries)."""
        body = body or {}
        try:
            names = self.node.metadata.resolve(index)
        except IndexNotFoundError as e:
            raise ApiError(404, "index_not_found_exception", str(e))
        try:
            q = dsl.parse_query(body.get("query", {"match_all": {}}))
        except ValueError as e:   # QueryParseError is a ValueError
            out = {"valid": False,
                   "_shards": {"total": 1, "successful": 1, "failed": 0}}
            if explain:
                out["explanations"] = [{"index": n, "valid": False,
                                        "error": str(e)} for n in names] \
                    or [{"index": index, "valid": False, "error": str(e)}]
            return out
        explanations = []
        all_valid = True
        for n in names:
            svc = self.node.indices[n]
            segs = [s for sh in svc.shards for s in sh.segments]
            ctx = C.ShardContext(svc.mappings, segs, svc.default_sim)
            try:
                detail = C.describe_plan(C.rewrite(q, ctx, scoring=True))
                explanations.append({
                    "index": n, "valid": True,
                    "explanation":
                        f"{detail['type']}({detail['description']})"})
            except ValueError as e:
                all_valid = False
                explanations.append({"index": n, "valid": False,
                                     "error": str(e)})
        out = {"valid": all_valid,
               "_shards": {"total": len(names) or 1,
                           "successful": len(names) or 1, "failed": 0}}
        if explain or rewrite:
            out["explanations"] = explanations
        return out

    # ---------------- cross-cluster search (reference RemoteClusterService)

    def put_remote_cluster(self, alias: str, remote) -> dict:
        """Register a peer cluster for "alias:index" expressions. `remote`
        is another RestClient or Node (in-process peers — the HTTP-less
        analog of `cluster.remote.<alias>.seeds`)."""
        node = getattr(remote, "node", remote)
        if node is self.node:
            raise ApiError(400, "illegal_argument_exception",
                           "cannot register a cluster with itself")
        self.node.remote_clusters[alias] = node
        return {"acknowledged": True}

    def delete_remote_cluster(self, alias: str) -> dict:
        if self.node.remote_clusters.pop(alias, None) is None:
            raise ApiError(404, "resource_not_found_exception",
                           f"remote cluster [{alias}] not found")
        return {"acknowledged": True}

    def remote_info(self) -> dict:
        """GET _remote/info shape."""
        return {alias: {"connected": True, "mode": "in_process",
                        "num_indices": len(n.indices),
                        "cluster_name": n.metadata.cluster_name}
                for alias, n in self.node.remote_clusters.items()}

    # ---------------- node stats + tracing (reference _nodes/stats) --------

    def nodes_stats(self) -> dict:
        """Full per-node stats rollup (reference NodesStatsResponse):
        indices totals + op counters, process mem/cpu, fs, pools,
        breakers, caches, pipelines, wlm, tracing."""
        import resource
        import shutil
        import sys
        n = self.node
        ru = resource.getrusage(resource.RUSAGE_SELF)
        # ru_maxrss: bytes on macOS, KiB on Linux
        rss_mult = 1 if sys.platform == "darwin" else 1024
        try:
            du = shutil.disk_usage(n.data_path or "/")
            fs = {"total": {"total_in_bytes": du.total,
                            "free_in_bytes": du.free,
                            "available_in_bytes": du.free}}
        except OSError:
            fs = {}
        summ = self.indices_summary()
        docs = summ["docs"]
        store = summ["store_in_bytes"]
        seg_count = summ["segments"]
        oc = n.op_counters
        node_block = {
            "name": n.node_name,
            "roles": ["cluster_manager", "data", "ingest"],
            "indices": {
                "docs": {"count": docs},
                "store": {"size_in_bytes": store},
                "segments": {"count": seg_count},
                "search": {"query_total": oc["search_total"],
                           "query_time_in_millis":
                               int(oc["search_time_ms"])},
                "indexing": {"index_total": oc["index_total"],
                             "index_time_in_millis":
                                 int(oc["index_time_ms"])},
                "get": {"total": oc["get_total"]},
                "request_cache": n.request_cache.stats(),
            },
            "process": {
                "mem": {"resident_set_size_in_bytes":
                        ru.ru_maxrss * rss_mult},
                "cpu": {"total_in_millis":
                        int((ru.ru_utime + ru.ru_stime) * 1000)},
            },
            "fs": fs,
            "thread_pool": n.thread_pools.stats(),
            "breakers": n.breakers.stats(),
            "tasks": n.tasks.stats(),
            "wlm": n.wlm.stats(),
            "search_backpressure": n.search_backpressure.stats(),
            # serving scheduler (serving/scheduler.py): queue depth,
            # batch-size / queue-wait percentiles, flush reasons, lanes
            "serving": n.serving.stats(),
            "search_pipelines": n.search_pipelines.stats(),
            "tracing": n.tracer.stats(),
            # flight recorder (obs/flight_recorder.py): ring occupancy,
            # timelines, anomaly-trigger counts, recent dump metadata
            "flight_recorder": n.flight_recorder.stats(),
            # HBM ledger (obs/hbm_ledger.py): attributed device-memory
            # residency by tenant kind, peaks, and breaker-derivation
            # counters — the byte-domain companion to the breakers block.
            # On silicon the snapshot carries the device allocator
            # cross-check (drift beyond threshold has already fired a
            # flight-recorder hbm_drift dump)
            "hbm": self._hbm_block(),
            # device query-phase telemetry: kernel serve/fallback counters
            # incl. pruned-path escalations (the pruning design is only as
            # good as its escalation rate), and the SPMD mesh dispatch
            # share when a mesh service is attached
            "fastpath": dict(_fastpath.STATS),
            # where the phase-2 candidate-union rescore ran and what it
            # cost (host numpy fallback vs batched device launches)
            "fastpath_rescore": _fastpath.rescore_stats(),
            # codec-v2 eager-impact path (search/impactpath.py): serve /
            # escalation ladder counters plus the device block-skip rate
            # (blocks the block-max prune never gathered)
            "impactpath": self._impactpath_block(),
            # hybrid retrieval (search/fusion.py): fused searches by
            # method, sub-query volume, and the coalesced pure-knn batch
            # launch counters (executor._launch_knn_segment)
            "hybridpath": self._hybridpath_block(),
            # unified telemetry (utils/metrics.py): per-stage latency
            # percentiles for every instrumented stage (search phases,
            # fastpath ladder rungs, mesh dispatch, distnode RPCs) and
            # the jit program-cache / compile-vs-execute attribution
            "telemetry": self._telemetry_block(),
            # fault tolerance (docs/RESILIENCE.md): distnode RPC retry /
            # failover / deadline counters, backoff percentiles, and the
            # chaos-harness installation state (cluster/faults.py).
            # Process-global like /_metrics — co-resident test nodes
            # share the rollup
            "resilience": self._resilience_block(),
            # time-series retention ring (obs/timeseries.py): sampler
            # state behind `_nodes/stats/history`
            "timeseries": n.timeseries.stats(),
            # SLO burn-rate engine (obs/slo.py): armed objectives, live
            # burn rates and alert counts (full view at GET /_slo)
            "slo": n.slo.stats(),
            # query insights (obs/insights.py): workload fingerprint
            # sketch occupancy (full view at GET /_insights/top_queries)
            "insights": n.insights.stats(),
            # remediation actuator (serving/remediator.py): live action
            # count + engage/shed totals (full view at GET /_remediation)
            "remediation": n.remediation.stats(),
            # ingest observatory (obs/ingest_obs.py): the whole write
            # path — bulk accept, pipelines, writer buffer, refresh with
            # stage attribution + refresh-to-visible, merge + reorder,
            # flush, translog, replica fan-out. Federated fleet-wide by
            # `DistClusterNode.indexing_stats` (summed counters, MERGED
            # sketches — percentiles never averaged)
            "indexing": self._indexing_block(),
        }
        if n.mesh_service is not None:
            node_block["mesh"] = n.mesh_service.stats()
        return {"cluster_name": n.metadata.cluster_name,
                "nodes": {n.node_name: node_block}}

    @staticmethod
    def _indexing_block() -> dict:
        return _iobs.assemble_block(_iobs.local_parts())

    @staticmethod
    def _impactpath_block() -> dict:
        from ..search import impactpath as _ip
        out = _ip.stats()
        out["block_skip_rate"] = round(_ip.block_skip_rate(), 4)
        return out

    @staticmethod
    def _hybridpath_block() -> dict:
        from ..search import fusion as _fusion
        return _fusion.stats()

    def _hbm_block(self) -> dict:
        out = self.node.hbm_ledger.snapshot()
        try:
            check = self.node.hbm_ledger.check_device()
        except Exception:           # stats probe must never fail a read
            check = None
        if check is not None:
            out["device_check"] = check
        return out

    @staticmethod
    def _resilience_block() -> dict:
        from ..cluster import faults as _faults
        from ..utils.metrics import METRICS

        def c(name):
            return METRICS.counter(name).value
        return {
            "rpc": {"failed": c("dist.rpc.failed"),
                    "retries": c("dist.rpc.retry"),
                    "failovers": c("dist.rpc.failover"),
                    "backoff_ms": METRICS.percentiles(
                        "dist.rpc.backoff_ms")},
            "deadline": {"exhausted": c("dist.deadline.exhausted"),
                         "expired_on_arrival":
                             c("dist.deadline.expired_on_arrival")},
            "shards_failed": c("dist.shard_failed"),
            "publish_failed": c("dist.publish.failed"),
            "refresh_failed": c("dist.refresh.failed"),
            "chaos": _faults.stats(),
        }

    @staticmethod
    def _telemetry_block() -> dict:
        from ..search import compiler as _compiler
        from ..utils.metrics import METRICS
        return {"stages": METRICS.stage_percentiles(),
                "jit": _compiler.jit_attribution()}

    # ------------- fleet observability (docs/OBSERVABILITY.md "fleet") ----

    def indices_summary(self) -> dict:
        """Node-local index totals — one scrape leg of `_cluster/stats`
        (and the `_nodes/stats` indices rollup above)."""
        docs = store = seg_count = 0
        for svc in self.node.indices.values():
            st = svc.stats()
            docs += st["docs"]["count"]
            store += st["store"]["size_in_bytes"]
            seg_count += st["segments"]["count"]
        return {"docs": docs, "store_in_bytes": store,
                "segments": seg_count}

    def cluster_stats(self) -> dict:
        """`GET /_cluster/stats` on an UNclustered node: the same shape
        the distnode federation serves (cluster/distnode.py
        `cluster_stats`), degenerated to a fleet of one — so dashboards
        and tests read one schema everywhere."""
        from ..utils.metrics import METRICS, sketch_snapshot
        wire = METRICS.to_wire()
        name = self.node.node_name
        indices = self.indices_summary()
        return {
            "cluster_name": self.node.metadata.cluster_name,
            "coordinator": name,
            "_nodes": {"total": 1, "successful": 1, "failed": 0},
            "nodes": {name: {"status": "ok",
                             "gauges": wire["gauges"],
                             "counters": wire["counters"],
                             "indices": indices}},
            "indices": indices,
            "counters": wire["counters"],
            "percentiles": {k: sketch_snapshot(w)
                            for k, w in wire["histograms"].items()},
            "histograms": wire["histograms"],
        }

    def metrics_history(self, metric: str, window_s: float = 60.0) -> dict:
        """`GET /_nodes/stats/history` on an unclustered node: the local
        sampler's window for one metric, in the federated response
        shape (obs/timeseries.py)."""
        name = self.node.node_name
        return {"metric": metric, "window_s": float(window_s),
                "_nodes": {"total": 1, "successful": 1, "failed": 0},
                "nodes": {name: self.node.timeseries.history(
                    metric, window_s)}}

    def slo_status(self) -> dict:
        """`GET /_slo`: armed objectives, live burn rates, alert log
        (obs/slo.py)."""
        return self.node.slo.status()

    def insights_top_queries(self, by: str = "latency", n: int = 10,
                             window_s: Optional[float] = None) -> dict:
        """`GET /_insights/top_queries` on an UNclustered node: the
        same schema the distnode federation serves (cluster/distnode.py
        `top_queries_federated`), degenerated to a fleet of one."""
        from ..obs import insights as _ins
        eng = self.node.insights
        try:
            top = eng.top(by=by, n=n, window_s=window_s)
        except ValueError as e:
            raise ApiError(400, "illegal_argument_exception", str(e))
        name = self.node.node_name
        return {"by": by, "n": int(n),
                **({"window_s": float(window_s)}
                   if window_s is not None else {}),
                "capacity": eng.capacity,
                "total_records": eng.sketch.total_records,
                "_nodes": {"total": 1, "successful": 1, "failed": 0},
                "nodes": {name: {"status": "ok"}},
                "top_queries": top}

    def insights_status(self) -> dict:
        """`GET /_insights`: engine state (capacity, entries,
        evictions, window occupancy)."""
        return {"insights": self.node.insights.stats()}

    def remediation_status(self) -> dict:
        """`GET /_remediation` on an UNclustered node: the same schema
        the distnode federation serves (cluster/distnode.py
        `remediation_federated`), degenerated to a fleet of one."""
        name = self.node.node_name
        return {"_nodes": {"total": 1, "successful": 1, "failed": 0},
                "nodes": {name: {"status": "ok",
                                 **self.node.remediation.status()}}}

    def get_traces(self, limit: int = 20) -> dict:
        """Recent completed request traces (reference telemetry in-memory
        span exporter shape)."""
        return {"traces": self.node.tracer.traces(limit)}

    # ------------- flight recorder + hot threads (obs/) -------------

    def flight_recorder(self, dumps: int = 5) -> dict:
        """`GET /_flight_recorder`: ring stats + the most recent dump
        bundles (full timelines, newest first)."""
        rec = self.node.flight_recorder
        return {"recorder": rec.stats(), "dumps": rec.dumps(limit=dumps)}

    def flight_recorder_dump(self, note: Optional[str] = None) -> dict:
        """`POST /_flight_recorder/dump`: manual snapshot — freeze every
        timeline currently in the ring into one bundle."""
        rec = self.node.flight_recorder
        if not rec.enabled:
            raise ApiError(400, "illegal_argument_exception",
                           "flight recorder is disabled on this node "
                           "(OPENSEARCH_TPU_FLIGHT_RECORDER=0)")
        bundle = rec.trigger("manual", None, note=note, force=True)
        return {"acknowledged": True, "dump": bundle}

    def hot_threads(self, snapshots: int = 3, interval_ms: float = 20.0,
                    ignore_idle: bool = True, as_json: bool = False):
        """`GET /_nodes/hot_threads`: live Python stacks of the runtime's
        worker threads (serving dispatcher/completion, named pools, HTTP
        request threads), idle-filtered, sampled `snapshots` times."""
        from ..obs.hot_threads import hot_threads as _ht
        return _ht(node_name=self.node.node_name, snapshots=snapshots,
                   interval_s=interval_ms / 1000.0,
                   ignore_idle=ignore_idle, as_json=as_json)

    # ---------------- tasks API (reference action/admin/cluster/node/tasks) --

    def tasks(self, actions: Optional[str] = None) -> dict:
        return {"nodes": {self.node.node_name: {
            "tasks": {str(t["id"]): t
                      for t in self.node.tasks.list(actions)}}}}

    def cancel_task(self, task_id, reason: str = "by user request") -> dict:
        try:
            tid = int(str(task_id).rsplit(":", 1)[-1])
        except ValueError:
            raise ApiError(404, "resource_not_found_exception",
                           f"task [{task_id}] is not found")
        ok = self.node.tasks.cancel(tid, reason)
        if not ok:
            raise ApiError(404, "resource_not_found_exception",
                           f"task [{task_id}] is not found or not cancellable")
        return {"acknowledged": True}

    # ---------------- lifecycle + workload management ----------------

    def put_lifecycle_policy(self, name: str, body: dict) -> dict:
        try:
            self.node.lifecycle.put_policy(name, body or {})
        except ValueError as e:
            raise ApiError(400, "illegal_argument_exception", str(e))
        return {"acknowledged": True}

    def get_lifecycle_policy(self, name: str) -> dict:
        p = self.node.lifecycle.get_policy(name)
        if p is None:
            raise ApiError(404, "resource_not_found_exception",
                           f"lifecycle policy [{name}] not found")
        return {name: {"policy": p}}

    def lifecycle_explain(self, index: str) -> dict:
        from ..cluster.state import ClusterStateError
        try:
            return self.node.lifecycle.explain(
                self.node.metadata.write_index(index))
        except ClusterStateError as e:
            raise ApiError(400, "illegal_argument_exception", str(e))

    def lifecycle_step(self, now: Optional[float] = None) -> dict:
        """One deterministic ISM tick (the reference runs this on a
        scheduler; callers own the clock here)."""
        return {"actions": self.node.lifecycle.step(now)}

    def rollover(self, alias: str, body: Optional[dict] = None) -> dict:
        """_rollover: roll the alias's (or data stream's) write index when
        ANY condition is met (empty conditions = always; reference
        RolloverRequest)."""
        body = body or {}
        if alias in self.node.metadata.data_streams:
            from ..cluster import datastream as dstream
            old = self.node.metadata.write_index(alias)
            conds = body.get("conditions", {})
            try:
                results = self.node.lifecycle.check_conditions(old, conds)
            except ValueError as e:
                raise ApiError(400, "illegal_argument_exception", str(e))
            rolled = (not conds) or any(results.values())
            if not rolled:
                return {"acknowledged": False, "rolled_over": False,
                        "old_index": old, "new_index": None,
                        "conditions": results}
            out = _map_ds_errors(dstream.rollover_data_stream, self.node,
                                 alias)
            out["conditions"] = results
            return out
        if alias not in self.node.metadata.aliases:
            raise ApiError(400, "illegal_argument_exception",
                           f"rollover target [{alias}] is not an alias")
        from ..cluster.state import ClusterStateError
        try:
            old = self.node.metadata.write_index(alias)
        except ClusterStateError as e:
            raise ApiError(400, "illegal_argument_exception", str(e))
        conds = body.get("conditions", {})
        try:
            results = self.node.lifecycle.check_conditions(old, conds)
        except ValueError as e:
            raise ApiError(400, "illegal_argument_exception", str(e))
        rolled = (not conds) or any(results.values())
        new_index = None
        if rolled:
            new_index = self.node.lifecycle.rollover(alias, old)
        return {"acknowledged": rolled, "rolled_over": rolled,
                "old_index": old, "new_index": new_index,
                "conditions": results}

    def put_workload_group(self, name: str, body: Optional[dict] = None) -> dict:
        body = body or {}
        try:
            self.node.wlm.put_group(name, body.get("search_rate"),
                                    body.get("search_burst"),
                                    body.get("resource_limits"),
                                    body.get("mode", "monitor"),
                                    body.get("lane", "interactive"))
        except ValueError as e:
            raise ApiError(400, "illegal_argument_exception", str(e))
        return {"acknowledged": True}

    # ---------------- search templates (reference modules/lang-mustache) ----

    def put_script(self, id: str, body: dict) -> dict:
        """PUT _scripts/{id}: store a search template / script."""
        script = body.get("script", body)
        self._stored_scripts[id] = script.get("source", script)
        return {"acknowledged": True}

    def get_script(self, id: str) -> dict:
        src = self._stored_scripts.get(id)
        if src is None:
            raise ApiError(404, "resource_not_found_exception",
                           f"unable to find script [{id}]")
        return {"_id": id, "found": True,
                "script": {"lang": "mustache", "source": src}}

    def delete_script(self, id: str) -> dict:
        if self._stored_scripts.pop(id, None) is None:
            raise ApiError(404, "resource_not_found_exception",
                           f"unable to find script [{id}]")
        return {"acknowledged": True}

    def _resolve_template(self, body: dict) -> dict:
        from .templates import TemplateError, render_template
        if body.get("id") is not None:
            src = self._stored_scripts.get(body["id"])
            if src is None:
                raise ApiError(404, "resource_not_found_exception",
                               f"unable to find script [{body['id']}]")
        else:
            src = body.get("source")
            if src is None:
                raise ApiError(400, "action_request_validation_exception",
                               "template is missing")
        try:
            return render_template(src, body.get("params"))
        except TemplateError as e:
            raise ApiError(400, "parsing_exception", str(e))

    def search_template(self, index: str = "_all",
                        body: Optional[dict] = None) -> dict:
        rendered = self._resolve_template(body or {})
        return self.search(index, rendered)

    def render_search_template(self, body: Optional[dict] = None) -> dict:
        return {"template_output": self._resolve_template(body or {})}

    def msearch_template(self, body: List[dict],
                         index: Optional[str] = None) -> dict:
        lines = []
        i = 0
        while i < len(body):
            header = body[i]; i += 1
            tmpl = body[i]; i += 1
            lines.append(header)
            try:
                lines.append(self._resolve_template(tmpl))
            except ApiError as e:
                lines.append({"_template_error": str(e)})
        msb = []
        for j in range(0, len(lines), 2):
            if "_template_error" not in lines[j + 1]:
                msb += [lines[j], lines[j + 1]]
        sub = self.msearch(msb, index=index)["responses"] if msb else []
        responses = []
        si = 0
        for j in range(0, len(lines), 2):
            if "_template_error" in lines[j + 1]:
                responses.append({"error": {
                    "type": "parsing_exception",
                    "reason": lines[j + 1]["_template_error"]}})
            else:
                responses.append(sub[si])
                si += 1
        return {"took": 0, "responses": responses}

    def rank_eval(self, index: str = "_all",
                  body: Optional[dict] = None) -> dict:
        """POST {index}/_rank_eval (reference modules/rank-eval)."""
        from ..search.rank_eval import run_rank_eval
        try:
            return run_rank_eval(self, index, body or {})
        except dsl.QueryParseError as e:
            raise ApiError(400, "parsing_exception", str(e))

    def count(self, index: str = "_all", body: Optional[dict] = None) -> dict:
        body = dict(body or {})
        body["size"] = 0
        body.pop("sort", None)
        if body.get("query") is not None:
            body["query"] = self._resolve_percolate_refs(body["query"])
        resp = self.node.search(index, body)
        return {"count": resp["hits"]["total"]["value"],
                "_shards": resp["_shards"]}

    def explain(self, index: str, id: str, body: dict) -> dict:
        svc = self.node.get_index(self.node.metadata.write_index(index))
        eng = svc.route(id)
        eng_refresh_needed = id in {d.doc_id for d in eng.buffer if d is not None}
        if eng_refresh_needed:
            eng.refresh()
        loc = eng.version_map.get(id)
        if loc is None or loc.in_buffer:
            raise ApiError(404, "document_missing_exception", f"[{id}] missing")
        seg, doc = loc.segment, loc.local_doc
        ctx = C.ShardContext(svc.mappings, eng.segments, svc.default_sim)
        qdict = (self._resolve_percolate_refs(body["query"])
                 if body.get("query") is not None else None)
        lroot = C.rewrite(dsl.parse_query(qdict), ctx, scoring=True)
        expl = explain_doc(lroot, seg, doc, ctx)
        return {"_index": svc.meta.name, "_id": id,
                "matched": expl["value"] > 0, "explanation": expl}

    def field_caps(self, index: str = "_all", fields: str = "*") -> dict:
        names = self.node.metadata.resolve(index)
        pats = fields if isinstance(fields, list) else fields.split(",")
        import fnmatch as fn
        out: Dict[str, dict] = {}
        for n in names:
            svc = self.node.indices[n]
            allf = dict(svc.mappings.fields)
            for f, ft in list(allf.items()):
                for sub, sft in ft.subfields.items():
                    allf[f"{f}.{sub}"] = sft
            for f, ft in allf.items():
                if not any(fn.fnmatch(f, p) for p in pats):
                    continue
                caps = out.setdefault(f, {}).setdefault(ft.type, {
                    "type": ft.type, "searchable": ft.index,
                    "aggregatable": ft.doc_values or ft.type == "text"})
        return {"indices": names, "fields": out}

    def termvectors(self, index: str, id: Optional[str] = None,
                    body: Optional[dict] = None,
                    fields: Optional[List[str]] = None,
                    term_statistics: bool = False,
                    field_statistics: bool = True,
                    positions: bool = True, offsets: bool = True) -> dict:
        """Reference `action/termvectors/TermVectorsRequest.java`: real doc
        or artificial (`body["doc"]`), per-term tokens with positions/
        offsets, optional term statistics (doc_freq/ttf across the index's
        segments), field statistics, and the tf-idf `filter` block."""
        body = body or {}
        fields = fields or body.get("fields")
        term_statistics = bool(body.get("term_statistics", term_statistics))
        field_statistics = bool(body.get("field_statistics",
                                         field_statistics))
        positions = bool(body.get("positions", positions))
        offsets = bool(body.get("offsets", offsets))
        tv_filter = body.get("filter") or {}
        svc = self.node.get_index(self.node.metadata.write_index(index))
        if body.get("doc") is not None:
            src = body["doc"]
            found = True
            resp_id = id or ""
        else:
            if id is None:
                raise ApiError(400, "action_request_validation_exception",
                               "termvectors needs an [id] or a [doc]")
            try:
                doc = self.get(index, id)
            except ApiError:
                return {"_index": svc.meta.name, "_id": id, "found": False}
            src = doc["_source"]
            found = True
            resp_id = id
        segs = [s for sh in svc.shards for s in sh.segments]

        def _stats(fname: str, term: str):
            df = ttf = 0
            for s in segs:
                pb = s.postings.get(fname)
                if pb is None:
                    continue
                r = pb.row(term)
                if r >= 0:
                    a, b = int(pb.starts[r]), int(pb.starts[r + 1])
                    df += b - a
                    ttf += int(pb.tfs[a:b].sum())
            return df, ttf

        out_fields = {}
        for fname, ft in list(svc.mappings.fields.items()):
            if ft.type not in ("text", "keyword", "annotated_text") or \
                    (fields and fname not in fields):
                continue
            vals = _get_source_path(src, fname)
            if vals is None:
                continue
            terms: Dict[str, dict] = {}
            for v in (vals if isinstance(vals, list) else [vals]):
                if ft.type == "keyword":
                    t = terms.setdefault(str(v), {"term_freq": 0})
                    t["term_freq"] += 1
                    continue
                raw_v = str(v)
                annot_spans: list = []
                if ft.type == "annotated_text":
                    from ..index.mappings import parse_annotated_text
                    raw_v, annot_spans = parse_annotated_text(raw_v)
                toks = list(svc.mappings.index_analyzer(ft).analyze(raw_v))
                for (cs, ce, anns) in annot_spans:
                    # annotation values occupy the first covered token's
                    # position/offsets, mirroring the index-time injection
                    tok0 = next((t for t in toks
                                 if cs <= t.start_offset < ce), None)
                    if tok0 is None:
                        continue
                    for a in anns:
                        toks.append(type(tok0)(
                            text=a, position=tok0.position,
                            start_offset=tok0.start_offset,
                            end_offset=tok0.end_offset))
                for tok in toks:
                    t = terms.setdefault(tok.text,
                                         {"term_freq": 0, "tokens": []})
                    t["term_freq"] += 1
                    entry = {}
                    if positions:
                        entry["position"] = tok.position
                    if offsets:
                        entry["start_offset"] = tok.start_offset
                        entry["end_offset"] = tok.end_offset
                    if entry:
                        t["tokens"].append(entry)
            if not terms:
                continue
            ndocs = max(sum(s.live_count for s in segs), 1)
            if term_statistics or tv_filter:
                for term, t in terms.items():
                    df, ttf = _stats(fname, term)
                    if term_statistics:
                        t["doc_freq"] = df
                        t["ttf"] = ttf
                    t["_df"] = df
            if tv_filter:
                import math
                min_tf = int(tv_filter.get("min_term_freq", 1))
                min_df = int(tv_filter.get("min_doc_freq", 1))
                max_df = int(tv_filter.get("max_doc_freq", 1 << 60))
                kept = {}
                for term, t in terms.items():
                    df = t["_df"]
                    if t["term_freq"] < min_tf or df < min_df or df > max_df:
                        continue
                    idf = math.log(1.0 + (ndocs - df + 0.5) / (df + 0.5))
                    kept[term] = (t["term_freq"] * idf, t)
                maxn = tv_filter.get("max_num_terms")
                ranked = sorted(kept.items(), key=lambda kv: -kv[1][0])
                if maxn is not None:
                    ranked = ranked[: int(maxn)]
                terms = {}
                for term, (score, t) in ranked:
                    t["score"] = round(score, 6)
                    terms[term] = t
            for t in terms.values():
                t.pop("_df", None)
            fblock: dict = {"terms": dict(sorted(terms.items()))}
            if field_statistics:
                sum_ttf = sum_df = 0
                for s in segs:
                    pb = s.postings.get(fname)
                    if pb is not None:
                        sum_df += len(pb.doc_ids)
                        sum_ttf += int(pb.tfs.sum())
                doc_count = 0
                for s in segs:
                    if fname in s.text_stats:
                        doc_count += s.text_stats[fname].doc_count
                    elif fname in s.postings:
                        import numpy as _np
                        doc_count += len(_np.unique(
                            s.postings[fname].doc_ids))
                fblock["field_statistics"] = {
                    "sum_doc_freq": sum_df, "doc_count": doc_count,
                    "sum_ttf": sum_ttf}
            out_fields[fname] = fblock
        return {"_index": svc.meta.name, "_id": resp_id, "found": found,
                "term_vectors": out_fields}

    def mtermvectors(self, body: dict, index: Optional[str] = None) -> dict:
        """Reference `action/termvectors/MultiTermVectorsRequest.java`."""
        docs = []
        for spec in body.get("docs", []):
            idx = spec.get("_index", index)
            if idx is None:
                raise ApiError(400, "action_request_validation_exception",
                               "mtermvectors doc needs an [_index]")
            docs.append(self.termvectors(
                idx, spec.get("_id"), body={k: v for k, v in spec.items()
                                            if not k.startswith("_")}))
        return {"docs": docs}

    # ---------------- reindex family ----------------

    def reindex(self, body: dict, refresh: bool = False) -> dict:
        src = body["source"]
        dest = body["dest"]
        query = {"query": src.get("query", {"match_all": {}}), "size": 10000}
        resp = self.search(src["index"], query)
        created = 0
        pipeline = dest.get("pipeline")
        for h in resp["hits"]["hits"]:
            self.index(dest["index"], h["_source"], id=h["_id"], pipeline=pipeline)
            created += 1
        if refresh and created:
            self.node.get_index(self.node.metadata.write_index(dest["index"])).refresh()
        return {"took": resp["took"], "created": created, "updated": 0,
                "total": created, "failures": []}

    def delete_by_query(self, index: str, body: dict, refresh: bool = False) -> dict:
        resp = self.search(index, {"query": body.get("query", {"match_all": {}}),
                                   "size": 10000})
        deleted = 0
        for h in resp["hits"]["hits"]:
            try:
                self.delete(h["_index"] or index, h["_id"])
                deleted += 1
            except ApiError:
                pass
        if refresh:
            for n in self.node.metadata.resolve(index):
                self.node.indices[n].refresh()
        return {"took": resp["took"], "deleted": deleted, "total": deleted,
                "failures": []}

    def update_by_query(self, index: str, body: Optional[dict] = None,
                        refresh: bool = False) -> dict:
        body = body or {}
        resp = self.search(index, {"query": body.get("query", {"match_all": {}}),
                                   "size": 10000})
        updated = 0
        script_body = body.get("script")
        for h in resp["hits"]["hits"]:
            new_src = h["_source"]
            if script_body is not None:
                new_src, op = _run_update_script_or_400(
                    script_body, new_src,
                    {"_index": h["_index"] or index, "_id": h["_id"]})
                if op == "none":
                    continue
                if op == "delete":
                    self.delete(h["_index"] or index, h["_id"])
                    updated += 1
                    continue
            self.index(h["_index"] or index, new_src, id=h["_id"])
            updated += 1
        if refresh:
            for n in self.node.metadata.resolve(index):
                self.node.indices[n].refresh()
        return {"took": resp["took"], "updated": updated, "total": updated,
                "failures": []}


def _search_snapshot(searchers: List[ShardSearcher], body: dict, index: str,
                     phase_hook=None, phase_ctx: Optional[dict] = None) -> dict:
    """Search against snapshotted segment lists (scroll/PIT)."""
    body = dict(body)
    body["_index_name"] = index
    from ..search.executor import _global_stats_contexts, reduce_shard_results
    stats = _global_stats_contexts(searchers)
    results = [s.query_phase(body, segments=s._snapshot_segments, shard_ord=i,
                             stats_ctx=stats[i])
               for i, s in enumerate(searchers)]
    if phase_hook is not None:
        phase_hook(results, body, phase_ctx if phase_ctx is not None else {})
    reduced = reduce_shard_results(results, body)
    by_shard: Dict[int, List] = {}
    for c in reduced["selected"]:
        by_shard.setdefault(c.shard, []).append(c)
    hits_by_key: Dict[tuple, dict] = {}
    for i, r in enumerate(results):
        sel = by_shard.get(r.shard, [])
        if sel:
            for c, h in zip(sel, searchers[i].fetch_phase(r, sel, body)):
                hits_by_key[(c.shard, c.seg_ord, c.local_doc)] = h
    hits = [hits_by_key[(c.shard, c.seg_ord, c.local_doc)]
            for c in reduced["selected"]
            if (c.shard, c.seg_ord, c.local_doc) in hits_by_key]
    resp = {"took": 0, "timed_out": False,
            "_shards": {"total": len(searchers), "successful": len(searchers),
                        "skipped": 0, "failed": 0},
            "hits": {"total": {"value": reduced["total"],
                               "relation": reduced.get("total_rel", "eq")},
                     "max_score": reduced["max_score"], "hits": hits}}
    if reduced["aggs"]:
        resp["aggregations"] = reduced["aggs"]
    return resp


def _deep_merge(base: dict, patch: dict) -> dict:
    out = dict(base)
    for k, v in patch.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _get_source_path(src: dict, path: str):
    node: Any = src
    for p in path.split("."):
        if isinstance(node, dict):
            node = node.get(p)
        else:
            return None
    return node


# =====================================================================
# namespaced sub-clients
# =====================================================================

class IndicesClient:
    def __init__(self, client: RestClient):
        self.c = client

    def create(self, index: str, body: Optional[dict] = None) -> dict:
        return self.c.node.create_index(index, body)

    def delete(self, index: str) -> dict:
        return _map_ds_errors(self.c.node.delete_index, index)

    def exists(self, index: str) -> bool:
        try:
            return bool(self.c.node.metadata.resolve(index, allow_no_indices=False))
        except IndexNotFoundError:
            return False

    def get(self, index: str) -> dict:
        out = {}
        for n in self.c.node.metadata.resolve(index, allow_no_indices=False):
            svc = self.c.node.indices[n]
            aliases = {a: am.indices[n] for a, am in self.c.node.metadata.aliases.items()
                       if n in am.indices}
            out[n] = {"settings": {"index": {**svc.meta.settings.get("index", {}),
                                             "number_of_shards": svc.meta.num_shards,
                                             "uuid": n}},
                      "mappings": svc.mappings.to_dict(),
                      "aliases": aliases}
        return out

    def get_mapping(self, index: str = "_all") -> dict:
        return {n: {"mappings": self.c.node.indices[n].mappings.to_dict()}
                for n in self.c.node.metadata.resolve(index)}

    def put_mapping(self, index: str, body: dict) -> dict:
        for n in self.c.node.metadata.resolve(index, allow_no_indices=False):
            svc = self.c.node.indices[n]
            # mapping merge mutates structures in-flight doc parses read
            with svc.write_lock:
                svc.mappings.merge(body)
                self.c.node._persist_meta(n)
        return {"acknowledged": True}

    def get_settings(self, index: str = "_all") -> dict:
        return {n: {"settings": {"index": self.c.node.indices[n].meta.settings.get("index", {})}}
                for n in self.c.node.metadata.resolve(index)}

    def put_settings(self, index: str, body: dict,
                     preserve_existing: bool = False) -> dict:
        """PUT /{index}/_settings (reference
        TransportUpdateSettingsAction): dynamic settings apply to open
        indices; static settings require the index to be closed; final
        settings never change."""
        return _map_admin_errors(
            self.c.node.update_index_settings, index, body,
            preserve_existing)

    def close(self, index: str) -> dict:
        """POST /{index}/_close (reference TransportCloseIndexAction)."""
        return _map_admin_errors(self.c.node.close_index, index)

    def open(self, index: str) -> dict:
        """POST /{index}/_open (reference TransportOpenIndexAction)."""
        return _map_admin_errors(self.c.node.open_index, index)

    def shrink(self, index: str, target: str,
               body: Optional[dict] = None) -> dict:
        """POST /{index}/_shrink/{target} (TransportResizeAction)."""
        return _map_admin_errors(self.c.node.resize_index, index, target,
                                 "shrink", body)

    def split(self, index: str, target: str,
              body: Optional[dict] = None) -> dict:
        return _map_admin_errors(self.c.node.resize_index, index, target,
                                 "split", body)

    def clone(self, index: str, target: str,
              body: Optional[dict] = None) -> dict:
        return _map_admin_errors(self.c.node.resize_index, index, target,
                                 "clone", body)

    def refresh(self, index: str = "_all") -> dict:
        for n in self.c.node.metadata.resolve(index):
            svc = self.c.node.indices[n]
            with svc.write_lock:
                svc.refresh()
        return {"_shards": {"successful": 1, "failed": 0}}

    def flush(self, index: str = "_all") -> dict:
        n_shards = 0
        for n in self.c.node.metadata.resolve(index):
            svc = self.c.node.indices[n]
            n_shards += len(svc.shards)
            with svc.write_lock:
                svc.flush()
        return {"_shards": {"successful": n_shards, "failed": 0}}

    def forcemerge(self, index: str = "_all", max_num_segments: int = 1) -> dict:
        for n in self.c.node.metadata.resolve(index):
            svc = self.c.node.indices[n]
            with svc.write_lock:
                svc.force_merge(max_num_segments)
        return {"_shards": {"successful": 1, "failed": 0}}

    def stats(self, index: str = "_all") -> dict:
        out = {n: self.c.node.indices[n].stats()
               for n in self.c.node.metadata.resolve(index)}
        total = {"docs": {"count": sum(v["docs"]["count"] for v in out.values())}}
        return {"_all": {"primaries": total, "total": total},
                "indices": {n: {"primaries": v, "total": v} for n, v in out.items()}}

    def analyze(self, index: Optional[str] = None, body: Optional[dict] = None) -> dict:
        body = body or {}
        text = body.get("text", "")
        texts = text if isinstance(text, list) else [text]
        if index is not None:
            svc = self.c.node.get_index(self.c.node.metadata.write_index(index))
            registry = svc.mappings.analysis
            if "field" in body:
                ft = svc.mappings.resolve_field(body["field"])
                analyzer = svc.mappings.index_analyzer(ft) if ft else registry.get("standard")
            else:
                analyzer = registry.get(body.get("analyzer", "standard"))
        else:
            from ..analysis import AnalysisRegistry
            analyzer = AnalysisRegistry().get(body.get("analyzer", "standard"))
        tokens = []
        for t in texts:
            for tok in analyzer.analyze(t):
                tokens.append({"token": tok.text, "position": tok.position,
                               "start_offset": tok.start_offset,
                               "end_offset": tok.end_offset, "type": "<ALPHANUM>"})
        return {"tokens": tokens}

    def get_alias(self, index: str = "_all", name: Optional[str] = None) -> dict:
        out: Dict[str, dict] = {}
        for a, am in self.c.node.metadata.aliases.items():
            if name and a != name:
                continue
            for n, cfg in am.indices.items():
                out.setdefault(n, {"aliases": {}})["aliases"][a] = cfg
        return out

    def update_aliases(self, body: dict) -> dict:
        return self.c.node.update_aliases(body.get("actions", []))

    def put_alias(self, index: str, name: str, body: Optional[dict] = None) -> dict:
        return self.c.node.update_aliases(
            [{"add": {"index": index, "alias": name, **(body or {})}}])

    def put_index_template(self, name: str, body: dict) -> dict:
        self.c.node.metadata.templates[name] = body
        return {"acknowledged": True}

    put_template = put_index_template

    def delete_index_template(self, name: str) -> dict:
        if self.c.node.metadata.templates.pop(name, None) is None:
            raise ApiError(404, "resource_not_found_exception",
                           f"index template [{name}] missing")
        return {"acknowledged": True}

    def exists_index_template(self, name: str) -> bool:
        return name in self.c.node.metadata.templates

    # -------- data streams (reference action/admin/indices/datastream) ----

    def create_data_stream(self, name: str) -> dict:
        from ..cluster import datastream as dstream
        return _map_ds_errors(dstream.create_data_stream, self.c.node, name)

    def get_data_stream(self, name: str = "*") -> dict:
        from ..cluster import datastream as dstream
        return {"data_streams": _map_ds_errors(dstream.get_data_streams,
                                               self.c.node, name)}

    def delete_data_stream(self, name: str) -> dict:
        from ..cluster import datastream as dstream
        return _map_ds_errors(dstream.delete_data_stream, self.c.node, name)


def _map_ds_errors(fn, *args):
    from ..cluster.datastream import DataStreamError
    try:
        return fn(*args)
    except DataStreamError as e:
        raise ApiError(400, "illegal_argument_exception", str(e))
    except IndexNotFoundError as e:
        raise ApiError(404, "index_not_found_exception", str(e))


class IngestClient:
    def __init__(self, client: RestClient):
        self.c = client

    def put_pipeline(self, id: str, body: dict) -> dict:
        self.c.node.ingest.put_pipeline(id, body)
        return {"acknowledged": True}

    def get_pipeline(self, id: Optional[str] = None) -> dict:
        svc = self.c.node.ingest
        if id:
            p = svc.get_pipeline(id)
            if p is None:
                raise ApiError(404, "resource_not_found_exception",
                               f"pipeline [{id}] not found")
            return {id: copy.deepcopy(p.config)}
        return {pid: copy.deepcopy(p.config)
                for pid, p in svc.pipelines.items()}

    def delete_pipeline(self, id: str) -> dict:
        self.c.node.ingest.delete_pipeline(id)
        return {"acknowledged": True}

    def simulate(self, body: dict) -> dict:
        return {"docs": self.c.node.ingest.simulate(body.get("pipeline", body),
                                                    body.get("docs", []))}


class SnapshotClient:
    def __init__(self, client: RestClient):
        self.c = client
        self.repos: Dict[str, dict] = {}

    def create_repository(self, repository: str, body: dict) -> dict:
        self.repos[repository] = body.get("settings", body)
        return {"acknowledged": True}

    def create(self, repository: str, snapshot: str, body: Optional[dict] = None,
               wait_for_completion: bool = True) -> dict:
        repo = self.repos.get(repository)
        if repo is None:
            raise ApiError(404, "repository_missing_exception",
                           f"[{repository}] missing")
        return self.c.node.snapshot(repo["location"], snapshot,
                                    (body or {}).get("indices", "_all"))

    def restore(self, repository: str, snapshot: str, body: Optional[dict] = None) -> dict:
        repo = self.repos.get(repository)
        if repo is None:
            raise ApiError(404, "repository_missing_exception",
                           f"[{repository}] missing")
        body = body or {}
        return self.c.node.restore(repo["location"], snapshot,
                                   body.get("rename_pattern"),
                                   body.get("rename_replacement"))

    def get(self, repository: str, snapshot: str = "_all") -> dict:
        import os
        repo = self.repos.get(repository)
        snaps = []
        if repo:
            seen = set()
            sdir = os.path.join(repo["location"], "snapshots")
            if os.path.isdir(sdir):
                for fn in sorted(os.listdir(sdir)):
                    if fn.endswith(".json"):
                        seen.add(fn[:-5])
            # legacy (pre-r4) directory-layout snapshots stay listed
            if os.path.isdir(repo["location"]):
                for d in sorted(os.listdir(repo["location"])):
                    if d in ("snapshots", "blobs"):
                        continue
                    if os.path.exists(os.path.join(repo["location"], d,
                                                   "manifest.json")):
                        seen.add(d)
            for name in sorted(seen):
                if snapshot in ("_all", "*") or name == snapshot:
                    snaps.append({"snapshot": name, "state": "SUCCESS"})
        return {"snapshots": snaps}


def _map_admin_errors(fn, *args):
    """cluster/admin.py exceptions -> HTTP-shaped ApiErrors."""
    from ..cluster.admin import IndexClosedError, SettingsError
    try:
        return fn(*args)
    except IndexClosedError as e:
        raise ApiError(400, "index_closed_exception", str(e))
    except SettingsError as e:
        raise ApiError(400, "illegal_argument_exception", str(e))
    except IndexNotFoundError as e:
        raise ApiError(404, "index_not_found_exception", str(e))


class ClusterClient:
    def __init__(self, client: RestClient):
        self.c = client

    def put_settings(self, body: dict) -> dict:
        """PUT /_cluster/settings (reference
        TransportClusterUpdateSettingsAction): persistent/transient dynamic
        settings; null values reset."""
        return _map_admin_errors(self.c.node.update_cluster_settings, body)

    def get_settings(self, include_defaults: bool = False) -> dict:
        return self.c.node.get_cluster_settings()

    def health(self, index: Optional[str] = None) -> dict:
        node = self.c.node
        names = (node.metadata.resolve(index) if index
                 else list(node.indices.keys()))
        primaries = active = unassigned = 0
        status = "green"
        rank = {"green": 0, "yellow": 1, "red": 2}
        for n in names:
            svc = node.indices[n]
            for c in svc.table.copies:
                if c.state == "STARTED":
                    active += 1
                    if c.primary:
                        primaries += 1
                else:
                    unassigned += 1
            s = svc.health_status()
            if rank[s] > rank[status]:
                status = s
        total = active + unassigned
        return {"cluster_name": node.metadata.cluster_name, "status": status,
                "number_of_nodes": 1, "number_of_data_nodes": 1,
                "active_primary_shards": primaries, "active_shards": active,
                "relocating_shards": 0, "initializing_shards": 0,
                "unassigned_shards": unassigned,
                "active_shards_percent_as_number":
                    100.0 * active / total if total else 100.0}

    def state(self) -> dict:
        node = self.c.node
        return {"cluster_name": node.metadata.cluster_name,
                "version": node.metadata.version,
                "metadata": {"indices": {n: {"state": m.state,
                                             "settings": m.settings}
                                         for n, m in node.metadata.indices.items()}}}

    def stats(self) -> dict:
        return self.c.node.stats()


class CatClient:
    def __init__(self, client: RestClient):
        self.c = client

    def indices(self, format: str = "json") -> List[dict]:
        out = []
        for n, svc in sorted(self.c.node.indices.items()):
            st = svc.stats()
            buf = st["indexing"].get("buffer", {})
            out.append({"health": svc.health_status(), "status": "open",
                        "index": n,
                        "pri": str(svc.meta.num_shards),
                        "rep": str(svc.meta.num_replicas),
                        "docs.count": str(st["docs"]["count"]),
                        "store.size": str(st["store"]["size_in_bytes"]),
                        # write-pressure columns (ingest observatory):
                        # docs/bytes sitting in the writer buffer, merges
                        # run so far, merge groups still pending
                        "buffer.docs": str(buf.get("docs", 0)),
                        "buffer.bytes": str(buf.get("bytes", 0)),
                        "merges.total": str(st["merges"]["total"]),
                        "merges.backlog": str(st["merges"].get("backlog",
                                                               0))})
        return out

    def shards(self, index: str = "_all", format: str = "json") -> List[dict]:
        """_cat/shards: one row per shard copy with its device placement."""
        out = []
        node = self.c.node
        for n in sorted(node.metadata.resolve(index)):
            svc = node.indices[n]
            for c in sorted(svc.table.copies, key=lambda c: (c.shard, c.replica)):
                if c.primary:
                    docs = svc.shards[c.shard].num_docs
                else:
                    rep = svc.replicas.get((c.shard, c.replica))
                    docs = rep.num_docs if rep else 0
                out.append({"index": n, "shard": str(c.shard),
                            "prirep": "p" if c.primary else "r",
                            "state": c.state,
                            "docs": str(docs),
                            "node": (f"device-{c.device}"
                                     if c.device is not None else "")})
        return out

    def count(self, index: str = "_all") -> List[dict]:
        total = sum(self.c.node.indices[n].num_docs
                    for n in self.c.node.metadata.resolve(index))
        return [{"epoch": str(int(time.time())), "count": str(total)}]

    def thread_pool(self, format: str = "json") -> List[dict]:
        node = self.c.node
        return [{"node_name": node.node_name, "name": p["name"],
                 "size": str(p["size"]), "active": str(p["active"]),
                 "completed": str(p["completed"])}
                for p in node.thread_pools.stats()]

    def tasks(self, format: str = "json") -> List[dict]:
        return [{"action": t["action"], "task_id": str(t["id"]),
                 "running_time": str(t["running_time_in_nanos"]),
                 "cancellable": str(t["cancellable"]).lower()}
                for t in self.c.node.tasks.list()]

    def nodes(self, format: str = "json") -> List[dict]:
        stats = self.c.nodes_stats()["nodes"][self.c.node.node_name]
        return [{"name": self.c.node.node_name,
                 "node.role": "".join(r[0] for r in stats["roles"]),
                 "master": "*",
                 "segments.count": str(stats["indices"]["segments"]["count"]),
                 "docs.count": str(stats["indices"]["docs"]["count"])}]

    def health(self, format: str = "json") -> List[dict]:
        h = self.c.cluster.health()
        return [{"epoch": str(int(time.time())),
                 "cluster": h["cluster_name"], "status": h["status"],
                 "node.total": str(h["number_of_nodes"]),
                 "shards": str(h["active_shards"]),
                 "pri": str(h["active_primary_shards"]),
                 "unassign": str(h["unassigned_shards"])}]

    def segments(self, index: str = "_all",
                 format: str = "json") -> List[dict]:
        """_cat/segments with per-segment DEVICE residency from the HBM
        ledger: `memory.device` is the segment's total attributed HBM
        bytes, `memory.device.tenants` the per-kind breakdown (e.g.
        `aligned_postings=1048576,segment_columns=262144`)."""
        residency = self.c.node.hbm_ledger.segment_residency()
        out = []
        for n in sorted(self.c.node.metadata.resolve(index)):
            svc = self.c.node.indices[n]
            for si, sh in enumerate(svc.shards):
                for seg in sh.segments:
                    res = residency.get(getattr(seg, "uid", None)) \
                        or residency.get(seg.name) or {}
                    kinds = res.get("kinds", {})
                    out.append({"index": n, "shard": str(si),
                                "prirep": "p", "segment": seg.name,
                                "docs.count": str(seg.live_count),
                                "docs.deleted":
                                    str(seg.ndocs - seg.live_count),
                                "memory.device":
                                    str(res.get("total_bytes", 0)),
                                "memory.device.tenants": ",".join(
                                    f"{k}={v}" for k, v in
                                    sorted(kinds.items()))})
        return out

    def aliases(self, format: str = "json") -> List[dict]:
        out = []
        for alias, am in sorted(self.c.node.metadata.aliases.items()):
            for idx, cfg in sorted(am.indices.items()):
                out.append({"alias": alias, "index": idx,
                            "is_write_index":
                                str(cfg.get("is_write_index",
                                            False)).lower()})
        return out

    def templates(self, format: str = "json") -> List[dict]:
        return [{"name": name,
                 "index_patterns": str(t.get("index_patterns", [])),
                 "order": str(t.get("order", t.get("priority", 0)))}
                for name, t in sorted(
                    self.c.node.metadata.templates.items())]

    def allocation(self, format: str = "json") -> List[dict]:
        shards = sum(len(svc.shards) for svc in self.c.node.indices.values())
        return [{"node": self.c.node.node_name, "shards": str(shards)}]
