"""Search templates: mustache-lite rendering (reference
`modules/lang-mustache/` — MustacheScriptEngine + TransportSearchTemplateAction).

Supported syntax (the subset the reference's search-template docs exercise):
- `{{var}}` / `{{a.b.c}}` — scalar substitution (JSON-encoded when not str)
- `{{{var}}}` — raw substitution
- `{{#toJson}}var{{/toJson}}` — JSON-dump a param
- `{{#join}}var{{/join}}` — comma-join an array param
- `{{#var}}...{{/var}}` — section: truthy scalar, dict scope, or list loop
  (`{{.}}` is the loop element)
- `{{^var}}...{{/var}}` — inverted section
- `{{! comment}}`
"""

from __future__ import annotations

import json
import re
from typing import Any, List, Optional, Tuple


class TemplateError(ValueError):
    pass


def _lookup(ctx_stack: List[Any], path: str):
    if path == ".":
        return ctx_stack[-1]
    for ctx in reversed(ctx_stack):
        cur = ctx
        ok = True
        for part in path.split("."):
            if isinstance(cur, dict) and part in cur:
                cur = cur[part]
            else:
                ok = False
                break
        if ok:
            return cur
    return None


_TAG = re.compile(r"\{\{\{(.+?)\}\}\}|\{\{(.+?)\}\}", re.S)


def _tokenize(src: str):
    """-> list of ("text", s) | ("var"/"raw", name) | ("open"/"inv", name)
    | ("close", name) | ("comment", _)."""
    out = []
    pos = 0
    for m in _TAG.finditer(src):
        if m.start() > pos:
            out.append(("text", src[pos: m.start()]))
        if m.group(1) is not None:
            out.append(("raw", m.group(1).strip()))
        else:
            tag = m.group(2).strip()
            if tag.startswith("#"):
                out.append(("open", tag[1:].strip()))
            elif tag.startswith("^"):
                out.append(("inv", tag[1:].strip()))
            elif tag.startswith("/"):
                out.append(("close", tag[1:].strip()))
            elif tag.startswith("!"):
                out.append(("comment", ""))
            elif tag.startswith("&"):
                out.append(("raw", tag[1:].strip()))
            else:
                out.append(("var", tag))
        pos = m.end()
    if pos < len(src):
        out.append(("text", src[pos:]))
    return out


def _parse_block(tokens, i: int, until: Optional[str]) -> Tuple[list, int]:
    """-> (nodes, next_index); nodes: ("text", s) | ("var"/"raw", name) |
    ("section", name, inverted, children)."""
    nodes = []
    while i < len(tokens):
        kind, val = tokens[i]
        if kind == "close":
            if val != until:
                raise TemplateError(f"mismatched close tag [{val}]")
            return nodes, i + 1
        if kind in ("open", "inv"):
            children, i2 = _parse_block(tokens, i + 1, val)
            nodes.append(("section", val, kind == "inv", children))
            i = i2
            continue
        if kind != "comment":
            nodes.append((kind, val))
        i += 1
    if until is not None:
        raise TemplateError(f"unclosed section [{until}]")
    return nodes, i


def _stringify(v: Any, raw: bool) -> str:
    if v is None:
        return ""
    if isinstance(v, str):
        return v if raw else json.dumps(v)[1:-1]
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (dict, list)):
        return json.dumps(v)
    return json.dumps(v)


def _render_nodes(nodes, stack: List[Any]) -> str:
    out = []
    for node in nodes:
        kind = node[0]
        if kind == "text":
            out.append(node[1])
        elif kind in ("var", "raw"):
            out.append(_stringify(_lookup(stack, node[1]), kind == "raw"))
        else:
            _, name, inverted, children = node
            if name == "toJson":
                inner = _render_nodes(children, stack).strip()
                out.append(json.dumps(_lookup(stack, inner)))
                continue
            if name == "join":
                inner = _render_nodes(children, stack).strip()
                v = _lookup(stack, inner) or []
                out.append(",".join(_stringify(x, True) for x in v))
                continue
            v = _lookup(stack, name)
            truthy = bool(v) and v != []
            if inverted:
                if not truthy:
                    out.append(_render_nodes(children, stack))
            elif truthy:
                if isinstance(v, list):
                    for item in v:
                        out.append(_render_nodes(children, stack + [item]))
                elif isinstance(v, dict):
                    out.append(_render_nodes(children, stack + [v]))
                else:
                    out.append(_render_nodes(children, stack))
    return "".join(out)


def render_template(source: Any, params: Optional[dict]) -> dict:
    """Render a search template (string or dict source) + params -> the
    search body dict."""
    if isinstance(source, dict):
        src = json.dumps(source)
    else:
        src = str(source)
    tokens = _tokenize(src)
    nodes, _ = _parse_block(tokens, 0, None)
    rendered = _render_nodes(nodes, [params or {}])
    try:
        return json.loads(rendered)
    except json.JSONDecodeError as e:
        raise TemplateError(f"rendered template is not valid JSON: {e}: "
                            f"{rendered[:200]}")
