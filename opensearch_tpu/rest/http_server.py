"""HTTP wire layer over the REST façade — the network transport the
reference exposes through `http/HttpServerTransport.java:1` +
`rest/RestController.java:1`.

A threaded stdlib HTTP server speaking the same JSON (and NDJSON for
_bulk/_msearch) dialect as the dict-level `RestClient`. Concurrency
contract: searches and reads run fully concurrently (the engine's query
path is read-only over immutable segments and its caches are
lock-guarded); writes serialize PER INDEX at the ENGINE boundary, not
here — `IndexService.write_lock` is acquired by the client layer after
alias/data-stream/pipeline-`_index` resolution (rest/client.py), the
analog of the reference's per-shard engine locks
(`index/engine/InternalEngine.java:1`), and `Node.meta_lock` serializes
cluster-metadata mutations (create/delete/open/close, dynamic
auto-create). So concurrent HTTP writers on different indices proceed in
parallel, two names resolving to one engine share one lock, and this
transport stays lock-free.

Under concurrent search load the per-thread requests do NOT each pay a
device dispatch: eligible searches coalesce in the serving scheduler
(`serving/scheduler.py`, docs/SERVING.md) into one batched program
invocation per flush, and `stop()` drains that queue before the
transport goes away.

Usage:
    srv = HttpServer(client)          # or HttpServer(port=9200)
    port = srv.start()                # background thread, returns port
    ... real HTTP against http://localhost:{port} ...
    srv.stop()
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, unquote, urlparse

from .client import ApiError, IndexNotFoundError, RestClient


def _truthy(v) -> bool:
    return str(v).lower() in ("1", "true", "yes", "")


# routes any authenticated principal may hit (cluster "monitor" class)
_MONITOR_HEADS = {"", "_cluster", "_nodes", "_cat", "_stats", "_tasks",
                  "_metrics", "_flight_recorder", "_slo", "_insights",
                  "_remediation"}
# cluster-admin routes
_ADMIN_HEADS = {"_index_template", "_template", "_remotestore", "_snapshot",
                "_ingest", "_scripts", "_search_pipeline", "_data_stream",
                "_aliases", "_security"}
# per-index sub-ops that mutate data vs admin the index
_INDEX_WRITE_OPS = {"_doc", "_create", "_update", "_bulk",
                    "_update_by_query", "_delete_by_query"}
_INDEX_ADMIN_OPS = {"_mapping", "_settings", "_open", "_close", "_refresh",
                    "_flush", "_forcemerge", "_shrink", "_split", "_clone",
                    "_rollover", "_alias", "_aliases"}


def _resolve_targets(c: RestClient, name: str):
    """Concrete indices `name` resolves to (alias/data-stream/wildcard
    expansion) for authorization; the raw name is always included so
    pattern-based roles grant the names users actually type."""
    out = {name}
    try:
        out.update(c.node.metadata.resolve(name))
    except Exception:       # noqa: BLE001 — unresolvable: raw name only
        pass
    return out


def _classify(method: str, parts) -> Tuple[str, Optional[str]]:
    """-> (action_group, index_or_None) for authorization. Mirrors the
    reference security plugin's action-name -> action-group mapping at the
    granularity this REST surface distinguishes."""
    from ..security.identity import CLUSTER_ADMIN, INDEX_ADMIN, READ, WRITE
    head = parts[0] if parts else ""
    if head in _MONITOR_HEADS:
        if head == "_cluster" and method == "PUT":
            return CLUSTER_ADMIN, None
        if head == "_tasks" and method == "POST":
            return CLUSTER_ADMIN, None    # cancel is a mutating op
        if head == "_flight_recorder" and method == "POST":
            # manual dump mutates the bounded dump store (force=True
            # bypasses cooldowns and can evict genuine anomaly bundles)
            return CLUSTER_ADMIN, None
        return "monitor", None
    if head in _ADMIN_HEADS:
        return CLUSTER_ADMIN, None
    if head in ("_search", "_msearch", "_mget", "_count"):
        return READ, "*"
    if head == "_bulk":
        return WRITE, "*"
    # /{index}[/op...]
    index = head
    op = parts[1] if len(parts) > 1 else None
    if op is None:
        if method in ("PUT", "DELETE"):
            return INDEX_ADMIN, index
        return READ, index
    if op in _INDEX_WRITE_OPS:
        if op == "_doc" and method in ("GET", "HEAD"):
            return READ, index
        return WRITE, index
    if op in _INDEX_ADMIN_OPS:
        # _mapping/_settings GETs are reads; refresh/flush/forcemerge are
        # maintenance regardless of method (the routes accept GET, like
        # the reference's method-agnostic registrations)
        if method == "GET" and op in ("_mapping", "_settings", "_alias",
                                      "_aliases"):
            return READ, index
        return INDEX_ADMIN, index
    return READ, index


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "opensearch-tpu"

    # quiet the default stderr access log
    def log_message(self, fmt, *args):
        pass

    # ---------------- plumbing ----------------

    def _body(self):
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b""
        return raw.decode("utf-8") if raw else ""

    def _json_body(self) -> Optional[dict]:
        cached = getattr(self, "_json_cache", None)
        if cached is not None:
            return cached
        raw = self._body()
        if not raw.strip():
            return None
        self._json_cache = json.loads(raw)
        return self._json_cache

    def _ndjson_body(self):
        cached = getattr(self, "_ndjson_cache", None)
        if cached is not None:
            return cached
        self._ndjson_cache = [json.loads(ln)
                              for ln in self._body().splitlines()
                              if ln.strip()]
        return self._ndjson_cache

    def _send(self, status: int, payload,
              content_type="application/json", headers=None):
        if isinstance(payload, (dict, list)):
            data = json.dumps(payload).encode("utf-8")
        else:
            # plain-text payloads (_cat tables, /_metrics Prometheus
            # exposition) must not claim to be JSON
            content_type = "text/plain; charset=utf-8"
            data = str(payload).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            # error-shaped extras (Retry-After on 429s): the rejecting
            # layer decides the value, the wire layer just carries it
            self.send_header(k, str(v))
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(data)

    def _dispatch(self):
        # one handler serves many requests over a keep-alive connection:
        # body caches are strictly per-request
        self._ndjson_cache = None
        self._json_cache = None
        try:
            url = urlparse(self.path)
            parts = [unquote(p) for p in url.path.split("/") if p]
            # keep_blank_values: the bare `?refresh` idiom must read as true
            params = {k: v[0] for k, v in
                      parse_qs(url.query, keep_blank_values=True).items()}
            status, payload = self._route(self.command, parts, params)
            self._send(status, payload)
        except ApiError as e:
            self._send(e.status, e.body(), headers=e.headers)
        except IndexNotFoundError as e:
            self._send(404, {"error": {"type": "index_not_found_exception",
                                       "reason": str(e)}, "status": 404})
        except json.JSONDecodeError as e:
            self._send(400, {"error": {"type": "parsing_exception",
                                       "reason": str(e)}, "status": 400})
        except ValueError as e:
            self._send(400, {"error": {"type": "illegal_argument_exception",
                                       "reason": str(e)}, "status": 400})
        except Exception as e:                         # noqa: BLE001
            self._send(500, {"error": {"type": type(e).__name__,
                                       "reason": str(e)}, "status": 500})

    do_GET = do_POST = do_PUT = do_DELETE = do_HEAD = _dispatch

    def _body_targets(self, method: str, parts, url_index: str):
        """The full set of indices a request addresses: the URL index plus
        any per-line targets in bulk/msearch bodies and per-doc _index in
        an _mget body."""
        head = parts[0] if parts else ""
        op = parts[1] if len(parts) > 1 else None
        targets = set() if url_index == "*" else {url_index}
        if head == "_bulk" or op == "_bulk":
            default = None if head == "_bulk" else url_index
            for ln in self._ndjson_body():
                if isinstance(ln, dict):
                    for verb in ("index", "create", "update", "delete"):
                        if verb in ln and isinstance(ln[verb], dict):
                            targets.add(ln[verb].get("_index", default))
                            break
        elif head == "_msearch" or op == "_msearch":
            default = None if head == "_msearch" else url_index
            for ln in self._ndjson_body():
                if isinstance(ln, dict) and ("index" in ln
                                             or not ln.get("query")):
                    idx = ln.get("index", default)
                    for i in (idx if isinstance(idx, list) else [idx]):
                        targets.add(i)
        elif head == "_mget" or op == "_mget":
            body = self._json_body() or {}
            for d in body.get("docs", []):
                targets.add(d.get("_index",
                                  None if head == "_mget" else url_index))
        targets.discard(None)
        # a line with no resolvable index (top-level bulk without _index)
        # is a 400 later; for auth, treat it as the wildcard target
        return targets or {"*"}

    # ---------------- security API ----------------

    def _security_route(self, method: str, parts, ident, subject):
        """_security/user|role|token|authinfo (reference security plugin
        REST API shapes). User/role management needs cluster_admin."""
        from ..security.identity import CLUSTER_ADMIN
        kind = parts[1] if len(parts) > 1 else None
        if kind == "authinfo":
            return 200, {"user_name": subject.principal,
                         "roles": subject.roles}
        if kind == "token" and method == "POST":
            import math
            body = self._json_body() or {}
            try:
                ttl = float(body.get("ttl_seconds", 3600))
            except (TypeError, ValueError):
                ttl = float("nan")
            if not math.isfinite(ttl) or not 0 < ttl <= 30 * 86400:
                return 400, {"error": {
                    "type": "illegal_argument_exception",
                    "reason": "ttl_seconds must be in (0, 2592000]"},
                    "status": 400}
            return 200, {"token": ident.issue_token(subject, ttl),
                         "type": "bearer"}
        if kind in ("user", "role") and len(parts) > 2:
            ident.authorize_cluster(subject, CLUSTER_ADMIN)
            name = parts[2]
            if kind == "user":
                if method == "PUT":
                    body = self._json_body() or {}
                    try:
                        ident.put_user(name, body.get("password", ""),
                                       roles=body.get("roles", []),
                                       attributes=body.get("attributes"))
                    except ValueError as e:
                        return 400, {"error": {
                            "type": "illegal_argument_exception",
                            "reason": str(e)}, "status": 400}
                    return 200, {"status": "CREATED", "user": name}
                if method == "DELETE":
                    return ((200, {"status": "OK"})
                            if ident.delete_user(name)
                            else (404, {"status": "NOT_FOUND"}))
                if method == "GET":
                    u = ident.users.get(name)
                    if u is None:
                        return 404, {"status": "NOT_FOUND"}
                    return 200, {name: {"roles": u.roles,
                                        "attributes": u.attributes}}
            else:
                if method == "PUT":
                    try:
                        ident.put_role(name, self._json_body() or {})
                    except ValueError as e:
                        return 400, {"error": {
                            "type": "illegal_argument_exception",
                            "reason": str(e)}, "status": 400}
                    return 200, {"status": "CREATED", "role": name}
                if method == "DELETE":
                    return ((200, {"status": "OK"})
                            if ident.delete_role(name)
                            else (404, {"status": "NOT_FOUND"}))
                if method == "GET":
                    r = ident.roles.get(name)
                    if r is None:
                        return 404, {"status": "NOT_FOUND"}
                    return 200, {name: {
                        "cluster_permissions": sorted(r.cluster),
                        "index_permissions": [
                            {"index_patterns": [p],
                             "allowed_actions": sorted(a)}
                            for p, a in r.indices]}}
        return 400, {"error": {"type": "illegal_argument_exception",
                               "reason": f"unsupported _security route "
                                         f"{parts}"}, "status": 400}

    # ---------------- routing ----------------

    def _route(self, method: str, parts, params) -> Tuple[int, object]:
        # node-to-node RPC surface for the multi-process cluster
        # (cluster/distnode.py); absent unless a DistClusterNode owns this
        # server
        if parts and parts[0] == "_internal":
            # read through the HttpServer wrapper so `srv.dist = node` works
            # whether assigned before or after start()
            owner = getattr(self.server, "owner", None)
            dist = owner.dist if owner is not None else None
            if dist is None:
                return 404, {"error": {
                    "type": "resource_not_found_exception",
                    "reason": "not a cluster transport endpoint"}}
            # when REST security is on, node-to-node calls must present
            # the cluster's shared secret (OPENSEARCH_TPU_CLUSTER_TOKEN;
            # compact analog of the reference's mutual transport TLS) —
            # otherwise /_internal would be an auth bypass on this port
            sident = getattr(self.server, "identity", None)
            if sident is not None and sident.enabled:
                import hmac as _hmac
                tok = os.environ.get("OPENSEARCH_TPU_CLUSTER_TOKEN")
                got = self.headers.get("X-Cluster-Token", "")
                if not tok or not _hmac.compare_digest(tok, got):
                    return 403, {"error": {
                        "type": "security_exception",
                        "reason": "node-to-node calls require the cluster "
                                  "token when security is enabled"},
                        "status": 403}
            return dist.handle_internal(method, parts,
                                        self._json_body() or {})
        c: RestClient = self.server.client            # type: ignore

        # ---- authentication / authorization (security/identity.py) ----
        # disabled unless an IdentityService is attached, like a reference
        # distribution without the security plugin. `_internal` (above)
        # stays exempt: node-to-node transport trust is a separate layer,
        # as in the reference (transport TLS vs REST auth).
        ident = getattr(self.server, "identity", None)
        if ident is not None and ident.enabled:
            from ..security.identity import (AuthenticationError,
                                             AuthorizationError)
            from ..security.context import request_subject
            try:
                subject = ident.authenticate_header(
                    self.headers.get("Authorization"))
                if parts and parts[0] == "_security":
                    return self._security_route(method, parts, ident,
                                                subject)
                action, index = _classify(method, parts)
                if action == "monitor":
                    pass                  # any authenticated principal
                elif index is None:
                    ident.authorize_cluster(subject, action)
                else:
                    # bulk/msearch/mget bodies address indices PER LINE —
                    # authorize every target, not just the URL index; and
                    # authorize the CONCRETE indices a name resolves to
                    # (alias/data-stream), not just the request name
                    for tgt in self._body_targets(method, parts, index):
                        for concrete in _resolve_targets(c, tgt):
                            ident.authorize_index(subject, concrete,
                                                  action)
                # mid-flight re-checks (ingest `_index` rewrites) consult
                # the ambient request subject (security/context.py)
                with request_subject(ident, subject):
                    return self._route_after_auth(method, parts, params, c)
            except AuthenticationError as e:
                return 401, {"error": {"type": "security_exception",
                                       "reason": str(e)}, "status": 401}
            except AuthorizationError as e:
                return 403, {"error": {"type": "security_exception",
                                       "reason": str(e)}, "status": 403}
        elif parts and parts[0] == "_security":
            return 400, {"error": {
                "type": "illegal_argument_exception",
                "reason": "security is not enabled on this node"},
                "status": 400}
        return self._route_after_auth(method, parts, params, c)

    def _route_after_auth(self, method: str, parts, params,
                          c: RestClient) -> Tuple[int, object]:
        if not parts:
            return 200, {"name": c.node.node_name,
                         "cluster_name": c.node.metadata.cluster_name,
                         "version": {"distribution": "opensearch-tpu"},
                         "tagline": "TPU-native search"}

        head = parts[0]
        # the cluster transport owner, when this server fronts a
        # DistClusterNode: observability reads fan out fleet-wide
        owner = getattr(self.server, "owner", None)
        dist = owner.dist if owner is not None else None
        # ---- cluster-level ----
        if head == "_cluster":
            if len(parts) >= 2 and parts[1] == "health":
                return 200, c.cluster.health(parts[2] if len(parts) > 2
                                             else None)
            if len(parts) >= 2 and parts[1] == "stats":
                # fleet rollup (docs/OBSERVABILITY.md "fleet"): counters
                # summed, gauges per-node, DDSketch sketches MERGED so
                # the percentiles are fleet-true; unclustered nodes
                # serve the same shape as a fleet of one
                return 200, (dist.cluster_stats() if dist is not None
                             else c.cluster_stats())
            if len(parts) >= 2 and parts[1] == "settings":
                if method == "PUT":
                    return 200, c.cluster.put_settings(self._json_body())
                return 200, c.cluster.get_settings()
            raise ApiError(400, "illegal_argument_exception",
                           f"unsupported _cluster route {parts}")
        if head == "_nodes":
            # /_nodes[/{id}]/hot_threads | /_nodes/stats[/history] |
            # /_nodes[/stats]
            sub = parts[1] if len(parts) > 1 else None
            node_id = None
            if sub is not None and sub not in ("stats", "hot_threads"):
                node_id = sub
                sub = parts[2] if len(parts) > 2 else None
            if sub == "hot_threads":
                # py-side stack sampler over the runtime's worker threads
                # (obs/hot_threads.py); plain text like the reference,
                # ?format=json for the structured form. Clustered: fans
                # out so every member samples ITS OWN process, with
                # per-node sections + unreachable-node degradation
                ht_kw = dict(
                    snapshots=int(params.get("snapshots", 3)),
                    interval_ms=float(params.get("interval_ms", 20)),
                    ignore_idle=_truthy(params.get("ignore_idle",
                                                   "true")),
                    as_json=params.get("format") == "json")
                if dist is not None:
                    return 200, dist.hot_threads_federated(
                        node_id=node_id, **ht_kw)
                if node_id not in (None, "_all", "_local",
                                   c.node.node_name):
                    raise ApiError(404, "resource_not_found_exception",
                                   f"no such node [{node_id}]")
                return 200, c.hot_threads(**ht_kw)
            if sub == "stats" and "history" in parts:
                # time-series retention (obs/timeseries.py): windowed
                # per-node series with delta/rate derivation
                metric = params.get("metric")
                if not metric:
                    raise ApiError(400, "illegal_argument_exception",
                                   "history requires ?metric=<name>")
                window_s = float(params.get("window", 60.0))
                if dist is not None:
                    return 200, dist.history_federated(
                        metric, window_s, node_id=node_id)
                return 200, c.metrics_history(metric, window_s)
            if dist is not None:
                return 200, dist.nodes_stats_federated(node_id=node_id)
            if node_id not in (None, "_all", "_local",
                               c.node.node_name):
                raise ApiError(404, "resource_not_found_exception",
                               f"no such node [{node_id}]")
            return 200, c.nodes_stats()
        if head == "_slo":
            # SLO burn-rate engine (obs/slo.py): armed objectives, live
            # multi-window burn rates, the recent alert log
            return 200, c.slo_status()
        if head == "_remediation":
            # remediation actuator (serving/remediator.py): the live
            # action table + engage/release history; clustered nodes
            # fan the read out over /_internal like the observatory
            if method != "GET":
                raise ApiError(405, "method_not_allowed",
                               "_remediation requires GET")
            if dist is not None:
                return 200, dist.remediation_federated()
            return 200, c.remediation_status()
        if head == "_insights":
            # query insights (obs/insights.py): workload fingerprints +
            # heavy-hitter attribution. /_insights/top_queries is the
            # read surface; clustered nodes merge every member's sketch
            # (commutative space-saving merge) before ranking
            if len(parts) > 1 and parts[1] == "top_queries":
                if method != "GET":
                    raise ApiError(405, "method_not_allowed",
                                   "top_queries requires GET")
                by = params.get("by", "latency")
                try:
                    n_top = int(params.get("n", 10))
                    window_s = (float(params["window"])
                                if "window" in params else None)
                except (TypeError, ValueError):
                    raise ApiError(400, "parsing_exception",
                                   "top_queries ?n= and ?window= must "
                                   "be numeric")
                if window_s is not None and window_s <= 0:
                    raise ApiError(400, "illegal_argument_exception",
                                   "top_queries ?window= must be "
                                   "positive seconds")
                if n_top < 0:
                    raise ApiError(400, "illegal_argument_exception",
                                   "top_queries ?n= must be >= 0")
                if dist is not None:
                    return 200, dist.top_queries_federated(
                        by=by, n=n_top, window_s=window_s)
                return 200, c.insights_top_queries(by=by, n=n_top,
                                                   window_s=window_s)
            if method != "GET":
                raise ApiError(405, "method_not_allowed",
                               "_insights requires GET")
            return 200, c.insights_status()
        if head == "_flight_recorder":
            # black-box event journal (obs/flight_recorder.py): ring
            # stats + recent anomaly dumps; POST …/dump freezes a manual
            # snapshot bundle
            if len(parts) > 1 and parts[1] == "dump":
                if method != "POST":
                    raise ApiError(405, "method_not_allowed",
                                   "dump requires POST")
                body = self._json_body() or {}
                return 200, c.flight_recorder_dump(
                    note=body.get("note") or params.get("note"))
            return 200, c.flight_recorder(
                dumps=int(params.get("dumps", 5)))
        if head == "_metrics":
            # Prometheus text exposition of the unified metrics registry
            # (utils/metrics.py): counters, gauges, and latency-histogram
            # summaries — the scrape surface of the same data
            # `_nodes/stats` serves as JSON
            from ..obs.insights import INSIGHTS
            from ..utils.metrics import METRICS, render_prometheus
            # node label: federated scrapes of several processes must
            # not collapse identically-named series into one stream;
            # the insights export is the BOUNDED top-K (shape hashes
            # only — workload cardinality never inflates the scrape)
            return 200, render_prometheus(
                METRICS, node=c.node.node_name,
                insights=INSIGHTS.prometheus_top())
        if head == "_cat":
            kind = parts[1] if len(parts) > 1 else "indices"
            fn = getattr(c.cat, kind, None)
            if fn is None:
                raise ApiError(400, "illegal_argument_exception",
                               f"unknown _cat endpoint [{kind}]")
            rows = fn()
            if params.get("format") == "json":
                return 200, rows
            text = "\n".join(" ".join(str(v) for v in r.values())
                             for r in rows)
            return 200, text + "\n"
        if head == "_search":
            if len(parts) > 1 and parts[1] == "scroll":
                body = self._json_body() or {}
                # id may arrive in the body, query string, or URL path
                sid = body.get("scroll_id", params.get("scroll_id"))
                if sid is None and len(parts) > 2:
                    sid = parts[2]
                if method == "DELETE":
                    return 200, c.clear_scroll(sid)
                return 200, c.scroll(sid, scroll=body.get(
                    "scroll", params.get("scroll")))
            return 200, c.search("_all", self._json_body() or {},
                                 scroll=params.get("scroll"))
        if head == "_msearch":
            return 200, c.msearch(self._ndjson_body())
        if head == "_bulk":
            return 200, c.bulk(self._ndjson_body(),
                               refresh=_truthy(params.get("refresh",
                                                          "false")))
        if head == "_mget":
            return 200, c.mget(self._json_body())
        if head == "_tasks":
            if parts[-1] == "_cancel":
                if method != "POST":
                    raise ApiError(405, "method_not_allowed",
                                   "cancel requires POST")
                if len(parts) >= 3:
                    return 200, c.cancel_task(parts[1])
                # cancel-all form: POST /_tasks/_cancel[?actions=...]
                cancelled = []
                for t in c.node.tasks.list(params.get("actions")):
                    if c.node.tasks.cancel(t["id"]):
                        cancelled.append(t["id"])
                return 200, {"nodes": {}, "cancelled": cancelled}
            if len(parts) == 2:
                # single-task form: GET /_tasks/{id}
                for t in c.node.tasks.list(None):
                    if str(t["id"]) == parts[1]:
                        return 200, {"completed": t.get("cancelled", False)
                                     or not t.get("running", True),
                                     "task": t}
                raise ApiError(404, "resource_not_found_exception",
                               f"task [{parts[1]}] not found")
            return 200, c.tasks(params.get("actions"))
        if head == "_stats":
            return 200, c.node.stats()
        if head == "_remotestore":
            if len(parts) > 1 and parts[1] == "_restore":
                if method != "POST":
                    raise ApiError(405, "method_not_allowed",
                                   "restore requires POST")
                return 200, c.remotestore_restore(self._json_body() or {})
        if head == "_ingest" and len(parts) >= 2 and \
                parts[1] == "pipeline":
            # reference RestPutPipelineAction / RestGetPipelineAction /
            # RestDeletePipelineAction / RestSimulatePipelineAction
            if parts[-1] == "_simulate":
                body = self._json_body() or {}
                if len(parts) > 3:       # simulate the STORED pipeline
                    p = c.node.ingest.get_pipeline(parts[2])
                    if p is None:
                        raise ApiError(404, "resource_not_found_exception",
                                       f"pipeline [{parts[2]}] not found")
                    body = {"pipeline": p.config,
                            "docs": body.get("docs", [])}
                return 200, c.ingest.simulate(body)
            pid = parts[2] if len(parts) > 2 else None
            if method == "PUT":
                if pid is None:
                    raise ApiError(400, "illegal_argument_exception",
                                   "pipeline id required")
                return 200, c.ingest.put_pipeline(pid, self._json_body())
            if method == "DELETE":
                if pid is None:
                    raise ApiError(400, "illegal_argument_exception",
                                   "pipeline id required")
                return 200, c.ingest.delete_pipeline(pid)
            return 200, c.ingest.get_pipeline(pid)
        if head == "_aliases" and method == "POST":
            return 200, c.indices.update_aliases(self._json_body() or {})
        if head == "_index_template" and len(parts) == 2:
            if method == "PUT":
                return 200, c.indices.put_index_template(
                    parts[1], self._json_body())
            if method == "HEAD":
                return (200 if c.indices.exists_index_template(parts[1])
                        else 404), {}
            if method == "DELETE":
                return 200, c.indices.delete_index_template(parts[1])

        # ---- index-level: /{index}[/...] ----
        index = head
        rest = parts[1:]
        if not rest:
            if method == "PUT":
                return 200, c.indices.create(index, self._json_body())
            if method == "DELETE":
                return 200, c.indices.delete(index)
            if method == "HEAD":
                return (200 if c.indices.exists(index) else 404), {}
            return 200, c.indices.get(index)

        op = rest[0]
        if op == "_doc":
            doc_id = rest[1] if len(rest) > 1 else None
            refresh = _truthy(params.get("refresh", "false"))
            if method in ("PUT", "POST"):
                resp = c.index(index, self._json_body() or {},
                               id=doc_id, refresh=refresh,
                               routing=params.get("routing"),
                               pipeline=params.get("pipeline"))
                # reference: 201 on create, 200 on overwrite-update
                return (201 if resp.get("result") == "created"
                        else 200), resp
            if method == "GET":
                return 200, c.get(index, doc_id,
                                  routing=params.get("routing"))
            if method == "HEAD":
                return (200 if c.exists(index, doc_id) else 404), {}
            if method == "DELETE":
                return 200, c.delete(index, doc_id,
                                     routing=params.get("routing"))
        if op == "_create" and len(rest) > 1:
            return 201, c.create(index, rest[1], self._json_body() or {})
        if op == "_update" and len(rest) > 1:
            return 200, c.update(index, rest[1], self._json_body() or {},
                                 routing=params.get("routing"))
        if op == "_search":
            return 200, c.search(index, self._json_body() or {},
                                 scroll=params.get("scroll"))
        if op == "_msearch":
            body = self._ndjson_body()
            return 200, c.msearch(body, index=index)
        if op == "_count":
            return 200, c.count(index, self._json_body())
        if op == "_bulk":
            return 200, c.bulk(self._ndjson_body(), index=index,
                               refresh=_truthy(params.get("refresh",
                                                          "false")))
        if op in ("_forcemerge", "_open", "_close") \
                and method not in ("POST", "PUT"):
            # POST-only routes (reference RestController; note the
            # reference DOES register GET for _refresh/_flush, so those
            # stay method-agnostic): a probe must never close an index
            raise ApiError(405, "method_not_allowed",
                           f"{op} requires POST")
        if op == "_refresh":
            return 200, c.indices.refresh(index)
        if op == "_flush":
            return 200, c.indices.flush(index)
        if op == "_forcemerge":
            return 200, c.indices.forcemerge(index)
        if op == "_mapping":
            if method == "PUT":
                return 200, c.indices.put_mapping(index,
                                                  self._json_body())
            return 200, c.indices.get_mapping(index)
        if op == "_settings":
            if method == "PUT":
                return 200, c.indices.put_settings(index,
                                                   self._json_body())
            return 200, c.indices.get_settings(index)
        if op == "_open":
            return 200, c.indices.open(index)
        if op == "_close":
            return 200, c.indices.close(index)
        raise ApiError(400, "illegal_argument_exception",
                       f"unsupported route {method} /{'/'.join(parts)}")


class HttpServer:
    """Threaded HTTP transport bound to a RestClient."""

    def __init__(self, client: Optional[RestClient] = None,
                 host: str = "127.0.0.1", port: int = 0, identity=None):
        self.client = client or RestClient()
        self.host = host
        self.port = port
        self.identity = identity  # security.IdentityService or None (open)
        self.dist = None          # DistClusterNode when clustered
        self._srv: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        self._srv = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._srv.client = self.client                 # type: ignore
        self._srv.owner = self                         # type: ignore
        self._srv.identity = self.identity             # type: ignore
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None
            # drain the serving scheduler so queued searches resolve
            # before the transport disappears — WITHOUT closing it: the
            # scheduler belongs to the Node, which may outlive this
            # transport (serving/scheduler.py)
            serving = getattr(self.client.node, "serving", None)
            if serving is not None:
                serving.drain()
