"""HTTP wire layer over the REST façade — the network transport the
reference exposes through `http/HttpServerTransport.java:1` +
`rest/RestController.java:1`.

A threaded stdlib HTTP server speaking the same JSON (and NDJSON for
_bulk/_msearch) dialect as the dict-level `RestClient`. Concurrency
contract: searches and reads run fully concurrently (the engine's query
path is read-only over immutable segments and its caches are
lock-guarded); writes serialize PER INDEX at the ENGINE boundary, not
here — `IndexService.write_lock` is acquired by the client layer after
alias/data-stream/pipeline-`_index` resolution (rest/client.py), the
analog of the reference's per-shard engine locks
(`index/engine/InternalEngine.java:1`), and `Node.meta_lock` serializes
cluster-metadata mutations (create/delete/open/close, dynamic
auto-create). So concurrent HTTP writers on different indices proceed in
parallel, two names resolving to one engine share one lock, and this
transport stays lock-free.

Usage:
    srv = HttpServer(client)          # or HttpServer(port=9200)
    port = srv.start()                # background thread, returns port
    ... real HTTP against http://localhost:{port} ...
    srv.stop()
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, unquote, urlparse

from .client import ApiError, IndexNotFoundError, RestClient


def _truthy(v) -> bool:
    return str(v).lower() in ("1", "true", "yes", "")


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "opensearch-tpu"

    # quiet the default stderr access log
    def log_message(self, fmt, *args):
        pass

    # ---------------- plumbing ----------------

    def _body(self):
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b""
        return raw.decode("utf-8") if raw else ""

    def _json_body(self) -> Optional[dict]:
        raw = self._body()
        if not raw.strip():
            return None
        return json.loads(raw)

    def _ndjson_body(self):
        return [json.loads(ln) for ln in self._body().splitlines()
                if ln.strip()]

    def _send(self, status: int, payload, content_type="application/json"):
        if isinstance(payload, (dict, list)):
            data = json.dumps(payload).encode("utf-8")
        else:
            data = str(payload).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(data)

    def _dispatch(self):
        try:
            url = urlparse(self.path)
            parts = [unquote(p) for p in url.path.split("/") if p]
            # keep_blank_values: the bare `?refresh` idiom must read as true
            params = {k: v[0] for k, v in
                      parse_qs(url.query, keep_blank_values=True).items()}
            status, payload = self._route(self.command, parts, params)
            self._send(status, payload)
        except ApiError as e:
            self._send(e.status, e.body())
        except IndexNotFoundError as e:
            self._send(404, {"error": {"type": "index_not_found_exception",
                                       "reason": str(e)}, "status": 404})
        except json.JSONDecodeError as e:
            self._send(400, {"error": {"type": "parsing_exception",
                                       "reason": str(e)}, "status": 400})
        except ValueError as e:
            self._send(400, {"error": {"type": "illegal_argument_exception",
                                       "reason": str(e)}, "status": 400})
        except Exception as e:                         # noqa: BLE001
            self._send(500, {"error": {"type": type(e).__name__,
                                       "reason": str(e)}, "status": 500})

    do_GET = do_POST = do_PUT = do_DELETE = do_HEAD = _dispatch

    # ---------------- routing ----------------

    def _route(self, method: str, parts, params) -> Tuple[int, object]:
        # node-to-node RPC surface for the multi-process cluster
        # (cluster/distnode.py); absent unless a DistClusterNode owns this
        # server
        if parts and parts[0] == "_internal":
            # read through the HttpServer wrapper so `srv.dist = node` works
            # whether assigned before or after start()
            owner = getattr(self.server, "owner", None)
            dist = owner.dist if owner is not None else None
            if dist is None:
                return 404, {"error": {
                    "type": "resource_not_found_exception",
                    "reason": "not a cluster transport endpoint"}}
            return dist.handle_internal(method, parts,
                                        self._json_body() or {})
        c: RestClient = self.server.client            # type: ignore

        if not parts:
            return 200, {"name": c.node.node_name,
                         "cluster_name": c.node.metadata.cluster_name,
                         "version": {"distribution": "opensearch-tpu"},
                         "tagline": "TPU-native search"}

        head = parts[0]
        # ---- cluster-level ----
        if head == "_cluster":
            if len(parts) >= 2 and parts[1] == "health":
                return 200, c.cluster.health(parts[2] if len(parts) > 2
                                             else None)
            if len(parts) >= 2 and parts[1] == "settings":
                if method == "PUT":
                    return 200, c.cluster.put_settings(self._json_body())
                return 200, c.cluster.get_settings()
            raise ApiError(400, "illegal_argument_exception",
                           f"unsupported _cluster route {parts}")
        if head == "_nodes":
            return 200, c.nodes_stats()
        if head == "_cat":
            kind = parts[1] if len(parts) > 1 else "indices"
            fn = getattr(c.cat, kind, None)
            if fn is None:
                raise ApiError(400, "illegal_argument_exception",
                               f"unknown _cat endpoint [{kind}]")
            rows = fn()
            if params.get("format") == "json":
                return 200, rows
            text = "\n".join(" ".join(str(v) for v in r.values())
                             for r in rows)
            return 200, text + "\n"
        if head == "_search":
            if len(parts) > 1 and parts[1] == "scroll":
                body = self._json_body() or {}
                # id may arrive in the body, query string, or URL path
                sid = body.get("scroll_id", params.get("scroll_id"))
                if sid is None and len(parts) > 2:
                    sid = parts[2]
                if method == "DELETE":
                    return 200, c.clear_scroll(sid)
                return 200, c.scroll(sid, scroll=body.get(
                    "scroll", params.get("scroll")))
            return 200, c.search("_all", self._json_body() or {},
                                 scroll=params.get("scroll"))
        if head == "_msearch":
            return 200, c.msearch(self._ndjson_body())
        if head == "_bulk":
            return 200, c.bulk(self._ndjson_body(),
                               refresh=_truthy(params.get("refresh",
                                                          "false")))
        if head == "_mget":
            return 200, c.mget(self._json_body())
        if head == "_tasks":
            if parts[-1] == "_cancel":
                if method != "POST":
                    raise ApiError(405, "method_not_allowed",
                                   "cancel requires POST")
                if len(parts) >= 3:
                    return 200, c.cancel_task(parts[1])
                # cancel-all form: POST /_tasks/_cancel[?actions=...]
                cancelled = []
                for t in c.node.tasks.list(params.get("actions")):
                    if c.node.tasks.cancel(t["id"]):
                        cancelled.append(t["id"])
                return 200, {"nodes": {}, "cancelled": cancelled}
            if len(parts) == 2:
                # single-task form: GET /_tasks/{id}
                for t in c.node.tasks.list(None):
                    if str(t["id"]) == parts[1]:
                        return 200, {"completed": t.get("cancelled", False)
                                     or not t.get("running", True),
                                     "task": t}
                raise ApiError(404, "resource_not_found_exception",
                               f"task [{parts[1]}] not found")
            return 200, c.tasks(params.get("actions"))
        if head == "_stats":
            return 200, c.node.stats()
        if head == "_remotestore":
            if len(parts) > 1 and parts[1] == "_restore":
                if method != "POST":
                    raise ApiError(405, "method_not_allowed",
                                   "restore requires POST")
                return 200, c.remotestore_restore(self._json_body() or {})
        if head == "_index_template" and len(parts) == 2:
            if method == "PUT":
                return 200, c.indices.put_index_template(
                    parts[1], self._json_body())
            if method == "HEAD":
                return (200 if c.indices.exists_index_template(parts[1])
                        else 404), {}
            if method == "DELETE":
                return 200, c.indices.delete_index_template(parts[1])

        # ---- index-level: /{index}[/...] ----
        index = head
        rest = parts[1:]
        if not rest:
            if method == "PUT":
                return 200, c.indices.create(index, self._json_body())
            if method == "DELETE":
                return 200, c.indices.delete(index)
            if method == "HEAD":
                return (200 if c.indices.exists(index) else 404), {}
            return 200, c.indices.get(index)

        op = rest[0]
        if op == "_doc":
            doc_id = rest[1] if len(rest) > 1 else None
            refresh = _truthy(params.get("refresh", "false"))
            if method in ("PUT", "POST"):
                resp = c.index(index, self._json_body() or {},
                               id=doc_id, refresh=refresh,
                               routing=params.get("routing"))
                # reference: 201 on create, 200 on overwrite-update
                return (201 if resp.get("result") == "created"
                        else 200), resp
            if method == "GET":
                return 200, c.get(index, doc_id,
                                  routing=params.get("routing"))
            if method == "HEAD":
                return (200 if c.exists(index, doc_id) else 404), {}
            if method == "DELETE":
                return 200, c.delete(index, doc_id,
                                     routing=params.get("routing"))
        if op == "_create" and len(rest) > 1:
            return 201, c.create(index, rest[1], self._json_body() or {})
        if op == "_update" and len(rest) > 1:
            return 200, c.update(index, rest[1], self._json_body() or {},
                                 routing=params.get("routing"))
        if op == "_search":
            return 200, c.search(index, self._json_body() or {},
                                 scroll=params.get("scroll"))
        if op == "_msearch":
            body = self._ndjson_body()
            return 200, c.msearch(body, index=index)
        if op == "_count":
            return 200, c.count(index, self._json_body())
        if op == "_bulk":
            return 200, c.bulk(self._ndjson_body(), index=index,
                               refresh=_truthy(params.get("refresh",
                                                          "false")))
        if op in ("_forcemerge", "_open", "_close") \
                and method not in ("POST", "PUT"):
            # POST-only routes (reference RestController; note the
            # reference DOES register GET for _refresh/_flush, so those
            # stay method-agnostic): a probe must never close an index
            raise ApiError(405, "method_not_allowed",
                           f"{op} requires POST")
        if op == "_refresh":
            return 200, c.indices.refresh(index)
        if op == "_flush":
            return 200, c.indices.flush(index)
        if op == "_forcemerge":
            return 200, c.indices.forcemerge(index)
        if op == "_mapping":
            if method == "PUT":
                return 200, c.indices.put_mapping(index,
                                                  self._json_body())
            return 200, c.indices.get_mapping(index)
        if op == "_settings":
            if method == "PUT":
                return 200, c.indices.put_settings(index,
                                                   self._json_body())
            return 200, c.indices.get_settings(index)
        if op == "_open":
            return 200, c.indices.open(index)
        if op == "_close":
            return 200, c.indices.close(index)
        raise ApiError(400, "illegal_argument_exception",
                       f"unsupported route {method} /{'/'.join(parts)}")


class HttpServer:
    """Threaded HTTP transport bound to a RestClient."""

    def __init__(self, client: Optional[RestClient] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.client = client or RestClient()
        self.host = host
        self.port = port
        self.dist = None          # DistClusterNode when clustered
        self._srv: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        self._srv = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._srv.client = self.client                 # type: ignore
        self._srv.owner = self                         # type: ignore
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None
