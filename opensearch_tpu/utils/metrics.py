"""Unified telemetry: a thread-safe metrics registry for the query path.

Reference analogs: `telemetry/metrics/MetricsRegistry.java` (counters /
gauges / histograms behind one named registry) and the percentile plumbing
of `search/profile/`. Three instrument kinds:

- `Counter` — monotonic (or reset-by-tests) numeric cell; `inc()` is
  atomic under the cell's lock, so concurrent searches never lose counts
  the way the old plain-dict `STATS[k] += 1` pattern did.
- `Gauge` — last-write-wins numeric cell.
- `LatencyHistogram` — a DDSketch-style log-binned sketch reusing the
  SAME bin math the `percentile_ranks` aggregation runs on device
  (`ops/aggs.py: ddsketch_bin/ddsketch_value`, ~0.5% relative error,
  mergeable by bin-wise addition). Bins are value-independent global
  constants, so percentile queries are deterministic: the same recorded
  multiset always yields the same p50/p95/p99 no matter the record order
  or thread interleaving.

The process-default registry is `METRICS`; `_nodes/stats` serves its
snapshot (per-stage p50/p95/p99 + jit compile-vs-execute attribution) and
`rest/http_server.py` exposes a Prometheus text rendition at `/_metrics`.
Disabled mode (`METRICS.enabled = False`) turns `timer()` and histogram
`record()` into near-no-ops — the fastpath overhead guard in
tests/test_telemetry.py pins that cost.

Fleet federation (docs/OBSERVABILITY.md "fleet"): sketches are mergeable
by bin-wise addition — `LatencyHistogram.merge_wire` / `merge_sketches`
let a coordinator compute TRUE fleet-wide percentiles from per-node
sketches instead of averaging per-node percentiles (which is wrong for
any skewed distribution). `MetricsRegistry.to_wire()` is the JSON-safe
scrape payload a node answers on `/_internal/stats`: counters and gauges
as plain values, histograms in wire form (bins keyed by stringified bin
index). Merging is exact: a sketch merged from N nodes holds the same
bin multiset as one sketch fed the union stream, so nearest-rank
percentile queries agree bit-for-bit (tests/test_observatory.py pins
commutativity, associativity, and union parity).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

__all__ = ["Counter", "Gauge", "LatencyHistogram", "MetricsRegistry",
           "CounterGroup", "render_prometheus", "METRICS",
           "merge_sketches", "sketch_percentile"]


_SKETCH_FNS = None


def _sketch_fns():
    """The proven DDSketch bin math from the percentile_ranks agg
    (ops/aggs.py). Imported lazily — ops pulls in jax, and utils must
    stay importable without touching the device stack — then cached so
    hot-path records don't re-resolve the import per sample."""
    global _SKETCH_FNS
    if _SKETCH_FNS is None:
        from ..ops.aggs import ddsketch_bin, ddsketch_value
        _SKETCH_FNS = (ddsketch_bin, ddsketch_value)
    return _SKETCH_FNS


class Counter:
    """Atomic numeric cell. Holds ints until a float is added (wall-ms
    accumulators), mirroring the old STATS/RESCORE_STATS value types."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1) -> None:
        with self._lock:
            self._value += n

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class LatencyHistogram:
    """Sparse DDSketch: bin index -> count. `record` takes milliseconds.

    Percentile queries use the nearest-rank definition (rank
    ceil(p/100 * n)) over the sorted bins, then return the bin's
    representative value — deterministic for a given recorded multiset,
    within the sketch's ~0.5% relative error of the exact empirical
    percentile (tests pin this against numpy)."""

    __slots__ = ("name", "_bins", "count", "sum_ms", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._bins: Dict[int, int] = {}
        self.count = 0
        self.sum_ms = 0.0
        self._lock = threading.Lock()

    def record(self, ms: float) -> None:
        dd_bin, _ = _sketch_fns()
        b = dd_bin(float(ms))
        with self._lock:
            self._bins[b] = self._bins.get(b, 0) + 1
            self.count += 1
            self.sum_ms += float(ms)

    def record_many(self, values) -> None:
        """Vectorized `record` for a batch (the refresh-to-visible path:
        one refresh lands one delta per published doc). Binning runs the
        same f32 arithmetic as `ops/aggs.ddsketch_bin` element-wise, so a
        value records into the identical bin either way (tests pin scalar
        /vector parity), and the whole batch costs ONE lock acquisition."""
        import numpy as np
        from ..ops.aggs import DD_HALF, DD_LN_GAMMA, DD_MIN_MAG
        arr = np.asarray(values, dtype=np.float64)
        if arr.size == 0:
            return
        mag = np.abs(arr).astype(np.float32)
        ln = np.log(np.maximum(mag, np.float32(DD_MIN_MAG)))
        idx = np.floor((ln - np.float32(np.log(DD_MIN_MAG)))
                       / np.float32(DD_LN_GAMMA)).astype(np.int64)
        np.clip(idx, 0, DD_HALF - 1, out=idx)
        b = np.where(arr > 0, DD_HALF + 1 + idx,
                     np.where(arr < 0, DD_HALF - 1 - idx, DD_HALF))
        bins_u, counts = np.unique(b, return_counts=True)
        batch_sum = float(arr.sum())
        with self._lock:
            for bi, c in zip(bins_u.tolist(), counts.tolist()):
                self._bins[bi] = self._bins.get(bi, 0) + c
            self.count += int(arr.size)
            self.sum_ms += batch_sum

    def percentile(self, p: float) -> Optional[float]:
        with self._lock:
            total = self.count
            bins = dict(self._bins)
        return sketch_percentile(bins, total, p)

    def snapshot(self, percentiles: Sequence[float] = (50, 95, 99)) -> dict:
        out = {"count": self.count, "sum_ms": round(self.sum_ms, 3)}
        for p in percentiles:
            v = self.percentile(p)
            out[f"p{int(p) if float(p).is_integer() else p}_ms"] = (
                round(v, 4) if v is not None else None)
        return out

    # -- federation: sketches cross the wire and merge bin-wise --

    def to_wire(self) -> dict:
        """JSON-safe serialized form (bin keys stringified). The bins are
        global constants of the DDSketch mapping, so wire forms from
        different nodes merge without any re-binning."""
        with self._lock:
            return {"bins": {str(b): c for b, c in self._bins.items()},
                    "count": self.count,
                    "sum_ms": round(self.sum_ms, 6)}

    def merge_wire(self, wire: dict) -> None:
        """Fold another sketch's wire form into this one (bin-wise add).
        Exact: merging preserves the bin multiset, so percentile queries
        on the merged sketch equal those on a sketch fed the union
        stream."""
        bins = wire.get("bins") or {}
        with self._lock:
            for b, c in bins.items():
                bi = int(b)
                self._bins[bi] = self._bins.get(bi, 0) + int(c)
            self.count += int(wire.get("count", 0))
            self.sum_ms += float(wire.get("sum_ms", 0.0))


def sketch_percentile(bins: Dict[int, int], total: int,
                      p: float) -> Optional[float]:
    """Nearest-rank percentile over sparse DDSketch bins (rank
    ceil(p/100 * n) over the sorted bins, returning the bin's
    representative value) — the single definition the instance
    percentile, windowed time-series deltas (obs/timeseries.py), and
    fleet-merged sketches (cluster federation) all share."""
    if total <= 0:
        return None
    _, dd_value = _sketch_fns()
    items = sorted(bins.items())
    if not items:
        return None
    rank = max(1, -(-int(p * total) // 100))     # ceil(p/100 * total)
    cum = 0
    for b, c in items:
        cum += c
        if cum >= rank:
            return float(dd_value(b))
    return float(dd_value(items[-1][0]))


def merge_sketches(wires: Sequence[dict]) -> dict:
    """Merge several sketch wire forms into one (bin-wise addition).
    Commutative and associative — the order nodes answer a fleet scrape
    in can never change the merged percentiles."""
    bins: Dict[int, int] = {}
    count = 0
    sum_ms = 0.0
    for w in wires:
        if not isinstance(w, dict):
            continue
        for b, c in (w.get("bins") or {}).items():
            bi = int(b)
            bins[bi] = bins.get(bi, 0) + int(c)
        count += int(w.get("count", 0))
        sum_ms += float(w.get("sum_ms", 0.0))
    return {"bins": {str(b): c for b, c in sorted(bins.items())},
            "count": count, "sum_ms": round(sum_ms, 6)}


def sketch_snapshot(wire: dict,
                    percentiles: Sequence[float] = (50, 95, 99)) -> dict:
    """The `LatencyHistogram.snapshot` shape computed from a wire form —
    what `_cluster/stats` serves for fleet-merged sketches."""
    bins = {int(b): int(c) for b, c in (wire.get("bins") or {}).items()}
    total = int(wire.get("count", 0))
    out = {"count": total, "sum_ms": round(float(wire.get("sum_ms", 0.0)),
                                           3)}
    for p in percentiles:
        v = sketch_percentile(bins, total, p)
        out[f"p{int(p) if float(p).is_integer() else p}_ms"] = (
            round(v, 4) if v is not None else None)
    return out


class MetricsRegistry:
    """Named instruments behind one lock for creation; each instrument
    carries its own fine-grained lock for updates."""

    def __init__(self):
        self.enabled = True
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, LatencyHistogram] = {}

    # -- instrument factories (create-on-first-use, stable identity) --

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def histogram(self, name: str) -> LatencyHistogram:
        h = self._hists.get(name)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(name, LatencyHistogram(name))
        return h

    @contextlib.contextmanager
    def timer(self, name: str):
        """Record a wall-time span (perf_counter, never time.time) into
        the named latency histogram. Near-free when disabled."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.histogram(name).record(
                (time.perf_counter() - t0) * 1000.0)

    # -- queries --

    def percentiles(self, name: str,
                    ps: Sequence[float] = (50, 95, 99)) -> dict:
        h = self._hists.get(name)
        if h is None:
            return {}
        return h.snapshot(ps)

    def stage_percentiles(self, prefix: str = "") -> Dict[str, dict]:
        """p50/p95/p99 + count for every latency histogram (optionally
        name-filtered), sorted by name — the `_nodes/stats` telemetry
        stage block."""
        with self._lock:
            hists = sorted((n, h) for n, h in self._hists.items()
                           if n.startswith(prefix))
        return {n: h.snapshot() for n, h in hists}

    def snapshot(self) -> dict:
        """Deterministic full dump: sorted names, plain values."""
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(self._hists.items())
        return {
            "counters": {n: c.value for n, c in counters},
            "gauges": {n: g.value for n, g in gauges},
            "histograms": {n: h.snapshot() for n, h in hists},
        }

    def to_wire(self) -> dict:
        """JSON-safe federation payload: counters/gauges as plain values,
        histograms in mergeable wire form — what a node answers on a
        `/_internal/stats` fleet scrape (cluster/distnode.py)."""
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(self._hists.items())
        return {
            "counters": {n: c.value for n, c in counters},
            "gauges": {n: g.value for n, g in gauges},
            "histograms": {n: h.to_wire() for n, h in hists},
        }

    def reset(self) -> None:
        """Drop every instrument — isolation hook for bench runs and
        tests that diff a cold registry. Instruments obtained before a
        reset keep working but detach from future snapshots."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


class CounterGroup:
    """A dict-shaped view over a family of registry counters — the
    migration shim for `fastpath.STATS` / `fastpath.RESCORE_STATS`.

    Reads (`d[k]`, `dict(d)`, iteration) serve the exact key set and value
    types the old plain dicts had, so `_nodes/stats` shapes and the
    delta-diff idiom in tests/bench stay byte-compatible. Writes go
    through `inc()` (atomic) instead of the racy `d[k] += 1`; plain
    `d[k] = v` assignment still works for test resets."""

    __slots__ = ("_registry", "_prefix", "_keys")

    def __init__(self, registry: MetricsRegistry, prefix: str,
                 initial: Dict[str, Any]):
        self._registry = registry
        self._prefix = prefix
        self._keys = list(initial)
        for k, v in initial.items():
            self._counter(k).set(v)

    def _counter(self, key: str) -> Counter:
        return self._registry.counter(f"{self._prefix}.{key}")

    def inc(self, key: str, n=1) -> None:
        if key not in self._keys:
            raise KeyError(key)
        self._counter(key).inc(n)

    def __getitem__(self, key: str):
        if key not in self._keys:
            raise KeyError(key)
        return self._counter(key).value

    def __setitem__(self, key: str, v) -> None:
        if key not in self._keys:
            raise KeyError(key)
        self._counter(key).set(v)

    def keys(self) -> List[str]:
        return list(self._keys)

    def __iter__(self) -> Iterator[str]:
        return iter(self._keys)

    def __len__(self) -> int:
        return len(self._keys)

    def __contains__(self, key) -> bool:
        return key in self._keys

    def items(self):
        return [(k, self[k]) for k in self._keys]

    def values(self):
        return [self[k] for k in self._keys]

    def copy(self) -> dict:
        return dict(self.items())

    def __repr__(self) -> str:
        return f"CounterGroup({self._prefix}, {self.copy()!r})"


def _prom_name(name: str) -> str:
    """Stable metric-name sanitization: every character outside
    Prometheus's [a-zA-Z0-9_] maps to ONE underscore (no run collapsing
    — collapsing would let `a.b` and `a..b` collide), and ASCII-only
    (any non-ASCII alphanumeric maps to `_` too, so the mapping is the
    same on every locale). The `ostpu_` prefix keeps the result from
    starting with a digit."""
    return "ostpu_" + "".join(
        c if (c.isascii() and (c.isalnum() or c == "_")) else "_"
        for c in name)


def _prom_label_value(v: str) -> str:
    """Label-value escaping per the text exposition format: backslash,
    double quote, and newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


# the bounded query-insights exposition: metric name -> (entry field,
# HELP text). Labels carry the shape HASH only — raw query text never
# reaches a label position (oslint OSL602; obs/insights.py)
_INSIGHTS_SERIES = (
    ("insights.top_query.count", "count",
     "estimated request count of a top-K query shape (space-saving "
     "bound; label is the shape hash, never query text)"),
    ("insights.top_query.latency_ms_total", "latency_sum_ms",
     "total recorded latency of a top-K query shape (ms)"),
    ("insights.top_query.bytes_moved_total", "bytes_moved",
     "total device bytes moved by a top-K query shape"),
)


def render_prometheus(registry: MetricsRegistry,
                      node: Optional[str] = None,
                      insights: Optional[Sequence[dict]] = None) -> str:
    """Prometheus text exposition format 0.0.4. Counters and gauges render
    directly; latency histograms render as summaries (quantile series +
    _count/_sum) since DDSketch quantiles are what the registry serves.

    Every sample line carries a `# HELP` + `# TYPE` header pair, and when
    `node` is given every sample gets a `node` label — without it, a
    Prometheus federating several opensearch-tpu processes would collapse
    their identically-named series into one incoherent stream.

    `insights` is the BOUNDED top-K query-shape export from
    `obs/insights.py QueryInsights.prometheus_top()`: one sample per
    (metric, fingerprint) pair, at most K fingerprints — workload
    cardinality can never inflate the scrape, and the only label value
    is the shape hash."""
    snap = registry.snapshot()
    nl = f'node="{_prom_label_value(node)}"' if node is not None else ""

    def labeled(pn: str, extra: str = "") -> str:
        labels = ",".join(x for x in (nl, extra) if x)
        return f"{pn}{{{labels}}}" if labels else pn

    lines: List[str] = []
    for n, v in snap["counters"].items():
        pn = _prom_name(n)
        lines.append(f"# HELP {pn} registry counter {n}")
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{labeled(pn)} {v}")
    for n, v in snap["gauges"].items():
        pn = _prom_name(n)
        lines.append(f"# HELP {pn} registry gauge {n}")
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{labeled(pn)} {v}")
    for n, h in snap["histograms"].items():
        pn = _prom_name(n)
        if not pn.endswith("_ms"):     # unit suffix, never doubled
            pn += "_ms"
        lines.append(f"# HELP {pn} DDSketch latency summary {n} (ms)")
        lines.append(f"# TYPE {pn} summary")
        for q, key in ((0.5, "p50_ms"), (0.95, "p95_ms"), (0.99, "p99_ms")):
            if h.get(key) is not None:
                qlab = 'quantile="%s"' % q
                lines.append(f"{labeled(pn, qlab)} {h[key]}")
        lines.append(f"{labeled(pn + '_sum')} {h['sum_ms']}")
        lines.append(f"{labeled(pn + '_count')} {h['count']}")
    for name, field, help_ in (_INSIGHTS_SERIES if insights else ()):
        pn = _prom_name(name)
        lines.append(f"# HELP {pn} {help_}")
        lines.append(f"# TYPE {pn} gauge")
        for e in insights:
            fplab = 'fingerprint="%s"' % _prom_label_value(
                str(e.get("fingerprint", "")))
            lines.append(f"{labeled(pn, fplab)} {e.get(field, 0)}")
    return "\n".join(lines) + "\n"


# process-default registry (one node per process, like utils/trace.TRACER)
METRICS = MetricsRegistry()
