"""Unified telemetry: a thread-safe metrics registry for the query path.

Reference analogs: `telemetry/metrics/MetricsRegistry.java` (counters /
gauges / histograms behind one named registry) and the percentile plumbing
of `search/profile/`. Three instrument kinds:

- `Counter` — monotonic (or reset-by-tests) numeric cell; `inc()` is
  atomic under the cell's lock, so concurrent searches never lose counts
  the way the old plain-dict `STATS[k] += 1` pattern did.
- `Gauge` — last-write-wins numeric cell.
- `LatencyHistogram` — a DDSketch-style log-binned sketch reusing the
  SAME bin math the `percentile_ranks` aggregation runs on device
  (`ops/aggs.py: ddsketch_bin/ddsketch_value`, ~0.5% relative error,
  mergeable by bin-wise addition). Bins are value-independent global
  constants, so percentile queries are deterministic: the same recorded
  multiset always yields the same p50/p95/p99 no matter the record order
  or thread interleaving.

The process-default registry is `METRICS`; `_nodes/stats` serves its
snapshot (per-stage p50/p95/p99 + jit compile-vs-execute attribution) and
`rest/http_server.py` exposes a Prometheus text rendition at `/_metrics`.
Disabled mode (`METRICS.enabled = False`) turns `timer()` and histogram
`record()` into near-no-ops — the fastpath overhead guard in
tests/test_telemetry.py pins that cost.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

__all__ = ["Counter", "Gauge", "LatencyHistogram", "MetricsRegistry",
           "CounterGroup", "render_prometheus", "METRICS"]


_SKETCH_FNS = None


def _sketch_fns():
    """The proven DDSketch bin math from the percentile_ranks agg
    (ops/aggs.py). Imported lazily — ops pulls in jax, and utils must
    stay importable without touching the device stack — then cached so
    hot-path records don't re-resolve the import per sample."""
    global _SKETCH_FNS
    if _SKETCH_FNS is None:
        from ..ops.aggs import ddsketch_bin, ddsketch_value
        _SKETCH_FNS = (ddsketch_bin, ddsketch_value)
    return _SKETCH_FNS


class Counter:
    """Atomic numeric cell. Holds ints until a float is added (wall-ms
    accumulators), mirroring the old STATS/RESCORE_STATS value types."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1) -> None:
        with self._lock:
            self._value += n

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class LatencyHistogram:
    """Sparse DDSketch: bin index -> count. `record` takes milliseconds.

    Percentile queries use the nearest-rank definition (rank
    ceil(p/100 * n)) over the sorted bins, then return the bin's
    representative value — deterministic for a given recorded multiset,
    within the sketch's ~0.5% relative error of the exact empirical
    percentile (tests pin this against numpy)."""

    __slots__ = ("name", "_bins", "count", "sum_ms", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._bins: Dict[int, int] = {}
        self.count = 0
        self.sum_ms = 0.0
        self._lock = threading.Lock()

    def record(self, ms: float) -> None:
        dd_bin, _ = _sketch_fns()
        b = dd_bin(float(ms))
        with self._lock:
            self._bins[b] = self._bins.get(b, 0) + 1
            self.count += 1
            self.sum_ms += float(ms)

    def percentile(self, p: float) -> Optional[float]:
        _, dd_value = _sketch_fns()
        with self._lock:
            total = self.count
            items = sorted(self._bins.items())
        if total == 0:
            return None
        rank = max(1, -(-int(p * total) // 100))     # ceil(p/100 * total)
        cum = 0
        for b, c in items:
            cum += c
            if cum >= rank:
                return float(dd_value(b))
        return float(dd_value(items[-1][0]))

    def snapshot(self, percentiles: Sequence[float] = (50, 95, 99)) -> dict:
        out = {"count": self.count, "sum_ms": round(self.sum_ms, 3)}
        for p in percentiles:
            v = self.percentile(p)
            out[f"p{int(p) if float(p).is_integer() else p}_ms"] = (
                round(v, 4) if v is not None else None)
        return out


class MetricsRegistry:
    """Named instruments behind one lock for creation; each instrument
    carries its own fine-grained lock for updates."""

    def __init__(self):
        self.enabled = True
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, LatencyHistogram] = {}

    # -- instrument factories (create-on-first-use, stable identity) --

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def histogram(self, name: str) -> LatencyHistogram:
        h = self._hists.get(name)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(name, LatencyHistogram(name))
        return h

    @contextlib.contextmanager
    def timer(self, name: str):
        """Record a wall-time span (perf_counter, never time.time) into
        the named latency histogram. Near-free when disabled."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.histogram(name).record(
                (time.perf_counter() - t0) * 1000.0)

    # -- queries --

    def percentiles(self, name: str,
                    ps: Sequence[float] = (50, 95, 99)) -> dict:
        h = self._hists.get(name)
        if h is None:
            return {}
        return h.snapshot(ps)

    def stage_percentiles(self, prefix: str = "") -> Dict[str, dict]:
        """p50/p95/p99 + count for every latency histogram (optionally
        name-filtered), sorted by name — the `_nodes/stats` telemetry
        stage block."""
        with self._lock:
            hists = sorted((n, h) for n, h in self._hists.items()
                           if n.startswith(prefix))
        return {n: h.snapshot() for n, h in hists}

    def snapshot(self) -> dict:
        """Deterministic full dump: sorted names, plain values."""
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(self._hists.items())
        return {
            "counters": {n: c.value for n, c in counters},
            "gauges": {n: g.value for n, g in gauges},
            "histograms": {n: h.snapshot() for n, h in hists},
        }

    def reset(self) -> None:
        """Drop every instrument — isolation hook for bench runs and
        tests that diff a cold registry. Instruments obtained before a
        reset keep working but detach from future snapshots."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


class CounterGroup:
    """A dict-shaped view over a family of registry counters — the
    migration shim for `fastpath.STATS` / `fastpath.RESCORE_STATS`.

    Reads (`d[k]`, `dict(d)`, iteration) serve the exact key set and value
    types the old plain dicts had, so `_nodes/stats` shapes and the
    delta-diff idiom in tests/bench stay byte-compatible. Writes go
    through `inc()` (atomic) instead of the racy `d[k] += 1`; plain
    `d[k] = v` assignment still works for test resets."""

    __slots__ = ("_registry", "_prefix", "_keys")

    def __init__(self, registry: MetricsRegistry, prefix: str,
                 initial: Dict[str, Any]):
        self._registry = registry
        self._prefix = prefix
        self._keys = list(initial)
        for k, v in initial.items():
            self._counter(k).set(v)

    def _counter(self, key: str) -> Counter:
        return self._registry.counter(f"{self._prefix}.{key}")

    def inc(self, key: str, n=1) -> None:
        if key not in self._keys:
            raise KeyError(key)
        self._counter(key).inc(n)

    def __getitem__(self, key: str):
        if key not in self._keys:
            raise KeyError(key)
        return self._counter(key).value

    def __setitem__(self, key: str, v) -> None:
        if key not in self._keys:
            raise KeyError(key)
        self._counter(key).set(v)

    def keys(self) -> List[str]:
        return list(self._keys)

    def __iter__(self) -> Iterator[str]:
        return iter(self._keys)

    def __len__(self) -> int:
        return len(self._keys)

    def __contains__(self, key) -> bool:
        return key in self._keys

    def items(self):
        return [(k, self[k]) for k in self._keys]

    def values(self):
        return [self[k] for k in self._keys]

    def copy(self) -> dict:
        return dict(self.items())

    def __repr__(self) -> str:
        return f"CounterGroup({self._prefix}, {self.copy()!r})"


def _prom_name(name: str) -> str:
    return "ostpu_" + "".join(
        c if (c.isalnum() or c == "_") else "_" for c in name)


def render_prometheus(registry: MetricsRegistry) -> str:
    """Prometheus text exposition format 0.0.4. Counters and gauges render
    directly; latency histograms render as summaries (quantile series +
    _count/_sum) since DDSketch quantiles are what the registry serves."""
    snap = registry.snapshot()
    lines: List[str] = []
    for n, v in snap["counters"].items():
        pn = _prom_name(n)
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {v}")
    for n, v in snap["gauges"].items():
        pn = _prom_name(n)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {v}")
    for n, h in snap["histograms"].items():
        pn = _prom_name(n) + "_ms"
        lines.append(f"# TYPE {pn} summary")
        for q, key in ((0.5, "p50_ms"), (0.95, "p95_ms"), (0.99, "p99_ms")):
            if h.get(key) is not None:
                lines.append(f'{pn}{{quantile="{q}"}} {h[key]}')
        lines.append(f"{pn}_sum {h['sum_ms']}")
        lines.append(f"{pn}_count {h['count']}")
    return "\n".join(lines) + "\n"


# process-default registry (one node per process, like utils/trace.TRACER)
METRICS = MetricsRegistry()
