"""Request tracing: nested spans with a ring buffer of finished traces.

Reference analog: `telemetry/tracing/Tracer.java` (+ the telemetry-otel
plugin). Spans carry name/attributes/duration and parent links via a
contextvar, so instrumented layers (REST parse, per-shard query phase,
reduce, fetch) nest naturally without passing a context object around.
No exporter: completed root spans land in a bounded in-memory ring the
stats API serves — the deterministic, dependency-free equivalent of an
OTel in-memory span processor."""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

_current: contextvars.ContextVar = contextvars.ContextVar(
    "opensearch_tpu_span", default=None)


class Span:
    __slots__ = ("span_id", "name", "attributes", "start", "end", "children",
                 "parent")

    def __init__(self, span_id: int, name: str, attributes: Optional[dict],
                 parent: Optional["Span"]):
        self.span_id = span_id
        self.name = name
        self.attributes = dict(attributes or {})
        self.start = time.monotonic()
        self.end: Optional[float] = None
        self.children: List["Span"] = []
        self.parent = parent

    def to_dict(self) -> dict:
        dur = ((self.end if self.end is not None else time.monotonic())
               - self.start)
        return {"name": self.name, "span_id": self.span_id,
                "duration_ms": round(dur * 1000.0, 3),
                **({"attributes": self.attributes} if self.attributes else {}),
                **({"children": [c.to_dict() for c in self.children]}
                   if self.children else {})}


class Tracer:
    def __init__(self, max_traces: int = 256, enabled: bool = True):
        self.enabled = enabled
        self._ids = itertools.count(1)
        self._traces: deque = deque(maxlen=max_traces)
        self._lock = threading.Lock()
        self.span_count = 0

    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        if not self.enabled:
            yield None
            return
        parent = _current.get()
        s = Span(next(self._ids), name, attributes, parent)
        if parent is not None:
            parent.children.append(s)
        token = _current.set(s)
        try:
            yield s
        finally:
            _current.reset(token)
            s.end = time.monotonic()
            with self._lock:
                self.span_count += 1
                if parent is None:
                    self._traces.append(s)

    def set_attribute(self, key: str, value: Any) -> None:
        s = _current.get()
        if s is not None:
            s.attributes[key] = value

    def traces(self, limit: int = 20) -> List[dict]:
        with self._lock:
            items = list(self._traces)[-limit:]
        return [s.to_dict() for s in reversed(items)]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"enabled": self.enabled, "spans": self.span_count,
                    "retained_traces": len(self._traces)}


# process-default tracer (one node per process, like the fielddata breaker)
TRACER = Tracer()
