"""Request tracing: nested spans with a ring buffer of finished traces.

Reference analog: `telemetry/tracing/Tracer.java` (+ the telemetry-otel
plugin). Spans carry name/attributes/duration and parent links via a
contextvar, so instrumented layers (REST parse, per-shard query phase,
reduce, fetch) nest naturally without passing a context object around.
No exporter: completed root spans land in a bounded in-memory ring the
stats API serves — the deterministic, dependency-free equivalent of an
OTel in-memory span processor.

Thread-safety contract: spans may START on pool threads (the
context-carrying submit in `utils/threadpool.py` propagates the ambient
parent into workers), so `parent.children.append` happens concurrently —
child attachment is lock-guarded. Cross-process traces (cluster/distnode)
graft serialized remote subtrees via `attach_remote`, keyed to the wire
context from `wire_context()`."""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

_current: contextvars.ContextVar = contextvars.ContextVar(
    "opensearch_tpu_span", default=None)

# one lock for all child/remote attachment: attachment is rare relative to
# span bodies and a per-span lock would cost a slot on every span
_attach_lock = threading.Lock()


class Span:
    __slots__ = ("span_id", "name", "attributes", "start", "end", "children",
                 "parent", "remote_children")

    def __init__(self, span_id: int, name: str, attributes: Optional[dict],
                 parent: Optional["Span"]):
        self.span_id = span_id
        self.name = name
        self.attributes = dict(attributes or {})
        self.start = time.monotonic()
        self.end: Optional[float] = None
        self.children: List["Span"] = []
        # pre-serialized subtrees grafted from other processes (distnode
        # RPC responses carry the remote node's span tree)
        self.remote_children: List[dict] = []
        self.parent = parent

    def to_dict(self) -> dict:
        dur = ((self.end if self.end is not None else time.monotonic())
               - self.start)
        with _attach_lock:
            kids = list(self.children)
            remote = list(self.remote_children)
        children = [c.to_dict() for c in kids] + remote
        return {"name": self.name, "span_id": self.span_id,
                "duration_ms": round(dur * 1000.0, 3),
                **({"attributes": self.attributes} if self.attributes else {}),
                **({"children": children} if children else {})}


class Tracer:
    def __init__(self, max_traces: int = 256, enabled: bool = True):
        self.enabled = enabled
        self._ids = itertools.count(1)
        self._traces: deque = deque(maxlen=max_traces)
        self._lock = threading.Lock()
        self.span_count = 0

    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        if not self.enabled:
            yield None
            return
        parent = _current.get()
        s = Span(next(self._ids), name, attributes, parent)
        if parent is not None:
            # pool threads share a parent (context-carrying submit):
            # concurrent appends must not lose children
            with _attach_lock:
                parent.children.append(s)
        token = _current.set(s)
        try:
            yield s
        finally:
            _current.reset(token)
            s.end = time.monotonic()
            with self._lock:
                self.span_count += 1
                if parent is None:
                    self._traces.append(s)

    def current(self) -> Optional[Span]:
        return _current.get()

    def set_attribute(self, key: str, value: Any) -> None:
        s = _current.get()
        if s is not None:
            s.attributes[key] = value

    def attach_remote(self, span_dict: Optional[dict]) -> None:
        """Graft a serialized span subtree (from another process's tracer,
        carried over the RPC wire) under the current span, so a
        distributed search reads as ONE parent-child trace."""
        if not span_dict:
            return
        s = _current.get()
        if s is not None:
            with _attach_lock:
                s.remote_children.append(span_dict)

    def wire_context(self) -> Optional[dict]:
        """Serializable trace context for cross-node propagation: the
        remote side stamps these onto its local root span so a grafted
        subtree stays attributable even when read from the remote node's
        own ring."""
        s = _current.get()
        if s is None:
            return None
        root = s
        while root.parent is not None:
            root = root.parent
        return {"trace_root_id": root.span_id, "parent_span_id": s.span_id}

    def traces(self, limit: int = 20) -> List[dict]:
        with self._lock:
            items = list(self._traces)[-limit:]
        return [s.to_dict() for s in reversed(items)]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"enabled": self.enabled, "spans": self.span_count,
                    "retained_traces": len(self._traces)}


# process-default tracer (one node per process, like the fielddata breaker)
TRACER = Tracer()
