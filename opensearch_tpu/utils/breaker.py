"""Circuit breakers: HBM budget accounting. Analog of reference
`indices/breaker/HierarchyCircuitBreakerService.java` — instead of JVM heap,
we budget device HBM for segment residency and reject loads that would
exceed the limit.

Charge discipline (oslint OSL506): product code never calls
`add_estimate`/`release` directly — every HBM tenant registers an
attributed allocation with the ledger (`obs/hbm_ledger.py`), which
derives the breaker charge and guarantees the standing invariant
`sum(live charged ledger bytes) == breaker.used`."""

from __future__ import annotations


class CircuitBreakingException(Exception):
    """HTTP 429 analog (reference CircuitBreakingException)."""


class CircuitBreaker:
    def __init__(self, name: str, limit_bytes: int):
        self.name = name
        self.limit = limit_bytes
        self.used = 0
        self.trip_count = 0

    def add_estimate(self, bytes_: int, label: str = "") -> None:
        if self.used + bytes_ > self.limit:
            self.trip_count += 1
            raise CircuitBreakingException(
                f"[{self.name}] Data too large, data for [{label}] would be "
                f"[{self.used + bytes_}/{self.limit}] bytes")
        self.used += bytes_

    def release(self, bytes_: int) -> None:
        self.used = max(0, self.used - bytes_)

    def stats(self) -> dict:
        return {"limit_size_in_bytes": self.limit, "estimated_size_in_bytes": self.used,
                "tripped": self.trip_count}


class BreakerService:
    def __init__(self, device_limit_bytes: int = 12 << 30):
        # v5e has 16 GiB HBM; leave headroom for scratch + compiled programs.
        # fielddata covers the fastpath's device-resident layouts (aligned
        # postings + filter-specialized copies), the dominant HBM tenant —
        # give it most of the budget (reference fielddata default is 40% of
        # a JVM heap; HBM residency is this engine's whole design)
        self.breakers = {
            "fielddata": CircuitBreaker("fielddata",
                                        device_limit_bytes * 3 // 4),
            "request": CircuitBreaker("request", device_limit_bytes // 3),
            "parent": CircuitBreaker("parent", device_limit_bytes),
        }

    def breaker(self, name: str) -> CircuitBreaker:
        return self.breakers[name]

    def stats(self) -> dict:
        return {k: v.stats() for k, v in self.breakers.items()}
