"""Workload management: indexing pressure + search admission control.

Reference `index/IndexingPressure.java` (byte-budgeted write admission,
rejections counted) and `wlm/` workload groups (per-group concurrent-search
and token-bucket rate limits). Host-side accounting; device work is already
admission-controlled by the HBM circuit breakers."""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class PressureRejectedException(Exception):
    """HTTP 429 (reference OpenSearchRejectedExecutionException)."""


class IndexingPressure:
    """Byte budget for in-flight indexing (coordinating + primary combined;
    this runtime has one node so the split collapses)."""

    def __init__(self, limit_bytes: int = 64 << 20):
        self.limit = limit_bytes
        self.current = 0
        self.total = 0
        self.rejections = 0
        self._lock = threading.Lock()

    def acquire(self, nbytes: int) -> None:
        with self._lock:
            if self.current + nbytes > self.limit:
                self.rejections += 1
                raise PressureRejectedException(
                    f"rejecting operation of [{nbytes}] bytes: current "
                    f"[{self.current}] + operation would exceed "
                    f"[{self.limit}]")
            self.current += nbytes
            self.total += nbytes

    def release(self, nbytes: int) -> None:
        with self._lock:
            self.current = max(0, self.current - nbytes)

    def stats(self) -> dict:
        return {"current_bytes": self.current,
                "total_bytes": self.total,
                "limit_bytes": self.limit,
                "rejections": self.rejections}


class TokenBucket:
    def __init__(self, rate_per_s: float, burst: float):
        self.rate = rate_per_s
        self.burst = burst
        self.tokens = burst
        self.last = time.monotonic()
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0) -> bool:
        with self._lock:
            now = time.monotonic()
            self.tokens = min(self.burst,
                              self.tokens + (now - self.last) * self.rate)
            self.last = now
            if self.tokens >= n:
                self.tokens -= n
                return True
            return False


class WorkloadGroup:
    def __init__(self, name: str, search_rate: Optional[float] = None,
                 search_burst: Optional[float] = None):
        self.name = name
        # rate=0 means "block" (a bucket that never refills), not unlimited;
        # burst=0 is honored (only refill admits)
        self.bucket = (TokenBucket(search_rate,
                                   search_burst if search_burst is not None
                                   else max(search_rate, 1.0))
                       if search_rate is not None else None)
        self.searches = 0
        self.rejections = 0

    def admit_search(self) -> None:
        self.searches += 1
        if self.bucket is not None and not self.bucket.try_take():
            self.rejections += 1
            raise PressureRejectedException(
                f"workload group [{self.name}] search rate limit exceeded")

    def stats(self) -> dict:
        return {"searches": self.searches, "rejections": self.rejections,
                "rate_limited": self.bucket is not None}


class WorkloadManagement:
    def __init__(self, indexing_limit_bytes: int = 64 << 20):
        self.indexing = IndexingPressure(indexing_limit_bytes)
        self.groups: Dict[str, WorkloadGroup] = {
            "default": WorkloadGroup("default")}

    def put_group(self, name: str, search_rate: Optional[float] = None,
                  search_burst: Optional[float] = None) -> WorkloadGroup:
        g = WorkloadGroup(name, search_rate, search_burst)
        self.groups[name] = g
        return g

    def group(self, name: Optional[str]) -> WorkloadGroup:
        return self.groups.get(name or "default", self.groups["default"])

    def stats(self) -> dict:
        return {"indexing_pressure": self.indexing.stats(),
                "groups": {n: g.stats() for n, g in self.groups.items()}}
