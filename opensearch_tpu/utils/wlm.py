"""Workload management: indexing pressure + search admission control.

Reference `index/IndexingPressure.java` (byte-budgeted write admission,
rejections counted) and `wlm/` workload groups (per-group concurrent-search
and token-bucket rate limits). Host-side accounting; device work is already
admission-controlled by the HBM circuit breakers."""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class PressureRejectedException(Exception):
    """HTTP 429 (reference OpenSearchRejectedExecutionException).

    `retry_after_s`, when set by the rejecting layer (scheduler queue
    depth, remediation admission state), surfaces as the HTTP
    `Retry-After` header — a 429 that tells the client WHEN to come
    back instead of inviting an immediate hammer-retry."""

    def __init__(self, *args, retry_after_s: Optional[float] = None,
                 source: Optional[str] = None):
        super().__init__(*args)
        self.retry_after_s = retry_after_s
        self.source = source


class IndexingPressure:
    """Byte budget for in-flight indexing (coordinating + primary combined;
    this runtime has one node so the split collapses)."""

    def __init__(self, limit_bytes: int = 64 << 20):
        self.limit = limit_bytes
        self.current = 0
        self.total = 0
        self.rejections = 0
        self._lock = threading.Lock()

    def acquire(self, nbytes: int) -> None:
        with self._lock:
            if self.current + nbytes > self.limit:
                self.rejections += 1
                raise PressureRejectedException(
                    f"rejecting operation of [{nbytes}] bytes: current "
                    f"[{self.current}] + operation would exceed "
                    f"[{self.limit}]")
            self.current += nbytes
            self.total += nbytes

    def release(self, nbytes: int) -> None:
        with self._lock:
            self.current = max(0, self.current - nbytes)

    def stats(self) -> dict:
        return {"current_bytes": self.current,
                "total_bytes": self.total,
                "limit_bytes": self.limit,
                "rejections": self.rejections}


class TokenBucket:
    def __init__(self, rate_per_s: float, burst: float):
        self.rate = rate_per_s
        self.burst = burst
        self.tokens = burst
        self.last = time.monotonic()
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0) -> bool:
        with self._lock:
            now = time.monotonic()
            self.tokens = min(self.burst,
                              self.tokens + (now - self.last) * self.rate)
            self.last = now
            if self.tokens >= n:
                self.tokens -= n
                return True
            return False


class _UsageWindow:
    """Rolling window of (ts, seconds) samples -> consumption rate
    (seconds of search time per second of wall clock — 'cores used')."""

    def __init__(self, horizon_s: float = 30.0):
        self.horizon = horizon_s
        self._samples = []          # [(ts, secs)]
        self._lock = threading.Lock()

    def add(self, secs: float) -> None:
        now = time.monotonic()
        with self._lock:
            self._samples.append((now, secs))
            cut = now - self.horizon
            while self._samples and self._samples[0][0] < cut:
                self._samples.pop(0)

    def rate(self) -> float:
        now = time.monotonic()
        with self._lock:
            cut = now - self.horizon
            while self._samples and self._samples[0][0] < cut:
                self._samples.pop(0)
            return sum(s for _, s in self._samples) / self.horizon


class WorkloadGroup:
    """Reference `wlm/` QueryGroup: token-bucket rate limits AND
    resource-tracking limits. `resource_limits={"cpu": f}` caps the
    group's rolling search-time consumption at f cores; mode "monitor"
    only tracks (usage visible in stats), "enforced" rejects admission
    while the group is over its cap (QueryGroupService's enforcement)."""

    def __init__(self, name: str, search_rate: Optional[float] = None,
                 search_burst: Optional[float] = None,
                 resource_limits: Optional[Dict[str, float]] = None,
                 mode: str = "monitor", lane: str = "interactive"):
        self.name = name
        # serving-scheduler priority lane (serving/scheduler.py): the
        # interactive lane preempts the batch lane at flush time; groups
        # carrying offline/scroll traffic declare `lane: "batch"`
        if lane not in ("interactive", "batch"):
            raise ValueError(f"unknown workload lane [{lane}]")
        self.lane = lane
        # rate=0 means "block" (a bucket that never refills), not unlimited;
        # burst=0 is honored (only refill admits)
        self.bucket = (TokenBucket(search_rate,
                                   search_burst if search_burst is not None
                                   else max(search_rate, 1.0))
                       if search_rate is not None else None)
        self.resource_limits = resource_limits or {}
        self.mode = mode
        self.usage = _UsageWindow()
        self.searches = 0
        self.rejections = 0
        self.resource_rejections = 0

    def admit_search(self, cost: float = 1.0) -> None:
        """`cost` > 1 is the remediation admission-tightening hook
        (serving/remediator.py): while a tighten_admission action is
        engaged, every search spends `cost` tokens from the group's
        bucket instead of one — the rate limit contracts by that factor
        without touching the configured rate, and releases to exactly
        the configured behavior when the action expires. The cost is
        capped at the bucket's burst (floor 1): a group whose burst can
        never hold `cost` tokens must contract to its own capacity, not
        silently turn into a 100% outage for the action's TTL."""
        self.searches += 1
        if self.bucket is not None:
            cost = min(max(float(cost), 1.0),
                       max(self.bucket.burst, 1.0))
            if not self.bucket.try_take(cost):
                self.rejections += 1
                raise PressureRejectedException(
                    f"workload group [{self.name}] search rate limit "
                    f"exceeded")
        cpu_cap = self.resource_limits.get("cpu")
        if cpu_cap is not None and self.mode == "enforced" \
                and self.usage.rate() > cpu_cap:
            self.rejections += 1
            self.resource_rejections += 1
            raise PressureRejectedException(
                f"workload group [{self.name}] over its cpu resource limit "
                f"({self.usage.rate():.3f} > {cpu_cap}) [enforced mode]")

    def record(self, seconds: float) -> None:
        """Charge one completed search's wall time against the group."""
        self.usage.add(max(seconds, 0.0))

    def stats(self) -> dict:
        return {"searches": self.searches, "rejections": self.rejections,
                "resource_rejections": self.resource_rejections,
                "rate_limited": self.bucket is not None,
                "mode": self.mode,
                "lane": self.lane,
                "resource_limits": self.resource_limits,
                "cpu_usage_rate": round(self.usage.rate(), 4)}


class WorkloadManagement:
    def __init__(self, indexing_limit_bytes: int = 64 << 20):
        self.indexing = IndexingPressure(indexing_limit_bytes)
        self.groups: Dict[str, WorkloadGroup] = {
            "default": WorkloadGroup("default")}

    def put_group(self, name: str, search_rate: Optional[float] = None,
                  search_burst: Optional[float] = None,
                  resource_limits: Optional[Dict[str, float]] = None,
                  mode: str = "monitor",
                  lane: str = "interactive") -> WorkloadGroup:
        g = WorkloadGroup(name, search_rate, search_burst,
                          resource_limits, mode, lane)
        self.groups[name] = g
        return g

    def group(self, name: Optional[str]) -> WorkloadGroup:
        return self.groups.get(name or "default", self.groups["default"])

    def stats(self) -> dict:
        return {"indexing_pressure": self.indexing.stats(),
                "groups": {n: g.stats() for n, g in self.groups.items()}}
