"""Request deadline propagation (docs/RESILIENCE.md).

A search request's `timeout` becomes ONE budget, fixed at accept time,
that every stage downstream derives its own limit from — the executor's
between-segment budget check, the serving scheduler's queue wait, and
every cross-node `/_internal` RPC timeout (cluster/distnode.py stamps the
remaining budget onto the RPC payload exactly like the `trace_ctx` /
`obs_ctx` pair). The reference analog is the coordinator's
`SearchTimeoutException` ladder: one `timeout` on the request, honored
end-to-end, instead of a fixed per-hop transport timeout.

Two invariants:

- **Monotonic only.** The budget is a duration anchored to
  `time.monotonic()`; the wire carries `remaining_ms` (a duration
  re-anchored on arrival), never an absolute wall timestamp — clocks on
  two nodes need not agree (OSL501 discipline).
- **Ambient, not threaded.** The active deadline rides a contextvar so
  the executor / scheduler / RPC layers consult it without plumbing a
  parameter through every signature; `scope()` owns set/reset.
"""

from __future__ import annotations

import contextlib
import contextvars
import time
from typing import Optional

# an RPC must never be issued with a zero/negative socket timeout (urllib
# treats 0 as "no timeout"); the floor converts "nearly exhausted" into
# "fail fast" instead of "wait forever"
MIN_RPC_TIMEOUT_S = 0.001


class DeadlineExhausted(Exception):
    """An operation was attempted with no request budget left."""


class PartialResultsUnacceptable(Exception):
    """`allow_partial_search_results=false` and a shard failed or the
    request timed out — the whole request fails instead of serving a
    partial page (reference SearchPhaseExecutionException)."""


def parse_timeout_s(spec) -> Optional[float]:
    """Parse a search `timeout` value into seconds. Accepts reference
    time-value strings (`"500ms"`, `"2s"`, `"1m"`, `"1h"`, `"250micros"`,
    `"10nanos"`) and bare numbers, which are milliseconds (reference
    TimeValue default unit). None/False -> no deadline. NEGATIVE values
    are the reference's "no timeout" sentinel (`-1`,
    `search.default_search_timeout=-1`) -> no deadline; an explicit zero
    is a legitimate degenerate budget (instantly exhausted)."""
    if spec is None or spec is False:
        return None
    if isinstance(spec, bool):
        raise ValueError(f"failed to parse timeout [{spec}]")
    if isinstance(spec, (int, float)):
        v = float(spec) / 1000.0
        return None if v < 0 else v
    s = str(spec).strip().lower()
    units = (("nanos", 1e-9), ("micros", 1e-6), ("ms", 1e-3),
             ("s", 1.0), ("m", 60.0), ("h", 3600.0), ("d", 86400.0))
    try:
        v = None
        for suffix, mult in units:
            if s.endswith(suffix):
                v = float(s[: -len(suffix)]) * mult
                break
        if v is None:
            v = float(s) / 1000.0
    except ValueError:
        raise ValueError(f"failed to parse timeout [{spec}]")
    return None if v < 0 else v


class Deadline:
    """A fixed budget anchored at creation; every consumer derives from
    `remaining_s()` so the ladder is consistent no matter how many hops
    or stages the request crosses."""

    __slots__ = ("budget_s", "_t0")

    def __init__(self, budget_s: float, _t0: Optional[float] = None):
        self.budget_s = float(budget_s)
        self._t0 = time.monotonic() if _t0 is None else _t0

    @classmethod
    def from_body(cls, body) -> Optional["Deadline"]:
        """Deadline from a search body's `timeout` key (None when the
        request carries no timeout). Raises ValueError on junk."""
        if not isinstance(body, dict):
            return None
        budget = parse_timeout_s(body.get("timeout"))
        return cls(budget) if budget is not None else None

    def remaining_s(self) -> float:
        return self.budget_s - (time.monotonic() - self._t0)

    def exhausted(self) -> bool:
        return self.remaining_s() <= 0.0

    def rpc_timeout_s(self, cap_s: float) -> float:
        """The per-hop RPC timeout: min(remaining budget, transport cap),
        floored so a nearly-exhausted budget fails fast instead of
        turning into an unbounded socket wait."""
        return max(min(cap_s, self.remaining_s()), MIN_RPC_TIMEOUT_S)

    # ---- wire form: a duration, re-anchored by the receiving hop ----

    def to_wire(self) -> dict:
        return {"remaining_ms": max(self.remaining_s(), 0.0) * 1000.0}

    @classmethod
    def from_wire(cls, ctx) -> Optional["Deadline"]:
        if not isinstance(ctx, dict) or "remaining_ms" not in ctx:
            return None
        try:
            return cls(float(ctx["remaining_ms"]) / 1000.0)
        except (TypeError, ValueError):
            return None


_current: contextvars.ContextVar = contextvars.ContextVar(
    "ostpu_deadline", default=None)


def current() -> Optional[Deadline]:
    return _current.get()


def set_current(dl: Optional[Deadline]):
    return _current.set(dl)


def reset_current(token) -> None:
    _current.reset(token)


@contextlib.contextmanager
def scope(dl: Optional[Deadline]):
    """Install `dl` as the ambient deadline for the duration (no-op when
    dl is None, so callers need not branch)."""
    if dl is None:
        yield None
        return
    token = set_current(dl)
    try:
        yield dl
    finally:
        reset_current(token)
